/* MEX gateway over the C predict ABI — makes the matlab/ wrapper
 * EXECUTABLE under GNU Octave (mkoctfile --mex) as well as MATLAB,
 * replacing the loadlibrary path that Octave lacks. Role parity: the
 * reference's matlab predict-only wrapper (matlab/+mxnet/model.m over
 * c_predict_api.h:77-152).
 *
 * [out, oshape] = mxtpu_predict_mex(symbol_json, param_bytes, ...
 *                                   input_name, data_flat, shape)
 *   symbol_json : char row vector (model JSON)
 *   param_bytes : uint8 vector (.params file bytes)
 *   input_name  : char row vector (e.g. 'data')
 *   data_flat   : single vector, C-row-major flattened input
 *   shape       : uint32 row vector, C-order input shape
 * Returns the flat single output of head 0 and its C-order shape.
 *
 * Build: mkoctfile --mex -I../src/capi mxtpu_predict_mex.c \
 *          -L../mxtpu/native -lmxtpu_predict \
 *          -Wl,-rpath=../mxtpu/native
 */
#include <stdint.h>
#include <string.h>

#include "mex.h"

#include "c_predict_api.h"

static void die(PredictorHandle h, const char *where) {
  if (h != NULL) MXPredFree(h);
  mexErrMsgIdAndTxt("mxtpu:predict", "%s: %s", where, MXGetLastError());
}

void mexFunction(int nlhs, mxArray *plhs[], int nrhs,
                 const mxArray *prhs[]) {
  if (nrhs != 5) {
    mexErrMsgIdAndTxt("mxtpu:usage",
                      "usage: mxtpu_predict_mex(json, params, name, "
                      "data, shape)");
  }
  char *json = mxArrayToString(prhs[0]);
  char *name = mxArrayToString(prhs[2]);
  const uint8_t *params = (const uint8_t *)mxGetData(prhs[1]);
  size_t n_params = mxGetNumberOfElements(prhs[1]);
  const float *data = (const float *)mxGetData(prhs[3]);
  size_t n_data = mxGetNumberOfElements(prhs[3]);
  const uint32_t *shape = (const uint32_t *)mxGetData(prhs[4]);
  mx_uint ndim = (mx_uint)mxGetNumberOfElements(prhs[4]);

  mx_uint indptr[2] = {0, ndim};
  const char *input_keys[1];
  input_keys[0] = name;

  PredictorHandle h = NULL;
  if (MXPredCreate(json, params, (int)n_params, 1, 0, 1, input_keys,
                   indptr, shape, &h) != 0) {
    die(NULL, "MXPredCreate");
  }
  if (MXPredSetInput(h, name, data, (mx_uint)n_data) != 0) {
    die(h, "MXPredSetInput");
  }
  if (MXPredForward(h) != 0) die(h, "MXPredForward");

  mx_uint *oshape = NULL;
  mx_uint odim = 0;
  if (MXPredGetOutputShape(h, 0, &oshape, &odim) != 0) {
    die(h, "MXPredGetOutputShape");
  }
  size_t total = 1;
  for (mx_uint i = 0; i < odim; ++i) total *= oshape[i];

  plhs[0] = mxCreateNumericMatrix((mwSize)total, 1, mxSINGLE_CLASS,
                                  mxREAL);
  if (MXPredGetOutput(h, 0, (float *)mxGetData(plhs[0]),
                      (mx_uint)total) != 0) {
    die(h, "MXPredGetOutput");
  }
  if (nlhs > 1) {
    plhs[1] = mxCreateNumericMatrix(1, odim, mxUINT32_CLASS, mxREAL);
    memcpy(mxGetData(plhs[1]), oshape, odim * sizeof(uint32_t));
  }
  MXPredFree(h);
  mxFree(json);
  mxFree(name);
}
