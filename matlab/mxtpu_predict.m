function out = mxtpu_predict(symbol_file, param_file, data, varargin)
%MXTPU_PREDICT Run inference through the C predict ABI from MATLAB.
%   OUT = MXTPU_PREDICT(SYMBOL_FILE, PARAM_FILE, DATA) loads a trained
%   model (symbol JSON + .params saved by mxtpu) and returns the network
%   output for DATA (numeric array, batch along the first dimension).
%
%   OUT = MXTPU_PREDICT(..., 'InputName', NAME) overrides the input name
%   (default 'data').
%
%   Role parity: the reference's matlab/ predict-only wrapper over
%   libmxnet_predict (matlab/+mxnet/model.m, c_predict_api.h). This
%   wrapper drives the identical four-call ABI:
%     MXPredCreate -> MXPredSetInput -> MXPredForward -> MXPredGetOutput
%   against mxtpu/native/libmxtpu_predict.so (build: make -C src predict).
%
%   Requires the library + header on the path:
%     addpath <repo>/matlab
%     setenv('MXTPU_NATIVE', '<repo>/mxtpu/native');

p = inputParser;
addParameter(p, 'InputName', 'data');
parse(p, varargin{:});
input_name = p.Results.InputName;

% Preferred path (works in BOTH MATLAB and GNU Octave): the compiled MEX
% gateway (matlab/mxtpu_predict_mex.c, built with `mex` or
% `mkoctfile --mex`). Octave has no loadlibrary, so the MEX is the only
% route there; in MATLAB it simply skips the header parse.
if exist('mxtpu_predict_mex', 'file') == 3
    symbol_json = fileread(symbol_file);
    fid = fopen(param_file, 'rb');
    param_bytes = fread(fid, inf, '*uint8');
    fclose(fid);
    shape = uint32(fliplr(size(data)));
    flat = single(permute(data, ndims(data):-1:1));
    [flat_out, oshape] = mxtpu_predict_mex(symbol_json, param_bytes, ...
                                           input_name, flat(:), shape);
    oshape = double(oshape);
    out = reshape(flat_out, fliplr(oshape));
    out = permute(out, numel(oshape):-1:1);
    return
end

native = getenv('MXTPU_NATIVE');
if isempty(native)
    error('set MXTPU_NATIVE to the mxtpu/native directory');
end
header = fullfile(fileparts(mfilename('fullpath')), ...
                  '..', 'src', 'capi', 'c_predict_api.h');
if ~libisloaded('libmxtpu_predict')
    loadlibrary(fullfile(native, 'libmxtpu_predict.so'), header, ...
                'alias', 'libmxtpu_predict');
end

symbol_json = fileread(symbol_file);
fid = fopen(param_file, 'rb');
param_bytes = fread(fid, inf, '*uint8');
fclose(fid);

% input shape: MATLAB dims reversed into C row-major order
shape = uint32(fliplr(size(data)));
indptr = uint32([0, numel(shape)]);

handle = libpointer('voidPtrPtr');
rc = calllib('libmxtpu_predict', 'MXPredCreate', symbol_json, ...
             param_bytes, numel(param_bytes), 1, 0, 1, {input_name}, ...
             indptr, shape, handle);
assert(rc == 0, mxtpu_last_error());

flat = single(permute(data, ndims(data):-1:1));  % row-major flatten
rc = calllib('libmxtpu_predict', 'MXPredSetInput', handle, input_name, ...
             flat(:), numel(flat));
assert(rc == 0, mxtpu_last_error());

rc = calllib('libmxtpu_predict', 'MXPredForward', handle);
assert(rc == 0, mxtpu_last_error());

% output 0 shape, then the data
dim = libpointer('uint32Ptr', 0);
pshape = libpointer('uint32PtrPtr');
rc = calllib('libmxtpu_predict', 'MXPredGetOutputShape', handle, 0, ...
             pshape, dim);
assert(rc == 0, mxtpu_last_error());
setdatatype(pshape.Value, 'uint32Ptr', double(dim.Value));
oshape = double(pshape.Value.Value(:)');

n = prod(oshape);
buf = libpointer('singlePtr', zeros(n, 1, 'single'));
rc = calllib('libmxtpu_predict', 'MXPredGetOutput', handle, 0, buf, n);
assert(rc == 0, mxtpu_last_error());

out = reshape(buf.Value, fliplr(oshape));   % back to MATLAB column-major
out = permute(out, numel(oshape):-1:1);

calllib('libmxtpu_predict', 'MXPredFree', handle);
end

function msg = mxtpu_last_error()
msg = calllib('libmxtpu_predict', 'MXGetLastError');
end
