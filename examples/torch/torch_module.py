"""Mixing torch layers into an mxtpu graph (parity: example/torch/
torch_module.py — the reference sandwiches Torch nn layers between MXNet
symbols via the torch plugin; here `mx.th.as_symbol` wraps any
torch.nn.Module as an in-graph op whose forward runs functional_call and
whose backward runs torch.autograd, with the torch parameters trained by
the mxtpu optimizer).

Run:  python torch_module.py --epochs 6
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def synth(n, rng, classes=6, dim=24):
    protos = (rng.rand(classes, dim) > 0.5).astype("f4")
    y = rng.randint(0, classes, n)
    X = protos[y] + rng.randn(n, dim).astype("f4") * 0.25
    return X, y.astype("f4")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=1536)
    ap.add_argument("--seed", type=int, default=2)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    import torch
    import torch.nn as tnn
    torch.manual_seed(args.seed)  # the wrapped block inits from torch's RNG
    torch_block = tnn.Sequential(tnn.Linear(24, 48), tnn.ReLU(),
                                 tnn.Linear(48, 48), tnn.Tanh())

    data = mx.sym.Variable("data")
    hidden = mx.th.as_symbol(torch_block, data, name="torch_block")
    out = mx.sym.FullyConnected(hidden, num_hidden=6, name="fc_out")
    net = mx.sym.SoftmaxOutput(out, mx.sym.Variable("softmax_label"),
                               name="softmax")

    rng = np.random.RandomState(args.seed)
    X, y = synth(args.num_examples, rng)
    nval = args.num_examples // 4
    train = mx.io.NDArrayIter(X[:-nval], y[:-nval], args.batch_size,
                              shuffle=True, label_name="softmax_label")
    val = mx.io.NDArrayIter(X[-nval:], y[-nval:], args.batch_size,
                            label_name="softmax_label")

    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    # keep torch's own init for the wrapped block
    arg, aux = mod.get_params()
    mod.set_params({**arg, **mx.th.torch_params(torch_block, "torch_block")},
                   aux, allow_missing=False)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})
    metric = mx.metric.Accuracy()
    for epoch in range(args.epochs):
        train.reset()
        metric.reset()
        for batch in train:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
        logging.info("Epoch[%d] train acc %.3f", epoch, metric.get()[1])
    acc = mod.score(val, mx.metric.Accuracy())[0][1]
    logging.info("val accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    print("torch-in-graph val accuracy %.3f" % main())
