"""Custom-op training: a softmax loss head written as a numpy CustomOp
(parity: example/numpy-ops/custom_softmax.py — mx.operator.CustomOp /
CustomOpProp with need_top_grad=False, registered and instantiated as
``mx.sym.Custom(op_type=...)``). The op body runs as an XLA host callback
(ops/custom.py pure_callback), so the same graph path works jitted.

Run:  python custom_softmax.py --epochs 6
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


class NumpySoftmax(mx.operator.CustomOp):
    """Forward: row softmax. Backward: softmax - onehot(label) — the
    loss-head gradient, ignoring incoming cotangents (need_top_grad=False,
    exactly the reference example's Softmax)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1).reshape((x.shape[0], 1)))
        y /= y.sum(axis=1).reshape((x.shape[0], 1))
        self.assign(out_data[0], req[0], y)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        lab = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy().copy()
        y[np.arange(lab.shape[0]), lab] -= 1.0
        self.assign(in_grad[0], req[0], y)


@mx.operator.register("numpy_softmax")
class NumpySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = [in_shape[0][0]]
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return NumpySoftmax()


def synth(n, rng, classes=10, dim=64):
    protos = rng.rand(classes, dim) > 0.5
    y = rng.randint(0, classes, n)
    X = protos[y].astype("float32") + rng.randn(n, dim).astype("float32") * 0.3
    return X, y.astype("float32")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=1024)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(3)
    X, Y = synth(args.num_examples, rng)
    nval = args.num_examples // 4
    train = mx.io.NDArrayIter(X[:-nval], Y[:-nval], args.batch_size,
                              shuffle=True, label_name="softmax_label")
    val = mx.io.NDArrayIter(X[-nval:], Y[-nval:], args.batch_size,
                            label_name="softmax_label")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc1 = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="fc2")
    net = mx.sym.Custom(fc2, label, op_type="numpy_softmax", name="softmax")

    mod = mx.mod.Module(net, context=mx.cpu(0),
                        label_names=("softmax_label",))
    mod.fit(train, eval_data=val, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            eval_metric=mx.metric.Accuracy(),
            initializer=mx.initializer.Xavier())

    metric = mx.metric.Accuracy()
    mod.score(val, metric)
    acc = metric.get()[1]
    logging.info("custom-softmax val accuracy: %.4f", acc)
    return acc


if __name__ == "__main__":
    main()
