"""Format a Kaggle NDSB-1 submission csv (parity:
example/kaggle-ndsb1/submission_dsb.py — image,<121 class probs> rows,
clipped and renormalized).

Run: python submission_dsb.py --probs probs.npy --test-lst data/test.lst \
        --classes data/classes.txt --out submission.csv
"""
import argparse
import csv
import os

import numpy as np


def write_submission(probs, image_names, class_names, out_path,
                     clip=1e-15):
    probs = np.clip(np.asarray(probs, dtype=np.float64), clip, 1.0)
    probs /= probs.sum(axis=1, keepdims=True)
    with open(out_path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["image"] + list(class_names))
        for name, row in zip(image_names, probs):
            w.writerow([name] + ["%.6f" % p for p in row])


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--probs", required=True)
    ap.add_argument("--test-lst", required=True)
    ap.add_argument("--classes", required=True)
    ap.add_argument("--out", default="submission.csv")
    args = ap.parse_args(argv)
    probs = np.load(args.probs)
    with open(args.classes) as f:
        class_names = [ln.strip() for ln in f if ln.strip()]
    names = []
    with open(args.test_lst) as f:
        for ln in f:
            parts = ln.rstrip("\n").split("\t")
            if parts and parts[-1]:
                names.append(os.path.basename(parts[-1]))
    write_submission(probs[:len(names)], names, class_names, args.out)
    print("wrote %s (%d rows)" % (args.out, len(names)))


if __name__ == "__main__":
    main()
