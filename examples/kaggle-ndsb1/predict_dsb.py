"""Predict class probabilities for the test .rec (parity:
example/kaggle-ndsb1/predict_dsb.py — load the checkpoint, run the test
set, dump a probabilities matrix aligned with the test .lst order).

Run: python predict_dsb.py --model-prefix models/dsb --epoch 40 \
        --test-rec data48/test.rec --num-classes 121 --out probs.npy
"""
import argparse

import numpy as np

import mxtpu as mx


def predict(model_prefix, epoch, test_rec, num_classes, edge, batch_size):
    sym, arg_params, aux_params = mx.model.load_checkpoint(model_prefix,
                                                           epoch)
    it = mx.io.ImageRecordIter(path_imgrec=test_rec,
                               data_shape=(3, edge, edge),
                               batch_size=batch_size, round_batch=True,
                               scale=1.0 / 255)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.set_params(arg_params, aux_params)
    probs = []
    n_real = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0].asnumpy()
        keep = batch.data[0].shape[0] - batch.pad
        probs.append(out[:keep])
        n_real += keep
    return np.concatenate(probs, axis=0)[:, :num_classes]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-prefix", required=True)
    ap.add_argument("--epoch", type=int, required=True)
    ap.add_argument("--test-rec", required=True)
    ap.add_argument("--num-classes", type=int, required=True)
    ap.add_argument("--edge", type=int, default=48)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--out", default="probs.npy")
    args = ap.parse_args(argv)
    probs = predict(args.model_prefix, args.epoch, args.test_rec,
                    args.num_classes, args.edge, args.batch_size)
    np.save(args.out, probs)
    print("wrote %s %s" % (args.out, probs.shape))
    return probs


if __name__ == "__main__":
    main()
