"""The NDSB-1 conv net (parity: example/kaggle-ndsb1/symbol_dsb.py —
three conv/pool stages + two fc, softmax head), width parameterized so
the CI gate trains in seconds at small scale."""
import mxtpu as mx


def get_symbol(num_classes, width=1.0):
    w = lambda n: max(4, int(n * width))  # noqa: E731
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                             num_filter=w(32), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                             num_filter=w(64), name="conv2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                             num_filter=w(128), name="conv3")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=w(256), name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Dropout(net, p=0.5)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")
