"""Generate .lst image lists from a class-per-subfolder tree (parity:
example/kaggle-ndsb1/gen_img_list.py — walk data/train/<class>/*.jpg,
assign integer labels in sorted class order, optionally split into
stratified tr/va lists).

Run: python gen_img_list.py --image-folder data/train --out-folder data \
        --train [--percent-val 0.25] [--stratified]
Then pack with tools/im2rec.py and train with train_dsb.py.
"""
import argparse
import os
import random


def list_classes(folder):
    return sorted(d for d in os.listdir(folder)
                  if os.path.isdir(os.path.join(folder, d)))


def build_list(image_folder, train):
    """[(idx, label, relpath)] + class names (label order)."""
    items = []
    if train:
        classes = list_classes(image_folder)
        for li, cls in enumerate(classes):
            sub = os.path.join(image_folder, cls)
            for img in sorted(os.listdir(sub)):
                items.append((len(items), li, os.path.join(cls, img)))
    else:
        classes = []
        for img in sorted(os.listdir(image_folder)):
            items.append((len(items), 0, img))
    return items, classes


def write_lst(path, items):
    with open(path, "w") as f:
        for idx, label, rel in items:
            f.write("%d\t%f\t%s\n" % (idx, float(label), rel))


def split(items, percent_val, stratified, rng):
    if not stratified:
        items = list(items)
        rng.shuffle(items)
        n_va = int(len(items) * percent_val)
        return items[n_va:], items[:n_va]
    by_cls = {}
    for it in items:
        by_cls.setdefault(it[1], []).append(it)
    tr, va = [], []
    for cls in sorted(by_cls):
        group = by_cls[cls]
        rng.shuffle(group)
        n_va = int(len(group) * percent_val)
        va += group[:n_va]
        tr += group[n_va:]
    rng.shuffle(tr)
    rng.shuffle(va)
    return tr, va


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--image-folder", default="data/train")
    ap.add_argument("--out-folder", default="data")
    ap.add_argument("--out-file", default="train.lst")
    ap.add_argument("--train", action="store_true")
    ap.add_argument("--percent-val", type=float, default=0.25)
    ap.add_argument("--stratified", action="store_true")
    ap.add_argument("--seed", type=int, default=888)
    args = ap.parse_args(argv)
    rng = random.Random(args.seed)

    items, classes = build_list(args.image_folder, args.train)
    os.makedirs(args.out_folder, exist_ok=True)
    write_lst(os.path.join(args.out_folder, args.out_file), items)
    if args.train:
        tr, va = split(items, args.percent_val, args.stratified, rng)
        write_lst(os.path.join(args.out_folder, "tr.lst"), tr)
        write_lst(os.path.join(args.out_folder, "va.lst"), va)
        with open(os.path.join(args.out_folder, "classes.txt"), "w") as f:
            f.write("\n".join(classes) + "\n")
    return len(items), classes


if __name__ == "__main__":
    main()
