"""Train the NDSB-1 net on packed .rec files (parity:
example/kaggle-ndsb1/train_dsb.py — ImageRecordIter over tr.rec/va.rec,
Module.fit with checkpoints).

Run after gen_img_list.py + tools/im2rec.py:
    python train_dsb.py --data-dir data48 --num-classes 121 \
        --num-epochs 40 --model-prefix models/dsb
"""
import argparse
import logging
import os

import mxtpu as mx

import symbol_dsb


def get_iters(data_dir, edge, batch_size):
    train = mx.io.ImageRecordIter(
        path_imgrec=os.path.join(data_dir, "tr.rec"),
        data_shape=(3, edge, edge), batch_size=batch_size,
        shuffle=True, rand_crop=True, rand_mirror=True, scale=1.0 / 255)
    val = mx.io.ImageRecordIter(
        path_imgrec=os.path.join(data_dir, "va.rec"),
        data_shape=(3, edge, edge), batch_size=batch_size,
        scale=1.0 / 255)
    return train, val


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default="data48")
    ap.add_argument("--num-classes", type=int, required=True)
    ap.add_argument("--edge", type=int, default=48)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=40)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--optimizer", default="sgd")
    ap.add_argument("--width", type=float, default=1.0)
    ap.add_argument("--model-prefix", default=None)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    net = symbol_dsb.get_symbol(args.num_classes, width=args.width)
    train, val = get_iters(args.data_dir, args.edge, args.batch_size)
    mod = mx.mod.Module(net, context=mx.cpu())
    cb = (mx.callback.do_checkpoint(args.model_prefix)
          if args.model_prefix else None)
    opt_params = {"learning_rate": args.lr, "wd": 1e-4,
                  "rescale_grad": 1.0 / args.batch_size}
    if args.optimizer == "sgd":
        opt_params["momentum"] = 0.9
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer=args.optimizer,
            optimizer_params=opt_params,
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.34),
            epoch_end_callback=cb)
    val.reset()
    acc = dict(mod.score(val, mx.metric.Accuracy()))["accuracy"]
    print("val-accuracy %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
