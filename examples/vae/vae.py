"""Variational autoencoder (parity: the reference's example/vae — MLP
encoder to a diagonal-Gaussian latent, reparameterized sampling, MLP
decoder to Bernoulli pixels, ELBO = reconstruction + KL to N(0, I)).

TPU-native shape: the reparameterization noise comes from the framework's
threaded PRNG (mx.nd.random_normal), so the whole ELBO step — encode,
sample, decode, both loss terms, backward — is one autograd tape over
fused ops with no host round trips.

Run:  python vae.py --epochs 30
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon


class VAE(gluon.Block):
    def __init__(self, n_in, n_latent=4, n_hidden=64, **kw):
        super().__init__(**kw)
        self.n_latent = n_latent
        with self.name_scope():
            self.enc_h = gluon.nn.Dense(n_hidden, activation="tanh")
            self.enc_mu = gluon.nn.Dense(n_latent)
            self.enc_logvar = gluon.nn.Dense(n_latent)
            self.dec_h = gluon.nn.Dense(n_hidden, activation="tanh")
            self.dec_x = gluon.nn.Dense(n_in)

    def forward(self, x):
        h = self.enc_h(x)
        mu, logvar = self.enc_mu(h), self.enc_logvar(h)
        eps = mx.nd.random_normal(shape=mu.shape)
        z = mu + mx.nd.exp(0.5 * logvar) * eps
        logits = self.dec_x(self.dec_h(z))
        return logits, mu, logvar


def elbo_loss(x, logits, mu, logvar):
    """Negative ELBO: Bernoulli reconstruction + analytic Gaussian KL."""
    # log-sigmoid reconstruction, numerically stable
    rec = (mx.nd.relu(logits) - logits * x +
           mx.nd.log(1.0 + mx.nd.exp(-mx.nd.abs(logits)))).sum(axis=1)
    kl = 0.5 * (mx.nd.exp(logvar) + mu ** 2 - 1.0 - logvar).sum(axis=1)
    return (rec + kl).mean()


def glyph_data(n, rng, size=8, protos=None):
    """Binary prototype glyphs with pixel noise: a latent structure a 4-D
    code can capture. Pass the same `protos` for train/val so both draw
    from one distribution."""
    if protos is None:
        protos = (rng.rand(6, size * size) > 0.6).astype("f4")
    idx = rng.randint(0, len(protos), n)
    X = protos[idx]
    flip = rng.rand(n, size * size) < 0.05
    return np.abs(X - flip.astype("f4")), protos


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.005)
    ap.add_argument("--seed", type=int, default=2)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    X, protos = glyph_data(1024, rng)
    Xv, _ = glyph_data(256, rng, protos=protos)
    net = VAE(X.shape[1])
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    def val_elbo():
        logits, mu, logvar = net(mx.nd.array(Xv))
        return float(elbo_loss(mx.nd.array(Xv), logits, mu,
                               logvar).asnumpy())

    start = val_elbo()
    n_batches = len(X) // args.batch_size
    for ep in range(args.epochs):
        perm = rng.permutation(len(X))
        tot = 0.0
        for b in range(n_batches):
            xb = mx.nd.array(X[perm[b * args.batch_size:
                                    (b + 1) * args.batch_size]])
            with autograd.record():
                logits, mu, logvar = net(xb)
                loss = elbo_loss(xb, logits, mu, logvar)
            loss.backward()
            trainer.step(1)
            tot += float(loss.asnumpy())
        if (ep + 1) % 10 == 0:
            logging.info("epoch %d train -ELBO %.2f", ep + 1,
                         tot / n_batches)
    end = val_elbo()
    logging.info("val -ELBO: %.2f -> %.2f", start, end)
    return start, end


if __name__ == "__main__":
    s, e = main()
    print("val -ELBO %.2f -> %.2f" % (s, e))
