"""LSTM + CTC optical character recognition (parity: example/ctc/lstm_ocr.py
and example/captcha/ — train an unrolled LSTM over image columns to read a
variable-length digit string with no per-column alignment, via the
`_contrib_CTCLoss` head replacing the reference's warp-ctc plugin).

Images are synthetic digit strips rendered from a 7x5 bitmap font at random
horizontal offsets (the reference draws captchas with the `captcha` package;
the task shape — variable-length digit string in a fixed-width image — is the
same, without the font asset download). Labels follow the warp-ctc
convention: blank = class 0, digit d = class d+1, label 0 = padding.

Run:  python lstm_ocr.py --epochs 25
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import rnn

# 7x5 bitmap font for digits 0-9 (rows of 5 bits, msb left)
_FONT = {
    0: "01110 10001 10011 10101 11001 10001 01110",
    1: "00100 01100 00100 00100 00100 00100 01110",
    2: "01110 10001 00001 00010 00100 01000 11111",
    3: "11111 00010 00100 00010 00001 10001 01110",
    4: "00010 00110 01010 10010 11111 00010 00010",
    5: "11111 10000 11110 00001 00001 10001 01110",
    6: "00110 01000 10000 11110 10001 10001 01110",
    7: "11111 00001 00010 00100 01000 01000 01000",
    8: "01110 10001 10001 01110 10001 10001 01110",
    9: "01110 10001 10001 01111 00001 00010 01100",
}
_GLYPHS = {
    d: np.array([[int(b) for b in row] for row in s.split()], dtype=np.float32)
    for d, s in _FONT.items()
}

IMG_H, IMG_W = 16, 64
MAX_LABEL = 5          # up to 5 digits per strip
NUM_CLASSES = 11       # blank + 10 digits


def render_strip(digits, rng):
    """Render a digit string into an (IMG_H, IMG_W) float image with random
    vertical jitter and per-digit horizontal spacing."""
    img = np.zeros((IMG_H, IMG_W), dtype=np.float32)
    slack = IMG_W - len(digits) * 7 - 2
    x = 1 + rng.randint(0, max(1, slack // 2))
    for d in digits:
        g = _GLYPHS[d]
        y = 3 + rng.randint(0, 4)
        img[y:y + 7, x:x + 5] = np.maximum(img[y:y + 7, x:x + 5], g)
        x += 7 + rng.randint(0, 2)
    img += rng.uniform(0.0, 0.15, img.shape).astype(np.float32)
    return np.minimum(img, 1.0)


def make_dataset(n, rng):
    X = np.zeros((n, IMG_W // 2, IMG_H * 2), dtype=np.float32)  # (N, T, F)
    Y = np.zeros((n, MAX_LABEL), dtype=np.float32)              # padded labels
    for i in range(n):
        k = rng.randint(3, MAX_LABEL + 1)
        digits = [rng.randint(0, 10) for _ in range(k)]
        img = render_strip(digits, rng)
        # two columns per step: (H, W) -> (W/2, 2H) feature sequence
        X[i] = img.T.reshape(IMG_W // 2, IMG_H * 2)
        Y[i, :k] = [d + 1 for d in digits]  # 0 is blank/pad
    return X, Y


def build_symbol(num_hidden, seq_len, for_training):
    data = mx.sym.Variable("data")            # (N, T, F)
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm1_"))
    stack.add(rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm2_"))
    outputs, _ = stack.unroll(seq_len, inputs=data, merge_outputs=True,
                              layout="NTC")
    flat = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
    pred = mx.sym.FullyConnected(flat, num_hidden=NUM_CLASSES, name="pred")
    pred = mx.sym.Reshape(pred, shape=(-1, seq_len, NUM_CLASSES))
    pred_tnc = mx.sym.transpose(pred, axes=(1, 0, 2))  # (T, N, C)
    if not for_training:
        return mx.sym.softmax(pred_tnc, axis=-1)
    label = mx.sym.Variable("label")
    return mx.sym.CTCLoss(pred_tnc, label, name="ctc", blank_label="first")


def greedy_decode(probs):
    """probs (T, N, C) -> list of digit lists (collapse repeats, drop blank)."""
    ids = probs.argmax(axis=-1)  # (T, N)
    out = []
    for n in range(ids.shape[1]):
        seq, prev = [], -1
        for t in ids[:, n]:
            if t != prev and t != 0:
                seq.append(int(t) - 1)
            prev = t
        out.append(seq)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=25)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-examples", type=int, default=3072)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)

    rng = np.random.RandomState(args.seed)
    np.random.seed(args.seed)  # NDArrayIter(shuffle=True) uses the global RNG
    X, Y = make_dataset(args.num_examples, rng)
    n_train = int(len(X) * 0.9)
    # the 10% validation split must still hold at least one batch
    args.batch_size = max(1, min(args.batch_size, len(X) - n_train))
    seq_len = X.shape[1]
    it = mx.io.NDArrayIter(X[:n_train], Y[:n_train],
                           batch_size=args.batch_size, shuffle=True,
                           label_name="label")

    net = build_symbol(args.num_hidden, seq_len, for_training=True)
    mod = mx.mod.Module(net, context=mx.cpu(0), label_names=("label",))
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            eval_metric=mx.metric.Loss(),
            initializer=mx.initializer.Xavier())

    # greedy-decode accuracy on held-out strips through a prediction symbol
    # sharing the trained weights
    pred_net = build_symbol(args.num_hidden, seq_len, for_training=False)
    pmod = mx.mod.Module(pred_net, context=mx.cpu(0), label_names=None)
    pmod.bind(data_shapes=[("data", (args.batch_size, seq_len,
                                     X.shape[2]))], for_training=False)
    arg_params, aux_params = mod.get_params()
    pmod.set_params(arg_params, aux_params, allow_missing=False)

    val_X, val_Y = X[n_train:], Y[n_train:]
    vit = mx.io.NDArrayIter(val_X, val_Y, batch_size=args.batch_size,
                            label_name="label")
    correct = total = 0
    for batch in vit:
        pmod.forward(batch, is_train=False)
        probs = pmod.get_outputs()[0].asnumpy()
        decoded = greedy_decode(probs)
        labels = batch.label[0].asnumpy()
        n_valid = len(decoded) - batch.pad
        for n in range(n_valid):
            want = [int(v) - 1 for v in labels[n] if v > 0]
            correct += int(decoded[n] == want)
            total += 1
    acc = correct / max(total, 1)
    logging.info("held-out whole-sequence accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    print("ocr sequence accuracy %.3f" % main())
