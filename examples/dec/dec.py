"""Deep Embedded Clustering (parity: the reference's example/dec/dec.py —
stacked-autoencoder pretraining, then joint refinement of an embedding
and cluster centroids by minimizing KL(P || Q) between the Student-t soft
assignment Q and the sharpened target distribution P, re-estimated every
update_interval).

TPU-native shape: the whole DEC step (encoder forward, soft assignment,
KL loss, backward over both net and centroids) is one autograd tape over
fused ops; only the periodic target-distribution refresh runs on host,
exactly where the reference also syncs (dec.py solver callback).

Run:  python dec.py --clusters 4
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon


class Encoder(gluon.Block):
    def __init__(self, n_latent=2, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.h = gluon.nn.Dense(32, activation="relu")
            self.z = gluon.nn.Dense(n_latent)

    def forward(self, x):
        return self.z(self.h(x))


class Decoder(gluon.Block):
    def __init__(self, n_out, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.h = gluon.nn.Dense(32, activation="relu")
            self.o = gluon.nn.Dense(n_out)

    def forward(self, z):
        return self.o(self.h(z))


def soft_assign(z, centroids, alpha=1.0):
    """Student-t similarity q_ij (DEC eq. 1)."""
    d2 = ((z.expand_dims(1) - centroids.expand_dims(0)) ** 2).sum(axis=2)
    q = (1.0 + d2 / alpha) ** (-(alpha + 1.0) / 2.0)
    return q / q.sum(axis=1, keepdims=True)


def target_distribution(q):
    """Sharpened targets p_ij = q^2/f normalized (DEC eq. 3), on host."""
    w = q ** 2 / q.sum(axis=0, keepdims=True)
    return (w / w.sum(axis=1, keepdims=True)).astype("f4")


def cluster_accuracy(pred, truth, k):
    """Best one-to-one label matching accuracy (greedy Hungarian-lite)."""
    conf = np.zeros((k, k))
    for p, t in zip(pred, truth):
        conf[p, t] += 1
    total = 0.0
    for _ in range(k):
        i, j = np.unravel_index(np.argmax(conf), conf.shape)
        total += conf[i, j]
        conf[i, :] = -1
        conf[:, j] = -1
    return total / len(pred)


def kmeans_init(z, k, rng, iters=20):
    """Plain numpy k-means for centroid init (the reference uses sklearn)."""
    c = z[rng.choice(len(z), k, replace=False)].copy()
    for _ in range(iters):
        d = ((z[:, None, :] - c[None]) ** 2).sum(-1)
        a = d.argmin(1)
        for j in range(k):
            if (a == j).any():
                c[j] = z[a == j].mean(0)
    return c, a


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--clusters", type=int, default=4)
    ap.add_argument("--pretrain-epochs", type=int, default=30)
    ap.add_argument("--dec-iters", type=int, default=60)
    ap.add_argument("--update-interval", type=int, default=10)
    ap.add_argument("--seed", type=int, default=1)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    k = args.clusters

    # blobs in 16-D whose structure survives a 2-D bottleneck
    n_per = 150
    centers = rng.randn(k, 16).astype("f4") * 3.0
    X = np.concatenate([centers[i] + 0.7 * rng.randn(n_per, 16).astype("f4")
                        for i in range(k)])
    truth = np.repeat(np.arange(k), n_per)
    perm = rng.permutation(len(X))
    X, truth = X[perm].astype("f4"), truth[perm]

    enc, dec = Encoder(), Decoder(X.shape[1])
    enc.initialize(mx.initializer.Xavier())
    dec.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(enc.collect_params(), "adam",
                            {"learning_rate": 0.01})
    trainer_d = gluon.Trainer(dec.collect_params(), "adam",
                              {"learning_rate": 0.01})
    xs = mx.nd.array(X)

    # --- stage 1: autoencoder pretraining (reconstruction)
    for ep in range(args.pretrain_epochs):
        with autograd.record():
            rec = dec(enc(xs))
            loss = ((rec - xs) ** 2).mean()
        loss.backward()
        trainer.step(1)
        trainer_d.step(1)
    logging.info("pretrain recon loss: %.4f", float(loss.asnumpy()))

    # --- stage 2: DEC refinement with trainable centroids
    z0 = enc(xs).asnumpy()
    c0, assign0 = kmeans_init(z0, k, rng)
    acc0 = cluster_accuracy(assign0, truth, k)
    centroids = mx.nd.array(c0)
    centroids.attach_grad()
    p = mx.nd.array(target_distribution(
        soft_assign(mx.nd.array(z0), mx.nd.array(c0)).asnumpy()))
    for it in range(args.dec_iters):
        if it and it % args.update_interval == 0:
            q_np = soft_assign(enc(xs), centroids).asnumpy()
            p = mx.nd.array(target_distribution(q_np))
        with autograd.record():
            q = soft_assign(enc(xs), centroids)
            kl = (p * (mx.nd.log(p + 1e-10) - mx.nd.log(q + 1e-10))) \
                .sum(axis=1).mean()
        kl.backward()
        trainer.step(1)
        centroids -= 0.1 * centroids.grad
        centroids.attach_grad()
    pred = soft_assign(enc(xs), centroids).asnumpy().argmax(1)
    acc = cluster_accuracy(pred, truth, k)
    logging.info("cluster acc: kmeans-on-z %.3f -> DEC %.3f", acc0, acc)
    return acc


if __name__ == "__main__":
    print("cluster accuracy: %.3f" % main())
