"""Parallel advantage actor-critic (parity: the reference's
example/reinforcement-learning/parallel_actor_critic — many environments
stepped in lockstep, one batched policy+value network, policy-gradient +
value-regression + entropy update per rollout chunk).

TPU-native shape: the environments are a VECTORIZED numpy CartPole (one
array op steps all of them), so the network always sees a fixed
(n_envs*t_max, obs) batch — no retracing, and the whole update (forward,
losses, backward, clip, step) is one autograd tape over fused ops.

Run:  python parallel_actor_critic.py --iters 250
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon


class VecCartPole:
    """Classic CartPole-v0 dynamics, vectorized over n environments.

    Physics follows the standard Barto-Sutton-Anderson equations; an
    episode ends when |x| > 2.4, |theta| > 12 deg, or after 200 steps."""

    def __init__(self, n, seed=0):
        self.n = n
        self._rng = np.random.RandomState(seed)
        self.state = np.zeros((n, 4), np.float32)
        self.steps = np.zeros(n, np.int64)
        self.reset(np.arange(n))

    def reset(self, idx):
        self.state[idx] = self._rng.uniform(-0.05, 0.05,
                                            (len(idx), 4)).astype(np.float32)
        self.steps[idx] = 0
        return self.state.copy()

    def step(self, act):
        g, mc, mp, length, f, tau = 9.8, 1.0, 0.1, 0.5, 10.0, 0.02
        x, xd, th, thd = (self.state[:, 0], self.state[:, 1],
                          self.state[:, 2], self.state[:, 3])
        force = np.where(act == 1, f, -f)
        costh, sinth = np.cos(th), np.sin(th)
        tmp = (force + mp * length * thd ** 2 * sinth) / (mc + mp)
        thacc = (g * sinth - costh * tmp) / (
            length * (4.0 / 3.0 - mp * costh ** 2 / (mc + mp)))
        xacc = tmp - mp * length * thacc * costh / (mc + mp)
        self.state = np.stack([x + tau * xd, xd + tau * xacc,
                               th + tau * thd, thd + tau * thacc],
                              axis=1).astype(np.float32)
        self.steps += 1
        done = ((np.abs(self.state[:, 0]) > 2.4) |
                (np.abs(self.state[:, 2]) > 12 * np.pi / 180) |
                (self.steps >= 200))
        reward = np.ones(self.n, np.float32)
        if done.any():
            self.reset(np.nonzero(done)[0])
        return self.state.copy(), reward, done


class ACNet(gluon.Block):
    """Shared trunk, softmax policy head + scalar value head (the
    reference's model.py Agent builds the same two-headed net)."""

    def __init__(self, n_act, n_hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.fc = gluon.nn.Dense(n_hidden, activation="tanh")
            self.policy = gluon.nn.Dense(n_act)
            self.value = gluon.nn.Dense(1)

    def forward(self, x):
        h = self.fc(x)
        return self.policy(h), self.value(h)


def discount(rewards, dones, bootstrap, gamma):
    """Backward-accumulated n-step returns, cut at episode boundaries."""
    t_max, n = rewards.shape
    out = np.zeros((t_max, n), np.float32)
    run = bootstrap
    for t in range(t_max - 1, -1, -1):
        run = rewards[t] + gamma * run * (1.0 - dones[t])
        out[t] = run
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=250)
    ap.add_argument("--n-envs", type=int, default=16)
    ap.add_argument("--t-max", type=int, default=8)
    ap.add_argument("--gamma", type=float, default=0.99)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--entropy-wt", type=float, default=0.01)
    ap.add_argument("--value-wt", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=3)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    envs = VecCartPole(args.n_envs, seed=args.seed)
    net = ACNet(n_act=2)
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    obs = envs.state.copy()
    ep_lengths = []  # completed-episode lengths, rolling
    for it in range(args.iters):
        obs_buf = np.zeros((args.t_max, args.n_envs, 4), np.float32)
        act_buf = np.zeros((args.t_max, args.n_envs), np.int64)
        rew_buf = np.zeros((args.t_max, args.n_envs), np.float32)
        done_buf = np.zeros((args.t_max, args.n_envs), np.float32)
        for t in range(args.t_max):
            logits, _ = net(mx.nd.array(obs))
            p = mx.nd.softmax(logits).asnumpy()
            acts = (p.cumsum(axis=1) > rng.rand(args.n_envs, 1)).argmax(1)
            steps_before = envs.steps.copy()
            obs_buf[t], act_buf[t] = obs, acts
            obs, rew_buf[t], done = envs.step(acts)
            done_buf[t] = done.astype(np.float32)
            ep_lengths.extend(steps_before[done] + 1)
        _, v_boot = net(mx.nd.array(obs))
        returns = discount(rew_buf, done_buf,
                           v_boot.asnumpy().ravel(), args.gamma)

        flat_obs = mx.nd.array(obs_buf.reshape(-1, 4))
        flat_act = mx.nd.array(act_buf.reshape(-1).astype(np.float32))
        flat_ret = mx.nd.array(returns.reshape(-1, 1))
        with autograd.record():
            logits, values = net(flat_obs)
            logp = mx.nd.log_softmax(logits)
            p = mx.nd.softmax(logits)
            adv = (flat_ret - values).detach()
            chosen = mx.nd.pick(logp, flat_act, axis=1, keepdims=True)
            pg_loss = -(chosen * adv).mean()
            v_loss = ((values - flat_ret) ** 2).mean()
            ent = -(p * logp).sum(axis=1).mean()
            loss = (pg_loss + args.value_wt * v_loss -
                    args.entropy_wt * ent)
        loss.backward()
        trainer.step(1)
        if (it + 1) % 50 == 0 and ep_lengths:
            logging.info("iter %d mean episode length (last 20): %.1f",
                         it + 1, np.mean(ep_lengths[-20:]))
    return float(np.mean(ep_lengths[-20:])) if ep_lengths else 0.0


if __name__ == "__main__":
    print("mean episode length: %.1f" % main())
