"""Deep Q-Network on a deterministic grid world (parity: the reference's
example/reinforcement-learning/dqn — replay memory, epsilon-greedy
exploration, target network, TD(0) regression; dqn_demo.py trains via a
Q-value regression head exactly as here).

TPU-native shape: the Q-network is one fused Module program (forward,
TD-target regression backward, SGD update in a single jitted step); the
environment and replay buffer stay host-side numpy, feeding fixed-shape
batches so nothing retraces.

Run:  python dqn.py --updates 400
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


class GridWorld:
    """5x5 deterministic grid: start anywhere, goal at (4,4); reward +1 at
    the goal, -0.01 per step. Observation = one-hot cell index."""

    def __init__(self, n=5, max_steps=40, seed=0):
        self.n = n
        self.max_steps = max_steps
        self._rng = np.random.RandomState(seed)
        self.n_obs = n * n
        self.n_act = 4  # up, down, left, right
        self.reset()

    def reset(self, pos=None):
        self._pos = (tuple(pos) if pos is not None else
                     (self._rng.randint(self.n), self._rng.randint(self.n)))
        if self._pos == (self.n - 1, self.n - 1):
            self._pos = (0, 0)
        self._t = 0
        return self._obs()

    def _obs(self):
        o = np.zeros(self.n_obs, dtype=np.float32)
        o[self._pos[0] * self.n + self._pos[1]] = 1.0
        return o

    def step(self, act):
        r, c = self._pos
        dr, dc = ((-1, 0), (1, 0), (0, -1), (0, 1))[act]
        self._pos = (min(max(r + dr, 0), self.n - 1),
                     min(max(c + dc, 0), self.n - 1))
        self._t += 1
        done = self._pos == (self.n - 1, self.n - 1)
        reward = 1.0 if done else -0.01
        if self._t >= self.max_steps:
            done = True
        return self._obs(), reward, done


class ReplayMemory:
    """Uniform-sampling circular replay buffer (the reference keeps frames
    in a numpy ring the same way, replay_memory.py)."""

    def __init__(self, size, n_obs, rng):
        self.size = size
        self._rng = rng
        self.obs = np.zeros((size, n_obs), np.float32)
        self.act = np.zeros(size, np.int64)
        self.rew = np.zeros(size, np.float32)
        self.nxt = np.zeros((size, n_obs), np.float32)
        self.done = np.zeros(size, np.float32)
        self._n = 0
        self._i = 0

    def add(self, o, a, r, o2, d):
        i = self._i
        self.obs[i], self.act[i], self.rew[i] = o, a, r
        self.nxt[i], self.done[i] = o2, float(d)
        self._i = (i + 1) % self.size
        self._n = min(self._n + 1, self.size)

    def sample(self, k):
        idx = self._rng.randint(0, self._n, k)
        return (self.obs[idx], self.act[idx], self.rew[idx],
                self.nxt[idx], self.done[idx])

    def __len__(self):
        return self._n


def q_symbol(n_act, n_hidden=64):
    data = mx.sym.Variable("data")
    target = mx.sym.Variable("qtarget")
    h = mx.sym.FullyConnected(data, num_hidden=n_hidden, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=n_hidden, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    q = mx.sym.FullyConnected(h, num_hidden=n_act, name="qout")
    return mx.sym.LinearRegressionOutput(q, target, name="td")


def _batch(obs, tgt):
    return mx.io.DataBatch(data=[mx.nd.array(obs)],
                           label=[mx.nd.array(tgt)])


def _predict_q(mod, obs, n_act, batch):
    """Q-values for a (k, n_obs) observation block, padded to the bound
    batch size (the network is compiled for one fixed shape)."""
    k = obs.shape[0]
    pad = np.zeros((batch, obs.shape[1]), np.float32)
    pad[:k] = obs
    mod.forward(_batch(pad, np.zeros((batch, n_act), np.float32)),
                is_train=False)
    return mod.get_outputs()[0].asnumpy()[:k]


def greedy_action(mod, env, batch, o):
    q = _predict_q(mod, o[None, :], env.n_act, batch)
    return int(np.argmax(q[0]))


def greedy_return(mod, env, batch, starts):
    """Average undiscounted return of the greedy policy over fixed starts."""
    totals = []
    for s in starts:
        o = env.reset(pos=s)
        done, ret = False, 0.0
        while not done:
            o, r, done = env.step(greedy_action(mod, env, batch, o))
            ret += r
        totals.append(ret)
    return float(np.mean(totals))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--updates", type=int, default=400)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--gamma", type=float, default=0.95)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--target-sync", type=int, default=25)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    env = GridWorld(seed=args.seed)
    mem = ReplayMemory(4000, env.n_obs, rng)
    sym = q_symbol(env.n_act)
    batch = args.batch_size

    def make_mod(for_training):
        mod = mx.mod.Module(sym, data_names=("data",),
                            label_names=("qtarget",), context=mx.cpu())
        mod.bind(data_shapes=[("data", (batch, env.n_obs))],
                 label_shapes=[("qtarget", (batch, env.n_act))],
                 for_training=for_training)
        return mod

    qnet = make_mod(True)
    qnet.init_params(mx.initializer.Xavier())
    qnet.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": args.lr,
                                          "momentum": 0.9})
    tnet = make_mod(False)
    tnet.init_params(mx.initializer.Xavier())

    def sync_target():
        a, x = qnet.get_params()
        tnet.set_params(a, x)

    sync_target()

    eps, eps_min, eps_decay = 1.0, 0.05, 0.995
    o = env.reset()
    for upd in range(args.updates):
        # interact: a handful of env steps per gradient update
        for _ in range(4):
            if rng.rand() < eps:
                a = rng.randint(env.n_act)
            else:
                a = greedy_action(qnet, env, batch, o)
            o2, r, done = env.step(a)
            mem.add(o, a, r, o2, done)
            o = env.reset() if done else o2
        eps = max(eps_min, eps * eps_decay)
        if len(mem) < batch:
            continue
        obs, act, rew, nxt, done = mem.sample(batch)
        # TD target: r + gamma * max_a' Q_target(s', a') on live transitions
        qn = _predict_q(tnet, nxt, env.n_act, batch)
        tgt = _predict_q(qnet, obs, env.n_act, batch).copy()
        tgt[np.arange(batch), act] = rew + args.gamma * (1 - done) * \
            qn.max(axis=1)
        b = _batch(obs, tgt)
        qnet.forward(b, is_train=True)
        qnet.backward()
        qnet.update()
        if (upd + 1) % args.target_sync == 0:
            sync_target()
        if (upd + 1) % 100 == 0:
            logging.info("update %d eps=%.2f", upd + 1, eps)

    starts = [(0, 0), (0, 4), (4, 0), (2, 2)]
    ret = greedy_return(qnet, env, batch, starts)
    logging.info("greedy mean return over fixed starts: %.3f", ret)
    return ret


if __name__ == "__main__":
    print("greedy return: %.3f" % main())
