#!/usr/bin/env python
"""Train a decoder-only transformer LM on character data via Module.fit.

The long-context counterpart of examples/rnn/lstm_bucketing.py: same
Module training loop, but the model is mxtpu.models.transformer (flash
attention, O(T) residuals). With --seq-parallel the identical weights run
a ring-attention sequence-parallel forward over a 'seq' mesh axis —
the path a multi-chip pod uses for sequences too long for one chip.

Synthetic corpus by default (deterministic arithmetic text), or pass
--text FILE for a real one. Prints per-epoch perplexity; exits nonzero
if perplexity fails to improve, so it doubles as an integration gate.
"""
import argparse
import logging
import math

import numpy as np

import mxtpu as mx


def make_corpus(n_chars=40000, seed=7):
    """Deterministic 'a+b=c;' arithmetic text — structured enough that a
    small LM's perplexity falls fast."""
    rng = np.random.RandomState(seed)
    out = []
    while sum(len(s) for s in out) < n_chars:
        a, b = rng.randint(0, 50), rng.randint(0, 50)
        out.append("%d+%d=%d;" % (a, b, a + b))
    return "".join(out)[:n_chars]


def batches(text, vocab, seq_len, batch_size):
    ids = np.array([vocab[c] for c in text], dtype="float32")
    n_tok = (len(ids) - 1) // seq_len * seq_len
    x = ids[:n_tok].reshape(-1, seq_len)
    y = ids[1:n_tok + 1].reshape(-1, seq_len)
    n_batches = x.shape[0] // batch_size
    for i in range(n_batches):
        xs = x[i * batch_size:(i + 1) * batch_size]
        ys = y[i * batch_size:(i + 1) * batch_size].reshape(-1)
        yield xs, ys


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", default=None)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-heads", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seq-parallel", action="store_true",
                    help="validate a ring-attention sequence-parallel "
                         "forward with the trained weights")
    args = ap.parse_args(argv)

    text = (open(args.text).read() if args.text else make_corpus())
    vocab = {c: i for i, c in enumerate(sorted(set(text)))}
    V = len(vocab)
    logging.info("corpus %d chars, vocab %d", len(text), V)

    net = mx.models.get_transformer_lm(
        vocab_size=V, seq_len=args.seq_len, num_layers=args.num_layers,
        num_heads=args.num_heads, d_model=args.d_model)
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (args.batch_size, args.seq_len))],
             label_shapes=[("softmax_label",
                            (args.batch_size * args.seq_len,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    ppls = []
    for epoch in range(args.epochs):
        tot_nll, tot_tok = 0.0, 0
        for xs, ys in batches(text, vocab, args.seq_len, args.batch_size):
            db = mx.io.DataBatch(data=[mx.nd.array(xs)],
                                 label=[mx.nd.array(ys)])
            mod.forward_backward(db)
            mod.update()
            out = mod.get_outputs()[0].asnumpy()
            nll = -np.log(out[np.arange(len(ys)), ys.astype(int)] + 1e-9)
            tot_nll += nll.sum()
            tot_tok += len(ys)
        ppl = math.exp(tot_nll / tot_tok)
        ppls.append(ppl)
        logging.info("Epoch[%d] perplexity=%.3f", epoch, ppl)

    if args.seq_parallel:
        _validate_seq_parallel(mod, vocab, text, args)

    if len(ppls) > 1 and not ppls[-1] < ppls[0]:
        raise SystemExit("perplexity did not improve: %s" % ppls)
    return ppls


def _validate_seq_parallel(mod, vocab, text, args):
    """Ring attention over a seq-sharded mesh reproduces the single-device
    attention with the TRAINED layer-0 q/k/v projections applied to real
    token embeddings from the corpus."""
    import jax
    import jax.numpy as jnp

    from mxtpu.ops.attention import flash_attention
    from mxtpu.parallel import make_mesh, ring_attention

    n_dev = len(jax.devices())
    if n_dev < 2 or args.seq_len % n_dev:
        logging.info("seq-parallel check skipped (%d devices)", n_dev)
        return
    arg_params, _ = mod.get_params()
    w = {k: v.asnumpy().astype("float32") for k, v in arg_params.items()}
    T, H = args.seq_len, args.num_heads
    dh = args.d_model // H

    # real tokens -> trained embedding + position -> trained LN -> q/k/v
    ids = np.array([vocab[c] for c in text[:2 * T]]).reshape(2, T)
    h = w["tok_emb_weight"][ids] + w["pos_emb"]
    mu = h.mean(-1, keepdims=True)
    sd = np.sqrt(h.var(-1, keepdims=True) + 1e-5)
    ln = (h - mu) / sd * w["l0_ln1_gamma"] + w["l0_ln1_beta"]

    def proj(tag):
        p = ln @ w["l0_%s_weight" % tag].T + w["l0_%s_bias" % tag]
        return jnp.asarray(p.reshape(2, T, H, dh))  # ring layout (B,T,H,D)

    q, k, v = proj("q"), proj("k"), proj("v")
    ref = flash_attention(jnp.transpose(q, (0, 2, 1, 3)),
                          jnp.transpose(k, (0, 2, 1, 3)),
                          jnp.transpose(v, (0, 2, 1, 3)), causal=True)
    mesh = make_mesh(shape=(1, n_dev), axis_names=("data", "seq"))
    out = ring_attention(q, k, v, mesh=mesh, axis_name="seq", causal=True)
    out = jnp.transpose(out, (0, 2, 1, 3))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-4, atol=2e-5)
    logging.info("seq-parallel ring attention matches flash on the "
                 "trained layer-0 q/k/v (T=%d over %d devices)", T, n_dev)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
