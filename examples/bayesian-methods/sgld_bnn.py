"""Bayesian neural net via Stochastic Gradient Langevin Dynamics (parity:
the reference's example/bayesian-methods — bdk_demo.py/sgld demos train
with the SGLD optimizer, keep posterior weight samples after burn-in, and
predict with the sample ensemble).

TPU-native shape: SGLD's gradient+noise update is just another fused
optimizer rule (mxtpu/optimizer.py SGLD), so posterior sampling costs the
same per step as SGD; posterior snapshots are device-side param copies
(export_params is zero-transfer).

Run:  python sgld_bnn.py --epochs 20
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def mlp(num_classes):
    d = mx.sym.Variable("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(d, num_hidden=32,
                                                name="fc1"),
                          act_type="relu")
    return mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=num_classes, name="fc2"),
        name="softmax")


def two_moons(n, rng, noise=0.15):
    """Two interleaved half-circles — the classic BNN uncertainty demo."""
    t = rng.rand(n) * np.pi
    half = rng.randint(0, 2, n)
    x = np.where(half, 1.0 - np.cos(t), np.cos(t))
    y = np.where(half, 0.5 - np.sin(t), np.sin(t))
    X = np.stack([x, y], 1).astype("f4") + \
        noise * rng.randn(n, 2).astype("f4")
    return X, half.astype("f4")


def predict_probs(mod, X, batch):
    it = mx.io.NDArrayIter(X, np.zeros(len(X), "f4"), batch_size=batch)
    out = []
    for b in it:
        mod.forward(b, is_train=False)
        out.append(mod.get_outputs()[0].asnumpy())
    return np.concatenate(out)[:len(X)]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--burn-in", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=32)
    # NOTE the N/batch gradient rescale below: step sizes that look tame
    # for plain SGD diverge here, hence the small default
    ap.add_argument("--lr", type=float, default=0.0003)
    ap.add_argument("--seed", type=int, default=8)
    args = ap.parse_args(argv)
    args.epochs = max(args.epochs, 1)
    if args.burn_in >= args.epochs:   # guarantee a non-empty posterior
        args.burn_in = args.epochs - 1
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    X, y = two_moons(1200, rng)
    Xv, yv = two_moons(300, rng)
    train = mx.io.NDArrayIter(X, y, batch_size=args.batch_size,
                              shuffle=True)

    mod = mx.mod.Module(mlp(2), context=mx.cpu())
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    # wd gives the Gaussian prior; SGLD injects sqrt(lr) Gaussian noise.
    # The Langevin drift needs the FULL-dataset gradient scale, so the
    # batch-sum gradient is rescaled by N/batch (Welling & Teh eq. 4 —
    # same convention the reference's sgld demo uses).
    mod.init_optimizer(optimizer="sgld",
                       optimizer_params={"learning_rate": args.lr,
                                         "wd": 1e-4,
                                         "rescale_grad":
                                             float(len(X)) / args.batch_size})
    posterior = []
    for ep in range(args.epochs):
        train.reset()
        for b in train:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
        if ep >= args.burn_in:
            posterior.append({n: a.copy() for n, a in
                              mod.get_params()[0].items()})
    logging.info("kept %d posterior samples", len(posterior))

    # single-sample vs posterior-ensemble prediction
    probs_single = predict_probs(mod, Xv, args.batch_size)
    acc_single = float((probs_single.argmax(1) == yv).mean())

    # the Bayesian signature (Jensen): the mixture's predictive entropy
    # dominates the MEAN of the per-sample entropies — the gap is the
    # epistemic uncertainty a point estimate hasn't. One inference pass
    # per posterior sample feeds both the ensemble sum and the mean
    # entropy.
    ent = lambda p: float((-p * np.log(p + 1e-9)).sum(1).mean())  # noqa: E731
    ens = np.zeros_like(probs_single)
    h_mean_single = 0.0
    aux = mod.get_params()[1]
    for sample in posterior:
        mod.set_params(sample, aux)
        p = predict_probs(mod, Xv, args.batch_size)
        ens += p
        h_mean_single += ent(p)
    ens /= len(posterior)
    h_mean_single /= len(posterior)
    acc_ens = float((ens.argmax(1) == yv).mean())
    h_ens = ent(ens)
    spread = float(np.std([s["fc1_weight"].asnumpy() for s in posterior],
                          axis=0).mean())
    logging.info("acc single %.3f ensemble %.3f | H mean-single %.3f "
                 "ensemble %.3f | posterior weight spread %.4f",
                 acc_single, acc_ens, h_mean_single, h_ens, spread)
    return acc_single, acc_ens, h_mean_single, h_ens, spread


if __name__ == "__main__":
    print("single %.3f ens %.3f Hmean %.3f Hens %.3f spread %.4f" % main())
