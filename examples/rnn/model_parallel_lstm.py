"""Model-parallel LSTM (parity: example/model-parallel-lstm/lstm.py — the
reference's ONLY non-data-parallel strategy: group2ctx places layer groups
on different devices and the executor inserts the cross-device transfers;
like the reference example, this drives the raw Executor bind, not
Module).

TPU-native twist: ctx_group tags become device placements inside ONE
compiled program (mxtpu/executor.py _trace_graph placements) — XLA emits
the transfers the reference realized as _CrossDeviceCopy engine ops, and
overlaps them with compute.

Trains a 2-layer unrolled LSTM LM on a synthetic Markov corpus with the
embedding + layer 1 on ctx group 'embed_rnn1' and layer 2 + head on
'rnn2_head'. Run:  python model_parallel_lstm.py --epochs 3
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import rnn


def build_symbol(vocab, num_hidden, seq_len):
    with mx.AttrScope(ctx_group="embed_rnn1"):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab,
                                 output_dim=num_hidden, name="embed")
        cell1 = rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm1_")
        out1, _ = cell1.unroll(seq_len, inputs=embed, merge_outputs=True,
                               layout="NTC")
    with mx.AttrScope(ctx_group="rnn2_head"):
        cell2 = rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm2_")
        out2, _ = cell2.unroll(seq_len, inputs=out1, merge_outputs=True,
                               layout="NTC")
        flat = mx.sym.Reshape(out2, shape=(-1, num_hidden))
        fc = mx.sym.FullyConnected(flat, num_hidden=vocab, name="fc")
        lbl = mx.sym.Reshape(label, shape=(-1,))
        return mx.sym.SoftmaxOutput(fc, lbl, name="softmax",
                                    normalization="batch")


def synth_corpus(n_tokens, vocab, rng):
    """Markov chain: next token strongly depends on the previous one."""
    trans = rng.dirichlet(np.full(vocab, 0.08), size=vocab)
    toks = [int(rng.randint(vocab))]
    for _ in range(n_tokens - 1):
        toks.append(int(rng.choice(vocab, p=trans[toks[-1]])))
    return np.array(toks, dtype=np.float32)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--vocab", type=int, default=40)
    ap.add_argument("--num-hidden", type=int, default=48)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--n-tokens", type=int, default=6000)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(3)
    toks = synth_corpus(args.n_tokens, args.vocab, rng)
    n_seq = (len(toks) - 1) // args.seq_len
    X = toks[:n_seq * args.seq_len].reshape(n_seq, args.seq_len)
    Y = toks[1:n_seq * args.seq_len + 1].reshape(n_seq, args.seq_len)

    net = build_symbol(args.vocab, args.num_hidden, args.seq_len)

    # layer groups on two devices of the default platform (two CPU
    # "devices" under the test mesh; two chips on real hardware)
    import jax
    devs = jax.local_devices()
    plat = devs[0].platform
    g2c = {"embed_rnn1": mx.Context(plat, 0),
           "rnn2_head": mx.Context(plat, 1 if len(devs) > 1 else 0)}

    shapes, _, _ = net.infer_shape(
        data=(args.batch_size, args.seq_len),
        softmax_label=(args.batch_size, args.seq_len))
    arg_names = net.list_arguments()
    init = mx.initializer.Xavier()
    arrs, grads = {}, {}
    for n, s in zip(arg_names, shapes):
        arrs[n] = mx.nd.zeros(s)
        if n not in ("data", "softmax_label"):
            init(mx.initializer.InitDesc(n), arrs[n])
            grads[n] = mx.nd.zeros(s)
    exe = net.bind(mx.Context(plat, 0), arrs, args_grad=grads,
                   group2ctx=g2c)

    perplexities = []
    for e in range(args.epochs):
        tot_nll, tot_tok = 0.0, 0
        order = rng.permutation(n_seq // args.batch_size)
        for b in order:
            sl = slice(b * args.batch_size, (b + 1) * args.batch_size)
            arrs["data"][:] = mx.nd.array(X[sl])
            arrs["softmax_label"][:] = mx.nd.array(Y[sl])
            out = exe.forward(is_train=True)[0].asnumpy()
            exe.backward()
            for n, g in grads.items():
                mx.nd.sgd_update(arrs[n], g, lr=args.lr, out=arrs[n])
            p = out.reshape(-1, args.vocab)
            idx = Y[sl].reshape(-1).astype(int)
            tot_nll -= np.log(np.maximum(p[np.arange(len(idx)), idx],
                                         1e-10)).sum()
            tot_tok += len(idx)
        ppl = float(np.exp(tot_nll / tot_tok))
        perplexities.append(ppl)
        logging.info("epoch %d perplexity %.2f", e, ppl)
    return perplexities


if __name__ == "__main__":
    ppl = main()
    print("final perplexity %.2f" % ppl[-1])
