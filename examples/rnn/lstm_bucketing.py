#!/usr/bin/env python
"""LSTM bucketing language model (parity: example/rnn/lstm_bucketing.py —
baseline config 4: BucketingModule + BucketSentenceIter + stacked
LSTMCell.unroll + Perplexity)."""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxtpu as mx  # noqa: E402


def load_corpus(path, vocab=None):
    """PTB-style text -> sentences of word ids (parity rnn/io.py
    encode_sentences flow)."""
    from mxtpu.rnn.io import encode_sentences

    with open(path) as f:
        sentences = [line.strip().split() for line in f if line.strip()]
    return encode_sentences(sentences, vocab=vocab, start_label=2,
                            invalid_label=0)


def synthetic_corpus(n=400, vocab_size=60, seed=0):
    rng = np.random.RandomState(seed)
    sents = []
    for _ in range(n):
        ln = rng.randint(4, 33)
        # a learnable pattern: next id = id + 1 mod vocab
        start = rng.randint(2, vocab_size - 1)
        sents.append([(start + i) % (vocab_size - 2) + 2
                      for i in range(ln)])
    return sents, {i: i for i in range(vocab_size)}


def main(argv=None):
    """Returns the list of per-epoch validation perplexities (the config-4
    gate: perplexity must fall as training proceeds)."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--train-data", default=None, help="text corpus (PTB)")
    ap.add_argument("--num-hidden", type=int, default=128)
    ap.add_argument("--num-embed", type=int, default=64)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--fused", action="store_true",
                    help="fused multi-layer RNN op (cudnn_lstm_bucketing "
                         "parity; lowers to an XLA while loop)")
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    buckets = [10, 20, 30, 40]
    if args.train_data:
        sentences, vocab = load_corpus(args.train_data)
    else:
        logging.warning("no --train-data; using synthetic corpus")
        sentences, vocab = synthetic_corpus()
    vocab_size = max(max(v for v in vocab.values()), 2) + 1

    train = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                      buckets=buckets)

    if args.fused:
        # the cudnn_lstm_bucketing.py variant: one fused multi-layer op
        # (here an XLA while-loop RNN instead of cuDNN)
        stack = mx.rnn.FusedRNNCell(args.num_hidden,
                                    num_layers=args.num_layers,
                                    mode="lstm", prefix="lstm_")
    else:
        stack = mx.rnn.SequentialRNNCell()
        for i in range(args.num_layers):
            stack.add(mx.rnn.LSTMCell(num_hidden=args.num_hidden,
                                      prefix="lstm_l%d_" % i))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab_size,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, name="softmax")
        return pred, ("data",), ("softmax_label",)

    model = mx.mod.BucketingModule(
        sym_gen=sym_gen, default_bucket_key=train.default_bucket_key,
        context=mx.test_utils.default_context())
    val = mx.rnn.BucketSentenceIter(sentences, args.batch_size,
                                    buckets=buckets)
    per_epoch = []

    def _collect(param):
        for name, value in param.eval_metric.get_name_value():
            if name == "perplexity":
                per_epoch.append(value)

    model.fit(train, eval_data=val, num_epoch=args.num_epochs,
              eval_metric=mx.metric.Perplexity(ignore_label=0),
              optimizer="sgd",
              optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                                "wd": 1e-5, "clip_gradient": 1.0},
              initializer=mx.initializer.Xavier(factor_type="in",
                                                magnitude=2.34),
              batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                         20),
              eval_end_callback=_collect)
    logging.info("per-epoch validation perplexity: %s", per_epoch)
    return per_epoch


if __name__ == "__main__":
    main()
