"""Time-major (TNC) LSTM language model (parity:
example/rnn-time-major/rnn_cell_demo.py — the reference demonstrates the
time-major layout, which avoids the per-step transpose the batch-major
path pays; on TPU the same holds: `unroll(layout='TNC')` scans the leading
axis directly, so XLA never materializes an NTC->TNC transpose).

Synthetic corpus: each next token is (3*prev + 1) mod vocab with
occasional noise, so a converged model's perplexity approaches the noise
floor while a unigram model stays near log(vocab).

Run:  python rnn_cell_demo.py --epochs 8
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import rnn


def synth_corpus(n_seq, seq_len, vocab, rng):
    X = np.zeros((n_seq, seq_len), np.float32)
    Y = np.zeros((n_seq, seq_len), np.float32)
    for i in range(n_seq):
        t = rng.randint(0, vocab)
        for s in range(seq_len):
            X[i, s] = t
            nxt = (3 * t + 1) % vocab
            if rng.rand() < 0.05:          # 5% noise floor
                nxt = rng.randint(0, vocab)
            Y[i, s] = nxt
            t = nxt
    return X, Y


def build_symbol(vocab, seq_len, num_hidden):
    # data arrives time-major: (T, N)
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_hidden,
                             name="embed")             # (T, N, H)
    cell = rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm_")
    outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True,
                             layout="TNC")             # (T, N, H)
    pred = mx.sym.FullyConnected(mx.sym.Reshape(outputs, shape=(-1, num_hidden)),
                                 num_hidden=vocab, name="pred")
    lbl = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, lbl, name="softmax")


class TimeMajorIter(mx.io.DataIter):
    """Serves (T, N) batches from an (N, T) corpus — the transpose happens
    ONCE per batch on the host, not per step in the graph."""

    def __init__(self, X, Y, batch_size):
        super().__init__(batch_size)
        self._X, self._Y = X, Y
        self._i = 0
        T = X.shape[1]
        self.provide_data = [mx.io.DataDesc("data", (T, batch_size))]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (T, batch_size))]

    def reset(self):
        self._i = 0

    def next(self):
        if (self._i + 1) * self.batch_size > len(self._X):
            raise StopIteration
        sl = slice(self._i * self.batch_size, (self._i + 1) * self.batch_size)
        self._i += 1
        return mx.io.DataBatch(
            data=[mx.nd.array(self._X[sl].T)],
            label=[mx.nd.array(self._Y[sl].T)], pad=0, index=None,
            provide_data=self.provide_data,
            provide_label=self.provide_label)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-seq", type=int, default=1536)
    ap.add_argument("--seed", type=int, default=6)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    rng = np.random.RandomState(args.seed)
    X, Y = synth_corpus(args.num_seq, args.seq_len, args.vocab, rng)
    n_train = int(len(X) * 0.9)
    train = TimeMajorIter(X[:n_train], Y[:n_train], args.batch_size)
    val = TimeMajorIter(X[n_train:], Y[n_train:], args.batch_size)

    net = build_symbol(args.vocab, args.seq_len, args.num_hidden)
    mod = mx.mod.Module(net, context=mx.cpu(0))
    ppl = mx.metric.Perplexity(ignore_label=None)
    history = []
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.003})
    for epoch in range(args.epochs):
        train.reset()
        for batch in train:
            mod.forward_backward(batch)
            mod.update()
        val.reset()
        ppl.reset()
        for batch in val:
            mod.forward(batch, is_train=False)
            mod.update_metric(ppl, batch.label)
        history.append(ppl.get()[1])
        logging.info("Epoch[%d] val perplexity %.2f", epoch, history[-1])
    return history


if __name__ == "__main__":
    h = main()
    print("time-major LSTM val perplexity %.2f -> %.2f" % (h[0], h[-1]))
