"""SVM-output training (parity: example/svm_mnist/svm_mnist.py — the
SVMOutput head: hinge-loss gradients instead of softmax cross-entropy,
both the L1 margin and squared-hinge `use_linear` variants).

Run:  python svm_mnist.py --epochs 4
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def synth(n, rng):
    protos = rng.rand(10, 64) > 0.55
    y = rng.randint(0, 10, n)
    X = protos[y].astype("float32") + rng.randn(n, 64).astype("float32") * 0.2
    return X, y.astype("float32")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=1024)
    ap.add_argument("--margin", type=float, default=1.0)
    ap.add_argument("--squared", action="store_true",
                    help="squared hinge (SVMOutput use_linear=False role)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(2)
    X, Y = synth(args.num_examples, rng)
    it = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size, shuffle=True,
                           label_name="svm_label")

    data = mx.sym.Variable("data")
    lbl = mx.sym.Variable("svm_label")
    fc1 = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=10, name="fc2")
    net = mx.sym.SVMOutput(fc2, lbl, margin=args.margin,
                           use_linear=not args.squared, name="svm")

    mod = mx.mod.Module(net, context=mx.cpu(0), label_names=("svm_label",))
    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            eval_metric=mx.metric.Accuracy(),
            initializer=mx.initializer.Xavier())

    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0].asnumpy()
        n_valid = out.shape[0] - batch.pad
        correct += int((out.argmax(1)[:n_valid]
                        == batch.label[0].asnumpy()[:n_valid]).sum())
        total += n_valid
    acc = correct / total
    logging.info("train accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    print("accuracy %.3f" % main())
