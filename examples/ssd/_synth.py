"""Deterministic synthetic detection data for offline SSD runs: each image
carries one bright axis-aligned rectangle whose class is its color channel,
so the detector has real signal to learn (train) and score (evaluate)."""
import numpy as np

import mxtpu as mx


def make_batch(rng, batch_size, shape, num_classes, max_objs=8):
    """Returns (data NDArray, label (B, max_objs, 5)) with [cls,x1,y1,x2,y2]
    in relative coords; unused label rows are -1."""
    c, h, w = shape
    x = rng.rand(batch_size, c, h, w).astype("float32") * 0.1
    lab = np.full((batch_size, max_objs, 5), -1.0, "float32")
    for b in range(batch_size):
        cls = rng.randint(0, min(num_classes, c))
        cx, cy = rng.uniform(0.35, 0.65, 2)
        # half-extents sized to the default anchor spec (sizes 0.1-0.45),
        # so matching clears the 0.5 IoU threshold and positives exist
        bw, bh = rng.uniform(0.1, 0.2, 2)
        x1, y1 = max(cx - bw, 0.02), max(cy - bh, 0.02)
        x2, y2 = min(cx + bw, 0.98), min(cy + bh, 0.98)
        # paint the object: bright block in ITS class channel
        x[b, cls % c, int(y1 * h):int(y2 * h), int(x1 * w):int(x2 * w)] = 1.0
        lab[b, 0] = [cls, x1, y1, x2, y2]
    return x, lab


class SynthDetIter(mx.io.DataIter):
    """Fixed-size epoch of deterministic synthetic detection batches."""

    def __init__(self, batch_size, shape, num_classes, num_batches=4,
                 seed=0, max_objs=8):
        super().__init__(batch_size)
        self._shape = shape
        self._classes = num_classes
        self._num = num_batches
        self._seed = seed
        self._max_objs = max_objs
        self._i = 0
        self.provide_data = [mx.io.DataDesc("data",
                                            (batch_size,) + tuple(shape))]
        self.provide_label = [mx.io.DataDesc("label",
                                             (batch_size, max_objs, 5))]

    def reset(self):
        self._i = 0

    def next(self):
        if self._i >= self._num:
            raise StopIteration
        rng = np.random.RandomState(self._seed * 1000 + self._i)
        self._i += 1
        x, lab = make_batch(rng, self.batch_size, self._shape,
                            self._classes, self._max_objs)
        return mx.io.DataBatch(
            data=[mx.nd.array(x)], label=[mx.nd.array(lab)], pad=0,
            index=None, provide_data=self.provide_data,
            provide_label=self.provide_label)
