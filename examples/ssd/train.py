#!/usr/bin/env python
"""SSD detector training (parity: example/ssd/train.py → train/train_net.py
— baseline config 5: VGG16-reduced SSD over ImageDetRecordIter with the
MultiBox target/detection ops and a mAP-style metric)."""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxtpu as mx  # noqa: E402
from mxtpu.models import ssd as ssd_model  # noqa: E402


class MultiBoxMetric(mx.metric.EvalMetric):
    """Train-time metric pair (parity example/ssd/train/metric.py):
    cross-entropy over matched anchors + smooth-l1 loc loss."""

    def __init__(self):
        super().__init__("MultiBox")
        self.num = 2
        self.name = ["CrossEntropy", "SmoothL1"]
        self.reset()

    def reset(self):
        self.num_inst = [0, 0]
        self.sum_metric = [0.0, 0.0]

    def update(self, labels, preds):
        cls_prob = preds[0].asnumpy()
        loc_loss = preds[1].asnumpy()
        cls_label = preds[2].asnumpy()
        valid = cls_label >= 0
        label = cls_label[valid].astype(int)
        flat = np.moveaxis(cls_prob, 1, -1).reshape(-1, cls_prob.shape[1])
        prob = flat[valid.reshape(-1)][np.arange(label.size), label]
        self.sum_metric[0] += (-np.log(np.maximum(prob, 1e-12))).sum()
        self.num_inst[0] += label.size
        self.sum_metric[1] += np.abs(loc_loss).sum()
        self.num_inst[1] += max((cls_label > 0).sum(), 1)

    def get(self):
        return (self.name,
                [s / max(n, 1) for s, n in zip(self.sum_metric,
                                               self.num_inst)])

    def get_name_value(self):
        names, values = self.get()
        return list(zip(names, values))


def main(argv=None):
    """Returns (module, final MultiBox metric pairs); with --prefix set,
    also writes a checkpoint evaluate.py can score (the config-5 gate)."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--train-rec", default=None,
                    help="detection .rec (tools/im2rec.py packed .lst with "
                         "[2,5,id,xmin,ymin,xmax,ymax] labels)")
    ap.add_argument("--num-classes", type=int, default=20)
    ap.add_argument("--data-shape", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--num-scales", type=int, default=6)
    ap.add_argument("--network", default="vgg16_reduced",
                    choices=["vgg16_reduced", "tiny"])
    ap.add_argument("--num-batches", type=int, default=4,
                    help="synthetic batches per epoch (no --train-rec)")
    ap.add_argument("--lr", type=float, default=0.001)
    ap.add_argument("--prefix", default=None, help="checkpoint prefix")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    net = ssd_model.get_symbol_train(num_classes=args.num_classes,
                                     num_scales=args.num_scales,
                                     network=args.network)
    shape = (3, args.data_shape, args.data_shape)
    if args.train_rec:
        train = mx.io.ImageDetRecordIter(
            path_imgrec=args.train_rec, data_shape=shape,
            batch_size=args.batch_size, shuffle=True,
            mean_pixels=(123, 117, 104), rand_mirror_prob=0.5)
        batches = None
    else:
        logging.warning("no --train-rec; using synthetic painted boxes")
        from _synth import SynthDetIter
        train = SynthDetIter(args.batch_size, shape, args.num_classes,
                             num_batches=args.num_batches, seed=0)

    metric = MultiBoxMetric()
    mod = mx.mod.Module(net, label_names=("label",),
                        context=mx.test_utils.default_context())
    mod.fit(train, num_epoch=args.num_epochs,
            eval_metric=metric,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "momentum": 0.9, "wd": 5e-4},
            initializer=mx.initializer.Xavier(),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 10),
            epoch_end_callback=(mx.callback.do_checkpoint(args.prefix)
                                if args.prefix else None))
    if args.prefix:
        mx.nd.waitall()  # drain async checkpoint writes before scoring
    return mod, metric.get_name_value()


if __name__ == "__main__":
    main()
