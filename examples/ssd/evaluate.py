#!/usr/bin/env python
"""SSD evaluation: VOC-style mean average precision (parity:
example/ssd/evaluate.py + train/metric.py MApMetric)."""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxtpu as mx  # noqa: E402
from mxtpu.models import ssd as ssd_model  # noqa: E402


class MApMetric(mx.metric.EvalMetric):
    """VOC mean average precision (parity example/ssd/train/metric.py).

    update() takes detection outputs (N, num_det, 6) rows
    [cls, score, x1, y1, x2, y2] (invalid cls < 0) and labels
    (N, num_obj, >=5) rows [cls, x1, y1, x2, y2] (invalid cls < 0).
    """

    def __init__(self, ovp_thresh=0.5, use_difficult=False, class_names=None,
                 pred_idx=0):
        self.ovp_thresh = ovp_thresh
        self.use_difficult = use_difficult
        self.class_names = class_names
        self.pred_idx = int(pred_idx)
        super().__init__("mAP")
        self.reset()

    def reset(self):
        # per-class list of (score, tp) plus gt counts
        self.records = {}
        self.gt_counts = {}
        self.num_inst = 0
        self.sum_metric = 0.0

    @staticmethod
    def _iou(box, boxes):
        ix1 = np.maximum(box[0], boxes[:, 0])
        iy1 = np.maximum(box[1], boxes[:, 1])
        ix2 = np.minimum(box[2], boxes[:, 2])
        iy2 = np.minimum(box[3], boxes[:, 3])
        iw = np.maximum(ix2 - ix1, 0)
        ih = np.maximum(iy2 - iy1, 0)
        inter = iw * ih
        a1 = (box[2] - box[0]) * (box[3] - box[1])
        a2 = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        union = a1 + a2 - inter
        return inter / np.maximum(union, 1e-12)

    def update(self, labels, preds):
        det = preds[self.pred_idx].asnumpy()
        lab = labels[0].asnumpy()
        for i in range(det.shape[0]):
            d = det[i]
            d = d[d[:, 0] >= 0]
            g = lab[i]
            g = g[g[:, 0] >= 0]
            for cls in np.unique(np.concatenate([d[:, 0], g[:, 0]])):
                cls = int(cls)
                dc = d[d[:, 0] == cls]
                gc = g[g[:, 0] == cls][:, 1:5]
                self.gt_counts[cls] = self.gt_counts.get(cls, 0) + len(gc)
                taken = np.zeros(len(gc), bool)
                order = np.argsort(-dc[:, 1])
                for j in order:
                    box = dc[j, 2:6]
                    if len(gc):
                        ious = self._iou(box, gc)
                        best = int(np.argmax(ious))
                        if ious[best] >= self.ovp_thresh and not taken[best]:
                            taken[best] = True
                            self.records.setdefault(cls, []).append(
                                (dc[j, 1], 1))
                            continue
                    self.records.setdefault(cls, []).append((dc[j, 1], 0))

    def get(self):
        aps = []
        for cls, count in self.gt_counts.items():
            if count == 0:
                continue
            recs = sorted(self.records.get(cls, []), reverse=True)
            if not recs:
                aps.append(0.0)
                continue
            tps = np.cumsum([r[1] for r in recs])
            fps = np.cumsum([1 - r[1] for r in recs])
            recall = tps / count
            precision = tps / np.maximum(tps + fps, 1e-12)
            # VOC-style interpolated AP (all-points)
            ap = 0.0
            prev_r = 0.0
            for r, p in zip(recall, precision):
                ap += (r - prev_r) * np.max(
                    precision[recall >= r]) if r > prev_r else 0.0
                prev_r = r
            aps.append(ap)
        return "mAP", float(np.mean(aps)) if aps else 0.0


def main(argv=None):
    """Returns the mAP value (the config-5 gate: training must raise it)."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--val-rec", default=None,
                    help="detection .rec; omitted, deterministic synthetic "
                         "painted boxes are scored instead")
    ap.add_argument("--num-classes", type=int, default=20)
    ap.add_argument("--num-scales", type=int, default=6)
    ap.add_argument("--network", default="vgg16_reduced",
                    choices=["vgg16_reduced", "tiny"])
    ap.add_argument("--data-shape", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-batches", type=int, default=4,
                    help="synthetic batches (no --val-rec)")
    ap.add_argument("--prefix", default=None, help="checkpoint prefix")
    ap.add_argument("--epoch", type=int, default=0)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    net = ssd_model.get_symbol(num_classes=args.num_classes,
                               num_scales=args.num_scales,
                               network=args.network)
    shape = (3, args.data_shape, args.data_shape)
    if args.val_rec:
        it = mx.io.ImageDetRecordIter(
            path_imgrec=args.val_rec, data_shape=shape,
            batch_size=args.batch_size, mean_pixels=(123, 117, 104))
    else:
        logging.warning("no --val-rec; scoring synthetic painted boxes")
        from _synth import SynthDetIter
        it = SynthDetIter(args.batch_size, shape, args.num_classes,
                          num_batches=args.num_batches, seed=77)
    mod = mx.mod.Module(net, label_names=("label",),
                        context=mx.test_utils.default_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    if args.prefix:
        _, arg_params, aux_params = mx.model.load_checkpoint(args.prefix,
                                                             args.epoch)
        mod.set_params(arg_params, aux_params, allow_missing=True)
    else:
        mod.init_params()
    metric = MApMetric()
    for batch in it:
        mod.forward(batch, is_train=False)
        metric.update(batch.label, mod.get_outputs())
    name, value = metric.get()
    logging.info("%s: %.4f", name, value)
    return value


if __name__ == "__main__":
    main()
