#!/usr/bin/env python
"""Gluon super-resolution (parity: example/gluon/super_resolution.py in
the reference — ESPCN): conv stack + pixel shuffle upsampling, trained
imperatively with L2 loss; the quality metric is PSNR on held-out images.

Synthetic band-limited images by default (random low-frequency mixtures,
downsampled bicubic-ish by area averaging) so the gate runs offline.
Returns per-epoch validation PSNRs; exits nonzero when PSNR does not
improve over training.
"""
import argparse
import logging
import math

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn


class PixelShuffle(gluon.HybridBlock):
    """(B, C*r^2, H, W) -> (B, C, H*r, W*r) via reshape/transpose (the
    reference implements this with F.reshape + F.transpose the same way)."""

    def __init__(self, upscale_factor, **kwargs):
        super().__init__(**kwargs)
        self._r = int(upscale_factor)

    def hybrid_forward(self, F, x):
        r = self._r
        # shape magic (reference reshape semantics): -4 splits a dim,
        # 0 copies, -3 merges — shape-agnostic so it hybridizes
        x = F.reshape(x, shape=(0, -4, -1, r * r, 0, 0))  # (B,C,r^2,H,W)
        x = F.reshape(x, shape=(0, 0, -4, r, r, 0, 0))    # (B,C,r,r,H,W)
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))       # (B,C,H,r,W,r)
        return F.reshape(x, shape=(0, 0, -3, -3))         # (B,C,Hr,Wr)


class SuperResolutionNet(gluon.HybridBlock):
    def __init__(self, upscale_factor, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.conv1 = nn.Conv2D(64, kernel_size=5, padding=2)
            self.conv2 = nn.Conv2D(64, kernel_size=3, padding=1)
            self.conv3 = nn.Conv2D(32, kernel_size=3, padding=1)
            self.conv4 = nn.Conv2D(upscale_factor ** 2, kernel_size=3,
                                   padding=1)
            self.shuffle = PixelShuffle(upscale_factor)

    def hybrid_forward(self, F, x):
        x = F.Activation(self.conv1(x), act_type="relu")
        x = F.Activation(self.conv2(x), act_type="relu")
        x = F.Activation(self.conv3(x), act_type="relu")
        return self.shuffle(self.conv4(x))


def make_images(n, hr=32, seed=3):
    """Band-limited random images: sums of low-frequency 2D cosines."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[0:hr, 0:hr].astype("float32") / hr
    imgs = np.zeros((n, 1, hr, hr), "float32")
    for i in range(n):
        img = np.zeros((hr, hr), "float32")
        for _ in range(6):
            fx, fy = rng.uniform(0.5, 3.0, 2)
            ph = rng.uniform(0, 2 * np.pi, 2)
            img += rng.uniform(0.2, 1.0) * \
                np.cos(2 * np.pi * fx * xx + ph[0]) * \
                np.cos(2 * np.pi * fy * yy + ph[1])
        img -= img.min()
        imgs[i, 0] = img / max(img.max(), 1e-6)
    return imgs


def downsample(hr_imgs, r):
    b, c, h, w = hr_imgs.shape
    return hr_imgs.reshape(b, c, h // r, r, w // r, r).mean((3, 5))


def psnr(pred, target):
    mse = float(np.mean((pred - target) ** 2))
    return 99.0 if mse == 0 else 10.0 * math.log10(1.0 / mse)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--upscale", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--n-train", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.003)
    args = ap.parse_args(argv)

    if 32 % args.upscale:
        raise SystemExit("--upscale must divide the image size 32")
    hr_train = make_images(args.n_train)
    hr_val = make_images(16, seed=17)
    lr_train = downsample(hr_train, args.upscale)
    lr_val = downsample(hr_val, args.upscale)

    net = SuperResolutionNet(args.upscale)
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.L2Loss()

    def val_psnr():
        out = net(mx.nd.array(lr_val)).asnumpy()
        return psnr(out, hr_val)

    psnrs = [val_psnr()]
    logging.info("untrained val PSNR=%.2f dB", psnrs[0])
    for epoch in range(args.epochs):
        perm = np.random.RandomState(epoch).permutation(args.n_train)
        tot = 0.0
        for i in range(0, args.n_train, args.batch_size):
            sel = perm[i:i + args.batch_size]
            x = mx.nd.array(lr_train[sel])
            y = mx.nd.array(hr_train[sel])
            with autograd.record():
                L = loss_fn(net(x), y)   # per-sample losses
            L.backward()
            trainer.step(len(sel))       # grads rescaled by 1/batch here
            tot += float(L.mean().asscalar())
        psnrs.append(val_psnr())
        n_batches = (args.n_train + args.batch_size - 1) // args.batch_size
        logging.info("Epoch[%d] train-L2=%.5f val-PSNR=%.2f dB",
                     epoch, tot / n_batches, psnrs[-1])
    if psnrs[-1] <= psnrs[0]:
        raise SystemExit("PSNR did not improve: %s" % psnrs)
    return psnrs


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
