#!/usr/bin/env python
"""Gluon imperative training (parity: example/gluon/image_classification.py
— baseline config 3: model_zoo net + autograd.record + Trainer.step,
optionally hybridized)."""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxtpu as mx  # noqa: E402
from mxtpu import autograd, gluon  # noqa: E402
from mxtpu.gluon.model_zoo import vision  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--hybridize", action="store_true")
    ap.add_argument("--data-rec", default=None,
                    help=".rec pack; synthetic data when omitted")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = vision.get_model(args.model, classes=args.classes)
    net.collect_params().initialize(ctx=mx.test_utils.default_context())
    if args.hybridize:
        net.hybridize()

    if args.data_rec:
        train_iter = mx.io.ImageRecordIter(
            path_imgrec=args.data_rec, batch_size=args.batch_size,
            data_shape=(3, args.image_size, args.image_size), shuffle=True,
            rand_mirror=True, scale=1.0 / 255)
        batches = list(train_iter)
    else:
        rng = np.random.RandomState(0)
        batches = []
        for _ in range(16):
            x = mx.nd.array(rng.rand(args.batch_size, 3, args.image_size,
                                     args.image_size).astype("float32"))
            y = mx.nd.array(rng.randint(
                0, args.classes, args.batch_size).astype("float32"))
            batches.append(mx.io.DataBatch(data=[x], label=[y], pad=0,
                                           index=None))

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9,
                             "wd": 1e-4})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        tic = time.time()
        for batch in batches:
            data, label = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update([label], [out])
        name, acc = metric.get()
        logging.info("epoch %d: %s=%f (%.1f samples/s)", epoch, name, acc,
                     len(batches) * args.batch_size / (time.time() - tic))


if __name__ == "__main__":
    main()
