#!/usr/bin/env python
"""Gluon DCGAN (parity: example/gluon/dcgan.py in the reference): a
Conv2DTranspose generator against a strided-conv discriminator, trained
adversarially with SigmoidBinaryCrossEntropyLoss and two Trainers.

Synthetic image data by default (the band-limited textures from the
super-resolution example) so the gate runs offline. Success criterion
(returned): at some point in training the generator genuinely fools the
discriminator — the minimum over epochs of D's fake-detection rate falls
well below the ~1.0 it shows against an untrained generator (GAN
equilibria oscillate, so the minimum is the stable signal, not the
final value).
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn


def build_generator(ngf=32, nc=1):
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # latent (B, nz, 1, 1) -> (B, ngf*2, 4, 4)
        net.add(nn.Conv2DTranspose(ngf * 2, 4, strides=1, padding=0,
                                   use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        # -> (B, ngf, 8, 8)
        net.add(nn.Conv2DTranspose(ngf, 4, strides=2, padding=1,
                                   use_bias=False))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
        # -> (B, nc, 16, 16)
        net.add(nn.Conv2DTranspose(nc, 4, strides=2, padding=1,
                                   use_bias=False))
        net.add(nn.Activation("sigmoid"))
    return net


def build_discriminator(ndf=32):
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, strides=2, padding=1))      # 16 -> 8
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(ndf * 2, 4, strides=2, padding=1))  # 8 -> 4
        net.add(nn.BatchNorm())
        net.add(nn.LeakyReLU(0.2))
        net.add(nn.Conv2D(1, 4, strides=1, padding=0))        # 4 -> 1
    return net


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--nz", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--n-train", type=int, default=128)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args(argv)

    import super_resolution as sr  # reuse the deterministic image source
    data = sr.make_images(args.n_train, hr=16, seed=5)

    gen = build_generator()
    disc = build_discriminator()
    gen.initialize(mx.initializer.Normal(0.02))
    disc.initialize(mx.initializer.Normal(0.02))
    gt = gluon.Trainer(gen.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    dt = gluon.Trainer(disc.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    rng = np.random.RandomState(0)

    def noise(b):
        return mx.nd.array(rng.randn(b, args.nz, 1, 1).astype("float32"))

    def fake_acc(n=64):
        """Fraction of generator samples the discriminator calls fake."""
        logits = disc(gen(noise(n))).reshape((-1,)).asnumpy()
        return float((logits < 0).mean())

    B = args.batch_size
    real_y = mx.nd.array(np.ones(B, "float32"))
    fake_y = mx.nd.array(np.zeros(B, "float32"))
    acc0 = None
    min_acc = 1.0
    for epoch in range(args.epochs):
        perm = rng.permutation(args.n_train)
        dl = gl = 0.0
        nb = 0
        for i in range(0, args.n_train - B + 1, B):
            real = mx.nd.array(data[perm[i:i + B]])
            z = noise(B)
            # D step: real -> 1, fake -> 0 (fake detached via fresh fwd)
            with autograd.record():
                l_real = loss_fn(disc(real).reshape((-1,)), real_y)
                l_fake = loss_fn(disc(gen(z).detach()).reshape((-1,)),
                                 fake_y)
                l_d = l_real + l_fake
            l_d.backward()
            dt.step(B)
            # G step: make D call fakes real
            with autograd.record():
                l_g = loss_fn(disc(gen(z)).reshape((-1,)), real_y)
            l_g.backward()
            gt.step(B)
            dl += float(l_d.mean().asscalar())
            gl += float(l_g.mean().asscalar())
            nb += 1
        acc = fake_acc()
        if acc0 is None:
            acc0 = acc  # after 1 epoch, D trivially spots fakes
        min_acc = min(min_acc, acc)
        logging.info("Epoch[%d] d-loss=%.3f g-loss=%.3f D-spots-fakes=%.2f",
                     epoch, dl / nb, gl / nb, acc)
    logging.info("D fake-detection: %.2f after 1 epoch, min over epochs "
                 "%.2f", acc0, min_acc)
    return acc0, min_acc


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
