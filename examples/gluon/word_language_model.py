#!/usr/bin/env python
"""Gluon word-level language model (parity:
example/gluon/word_language_model/ in the reference): Embedding -> LSTM
(unrolled gluon.rnn cells) -> Dense head, trained imperatively with
autograd + Trainer + clipped SGD.

Synthetic corpus by default (token n-gram text with strong local
structure) so the gate runs offline; pass --text FILE for real data.
Returns per-epoch validation perplexities; exits nonzero when the last
is not an improvement — usable directly as an integration gate.
"""
import argparse
import logging
import math

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn, rnn


class RNNModel(gluon.Block):
    def __init__(self, vocab_size, num_embed, num_hidden, num_layers,
                 dropout=0.2, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, num_embed)
            self.rnn = rnn.SequentialRNNCell()
            with self.rnn.name_scope():
                for _ in range(num_layers):
                    self.rnn.add(rnn.LSTMCell(num_hidden))
            self.decoder = nn.Dense(vocab_size, flatten=False)
            self.num_hidden = num_hidden

    def forward(self, inputs, state):
        # inputs: (T, B) token ids
        emb = self.drop(self.encoder(inputs))
        outputs, state = self.rnn.unroll(emb.shape[0], emb, begin_state=state,
                                         layout="TNC", merge_outputs=True)
        decoded = self.decoder(self.drop(outputs))
        return decoded, state

    def begin_state(self, batch_size, **kwargs):
        return self.rnn.begin_state(batch_size=batch_size, **kwargs)


def make_corpus(n_tokens=30000, vocab=40, seed=11):
    """Markov chain with sharply peaked transitions: a model that learns
    the chain beats the unigram baseline decisively."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.full(vocab, 0.05), size=vocab)
    toks = [0]
    for _ in range(n_tokens - 1):
        toks.append(int(rng.choice(vocab, p=trans[toks[-1]])))
    return np.array(toks, dtype="int64"), vocab


def batchify(data, batch_size):
    n = len(data) // batch_size
    return data[:n * batch_size].reshape(batch_size, n).T  # (T, B)


def detach(state):
    if isinstance(state, (list, tuple)):
        return [detach(s) for s in state]
    return state.detach()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", default=None)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-layers", type=int, default=1)
    ap.add_argument("--bptt", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--dropout", type=float, default=0.0)
    ap.add_argument("--n-tokens", type=int, default=30000,
                    help="synthetic corpus size (ignored with --text)")
    args = ap.parse_args(argv)

    if args.text:
        words = open(args.text).read().split()
        idx = {w: i for i, w in enumerate(sorted(set(words)))}
        corpus = np.array([idx[w] for w in words], dtype="int64")
        vocab = len(idx)
    else:
        corpus, vocab = make_corpus(n_tokens=args.n_tokens)
    split = int(len(corpus) * 0.9)
    train = batchify(corpus[:split], args.batch_size)
    val = batchify(corpus[split:], args.batch_size)

    model = RNNModel(vocab, args.num_embed, args.num_hidden,
                     args.num_layers, args.dropout)
    model.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0,
                             "clip_gradient": args.clip})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def run_epoch(data, training):
        if data.shape[0] < 3:
            raise SystemExit("corpus too small for batch size %d"
                             % args.batch_size)
        total, count = 0.0, 0
        state = model.begin_state(batch_size=args.batch_size)
        # truncated final window included (reference example walks the
        # whole sequence, shortening the last BPTT slice)
        for i in range(0, data.shape[0] - 1, args.bptt):
            seq = min(args.bptt, data.shape[0] - 1 - i)
            x = mx.nd.array(data[i:i + seq].astype("float32"))
            y = mx.nd.array(data[i + 1:i + 1 + seq]
                            .astype("float32")).reshape((-1,))
            state = detach(state)
            if training:
                with autograd.record():
                    out, state = model(x, state)
                    L = loss_fn(out.reshape((-1, vocab)), y)
                L.backward()
                trainer.step(x.shape[0] * x.shape[1])
                lv = L
            else:
                out, state = model(x, state)
                lv = loss_fn(out.reshape((-1, vocab)), y)
            total += float(lv.mean().asscalar()) * y.shape[0]
            count += y.shape[0]
        return math.exp(total / count)

    ppls = []
    for epoch in range(args.epochs):
        train_ppl = run_epoch(train, training=True)
        val_ppl = run_epoch(val, training=False)
        ppls.append(val_ppl)
        logging.info("Epoch[%d] train-ppl=%.2f val-ppl=%.2f",
                     epoch, train_ppl, val_ppl)
    if len(ppls) > 1 and not ppls[-1] < ppls[0]:
        raise SystemExit("val perplexity did not improve: %s" % ppls)
    return ppls


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
