"""Matrix-factorization recommender (parity: example/recommenders/
matrix_fact.py + demo1-MF: user/item Embeddings, inner-product rating
prediction, LinearRegressionOutput head, RMSE metric). Synthetic
MovieLens-shaped data from ground-truth low-rank factors.

Run:  python matrix_fact.py --epochs 8
"""
import argparse
import logging
import math

import numpy as np

import mxtpu as mx


def plain_net(max_user, max_item, k):
    """pred(u, i) = <user_emb[u], item_emb[i]> (demo1-MF plain_net)."""
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score")
    user = mx.sym.Embedding(user, input_dim=max_user, output_dim=k,
                            name="user_emb")
    item = mx.sym.Embedding(item, input_dim=max_item, output_dim=k,
                            name="item_emb")
    pred = user * item
    pred = mx.sym.sum(pred, axis=1)
    pred = mx.sym.Flatten(pred)
    return mx.sym.LinearRegressionOutput(pred, score, name="lro")


def rmse(label, pred):
    pred = pred.ravel()
    label = label.ravel()
    return math.sqrt(float(((label - pred) ** 2).mean()))


def synth_ratings(n, max_user, max_item, k, rng, noise=0.1):
    """Ratings from hidden low-rank factors — learnable to ~`noise` RMSE."""
    U = rng.randn(max_user, k).astype("float32") / math.sqrt(k)
    V = rng.randn(max_item, k).astype("float32") / math.sqrt(k)
    u = rng.randint(0, max_user, n)
    i = rng.randint(0, max_item, n)
    r = (U[u] * V[i]).sum(axis=1) + rng.randn(n).astype("float32") * noise
    return u.astype("float32"), i.astype("float32"), r.astype("float32")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-ratings", type=int, default=8192)
    ap.add_argument("--max-user", type=int, default=100)
    ap.add_argument("--max-item", type=int, default=80)
    ap.add_argument("--factors", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(11)
    u, i, r = synth_ratings(args.num_ratings, args.max_user, args.max_item,
                            args.factors, rng)
    nval = args.num_ratings // 8
    train = mx.io.NDArrayIter({"user": u[:-nval], "item": i[:-nval]},
                              r[:-nval], args.batch_size, shuffle=True,
                              label_name="score")
    val = mx.io.NDArrayIter({"user": u[-nval:], "item": i[-nval:]},
                            r[-nval:], args.batch_size, label_name="score")

    net = plain_net(args.max_user, args.max_item, args.factors)
    mod = mx.mod.Module(net, context=mx.cpu(0),
                        data_names=("user", "item"), label_names=("score",))
    metric = mx.metric.create(rmse)
    mod.fit(train, eval_data=val, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": args.lr, "wd": 1e-4},
            eval_metric=metric,
            initializer=mx.initializer.Normal(0.05))

    final = mx.metric.create(rmse)
    mod.score(val, final)
    score = final.get()[1]
    logging.info("matrix-factorization val RMSE: %.4f", score)
    return score


if __name__ == "__main__":
    main()
