"""A numpy loss head behind a symbolic trunk via SequentialModule
(parity: example/module/python_loss.py — the reference chains
SequentialModule(Module(MLP), PythonLossModule(grad_func=mc_hinge_grad)):
the multiclass-hinge gradient is computed in plain numpy on the host and
injected back into the symbolic trunk's backward).

Run:  python python_loss.py --epochs 8
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def mc_hinge_grad(scores, labels):
    """Crammer-Singer multiclass hinge subgradient, pure numpy."""
    scores = scores.asnumpy()
    labels = labels.asnumpy().astype(np.int64)
    n, _ = scores.shape
    grad = np.zeros_like(scores)
    for i in range(n):
        margin = 1.0 + scores[i] - scores[i, labels[i]]
        margin[labels[i]] = 0.0
        worst = margin.argmax()
        if margin[worst] > 0:
            grad[i, labels[i]] -= 1.0
            grad[i, worst] += 1.0
    return grad / n


def synth(n, rng, classes=5, dim=32):
    protos = (rng.rand(classes, dim) > 0.5).astype("f4")
    y = rng.randint(0, classes, n)
    X = protos[y] + rng.randn(n, dim).astype("f4") * 0.25
    return X, y.astype("f4")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-examples", type=int, default=1024)
    ap.add_argument("--seed", type=int, default=4)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    rng = np.random.RandomState(args.seed)
    X, y = synth(args.num_examples, rng)
    nval = args.num_examples // 4
    train = mx.io.NDArrayIter(X[:-nval], y[:-nval], args.batch_size,
                              shuffle=True, label_name="softmax_label")
    val = mx.io.NDArrayIter(X[-nval:], y[-nval:], args.batch_size,
                            label_name="softmax_label")

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=5, name="fc2")

    mod = mx.mod.SequentialModule()
    mod.add(mx.mod.Module(net, context=mx.cpu(0), label_names=()),
            auto_wiring=True)
    mod.add(mx.mod.PythonLossModule(grad_func=mc_hinge_grad),
            take_labels=True, auto_wiring=True)

    mod.fit(train, eval_data=val, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            eval_metric="acc", initializer=mx.initializer.Xavier())

    val.reset()
    metric = mx.metric.Accuracy()
    acc = mod.score(val, metric)[0][1]
    logging.info("hinge-trained val accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    print("python-loss val accuracy %.3f" % main())
