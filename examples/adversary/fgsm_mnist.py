"""Fast-gradient-sign adversarial examples (parity: example/adversary/ —
train a small net, then perturb inputs along the INPUT gradient's sign
and watch accuracy collapse).

Exercises the imperative autograd path with gradients taken w.r.t. DATA
(mark_variables on the input batch), the flow the reference's adversary
notebook drives through mx.autograd.

Run:  python fgsm_mnist.py --epochs 3 --epsilon 0.2
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, nd


def synth_digits(n, rng):
    """Synthetic 10-class 'glyph' images (8x8): distinct random prototype
    per class + noise — linearly separable enough for a tiny net."""
    protos = rng.rand(10, 64) > 0.55
    y = rng.randint(0, 10, n)
    X = protos[y].astype("float32")
    X += rng.randn(n, 64).astype("float32") * 0.25
    return X.reshape(n, 1, 8, 8).clip(0, 1), y.astype("float32")


def forward(params, x, y=None):
    c = nd.Convolution(x, params["cw"], params["cb"], kernel=(3, 3),
                       num_filter=8)
    a = nd.Activation(c, act_type="relu")
    p = nd.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f = nd.Flatten(p)
    fc = nd.FullyConnected(f, params["fw"], params["fb"], num_hidden=10)
    if y is None:
        return fc
    return fc, nd.SoftmaxOutput(fc, y, normalization="batch")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--epsilon", type=float, default=0.2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=1024)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(0)
    X, Y = synth_digits(args.num_examples, rng)

    params = {
        "cw": nd.array(rng.randn(8, 1, 3, 3).astype("float32") * 0.3),
        "cb": nd.array(np.zeros(8, "float32")),
        "fw": nd.array(rng.randn(10, 72).astype("float32") * 0.1),
        "fb": nd.array(np.zeros(10, "float32")),
    }
    for p in params.values():
        p.attach_grad()

    bs = args.batch_size
    for e in range(args.epochs):
        for i in range(0, len(X), bs):
            xb = nd.array(X[i:i + bs])
            yb = nd.array(Y[i:i + bs])
            with autograd.record():
                _, sm = forward(params, xb, yb)
            sm.backward()
            for p in params.values():
                nd.sgd_update(p, p.grad, lr=0.5, out=p)

    def accuracy(Xe):
        correct = 0
        for i in range(0, len(Xe), bs):
            fc = forward(params, nd.array(Xe[i:i + bs]))
            correct += int((fc.asnumpy().argmax(1)
                            == Y[i:i + bs].astype(int)).sum())
        return correct / len(Xe)

    clean_acc = accuracy(X)

    # FGSM: gradient of the loss w.r.t. the INPUT, one signed step
    X_adv = np.empty_like(X)
    for i in range(0, len(X), bs):
        xb = nd.array(X[i:i + bs])
        yb = nd.array(Y[i:i + bs])
        xb.attach_grad()
        with autograd.record():
            _, sm = forward(params, xb, yb)
        sm.backward()
        X_adv[i:i + bs] = np.clip(
            X[i:i + bs] + args.epsilon * np.sign(xb.grad.asnumpy()), 0, 1)
    adv_acc = accuracy(X_adv)

    logging.info("clean accuracy %.3f, adversarial accuracy %.3f",
                 clean_acc, adv_acc)
    return clean_acc, adv_acc


if __name__ == "__main__":
    clean, adv = main()
    print("clean %.3f adversarial %.3f" % (clean, adv))
