"""Neural style transfer (parity: example/neural-style/nstyle.py — the
input-space optimization flow: style Gram matrices via the
``FullyConnected(x, x, no_bias=True)`` dot-trick, target Variables,
symbolic sum-of-squares losses, an executor bound with gradient on the
DATA variable, and optimizer steps applied to the image itself).

The reference extracts features with downloaded VGG19 weights; offline
here, the feature net is a small fixed random conv stack — the transfer
machinery (grams, losses, input-space gradients) is identical.

Run:  python nstyle.py --iters 40
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def feature_symbol():
    """Two conv feature maps (relu1/relu2) standing in for the VGG relus
    (model_vgg19.py get_symbol returns style + content layer groups)."""
    data = mx.sym.Variable("data")
    conv1 = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                               num_filter=8, no_bias=True, name="feat_conv1")
    relu1 = mx.sym.Activation(conv1, act_type="relu", name="feat_relu1")
    conv2 = mx.sym.Convolution(relu1, kernel=(3, 3), pad=(1, 1),
                               stride=(2, 2), num_filter=16, no_bias=True,
                               name="feat_conv2")
    relu2 = mx.sym.Activation(conv2, act_type="relu", name="feat_relu2")
    style = mx.sym.Group([relu1, relu2])
    content = relu2
    return style, content


def style_gram_symbol(input_size, style):
    """Gram matrix per style layer via the reference's FC dot-trick
    (nstyle.py:120-131)."""
    _, output_shapes, _ = style.infer_shape(
        data=(1, 1, input_size[0], input_size[1]))
    gram_list = []
    grad_scale = []
    for i in range(len(style.list_outputs())):
        shape = output_shapes[i]
        x = mx.sym.Reshape(style[i], target_shape=(int(shape[1]),
                                                   int(np.prod(shape[2:]))))
        gram = mx.sym.FullyConnected(x, x, no_bias=True,
                                     num_hidden=int(shape[1]))
        gram_list.append(gram)
        grad_scale.append(float(np.prod(shape[1:])) * shape[1])
    return mx.sym.Group(gram_list), grad_scale


def get_loss(gram, content):
    """Sum-of-squares losses against target Variables (nstyle.py:134)."""
    gram_loss = []
    for i in range(len(gram.list_outputs())):
        gvar = mx.sym.Variable("target_gram_%d" % i)
        gram_loss.append(mx.sym.sum(mx.sym.square(gvar - gram[i])))
    cvar = mx.sym.Variable("target_content")
    content_loss = mx.sym.sum(mx.sym.square(cvar - content))
    return mx.sym.Group(gram_loss), content_loss


def _fixed_feature_args(rng, sym, size):
    """Fixed random feature weights, shared by every executor."""
    args = {}
    arg_shapes, _, _ = sym.infer_shape(data=(1, 1, size[0], size[1]))
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name.startswith("feat_"):
            args[name] = mx.nd.array(
                (rng.randn(*shape) * 0.4).astype("float32"))
    return args


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=40)
    ap.add_argument("--size", type=int, default=24)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--content-weight", type=float, default=10.0)
    ap.add_argument("--style-weight", type=float, default=1.0)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    size = (args.size, args.size)
    rng = np.random.RandomState(6)
    # content: smooth blob; style: diagonal stripes
    ys, xs = np.mgrid[0:size[0], 0:size[1]]
    content_np = np.exp(-((ys - size[0] / 2) ** 2 +
                          (xs - size[1] / 2) ** 2) / 40.0)
    content_np = content_np[None, None].astype("float32")
    style_np = (np.sin((ys + xs) * 0.8) > 0).astype("float32")[None, None]

    style, content = feature_symbol()
    gram, gscale = style_gram_symbol(size, style)
    feat_args = _fixed_feature_args(rng, style, size)

    # pass 1: record style grams + content features of the two sources
    ex = mx.sym.Group([gram, content]).bind(
        mx.cpu(), dict(feat_args, data=mx.nd.array(style_np)),
        grad_req="null")
    ex.forward()
    n_gram = len(gram.list_outputs())
    style_targets = [o.copyto(mx.cpu()) for o in ex.outputs[:n_gram]]
    ex.arg_dict["data"][:] = content_np
    ex.forward()
    content_target = ex.outputs[n_gram].copyto(mx.cpu())

    # pass 2: loss executor, gradient ON THE IMAGE only
    style_loss, content_loss = get_loss(gram, content)
    total = mx.sym.Group([style_loss, content_loss])
    img = mx.nd.array(rng.uniform(-0.1, 0.1, (1, 1) + size)
                      .astype("float32"))
    arg_map = dict(feat_args, data=img)
    for i, t in enumerate(style_targets):
        arg_map["target_gram_%d" % i] = t
    arg_map["target_content"] = content_target
    grad_req = {n: "null" for n in total.list_arguments()}
    grad_req["data"] = "write"
    data_grad = mx.nd.zeros(img.shape)
    ex = total.bind(mx.cpu(), arg_map, args_grad={"data": data_grad},
                    grad_req=grad_req)

    opt = mx.optimizer.create("adam", learning_rate=args.lr)
    updater = mx.optimizer.get_updater(opt)
    first = last = None
    for it in range(args.iters):
        ex.forward(is_train=True)
        losses = [float(o.asnumpy()) for o in ex.outputs]
        weighted = (args.style_weight *
                    sum(l / s for l, s in zip(losses[:n_gram], gscale)) +
                    args.content_weight * losses[n_gram] /
                    float(np.prod(content_target.shape)))
        if first is None:
            first = weighted
        last = weighted
        # head grads: weight each loss output like the reference's
        # grad_scale bookkeeping
        heads = [mx.nd.array(np.array(args.style_weight / s, "float32"))
                 for s in gscale]
        heads.append(mx.nd.array(np.array(
            args.content_weight / float(np.prod(content_target.shape)),
            "float32")))
        ex.backward(heads)
        updater(0, data_grad, img)
        if it % 10 == 0:
            logging.info("iter %d: weighted loss %.5f", it, weighted)

    logging.info("nstyle: loss %.5f -> %.5f", first, last)
    return first, last


if __name__ == "__main__":
    main()
