"""Faster R-CNN end-to-end training on synthetic detection data (parity:
example/rcnn/train_end2end.py — the two-stage detector wiring: RPN heads
trained with anchor targets, `_contrib_Proposal` turning RPN outputs into
ROIs, a python CustomOp assigning stage-2 targets to sampled proposals
(the reference's rcnn/symbol/proposal_target.py layer), `ROIPooling` over
the shared feature map, and joint classification + smooth-L1 bbox heads).

Images are 3x64x64 with one painted rectangle whose class is its color
channel (the same signal as examples/ssd/_synth.py). The gate is top-1
detection accuracy: predicted class matches AND IoU > 0.5.

Run:  python train_end2end.py --epochs 6
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu.ops.spatial import _gen_anchors

IMG = 64
STRIDE = 8
FEAT = IMG // STRIDE
SCALES = (2.0, 3.0)
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
NUM_CLASSES = 3            # fg classes; stage-2 adds background as class 0
ROIS_PER_IMG = 8
POST_NMS = 16


def _all_anchors():
    """(A*H*W, 4) pixel anchors in label order a*H*W + y*W + x — the order
    rpn_cls_score reshaped to (2, A, H, W) flattens to."""
    base = _gen_anchors(STRIDE, SCALES, RATIOS)  # (A,4)
    out = np.zeros((A, FEAT, FEAT, 4), np.float32)
    for a in range(A):
        for y in range(FEAT):
            for x in range(FEAT):
                sx, sy = x * STRIDE, y * STRIDE
                out[a, y, x] = base[a] + [sx, sy, sx, sy]
    return out.reshape(-1, 4)


ANCHORS = _all_anchors()


def _iou(boxes, gt):
    """boxes (K,4), gt (4,) -> (K,) IoU with the +1 width convention."""
    ix1 = np.maximum(boxes[:, 0], gt[0])
    iy1 = np.maximum(boxes[:, 1], gt[1])
    ix2 = np.minimum(boxes[:, 2], gt[2])
    iy2 = np.minimum(boxes[:, 3], gt[3])
    iw = np.maximum(ix2 - ix1 + 1, 0)
    ih = np.maximum(iy2 - iy1 + 1, 0)
    inter = iw * ih
    area = ((boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1] + 1)
            + (gt[2] - gt[0] + 1) * (gt[3] - gt[1] + 1) - inter)
    return inter / np.maximum(area, 1e-6)


def _bbox_transform(anchors, gt):
    """Encode gt (4,) against anchors (K,4) -> (K,4) [dx,dy,dw,dh]."""
    aw = anchors[:, 2] - anchors[:, 0] + 1
    ah = anchors[:, 3] - anchors[:, 1] + 1
    acx = anchors[:, 0] + 0.5 * (aw - 1)
    acy = anchors[:, 1] + 0.5 * (ah - 1)
    gw = gt[2] - gt[0] + 1
    gh = gt[3] - gt[1] + 1
    gcx = gt[0] + 0.5 * (gw - 1)
    gcy = gt[1] + 0.5 * (gh - 1)
    return np.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                     np.log(gw / aw), np.log(gh / ah)], axis=-1)


def _bbox_decode(rois, deltas):
    """Decode stage-2 deltas (K,4) against roi boxes (K,4)."""
    w = rois[:, 2] - rois[:, 0] + 1
    h = rois[:, 3] - rois[:, 1] + 1
    cx = rois[:, 0] + 0.5 * (w - 1)
    cy = rois[:, 1] + 0.5 * (h - 1)
    pcx = deltas[:, 0] * w + cx
    pcy = deltas[:, 1] * h + cy
    pw = np.exp(deltas[:, 2]) * w
    ph = np.exp(deltas[:, 3]) * h
    return np.stack([pcx - 0.5 * (pw - 1), pcy - 0.5 * (ph - 1),
                     pcx + 0.5 * (pw - 1), pcy + 0.5 * (ph - 1)], axis=-1)


def make_batch(rng, n):
    """Returns data (N,3,64,64), im_info (N,3), rpn_label (N, A*H*W),
    rpn_bbox_target (N,4A,H,W), rpn_bbox_weight, gt_boxes (N,1,5) px."""
    x = rng.rand(n, 3, IMG, IMG).astype(np.float32) * 0.1
    gt = np.zeros((n, 1, 5), np.float32)
    lab = np.full((n, A * FEAT * FEAT), -1.0, np.float32)
    btgt = np.zeros((n, 4 * A, FEAT, FEAT), np.float32)
    bwt = np.zeros_like(btgt)
    for b in range(n):
        cls = rng.randint(0, NUM_CLASSES)
        cx, cy = rng.uniform(0.3, 0.7, 2) * IMG
        half = rng.uniform(7.0, 12.0, 2)
        x1, y1 = max(cx - half[0], 1), max(cy - half[1], 1)
        x2, y2 = min(cx + half[0], IMG - 2), min(cy + half[1], IMG - 2)
        x[b, cls, int(y1):int(y2), int(x1):int(x2)] = 1.0
        gt[b, 0] = [cls, x1, y1, x2, y2]
        ious = _iou(ANCHORS, gt[b, 0, 1:])
        pos = ious > 0.5
        pos[np.argmax(ious)] = True
        neg = ious < 0.3
        lab[b, pos] = 1.0
        # balance: keep ~3 negatives per positive, ignore the rest
        neg_idx = np.where(neg & ~pos)[0]
        keep = rng.permutation(neg_idx)[:max(3 * int(pos.sum()), 6)]
        lab[b, keep] = 0.0
        tgt = _bbox_transform(ANCHORS, gt[b, 0, 1:])
        for idx in np.where(pos)[0]:
            a, rem = divmod(idx, FEAT * FEAT)
            fy, fx = divmod(rem, FEAT)
            btgt[b, 4 * a:4 * a + 4, fy, fx] = tgt[idx]
            bwt[b, 4 * a:4 * a + 4, fy, fx] = 1.0
    info = np.tile(np.array([IMG, IMG, 1.0], np.float32), (n, 1))
    return x, info, lab, btgt, bwt, gt


class ProposalTarget(mx.operator.CustomOp):
    """Stage-2 target assignment (reference rcnn proposal_target.py): sample
    a fixed ROIS_PER_IMG proposals per image (gt box appended so positives
    always exist), label each by IoU, and emit per-class bbox targets."""

    def forward(self, is_train, req, in_data, out_data, aux):
        rois = in_data[0].asnumpy()        # (N*POST, 5)
        gts = in_data[1].asnumpy()         # (N, 1, 5)
        n = gts.shape[0]
        R = ROIS_PER_IMG
        K1 = NUM_CLASSES + 1
        out_rois = np.zeros((n * R, 5), np.float32)
        labels = np.zeros((n * R,), np.float32)
        btgt = np.zeros((n * R, 4 * K1), np.float32)
        bwt = np.zeros_like(btgt)
        per_img = rois.reshape(n, -1, 5)
        for b in range(n):
            # gt box joins the candidate pool so positives always exist
            cand = np.concatenate([per_img[b][:, 1:], gts[b, :, 1:]])
            ious = _iou(cand, gts[b, 0, 1:])
            order = np.argsort(-ious)
            fg = order[ious[order] > 0.5][:R // 2]
            bg = order[ious[order] <= 0.5][:R - len(fg)]
            pick = np.concatenate([fg, bg])
            if len(pick) < R:              # degenerate: repeat best
                pick = np.resize(pick, R)
            sel = cand[pick]
            out_rois[b * R:(b + 1) * R, 0] = b
            out_rois[b * R:(b + 1) * R, 1:] = sel
            cls = int(gts[b, 0, 0]) + 1
            is_fg = ious[pick] > 0.5
            labels[b * R:(b + 1) * R] = np.where(is_fg, cls, 0)
            tgt = _bbox_transform(sel, gts[b, 0, 1:])
            for i in np.where(is_fg)[0]:
                btgt[b * R + i, 4 * cls:4 * cls + 4] = tgt[i]
                bwt[b * R + i, 4 * cls:4 * cls + 4] = 1.0
        for i, arr in enumerate([out_rois, labels, btgt, bwt]):
            self.assign(out_data[i], req[i], arr)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for i in range(len(in_grad)):
            self.assign(in_grad[i], req[i],
                        np.zeros(in_grad[i].shape, np.float32))


@mx.operator.register("proposal_target")
class ProposalTargetProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_out", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        n = in_shape[1][0]
        R = n * ROIS_PER_IMG
        K1 = NUM_CLASSES + 1
        return in_shape, [[R, 5], [R], [R, 4 * K1], [R, 4 * K1]], []

    def create_operator(self, ctx, shapes, dtypes):
        return ProposalTarget()


def backbone(data):
    body = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3),
                              pad=(1, 1), name="conv1")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2), pool_type="max")
    body = mx.sym.Convolution(body, num_filter=32, kernel=(3, 3),
                              pad=(1, 1), name="conv2")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2), pool_type="max")
    body = mx.sym.Convolution(body, num_filter=32, kernel=(3, 3),
                              pad=(1, 1), stride=(2, 2), name="conv3")
    return mx.sym.Activation(body, act_type="relu")


def rpn_heads(feat):
    rpn = mx.sym.Convolution(feat, num_filter=64, kernel=(3, 3), pad=(1, 1),
                             name="rpn_conv")
    rpn = mx.sym.Activation(rpn, act_type="relu")
    score = mx.sym.Convolution(rpn, num_filter=2 * A, kernel=(1, 1),
                               name="rpn_cls_score")
    bbox = mx.sym.Convolution(rpn, num_filter=4 * A, kernel=(1, 1),
                              name="rpn_bbox_pred")
    return score, bbox


def _proposal_rois(score, bbox, im_info, post_nms):
    """softmax the RPN scores and run the Proposal op (grad-blocked — the
    reference's proposal layer is likewise non-differentiable)."""
    prob = mx.sym.Reshape(score, shape=(0, 2, -1))
    prob = mx.sym.softmax(prob, axis=1)
    prob = mx.sym.Reshape(prob, shape=(0, 2 * A, FEAT, FEAT))
    return mx.sym.contrib.Proposal(
        mx.sym.BlockGrad(prob), mx.sym.BlockGrad(bbox), im_info,
        feature_stride=STRIDE, scales=SCALES, ratios=RATIOS,
        rpn_pre_nms_top_n=A * FEAT * FEAT, rpn_post_nms_top_n=post_nms,
        threshold=0.7, rpn_min_size=4)


def stage2_heads(feat, rois):
    pooled = mx.sym.ROIPooling(feat, rois, pooled_size=(4, 4),
                               spatial_scale=1.0 / STRIDE)
    flat = mx.sym.Flatten(pooled)
    fc = mx.sym.FullyConnected(flat, num_hidden=128, name="fc6")
    fc = mx.sym.Activation(fc, act_type="relu")
    cls_score = mx.sym.FullyConnected(fc, num_hidden=NUM_CLASSES + 1,
                                      name="cls_score")
    bbox_pred = mx.sym.FullyConnected(fc, num_hidden=4 * (NUM_CLASSES + 1),
                                      name="bbox_pred")
    return cls_score, bbox_pred


def build_train_symbol():
    data = mx.sym.Variable("data")
    im_info = mx.sym.Variable("im_info")
    rpn_label = mx.sym.Variable("rpn_label")
    rpn_bbox_target = mx.sym.Variable("rpn_bbox_target")
    rpn_bbox_weight = mx.sym.Variable("rpn_bbox_weight")
    gt_boxes = mx.sym.Variable("gt_boxes")

    feat = backbone(data)
    score, bbox = rpn_heads(feat)

    score_2 = mx.sym.Reshape(score, shape=(0, 2, -1))
    rpn_cls_loss = mx.sym.SoftmaxOutput(
        score_2, rpn_label, multi_output=True, use_ignore=True,
        ignore_label=-1, normalization="valid", name="rpn_cls_prob")
    rpn_bbox_loss = mx.sym.MakeLoss(
        mx.sym.sum(mx.sym.smooth_l1(rpn_bbox_weight * (bbox - rpn_bbox_target),
                                    scalar=3.0)),
        grad_scale=1.0 / (A * FEAT * FEAT), name="rpn_bbox_loss")

    rois = _proposal_rois(score, bbox, im_info, POST_NMS)
    group = mx.sym.Custom(rois, gt_boxes, op_type="proposal_target")
    rois_out, s2_label, s2_tgt, s2_wt = (group[0], group[1], group[2],
                                         group[3])

    cls_score, bbox_pred = stage2_heads(feat, rois_out)
    cls_loss = mx.sym.SoftmaxOutput(cls_score, s2_label,
                                    normalization="batch", name="cls_prob")
    bbox_loss = mx.sym.MakeLoss(
        mx.sym.sum(mx.sym.smooth_l1(s2_wt * (bbox_pred - s2_tgt),
                                    scalar=1.0)),
        grad_scale=1.0 / ROIS_PER_IMG, name="bbox_loss")
    return mx.sym.Group([rpn_cls_loss, rpn_bbox_loss, cls_loss, bbox_loss])


def build_test_symbol():
    data = mx.sym.Variable("data")
    im_info = mx.sym.Variable("im_info")
    feat = backbone(data)
    score, bbox = rpn_heads(feat)
    rois = _proposal_rois(score, bbox, im_info, ROIS_PER_IMG)
    cls_score, bbox_pred = stage2_heads(feat, rois)
    cls_prob = mx.sym.softmax(cls_score, axis=-1)
    return mx.sym.Group([rois, cls_prob, bbox_pred])


def evaluate(mod, rng, batches, batch_size):
    """Top-1 detection accuracy: best-scored fg roi per image must carry the
    right class and IoU>0.5 after bbox decode."""
    correct = total = 0
    for _ in range(batches):
        x, info, _, _, _, gt = make_batch(rng, batch_size)
        mod.forward(mx.io.DataBatch(
            data=[mx.nd.array(x), mx.nd.array(info)], label=[], pad=0,
            index=None), is_train=False)
        rois, prob, deltas = [o.asnumpy() for o in mod.get_outputs()]
        R = ROIS_PER_IMG
        for b in range(batch_size):
            p = prob[b * R:(b + 1) * R]
            fg_score = p[:, 1:]
            flat = np.argmax(fg_score)
            ri, cls = divmod(int(flat), NUM_CLASSES)
            roi = rois[b * R + ri, 1:]
            d = deltas[b * R + ri, 4 * (cls + 1):4 * (cls + 2)]
            box = _bbox_decode(roi[None, :], d[None, :])[0]
            ok = (cls == int(gt[b, 0, 0]) and
                  _iou(box[None, :], gt[b, 0, 1:])[0] > 0.5)
            correct += int(ok)
            total += 1
    return correct / max(total, 1)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--batches-per-epoch", type=int, default=24)
    ap.add_argument("--lr", type=float, default=0.003)
    ap.add_argument("--seed", type=int, default=5)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    sym = build_train_symbol()
    mod = mx.mod.Module(
        sym, context=mx.cpu(0), data_names=("data", "im_info"),
        label_names=("rpn_label", "rpn_bbox_target", "rpn_bbox_weight",
                     "gt_boxes"))
    n = args.batch_size
    mod.bind(data_shapes=[("data", (n, 3, IMG, IMG)), ("im_info", (n, 3))],
             label_shapes=[("rpn_label", (n, A * FEAT * FEAT)),
                           ("rpn_bbox_target", (n, 4 * A, FEAT, FEAT)),
                           ("rpn_bbox_weight", (n, 4 * A, FEAT, FEAT)),
                           ("gt_boxes", (n, 1, 5))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    rng = np.random.RandomState(args.seed)
    for epoch in range(args.epochs):
        losses = []
        for _ in range(args.batches_per_epoch):
            x, info, lab, btgt, bwt, gt = make_batch(rng, n)
            batch = mx.io.DataBatch(
                data=[mx.nd.array(x), mx.nd.array(info)],
                label=[mx.nd.array(lab), mx.nd.array(btgt),
                       mx.nd.array(bwt), mx.nd.array(gt)],
                pad=0, index=None)
            mod.forward_backward(batch)
            mod.update()
            outs = mod.get_outputs()
            losses.append(float(outs[1].asnumpy()) +
                          float(outs[3].asnumpy()))
        logging.info("Epoch[%d] rpn+rcnn bbox loss %.4f", epoch,
                     np.mean(losses))

    # share trained weights into the test symbol
    test_mod = mx.mod.Module(build_test_symbol(), context=mx.cpu(0),
                             data_names=("data", "im_info"), label_names=None)
    test_mod.bind(data_shapes=[("data", (n, 3, IMG, IMG)),
                               ("im_info", (n, 3))], for_training=False)
    arg_params, aux_params = mod.get_params()
    test_mod.set_params(arg_params, aux_params, allow_missing=False)
    acc = evaluate(test_mod, np.random.RandomState(77), 8, n)
    logging.info("detection accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    print("rcnn detection accuracy %.3f" % main())
