"""FCN semantic segmentation (parity: example/fcn-xs — fully-convolutional
net: conv trunk, 1x1 score convolution, Deconvolution upsampling back to
input resolution, Crop alignment, and per-pixel SoftmaxOutput with
``multi_output=True``, the fcn-xs head in symbol_fcnxs.py).

Synthetic task: segment images containing a bright rectangle into
{background, rectangle} pixel classes.

Run:  python fcn_xs.py --epochs 6
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def fcn_symbol(num_classes=2, workspace=256):
    """Downsample 4x with two conv/pool stages, score with a 1x1 conv,
    upsample 4x with a stride-4 Deconvolution, Crop to the input, per-pixel
    softmax (symbol_fcnxs.py pattern)."""
    data = mx.sym.Variable("data")
    conv1 = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                               num_filter=16, name="conv1")
    act1 = mx.sym.Activation(conv1, act_type="relu")
    pool1 = mx.sym.Pooling(act1, kernel=(2, 2), stride=(2, 2),
                           pool_type="max", name="pool1")
    conv2 = mx.sym.Convolution(pool1, kernel=(3, 3), pad=(1, 1),
                               num_filter=32, name="conv2")
    act2 = mx.sym.Activation(conv2, act_type="relu")
    pool2 = mx.sym.Pooling(act2, kernel=(2, 2), stride=(2, 2),
                           pool_type="max", name="pool2")
    score = mx.sym.Convolution(pool2, kernel=(1, 1), num_filter=num_classes,
                               name="score")
    # kernel=2*stride, pad=stride/2: the fcn-xs upsampling arithmetic
    up = mx.sym.Deconvolution(score, kernel=(8, 8), stride=(4, 4),
                              pad=(2, 2), num_filter=num_classes,
                              name="bigscore")
    crop = mx.sym.Crop(up, data, name="crop")
    # normalization='valid' divides the per-pixel gradients by the pixel
    # count — without it the summed gradient explodes (the reference's
    # fcn-xs compensates with a 1e-10 lr, solver.py)
    return mx.sym.SoftmaxOutput(crop, multi_output=True, use_ignore=True,
                                ignore_label=-1, normalization="valid",
                                name="softmax")


def synth_segmentation(n, img, rng):
    X = rng.randn(n, 1, img, img).astype("float32") * 0.3
    Y = np.zeros((n, img, img), "float32")
    for i in range(n):
        h, w = rng.randint(img // 4, img // 2, 2)
        r, c = rng.randint(0, img - h), rng.randint(0, img - w)
        X[i, 0, r:r + h, c:c + w] += 1.5
        Y[i, r:r + h, c:c + w] = 1.0
    return X, Y


def pixel_accuracy(mod, it, n, img):
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        probs = mod.get_outputs()[0].asnumpy()
        pred = probs.argmax(axis=1)
        lab = batch.label[0].asnumpy()
        correct += (pred == lab).sum()
        total += lab.size
    return correct / float(total)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-images", type=int, default=256)
    ap.add_argument("--img", type=int, default=16)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(5)
    X, Y = synth_segmentation(args.num_images, args.img, rng)
    nval = args.num_images // 4
    train = mx.io.NDArrayIter(X[:-nval], Y[:-nval], args.batch_size,
                              shuffle=True, label_name="softmax_label")
    val = mx.io.NDArrayIter(X[-nval:], Y[-nval:], args.batch_size,
                            label_name="softmax_label")

    net = fcn_symbol()
    mod = mx.mod.Module(net, context=mx.cpu(0),
                        label_names=("softmax_label",))
    mod.fit(train, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            eval_metric=PixAcc(),
            initializer=mx.initializer.Xavier())

    acc = pixel_accuracy(mod, val, nval, args.img)
    logging.info("fcn-xs val pixel accuracy: %.4f", acc)
    return acc


class PixAcc(mx.metric.EvalMetric):
    """Per-pixel accuracy over the (b, c, h, w) softmax output."""

    def __init__(self):
        super().__init__("pixacc")

    def update(self, labels, preds):
        pred = preds[0].asnumpy().argmax(axis=1)
        lab = labels[0].asnumpy()
        self.sum_metric += float((pred == lab).sum())
        self.num_inst += lab.size


if __name__ == "__main__":
    main()
