"""Frame-level acoustic model (parity: the reference's example/speech-demo
— a recurrent acoustic model over filterbank frames trained with
per-frame cross-entropy against Kaldi-style alignments, evaluated by
frame accuracy).

TPU-native shape: utterances are bucketed to one padded (N, T, F) batch
shape, the BiLSTM unrolls inside the traced program (lax.scan under the
hood via the fused RNN cells), and per-frame softmax + masking stay in
the same jit step — no per-frame host loop.

Run:  python speech_acoustic.py --epochs 10
"""
import argparse
import logging

import numpy as np

import mxtpu as mx

N_MEL = 12          # filterbank bins
N_PHONE = 6         # phoneme classes
T = 20              # frames per utterance


def synth_utterances(n, rng):
    """Formant-template phoneme segments + noise: each utterance is a
    random phoneme sequence, each phoneme spans 2-5 frames, each class has
    a fixed spectral envelope (what filterbanks look like to an AM)."""
    templates = np.zeros((N_PHONE, N_MEL), np.float32)
    for p in range(N_PHONE):
        f1, f2 = (p * 2) % N_MEL, (p * 5 + 3) % N_MEL
        templates[p, f1] = 2.0
        templates[p, f2] = 1.5
        templates[p, (f1 + 1) % N_MEL] = 1.0
    X = np.zeros((n, T, N_MEL), np.float32)
    y = np.zeros((n, T), np.float32)
    for i in range(n):
        t = 0
        while t < T:
            p = rng.randint(N_PHONE)
            span = min(int(rng.randint(2, 6)), T - t)
            X[i, t:t + span] = templates[p] + \
                0.3 * rng.randn(span, N_MEL).astype(np.float32)
            y[i, t:t + span] = p
            t += span
    return X, y


def get_symbol():
    """BiLSTM over frames -> per-frame softmax (NTC layout)."""
    data = mx.sym.Variable("data")            # (N, T, F)
    label = mx.sym.Variable("softmax_label")  # (N, T)
    stack = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(num_hidden=32, prefix="fw_"),
        mx.rnn.LSTMCell(num_hidden=32, prefix="bw_"))
    outputs, _ = stack.unroll(T, inputs=data, merge_outputs=True,
                              layout="NTC")
    pred = mx.sym.Reshape(outputs, shape=(-1, 64))      # (N*T, 2H)
    pred = mx.sym.FullyConnected(pred, num_hidden=N_PHONE, name="fc")
    lab = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, lab, name="softmax",
                                normalization="batch")


def frame_accuracy(mod, X, y, batch):
    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    preds = []
    for b in it:
        mod.forward(b, is_train=False)
        # outputs are (N*T, P) batch-major, labels (N, T)
        preds.append(mod.get_outputs()[0].asnumpy().argmax(1)
                     .reshape(-1, T))
    # trim the wrap-around padding of the last batch before scoring
    pred = np.concatenate(preds)[:len(X)]
    return float((pred == y).mean())


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=6)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    X, y = synth_utterances(1200, rng)
    Xv, yv = synth_utterances(240, rng)
    train = mx.io.NDArrayIter(X, y, batch_size=args.batch_size,
                              shuffle=True)

    mod = mx.mod.Module(get_symbol(), context=mx.cpu())
    mod.fit(train, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.initializer.Xavier())
    acc = frame_accuracy(mod, Xv, yv, args.batch_size)
    logging.info("frame accuracy: %.3f", acc)
    return acc


if __name__ == "__main__":
    print("frame accuracy: %.3f" % main())
