"""Adversarial VAE (parity: example/mxnet_adversarial_vae/vaegan_mxnet.py
— the VAE/GAN hybrid: a VAE encoder/decoder trained with the ELBO's KL
term plus an ADVERSARIAL reconstruction signal from a discriminator,
instead of (only) per-pixel likelihood; the discriminator trains on
real vs reconstructed samples simultaneously).

Three-way update per batch, as in the reference:
  1. D: maximize log D(x) + log(1 - D(G(z|x)))           (real vs recon)
  2. G (decoder): KL-free adversarial term via D's input gradients,
     plus a feature-matching reconstruction loss
  3. E (encoder): KL(q(z|x) || N(0,I)) + the same reconstruction path

Run:  python vaegan.py --epochs 12
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon


class Encoder(gluon.Block):
    def __init__(self, n_latent=4, n_hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.h = gluon.nn.Dense(n_hidden, activation="tanh")
            self.mu = gluon.nn.Dense(n_latent)
            self.logvar = gluon.nn.Dense(n_latent)

    def forward(self, x):
        h = self.h(x)
        return self.mu(h), self.logvar(h)


class Decoder(gluon.Block):
    def __init__(self, n_out, n_hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.h = gluon.nn.Dense(n_hidden, activation="tanh")
            self.x = gluon.nn.Dense(n_out, activation="sigmoid")

    def forward(self, z):
        return self.x(self.h(z))


class Discriminator(gluon.Block):
    """Binary real/recon head; the penultimate layer doubles as the
    feature-matching target (the reference's 'Dis_l' layer role)."""

    def __init__(self, n_hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.feat = gluon.nn.Dense(n_hidden, activation="tanh")
            self.out = gluon.nn.Dense(1)

    def features(self, x):
        return self.feat(x)

    def forward(self, x):
        return self.out(self.feat(x))


def glyph_data(n, rng, size=8, protos=None):
    if protos is None:
        protos = (rng.rand(6, size * size) > 0.6).astype("f4")
    idx = rng.randint(0, len(protos), n)
    X = protos[idx]
    flip = rng.rand(n, size * size) < 0.05
    return np.abs(X - flip.astype("f4")), protos


def bce(logit, target):
    return (mx.nd.relu(logit) - logit * target +
            mx.nd.log(1.0 + mx.nd.exp(-mx.nd.abs(logit)))).mean()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=2048)
    ap.add_argument("--n-latent", type=int, default=4)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=13)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)

    rng = np.random.RandomState(args.seed)
    X, protos = glyph_data(args.num_examples, rng)
    Xv, _ = glyph_data(512, rng, protos=protos)
    n_in = X.shape[1]

    enc = Encoder(n_latent=args.n_latent)
    dec = Decoder(n_out=n_in)
    dis = Discriminator()
    for net in (enc, dec, dis):
        net.collect_params().initialize(mx.initializer.Xavier())
    t_enc = gluon.Trainer(enc.collect_params(), "adam",
                          {"learning_rate": args.lr})
    t_dec = gluon.Trainer(dec.collect_params(), "adam",
                          {"learning_rate": args.lr})
    t_dis = gluon.Trainer(dis.collect_params(), "adam",
                          {"learning_rate": args.lr})

    it = mx.io.NDArrayIter(X, None, args.batch_size, shuffle=True)
    d_accs, recs = [], []
    for epoch in range(args.epochs):
        it.reset()
        d_correct = d_total = 0
        rec_sum = 0.0
        batches = 0
        for batch in it:
            x = batch.data[0]
            bs = x.shape[0]

            # ---- D step: real vs reconstruction. The VAE forward runs
            # OUTSIDE the tape — only D's params need gradients here, and
            # recording enc/dec would make backward replay them for
            # all-zero grads
            mu, logvar = enc(x)
            eps = mx.nd.random_normal(shape=mu.shape)
            z = mu + mx.nd.exp(0.5 * logvar) * eps
            xr = dec(z)
            with autograd.record():
                d_real = dis(x)
                d_fake = dis(xr)
                loss_d = bce(d_real, mx.nd.ones((bs, 1))) + \
                    bce(d_fake, mx.nd.zeros((bs, 1)))
            loss_d.backward()
            t_dis.step(bs)
            d_correct += int((d_real.asnumpy() > 0).sum()
                             + (d_fake.asnumpy() < 0).sum())
            d_total += 2 * bs

            # ---- G(dec) + E(enc) step: fool D + feature matching + KL
            with autograd.record():
                mu, logvar = enc(x)
                eps = mx.nd.random_normal(shape=mu.shape)
                z = mu + mx.nd.exp(0.5 * logvar) * eps
                xr = dec(z)
                adv = bce(dis(xr), mx.nd.ones((bs, 1)))
                fm = ((dis.features(xr) - dis.features(x).detach()) ** 2
                      ).mean()
                kl = (0.5 * (mx.nd.exp(logvar) + mu ** 2 - 1.0 - logvar)
                      .sum(axis=1)).mean()
                pix = ((xr - x) ** 2).sum(axis=1).mean()
                loss_g = adv + 10.0 * fm + 0.1 * kl + pix
            loss_g.backward()
            t_dec.step(bs)
            t_enc.step(bs)
            rec_sum += float(pix.asnumpy())
            batches += 1

        d_accs.append(d_correct / max(d_total, 1))
        recs.append(rec_sum / max(batches, 1))
        logging.info("Epoch[%d] D acc %.3f  recon mse %.3f", epoch,
                     d_accs[-1], recs[-1])

    # held-out reconstruction quality
    mu, _ = enc(mx.nd.array(Xv))
    xr = dec(mu).asnumpy()
    val_mse = float(((xr - Xv) ** 2).sum(axis=1).mean())
    data_power = float((Xv ** 2).sum(axis=1).mean())
    logging.info("val recon mse %.3f (data power %.3f)", val_mse,
                 data_power)
    return d_accs, recs, val_mse, data_power


if __name__ == "__main__":
    d_accs, recs, mse, power = main()
    print("vaegan: D acc %.3f, val recon mse %.3f / power %.3f"
          % (d_accs[-1], mse, power))
