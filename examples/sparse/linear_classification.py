"""Sparse linear classification (parity: example/sparse/
linear_classification.py — the reference's showcase for csr data +
row_sparse weights + kvstore row_sparse_pull).

Flow: LibSVMIter streams csr batches -> sparse dot against a row_sparse
weight -> SGD updates only the rows the batch touched, pulled through
kvstore.row_sparse_pull. TPU note: the csr batch densifies at the device
boundary (storage-fallback, like the reference's
MXNET_EXEC_STORAGE_FALLBACK path) while the HOST-side weight store stays
row-sparse — the part that matters at embedding scale.

Run:  python linear_classification.py --epochs 5
"""
import argparse
import logging
import os
import tempfile

import numpy as np

import mxtpu as mx
from mxtpu import nd


def synth_libsvm(path, n, dim, rng, nnz=6):
    """Sparse separable two-class data in libsvm format."""
    w_true = rng.randn(dim)
    with open(path, "w") as f:
        for _ in range(n):
            idx = np.sort(rng.choice(dim, size=nnz, replace=False))
            val = rng.randn(nnz)
            y = 1 if float(np.dot(w_true[idx], val)) > 0 else 0
            feats = " ".join("%d:%.4f" % (i, v)
                             for i, v in zip(idx, val))
            f.write("%d %s\n" % (y, feats))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--num-examples", type=int, default=1024)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(7)
    path = os.path.join(tempfile.mkdtemp(), "train.libsvm")
    synth_libsvm(path, args.num_examples, args.dim, rng)

    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(args.dim,),
                          batch_size=args.batch_size)

    # row_sparse weight lives in the kvstore; batches pull only the rows
    # they touch (the reference's distributed embedding pattern)
    kv = mx.kv.create("local")
    weight = nd.sparse.zeros("row_sparse", (args.dim, 1))
    kv.init("w", weight)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=args.lr,
                                      rescale_grad=1.0))
    bias = nd.zeros((1,))

    accs = []
    for e in range(args.epochs):
        it.reset()
        correct = total = 0
        for batch in it:
            x = batch.data[0]          # csr
            y = batch.label[0]
            row_ids = nd.array(np.nonzero(
                x.asnumpy().sum(axis=0) != 0)[0].astype("float32"))
            w_rows = nd.sparse.zeros("row_sparse", (args.dim, 1))
            kv.row_sparse_pull("w", out=w_rows, row_ids=row_ids)
            xd = nd.array(x.asnumpy())          # densify at the boundary
            wd = nd.array(w_rows.asnumpy())
            score = nd.dot(xd, wd) + bias
            prob = 1.0 / (1.0 + nd.exp(-score))
            # logistic-loss gradient, touched rows only
            err = prob - y.reshape((-1, 1))
            gw = nd.dot(xd.T, err) / args.batch_size
            gb = err.mean()
            grad_rs = nd.array(gw.asnumpy()).tostype("row_sparse")
            kv.push("w", grad_rs)
            # local updater applies -lr * grad into the stored weight
            pred = (prob.asnumpy() > 0.5).astype(int).ravel()
            correct += int((pred == y.asnumpy().astype(int)).sum())
            total += len(pred)
            bias -= args.lr * gb.asnumpy()
        accs.append(correct / max(total, 1))
        logging.info("epoch %d train-accuracy %.3f", e, accs[-1])
    return accs


if __name__ == "__main__":
    accs = main()
    print("final accuracy %.3f" % accs[-1])
