"""Multi-task training (parity: example/multi-task/example_multi_task.py —
one trunk, TWO loss heads trained jointly via sym.Group, each with its own
label and metric).

Task A: 10-way glyph classification. Task B: parity (odd/even) of the
same glyph — shares the trunk, so gradients from both heads flow into
the shared features.

Run:  python multitask_mnist.py --epochs 4
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def build_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    # head 1: digit class
    fc_d = mx.sym.FullyConnected(act, num_hidden=10, name="fc_digit")
    lbl_d = mx.sym.Variable("digit_label")
    sm_d = mx.sym.SoftmaxOutput(fc_d, lbl_d, name="digit",
                                normalization="batch")
    # head 2: parity
    fc_p = mx.sym.FullyConnected(act, num_hidden=2, name="fc_parity")
    lbl_p = mx.sym.Variable("parity_label")
    sm_p = mx.sym.SoftmaxOutput(fc_p, lbl_p, name="parity",
                                normalization="batch")
    return mx.sym.Group([sm_d, sm_p])


def synth(n, rng):
    protos = rng.rand(10, 64) > 0.55
    y = rng.randint(0, 10, n)
    X = protos[y].astype("float32") + rng.randn(n, 64).astype("float32") * 0.2
    return X, y.astype("float32"), (y % 2).astype("float32")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=1024)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(1)
    X, yd, yp = synth(args.num_examples, rng)
    it = mx.io.NDArrayIter(
        X, {"digit_label": yd, "parity_label": yp},
        batch_size=args.batch_size, shuffle=True)

    net = build_symbol()
    mod = mx.mod.Module(net, context=mx.cpu(0),
                        label_names=("digit_label", "parity_label"))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})

    accs = None
    for e in range(args.epochs):
        it.reset()
        hits = np.zeros(2)
        total = 0
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            outs = [o.asnumpy() for o in mod.get_outputs()]
            n_valid = outs[0].shape[0] - batch.pad
            hits[0] += (outs[0].argmax(1)[:n_valid]
                        == batch.label[0].asnumpy()[:n_valid]).sum()
            hits[1] += (outs[1].argmax(1)[:n_valid]
                        == batch.label[1].asnumpy()[:n_valid]).sum()
            total += n_valid
        accs = hits / total
        logging.info("epoch %d digit-acc %.3f parity-acc %.3f",
                     e, accs[0], accs[1])
    return tuple(accs)


if __name__ == "__main__":
    d, p = main()
    print("digit %.3f parity %.3f" % (d, p))
