"""CNN sentence classification (parity: example/cnn_text_classification/
text_cnn.py — the Kim-2014 architecture: embedding -> parallel conv
branches of widths 3/4/5 -> max-over-time pooling -> concat -> FC).

TPU note: the per-width branches are independent convs over the same
embedding tensor; XLA schedules them in parallel on the MXU and the
max-over-time reductions fuse into each branch's epilogue.

Run:  python text_cnn.py --epochs 4
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def build_symbol(vocab, seq_len, embed_dim, num_filter, num_classes):
    data = mx.sym.Variable("data")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed_dim,
                             name="embed")
    # NCHW: 1 channel, seq_len "height", embed_dim "width"
    x = mx.sym.Reshape(embed, shape=(-1, 1, seq_len, embed_dim))
    branches = []
    for width in (3, 4, 5):
        c = mx.sym.Convolution(x, kernel=(width, embed_dim),
                               num_filter=num_filter,
                               name="conv%d" % width)
        a = mx.sym.Activation(c, act_type="relu")
        p = mx.sym.Pooling(a, kernel=(seq_len - width + 1, 1),
                           pool_type="max", name="pool%d" % width)
        branches.append(mx.sym.Flatten(p))
    h = mx.sym.Concat(*branches, dim=1)
    h = mx.sym.Dropout(h, p=0.3)
    fc = mx.sym.FullyConnected(h, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def synth_sentences(n, vocab, seq_len, rng):
    """Two 'topics' drawn from disjoint-ish token distributions; class =
    topic. Learnable by n-gram detectors, which is what the conv widths
    model."""
    topic_tokens = [rng.choice(vocab, vocab // 3, replace=False)
                    for _ in range(2)]
    X = np.empty((n, seq_len), dtype="float32")
    y = rng.randint(0, 2, n)
    for i in range(n):
        pool = topic_tokens[y[i]]
        mixed = rng.rand(seq_len) < 0.35  # noise tokens
        X[i] = np.where(mixed, rng.randint(0, vocab, seq_len),
                        rng.choice(pool, seq_len))
    return X, y.astype("float32")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--seq-len", type=int, default=24)
    ap.add_argument("--embed-dim", type=int, default=32)
    ap.add_argument("--num-filter", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-examples", type=int, default=768)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(5)
    X, y = synth_sentences(args.num_examples, args.vocab, args.seq_len, rng)
    n_train = int(len(X) * 0.8)
    it = mx.io.NDArrayIter(X[:n_train], y[:n_train],
                           batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")
    val = mx.io.NDArrayIter(X[n_train:], y[n_train:],
                            batch_size=args.batch_size,
                            label_name="softmax_label")

    net = build_symbol(args.vocab, args.seq_len, args.embed_dim,
                       args.num_filter, 2)
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.fit(it, eval_data=val, num_epoch=args.epochs,
            optimizer="adam", optimizer_params={"learning_rate": args.lr},
            eval_metric="acc",
            initializer=mx.initializer.Xavier())
    val.reset()
    score = mod.score(val, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    logging.info("final val accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    print("val accuracy %.3f" % main())
