"""Stochastic-depth residual CNN (parity: the reference's
example/stochastic-depth/sd_cifar10.py + sd_module.py — residual blocks
whose conv branch is dropped whole with a per-block "death rate" during
training and always kept at inference).

TPU-native shape: the reference drives per-block Bernoulli gates from a
custom Module that re-plumbs the executor every batch (sd_module.py).
Here the gate lives INSIDE the one traced program: ``Dropout`` on a
scalar ones-tensor is exactly a whole-block Bernoulli gate — {0,
1/(1-death_rate)} in training, identity at inference — so the whole
stochastic net stays a single fused jit step with no host control flow.

Run:  python sd_cifar10.py --epochs 8
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def sd_block(data, num_filter, death_rate, name):
    """Pre-act residual block whose branch dies whole with prob death_rate."""
    b = mx.sym.BatchNorm(data, fix_gamma=False, name=name + "_bn1")
    b = mx.sym.Activation(b, act_type="relu")
    b = mx.sym.Convolution(b, num_filter=num_filter, kernel=(3, 3),
                           pad=(1, 1), no_bias=True, name=name + "_conv1")
    b = mx.sym.BatchNorm(b, fix_gamma=False, name=name + "_bn2")
    b = mx.sym.Activation(b, act_type="relu")
    b = mx.sym.Convolution(b, num_filter=num_filter, kernel=(3, 3),
                           pad=(1, 1), no_bias=True, name=name + "_conv2")
    # whole-branch Bernoulli gate: Dropout of a scalar one — zero (branch
    # dead) or 1/(1-p) (inverted scaling) in train, exactly 1.0 at eval
    gate = mx.sym.Dropout(mx.sym.full((1, 1), 1.0), p=death_rate,
                          name=name + "_gate")
    b = mx.sym.broadcast_mul(b, mx.sym.Reshape(gate, shape=(1, 1, 1, 1)))
    return data + b


def get_symbol(num_classes, num_blocks=3, death_mode="linear_decay",
               death_rate=0.5):
    """Death rates rise linearly with depth (the paper's linear_decay rule,
    mirrored from the reference example's --death-mode)."""
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=16, kernel=(3, 3), pad=(1, 1),
                             no_bias=True, name="conv0")
    for i in range(num_blocks):
        if death_mode == "linear_decay":
            rate = death_rate * (i + 1) / num_blocks
        else:
            rate = death_rate
        net = sd_block(net, 16, rate, "sd%d" % i)
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn_last")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def synth_images(n, num_classes, rng, size=16):
    """Class-dependent blob patterns, learnable by a small conv net."""
    y = rng.randint(0, num_classes, n)
    X = rng.randn(n, 3, size, size).astype("f4") * 0.3
    for i in range(n):
        c = y[i]
        r0, c0 = (c // 4) % 3, c % 4
        X[i, c % 3, r0 * 4:r0 * 4 + 5, c0 * 3:c0 * 3 + 4] += 1.5
    return X, y.astype("f4")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-classes", type=int, default=8)
    ap.add_argument("--death-rate", type=float, default=0.5)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=5)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    X, y = synth_images(1600, args.num_classes, rng)
    Xv, yv = synth_images(320, args.num_classes, rng)
    train = mx.io.NDArrayIter(X, y, batch_size=args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(Xv, yv, batch_size=args.batch_size)

    sym = get_symbol(args.num_classes, death_rate=args.death_rate)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.0),
            eval_metric="acc")
    score = mod.score(val, mx.metric.Accuracy())[0][1]
    logging.info("final val acc: %.3f", score)
    return score


if __name__ == "__main__":
    print("val acc: %.3f" % main())
