#!/usr/bin/env python
"""Runnable paged-decode demo: attention LM, chunked prefill, token
streaming over HTTP.

Default mode boots a ``DecodeSession`` in kv layout (PagedArena KV
cache) behind the shared HTTP server, streams a few generations over
``POST /v1/generate?stream=1`` (printing each token event as it
arrives), shows the KV-block/prefill panel, and drains. ``--serve``
keeps it up for manual curl traffic instead.
"""
import argparse
import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from mxtpu.serving import ServingHTTPServer  # noqa: E402
from mxtpu.serving.decode import (DecodeSession,  # noqa: E402
                                  attn_decode_fixture)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve", action="store_true",
                    help="stay up for manual traffic instead of the "
                         "demo burst")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()

    print("building paged attention fixture (block_size=4, "
          "max_blocks_per_seq=8 -> 32-token budget) ...")
    fx = attn_decode_fixture(vocab_size=16, block_size=4,
                             max_blocks_per_seq=8, seed=0)
    sess = DecodeSession(fx["step_symbol_json"], fx["params"],
                         fx["step_example_shapes"], [], arena="paged",
                         paged=fx, buckets=(1, 2, 4), slot_capacity=4,
                         prefill_chunk_tokens=4, prefill_buckets=(4,),
                         version_tag="demo-kv")
    server = ServingHTTPServer(None, decode=sess, port=args.port)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    print("decode serving on %s (slots %d, %d KV blocks of %d tokens)"
          % (server.endpoint, sess.slot_capacity,
             sess.arena.blocks_total, sess.block_size))

    if args.serve:
        print("POST %s/v1/generate?stream=1 | GET /debug/state | "
              "GET /healthz" % server.endpoint)
        print("Ctrl-C to drain and stop.")
        try:
            t.join()
        except KeyboardInterrupt:
            pass
        server.shutdown()
        return

    host, port = server.server_address[:2]
    prompts = [[2, 5, 7], [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12],
               [4, 4, 8]]
    for prompt in prompts:
        conn = http.client.HTTPConnection(host, port, timeout=60)
        conn.request("POST", "/v1/generate?stream=1",
                     json.dumps({"prompt": prompt, "max_new_tokens": 8,
                                 "seed": 1, "temperature": 0.7}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        print("prompt %s -> %s %s" % (prompt, resp.status,
                                      resp.getheader("Content-Type")))
        for line in resp:
            if line.strip():
                print("  event: %s" % line.decode().strip())
        conn.close()

    panel = sess.debug_panel()
    print("kv panel: %s" % json.dumps(panel["kv"]))
    print("prefill panel: %s" % json.dumps(panel["prefill"]))
    server.shutdown()
    print("drained.")


if __name__ == "__main__":
    main()
