#!/usr/bin/env python
"""Runnable mxtpu.serving demo: a resnet-8 HTTP inference server.

Default mode boots the server on an ephemeral port, runs a burst of
concurrent HTTP clients against it, prints the serving metrics, and
drains. ``--serve`` keeps it up for manual curl traffic instead.
"""
import argparse
import json
import os
import sys
import threading
import urllib.request

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from mxtpu.models.serving_fixtures import get_fixture  # noqa: E402
from mxtpu.serving import ServingHTTPServer, ServingSession  # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--serve", action="store_true",
                    help="stay up for manual traffic instead of the demo "
                         "burst")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--requests-per-client", type=int, default=8)
    args = ap.parse_args()

    print("building resnet-8 fixture + warming bucket executables ...")
    sym_json, params, shapes = get_fixture("resnet")
    session = ServingSession(sym_json, params, shapes,
                             buckets=(1, 8, 32), max_delay_ms=5)
    server = ServingHTTPServer(session, port=args.port)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    print("serving on %s (buckets %s, %d replica(s))"
          % (server.endpoint, list(session.buckets), len(session.pool)))

    if args.serve:
        print("POST %s/v1/predict | GET /v1/metrics | GET /healthz"
              % server.endpoint)
        print("Ctrl-C to drain and stop.")
        try:
            t.join()
        except KeyboardInterrupt:
            pass
        server.shutdown()
        return

    def client(seed):
        rng = np.random.RandomState(seed)
        for _ in range(args.requests_per_client):
            x = rng.rand(1, 3, 28, 28).astype(np.float32)
            req = urllib.request.Request(
                server.endpoint + "/v1/predict",
                data=json.dumps({"inputs": {"data": x.tolist()}}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                out = json.loads(r.read())["outputs"][0]
            assert len(out[0]) == 10  # resnet-8 fixture has 10 classes

    print("firing %d clients x %d requests over HTTP ..."
          % (args.clients, args.requests_per_client))
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.clients)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()

    stats = session.stats()
    print(json.dumps(stats, indent=2))
    print("batch-fill %.2f | cache hit rate %.2f | p99 %.1f ms | "
          "shed rate %.3f"
          % (stats["batch_fill_ratio"], stats["executor_cache_hit_rate"],
             stats["request_latency_ms"]["p99_ms"], stats["shed_rate"]))

    # zero-downtime hot-swap: new weights pre-warm in the process-wide
    # cache while v0 serves, then the pool pointer flips atomically
    print("hot-swapping to perturbed weights (version v1) ...")
    new_params = {k: v + 0.05 for k, v in params.items()}
    info = session.swap_model(sym_json, new_params, version_tag="v1")
    with urllib.request.urlopen(server.endpoint + "/v1/version",
                                timeout=10) as r:
        print("active version:", json.loads(r.read()))
    assert info["generation"] == 1

    server.shutdown()
    server.server_close()
    print("drained and stopped.")


if __name__ == "__main__":
    main()
