"""Operator-level profiling of a matmul chain (parity:
example/profiler/profiler_matmul.py — configure the profiler, run a chain
of `dot` ops under state='run', dump a chrome://tracing JSON viewable at
chrome://tracing).

With the profiler running, the executor drops from the fused one-program
path to the per-layer profiled mode and stamps a B/E span per named op
(the engine's OprExecStat analogue); `profiler.dumps()` prints the
aggregate per-op table.

Run:  python profiler_matmul.py && python -m json.tool profile_matmul.json | head
"""
import argparse
import json
import logging

import numpy as np

import mxtpu as mx
from mxtpu import profiler


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--chain", type=int, default=8)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--file", default="profile_matmul.json")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    net = mx.sym.Variable("data")
    for i in range(args.chain):
        net = mx.sym.dot(net, mx.sym.Variable("w%d" % i), name="dot%d" % i)

    profiler.clear()
    profiler.set_config(mode="symbolic", filename=args.file)
    profiler.set_state("run")
    try:
        exe = net.simple_bind(ctx=mx.cpu(),
                              **{"data": (args.dim, args.dim),
                                 **{"w%d" % i: (args.dim, args.dim)
                                    for i in range(args.chain)}})
        rng = np.random.RandomState(0)
        for name, arr in exe.arg_dict.items():
            arr[:] = mx.nd.array(rng.rand(*arr.shape).astype("f4") * 0.1)
        exe.forward()
        exe.outputs[0].wait_to_read()
    finally:
        profiler.set_state("stop")
    path = profiler.dump_profile()
    print(profiler.dumps())

    with open(path) as f:
        trace = json.load(f)
    spans = [e for e in trace["traceEvents"] if e.get("ph") == "B"]
    dots = [e for e in spans if e["name"].startswith("dot")]
    logging.info("trace %s: %d spans (%d dot)", path, len(spans), len(dots))
    return len(spans), len(dots)


if __name__ == "__main__":
    n, d = main()
    print("profile spans %d (dot %d) -> chrome://tracing" % (n, d))
