"""Memory cost of Inception-BN training under different residual-saving
plans (parity: example/memcost/inception_memcost.py — the reference prints
the memory planner's total allocation with inplace/sharing/mirror options;
docs/architecture/note_memory.md).

TPU-native shape: the planner is XLA + jax's autodiff residual choice.
The comparable knobs are the rematerialization plans Module's fused path
exposes as MXTPU_REMAT (module/fused.py): keep every residual
(`keep_all`), keep only block-boundary activations (`block`), or recompute
the whole forward (`mirror`, the reference's MXNET_BACKWARD_DO_MIRROR
analogue). This script measures each plan's FORWARD->BACKWARD residual
set with `jax.ad_checkpoint.saved_residuals` — the bytes the training
step must hold between the two passes, i.e. the number the reference's
planner prints. (XLA's CompiledMemoryStats is not used: on the CPU
backend its scheduler hoists recomputation, masking the plan
difference.)

Run:  python inception_memcost.py --batch-size 8 --image-size 128
"""
import argparse
import logging

import jax
import jax.numpy as jnp
from jax._src.ad_checkpoint import saved_residuals

import mxtpu as mx
from mxtpu.executor import _block_boundaries, _trace_graph


def residual_bytes(sym, plan, batch, image):
    names = sym.list_arguments()
    auxn = sym.list_auxiliary_states()
    arg_shapes, _, aux_shapes = sym.infer_shape(
        data=(batch, 3, image, image), softmax_label=(batch,))
    full_args = {n: jnp.zeros(s, jnp.float32)
                 for n, s in zip(names, arg_shapes)}
    aux = {n: jnp.zeros(s, jnp.float32) for n, s in zip(auxn, aux_shapes)}
    rng = jax.random.PRNGKey(0)

    tags = None
    if plan == "block":
        tags = {i: "mxtpu_boundary" for i in _block_boundaries(sym)}
    run = _trace_graph(sym, is_train=True, remat_tags=tags)

    # differentiate w.r.t. the weights, like the fused train step: data
    # and labels stay closed over (their residuals are inputs, saved
    # for free)
    data = {n: full_args.pop(n) for n in ("data", "softmax_label")}

    def f(p):
        env = dict(data)
        env.update(p)
        outs, _ = run(env, aux, rng)
        return sum(jnp.sum(o) for o in outs)

    if plan == "mirror":
        f = jax.checkpoint(f)
    elif plan == "block":
        f = jax.checkpoint(
            f, policy=jax.checkpoint_policies.save_only_these_names(
                "mxtpu_boundary"))
    res = saved_residuals(f, full_args)
    tot = sum(int(a.size * a.dtype.itemsize) for a, _ in res)
    # subtract the saved-because-input entries (weights themselves) so the
    # number is the ACTIVATION cost the plans actually trade
    inputs = sum(int(a.size * a.dtype.itemsize)
                 for a, why in res if "from the argument" in str(why))
    if inputs == 0:
        # the reason text is a jax-internal string; if it ever rewords,
        # fall back to reporting totals rather than mislabeling them
        logging.warning("saved_residuals reasons unrecognized; "
                        "'activation MB' below includes weights")
    return tot, tot - inputs, len(res)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=128)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    sym = mx.models.get_inception_bn(num_classes=100)
    results = {}
    for plan in ("keep_all", "block", "mirror"):
        tot, act, n = residual_bytes(sym, plan, args.batch_size,
                                     args.image_size)
        results[plan] = {"total_mb": tot / 2**20, "act_mb": act / 2**20,
                         "count": n}
        logging.info("%-9s %4d residuals  %8.1f MB total  %8.1f MB "
                     "activations", plan, n, tot / 2**20, act / 2**20)
    return results


if __name__ == "__main__":
    res = main()
    print("\n%-10s %10s %12s %14s" % ("plan", "residuals", "total MB",
                                      "activation MB"))
    for k, v in res.items():
        print("%-10s %10d %12.1f %14.1f" % (k, v["count"], v["total_mb"],
                                            v["act_mb"]))
