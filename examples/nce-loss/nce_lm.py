"""Noise-contrastive estimation LM (parity: example/nce-loss/ — train a
word model scoring the true next token against K sampled noise tokens
instead of a full-vocab softmax; the binary-logistic NCE objective).

The trained model is evaluated with a FULL softmax over the output
embedding — showing the NCE-trained scores rank the true token highly
without ever computing the full softmax during training.

Run:  python nce_lm.py --epochs 5
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, nd


def synth_corpus(n_tokens, vocab, rng):
    trans = rng.dirichlet(np.full(vocab, 0.02), size=vocab)
    toks = [int(rng.randint(vocab))]
    for _ in range(n_tokens - 1):
        toks.append(int(rng.choice(vocab, p=trans[toks[-1]])))
    return np.array(toks, dtype=np.int64)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--vocab", type=int, default=30)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--num-neg", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--n-tokens", type=int, default=12000)
    ap.add_argument("--lr", type=float, default=0.5)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(11)
    toks = synth_corpus(args.n_tokens, args.vocab, rng)
    ctx_tok, next_tok = toks[:-1], toks[1:]

    # unigram noise distribution (the reference samples by frequency)
    counts = np.bincount(next_tok, minlength=args.vocab).astype("float64")
    noise_p = (counts + 1.0) / (counts + 1.0).sum()

    in_embed = nd.array(rng.randn(args.vocab, args.dim).astype("float32")
                        * 0.1)
    out_embed = nd.array(rng.randn(args.vocab, args.dim).astype("float32")
                         * 0.1)
    out_bias = nd.array(np.zeros(args.vocab, "float32"))
    params = [in_embed, out_embed, out_bias]
    for p in params:
        p.attach_grad()

    n = len(ctx_tok)
    bs, K = args.batch_size, args.num_neg
    for e in range(args.epochs):
        perm = rng.permutation(n - bs)
        total = 0.0
        for bi in range(0, n - bs, bs):
            i = perm[bi]
            c = nd.array(ctx_tok[i:i + bs].astype("float32"))
            t = next_tok[i:i + bs]
            neg = rng.choice(args.vocab, size=(bs, K), p=noise_p)
            cand = nd.array(np.concatenate([t[:, None], neg], 1)
                            .astype("float32"))  # (bs, 1+K)
            sign = nd.array(np.concatenate(
                [np.ones((bs, 1)), -np.ones((bs, K))], 1)
                .astype("float32"))
            with autograd.record():
                h = nd.Embedding(c, in_embed, input_dim=args.vocab,
                                 output_dim=args.dim)           # (bs, d)
                w = nd.Embedding(cand, out_embed, input_dim=args.vocab,
                                 output_dim=args.dim)           # (bs,1+K,d)
                b = nd.Embedding(cand, out_bias.reshape((args.vocab, 1)),
                                 input_dim=args.vocab, output_dim=1)
                scores = nd.sum(w * h.reshape((bs, 1, args.dim)),
                                axis=2) + b.reshape((bs, 1 + K))
                # NCE binary objective: true token up, noise down
                loss = nd.mean(nd.log(1.0 + nd.exp(-sign * scores)))
            loss.backward()
            for p in params:
                nd.sgd_update(p, p.grad, lr=args.lr, out=p)
            total += float(loss.asscalar())
        logging.info("epoch %d nce-loss %.4f", e, total / max((n - bs) // bs, 1))

    # full-softmax evaluation of the NCE-trained model
    h = nd.Embedding(nd.array(ctx_tok[:2048].astype("float32")), in_embed,
                     input_dim=args.vocab, output_dim=args.dim)
    logits = nd.dot(h, out_embed.T) + out_bias.reshape((1, args.vocab))
    pred = logits.asnumpy().argmax(1)
    acc = float((pred == next_tok[:2048]).mean())
    base = counts.max() / counts.sum()  # majority-class baseline
    logging.info("next-token accuracy %.3f (unigram baseline %.3f)",
                 acc, base)
    return acc, float(base)


if __name__ == "__main__":
    acc, base = main()
    print("accuracy %.3f vs baseline %.3f" % (acc, base))
