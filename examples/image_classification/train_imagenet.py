#!/usr/bin/env python
"""ImageNet-style training over recordio (parity:
example/image-classification/train_imagenet.py + common/fit.py — baseline
config 2: ResNet-50 data-parallel over ImageRecordIter).

Point --data-train at an ImageNet .rec (build with tools/im2rec.py); the
script runs the same pipeline on any .rec pack.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxtpu as mx  # noqa: E402


def main(argv=None):
    """Returns the steady-state training throughput (img/s) measured by
    the Speedometer over the final logging window."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-train", required=True, help=".rec file")
    ap.add_argument("--data-val", default=None)
    ap.add_argument("--network", default="resnet")
    ap.add_argument("--num-layers", type=int, default=50)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--lr-step-epochs", default="30,60")
    ap.add_argument("--kv-store", default="device")
    ap.add_argument("--model-prefix", default=None)
    ap.add_argument("--epoch-size", type=int, default=0,
                    help="batches per epoch (0 = full pass)")
    ap.add_argument("--speedometer-period", type=int, default=20)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    shape = tuple(int(x) for x in args.image_shape.split(","))
    if args.network == "resnet":
        net = mx.models.get_resnet(num_classes=args.num_classes,
                                   num_layers=args.num_layers,
                                   image_shape=shape)
    elif args.network == "alexnet":
        net = mx.models.get_alexnet(num_classes=args.num_classes)
    elif args.network == "vgg":
        net = mx.models.get_vgg(num_classes=args.num_classes)
    elif args.network == "inception-bn":
        net = mx.models.get_inception_bn(num_classes=args.num_classes)
    elif args.network in ("resnet-v1", "resnext", "mobilenet", "googlenet",
                          "inception-v3", "inception-v4",
                          "inception-resnet-v2"):
        mod_name = args.network.replace("-", "_")
        factory = getattr(mx.models, mod_name).get_symbol
        kw = {"num_classes": args.num_classes}
        if args.network in ("resnet-v1", "resnext"):
            kw.update(num_layers=args.num_layers, image_shape=shape)
        net = factory(**kw)
    else:
        raise SystemExit("unknown network %s" % args.network)

    kv = mx.kv.create(args.kv_store)
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train, data_shape=shape,
        batch_size=args.batch_size, shuffle=True, rand_mirror=True,
        rand_crop=True, mean_r=123.68, mean_g=116.779, mean_b=103.939,
        num_parts=kv.num_workers, part_index=kv.rank)
    if args.epoch_size:
        train = mx.io.ResizeIter(train, args.epoch_size)
    val = None
    if args.data_val:
        val = mx.io.ImageRecordIter(
            path_imgrec=args.data_val, data_shape=shape,
            batch_size=args.batch_size,
            mean_r=123.68, mean_g=116.779, mean_b=103.939)

    steps = [int(e) for e in args.lr_step_epochs.split(",") if e]
    lr_sched = mx.lr_scheduler.MultiFactorScheduler(
        step=[s * 5000 for s in steps], factor=0.1) if steps else None

    mod = mx.mod.Module(net, context=mx.test_utils.default_context())
    checkpoint = (mx.callback.do_checkpoint(args.model_prefix)
                  if args.model_prefix and kv.rank == 0 else None)
    speeds = []

    class _MeterHook(mx.callback.Speedometer):
        def _emit(self, param, speed):
            speeds.append(speed)
            super()._emit(param, speed)

    mod.fit(train, eval_data=val, num_epoch=args.num_epochs, kvstore=kv,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-4, "lr_scheduler": lr_sched},
            eval_metric=[mx.metric.Accuracy(),
                         mx.metric.TopKAccuracy(top_k=5)],
            batch_end_callback=_MeterHook(args.batch_size,
                                          args.speedometer_period),
            epoch_end_callback=checkpoint)
    steady = speeds[-1] if speeds else 0.0
    logging.info("steady-state throughput: %.1f img/s", steady)
    return steady


if __name__ == "__main__":
    main()
