#!/usr/bin/env python
"""Train MLP/LeNet on MNIST via Module.fit (parity:
example/image-classification/train_mnist.py — baseline config 1).

Uses the real MNIST ubyte files when present (set --data-dir), otherwise
a synthetic stand-in so the script runs end-to-end offline.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import mxtpu as mx  # noqa: E402


def get_mnist_iter(args):
    img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    lbl = os.path.join(args.data_dir, "train-labels-idx1-ubyte")
    if os.path.exists(img) or os.path.exists(img + ".gz"):
        train = mx.io.MNISTIter(image=img, label=lbl,
                                batch_size=args.batch_size,
                                flat=(args.network == "mlp"))
        vimg = os.path.join(args.data_dir, "t10k-images-idx3-ubyte")
        vlbl = os.path.join(args.data_dir, "t10k-labels-idx1-ubyte")
        val = mx.io.MNISTIter(image=vimg, label=vlbl,
                              batch_size=args.batch_size, shuffle=False,
                              flat=(args.network == "mlp"))
        return train, val
    logging.warning("MNIST not found under %s; generating deterministic "
                    "glyph digits in idx format there", args.data_dir)
    mx.test_utils.make_synthetic_mnist_idx(args.data_dir)
    return get_mnist_iter(args)


def main(argv=None):
    """Returns the final validation accuracy (the config-1 gate value:
    reference tests/python/train/test_mlp.py:82 asserts >0.95)."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    ap.add_argument("--data-dir", default="data/mnist")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="local")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    net = (mx.models.get_mlp(num_classes=10) if args.network == "mlp"
           else mx.models.get_lenet(num_classes=10))
    train, val = get_mnist_iter(args)
    kv = mx.kv.create(args.kv_store)
    mod = mx.mod.Module(net, context=mx.test_utils.default_context())
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs, kvstore=kv,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.initializer.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50),
            eval_metric="acc")
    score = mod.score(val, mx.metric.Accuracy())
    logging.info("final validation %s", score)
    return score[0][1]


if __name__ == "__main__":
    main()
