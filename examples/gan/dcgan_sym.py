"""Symbolic DCGAN (parity: example/gan/dcgan.py — the MODULE-level GAN
loop, distinct from the Gluon one in examples/gluon/dcgan.py): generator
and discriminator as two Modules, trained with the reference's exact
mechanics — ``inputs_need_grad=True`` on D, fake/real gradient
accumulation (run D on fakes, copy grads, run on reals, add, update), and
G updated through ``D.get_input_grads()`` fed to ``G.backward(diffD)``.

Run:  python dcgan_sym.py --epochs 3
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def make_dcgan_sym(ngf, ndf, nc, img=16, z=16, fix_gamma=True):
    """Small DCGAN pair for img x img images (reference make_dcgan_sym
    shape, example/gan/dcgan.py:27, scaled down: 4->16 in two deconv
    doublings)."""
    BatchNorm = mx.sym.BatchNorm
    rand = mx.sym.Variable("rand")
    g1 = mx.sym.Deconvolution(rand, name="g1", kernel=(4, 4),
                              num_filter=ngf * 2, no_bias=True)
    gbn1 = BatchNorm(g1, name="gbn1", fix_gamma=fix_gamma)
    gact1 = mx.sym.Activation(gbn1, act_type="relu")
    g2 = mx.sym.Deconvolution(gact1, name="g2", kernel=(4, 4), stride=(2, 2),
                              pad=(1, 1), num_filter=ngf, no_bias=True)
    gbn2 = BatchNorm(g2, name="gbn2", fix_gamma=fix_gamma)
    gact2 = mx.sym.Activation(gbn2, act_type="relu")
    g3 = mx.sym.Deconvolution(gact2, name="g3", kernel=(4, 4), stride=(2, 2),
                              pad=(1, 1), num_filter=nc, no_bias=True)
    gout = mx.sym.Activation(g3, name="gact3", act_type="tanh")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    d1 = mx.sym.Convolution(data, name="d1", kernel=(4, 4), stride=(2, 2),
                            pad=(1, 1), num_filter=ndf, no_bias=True)
    dact1 = mx.sym.LeakyReLU(d1, name="dact1", act_type="leaky", slope=0.2)
    d2 = mx.sym.Convolution(dact1, name="d2", kernel=(4, 4), stride=(2, 2),
                            pad=(1, 1), num_filter=ndf * 2, no_bias=True)
    dbn2 = BatchNorm(d2, name="dbn2", fix_gamma=fix_gamma)
    dact2 = mx.sym.LeakyReLU(dbn2, name="dact2", act_type="leaky", slope=0.2)
    d3 = mx.sym.Convolution(dact2, name="d3", kernel=(4, 4), num_filter=1,
                            no_bias=True)
    d3 = mx.sym.Flatten(d3)
    dloss = mx.sym.LogisticRegressionOutput(d3, label, name="dloss")
    return gout, dloss


class RandIter(mx.io.DataIter):
    """Endless N(0,1) latent batches (reference RandIter)."""

    def __init__(self, batch_size, ndim):
        super().__init__()
        self.batch_size = batch_size
        self.ndim = ndim
        self.provide_data = [mx.io.DataDesc("rand",
                                            (batch_size, ndim, 1, 1))]
        self.provide_label = []

    def iter_next(self):
        return True

    def getdata(self):
        return [mx.nd.random_normal(0, 1.0,
                                    shape=(self.batch_size, self.ndim, 1, 1))]


def synth_images(n, img, rng):
    """Blobby 'digits': bright disc at a class-dependent offset, in
    [-1, 1] like the reference's rescaled MNIST."""
    ys, xs = np.mgrid[0:img, 0:img]
    X = np.zeros((n, 1, img, img), "float32")
    for i in range(n):
        cy, cx = rng.randint(img // 4, 3 * img // 4, 2)
        r = rng.randint(2, img // 4)
        X[i, 0] = ((ys - cy) ** 2 + (xs - cx) ** 2 <= r * r).astype("float32")
    return X * 2.0 - 1.0


def facc(label, pred):
    pred = pred.ravel()
    label = label.ravel()
    return float(((pred > 0.5) == label).mean())


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-images", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.0005)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    img, z, nc = 16, 16, 1
    rng = np.random.RandomState(4)
    X = synth_images(args.num_images, img, rng)
    train_iter = mx.io.NDArrayIter(X, batch_size=args.batch_size)
    rand_iter = RandIter(args.batch_size, z)
    label = mx.nd.zeros((args.batch_size,))

    symG, symD = make_dcgan_sym(ngf=16, ndf=16, nc=nc, img=img, z=z)

    modG = mx.mod.Module(symG, data_names=("rand",), label_names=None,
                         context=mx.cpu(0))
    modG.bind(data_shapes=rand_iter.provide_data)
    modG.init_params(initializer=mx.initializer.Normal(0.02))
    modG.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5, "wd": 0.0})

    modD = mx.mod.Module(symD, data_names=("data",), label_names=("label",),
                         context=mx.cpu(0))
    modD.bind(data_shapes=train_iter.provide_data,
              label_shapes=[mx.io.DataDesc("label", (args.batch_size,))],
              inputs_need_grad=True)
    modD.init_params(initializer=mx.initializer.Normal(0.02))
    modD.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5, "wd": 0.0})

    mACC = mx.metric.CustomMetric(facc)
    first_acc = None
    min_fake_acc = 1.0

    for epoch in range(args.epochs):
        train_iter.reset()
        for batch in train_iter:
            rbatch = rand_iter.next()
            modG.forward(rbatch, is_train=True)
            outG = modG.get_outputs()

            # D on fakes: keep the gradients, don't step yet
            label[:] = 0
            modD.forward(mx.io.DataBatch(outG, [label]), is_train=True)
            modD.backward()
            gradD = [[g.copyto(g.context) for g in grads]
                     for grads in modD._exec_group.grad_arrays]
            mACC.reset()
            modD.update_metric(mACC, [label])
            fake_acc = mACC.get()[1]
            if first_acc is None:
                first_acc = fake_acc
            min_fake_acc = min(min_fake_acc, fake_acc)

            # D on reals: accumulate fake grads, then one update
            label[:] = 1
            batch.label = [label]
            modD.forward(batch, is_train=True)
            modD.backward()
            for gradsr, gradsf in zip(modD._exec_group.grad_arrays, gradD):
                for gr, gf in zip(gradsr, gradsf):
                    gr += gf
            modD.update()

            # G through D's input gradients
            label[:] = 1
            modD.forward(mx.io.DataBatch(outG, [label]), is_train=True)
            modD.backward()
            diffD = modD.get_input_grads()
            modG.backward(diffD)
            modG.update()
        logging.info("epoch %d: fake-detect acc %.3f (min %.3f)",
                     epoch, fake_acc, min_fake_acc)

    return first_acc, min_fake_acc


if __name__ == "__main__":
    main()
