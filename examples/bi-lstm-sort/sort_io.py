"""Bi-LSTM sequence sorting (parity: example/bi-lstm-sort/ — train a
bidirectional LSTM to emit the SORTED version of its input sequence,
the classic showcase that the backward direction matters: each output
position needs counts from the WHOLE sequence).

Run:  python sort_io.py --epochs 5
"""
import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import rnn


def build_symbol(vocab, seq_len, num_hidden):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_hidden,
                             name="embed")
    bi = rnn.BidirectionalCell(
        rnn.LSTMCell(num_hidden=num_hidden, prefix="l_"),
        rnn.LSTMCell(num_hidden=num_hidden, prefix="r_"))
    outputs, _ = bi.unroll(seq_len, inputs=embed, merge_outputs=True,
                           layout="NTC")
    flat = mx.sym.Reshape(outputs, shape=(-1, num_hidden * 2))
    fc = mx.sym.FullyConnected(flat, num_hidden=vocab, name="fc")
    lbl = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(fc, lbl, name="softmax",
                                normalization="batch")


def synth_sort(n, vocab, seq_len, rng):
    X = rng.randint(0, vocab, (n, seq_len)).astype("float32")
    Y = np.sort(X, axis=1)
    return X, Y


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--vocab", type=int, default=12)
    ap.add_argument("--seq-len", type=int, default=8)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-examples", type=int, default=2048)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(9)
    X, Y = synth_sort(args.num_examples, args.vocab, args.seq_len, rng)
    n_train = int(len(X) * 0.9)
    it = mx.io.NDArrayIter(X[:n_train], Y[:n_train],
                           batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")
    val_X, val_Y = X[n_train:], Y[n_train:]

    net = build_symbol(args.vocab, args.seq_len, args.num_hidden)
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            eval_metric="acc", initializer=mx.initializer.Xavier())

    # token-level accuracy on held-out sequences
    vit = mx.io.NDArrayIter(val_X, val_Y, batch_size=args.batch_size,
                            label_name="softmax_label")
    correct = total = 0
    for batch in vit:
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0].asnumpy()
        pred = out.reshape(-1, args.vocab).argmax(1)
        lbl = batch.label[0].asnumpy().reshape(-1).astype(int)
        n_valid = (len(lbl) - batch.pad * args.seq_len)
        correct += int((pred[:n_valid] == lbl[:n_valid]).sum())
        total += n_valid
    acc = correct / max(total, 1)
    logging.info("held-out token accuracy %.3f", acc)
    return acc


if __name__ == "__main__":
    print("sorted-token accuracy %.3f" % main())
