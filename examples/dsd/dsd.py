"""Dense-Sparse-Dense training (parity: the reference's example/dsd —
train dense, prune the smallest weights to a fixed sparsity and retrain
under the mask, then release the mask and retrain dense; the final dense
model should match or beat the never-pruned baseline).

TPU-native shape: the sparsity mask is applied as a post-update hook on
the device arrays (one fused multiply per pruned tensor), not by
rewriting the graph — XLA sees the same dense program throughout, which
is how sparsity-as-regularization wants to run on an MXU anyway.

Run:  python dsd.py --sparsity 0.6
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def mlp(num_classes):
    d = mx.sym.Variable("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(d, num_hidden=128,
                                                name="fc1"),
                          act_type="relu")
    h = mx.sym.Activation(mx.sym.FullyConnected(h, num_hidden=64,
                                                name="fc2"),
                          act_type="relu")
    return mx.sym.SoftmaxOutput(mx.sym.FullyConnected(h, num_hidden=num_classes,
                                                      name="fc3"),
                                name="softmax")


def synth(n, num_classes, rng, dim=64, W=None):
    """Draw samples from a fixed ground-truth map W (pass the SAME W for
    train and validation — separate draws would make val labels
    uncorrelated with the trained mapping)."""
    if W is None:
        W = rng.randn(dim, num_classes).astype("f4")
    X = rng.randn(n, dim).astype("f4")
    y = (X @ W + 0.3 * rng.randn(n, num_classes)).argmax(1)
    return X, y.astype("f4"), W


def fit_epochs(mod, it, epochs, lr):
    it.reset()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": lr,
                                         "momentum": 0.9},
                       force_init=True)
    for _ in range(epochs):
        it.reset()
        for b in it:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()


def prune_masks(mod, sparsity):
    """Magnitude masks for the FC weights at the requested sparsity."""
    if not 0.0 <= sparsity < 1.0:
        raise ValueError("sparsity must be in [0, 1), got %r" % sparsity)
    args, _ = mod.get_params()
    masks = {}
    for name, arr in args.items():
        if not name.endswith("_weight"):
            continue
        w = arr.asnumpy()
        k = min(int(w.size * sparsity), w.size - 1)
        thresh = np.partition(np.abs(w).ravel(), k)[k]
        masks[name] = (np.abs(w) >= thresh).astype("f4")
    return masks


def apply_masks(mod, masks):
    args, aux = mod.get_params()
    pruned = {n: mx.nd.array(args[n].asnumpy() * m) if n in masks else args[n]
              for n, m in ((n, masks.get(n)) for n in args)}
    mod.set_params(pruned, aux)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--sparsity", type=float, default=0.6)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seed", type=int, default=9)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    num_classes = 6

    X, y, W = synth(2000, num_classes, rng)
    Xv, yv, _ = synth(400, num_classes, rng, W=W)
    it = mx.io.NDArrayIter(X, y, batch_size=args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(Xv, yv, batch_size=args.batch_size)

    mod = mx.mod.Module(mlp(num_classes), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())

    # D: dense training
    fit_epochs(mod, it, args.epochs, 0.05)
    acc_dense = mod.score(val, mx.metric.Accuracy())[0][1]

    # S: prune + masked retrain (mask re-applied after every update)
    masks = prune_masks(mod, args.sparsity)
    apply_masks(mod, masks)
    for _ in range(args.epochs):
        it.reset()
        for b in it:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
            apply_masks(mod, masks)
    acc_sparse = mod.score(val, mx.metric.Accuracy())[0][1]
    w = mod.get_params()[0]["fc1_weight"].asnumpy()
    frac_zero = float((w == 0).mean())

    # D: release the mask, low-lr dense fine-tune
    fit_epochs(mod, it, args.epochs, 0.01)
    acc_final = mod.score(val, mx.metric.Accuracy())[0][1]
    logging.info("dense %.3f -> sparse(%.0f%%) %.3f -> dsd %.3f "
                 "(mid-phase zero frac %.2f)", acc_dense,
                 100 * args.sparsity, acc_sparse, acc_final, frac_zero)
    return acc_dense, acc_sparse, acc_final, frac_zero


if __name__ == "__main__":
    print("dense %.3f sparse %.3f dsd %.3f (zeros %.2f)" % main())
