"""Training nets built from in-graph caffe layers (parity:
example/caffe/caffe_net.py — the reference composes MLP and LeNet from
mx.symbol.CaffeOp layers specified by inline prototxt and trains them;
here the caffe layers execute through the host-callback plugin
mxtpu/caffe_bridge.py, their blobs trained by the mxtpu optimizer).

Run:  python caffe_net.py --network mlp --epochs 10
      python caffe_net.py --network lenet --epochs 10
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def get_mlp(classes):
    """Reference caffe_net.py get_mlp: InnerProduct+TanH stack from
    inline prototxt, softmax head native."""
    data = mx.sym.Variable("data")
    fc1 = mx.sym.CaffeOp(
        data_0=data, num_weight=2, name="fc1",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: 128}}')
    act1 = mx.sym.CaffeOp(data_0=fc1, prototxt='layer{type:"TanH"}',
                          name="act1")
    fc2 = mx.sym.CaffeOp(
        data_0=act1, num_weight=2, name="fc2",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: 64}}')
    act2 = mx.sym.CaffeOp(data_0=fc2, prototxt='layer{type:"TanH"}',
                          name="act2")
    fc3 = mx.sym.CaffeOp(
        data_0=act2, num_weight=2, name="fc3",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: %d}}' % classes)
    return mx.sym.SoftmaxOutput(fc3, name="softmax")


def get_lenet(classes):
    """Reference caffe_net.py get_lenet: caffe conv/pool/tanh pipeline."""
    data = mx.sym.Variable("data")
    conv1 = mx.sym.CaffeOp(
        data_0=data, num_weight=2, name="conv1",
        prototxt='layer{type:"Convolution" convolution_param '
                 '{num_output: 8 kernel_size: 3 stride: 1 pad: 1}}')
    act1 = mx.sym.CaffeOp(data_0=conv1, prototxt='layer{type:"TanH"}',
                          name="cact1")
    pool1 = mx.sym.CaffeOp(
        data_0=act1, name="pool1",
        prototxt='layer{type:"Pooling" pooling_param '
                 '{pool: MAX kernel_size: 2 stride: 2}}')
    conv2 = mx.sym.CaffeOp(
        data_0=pool1, num_weight=2, name="conv2",
        prototxt='layer{type:"Convolution" convolution_param '
                 '{num_output: 16 kernel_size: 3 stride: 1 pad: 1}}')
    act2 = mx.sym.CaffeOp(data_0=conv2, prototxt='layer{type:"TanH"}',
                          name="cact2")
    pool2 = mx.sym.CaffeOp(
        data_0=act2, name="pool2",
        prototxt='layer{type:"Pooling" pooling_param '
                 '{pool: MAX kernel_size: 2 stride: 2}}')
    fc1 = mx.sym.CaffeOp(
        data_0=pool2, num_weight=2, name="fc1",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: 64}}')
    act3 = mx.sym.CaffeOp(data_0=fc1, prototxt='layer{type:"TanH"}',
                          name="fact")
    fc2 = mx.sym.CaffeOp(
        data_0=act3, num_weight=2, name="fc2",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: %d}}' % classes)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def synth_images(n, edge, classes, rng):
    """Brightest-quadrant images: linearly inseparable, conv-learnable."""
    y = rng.randint(0, classes, n)
    X = rng.rand(n, 1, edge, edge).astype("f4") * 0.4
    half = edge // 2
    for i, c in enumerate(y):
        r0, c0 = (c // 2) * half, (c % 2) * half
        X[i, 0, r0:r0 + half, c0:c0 + half] += 1.0
    return X, y.astype("f4")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", choices=["mlp", "lenet"], default="mlp")
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=512)
    ap.add_argument("--lr", type=float, default=0.2)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(args.seed)
    mx.random.seed(args.seed)

    classes = 4
    if args.network == "mlp":
        dim = 20
        centers = rng.randn(classes, dim) * 3
        y = rng.randint(0, classes, args.num_examples)
        X = (centers[y] + rng.randn(args.num_examples, dim)).astype("f4")
        y = y.astype("f4")
        net = get_mlp(classes)
    else:
        X, y = synth_images(args.num_examples, 12, classes, rng)
        net = get_lenet(classes)

    it = mx.io.NDArrayIter(X, y, batch_size=args.batch_size, shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.34),
            eval_data=it)
    acc = dict(mod.score(it, mx.metric.Accuracy()))["accuracy"]
    print("train-accuracy %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
