"""MLP autoencoder (parity: example/autoencoder/ — encoder/decoder MLP
trained to reconstruct inputs with an L2 regression head;
LinearRegressionOutput provides the (pred - label) gradient).

Run:  python autoencoder.py --epochs 5
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def build_symbol(dims):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("reco_label")
    x = data
    for i, d in enumerate(dims):
        x = mx.sym.FullyConnected(x, num_hidden=d, name="enc%d" % i)
        x = mx.sym.Activation(x, act_type="relu")
    for i, d in enumerate(reversed(dims[:-1])):
        x = mx.sym.FullyConnected(x, num_hidden=d, name="dec%d" % i)
        x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.FullyConnected(x, num_hidden=64, name="out")
    return mx.sym.LinearRegressionOutput(x, label, name="reco")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-examples", type=int, default=1024)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    rng = np.random.RandomState(4)
    # low-rank structured data: an AE with an 8-wide bottleneck can
    # reconstruct it well, random noise it cannot
    basis = rng.randn(8, 64).astype("float32")
    codes = rng.randn(args.num_examples, 8).astype("float32")
    X = np.tanh(codes @ basis)

    it = mx.io.NDArrayIter(X, X, batch_size=args.batch_size, shuffle=True,
                           label_name="reco_label")
    net = build_symbol([48, 8])
    mod = mx.mod.Module(net, context=mx.cpu(0), label_names=("reco_label",))
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 0.005},
            eval_metric="mse", initializer=mx.initializer.Xavier())

    it.reset()
    errs, base = [], []
    for batch in it:
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0].asnumpy()
        lbl = batch.label[0].asnumpy()
        n_valid = out.shape[0] - batch.pad
        errs.append(((out - lbl)[:n_valid] ** 2).mean())
        base.append((lbl[:n_valid] ** 2).mean())
    mse = float(np.mean(errs))
    var = float(np.mean(base))
    logging.info("reconstruction mse %.4f (data power %.4f)", mse, var)
    return mse, var


if __name__ == "__main__":
    mse, var = main()
    print("mse %.4f vs data power %.4f" % (mse, var))
