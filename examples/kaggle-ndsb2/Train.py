"""Train the NDSB-2 heart-volume nets (parity:
example/kaggle-ndsb2/Train.py — frame-difference LeNet over the
30-frame stack, 600-way CDF target through LogisticRegressionOutput,
CSVIter input, CRPS metric, one net for systole and one for diastole).

Run after Preprocessing.py:
    python Train.py --data-prefix train --frames 30 --edge 64
"""
import argparse
import logging

import numpy as np

import mxtpu as mx


def get_lenet(frames, cdf_dim, num_filter=40):
    """Frame-difference LeNet: consecutive-frame diffs -> conv stack ->
    CDF logits (the reference's get_lenet)."""
    source = mx.sym.Variable("data")
    source = (source - 128) * (1.0 / 128)
    fr = mx.sym.SliceChannel(source, num_outputs=frames)
    diffs = [fr[i + 1] - fr[i] for i in range(frames - 1)]
    source = mx.sym.Concat(*diffs)
    net = mx.sym.Convolution(source, kernel=(5, 5), num_filter=num_filter)
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=num_filter)
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    flatten = mx.sym.Flatten(net)
    flatten = mx.sym.Dropout(flatten)
    fc1 = mx.sym.FullyConnected(flatten, num_hidden=cdf_dim)
    # named softmax so the CSVIter's default label name matches
    return mx.sym.LogisticRegressionOutput(fc1, name="softmax")


def CRPS(label, pred):
    """Continuous Ranked Probability Score over the CDF encoding, with
    the monotonicity projection the reference applies."""
    pred = np.array(pred, copy=True)
    for i in range(pred.shape[0]):
        for j in range(pred.shape[1] - 1):
            if pred[i, j] > pred[i, j + 1]:
                pred[i, j + 1] = pred[i, j]
    return np.sum(np.square(label - pred)) / label.size


def train_one(target, args):
    network = get_lenet(args.frames, args.cdf_dim, args.num_filter)
    data_train = mx.io.CSVIter(
        data_csv="%s-%dx%d-data.csv" % (args.data_prefix, args.edge,
                                        args.edge),
        data_shape=(args.frames, args.edge, args.edge),
        label_csv="%s-%s.csv" % (args.data_prefix, target),
        label_shape=(args.cdf_dim,), batch_size=args.batch_size)
    model = mx.model.FeedForward(
        ctx=mx.cpu(), symbol=network, num_epoch=args.num_epochs,
        learning_rate=args.lr, wd=0.00001, momentum=0.9)
    model.fit(X=data_train, eval_metric=mx.metric.np(CRPS))
    data_train.reset()
    score = model.score(data_train, eval_metric=mx.metric.np(CRPS))
    print("%s train-CRPS %.4f" % (target, score))
    return model, score


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-prefix", default="train")
    ap.add_argument("--frames", type=int, default=30)
    ap.add_argument("--edge", type=int, default=64)
    ap.add_argument("--cdf-dim", type=int, default=600)
    ap.add_argument("--num-filter", type=int, default=40)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=65)
    ap.add_argument("--lr", type=float, default=0.001)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    _, sys_score = train_one("systole", args)
    _, dia_score = train_one("diastole", args)
    return sys_score, dia_score


if __name__ == "__main__":
    main()
