"""Preprocess cardiac frame stacks into CSV tensors (parity:
example/kaggle-ndsb2/Preprocessing.py — the reference walks DICOM SAX
series, resizes each study's 30 frames to 64x64, and writes one
data-csv row per study plus a label csv; here the input is a directory
of per-study frame images, since DICOM readers aren't part of this
image, and the tensor/CSV contract is identical).

Layout:  <root>/<study_id>/frame_00.png ... frame_NN.png
         <root>/labels.csv  rows: study_id,systole,diastole

Run: python Preprocessing.py --root data/train --out-prefix train \
        --frames 30 --edge 64
Writes train-<edge>x<edge>-data.csv + train-label.csv, the files
Train.py consumes.
"""
import argparse
import csv
import os

import numpy as np


def load_study(path, frames, edge):
    import cv2

    names = sorted(os.listdir(path))[:frames]
    stack = []
    for n in names:
        img = cv2.imread(os.path.join(path, n), cv2.IMREAD_GRAYSCALE)
        if img.shape != (edge, edge):
            img = cv2.resize(img, (edge, edge))
        stack.append(img.astype(np.float32))
    while len(stack) < frames:  # short series wrap-pad like the reference
        stack.append(stack[len(stack) % max(len(stack), 1)])
    return np.stack(stack)  # (frames, edge, edge)


def write_data_csv(root, out_prefix, frames, edge):
    labels = {}
    with open(os.path.join(root, "labels.csv")) as f:
        for row in csv.reader(f):
            if row and row[0] != "Id":
                labels[row[0]] = (float(row[1]), float(row[2]))
    studies = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    data_path = "%s-%dx%d-data.csv" % (out_prefix, edge, edge)
    label_path = "%s-label.csv" % out_prefix
    with open(data_path, "w") as df, open(label_path, "w") as lf:
        for sid in studies:
            stack = load_study(os.path.join(root, sid), frames, edge)
            df.write(",".join("%g" % v for v in stack.reshape(-1)) + "\n")
            sys_v, dia_v = labels[sid]
            lf.write("%s,%g,%g\n" % (sid, sys_v, dia_v))
    return data_path, label_path


def encode_label(label_data, dim=600):
    """volume -> CDF step target: target[j] = 1[volume < j]."""
    systole = label_data[:, 1]
    diastole = label_data[:, 2]
    grid = np.arange(dim)
    systole_encode = np.array([(x < grid) for x in systole], np.uint8)
    diastole_encode = np.array([(x < grid) for x in diastole], np.uint8)
    return systole_encode, diastole_encode


def encode_csv(label_csv, systole_csv, diastole_csv, dim=600):
    rows = []
    with open(label_csv) as f:
        for row in csv.reader(f):
            rows.append([0.0, float(row[1]), float(row[2])])
    systole_encode, diastole_encode = encode_label(np.asarray(rows), dim)
    np.savetxt(systole_csv, systole_encode, delimiter=",", fmt="%g")
    np.savetxt(diastole_csv, diastole_encode, delimiter=",", fmt="%g")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True)
    ap.add_argument("--out-prefix", required=True)
    ap.add_argument("--frames", type=int, default=30)
    ap.add_argument("--edge", type=int, default=64)
    ap.add_argument("--cdf-dim", type=int, default=600)
    args = ap.parse_args(argv)
    data_path, label_path = write_data_csv(args.root, args.out_prefix,
                                           args.frames, args.edge)
    encode_csv(label_path, args.out_prefix + "-systole.csv",
               args.out_prefix + "-diastole.csv", args.cdf_dim)
    print("wrote %s, %s, encoded CDF targets" % (data_path, label_path))


if __name__ == "__main__":
    main()
