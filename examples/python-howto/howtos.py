"""Small API how-tos in one runnable file (parity: example/python-howto/
{monitor_weights, multiple_outputs, debug_conv, data_iter} — each a tiny
self-contained demonstration of one mechanism).

Run:  python howtos.py        # runs all four, prints a line per how-to
"""
import logging

import numpy as np

import mxtpu as mx


def monitor_weights():
    """mx.monitor.Monitor: per-batch tensor statistics on every op output
    (the executor monitor callback, graph_executor.cc:1400 role)."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    seen = []
    mon = mx.monitor.Monitor(
        interval=1, stat_func=lambda arr: mx.nd.array(
            np.array([float(np.abs(arr.asnumpy()).mean())], "f4")),
        pattern=".*fc.*")
    rng = np.random.RandomState(0)
    it = mx.io.NDArrayIter(rng.randn(64, 8).astype("f4"),
                           rng.randint(0, 4, 64).astype("f4"),
                           batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.install_monitor(mon)
    mod.init_optimizer()
    for batch in it:
        mon.tic()
        mod.forward_backward(batch)
        mod.update()
        seen.extend(mon.toc())
    names = {name for _, name, _ in seen}
    assert any("fc" in n for n in names), names
    return len(seen)


def multiple_outputs():
    """sym.Group exposes several heads from one network; the executor
    returns all of them."""
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="tanh")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    group = mx.sym.Group([fc2, mx.sym.BlockGrad(act, name="feat")])
    exe = group.simple_bind(ctx=mx.cpu(), data=(8, 12))
    exe.arg_dict["data"][:] = mx.nd.array(
        np.random.RandomState(1).randn(8, 12).astype("f4"))
    outs = exe.forward()
    assert outs[0].shape == (8, 4) and outs[1].shape == (8, 16)
    return [tuple(o.shape) for o in outs]


def debug_conv():
    """Inspect one conv's output directly: bind just the conv and read the
    result (the reference's debug_conv.py flow)."""
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data, num_filter=2, kernel=(3, 3),
                              pad=(1, 1), name="conv")
    exe = conv.simple_bind(ctx=mx.cpu(), data=(1, 1, 5, 5))
    exe.arg_dict["data"][:] = mx.nd.ones((1, 1, 5, 5))
    exe.arg_dict["conv_weight"][:] = mx.nd.ones((2, 1, 3, 3))
    exe.arg_dict["conv_bias"][:] = mx.nd.zeros((2,))
    out = exe.forward()[0].asnumpy()
    assert out.shape == (1, 2, 5, 5)
    assert out[0, 0, 2, 2] == 9.0     # full 3x3 window of ones
    assert out[0, 0, 0, 0] == 4.0     # corner sees a 2x2 window
    return out.shape


def data_iter():
    """Iterate a DataIter by hand: provide_data/label, reset, pad."""
    X = np.arange(20, dtype="f4").reshape(10, 2)
    it = mx.io.NDArrayIter(X, np.zeros(10, "f4"), batch_size=4,
                           label_name="softmax_label")
    sizes = []
    for batch in it:
        sizes.append((batch.data[0].shape[0], batch.pad))
    assert sizes == [(4, 0), (4, 0), (4, 2)], sizes  # last batch pads 2
    it.reset()
    assert next(iter(it)).pad == 0
    return sizes


def main():
    logging.basicConfig(level=logging.INFO)
    n = monitor_weights()
    logging.info("monitor_weights: %d stats collected", n)
    shapes = multiple_outputs()
    logging.info("multiple_outputs: %s", shapes)
    cshape = debug_conv()
    logging.info("debug_conv: %s", cshape)
    sizes = data_iter()
    logging.info("data_iter: %s", sizes)
    return True


if __name__ == "__main__":
    print("howtos ok:", main())
