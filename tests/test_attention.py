"""Flash-attention Pallas kernel tests: interpret-mode kernel vs the jnp
reference oracle, causal masking, gradients, op registration, and the
ring-attention cross-check."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.ops import attention as att

import jax
import jax.numpy as jnp


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype("float32") * 0.5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,s", [(128, 128), (256, 128), (128, 256)])
def test_flash_matches_reference(causal, t, s):
    b, h, d = 2, 2, 64
    q = _rand((b, h, t, d), 0)
    k = _rand((b, h, s, d), 1)
    v = _rand((b, h, s, d), 2)
    if causal and t != s:
        pytest.skip("causal assumes aligned q/kv lengths")
    out = att.flash_attention(q, k, v, causal=causal)
    ref = att._reference(q.reshape(b * h, t, d), k.reshape(b * h, s, d),
                         v.reshape(b * h, s, d), 1.0 / d ** 0.5,
                         causal).reshape(b, h, t, d)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_flash_multiblock_accumulation():
    # kv length spans several 128-blocks: exercises the online softmax
    b, h, t, s, d = 1, 1, 128, 512, 64
    q, k, v = _rand((b, h, t, d)), _rand((b, h, s, d), 1), _rand(
        (b, h, s, d), 2)
    out = att.flash_attention(q, k, v, block_k=128)
    ref = att._reference(q[0], k[0], v[0], 1.0 / d ** 0.5, False)[None]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_flash_gradients_match_reference():
    b, h, t, d = 1, 2, 128, 32
    q, k, v = _rand((b, h, t, d)), _rand((b, h, t, d), 1), _rand(
        (b, h, t, d), 2)

    def loss_flash(q, k, v):
        return att.flash_attention(q, k, v, causal=True).sum()

    def loss_ref(q, k, v):
        return att._reference(q.reshape(h, t, d), k.reshape(h, t, d),
                              v.reshape(h, t, d), 1.0 / d ** 0.5,
                              True).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a),
                                   np.asarray(b_).reshape(a.shape),
                                   rtol=2e-3, atol=2e-3)


def test_flash_op_registered():
    q = mx.nd.array(np.random.RandomState(0).randn(1, 2, 128, 32)
                    .astype("float32"))
    out = mx.nd.contrib.FlashAttention(q, q, q, causal=True)
    assert out.shape == (1, 2, 128, 32)


def test_blockwise_agrees_with_flash():
    from mxtpu.parallel.ring_attention import blockwise_attention

    b, t, h, d = 1, 256, 2, 32
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(b, t, h, d).astype("float32") * 0.3)
    k = jnp.asarray(rng.randn(b, t, h, d).astype("float32") * 0.3)
    v = jnp.asarray(rng.randn(b, t, h, d).astype("float32") * 0.3)
    blockwise = blockwise_attention(q, k, v, block_size=64)
    flash = att.flash_attention(q.transpose(0, 2, 1, 3),
                                k.transpose(0, 2, 1, 3),
                                v.transpose(0, 2, 1, 3))
    np.testing.assert_allclose(np.asarray(blockwise),
                               np.asarray(flash.transpose(0, 2, 1, 3)),
                               rtol=2e-3, atol=2e-3)


def test_flash_ragged_kv_tail():
    # kv length not a multiple of block_k: padded columns must not leak
    b, h, t, s, d = 1, 1, 64, 96, 32
    q = _rand((b, h, t, d), 0)
    k = _rand((b, h, s, d), 1)
    v = _rand((b, h, s, d), 2)
    out = att.flash_attention(q, k, v, block_q=64, block_k=64)
    ref = att._reference(q[0], k[0], v[0], 1.0 / d ** 0.5, False)[None]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_streaming_matches_reference():
    bh, t, s, d = 2, 96, 160, 32
    q = _rand((bh, t, d), 0)
    k = _rand((bh, s, d), 1)
    v = _rand((bh, s, d), 2)
    for causal in (False, True):
        if causal and t != s:
            ref = att._reference(q, k, v, 0.2, False)
            stream = att._streaming(q, k, v, 0.2, False, block=64)
        else:
            ref = att._reference(q, k, v, 0.2, causal)
            stream = att._streaming(q, k, v, 0.2, causal, block=64)
        np.testing.assert_allclose(np.asarray(stream), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)


def test_pallas_epilogue_matches_reference():
    """BN-apply+ReLU+add pallas kernel (ops/epilogue.py, interpret mode)
    agrees with the XLA formulation, with and without the residual."""
    import numpy as np
    import jax.numpy as jnp
    from mxtpu.ops.epilogue import (bn_apply_relu_add,
                                    bn_apply_relu_add_reference, fold_bn)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(96, 128), jnp.float32)
    r = jnp.asarray(rng.randn(96, 128), jnp.float32)
    gamma = jnp.asarray(rng.rand(128) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(128), jnp.float32)
    mean = jnp.asarray(rng.randn(128), jnp.float32)
    var = jnp.asarray(rng.rand(128) + 0.1, jnp.float32)
    scale, shift = fold_bn(gamma, beta, mean, var)
    got = bn_apply_relu_add(x, scale, shift, r, block_m=32, interpret=True)
    want = bn_apply_relu_add_reference(x, scale, shift, r)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    got2 = bn_apply_relu_add(x, scale, shift, None, block_m=32,
                             interpret=True)
    want2 = bn_apply_relu_add_reference(x, scale, shift, None)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2),
                               rtol=1e-5, atol=1e-5)
