"""Predict API, rtc, contrib.autograd, torch bridge, ccSGD, and the
per-row negative-binomial samplers (parity tier: tests/python/predict/,
test_rtc.py, contrib autograd tests)."""
import os
import numpy as np
import pytest

import mxtpu as mx


def _train_tiny(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.rand(32, 6).astype("float32")
    Y = (X.sum(1) > 3).astype("float32")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    prefix = str(tmp_path / "tiny")
    mod.save_checkpoint(prefix, 1)
    return prefix, X, mod


def test_predictor_matches_module(tmp_path):
    prefix, X, mod = _train_tiny(tmp_path)
    pred = mx.predict.load_checkpoint_predictor(
        prefix, 1, {"data": (8, 6)})
    pred.forward(data=X[:8])
    out = pred.get_output(0)
    assert out.shape == (8, 2)
    it = mx.io.NDArrayIter(X[:8], None, batch_size=8)
    ref = mod.predict(it).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    # reshape -> new batch geometry, same weights
    pred.reshape({"data": (4, 6)})
    pred.forward(data=X[:4])
    np.testing.assert_allclose(pred.get_output(0), ref[:4], rtol=1e-4,
                               atol=1e-5)


def test_predictor_errors(tmp_path):
    prefix, X, _ = _train_tiny(tmp_path)
    pred = mx.predict.load_checkpoint_predictor(prefix, 1,
                                                {"data": (8, 6)})
    with pytest.raises(mx.MXNetError):
        pred.set_input("nope", X[:8])
    with pytest.raises(mx.MXNetError):
        pred.set_input("data", X[:4])  # wrong shape


def test_rtc_jit_kernel():
    import jax.numpy as jnp

    k = mx.rtc.Rtc("saxpy", lambda a, x, y: a * x + y)
    x = mx.nd.array(np.arange(6, dtype="float32"))
    y = mx.nd.ones((6,))
    out = mx.nd.zeros((6,))
    k.push([mx.nd.array(np.array([2.0], "float32")), x, y], [out])
    np.testing.assert_allclose(out.asnumpy(),
                               2.0 * np.arange(6) + 1.0)
    with pytest.raises(mx.MXNetError):
        mx.rtc.Rtc("cuda", "__global__ void k() {}")


def test_contrib_autograd_grad_and_loss():
    from mxtpu.contrib import autograd as cag

    def f(x):
        return (x * x).sum()

    x = mx.nd.array(np.array([1.0, 2.0, 3.0], "float32"))
    grads, loss = cag.grad_and_loss(f)(x)
    np.testing.assert_allclose(grads[0].asnumpy(), [2.0, 4.0, 6.0],
                               rtol=1e-5)


def test_torch_bridge():
    x = mx.nd.array(np.array([[3.0, 1.0], [2.0, 4.0]], "float32"))
    t = mx.th.to_torch(x)
    assert tuple(t.shape) == (2, 2)
    back = mx.th.from_torch(t * 2)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy() * 2)
    sig = mx.th.function("sigmoid")(x)
    np.testing.assert_allclose(sig.asnumpy(),
                               1 / (1 + np.exp(-x.asnumpy())), rtol=1e-5)


def test_ccsgd_registered():
    o = mx.optimizer.create("ccsgd", learning_rate=0.1)
    assert isinstance(o, mx.optimizer.SGD)


def test_sample_negative_binomial_rowwise():
    k = mx.nd.array(np.array([1.0, 20.0], "float32"))
    p = mx.nd.array(np.array([0.5, 0.5], "float32"))
    out = mx.nd.sample_negative_binomial(k, p, shape=(400,))
    assert out.shape == (2, 400)
    m = out.asnumpy().mean(axis=1)
    # mean = k(1-p)/p = [1, 20]
    assert abs(m[0] - 1.0) < 0.5 and abs(m[1] - 20.0) < 3.0
    mu = mx.nd.array(np.array([2.0, 10.0], "float32"))
    alpha = mx.nd.array(np.array([0.0, 0.1], "float32"))
    out2 = mx.nd.sample_generalized_negative_binomial(mu, alpha,
                                                      shape=(400,))
    m2 = out2.asnumpy().mean(axis=1)
    assert abs(m2[0] - 2.0) < 0.5 and abs(m2[1] - 10.0) < 2.5


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_tensorboard_callback(tmp_path):
    from mxtpu.contrib.tensorboard import LogMetricsCallback
    from collections import namedtuple

    cb = LogMetricsCallback(str(tmp_path / "tb"))
    metric = mx.metric.Accuracy()
    metric.update([mx.nd.array(np.array([0.0, 1.0], "float32"))],
                  [mx.nd.array(np.array([[0.9, 0.1], [0.2, 0.8]],
                                        "float32"))])
    Param = namedtuple("Param", ["eval_metric"])
    cb(Param(eval_metric=metric))


def test_c_predict_abi(tmp_path):
    """Compile and run the C predict demo against a real checkpoint
    (parity tier: tests/python/predict + amalgamation smoke)."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lib = os.path.join(repo, "mxtpu", "native", "libmxtpu_predict.so")
    if not os.path.exists(lib):
        pytest.skip("libmxtpu_predict.so not built")
    prefix, X, _ = _train_tiny(tmp_path)
    exe = str(tmp_path / "predict_demo")
    src = os.path.join(repo, "src", "capi", "predict_demo.c")
    subprocess.run(["gcc", src, "-I", os.path.join(repo, "src", "capi"),
                    lib, "-o", exe, "-Wl,-rpath," + os.path.dirname(lib)],
                   check=True)
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [exe, prefix + "-symbol.json", prefix + "-0001.params", "8", "6"],
        capture_output=True, timeout=300, env=env)
    out = res.stdout.decode()
    assert res.returncode == 0, out + res.stderr.decode()
    assert "PREDICT_DEMO_OK" in out
    assert "output_shape: 8 2" in out


def test_profiler_chrome_trace(tmp_path):
    import json

    from mxtpu import profiler

    profiler.profiler_set_config(filename=str(tmp_path / "trace.json"))
    profiler.profiler_set_state("run")
    with profiler.scope("stage_a"):
        mx.nd.ones((4, 4)).asnumpy()
    with profiler.scope("stage_b"):
        pass
    profiler.profiler_set_state("stop")
    out = profiler.dump_profile()
    with open(out or str(tmp_path / "trace.json")) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    names = {e.get("name") for e in events}
    assert "stage_a" in names and "stage_b" in names


def test_native_im2rec_roundtrip(tmp_path):
    """The C++ packer's output reads back through MXIndexedRecordIO and
    ImageRecordIter (tools/im2rec.cc, role of the reference's C++ tool)."""
    import subprocess
    import numpy as np
    import mxtpu as mx
    from mxtpu import recordio

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    exe = os.path.join(repo, "tools", "im2rec")
    if not os.path.exists(exe):
        r = subprocess.run(["make", "-C", os.path.join(repo, "tools"),
                            "im2rec"], capture_output=True, text=True)
        if not os.path.exists(exe):
            import pytest
            pytest.skip("im2rec did not build: %s" % r.stderr[-300:])

    # source images + .lst
    import cv2
    rng = np.random.RandomState(0)
    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    lst = []
    for i in range(12):
        img = rng.randint(0, 255, (40 + i, 52, 3), dtype=np.uint8)
        cv2.imwrite(str(img_dir / ("im%d.png" % i)), img)
        lst.append("%d\t%d\tim%d.png" % (i, i % 3, i))
    (tmp_path / "all.lst").write_text("\n".join(lst) + "\n")

    out_prefix = str(tmp_path / "packed")
    r = subprocess.run([exe, str(tmp_path / "all.lst"), str(img_dir),
                        out_prefix, "--resize", "32", "--num-thread", "2"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr

    rec = recordio.MXIndexedRecordIO(out_prefix + ".idx",
                                     out_prefix + ".rec", "r")
    assert len(rec.keys) == 12
    hdr, img = recordio.unpack_img(rec.read_idx(5))
    assert hdr.label == 5 % 3 and hdr.id == 5
    assert min(img.shape[:2]) == 32  # shorter side resized

    it = mx.io.ImageRecordIter(path_imgrec=out_prefix + ".rec",
                               path_imgidx=out_prefix + ".idx",
                               data_shape=(3, 24, 24), batch_size=4,
                               shuffle=True, rand_crop=True)
    batches = sum(1 for _ in it)
    assert batches == 3


def test_torch_module_differentiable():
    """TorchModule: torch.nn blocks run on NDArrays with torch-autograd
    backward (plugin/torch torch_module role), numerically checked."""
    import numpy as np
    import torch
    import mxtpu as mx

    lin = torch.nn.Linear(3, 2)
    with torch.no_grad():
        lin.weight.copy_(torch.arange(6.).reshape(2, 3))
        lin.bias.zero_()
    mod = mx.th.TorchModule(lin)
    x = mx.nd.array(np.ones((4, 3), "float32"))
    out = mod(x)
    want = np.ones((4, 3)) @ np.arange(6.).reshape(2, 3).T
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-6)
    (gx,) = mod.backward()
    # d(sum(Wx))/dx = column sums of W, broadcast over the batch
    np.testing.assert_allclose(gx.asnumpy(),
                               np.tile(np.arange(6.).reshape(2, 3)
                                       .sum(0), (4, 1)), rtol=1e-6)


def test_torch_dlpack_zero_copy():
    import numpy as np
    import mxtpu as mx

    x = mx.nd.array(np.arange(4.0).astype("float32"))
    t = mx.th.to_torch(x)
    assert t.shape == (4,)
    back = mx.th.from_torch(t + 1)
    np.testing.assert_allclose(back.asnumpy(), [1, 2, 3, 4])


def test_torch_module_in_graph():
    """plugin/torch parity: a torch.nn block composed INTO a Symbol via
    mx.th.as_symbol trains through Module — forward via functional_call,
    backward via torch.autograd, torch params updated by the mxtpu
    optimizer. Gradient check: mxtpu executor grads == torch autograd."""
    import torch
    import torch.nn as tnn

    tmod = tnn.Sequential(tnn.Linear(6, 5), tnn.Tanh())
    data = mx.sym.Variable("data")
    out = mx.th.as_symbol(tmod, data, name="tb")
    # bind standalone and compare input grads against torch directly
    exe = out.simple_bind(ctx=mx.cpu(), data=(3, 6), grad_req="write")
    tp = mx.th.torch_params(tmod, "tb")
    for k, v in tp.items():
        exe.arg_dict[k][:] = v
    x = np.random.RandomState(1).randn(3, 6).astype("f4")
    exe.arg_dict["data"][:] = mx.nd.array(x)
    y = exe.forward(is_train=True)[0]
    tx = torch.from_numpy(x).requires_grad_(True)
    ty = tmod(tx)
    np.testing.assert_allclose(y.asnumpy(), ty.detach().numpy(), rtol=1e-5,
                               atol=1e-6)
    head = np.ones(ty.shape, "f4")
    exe.backward([mx.nd.array(head)])
    ty.backward(torch.from_numpy(head))
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               tx.grad.numpy(), rtol=1e-5, atol=1e-6)
    # weight grads arrive too (named <name>_<param> with dots flattened)
    g = exe.grad_dict["tb_0_weight"].asnumpy()
    tw = dict(tmod.named_parameters())["0.weight"]
    np.testing.assert_allclose(g, tw.grad.numpy(), rtol=1e-5, atol=1e-6)


def test_torch_module_in_graph_stochastic_consistency():
    """Dropout inside a wrapped torch block: backward's recomputed forward
    must reuse the SAME mask the loss saw (fork_rng + per-step seed), and
    is_train=False must disable dropout entirely."""
    import torch.nn as tnn

    tmod = tnn.Sequential(tnn.Dropout(0.5))
    data = mx.sym.Variable("data")
    out = mx.th.as_symbol(tmod, data, name="tdrop")
    exe = out.simple_bind(ctx=mx.cpu(), data=(64, 8), grad_req="write")
    x = np.ones((64, 8), "f4")
    exe.arg_dict["data"][:] = mx.nd.array(x)

    y = exe.forward(is_train=True)[0].asnumpy()
    mask = y != 0                       # the mask the loss saw
    assert 0.2 < mask.mean() < 0.8, "dropout inactive in train mode"
    exe.backward([mx.nd.array(np.ones_like(y))])
    g = exe.grad_dict["data"].asnumpy()
    # gradient flows exactly where THAT mask kept values: same zero set
    np.testing.assert_array_equal(g != 0, mask)

    y_eval = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(y_eval, x)   # eval mode: identity
