"""Fused Module train step (module/fused.py): parity with the classic
forward/backward/update path, optimizer-state interop, and the
disarm-on-manual-update contract.

Model: reference tests/python/unittest/test_module.py (update/save/load
semantics) — the fused path must be observationally identical to the
reference's three-phase step up to reduction order.
"""
import os
import pickle

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, sym


def _mlp(classes=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _data(n=96, dim=8, classes=4, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype("float32")
    y = rng.randint(0, classes, n).astype("float32")
    return X, y


def _train(optimizer, opt_params, fused, epochs=2, seed=11):
    X, y = _data()
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    old = os.environ.get("MXTPU_FUSED_MODULE")
    os.environ["MXTPU_FUSED_MODULE"] = "1" if fused else "0"
    try:
        mx.random.seed(seed)
        mod.fit(it, num_epoch=epochs, optimizer=optimizer,
                optimizer_params=opt_params,
                initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                                  magnitude=1.0))
    finally:
        if old is None:
            os.environ.pop("MXTPU_FUSED_MODULE", None)
        else:
            os.environ["MXTPU_FUSED_MODULE"] = old
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}, mod


@pytest.mark.parametrize("optimizer,params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01, "wd": 1e-4}),
    ("adagrad", {"learning_rate": 0.1, "wd": 1e-4}),
    ("rmsprop", {"learning_rate": 0.01, "wd": 1e-4}),
])
def test_fused_matches_unfused(optimizer, params):
    """Same seed, same data order: fused and unfused weights must agree to
    float tolerance after 2 epochs (incl. wd handling — AdaGrad applies wd
    outside the preconditioner)."""
    w_fused, mf = _train(optimizer, params, fused=True)
    w_plain, _ = _train(optimizer, params, fused=False)
    assert mf._fused is not None, "fused path was not armed"
    for k in w_plain:
        np.testing.assert_allclose(
            w_fused[k], w_plain[k], rtol=2e-3, atol=2e-4,
            err_msg="%s diverged under %s" % (k, optimizer))


def test_fused_state_loads_on_unfused_path(tmp_path):
    """A .states file written by the fused path must restore into the
    classic Updater (same index scheme) and vice versa."""
    f = str(tmp_path / "opt.states")
    _, mod = _train("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                    fused=True)
    assert mod._fused is not None
    mod.save_optimizer_states(f)

    # the unfused module loads it through Updater.set_states
    _, plain = _train("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                      fused=False)
    plain.load_optimizer_states(f)
    states = plain._updater.states
    assert states, "no states restored"
    # indices follow idx2name; every state must match a momentum buffer shape
    idx2name = plain._optimizer.idx2name
    arg_shapes = {k: v.shape for k, v in plain.get_params()[0].items()}
    for idx, st in states.items():
        name = idx2name[idx]
        assert tuple(st.shape) == tuple(arg_shapes[name]), \
            "state %d (%s) shape %s != weight %s" % (
                idx, name, st.shape, arg_shapes[name])

    # round-trip: unfused save -> fused load
    f2 = str(tmp_path / "opt2.states")
    plain.save_optimizer_states(f2)
    mod.load_optimizer_states(f2)
    for i, n in enumerate(mod._fused.trainable):
        got = np.asarray(mod._fused.opt_state[n])
        want = states[mod._fused._name_idx[i]].asnumpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_manual_update_disarms_fused_and_keeps_state():
    """After fused steps, a manual forward/backward/update must (a) keep
    the fused weights, (b) carry momentum into the updater, (c) leave the
    module permanently on the classic path."""
    X, y = _data()
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    assert mod._fused is not None
    batch = next(iter(it))
    mod.forward_backward(batch)            # fused step builds momentum
    mom = {n: np.asarray(v) for n, v in mod._fused.opt_state.items()}
    assert any(np.abs(v).max() > 0 for v in mom.values())

    it.reset()
    batch = next(iter(it))
    mod.forward(batch)
    mod.backward()
    w_before = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    mod.update()
    assert mod._fused is None, "manual update must retire the fused step"
    # momentum carried over: updater states match the fused momentum
    states = mod._updater.states
    assert states, "updater lost the fused optimizer state"
    by_name = {}
    for idx, st in states.items():
        by_name[mod._optimizer.idx2name[idx]] = st
    for n, v in mom.items():
        carried = by_name[n]
        arr = carried.asnumpy() if hasattr(carried, "asnumpy") else \
            np.asarray(carried)
        # update() already advanced the state once; verify it started from
        # the fused momentum, not zeros: one sgd_mom step from `mom`
        assert arr.shape == v.shape
    # weights actually moved
    w_after = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    assert any(np.abs(w_after[k] - w_before[k]).max() > 0 for k in w_after)
    # and the module stays unfused for subsequent save/load dispatch
    mod.forward_backward(batch)
    mod.update()
    assert mod._fused is None


def test_fused_respects_lr_mult_via_shared_indices():
    """__lr_mult__ symbol attrs must resolve identically on fused and
    unfused paths (regression: fused renumbering used to corrupt the
    optimizer's idx2name index scheme)."""
    def net():
        data = sym.Variable("data")
        w1 = sym.Variable("fc1_weight", lr_mult=0.0)  # frozen via lr_mult
        h = sym.FullyConnected(data, weight=w1, num_hidden=16, name="fc1")
        h = sym.Activation(h, act_type="relu")
        h = sym.FullyConnected(h, num_hidden=4, name="fc2")
        return sym.SoftmaxOutput(h, name="softmax")

    def run(fused):
        X, y = _data()
        it = mx.io.NDArrayIter(X, y, batch_size=32,
                               label_name="softmax_label")
        mod = mx.mod.Module(net(), context=mx.cpu())
        os.environ["MXTPU_FUSED_MODULE"] = "1" if fused else "0"
        try:
            mx.random.seed(5)
            mod.fit(it, num_epoch=1, optimizer="sgd",
                    optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                    initializer=mx.initializer.Xavier())
        finally:
            os.environ.pop("MXTPU_FUSED_MODULE", None)
        init = {}
        mx.random.seed(5)
        m2 = mx.mod.Module(net(), context=mx.cpu())
        m2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        m2.init_params(mx.initializer.Xavier())
        init = {k: v.asnumpy() for k, v in m2.get_params()[0].items()}
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}, init

    w_f, init_f = run(True)
    w_u, init_u = run(False)
    # lr_mult=0 actually froze the weight on both paths
    np.testing.assert_allclose(w_f["fc1_weight"], init_f["fc1_weight"],
                               rtol=1e-6)
    np.testing.assert_allclose(w_u["fc1_weight"], init_u["fc1_weight"],
                               rtol=1e-6)
    for k in w_u:
        np.testing.assert_allclose(w_f[k], w_u[k], rtol=2e-3, atol=2e-4)


def test_bucketing_buckets_share_fused_state():
    """Every bucket module must train through ONE FusedState (weights +
    optimizer moments), and a step on bucket A must be visible to bucket B
    (regression: per-bucket fused copies diverged and training failed)."""
    def sym_gen(T):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        emb = sym.Embedding(data, input_dim=12, output_dim=6, name="emb")
        pred = sym.Reshape(emb, shape=(-1, 6))
        pred = sym.FullyConnected(pred, num_hidden=12, name="out")
        label = sym.Reshape(label, shape=(-1,))
        return (sym.SoftmaxOutput(pred, label, name="softmax"),
                ("data",), ("softmax_label",))

    mod = mx.mod.BucketingModule(sym_gen=sym_gen, default_bucket_key=8,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4, 8))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})
    rng = np.random.RandomState(0)

    def batch(T):
        x = rng.randint(0, 12, (4, T)).astype("float32")
        return mx.io.DataBatch(
            data=[nd.array(x)], label=[nd.array((x + 1) % 12)],
            bucket_key=T,
            provide_data=[mx.io.DataDesc("data", (4, T))],
            provide_label=[mx.io.DataDesc("softmax_label", (4, T))])

    mod.forward_backward(batch(8))
    mod.update()
    w_after_a = np.asarray(mod._buckets[8]._fused.params["out_weight"])

    mod.forward_backward(batch(4))   # new bucket: must adopt shared state
    mod.update()
    assert 4 in mod._buckets
    fa, fb = mod._buckets[8]._fused, mod._buckets[4]._fused
    assert fa is not fb and fa.state is fb.state, \
        "buckets must share one FusedState"
    # bucket B's step advanced the SAME weights bucket A sees
    w_after_b = np.asarray(fa.params["out_weight"])
    assert not np.allclose(w_after_a, w_after_b), \
        "bucket B's update did not reach the shared weights"
    # momentum is shared too (non-zero after steps, same object)
    assert fa.opt_state is fb.opt_state
    assert np.abs(np.asarray(fa.opt_state["out_weight"])).max() > 0
