"""mxtpu.diagnostics: ledger exactness under concurrency + live_arrays
reconciliation, per-program cost capture across every build kind, the
flight recorder ring, watchdog detection (wedged fake engine) and
silence (healthy fit), /debug/state schema, SIGUSR2 dump roundtrip, and
the satellite surfaces (print_summary memory column, monitor series)."""
import gc
import json
import os
import signal
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import diagnostics as diag
from mxtpu import telemetry as tel
from mxtpu.diagnostics.ledger import DeviceMemoryLedger
from mxtpu.diagnostics.flight import FlightRecorder
from mxtpu.diagnostics.watchdog import Watchdog


# ------------------------------------------------------------------ ledger
def test_ledger_concurrent_alloc_free_exact():
    """N threads hammering alloc/free: totals must be EXACT — the
    postmortem's memory numbers are worthless if they drift."""
    led = DeviceMemoryLedger(register_gauges=False)
    n_threads, n_iter = 8, 1500
    barrier = threading.Barrier(n_threads)
    leaks = [None] * n_threads

    def worker(i):
        barrier.wait()
        tokens = []
        for k in range(n_iter):
            tokens.append(led.alloc(64, ctx="cpu(0)",
                                    origin="w%d" % (i % 2)))
            if k % 2:
                led.free(tokens.pop())
        leaks[i] = tokens

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    outstanding = sum(len(t) for t in leaks)
    assert led.live_bytes() == outstanding * 64
    assert led.live_bytes(origin="w0") + led.live_bytes(origin="w1") \
        == outstanding * 64
    assert led.peak_bytes("cpu(0)") >= led.live_bytes()
    for toks in leaks:
        for t in toks:
            led.free(t)
    assert led.live_bytes() == 0
    assert led.live_bytes(origin="w0") == 0 and led.live_bytes("w1") == 0


def test_ledger_concurrent_slot_set_exact():
    """set() is a read-modify-write against the slot's recorded size:
    racing resizes must serialize — a lost delta would skew the
    fused_step totals for process life."""
    led = DeviceMemoryLedger(register_gauges=False)

    class Owner:
        pass

    o = Owner()
    s = led.slot(o, 0, "slot_race", ctx="cpu(0)")
    n_threads, n_iter = 8, 400
    barrier = threading.Barrier(n_threads)

    def worker(i):
        barrier.wait()
        for k in range(n_iter):
            s.set((i * 131 + k * 17) % 4096)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s.set(777)   # whatever interleaving happened, totals must re-converge
    assert led.live_bytes(origin="slot_race") == 777
    s.set(0)
    assert led.live_bytes(origin="slot_race") == 0


def test_ledger_track_buffer_lifetime_and_dedup():
    import jax.numpy as jnp
    led = DeviceMemoryLedger(register_gauges=False)
    buf = jnp.zeros((128,), jnp.float32) + 1  # fresh buffer, not a constant
    assert led.track(buf, origin="probe")
    assert not led.track(buf, origin="other")  # dedup: same buffer counts once
    assert led.live_bytes(origin="probe") == 512
    assert led.live_bytes(origin="other") == 0
    del buf
    gc.collect()
    assert led.live_bytes(origin="probe") == 0
    assert led.tracked_buffers == 0


def test_ledger_slot_follows_owner():
    led = DeviceMemoryLedger(register_gauges=False)

    class Owner:
        pass

    o = Owner()
    s = led.slot(o, 1000, "slotted", ctx="cpu(0)")
    assert led.live_bytes(origin="slotted") == 1000
    s.set(2500)
    assert led.live_bytes(origin="slotted") == 2500
    del o, s
    gc.collect()
    assert led.live_bytes(origin="slotted") == 0


def test_mem_live_bytes_reconciles_with_jax_live_arrays():
    """The acceptance check: ledger-tracked allocations move in lockstep
    with jax.live_arrays() — drift stays flat while both grow/shrink."""
    gc.collect()
    r0 = diag.reconcile()
    arrs = [mx.nd.zeros((256, 1024)) for _ in range(4)]  # 4 MiB tracked
    r1 = diag.reconcile()
    grown = r1["ledger_bytes"] - r0["ledger_bytes"]
    assert grown == 4 * 256 * 1024 * 4
    # live_arrays grew by the same amount (small slack for cached jax
    # internals materialized on the way)
    assert abs((r1["live_bytes"] - r0["live_bytes"]) - grown) < (1 << 20)
    assert abs(r1["drift_bytes"] - r0["drift_bytes"]) < (1 << 20)
    del arrs
    gc.collect()
    r2 = diag.reconcile()
    assert abs(r2["ledger_bytes"] - r0["ledger_bytes"]) < (1 << 16)
    # the exported gauges carry the same numbers
    assert tel.registry().gauge(
        "mem_live_bytes",
        labels={"ctx": "cpu(0)", "origin": "ndarray"}).value >= 0
    assert tel.registry().gauge("mem_peak_bytes",
                                labels={"ctx": "cpu(0)"}).value >= grown


def test_alloc_origin_outermost_wins():
    with diag.alloc_origin("serving_pool"):
        with diag.alloc_origin("executor"):
            assert diag.current_origin() == "serving_pool"
        with diag.alloc_origin("executor", override=True):
            assert diag.current_origin() == "executor"
    assert diag.current_origin() == "ndarray"


# ------------------------------------------------------------------ programs
def _fit_once(**kw):
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    y = rng.randint(0, 4, 64).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fcd"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=1,
            optimizer_params={"learning_rate": 0.1}, **kw)
    return mod


def test_cost_capture_all_build_kinds():
    """fwd_eval, fwd_bwd (executor), fused_step, metric_accum all land in
    the program registry with XLA's own cost numbers."""
    diag.programs()  # import side effects settled
    _fit_once()      # fused_step + metric_accum
    x = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(x, num_hidden=4,
                                                     name="fcc"),
                               name="softmax")
    ex = mx.Executor.simple_bind(net, ctx=mx.cpu(), data=(8, 16),
                                 softmax_label=(8,))
    ex.forward(is_train=False)              # fwd_eval
    ex.forward(is_train=True)               # fwd_bwd (grads armed)
    ex.backward()
    by_kind = {}
    for p in diag.programs():
        by_kind.setdefault(p["kind"], []).append(p)
    for kind in ("fwd_eval", "fwd_bwd", "fused_step", "metric_accum"):
        assert kind in by_kind, "missing cost capture for %s" % kind
        rec = by_kind[kind][-1]
        assert rec["bytes_accessed"] > 0 or rec["flops"] > 0
        assert rec["calls"] >= 1
        assert rec["compile_ms"] > 0
    # the fused step moves real parameter bytes
    fused = by_kind["fused_step"][-1]
    assert fused["argument_bytes"] > 0 and fused["flops"] > 0
    # telemetry mirrors the capture
    assert tel.registry().counter("program_captured",
                                  labels={"kind": "fused_step"}).value >= 1
    assert tel.registry().counter("program_flops",
                                  labels={"kind": "fused_step"}).value > 0
    # the table renders every row
    table = diag.program_table()
    assert "fused_step" in table and "metric_accum" in table


def test_instrumented_program_first_call_race_single_record():
    """Concurrent first invocations of one shared wrapper (the
    _ACCUM_FN_CACHE case) must produce exactly one compile and one
    ProgramRecord — losers wait for the winner's executable."""
    import threading

    import jax
    import jax.numpy as jnp
    from mxtpu import executor as _executor

    compiles = [0]
    inner = jax.jit(lambda v: v + 1)
    orig_lower = inner.lower

    def counting_lower(*a, **k):
        compiles[0] += 1
        return orig_lower(*a, **k)

    inner.lower = counting_lower
    fn = _executor.record_program_build("diag_race_probe", None, inner)
    before = len([p for p in diag.programs()
                  if p["kind"] == "diag_race_probe"])
    barrier = threading.Barrier(4)
    outs, errs = [], []

    def call():
        try:
            barrier.wait()
            outs.append(float(fn(jnp.ones((3,), jnp.float32)).sum()))
        except Exception as exc:  # surface thread failures in the assert
            errs.append(exc)

    threads = [threading.Thread(target=call) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    assert outs == [6.0] * 4
    after = [p for p in diag.programs() if p["kind"] == "diag_race_probe"]
    assert len(after) - before == 1, "duplicate ProgramRecords: %r" % after
    assert compiles[0] == 1, "first-call race compiled %d times" % compiles[0]


def test_instrumented_program_falls_back_on_signature_change():
    """The AOT fast path must hand dispatch back to jit when a later call
    changes dtype/shape — same numerics, no crash."""
    import jax
    import jax.numpy as jnp
    from mxtpu import executor as _executor
    fn = _executor.record_program_build("diag_probe", None,
                                        jax.jit(lambda v: v * 2))
    a = fn(jnp.ones((4,), jnp.float32))
    assert float(a.sum()) == 8.0
    b = fn(jnp.ones((6,), jnp.float32))      # new shape -> jit retrace
    assert float(b.sum()) == 12.0
    c = fn(jnp.ones((4,), jnp.int32))        # new dtype
    assert int(c.sum()) == 8
    # a persistently-moved signature demotes the AOT fast path to jit
    # after _DEMOTE_MISSES consecutive misses — numerics stay correct
    # through and past the demotion point
    for _ in range(_executor._DEMOTE_MISSES + 4):
        d = fn(jnp.ones((6,), jnp.float32))
        assert float(d.sum()) == 12.0
    # ALTERNATING signatures (bucketed training) never trip the
    # consecutive counter; the lifetime total demotes instead — numerics
    # stay correct through and past that threshold too
    fn2 = _executor.record_program_build("diag_alt_probe", None,
                                         jax.jit(lambda v: v * 2))
    for i in range(2 * _executor._DEMOTE_MISS_TOTAL + 8):
        # only every other call misses: 2x the total to cross it
        shape = (4,) if i % 2 == 0 else (6,)
        out = fn2(jnp.ones(shape, jnp.float32))
        assert float(out.sum()) == 2.0 * shape[0]


# ------------------------------------------------------------------ flight
def test_flight_recorder_ring_order_and_capacity():
    rec = FlightRecorder(capacity=16)
    for i in range(40):
        rec.record("probe", "e%d" % i, i)
    snap = rec.snapshot()
    assert len(snap) == 16
    assert [e["seq"] for e in snap] == list(range(24, 40))
    assert snap[-1]["name"] == "e39" and snap[-1]["kind"] == "probe"
    assert rec.events_recorded == 40


def test_spans_land_in_flight_ring():
    rec = diag.recorder()
    assert rec is not None
    with tel.span("flight_probe_span"):
        pass
    names = [(e["kind"], e["name"]) for e in rec.snapshot()]
    assert ("span_start", "flight_probe_span") in names
    assert ("span_end", "flight_probe_span") in names


def test_engine_push_lands_in_flight_ring():
    rec = diag.recorder()
    eng = mx.engine.get()
    eng.push(lambda: None)
    eng.wait_for_all()
    assert any(e["kind"] == "engine" and e["name"] == "push"
               for e in rec.snapshot())


# ------------------------------------------------------------------ watchdog
def test_watchdog_fires_on_wedged_fake_engine():
    """Queue nonempty + completions frozen past the deadline -> exactly
    one postmortem, with ring + ledger + program table all present."""
    fired = []
    wd = Watchdog(interval=0.01, engine_stall_s=0.05, wait_stall_s=99,
                  engine_probe=lambda: (3, 7),
                  on_detect=lambda reason: fired.append(reason))
    t0 = time.monotonic()
    while not fired and time.monotonic() - t0 < 3.0:
        time.sleep(0.02)
        wd.check()
    assert fired and "engine stalled" in fired[0]
    assert wd.detections == 1
    for _ in range(5):   # stays wedged: still ONE dump per wedge
        time.sleep(0.02)
        wd.check()
    assert wd.detections == 1
    # the default sink (postmortem) carries all three sections
    pm = diag.postmortem("watchdog-test", source="test")
    assert "flight" in pm and "ledger" in pm and "programs" in pm
    assert "engine" in pm and isinstance(pm["flight"], list)
    assert pm["ledger"]["live_bytes_total"] >= 0


def test_watchdog_detects_stalled_device_wait():
    wd = Watchdog(interval=0.01, engine_stall_s=99, wait_stall_s=0.05,
                  engine_probe=lambda: (0, 0),
                  on_detect=lambda r: None)
    done = threading.Event()

    def stuck():
        diag.wait_begin("test_wait")
        done.wait(2.0)
        diag.wait_end()

    t = threading.Thread(target=stuck, daemon=True)
    t.start()
    time.sleep(0.15)
    reason = wd.check()
    done.set()
    t.join()
    assert reason is not None and "device_wait" in reason
    assert wd.check() is None or True  # wait gone after wait_end


def test_watchdog_silent_through_full_fit():
    """A healthy Module.fit must never trip the watchdog."""
    hits = []
    wd = Watchdog(interval=0.01, engine_stall_s=0.5, wait_stall_s=0.5,
                  on_detect=lambda r: hits.append(r)).start()
    try:
        _fit_once(batch_end_callback=mx.callback.Speedometer(
            16, frequent=2, auto_reset=False))
        time.sleep(0.1)
    finally:
        wd.stop()
    assert hits == []
    assert wd.detections == 0


# ------------------------------------------------------------------ dumps
def test_sigusr2_dump_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_DIAG_DUMP_DIR", str(tmp_path))
    assert diag.install_signal_handler()
    os.kill(os.getpid(), signal.SIGUSR2)
    deadline = time.monotonic() + 5.0
    files = []
    while not files and time.monotonic() < deadline:
        time.sleep(0.05)
        files = list(tmp_path.glob("mxtpu_postmortem_*.json"))
    assert files, "SIGUSR2 produced no dump file"
    dump = json.loads(files[0].read_text())
    assert dump["source"] == "signal"
    for section in ("flight", "ledger", "programs", "engine", "waits"):
        assert section in dump
    assert dump["ledger"]["live_bytes_total"] >= 0


def test_postmortem_on_fit_exception():
    class Boom(RuntimeError):
        pass

    def bad_callback(param):
        raise Boom("deliberate")

    before = diag.last_postmortem()
    with pytest.raises(Boom):
        _fit_once(batch_end_callback=bad_callback)
    pm = diag.last_postmortem()
    assert pm is not None and pm is not before
    assert pm["reason"] == "fit_exception" and "Boom" in pm["exception"]
    assert pm["source"] == "fit"


def test_postmortem_fires_on_native_error_not_usage_error():
    """MXNetError from fit is a usage error (silent); NativeError — a
    nonzero native-engine return — is a backend failure and must leave
    forensics despite being an MXNetError subclass."""
    from mxtpu.base import MXNetError, NativeError

    before = diag.last_postmortem()
    with pytest.raises(MXNetError):
        _fit_once(batch_end_callback=lambda p: (_ for _ in ()).throw(
            MXNetError("bad user input")))
    assert diag.last_postmortem() is before, \
        "plain MXNetError must not dump"
    with pytest.raises(NativeError):
        _fit_once(batch_end_callback=lambda p: (_ for _ in ()).throw(
            NativeError("engine push failed")))
    pm = diag.last_postmortem()
    assert pm is not None and pm is not before
    assert pm["reason"] == "fit_exception" and pm["source"] == "fit"
    assert "engine push failed" in pm["exception"]


def test_instrumented_program_defers_capture_under_precision_env(
        monkeypatch):
    """A first call under MXTPU_MATMUL_PRECISION must not consume the
    capture slot: the program table fills in at the first call after the
    env clears, instead of staying empty for the wrapper's life."""
    import jax
    import jax.numpy as jnp
    from mxtpu import executor as _executor
    fn = _executor._instrument_program(
        "diag_prec_probe", jax.jit(lambda v: v * 3), matmul_env=True)
    h = tel.registry().histogram("executor_compile_ms",
                                 labels={"kind": "diag_prec_probe"})
    before = h.snapshot()
    monkeypatch.setenv("MXTPU_MATMUL_PRECISION", "highest")
    assert float(fn(jnp.ones((2,), jnp.float32)).sum()) == 6.0
    assert not [p for p in diag.programs()
                if p["kind"] == "diag_prec_probe"]
    # the literal first call still lands in executor_compile_ms even
    # though capture was deferred (it paid jit's lazy compile)
    assert h.snapshot()[0] - before[0] == 1
    monkeypatch.delenv("MXTPU_MATMUL_PRECISION")
    assert float(fn(jnp.ones((2,), jnp.float32)).sum()) == 6.0
    assert [p for p in diag.programs() if p["kind"] == "diag_prec_probe"]


def test_dump_state_on_demand(tmp_path):
    p = diag.dump_state(str(tmp_path / "state.json"))
    state = json.loads(open(p).read())
    for section in ("ledger", "programs", "flight", "engine"):
        assert section in state


# ------------------------------------------------------------------ serving
def test_debug_state_http_schema():
    """GET /debug/state on a live serving session returns all three
    diagnostic sections (+ engine/serving) as JSON."""
    from mxtpu.models.serving_fixtures import get_fixture
    from mxtpu.serving.server import ServingHTTPServer, ServingSession
    sj, params, shapes = get_fixture("mlp")
    sess = ServingSession(sj, params, shapes, buckets=(1, 4),
                          contexts=[mx.cpu()])
    server = ServingHTTPServer(sess, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        body = json.dumps({"inputs": {"data": [[0.0] * 784]}}).encode()
        urllib.request.urlopen(server.endpoint + "/v1/predict", data=body)
        state = json.loads(urllib.request.urlopen(
            server.endpoint + "/debug/state").read())
        # the three tentpole sections
        assert isinstance(state["ledger"]["live_bytes"], dict)
        assert state["ledger"]["live_bytes_total"] >= 0
        assert isinstance(state["programs"], list) and state["programs"]
        assert {"kind", "flops", "compile_ms"} <= set(state["programs"][0])
        assert isinstance(state["flight"], list) and state["flight"]
        assert {"seq", "kind", "name", "thread"} <= set(state["flight"][0])
        # plus engine + per-session serving stats
        assert "queue_depth" in state["engine"]
        assert "uptime_sec" in state["serving"]
        # serving requests visible in the ring
        assert any(e["name"] == "serving.request"
                   for e in state["flight"])
    finally:
        server.shutdown()


def test_serving_pool_origin_attribution():
    """Buffers first allocated inside a pool bind are tagged
    serving_pool (outermost-origin attribution through the executor)."""
    from mxtpu.models.serving_fixtures import get_fixture
    from mxtpu.serving.pool import ExecutorPool
    sj, params, shapes = get_fixture("mlp")
    led = diag.ledger()
    pool = ExecutorPool(sj, params, shapes, contexts=[mx.cpu()])
    assert led.live_bytes(origin="serving_pool") > 0
    del pool


# ------------------------------------------------------------------ satellites
def test_print_summary_memory_column_and_params(capsys):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="c1")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mx.viz.print_summary(net, shape={"data": (1, 3, 8, 8)})
    out = capsys.readouterr().out
    assert "Mem (KB)" in out
    # conv: 3*3*3*8 + 8 = 224; fc: 288*10 + 10 = 2890
    assert "Total params: 3114" in out
    assert "Total memory" in out


def test_print_summary_grouped_symbol_shapes(capsys):
    """Grouped symbols and multi-output layers report real shapes (the
    old name-keyed lookup showed blanks)."""
    s = mx.sym.SliceChannel(mx.sym.Variable("x"), num_outputs=2, name="sl")
    g = mx.sym.Group([s, mx.sym.FullyConnected(mx.sym.Variable("y"),
                                               num_hidden=3, name="gfc")])
    mx.viz.print_summary(g, shape={"x": (2, 4), "y": (2, 5)})
    out = capsys.readouterr().out
    assert "(2,), (2,)" in out        # both slice outputs, batch stripped
    assert "Total params: 18" in out  # 5*3 + 3


def test_monitor_stats_become_telemetry_series():
    mon = mx.monitor.Monitor(1, pattern="diagmon_.*")
    mon.tic()
    mon.stat_helper("diagmon_w", mx.nd.ones((2, 2)))
    res = mon.toc()
    assert res and res[0][1] == "diagmon_w"
    g = tel.registry().gauge("monitor_stat", labels={"name": "diagmon_w"})
    assert g.value == 1.0


def test_series_inventory_documented():
    """Every literal telemetry series emitted by mxtpu/ appears in the
    docs/observability.md inventory (the CI check tool) — and every
    span name in its span-inventory section."""
    import subprocess
    import sys
    root = os.path.join(os.path.dirname(__file__), "..")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "check_series_documented.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_bench_files_carry_verdict_basis():
    """Every BENCH_*.json that claims a perf verdict records the
    deterministic basis the verdict was computed from (the CI check
    tool; raw run logs are exempt)."""
    import subprocess
    import sys
    root = os.path.join(os.path.dirname(__file__), "..")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "tools",
                                      "check_bench_basis.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
