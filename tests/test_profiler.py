"""Profiler: op-level attribution + config/dump API shaped like the
reference's MXSetProfilerConfig/MXSetProfilerState/MXDumpProfile
(src/engine/profiler.cc:152, python/mxnet/profiler.py)."""
import json
import os

import numpy as np

import mxtpu as mx
from mxtpu import profiler, sym


def _block(data, prefix, nf):
    c = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=nf,
                        no_bias=True, name="%s_conv" % prefix)
    b = sym.BatchNorm(c, fix_gamma=False, name="%s_bn" % prefix)
    return sym.Activation(b, act_type="relu", name="%s_relu" % prefix)


def test_per_layer_spans_and_dump(tmp_path):
    """One train step of a conv stack attributes time per NAMED layer and
    dumps a valid chrome://tracing file."""
    net = sym.Variable("data")
    for i in range(3):
        net = _block(net, "stage%d" % i, 8)
    net = sym.FullyConnected(sym.Flatten(net), num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")

    fname = str(tmp_path / "trace.json")
    profiler.clear()
    profiler.set_config(mode="symbolic", filename=fname)
    profiler.set_state("run")
    try:
        exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3, 16, 16),
                              softmax_label=(2,))
        exe.arg_dict["data"][:] = mx.nd.array(
            np.random.rand(2, 3, 16, 16).astype("float32"))
        exe.forward(is_train=True)
        exe.backward()
    finally:
        profiler.set_state("stop")
    path = profiler.dump_profile()
    assert path == fname and os.path.exists(fname)
    trace = json.load(open(fname))
    names = {ev["name"] for ev in trace["traceEvents"]}
    # every named layer appears, plus the one-program backward span
    for expect in ("stage0_conv", "stage1_bn", "stage2_relu", "fc",
                   "softmax", "backward"):
        assert expect in names, (expect, sorted(names)[:20])
    # spans are well-formed B/E pairs with non-negative duration
    begins = {}
    for ev in trace["traceEvents"]:
        key = (ev["name"], ev["tid"])
        if ev["ph"] == "B":
            begins[key] = ev["ts"]
        elif ev["ph"] == "E":
            assert ev["ts"] >= begins[key]

    # aggregate table parity (dumps): per-op rows with counts
    table = profiler.dumps()
    assert "stage0_conv" in table and "Count" in table


def test_aggregate_stats_mode():
    """aggregate_stats=True folds spans into standing per-layer histograms
    at record time: the dumps() table gains percentile columns and
    SURVIVES raw-event truncation (MXAggregateProfileStats contract) —
    with the flag off, the table is recomputed from raw events and dies
    with them."""
    profiler.clear()
    profiler.set_config(mode="symbolic", filename="/tmp/unused_agg.json",
                        aggregate_stats=True)
    profiler.set_state("run")
    try:
        for _ in range(5):
            with profiler.scope("agg_layer"):
                pass
    finally:
        profiler.set_state("stop")
    table = profiler.dumps()
    assert "agg_layer" in table and "Count" in table
    assert "P50(ms)" in table and "P99(ms)" in table
    row = next(l for l in table.splitlines() if l.startswith("agg_layer"))
    assert int(row.split()[1]) == 5
    # the aggregation outlives the raw events (dump-and-truncate cycle)
    with profiler._lock:
        profiler._events.clear()
    assert "agg_layer" in profiler.dumps()
    # snapshot API exposes the standing histograms
    snap = profiler.aggregate_stats_snapshot()
    assert snap["agg_layer"].count == 5
    # reset clears the aggregation too
    profiler.dumps(reset=True)
    assert "agg_layer" not in profiler.dumps()

    # flag off: plain table, no percentile columns, computed from events
    profiler.clear()
    profiler.set_config(mode="symbolic", filename="/tmp/unused_agg.json",
                        aggregate_stats=False)
    profiler.set_state("run")
    try:
        with profiler.scope("raw_layer"):
            pass
    finally:
        profiler.set_state("stop")
    table = profiler.dumps()
    assert "raw_layer" in table and "P99(ms)" not in table
    profiler.clear()


def test_profiler_off_keeps_fused_path():
    """With the profiler stopped, forward uses the fused program and
    records nothing."""
    profiler.clear()
    assert not profiler.ops_enabled()
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=4, name="fc")
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 8))
    exe.forward(is_train=False)
    assert profiler.dumps().count("\n") == 0  # header only, no rows


def test_named_scope_in_hlo():
    """Layer names land in the compiled HLO metadata (xprof attribution
    for the fused path)."""
    import jax

    from mxtpu.executor import _trace_graph

    net = _block(sym.Variable("data"), "layerX", 4)
    run = _trace_graph(net, is_train=False)
    args = {"data": np.zeros((1, 3, 8, 8), "float32"),
            "layerX_conv_weight": np.zeros((4, 3, 3, 3), "float32"),
            "layerX_bn_gamma": np.ones(4, "float32"),
            "layerX_bn_beta": np.zeros(4, "float32")}
    aux = {"layerX_bn_moving_mean": np.zeros(4, "float32"),
           "layerX_bn_moving_var": np.ones(4, "float32")}
    rng = np.zeros(2, "uint32")
    lowered = jax.jit(lambda a, x, r: run(a, x, r)).lower(args, aux, rng)
    try:  # loc() metadata carries scopes (kwarg added in newer jax)
        txt = lowered.as_text(debug_info=True)
    except TypeError:  # jax 0.4.x: ask the MLIR module for debug info
        txt = lowered.compiler_ir(dialect="stablehlo").operation.get_asm(
            enable_debug_info=True)
    assert "layerX_conv" in txt, "named_scope missing from lowered IR"


def test_profiler_ops_mode_through_module_fit():
    """Operator-mode profiling reaches Module.fit training: per-layer spans
    appear even though the fused one-program step is normally active."""
    import mxtpu as mx

    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    y = rng.randint(0, 4, 64).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("data"), num_hidden=4, name="fcp"),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    profiler.clear()
    profiler.set_config(mode="operator", filename="/tmp/unused.json")
    profiler.set_state("run")
    try:
        mod.fit(it, num_epoch=1,
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    finally:
        profiler.set_state("stop")
    table = profiler.dumps(reset=True)
    assert "fcp" in table and "backward" in table
    # training continued correctly on the classic path afterwards
    assert mod._fused is None  # retired by the first classic update
