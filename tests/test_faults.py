"""mxtpu.faults — seeded fault injection, the shared RetryPolicy, and
the chaos gates (docs/faults.md).

The chaos gates are the point of the subsystem: they convert the
robustness claims of PRs 4/8/10 from "handled" to "demonstrated under
injected failure":

* **elastic under fire** — a fit with ENOSPC + torn-write + writer-kill
  faults injected still resumes BIT-EXACT from the last good generation
  (the PR-8 parity gate, with the disk actively failing);
* **serving under fire** — replica-kill + dispatch-error faults at 1×
  load: every request answers or errors (zero hung waiters), no stale
  weights after recovery, and capacity returns to full via
  quarantine/respawn;
* **prefetch crash** — a producer-thread death surfaces the ORIGINAL
  exception at the consumer within one batch (regression for the
  silent-hang bug);
* **watchdog × faults** — an injected ``executor.device_wait`` latency
  past the stall deadline fires the real detector, the postmortem's
  flight ring names the injected cause, and the supervisor's
  restore-retry completes end-to-end.

Everything is seeded and bounded: fault schedules replay exactly,
RetryPolicy gets a no-op sleep wherever real backoff would cost suite
time (the ISSUE ops budget).
"""
import errno
import json
import os
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import faults
from mxtpu import metric as M
from mxtpu.base import MXNetError
from mxtpu.elastic import snapshot as esnap
from mxtpu.faults import RetryPolicy
from mxtpu.models import mlp as _mlp


NOSLEEP = {"sleep": lambda s: None}


@pytest.fixture(autouse=True)
def _disarm():
    """No schedule may leak across tests (or in from the environment)."""
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def fast_writer_retry():
    """The process snapshot writer with backoff sleeps removed (the
    injected-clock rule: chaos gates must not wait out real backoff)."""
    w = esnap.writer()
    old = w._retry
    w._retry = RetryPolicy(
        "elastic.snapshot.write", max_attempts=3, backoff_s=0.0,
        retryable=OSError, recover=w._recover_write, **NOSLEEP)
    yield w
    w.flush()
    w._retry = old


# ----------------------------------------------------------- injection unit
def test_schedule_grammar_and_validation():
    s = faults.parse_schedule(
        "elastic.snapshot.write:errno=ENOSPC,p=0.3,seed=7;"
        "serving.replica.dispatch:kind=kill,after=5")
    specs = {d["point"]: d for d in s.describe()}
    assert specs["elastic.snapshot.write"]["kind"] == "errno"
    assert specs["elastic.snapshot.write"]["errno"] == errno.ENOSPC
    assert specs["serving.replica.dispatch"]["kind"] == "kill"
    assert specs["serving.replica.dispatch"]["times"] == 1  # kill: once
    with pytest.raises(MXNetError):        # typo must fail loudly
        faults.parse_schedule("elastic.snapshott.write:kind=raise")
    with pytest.raises(MXNetError):        # unknown key too
        faults.parse_schedule("kvstore.push:frequency=2")
    with pytest.raises(MXNetError):
        faults.FaultSpec("kvstore.push", kind="explode")


def test_injection_is_seeded_deterministic():
    def firings(seed):
        s = faults.FaultSchedule(
            [faults.FaultSpec("kvstore.push", errno="EIO", p=0.3,
                              seed=seed)])
        out = []
        for _ in range(64):
            try:
                s.evaluate("kvstore.push")
                out.append(0)
            except OSError:
                out.append(1)
        return out

    a, b = firings(7), firings(7)
    assert a == b and sum(a) > 0          # replays exactly, and fires
    assert a != firings(8)                 # the seed is the schedule


def test_scope_arms_and_restores():
    assert faults.active() is None
    with faults.scope("kvstore.pull:kind=raise,times=1") as sched:
        assert faults.active() is sched
        with pytest.raises(faults.FaultInjected):
            faults.point("kvstore.pull")
        faults.point("kvstore.pull")       # times=1: spent
        assert sched.fired_total == 1
    assert faults.active() is None
    faults.point("kvstore.pull")           # disarmed: free no-op


def test_after_and_times_windows():
    with faults.scope("engine.dispatch:kind=raise,after=2,times=2"):
        faults.point("engine.dispatch")    # 1: within `after`
        faults.point("engine.dispatch")    # 2: within `after`
        for _ in range(2):                 # 3, 4: the firing window
            with pytest.raises(faults.FaultInjected):
                faults.point("engine.dispatch")
        faults.point("engine.dispatch")    # 5: `times` exhausted


def test_firing_emits_telemetry_and_flight_evidence():
    reg = mx.telemetry.registry()
    c = reg.counter("fault_injected",
                    labels={"point": "kvstore.push", "kind": "errno"})
    v0 = c.value
    with faults.scope("kvstore.push:errno=ENOSPC"):
        with pytest.raises(faults.InjectedIOError) as exc_info:
            faults.point("kvstore.push")
    assert exc_info.value.errno == errno.ENOSPC
    assert c.value == v0 + 1
    events = mx.diagnostics.recorder().snapshot()
    assert any(e["kind"] == "fault" and e["name"] == "kvstore.push"
               for e in events)


def test_env_arming(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULTS", "engine.dispatch:kind=raise,times=1")
    sched = faults.configure(None)
    assert [d["point"] for d in sched.describe()] == ["engine.dispatch"]
    monkeypatch.setenv("MXTPU_FAULTS", "")
    assert faults.configure(None) is None  # empty = off
    # malformed numeric values are MXNetError (not ValueError), so the
    # tolerant import-time arming catches them and import survives a
    # fat-fingered canary schedule
    with pytest.raises(MXNetError):
        faults.parse_schedule("kvstore.push:p=bogus")
    with pytest.raises(MXNetError):
        faults.parse_schedule("kvstore.push:after=2.5x")


# --------------------------------------------------------------- retry unit
def test_retry_policy_bounded_backoff_deterministic_jitter():
    sleeps = []
    calls = []
    pol = RetryPolicy("unit.op", max_attempts=4, backoff_s=1.0,
                      backoff_cap_s=3.0, sleep=sleeps.append,
                      clock=lambda: 0.0)

    def flaky():
        calls.append(1)
        if len(calls) < 4:
            raise ConnectionError("transient")
        return "ok"

    assert pol.call(flaky) == "ok"
    assert len(calls) == 4 and len(sleeps) == 3
    # exponential base with the cap engaged on the third retry
    assert sleeps == [pol.backoff(1), pol.backoff(2), pol.backoff(3)]
    assert pol.backoff(3) <= 3.0 * 1.1
    # jitter is a pure function of (op, seed, attempt): replayable
    assert pol.backoff(1) == RetryPolicy(
        "unit.op", backoff_s=1.0).backoff(1)
    assert pol.backoff(1) != RetryPolicy(
        "other.op", backoff_s=1.0).backoff(1)


def test_retry_policy_exhaustion_and_predicate():
    reg = mx.telemetry.registry()
    ex0 = reg.counter("retry_exhausted", labels={"op": "unit.dead"}).value

    def dead():
        raise OSError("disk on fire")

    with pytest.raises(OSError):
        RetryPolicy("unit.dead", max_attempts=3, backoff_s=0.0,
                    **NOSLEEP).call(dead)
    assert reg.counter("retry_exhausted",
                       labels={"op": "unit.dead"}).value == ex0 + 1

    # non-retryable: propagates immediately, no attempts counted
    calls = []
    def usage_error():
        calls.append(1)
        raise MXNetError("caller bug")
    with pytest.raises(MXNetError):
        RetryPolicy("unit.usage", max_attempts=5, **NOSLEEP).call(
            usage_error)
    assert len(calls) == 1


def test_env_attempts_convention(monkeypatch):
    """`*_RETRIES` env vars count retries AFTER the first attempt
    (N+1 attempts, 0 = no retries), and a bad value falls back to the
    default instead of crashing the mechanism it configures."""
    monkeypatch.delenv("X_RETRIES", raising=False)
    assert faults.env_attempts("X_RETRIES", 3) == 4
    monkeypatch.setenv("X_RETRIES", "0")
    assert faults.env_attempts("X_RETRIES", 3) == 1   # never < 1
    monkeypatch.setenv("X_RETRIES", "2")
    assert faults.env_attempts("X_RETRIES", 3) == 3
    monkeypatch.setenv("X_RETRIES", "bogus")
    assert faults.env_attempts("X_RETRIES", 3) == 4   # tolerant


def test_retry_policy_recover_hook_skips_backoff():
    sleeps = []
    recovered = []
    calls = []

    def recover(exc, attempt):
        recovered.append((type(exc).__name__, attempt))
        return True                        # resource freed: retry NOW

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise OSError(errno.ENOSPC, "full")
        return 42

    pol = RetryPolicy("unit.recover", max_attempts=3, backoff_s=9.0,
                      recover=recover, sleep=sleeps.append)
    assert pol.call(flaky) == 42
    assert recovered == [("OSError", 1)] and sleeps == []


# ------------------------------------------------------- kvstore under fire
def test_kvstore_push_pull_retry_transient():
    reg = mx.telemetry.registry()
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((4,)))
    a0 = reg.counter("retry_attempts", labels={"op": "kvstore.push"}).value
    # deterministic window: evaluations 2 and 3 fire — the second push
    # fails once, retries once more into the window, then lands
    with faults.scope("kvstore.push:errno=ECONNRESET,after=1,times=2"):
        kv.push("w", mx.nd.ones((4,)))         # eval 1: clean
        # evals 2,3 fire; attempt 3 (eval 4) lands — exactly at the
        # default bound of 3 attempts
        kv.push("w", mx.nd.array(np.full(4, 2.0, "f4")))
    assert reg.counter("retry_attempts",
                       labels={"op": "kvstore.push"}).value == a0 + 2
    out = mx.nd.zeros((4,))
    p0 = reg.counter("retry_attempts", labels={"op": "kvstore.pull"}).value
    with faults.scope("kvstore.pull:errno=ETIMEDOUT,times=1"):
        kv.pull("w", out=out)
    assert reg.counter("retry_attempts",
                       labels={"op": "kvstore.pull"}).value == p0 + 1
    # no updater armed: push assigns, so the LAST push's value sticks
    np.testing.assert_array_equal(out.asnumpy(), np.full(4, 2.0, "f4"))


def test_kvstore_push_exhaustion_raises_original():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((2,)))
    with faults.scope("kvstore.push:errno=ECONNRESET"):  # every attempt
        with pytest.raises(faults.InjectedIOError):
            kv.push("w", mx.nd.ones((2,)))


# --------------------------------------------------- snapshot writer's IO
def _gen_job(prefix, g, keep=2):
    return esnap.SnapshotJob(
        "generation", {"arg:w": np.full(4, float(g), "f4")}, prefix=prefix,
        generation=g, keep=keep,
        manifest={"format": esnap.FORMAT,
                  "cursor": {"epoch": 0, "nbatch": g, "global_step": g}})


def test_writer_enospc_prunes_then_retries(tmp_path, fast_writer_retry):
    """The named degradation contract: a disk-full generation write
    frees space (prune to keep-1) and retries immediately — the NEW
    state wins over history depth."""
    reg = mx.telemetry.registry()
    w = fast_writer_retry
    prefix = str(tmp_path / "run")
    for g in (1, 2):
        w.submit(_gen_job(prefix, g))
    w.flush()
    assert esnap.list_generations(prefix) == [1, 2]
    r0 = reg.counter("retry_attempts",
                     labels={"op": "elastic.snapshot.write"}).value
    with faults.scope("elastic.snapshot.write:errno=ENOSPC,times=1"):
        w.submit(_gen_job(prefix, 3))
        w.flush()
    assert reg.counter("retry_attempts",
                       labels={"op": "elastic.snapshot.write"}).value \
        == r0 + 1
    man = esnap.latest_manifest(prefix)
    assert man["_generation"] == 3          # the retried write LANDED
    assert 1 not in esnap.list_generations(prefix)  # prune freed space


def test_writer_exhaustion_degrades_not_raises(tmp_path,
                                               fast_writer_retry):
    """Retries exhausted: the generation is abandoned and COUNTED
    (elastic_write_failures), the previous one still loads, and the
    writer keeps serving later jobs — nothing raises anywhere near the
    training thread."""
    reg = mx.telemetry.registry()
    w = fast_writer_retry
    prefix = str(tmp_path / "run")
    w.submit(_gen_job(prefix, 1))
    w.flush()
    f0 = reg.counter("elastic_write_failures").value
    with faults.scope("elastic.snapshot.write:errno=EIO"):  # every attempt
        w.submit(_gen_job(prefix, 2))
        w.flush()
    assert reg.counter("elastic_write_failures").value == f0 + 1
    assert esnap.latest_manifest(prefix)["_generation"] == 1
    w.submit(_gen_job(prefix, 3))           # the writer is still alive
    w.flush()
    assert esnap.latest_manifest(prefix)["_generation"] == 3


def test_torn_rename_fault_leaves_previous_generation(tmp_path,
                                                      fast_writer_retry):
    """A fault between the tmp write and its rename (the crash window
    the atomic protocol exists for): the generation never completes,
    the pointer never flips, the previous generation loads."""
    w = fast_writer_retry
    prefix = str(tmp_path / "run")
    w.submit(_gen_job(prefix, 1))
    w.flush()
    # kind=raise is NOT retryable (not an OSError): the job dies on the
    # torn rename, simulating a crash mid-protocol
    with faults.scope("elastic.snapshot.fsync_rename:kind=raise,times=1"):
        w.submit(_gen_job(prefix, 2))
        w.flush()
    man = esnap.latest_manifest(prefix)
    assert man["_generation"] == 1
    np.testing.assert_array_equal(esnap.load_arrays(man)["arg:w"],
                                  np.ones(4, "f4"))


def test_writer_kill_respawns_on_next_use(tmp_path, fast_writer_retry):
    """An injected writer death loses its in-flight job but neither
    hangs flush() nor kills the process: the next submit respawns the
    thread and later generations land."""
    w = fast_writer_retry
    prefix = str(tmp_path / "run")
    w.submit(_gen_job(prefix, 1))
    w.flush()
    with faults.scope("elastic.snapshot.write:kind=kill"):
        w.submit(_gen_job(prefix, 2))
        assert w.flush(timeout=10)          # must NOT hang
    w.submit(_gen_job(prefix, 3))           # respawns the thread
    w.flush()
    assert esnap.latest_manifest(prefix)["_generation"] == 3


# ----------------------------------------------------- elastic chaos gate
def _mnist_like(n=256, seed=7):
    rng = np.random.RandomState(seed)
    return (rng.rand(n, 784).astype("float32"),
            rng.randint(0, 10, n).astype("float32"))


def _make_iter(batch_size=64):
    X, y = _mnist_like()
    return mx.io.NDArrayIter(X, y, batch_size=batch_size,
                             label_name="softmax_label")


class Kill(Exception):
    """Simulated hard death of the training process."""


def _fit(num_epoch=2, seed=11, kill_at_step=None, module=None,
         **fit_kwargs):
    it = _make_iter()
    mod = module or mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    metric = M.create(["acc", "ce"])
    mx.random.seed(seed)
    np.random.seed(seed)
    steps = [0]
    cb = None
    if kill_at_step is not None:
        def cb(param):
            steps[0] += 1
            if steps[0] >= kill_at_step:
                raise Kill()
    try:
        mod.fit(it, num_epoch=num_epoch, eval_metric=metric,
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
                initializer=mx.initializer.Xavier(),
                batch_end_callback=cb, metric_sync=2, **fit_kwargs)
    except Kill:
        pass
    weights = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    return dict(metric.get_name_value()), weights, mod


def test_chaos_gate_elastic_resume_bit_exact_under_write_faults(
        tmp_path, fast_writer_retry):
    """THE elastic chaos gate: ENOSPC (retried through prune), a torn
    rename (abandons its generation), and a writer kill (thread death)
    all injected into a checkpointing fit — the kill-at-step-N resume
    is STILL bit-exact, from whichever generation survived."""
    reg = mx.telemetry.registry()
    prefix = str(tmp_path / "ck")
    m_full, w_full, _ = _fit()
    # epoch_period=0: generation count == step count, so the schedule's
    # `after` windows land on exact, documented jobs (determinism)
    cfg = mx.elastic.ElasticConfig(prefix, every_n_steps=1,
                                   epoch_period=0, sync=True)
    f0 = reg.counter("elastic_write_failures").value
    # write-point evals: g1=1 | g2=2 (ENOSPC fires) +retry=3 | g3=4 |
    # g4=5 | g5=6 (kill fires). fsync evals: g1=1..3, g2 retry=4..6,
    # g3=7 (torn data rename — generation abandoned, not retried:
    # kind=raise is not an OSError). Landed generations: 1, 2, 4.
    sched = ("elastic.snapshot.write:errno=ENOSPC,times=1,after=1;"
             "elastic.snapshot.fsync_rename:kind=raise,after=6,times=1;"
             "elastic.snapshot.write:kind=kill,after=5,times=1")
    with faults.scope(sched) as s:
        _fit(kill_at_step=5, elastic=cfg)
        fired = s.fired_total
    assert fired >= 3, s.describe()          # all three fault flavors
    assert reg.counter("elastic_write_failures").value > f0
    man = esnap.latest_manifest(prefix)
    assert man is not None                   # at least one gen survived
    assert man["cursor"]["global_step"] < 5  # ...and not the latest: the
    # injected failures really cost generations, so resume must replay
    m_res, w_res, _ = _fit(resume=prefix, elastic=False)
    for k in w_full:
        np.testing.assert_array_equal(
            w_full[k], w_res[k],
            err_msg="weights diverged at %s under injected faults" % k)
    assert m_full["accuracy"] == m_res["accuracy"]
    np.testing.assert_allclose(m_full["cross-entropy"],
                               m_res["cross-entropy"], rtol=1e-5)


# ----------------------------------------------------- serving chaos gate
def test_chaos_gate_serving_replica_kill_no_hung_waiters():
    """THE serving chaos gate: dispatch-error + replica-kill faults at
    1× load — every request answers or errors (zero hung waiters),
    capacity recovers to full via quarantine/respawn, and post-recovery
    outputs are byte-identical to pre-fault ones (zero stale weights)."""
    from mxtpu.models.serving_fixtures import get_fixture
    from mxtpu.serving import ServingSession
    sym, params, shapes = get_fixture("mlp")
    with ServingSession(sym, params, shapes, buckets=(1, 4),
                        max_delay_ms=2, contexts=[mx.cpu(0)]) as sess:
        x = np.random.RandomState(0).rand(1, 784).astype(np.float32)
        want = sess.predict({"data": x})[0]

        results = []
        def client():
            try:
                out = sess.predict({"data": x}, timeout=20)
                results.append(("ok", out))
            except Exception as exc:
                results.append(("err", exc))

        sched = ("serving.replica.dispatch:kind=raise,p=0.3,seed=5;"
                 "serving.replica.dispatch:kind=kill,after=4,times=1")
        with faults.scope(sched) as s:
            threads = [threading.Thread(target=client, daemon=True)
                       for _ in range(30)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            hung = sum(t.is_alive() for t in threads)
            assert s.fired_total > 0
        assert hung == 0, "hung waiters under injected replica faults"
        assert len(results) == 30            # every request resolved
        oks = [r for r in results if r[0] == "ok"]
        errs = [r for r in results if r[0] == "err"]
        assert oks and errs                  # both outcomes exercised
        for _, out in oks:
            # answered = the CURRENT weights' answer. Tolerance, not
            # byte-equality: a coalesced request runs the bucket-4
            # program, whose XLA:CPU reduction order differs in the
            # last bits from the bucket-1 reference
            np.testing.assert_allclose(out[0], want, rtol=1e-5,
                                       atol=1e-6)
        # the kill quarantined the replica and the respawn recovered it
        assert sess.metrics.counter("replica_quarantined").value >= 1
        deadline = time.monotonic() + 20
        while sess.healthy_replicas() < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sess.healthy_replicas() == len(sess.pool)  # full capacity
        assert sess.metrics.counter(
            "replica_respawned", labels={"outcome": "ok"}).value >= 1
        # zero stale weights: the rebuilt replica serves the same bytes
        out2 = sess.predict({"data": x}, timeout=10)[0]
        np.testing.assert_array_equal(want, out2)


def test_serving_degraded_capacity_is_reported():
    """While a replica is quarantined, /healthz-visible state and the
    admission signals must see the reduced capacity (est-wait honesty),
    and recover when the respawn lands."""
    from mxtpu.models.serving_fixtures import get_fixture
    from mxtpu.serving import ServingSession
    sym, params, shapes = get_fixture("mlp")
    with ServingSession(sym, params, shapes, buckets=(1, 4),
                        max_delay_ms=2, contexts=[mx.cpu(0)]) as sess:
        full_limit = sess._signals().inflight_limit
        assert full_limit == sess.max_in_flight
        with faults.scope("serving.replica.dispatch:kind=kill"):
            try:
                sess.predict({"data": np.zeros((1, 784), "f4")},
                             timeout=10)
            except Exception:
                pass
            deadline = time.monotonic() + 10
            while sess.healthy_replicas() > 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert sess.healthy_replicas() == 0
            sig = sess._signals()
            assert sig.inflight_limit == 0 and sig.replicas == 0
            assert sess.metrics.gauge("replicas_healthy").value == 0
        deadline = time.monotonic() + 20
        while sess.healthy_replicas() < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sess._signals().inflight_limit == full_limit


def test_serving_collect_kill_answers_waiters():
    """A kill at the RETIRE seam (batch already out of the in-flight
    window) must still answer that batch's waiters before the thread
    unwinds — the hole a plain `except Exception` in _retire left."""
    from mxtpu.models.serving_fixtures import get_fixture
    from mxtpu.serving import ReplicaCrash, ServingSession
    sym, params, shapes = get_fixture("mlp")
    with ServingSession(sym, params, shapes, buckets=(1, 4),
                        max_delay_ms=2, contexts=[mx.cpu(0)]) as sess:
        x = np.zeros((1, 784), np.float32)
        sess.predict({"data": x})                 # warm
        with faults.scope("serving.replica.collect:kind=kill"):
            with pytest.raises(ReplicaCrash):
                sess.predict({"data": x}, timeout=10)
        deadline = time.monotonic() + 20
        while sess.healthy_replicas() < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sess.healthy_replicas() == len(sess.pool)
        sess.predict({"data": x}, timeout=10)     # serves again


def test_serving_respawn_failure_is_counted_not_silent(monkeypatch):
    """A rebuild that itself dies — including on a BaseException like a
    kill-mode fault — must land in `replica_respawned{outcome=failed}`
    with the replica still quarantined; a silently dead respawn thread
    is the exact capacity shrink this path exists to eliminate."""
    from mxtpu.models.serving_fixtures import get_fixture
    from mxtpu.serving import ServingSession
    from mxtpu.serving import pool as pool_mod
    sym, params, shapes = get_fixture("mlp")
    with ServingSession(sym, params, shapes, buckets=(1,),
                        max_delay_ms=2, contexts=[mx.cpu(0)]) as sess:
        sess.predict({"data": np.zeros((1, 784), "f4")})
        f0 = sess.metrics.counter("replica_respawned",
                                  labels={"outcome": "failed"}).value
        monkeypatch.setattr(
            pool_mod.ExecutorPool, "rebuild_replica",
            lambda self, idx: (_ for _ in ()).throw(
                faults.FaultKill("injected kill inside rebuild")))
        with faults.scope("serving.replica.dispatch:kind=kill"):
            try:
                sess.predict({"data": np.zeros((1, 784), "f4")},
                             timeout=10)
            except Exception:
                pass
        deadline = time.monotonic() + 20
        while sess.metrics.counter(
                "replica_respawned",
                labels={"outcome": "failed"}).value == f0 \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sess.metrics.counter(
            "replica_respawned", labels={"outcome": "failed"}).value \
            == f0 + 1
        assert sess.healthy_replicas() == 0  # honest: still quarantined


# ---------------------------------------------------- prefetch chaos gate
class _CrashingIter(mx.io.NDArrayIter):
    def __init__(self, *args, fail_at=3, exc=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._count = 0
        self._fail_at = fail_at
        self._exc = exc or ValueError("producer boom")

    def next(self):
        self._count += 1
        if self._count == self._fail_at:
            raise self._exc
        return super().next()


def test_chaos_gate_prefetch_producer_crash_surfaces_at_consumer():
    """THE prefetch gate (and the satellite bugfix's regression test):
    a producer-thread crash re-raises the ORIGINAL exception at the
    consumer within one batch — before the fix it hung the consumer
    forever on data_ready."""
    X, y = _mnist_like(n=256)
    base = _CrashingIter(X, y, batch_size=64, fail_at=3,
                         label_name="softmax_label")
    it = mx.io.PrefetchingIter(base)
    try:
        assert it.iter_next()                # batch 1
        assert it.iter_next()                # batch 2
        with pytest.raises(ValueError, match="producer boom"):
            it.iter_next()                   # batch 3: the crash surfaces
        # the iterator is poisoned, not half-working: every further use
        # re-raises the same original error
        with pytest.raises(ValueError, match="producer boom"):
            next(it)
        with pytest.raises(ValueError, match="producer boom"):
            it.reset()
        for t in it.prefetch_threads:        # the producer really exited
            t.join(timeout=5)
            assert not t.is_alive()
    finally:
        it.close()


def test_prefetch_injected_fault_surfaces():
    """Same contract through the injection point — and through
    Module.fit's consumption of the iterator: the fit dies with the
    injected error instead of hanging."""
    X, y = _mnist_like(n=256)
    it = mx.io.PrefetchingIter(
        mx.io.NDArrayIter(X, y, batch_size=64,
                          label_name="softmax_label"))
    try:
        with faults.scope("io.prefetch.produce:kind=raise,after=2,"
                          "times=1"):
            with pytest.raises(faults.FaultInjected):
                while True:
                    it.iter_next()
    finally:
        it.close()


# -------------------------------------------------- watchdog x faults gate
def test_watchdog_fires_on_injected_device_wait_latency(tmp_path,
                                                        fast_writer_retry):
    """End-to-end: an injected ``executor.device_wait`` latency past the
    watchdog's wait deadline fires the REAL detector (no hand-built
    wedged-engine plumbing), the postmortem's flight ring contains the
    ``fault_injected`` event naming the cause, and the supervisor's
    checkpoint-restore-retry completes with numbers equal to an
    uninterrupted fit."""
    from mxtpu.diagnostics import Watchdog
    prefix = str(tmp_path / "ck")
    m_full, w_full, _ = _fit()
    wd = Watchdog(interval=0.01, engine_stall_s=99,
                  wait_stall_s=0.05).start()
    sup = mx.elastic.Supervisor(retries=2, backoff_s=0.0, **NOSLEEP)
    cfg = mx.elastic.ElasticConfig(prefix, every_n_steps=1, sync=True,
                                   supervisor=sup)
    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    metric = M.create(["acc", "ce"])
    attempts = []

    def fit_fn(resume):
        attempts.append(resume)
        mx.random.seed(11)
        np.random.seed(11)
        mod.fit(_make_iter(), num_epoch=2, eval_metric=metric,
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
                initializer=mx.initializer.Xavier(), metric_sync=2,
                elastic=cfg, resume=resume)

    d0 = wd.detections
    try:
        # one 500ms stall inside the pacing wait, several steps in —
        # 10x the 50ms deadline, sampled every 10ms
        with faults.scope("executor.device_wait:latency_ms=500,after=3,"
                          "times=1"):
            sup.run(fit_fn)
    finally:
        wd.stop()
    assert attempts == [False, True]         # wedge -> restore-retry
    assert wd.detections > d0
    pm = mx.diagnostics.last_postmortem()
    assert pm is not None and pm["source"] == "watchdog"
    assert any(e["kind"] == "fault"
               and e["name"] == "executor.device_wait"
               for e in pm.get("flight", [])), \
        "postmortem flight ring must name the injected cause"
    # recovery half: final numbers equal the uninterrupted fit
    assert m_full["accuracy"] == dict(metric.get_name_value())["accuracy"]
    w_sup = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in w_full:
        np.testing.assert_array_equal(w_full[k], w_sup[k], err_msg=k)


# ----------------------------------------------------- supervisor / series
def test_supervisor_runs_through_shared_retry_policy():
    """Supervisor.run's loop IS a RetryPolicy now: its knobs surface as
    the policy's, WedgeAbort is the only retryable, and exhaustion
    lands in retry_exhausted{op=elastic.supervisor}."""
    reg = mx.telemetry.registry()
    sup = mx.elastic.Supervisor(retries=2, backoff_s=0.0, **NOSLEEP)
    pol = sup.retry_policy()
    assert pol.max_attempts == 3
    assert pol.is_retryable(mx.elastic.WedgeAbort("x"))
    assert not pol.is_retryable(mx.elastic.Preempted("x"))
    assert not pol.is_retryable(OSError("x"))

    calls = []
    ex0 = reg.counter("retry_exhausted",
                      labels={"op": "elastic.supervisor"}).value
    def always_wedged(resume):
        calls.append(resume)
        raise mx.elastic.WedgeAbort("synthetic wedge")
    with pytest.raises(mx.elastic.WedgeAbort):
        sup.run(always_wedged)
    assert calls == [False, True, True]
    assert reg.counter("retry_exhausted",
                       labels={"op": "elastic.supervisor"}).value == ex0 + 1
    assert sup.retries_done == 3


def test_point_guard_is_noop_when_disarmed():
    """The zero-overhead contract's functional half: with nothing armed
    every point is a silent no-op (the µs cost is bench_faults.py's)."""
    for name in faults.POINTS:
        faults.point(name)
