"""Model zoo forward-shape tests (parity tier: reference
tests/python/unittest/test_gluon_model_zoo.py which instantiates every
model and checks the forward pass)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.gluon.model_zoo import vision


def _check(net, size=32, classes=10, batch=2):
    net.collect_params().initialize(ctx=mx.cpu())
    x = mx.nd.random.uniform(shape=(batch, 3, size, size))
    out = net(x)
    assert out.shape == (batch, classes)
    return out


@pytest.mark.parametrize("name,size", [
    ("resnet18_v1", 32), ("resnet18_v2", 32),
    ("mobilenet0.25", 32),
    ("squeezenet1.0", 64), ("squeezenet1.1", 64),
    # tier-1 time budget (ROADMAP ops note, PR 7): the heaviest
    # forward (densenet: ~19s) runs in the slow tier; the cheap
    # per-family smokes stay tier-1
    pytest.param("densenet121", 32, marks=pytest.mark.slow),
    ("alexnet", 224),
    ("vgg11", 32), ("vgg11_bn", 32),
])
def test_models_forward(name, size):
    net = vision.get_model(name, classes=10)
    _check(net, size=size)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_inception_v3_forward():
    net = vision.get_model("inceptionv3", classes=10)
    _check(net, size=299)


def test_get_model_unknown():
    with pytest.raises(ValueError):
        vision.get_model("not_a_model")


def test_model_zoo_hybridize():
    net = vision.get_model("mobilenet0.25", classes=10)
    net.collect_params().initialize(ctx=mx.cpu())
    x = mx.nd.random.uniform(shape=(2, 3, 32, 32))
    eager = net(x).asnumpy()
    net.hybridize()
    cached = net(x).asnumpy()
    np.testing.assert_allclose(eager, cached, rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_model_zoo_trains():
    from mxtpu import gluon, autograd

    net = vision.get_model("squeezenet1.1", classes=4)
    net.collect_params().initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.nd.random.uniform(shape=(4, 3, 64, 64))
    y = mx.nd.array(np.array([0, 1, 2, 3], "float32"))
    losses = []
    for _ in range(3):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(4)
        losses.append(float(loss.mean().asnumpy()))
    assert np.isfinite(losses).all()


def test_symbol_model_factories():
    from mxtpu import models

    for get, shape in [(models.get_alexnet, (2, 3, 224, 224)),
                       (models.get_vgg, (2, 3, 32, 32)),
                       (models.get_inception_bn, (2, 3, 224, 224))]:
        s = get(num_classes=10)
        arg_shapes, out_shapes, _ = s.infer_shape(
            data=shape, softmax_label=(shape[0],))
        assert out_shapes[0] == (shape[0], 10), (get, out_shapes)


def test_googlenet_symbol_forward():
    """GoogLeNet symbol family (symbols/googlenet.py parity): shape chain
    through the inception concat blocks + a forward."""
    net = mx.models.get_googlenet(num_classes=10)
    args, outs, _ = net.infer_shape(data=(1, 3, 224, 224),
                                    softmax_label=(1,))
    assert outs == [(1, 10)]
    exe = net.simple_bind(ctx=mx.cpu(), data=(1, 3, 224, 224),
                          softmax_label=(1,), grad_req="null")
    rng = np.random.RandomState(0)
    for k, v in exe.arg_dict.items():
        if k != "softmax_label":
            v[:] = mx.nd.array(rng.uniform(-0.05, 0.05, v.shape)
                               .astype("float32"))
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (1, 10)
    np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-4)


def test_inception_v3_symbol_shapes():
    """Inception-v3 symbol family (symbols/inception-v3.py parity):
    module grammar A/B/C + reductions yields the paper's 2048-d trunk."""
    net = mx.models.get_inception_v3(num_classes=7)
    args, outs, _ = net.infer_shape(data=(2, 3, 299, 299),
                                    softmax_label=(2,))
    assert outs == [(2, 7)]
    # module-C trunk: 320 + (384+384) + (384+384) + 192 = 2048 channels
    d = dict(zip(net.list_arguments(), args))
    assert d["fc1_weight"] == (7, 2048)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_symbol_factories_round3():
    """resnext / mobilenet / resnet_v1 symbol factories (parity:
    example/image-classification/symbols/{resnext,mobilenet,resnet-v1}.py
    — the BASELINE.md resnext quality rows' architectures): shapes infer,
    a train step runs, grouped/depthwise convs lower through XLA."""
    import numpy as np
    import mxtpu as mx
    from mxtpu.models import mobilenet, resnet_v1, resnext

    cases = [
        (resnext.get_symbol(num_classes=10, num_layers=26,
                            image_shape=(3, 32, 32), num_group=8), 1370),
        (mobilenet.get_symbol(num_classes=10, multiplier=0.25), None),
        (resnet_v1.get_symbol(num_classes=10, num_layers=18,
                              image_shape=(3, 32, 32)), None),
    ]
    for net, _ in cases:
        shape = (2, 3, 224, 224) if "sep1" in str(net.list_arguments()) \
            else (2, 3, 32, 32)
        shapes, out_shapes, _ = net.infer_shape(data=shape)
        assert out_shapes[0] == (2, 10), out_shapes
        mod = mx.mod.Module(net, context=mx.cpu(0))
        mod.bind(data_shapes=[("data", shape)],
                 label_shapes=[("softmax_label", (shape[0],))])
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.01})
        rng = np.random.RandomState(0)
        batch = mx.io.DataBatch(
            data=[mx.nd.array(rng.rand(*shape).astype("float32"))],
            label=[mx.nd.array(rng.randint(0, 10, (shape[0],))
                               .astype("float32"))])
        mod.forward_backward(batch)
        mod.update()
        out = mod.get_outputs()[0].asnumpy()
        assert out.shape == (shape[0], 10)
        assert np.all(np.isfinite(out))


def test_inception_v4_symbol():
    """inception-v4 factory (parity symbols/inception-v4.py): paper block
    layout, shapes infer at 299x299, forward runs."""
    import numpy as np
    import mxtpu as mx
    from mxtpu.models import inception_v4

    net = inception_v4.get_symbol(num_classes=10)
    _, out_shapes, _ = net.infer_shape(data=(1, 3, 299, 299))
    assert out_shapes[0] == (1, 10)
    ex = net.simple_bind(mx.cpu(), data=(1, 3, 299, 299), grad_req="null")
    rng = np.random.RandomState(0)
    for n in ex.arg_dict:
        if n != "data" and n != "softmax_label":
            ex.arg_dict[n][:] = mx.nd.array(
                rng.randn(*ex.arg_dict[n].shape).astype("float32") * 0.05)
    ex.arg_dict["data"][:] = mx.nd.array(
        rng.rand(1, 3, 299, 299).astype("float32"))
    out = ex.forward(is_train=False)[0].asnumpy()
    assert out.shape == (1, 10) and np.all(np.isfinite(out))
    assert abs(out.sum() - 1.0) < 1e-3  # softmax head


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_inception_resnet_v2_symbol():
    """inception-resnet-v2 factory (parity symbols/inception-resnet-v2.py):
    residual-scaled blocks, shapes infer at 299x299, forward finite."""
    import numpy as np
    import mxtpu as mx
    from mxtpu.models import inception_resnet_v2 as irv2

    net = irv2.get_symbol(num_classes=10)
    _, out_shapes, _ = net.infer_shape(data=(1, 3, 299, 299))
    assert out_shapes[0] == (1, 10)
    ex = net.simple_bind(mx.cpu(), data=(1, 3, 299, 299), grad_req="null")
    rng = np.random.RandomState(0)
    for n in ex.arg_dict:
        if n not in ("data", "softmax_label"):
            ex.arg_dict[n][:] = mx.nd.array(
                rng.randn(*ex.arg_dict[n].shape).astype("float32") * 0.05)
    ex.arg_dict["data"][:] = mx.nd.array(
        rng.rand(1, 3, 299, 299).astype("float32"))
    out = ex.forward(is_train=False)[0].asnumpy()
    assert out.shape == (1, 10) and np.all(np.isfinite(out))
