"""mxtpu.sharding — the SPMD mesh execution layer (docs/sharding.md).

Runs tier-1 on the forced 8-device CPU mesh (conftest). The contracts:

* ``parameter_spec_from_name`` heuristics match the golden table for the
  mlp/lenet/lstm fixture params (replicated-bias + unknown-fallback rows
  included);
* ``Module.fit(mesh=...)`` trains the mlp fixture to metric parity with
  the single-device fused path: EXACT for integer-summed metrics,
  <=1e-5 cross-entropy drift (batch sharding reorders the gradient
  reduction, nothing else);
* cross-replica weight-update sharding really shards: optimizer state
  lives 1/n-per-chip (plus the replicated small-state overhead), and the
  diagnostics ledger's ``shard_bytes`` view reports it — replicated
  params at full size on EVERY device, sharded optimizer state only its
  shard;
* the ``sharding_consistency`` pass fails plan bugs at ``Module.check()``;
* KVStore 'local'/'device' push/pull ride mesh collectives when a mesh
  is active, bit-matching the legacy host merge loop.
"""
import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, PartitionSpec as P

import mxtpu as mx
from mxtpu import metric as M
from mxtpu import sharding as sh
from mxtpu import sym
from mxtpu.models import mlp as _mlp


@pytest.fixture(autouse=True)
def _clean_mesh():
    """No test may leak an active mesh (or MXTPU_MESH) into the suite."""
    yield
    sh.deactivate()
    os.environ.pop("MXTPU_MESH", None)


# --------------------------------------------------------------- heuristics
#: the golden table (satellite): fixture param name -> raw heuristic spec.
#: Raw = before plan pruning; on the 1-D data mesh every fsdp/tp entry
#: prunes to replication and only opt-state/batch specs use 'data'.
_GOLDEN = {
    # mlp fixture (models/mlp.py)
    "fc1_weight": P("fsdp", "tp"),
    "fc1_bias": P(),                        # replicated-bias rule
    "fc2_weight": P("fsdp", "tp"),
    "fc2_bias": P(),
    "fc3_weight": P("fsdp", "tp"),
    "fc3_bias": P(),
    # lenet fixture (models/lenet.py)
    "conv1_weight": P("fsdp", "tp"),
    "conv1_bias": P(),
    "conv2_weight": P("fsdp", "tp"),
    "conv2_bias": P(),
    # lstm LM fixture (examples/rnn/lstm_bucketing.py shape)
    "embed_weight": P(("fsdp", "tp"), None),  # embedding rule
    "lstm_l0_i2h_weight": P("fsdp", "tp"),    # projection rule
    "lstm_l0_i2h_bias": P(),
    "lstm_l0_h2h_weight": P("fsdp", "tp"),
    "lstm_l0_h2h_bias": P(),
    "pred_weight": P("fsdp", "tp"),
    "pred_bias": P(),
    # batch-norm stats replicate
    "bn0_gamma": P(),
    "bn0_beta": P(),
    "bn0_moving_mean": P(),
    "bn0_moving_var": P(),
    # unknown-name fallback: replicate (sharding can break an unknown
    # param, replication cannot)
    "mystery_state": P(),
    "rho": P(),
    # out-projections are row-parallel (checked BEFORE the 'attn'
    # input-projection key, which such names also contain)
    "self_attn.o_proj.weight": P("fsdp", None),
    "transformer_h0_attn_qkv_weight": P("fsdp", "tp"),
}


def _lstm_fixture_symbol():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(num_hidden=8, prefix="lstm_l0_"))
    data = sym.Variable("data")
    embed = sym.Embedding(data, input_dim=20, output_dim=8, name="embed")
    outputs, _ = stack.unroll(4, inputs=embed, merge_outputs=True)
    net = sym.Reshape(outputs, shape=(-1, 8))
    net = sym.FullyConnected(net, num_hidden=20, name="pred")
    return sym.SoftmaxOutput(net, name="softmax")


def test_parameter_spec_golden_table():
    for name, want in _GOLDEN.items():
        got = sh.parameter_spec_from_name(name)
        assert got == want, "%s: %s != golden %s" % (name, got, want)
    # the table is honest: every non-synthetic row is a REAL fixture
    # parameter name
    from mxtpu.models import lenet as _lenet
    real = set(_mlp.get_symbol(10).list_arguments()) \
        | set(_lenet.get_symbol(10).list_arguments()) \
        | set(_lstm_fixture_symbol().list_arguments())
    synthetic = {"bn0_gamma", "bn0_beta", "bn0_moving_mean",
                 "bn0_moving_var", "mystery_state", "rho",
                 "self_attn.o_proj.weight",
                 "transformer_h0_attn_qkv_weight"}
    for name in set(_GOLDEN) - synthetic:
        assert name in real, "golden row %s is not a fixture param" % name


def test_mesh_context_forms():
    n = len(jax.local_devices())
    assert n >= 8, "conftest must force an 8-device CPU mesh"
    assert sh.MeshContext.create("all").axis_sizes == {"data": n}
    assert sh.MeshContext.create(8).axis_sizes == {"data": 8}
    assert sh.MeshContext.create("4x2").axis_sizes == {"data": 4, "tp": 2}
    assert sh.MeshContext.create("data:2,tp:4").axis_sizes == \
        {"data": 2, "tp": 4}
    raw = Mesh(np.asarray(jax.local_devices()[:4]), ("data",))
    mc = sh.MeshContext.create(raw)
    assert mc.mesh is raw and mc.n_data == 4
    assert sh.MeshContext.create(mc) is mc
    with pytest.raises(mx.MXNetError):
        sh.MeshContext.create("definitely-not-a-mesh")
    with pytest.raises(mx.MXNetError):
        sh.MeshContext.create(10 ** 6)


def test_plan_weight_update_specs():
    mc = sh.MeshContext.create(8)
    shapes = {"fc1_weight": (128, 784), "fc1_bias": (128,),
              "fc2_weight": (64, 128), "fc2_bias": (64,),
              "fc3_weight": (10, 64), "fc3_bias": (10,)}
    plan = sh.ShardingPlan(mc, shapes, data_names=["data"],
                           label_names=["softmax_label"],
                           batch_shapes={"data": (64, 784),
                                         "softmax_label": (64,)})
    # params replicate on a data-only mesh (fsdp/tp prune away) ...
    for name in shapes:
        assert plan.param_spec(name) == P(), name
    # ... but the big optimizer states shard over 'data' (weight-update
    # sharding); dim0=10 doesn't divide by 8 and biases are under the
    # min-size floor -> replicated ("+ replication overhead")
    assert plan.opt_spec("fc1_weight") == P("data")
    assert plan.opt_spec("fc2_weight") == P("data")
    assert plan.opt_spec("fc3_weight") == P()
    assert plan.opt_spec("fc1_bias") == P()
    assert sorted(plan.sharded_opt_names()) == ["fc1_weight", "fc2_weight"]
    # batch shards over data; the naive fallback replicates what can't
    assert plan.batch_spec("data") == P("data")
    assert sh.naive_spec((30, 16), mc) == P()      # 30 % 8 != 0
    assert sh.naive_spec((64, 16), mc) == P("data")
    # MXTPU_SHARD_UPDATE=0 keeps everything on the param specs
    plan_off = sh.ShardingPlan(mc, shapes, shard_update=False)
    assert plan_off.opt_spec("fc1_weight") == P()
    assert plan_off.sharded_opt_names() == []


def test_mesh_resolution_and_env(monkeypatch):
    assert sh.resolve(None) is None                 # nothing decided
    monkeypatch.setenv("MXTPU_MESH", "8")
    assert sh.resolve(None).axis_sizes == {"data": 8}
    assert sh.current().axis_sizes == {"data": 8}   # env fallback
    assert sh.resolve(False) is sh.DISABLED         # explicit off wins
    with sh.use(sh.DISABLED):
        assert sh.current() is None                 # env suppressed
    monkeypatch.setenv("MXTPU_MESH", "none")
    assert sh.resolve(None) is None
    mc = sh.MeshContext.create(4)
    with sh.use(mc):
        assert sh.active() is mc and sh.current() is mc
    assert sh.active() is None


# ------------------------------------------------------------------ training
def _mnist_like(n=256, seed=7):
    rng = np.random.RandomState(seed)
    return (rng.rand(n, 784).astype("float32"),
            rng.randint(0, 10, n).astype("float32"))


def _fit_mlp(mesh, num_epoch=2, seed=11, batch_size=64):
    X, y = _mnist_like()
    it = mx.io.NDArrayIter(X, y, batch_size=batch_size,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    metric = M.create(["acc", "ce"])
    mx.random.seed(seed)
    np.random.seed(seed)
    mod.fit(it, num_epoch=num_epoch, eval_metric=metric, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), mesh=mesh)
    weights = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    return dict(metric.get_name_value()), weights, mod


def test_fit_mesh_parity_mlp():
    """THE acceptance gate: 8-way SPMD fit == single-device fused fit.

    Integer-summed metrics exact; cross-entropy within 1e-5 (f32
    reduction order is the only difference); optimizer state lives
    sharded; per-chip optimizer bytes <= 1/8 of the total + the
    replicated small-state overhead, as reported by the ledger."""
    m_one, w_one, _ = _fit_mlp(mesh=False)
    m_mesh, w_mesh, mod = _fit_mlp(mesh=8)

    assert mod._fused is not None and mod._fused._plan is not None, \
        "fit(mesh=8) did not arm the sharded fused step"
    assert m_one["accuracy"] == m_mesh["accuracy"], (m_one, m_mesh)
    np.testing.assert_allclose(m_one["cross-entropy"],
                               m_mesh["cross-entropy"], rtol=1e-5)
    for k in w_one:
        np.testing.assert_allclose(
            w_one[k], w_mesh[k], rtol=1e-4, atol=1e-5,
            err_msg="weights diverged at %s" % k)

    fused = mod._fused
    # optimizer state genuinely sharded over the data axis
    st = fused.opt_state["fc1_weight"]
    leaf = jax.tree.leaves(st)[0]
    assert leaf.sharding.spec == P("data"), leaf.sharding.spec
    assert len(leaf.sharding.device_set) == 8

    # per-chip optimizer memory: shard + replicated overhead
    opt_total = sum(x.nbytes for x in jax.tree.leaves(fused.opt_state))
    repl_overhead = sum(
        x.nbytes for n in fused.trainable
        for x in jax.tree.leaves(fused.opt_state[n])
        if n not in fused._plan.sharded_opt_names())
    per_dev = {}
    for x in jax.tree.leaves(fused.opt_state):
        for s in x.addressable_shards:
            key = "cpu(%d)" % s.device.id
            per_dev[key] = per_dev.get(key, 0) + s.data.nbytes
    assert len(per_dev) == 8
    for ctx, nbytes in per_dev.items():
        assert nbytes <= opt_total // 8 + repl_overhead, \
            (ctx, nbytes, opt_total, repl_overhead)

    # the ledger agrees: fused_step bytes exist on every device and the
    # totals match params(replicated everywhere) + aux + opt shard
    led = mx.diagnostics.ledger()
    view = led.shard_bytes(origin="fused_step")
    params_bytes = sum(v.nbytes for v in fused.params.values())
    aux_bytes = sum(v.nbytes for v in fused.aux.values())
    for ctx, nbytes in per_dev.items():
        assert view.get(ctx, 0) >= params_bytes + aux_bytes + nbytes, \
            (ctx, view.get(ctx), params_bytes, nbytes)

    # program table saw the SPMD program: 8 devices, sharded args
    rec = mx.diagnostics.latest_record("fused_step")
    if rec is not None and mx.diagnostics.cost_enabled():
        assert rec.n_devices == 8
        assert rec.sharded_args > 0

    # and the module audits clean (donation + sharding_consistency)
    with sh.use(fused._plan.mesh_ctx):
        report = mod.check()
    assert report.ok, report.to_dict()


def test_shard_bytes_ledger_view():
    """Satellite: the ledger's shard_bytes view proves the memory shape
    of weight-update sharding — replicated params cost their FULL size
    on every one of the 8 devices, sharded optimizer state only 1/8
    (plus replicated small states)."""
    _, _, mod = _fit_mlp(mesh=8, num_epoch=1)
    fused = mod._fused
    led = mx.diagnostics.ledger()
    view = led.shard_bytes(origin="fused_step")
    assert len([c for c in view if view[c]]) == 8, view

    params_bytes = sum(v.nbytes for v in fused.params.values())
    aux_bytes = sum(v.nbytes for v in fused.aux.values())
    sharded = set(fused._plan.sharded_opt_names())
    opt_sharded = sum(x.nbytes for n in sharded
                      for x in jax.tree.leaves(fused.opt_state[n]))
    opt_repl = sum(x.nbytes for n in fused.trainable if n not in sharded
                   for x in jax.tree.leaves(fused.opt_state[n]))
    expect = params_bytes + aux_bytes + opt_repl + opt_sharded // 8
    for ctx, nbytes in view.items():
        assert nbytes == expect, (ctx, nbytes, expect)
    # sanity: the same state UNSHARDED would cost the full opt total per
    # chip — the win is real and ~linear in the replica count
    assert expect < params_bytes + aux_bytes + opt_repl + opt_sharded


def test_consistency_pass_catches_plan_bugs():
    """Satellite: sharding_consistency fails plan bugs at Module.check()
    instead of inside jit."""
    from mxtpu import analysis as an
    _, _, mod = _fit_mlp(mesh=8, num_epoch=1)
    fused = mod._fused
    plan = fused._plan
    with sh.use(plan.mesh_ctx):
        assert mod.check().ok

        # (a) axis-name typo in an override -> ERROR
        typo = sh.ShardingPlan(
            plan.mesh_ctx, plan.param_shapes,
            data_names=plan.data_names, label_names=plan.label_names,
            overrides={"fc1_weight": P("dtaa", None)})
        fused._plan = typo
        rep = mod.check(passes=["sharding_consistency"])
        assert not rep.ok
        assert any(f.severity == an.ERROR and "dtaa" in f.message
                   for f in rep.findings), rep.to_dict()

        # (b) spec rank > param rank -> ERROR
        fused._plan = sh.ShardingPlan(
            plan.mesh_ctx, plan.param_shapes,
            overrides={"fc1_bias": P(None, None, "data")})
        rep = mod.check(passes=["sharding_consistency"])
        assert any("rank" in f.message for f in rep.errors), rep.to_dict()

        # (c) unsharded-param-on-mesh: state re-staged replicated behind
        # the plan's back -> ERROR
        fused._plan = plan
        good = fused.opt_state["fc1_weight"]
        fused.opt_state["fc1_weight"] = jax.tree.map(
            lambda t: fused._put(np.asarray(t), P()), good)
        rep = mod.check(passes=["sharding_consistency"])
        assert any("behind the plan" in f.message for f in rep.errors), \
            rep.to_dict()
        fused.opt_state["fc1_weight"] = good

    # (d) mesh active but plan declined (indivisible batch) -> WARNING
    mc = sh.MeshContext.create(8)
    with sh.use(mc):
        m, _, mod2 = _fit_mlp(mesh=None, num_epoch=1, batch_size=60)
        assert mod2._fused is not None and mod2._fused._plan is None
        rep = mod2.check(passes=["sharding_consistency"])
        assert any("WITHOUT a sharding plan" in f.message
                   for f in rep.findings), rep.to_dict()
        assert not rep.ok


def test_mxtpu_mesh_env_arms_the_plan(monkeypatch):
    monkeypatch.setenv("MXTPU_MESH", "8")
    _, _, mod = _fit_mlp(mesh=None, num_epoch=1)
    assert mod._fused is not None and mod._fused._plan is not None
    assert len(mod._fused.devices) == 8
    # explicit mesh=False beats the env
    _, _, mod2 = _fit_mlp(mesh=False, num_epoch=1)
    assert mod2._fused is not None and mod2._fused._plan is None


def test_kvstore_mesh_veneer_matches_host_loop():
    """KVStore 'device' push/pull as a veneer over mesh collectives: the
    aggregate bit-matches the legacy host merge, pull hands each device
    its own shard zero-copy, and the collective counter moves."""
    from mxtpu import telemetry as tel
    rng = np.random.RandomState(3)
    host_vals = [rng.randn(16, 5).astype("f4") for _ in range(8)]
    expect = np.sum(host_vals, axis=0)

    def push_pull(kv):
        vals = [mx.nd.array(v, ctx=mx.Context("cpu", i))
                for i, v in enumerate(host_vals)]
        kv.init("w", mx.nd.zeros((16, 5)))
        kv.push("w", vals)
        outs = [mx.nd.zeros((16, 5), ctx=mx.Context("cpu", i))
                for i in range(8)]
        kv.pull("w", out=outs)
        return outs

    legacy = push_pull(mx.kv.create("device"))
    before = tel.counter("kvstore_mesh_allreduce").value
    with sh.use(sh.MeshContext.create("all")):
        mesh_outs = push_pull(mx.kv.create("device"))
    assert tel.counter("kvstore_mesh_allreduce").value == before + 1
    for i, (a, b) in enumerate(zip(legacy, mesh_outs)):
        np.testing.assert_allclose(a.asnumpy(), expect, rtol=1e-6)
        np.testing.assert_allclose(b.asnumpy(), a.asnumpy(), rtol=1e-6,
                                   err_msg="device %d" % i)
        devs = b._data.devices()
        assert len(devs) == 1 and next(iter(devs)).id == i


def test_kvstore_veneer_declines_multi_axis_mesh():
    """The row-shard all-reduce trick is only shape-correct on a 1-D
    data mesh: under a data×tp mesh the veneer must FALL BACK to the
    host merge loop (correct values, no collective) instead of handing
    jax mis-shaped shards."""
    from mxtpu import telemetry as tel
    host_vals = [np.full((8, 3), i + 1.0, "f4") for i in range(8)]
    before = tel.counter("kvstore_mesh_allreduce").value
    with sh.use(sh.MeshContext.create("data:4,tp:2")):
        kv = mx.kv.create("device")
        vals = [mx.nd.array(v, ctx=mx.Context("cpu", i))
                for i, v in enumerate(host_vals)]
        kv.init("w", mx.nd.zeros((8, 3)))
        kv.push("w", vals)
        out = mx.nd.zeros((8, 3))
        kv.pull("w", out=out)
    assert tel.counter("kvstore_mesh_allreduce").value == before
    np.testing.assert_allclose(out.asnumpy(), np.sum(host_vals, axis=0),
                               rtol=1e-6)


def test_env_mesh_context_is_cached():
    """current()/from_env() must return a STABLE MeshContext per env
    value — downstream jit caches key on the mesh object."""
    os.environ["MXTPU_MESH"] = "8"
    try:
        assert sh.from_env() is sh.from_env()
        assert sh.current().mesh is sh.current().mesh
    finally:
        del os.environ["MXTPU_MESH"]


def test_placement_overlap_needs_group2ctx():
    """ctx-group TAGS alone place nothing; the two-placement-systems
    warning fires only when a group2ctx map is actually provided."""
    from mxtpu.analysis.passes import PassContext, ShardingConsistencyPass
    with mx.AttrScope(ctx_group="g1"):
        a = sym.Variable("a")
        net = sym.FullyConnected(a, num_hidden=4, name="fca")
    with mx.AttrScope(ctx_group="g2"):
        net = sym.FullyConnected(net, num_hidden=2, name="fcb")
    p = ShardingConsistencyPass()
    assert p._placement_overlap(PassContext(net), None) == []
    fired = p._placement_overlap(
        PassContext(net, group2ctx={"g1": mx.cpu(0)}), None)
    assert fired and "two" in fired[0].message


def test_active_mesh_is_per_thread():
    """Concurrent fits must not see each other's mesh: the active slot
    is a contextvar, so a sibling thread reads None while this thread's
    scope is active."""
    import threading
    seen = {}
    with sh.use(sh.MeshContext.create(8)):
        t = threading.Thread(
            target=lambda: seen.setdefault("peer", sh.active()))
        t.start(); t.join()
        assert sh.active() is not None
    assert seen["peer"] is None


def test_resolve_disable_vocabulary_matches_env():
    """Every string from_env() treats as 'off' must also disable as a
    fit(mesh=...) argument instead of raising."""
    for tok in ("0", "none", "off", "false"):
        assert sh.resolve(tok) is sh.DISABLED, tok
    assert sh.resolve(False) is sh.DISABLED


def test_heuristic_rank_prune_is_not_an_error():
    """A 1-D param whose NAME matches a matrix heuristic (spec rank >
    param rank, no override) is the normal prune path — info-free, and
    Module.check must not error on it; the same mismatch in an explicit
    override stays an error (test_consistency_pass_catches_plan_bugs)."""
    plan = sh.ShardingPlan(sh.MeshContext.create(8),
                           {"scale_weight": (7,)})
    assert plan.param_spec("scale_weight") == P()
    kinds = {i["kind"] for i in plan.validate()}
    assert "rank_mismatch" not in kinds
    assert "rank_pruned" in kinds


def test_parallel_current_mesh_one_truth(monkeypatch):
    """parallel/ consumers and the sharding layer resolve the SAME
    ambient mesh, most-explicit first: active scope > make_mesh'd
    module mesh > MXTPU_MESH > lazy default."""
    import mxtpu.parallel.mesh as pmesh
    mc = sh.MeshContext.create("data:4,tp:2")
    with sh.use(mc):
        assert pmesh.current_mesh() is mc.mesh   # active scope wins
    # an explicit make_mesh (e.g. a (dp, sp) mesh for ring_attention)
    # must NOT be shadowed by the env's 1-D mesh
    monkeypatch.setenv("MXTPU_MESH", "4")
    made = pmesh.make_mesh(shape=(4, 2), axis_names=("data", "seq"))
    assert pmesh.current_mesh() is made
    # with no explicit mesh anywhere, the env decides
    monkeypatch.setattr(pmesh, "_current", None)
    assert pmesh.current_mesh() is sh.from_env().mesh
    monkeypatch.delenv("MXTPU_MESH")
    monkeypatch.setattr(pmesh, "_current", None)
    assert pmesh.current_mesh() is not None      # lazy default intact


def test_opt_state_checkpoint_roundtrip_stays_sharded(tmp_path):
    """Optimizer-state save/restore under a mesh: the file is now a
    sharded MANIFEST (specs + per-shard pieces, written without
    gathering) instead of a pickle that serialized the per-process shard
    view as if global, and restore re-stages on the plan's weight-update
    sharding specs — a replicated restore would void the per-chip memory
    split and trip the consistency pass."""
    import json
    _, _, mod = _fit_mlp(mesh=8, num_epoch=1)
    fused = mod._fused
    path = str(tmp_path / "opt.states")
    mod.save_optimizer_states(path)
    # the sharded manifest format, not a pickle
    with open(path) as f:
        man = json.load(f)
    assert man["format"] == "mxtpu-opt-states-sharded-1"
    entry = man["entries"]["fc1_weight"]
    assert entry["spec"] == ["data"]
    assert len(entry["shards"]["0"]["pieces"]) == 8
    assert (tmp_path / "opt.states.data").exists()
    before = {n: [np.asarray(x) for x in
                  jax.tree.leaves(fused.opt_state[n])]
              for n in fused.trainable}
    mod.load_optimizer_states(path)
    # values survive exactly AND the PR-6 1/8 split survives
    for n, leaves in before.items():
        for want, got in zip(leaves, jax.tree.leaves(fused.opt_state[n])):
            np.testing.assert_array_equal(want, np.asarray(got), err_msg=n)
    leaf = jax.tree.leaves(fused.opt_state["fc1_weight"])[0]
    assert leaf.sharding.spec == P("data"), leaf.sharding.spec
    assert len(leaf.sharding.device_set) == 8
    shard_bytes = {s.device.id: s.data.nbytes
                   for s in leaf.addressable_shards}
    assert len(shard_bytes) == 8
    for nbytes in shard_bytes.values():
        assert nbytes == leaf.nbytes // 8
    with sh.use(fused._plan.mesh_ctx):
        assert mod.check().ok


def test_kvstore_updater_path_survives_mesh():
    """update_on_kvstore semantics under the veneer: the updater sees a
    single-device view of the mesh aggregate and the stored weight stays
    correct."""
    opt = mx.optimizer.SGD(learning_rate=0.5, rescale_grad=1.0)
    with sh.use(sh.MeshContext.create("all")):
        kv = mx.kv.create("device")
        kv.set_optimizer(opt)
        kv.init("3", mx.nd.ones((4, 4)))
        grads = [mx.nd.array(np.full((4, 4), 1.0, "f4"),
                             ctx=mx.Context("cpu", i)) for i in range(8)]
        kv.push("3", grads)
        out = mx.nd.zeros((4, 4))
        kv.pull("3", out=out)
    # w = 1 - 0.5 * sum(grads) = 1 - 0.5 * 8
    np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.5 * 8.0, rtol=1e-6)
