"""Translation validation (ISSUE 20): the equivalence canonicalizer,
per-pass certification over the model fixtures, the pipeline cert gate
(arm/disarm, counters, refusal-with-fallback), the ProgramRecord cert
column, the seeded transform fuzzer, and the docs-rot guard.

Acceptance gates:
* every catalog pass and the full canonical composition certify on
  mlp / lenet / resnet-20 / lstm decode step / attn prefill graphs
  (incl. the bf16 and quant inference kinds);
* a deliberately-miscompiling pass (the PR-14
  ``save_any_names_but_these`` near-miss shape) is REFUSED by
  certification — not by the error budget — the rest of the catalog
  still applies, and the fit falls back to the no-pipeline numbers;
* a bounded fuzz round (>= 64 seeded graphs x sampled configs)
  certifies and differential-tests deterministically: the same master
  seed reproduces the identical verdict sequence.
"""
import logging
import os

import numpy as np
import pytest

import mxtpu as mx
import mxtpu.symbol as S
from mxtpu import diagnostics as diag
from mxtpu import telemetry as tel
from mxtpu.analysis import equiv, graphgen, rewrite
from mxtpu.compile import pipeline
from mxtpu.models import lenet, mlp, resnet
from mxtpu.serving.decode.model import (attn_prefill_symbol,
                                        lm_step_symbol)


# ------------------------------------------------------------- fixtures
def _mlp_fix(batch=64):
    return mlp.get_symbol(10), {"data": (batch, 784),
                                "softmax_label": (batch,)}


def _lenet_fix(batch=64):
    return lenet.get_symbol(10), {"data": (batch, 1, 28, 28),
                                  "softmax_label": (batch,)}


def _resnet20_fix(batch=4):
    sym = resnet.get_symbol(num_classes=10, num_layers=20,
                            image_shape=(3, 28, 28))
    return sym, {"data": (batch, 3, 28, 28), "softmax_label": (batch,)}


def _decode_step_fix(batch=4):
    group, state_names, specs = lm_step_symbol(16, 8, 16, num_layers=2)
    shapes = {"data": (batch, 1)}
    for name, spec in zip(state_names, specs):
        shapes[name] = (batch,) + tuple(spec["shape"][1:])
    return group, shapes


def _prefill_fix():
    C, max_blocks, block, H, D = 4, 2, 4, 2, 4
    T = max_blocks * block
    sym = attn_prefill_symbol(16, 8, H, D, max_blocks, block,
                              num_layers=1)
    shapes = {"data": (C, 1), "attn_mask_cache": (C, T),
              "attn_mask_chunk": (C, C), "kv_valid_cache": (1, T),
              "chunk_valid": (C, 1),
              "kv_k_0": (1, max_blocks, block, H, D),
              "kv_v_0": (1, max_blocks, block, H, D)}
    return sym, shapes


FIXTURES = {
    "mlp": _mlp_fix,
    "lenet": _lenet_fix,
    "resnet20": _resnet20_fix,
    "decode_step": _decode_step_fix,
    "prefill": _prefill_fix,
}


def _seeded_values(sym, shapes, seed=3):
    """f32 arrays for every argument (quant reads scales off them)."""
    arg_shapes, _, _ = sym.infer_shape(**shapes)
    rng = np.random.RandomState(seed)
    out = {}
    for name, shp in zip(sym.list_arguments(), arg_shapes):
        out[name] = (rng.rand(*shp).astype(np.float32) - 0.5)
    return out


def _fit(symbol, names, n=256, batch=64, epochs=2, seed=7):
    rng = np.random.RandomState(0)
    X = rng.rand(n, 784).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, n).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch,
                           label_name="softmax_label")
    mod = mx.mod.Module(symbol, context=mx.cpu(),
                        logger=logging.getLogger("quiet"))
    mod.logger.setLevel(logging.ERROR)
    metric = mx.metric.create(["acc", "ce"])
    with pipeline.pipeline_scope(names):
        mx.random.seed(seed)
        np.random.seed(seed)
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                eval_metric=metric)
    args, _ = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in args.items()}, \
        dict(zip(*metric.get()))


# ------------------------------------------------------- canonical keys
def test_entry_key_name_independent():
    def build(prefix):
        x = S.Variable("%s_in" % prefix)
        x = S.FullyConnected(x, num_hidden=8, name="%s_fc" % prefix)
        x = S.Activation(x, act_type="relu", name="%s_act" % prefix)
        return S.SoftmaxOutput(x, name="%s_sm" % prefix)
    assert equiv.entry_key(build("a")) == equiv.entry_key(build("b"))
    assert equiv.canonical_digest(build("a")) == \
        equiv.canonical_digest(build("b"))


def test_entry_key_separates_structure():
    x = S.Variable("data")
    relu = S.SoftmaxOutput(S.Activation(x, act_type="relu", name="a"),
                           name="sm")
    tanh = S.SoftmaxOutput(S.Activation(x, act_type="tanh", name="a"),
                           name="sm")
    assert equiv.entry_key(relu) != equiv.entry_key(tanh)


def test_entry_key_commutative_input_order():
    x = S.Variable("data")
    r = S.Activation(x, act_type="relu", name="r")
    t = S.Activation(x, act_type="tanh", name="t")
    # elemwise_add is commutative: operand order canonicalizes away
    assert equiv.entry_key(S.elemwise_add(r, t, name="s")) == \
        equiv.entry_key(S.elemwise_add(t, r, name="s"))
    # Concat is NOT: operand order is semantic and must survive
    assert equiv.entry_key(S.Concat(r, t, dim=1, name="c")) != \
        equiv.entry_key(S.Concat(t, r, dim=1, name="c"))


def test_entry_key_strips_annotation_attrs():
    sym, _ = _mlp_fix()
    extra = {id(n): {"__remat__": "1", "__update_class__": "c0"}
             for n in sym._topo() if not n.is_variable}
    ann = rewrite._annotate_clone(sym, node_extra=extra)
    assert equiv.entry_key(ann) == equiv.entry_key(sym)


def test_entry_key_detects_rewire():
    def build(skip_relu):
        x = S.Variable("data")
        fc1 = S.FullyConnected(x, num_hidden=8, name="fc1")
        h = fc1 if skip_relu else S.Activation(fc1, act_type="relu",
                                               name="r1")
        fc2 = S.FullyConnected(h, num_hidden=8, name="fc2")
        return S.SoftmaxOutput(fc2, name="sm")
    assert equiv.entry_key(build(False)) != equiv.entry_key(build(True))


# ---------------------------------------- catalog certification (all kinds)
_CONFIG_IDS = {
    ("layout",): "layout",
    ("bf16",): "bf16",
    ("fuse_opt",): "fuse_opt",
    ("remat_reuse",): "remat_reuse",
    ("layout", "bf16", "fuse_opt", "remat_reuse"): "composed",
}


@pytest.mark.parametrize("fixture", sorted(FIXTURES))
@pytest.mark.parametrize("passes", list(_CONFIG_IDS),
                         ids=list(_CONFIG_IDS.values()))
def test_catalog_certifies_on_training_kind(fixture, passes):
    """Every catalog pass (and the full canonical composition) either
    declines or applies WITH a passing certificate — on every model
    fixture, decode step and prefill graphs included."""
    sym, shapes = FIXTURES[fixture]()
    _, rep = pipeline.transform_graph(sym, kind="fused_step",
                                      shapes=shapes, passes=list(passes))
    for e in rep.entries:
        assert not e["cert_refused"], (fixture, e["name"],
                                       e["cert"] and e["cert"].reason)
        assert e["error"] is None, (fixture, e["name"], e["error"])
        if e["applied"]:
            assert e["cert"] is not None and e["cert"].ok, \
                (fixture, e["name"], e["cert"])
            assert e["cert"].digest, (fixture, e["name"])
    if rep.applied:
        assert rep.cert == "ok"


@pytest.mark.parametrize("fixture", ["mlp", "decode_step"])
def test_quant_certifies_on_inference_kinds(fixture):
    """The quant rewrite — weight streams + composed bf16 — certifies
    under ``qdq_streams`` on its inference build kinds."""
    sym, shapes = FIXTURES[fixture]()
    kind = "executor_infer" if fixture == "mlp" else "decode"
    values = _seeded_values(sym, shapes)
    _, rep = pipeline.transform_graph(
        sym, kind=kind, shapes=shapes, passes=["bf16", "quant"],
        values=values)
    assert "quant" in rep.applied, [
        (e["name"], e["actions"], e["error"]) for e in rep.entries]
    for e in rep.entries:
        assert not e["cert_refused"], (e["name"],
                                       e["cert"] and e["cert"].reason)
    assert rep.cert == "ok"
    certs = rep.certificates()
    assert certs["quant"].algebra == "qdq_streams"
    assert certs["quant"].counts.get("weight_streams", 0) >= 1


def test_certify_refuses_undeclared_algebra():
    class _NoAlgebra(rewrite.TransformPass):
        name = "_test_noalg"
    sym, _ = _mlp_fix()
    cert = equiv.certify(_NoAlgebra(), sym, sym)
    assert not cert.ok and "no rewrite algebra" in cert.reason
    f = cert.to_finding()
    assert f.pass_name == "certificate" and f.severity == "error"
    cert2 = equiv.certify(
        type("_T", (rewrite.TransformPass,),
             {"name": "_test_badalg", "algebra": "no_such"})(),
        sym, sym)
    assert not cert2.ok and "unknown rewrite algebra" in cert2.reason


# ------------------------------------------------------- the gate itself
def test_set_certification_disarm_tags_off():
    prev = pipeline.set_certification(False)
    try:
        assert not pipeline.certification_enabled()
        sym, shapes = _mlp_fix()
        _, rep = pipeline.transform_graph(sym, kind="fused_step",
                                          shapes=shapes, passes=["bf16"])
        assert "bf16" in rep.applied
        assert all(e["cert"] is None for e in rep.entries)
        assert rep.cert == "off"
        assert rep.certificates() == {}
    finally:
        pipeline.set_certification(prev)
    assert pipeline.certification_enabled() == prev


def test_certified_counter_increments():
    before = tel.registry().counter("transform_certified",
                                    labels={"pass": "bf16"}).value
    sym, shapes = _mlp_fix()
    pipeline.transform_graph(sym, kind="fused_step", shapes=shapes,
                             passes=["bf16"])
    after = tel.registry().counter("transform_certified",
                                   labels={"pass": "bf16"}).value
    assert after == before + 1


# --------------------------------------------- the miscompile near-miss
class _SaveAnyNamesButThesePass(rewrite.TransformPass):
    """The PR-14 near-miss reborn as a fixture: verifier-CLEAN but
    semantics-changing — rebuilds the mlp graph with ``relu1`` spliced
    out of ``fc2``'s input edge (shapes all still check, so the error
    budget cannot see it; only certification can)."""

    name = "_test_miscompile"
    algebra = "annotation_only"

    def run(self, tctx):
        d = S.Flatten(S.Variable("data"))
        fc1 = S.FullyConnected(d, num_hidden=128, name="fc1")
        S.Activation(fc1, act_type="relu", name="relu1")  # spliced out
        fc2 = S.FullyConnected(fc1, num_hidden=64, name="fc2")
        act2 = S.Activation(fc2, act_type="relu", name="relu2")
        fc3 = S.FullyConnected(act2, num_hidden=10, name="fc3")
        self.action(tctx, "spliced relu1 out of fc2's input edge")
        return S.SoftmaxOutput(fc3, name="softmax")


def test_miscompile_refused_by_certification_not_error_budget():
    rewrite._TRANSFORMS.setdefault("_test_miscompile",
                                   _SaveAnyNamesButThesePass())
    try:
        before = tel.registry().counter(
            "transform_cert_refused",
            labels={"pass": "_test_miscompile"}).value
        sym, shapes = _mlp_fix()
        sym2, rep = pipeline.transform_graph(
            sym, kind="fused_step", shapes=shapes,
            passes=["_test_miscompile", "bf16", "fuse_opt",
                    "remat_reuse"])
        entry = next(e for e in rep.entries
                     if e["name"] == "_test_miscompile")
        # refused by the CERT gate, not the verifier error budget
        assert entry["cert_refused"] and entry["rejected"]
        assert not entry["applied"]
        assert entry["offending"], entry
        f = entry["offending"][0]
        assert f.pass_name == "certificate", f.pass_name
        assert "REFUSED" in f.message and "annotation_only" in f.message
        assert entry["cert"] is not None and not entry["cert"].ok
        # the rest of the catalog still applies, certified
        assert "bf16" in rep.applied
        for e in rep.entries:
            if e["applied"]:
                assert e["cert"].ok, e["name"]
        assert rep.cert == "ok"
        after = tel.registry().counter(
            "transform_cert_refused",
            labels={"pass": "_test_miscompile"}).value
        assert after == before + 1
        # the refusal surfaces in the report's findings stream
        msgs = [g.message for g in rep.findings()]
        assert any("REFUSED by certification" in m for m in msgs), msgs
    finally:
        rewrite._TRANSFORMS.pop("_test_miscompile", None)


def test_miscompile_fallback_trains_to_no_pipeline_parity():
    """The refused pass falls back exactly like the error-budget path:
    with ONLY the miscompiling pass configured, nothing rewrites and
    the fit reproduces the no-pipeline numbers."""
    rewrite._TRANSFORMS.setdefault("_test_miscompile",
                                   _SaveAnyNamesButThesePass())
    try:
        _, w0, v0 = _fit(mlp.get_symbol(10), [])
        mod, w1, v1 = _fit(mlp.get_symbol(10), ["_test_miscompile"])
        rep = mod._fused.pipeline_report
        entry = rep.entries[0]
        assert entry["cert_refused"] and entry["rejected"]
        assert rep.applied == [] and not rep.symbol_changed
        assert abs(v0["accuracy"] - v1["accuracy"]) <= 1e-12, (v0, v1)
        assert abs(v0["cross-entropy"] - v1["cross-entropy"]) < 1e-9
        for k in w0:
            np.testing.assert_allclose(w0[k], w1[k], rtol=0, atol=1e-6)
    finally:
        rewrite._TRANSFORMS.pop("_test_miscompile", None)


# --------------------------------------------------- ProgramRecord cert
def test_program_record_carries_cert_tag():
    mod, _, _ = _fit(mlp.get_symbol(10), ["bf16", "remat_reuse"],
                     epochs=1)
    recs = diag.programs("fused_step")
    assert recs and recs[-1]["cert"] == "ok"
    assert "bf16" in recs[-1]["transforms"]
    table = diag.program_table("fused_step")
    assert "cert" in table.splitlines()[0]
    assert rewrite is not None and mod is not None


# --------------------------------------------------------- the fuzzer
def test_fuzz_round_certifies_64_graphs():
    before = tel.registry().counter("fuzz_graphs_run").value
    res = graphgen.fuzz_round(20260808, n_graphs=64)
    assert res["n_graphs"] == 64 and len(res["verdicts"]) == 64
    assert res["refutations"] == [], res["refutations"]
    # the round exercises real rewrites, not 64 no-ops
    applied = [v for v in res["verdicts"] if "applied=-" not in v]
    assert len(applied) >= 20, len(applied)
    # ... and real numeric differentials on semantics-preserving configs
    diffed = [v for v in res["verdicts"]
              if "diff=exact" in v or "diff=max" in v]
    assert diffed, res["verdicts"][:8]
    after = tel.registry().counter("fuzz_graphs_run").value
    assert after == before + 64


def test_fuzz_round_is_deterministic():
    """PR-13 convention: same master seed => identical verdict
    sequence (graphs, sampled configs, certificates)."""
    r1 = graphgen.fuzz_round(7, n_graphs=16, numeric=False)
    r2 = graphgen.fuzz_round(7, n_graphs=16, numeric=False)
    assert r1["verdicts"] == r2["verdicts"]
    assert r1["refutations"] == [] == r2["refutations"]
    # a different master seed walks a different graph sequence
    r3 = graphgen.fuzz_round(8, n_graphs=16, numeric=False)
    assert r3["verdicts"] != r1["verdicts"]


def test_sub_seed_stable():
    assert graphgen.sub_seed(7, 0, "graph") == \
        graphgen.sub_seed(7, 0, "graph")
    assert graphgen.sub_seed(7, 0, "graph") != \
        graphgen.sub_seed(7, 1, "graph")
    assert graphgen.sub_seed(7, 0, "graph") != \
        graphgen.sub_seed(7, 0, "cfg")


# ------------------------------------------------------ docs-rot guard
def test_docs_catalog_matches_live_registry():
    """docs/compile.md's catalog table must track the registry: one row
    per registered pass carrying its declared algebra, license analysis
    and every knob — and the canonical-order prose must match
    ``rewrite.CANONICAL_ORDER`` exactly."""
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "docs", "compile.md")
    with open(path) as fh:
        doc = fh.read()
    lines = doc.splitlines()
    for name, _doc in rewrite.list_transforms():
        if name.startswith("_"):
            continue
        tp = rewrite.get_transform(name)
        rows = [l for l in lines if l.startswith("| `%s` |" % name)]
        assert len(rows) == 1, \
            "docs/compile.md catalog table needs exactly one row " \
            "for %r (found %d)" % (name, len(rows))
        row = rows[0]
        assert "`%s`" % tp.algebra in row, (name, tp.algebra)
        assert "`%s`" % tp.license in row, (name, tp.license)
        for knob in tp.knobs:
            assert "`%s`" % knob in row, (name, knob)
        assert name in rewrite.CANONICAL_ORDER, name
    order = "`%s`" % ", ".join(rewrite.CANONICAL_ORDER)
    assert order in doc, \
        "docs/compile.md canonical-order prose does not match " \
        "rewrite.CANONICAL_ORDER (%s)" % order
    assert "MXTPU_PIPELINE_CERT" in doc
