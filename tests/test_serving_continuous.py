"""Continuous batching, admission control, and hot-swap (PR 10).

Tier-1 (CPU, `not slow`). Contracts under test:

* the refill watermark releases a partial batch to a hungry device slot
  WITHOUT waiting for the deadline, and never lingers;
* byte-identity survives the K-in-flight pipeline (async dispatch +
  deferred retire must not perturb rows);
* the overload taxonomy is distinguishable over HTTP: 429 = admission
  shed, 504 = queue deadline, 503 = drain window only;
* a version hot-swap under load fails ZERO requests, and rolling back
  to a warm-cached version costs zero compiles.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.models.serving_fixtures import get_fixture
from mxtpu.predict import Predictor
from mxtpu.serving import (ACCEPTING, DEGRADED, SHEDDING, AdmissionShed,
                           AdmissionSignals, ContinuousBatcher,
                           ServingHTTPServer, ServingSession,
                           SignalAdmissionPolicy, derive_knobs, pad_rows,
                           prewarm)


def _rand(shape, seed):
    return np.random.RandomState(seed).rand(*shape).astype(np.float32)


# ------------------------------------------------------------------ batcher
def test_continuous_batcher_watermark_refill():
    """A hungry slot takes a partial batch the moment pending rows reach
    the refill watermark — no deadline wait, reason recorded."""
    b = ContinuousBatcher(["data"], buckets=(4, 8), max_delay_ms=10_000,
                          refill_watermark=2)
    assert b.refill_watermark == 2
    b.submit({"data": _rand((1, 3), 0)})
    b.submit({"data": _rand((1, 3), 1)})
    t0 = time.monotonic()
    batch = b.next_fill(timeout=5, hungry=True)
    assert time.monotonic() - t0 < 5  # did NOT wait the 10s deadline
    assert batch is not None and batch.n_valid == 2
    assert b.last_flush_reason == "watermark"
    # below the watermark + non-blocking poll: nothing comes back
    b.submit({"data": _rand((1, 3), 2)})
    assert b.next_fill(timeout=0, hungry=True) is None
    # a full largest bucket flushes with reason "full"
    for i in range(8):
        b.submit({"data": _rand((1, 3), 3 + i)})
    batch = b.next_fill(timeout=5, hungry=True)
    assert batch is not None and b.last_flush_reason == "full"


def test_continuous_batcher_not_hungry_behaves_like_burst():
    """With every slot occupied (hungry=False) the watermark is ignored:
    sub-bucket rows wait for the deadline exactly like the PR-1 batcher."""
    b = ContinuousBatcher(["data"], buckets=(8,), max_delay_ms=40,
                          refill_watermark=1)
    b.submit({"data": _rand((1, 3), 0)})
    assert b.next_fill(timeout=0, hungry=False) is None  # 1 row, not due
    t0 = time.monotonic()
    batch = b.next_fill(timeout=5, hungry=False)
    assert batch is not None and batch.n_valid == 1
    assert time.monotonic() - t0 >= 0.030  # held ~the deadline
    assert b.last_flush_reason == "deadline"


def test_continuous_batcher_default_watermark():
    b = ContinuousBatcher(["data"], buckets=(1, 8, 32, 128))
    assert b.refill_watermark == 32  # smallest bucket >= largest/4
    b2 = ContinuousBatcher(["data"], buckets=(4,))
    assert b2.refill_watermark == 1  # quarter of 4 -> smallest bucket


# ---------------------------------------------------------------- admission
def _signals(**kw):
    base = dict(queue_depth=0, queue_limit=256, pending_rows=0,
                inflight_depth=0, inflight_limit=2, replicas=1,
                est_batch_ms=2.0, est_queue_wait_ms=0.0,
                watchdog_age_s=0.0, mem_headroom_frac=None)
    base.update(kw)
    return AdmissionSignals(**base)


def test_admission_policy_signal_matrix():
    pol = SignalAdmissionPolicy(queue_wait_budget_ms=100.0,
                                watchdog_shed_s=10.0,
                                min_mem_headroom=0.05,
                                queue_frac_shed=0.9, degrade_frac=0.5)
    # healthy: admit, accepting
    d = pol.decide(_signals())
    assert d.admit and d.state == ACCEPTING
    # latency breach: shed with the reason naming the signal
    d = pol.decide(_signals(est_queue_wait_ms=150.0))
    assert not d.admit and d.state == SHEDDING and "latency" in d.reason
    # degrade band: admit but visible
    d = pol.decide(_signals(est_queue_wait_ms=60.0))
    assert d.admit and d.state == DEGRADED
    # watchdog stall dominates everything
    d = pol.decide(_signals(watchdog_age_s=11.0))
    assert not d.admit and "watchdog" in d.reason
    # memory headroom below floor sheds; missing budget never does
    d = pol.decide(_signals(mem_headroom_frac=0.01))
    assert not d.admit and "memory" in d.reason
    assert pol.decide(_signals(mem_headroom_frac=None)).admit
    # queue occupancy sheds a breath before QueueFull would
    d = pol.decide(_signals(queue_depth=240, queue_limit=256))
    assert not d.admit and "queue" in d.reason


def test_derive_knobs_from_cost_rows():
    # per-row cost: b=1 -> 1.0, b=8 -> 0.25, b=32 -> 0.125 (best),
    # 1.25x best = 0.15625 -> smallest qualifying bucket is 32
    costs = {1: {"exec_ms": 1.0}, 8: {"exec_ms": 2.0},
             32: {"exec_ms": 4.0}}
    k = derive_knobs(costs, (1, 8, 32))
    assert k["basis"] == "cost-registry"
    assert k["refill_watermark"] == 32
    assert k["est_batch_ms"] == 4.0
    # flat per-row cost (overhead-free model): dispatch at the smallest
    flat = {1: {"exec_ms": 1.0}, 8: {"exec_ms": 8.0}}
    assert derive_knobs(flat, (1, 8))["refill_watermark"] == 1
    # nothing measured -> structural default (None = batcher decides)
    assert derive_knobs({}, (1, 8))["basis"] == "default"
    assert derive_knobs({}, (1, 8))["refill_watermark"] is None


# ------------------------------------------------------------------ session
def test_continuous_session_byte_identical_inflight():
    """24 concurrent clients through the K=3-in-flight continuous
    pipeline: every response byte-identical to a direct Predictor at one
    of the bucket shapes (async dispatch + deferred retire must not
    perturb or cross rows)."""
    sj, params, shapes = get_fixture("mlp")
    buckets = (1, 8)
    refs = {b: Predictor(sj, dict(params), input_shapes={"data": (b, 784)})
            for b in buckets}

    def direct(x, b):
        refs[b].forward(data=pad_rows(x, b))
        return refs[b].get_output(0)[:1]

    with ServingSession(sj, params, shapes, buckets=buckets,
                        max_delay_ms=3, contexts=[mx.cpu(0)],
                        max_in_flight=3) as sess:
        results, errors = {}, []
        lock = threading.Lock()

        def client(i):
            x = _rand((1, 784), i)
            try:
                out = sess.predict({"data": x}, timeout=60)[0]
                with lock:
                    results[i] = (x, out)
            except Exception as exc:
                errors.append((i, exc))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert len(results) == 24
        for i, (x, out) in results.items():
            assert any(np.array_equal(out, direct(x, b)) for b in buckets), \
                "client %d response not byte-identical to any bucket" % i
        # a sequential tail: each dispatch after a retire re-occupies a
        # freed slot, which is what refill_latency_ms measures
        for i in range(3):
            sess.predict({"data": _rand((1, 784), 100 + i)}, timeout=30)
        stats = sess.stats()
        assert stats["requests_completed"] == 27
        # the continuous-path series exist and carry observations
        assert stats["batch_exec_ms"]["count"] >= 1
        assert stats["refill_latency_ms"]["count"] >= 1
        assert stats["admission_state"] == ACCEPTING
    # after drain every slot window is empty again
    assert sum(sess._inflight_n) == 0


def test_overload_taxonomy_http_429_504_503():
    """The three overload statuses are distinguishable: 429 = admission
    shed (policy, with "shed": true body), 504 = the request out-waited
    its own deadline in the queue, 503 = drain window only."""
    sj, params, shapes = get_fixture("mlp")
    sess = ServingSession(sj, params, shapes, buckets=(1, 4),
                          max_delay_ms=1, max_queue=64,
                          contexts=[mx.cpu(0)],
                          admission=SignalAdmissionPolicy(
                              queue_wait_budget_ms=1000.0))
    server = ServingHTTPServer(sess, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = server.endpoint

    def post(payload):
        req = urllib.request.Request(
            base + "/v1/predict", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=30)

    x = _rand((1, 784), 0).tolist()
    try:
        # healthy: 200
        with post({"inputs": {"data": x}}) as r:
            assert r.status == 200
        # wedge the (single) dispatcher inside dispatch, leave work in
        # the queue so pending_rows > 0, then tighten the latency budget
        gate = threading.Event()
        rep = sess.pool.replicas[0]
        orig = rep.dispatch
        rep.dispatch = lambda inputs: (gate.wait(15), orig(inputs))[1]
        stuck = sess.predict_async({"data": _rand((1, 784), 1)})
        deadline = time.time() + 5
        while sess.batcher.depth > 0 and time.time() < deadline:
            time.sleep(0.005)
        filler = sess.predict_async({"data": _rand((1, 784), 2)})
        sess._admission.queue_wait_budget_ms = 1e-6
        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"inputs": {"data": x}})
        assert ei.value.code == 429
        body = json.loads(ei.value.read())
        assert body.get("shed") is True and "latency" in body["error"]
        assert sess.stats()["shed_rate"] > 0
        # restore the budget: now the same overload yields a 504 once
        # the request's own deadline expires in the queue
        sess._admission.queue_wait_budget_ms = 1e9
        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"inputs": {"data": x}, "timeout_sec": 0.1})
        assert ei.value.code == 504
        gate.set()
        stuck.wait(30)
        filler.wait(30)
    finally:
        gate.set()
        sess.close()
    # drain window: the only time a healthy deploy serves 503
    with pytest.raises(urllib.error.HTTPError) as ei:
        post({"inputs": {"data": x}})
    assert ei.value.code == 503
    server.server_close()


def test_hot_swap_zero_failed_requests_under_load():
    """A version flip under concurrent load fails ZERO requests: every
    response is byte-identical to the old or the new weights, and after
    the flip quiesces new requests serve the new weights only."""
    sj, params_a, shapes = get_fixture("mlp")
    # same graph, perturbed weights — distinct version, same arg names
    params_b = {k: v + 0.25 for k, v in params_a.items()}
    buckets = (1, 8)
    refs = {}
    for tag, p in (("a", params_a), ("b", params_b)):
        for b in buckets:
            refs[(tag, b)] = Predictor(sj, dict(p),
                                       input_shapes={"data": (b, 784)})

    def direct(tag, x, b):
        refs[(tag, b)].forward(data=pad_rows(x, b))
        return refs[(tag, b)].get_output(0)[:1]

    sess = ServingSession(sj, params_a, shapes, buckets=buckets,
                          max_delay_ms=2, contexts=[mx.cpu(0)],
                          version_tag="swap-a")
    results, errors = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def client(i):
        n = 0
        while not stop.is_set() and n < 12:
            x = _rand((1, 784), 1000 * i + n)
            try:
                out = sess.predict({"data": x}, timeout=60)[0]
                with lock:
                    results.append((x, out))
            except Exception as exc:
                errors.append(exc)
            n += 1

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.15)  # mid-load...
        info = sess.swap_model(sj, params_b, version_tag="swap-b")
        assert info["generation"] == 1 and info["version"] == "swap-b"
        for t in threads:
            t.join()
        stop.set()
        assert not errors, errors[:3]  # ZERO failed requests across the flip
        for x, out in results:
            assert any(np.array_equal(out, direct(tag, x, b))
                       for tag in ("a", "b") for b in buckets), \
                "a response matched neither version's weights"
        # post-flip requests serve the NEW weights only
        x = _rand((1, 784), 424242)
        out = sess.predict({"data": x}, timeout=30)[0]
        assert any(np.array_equal(out, direct("b", x, b)) for b in buckets)
        assert not any(np.array_equal(out, direct("a", x, b))
                       for b in buckets)
        assert sess.stats()["model_swaps"] == 1
    finally:
        stop.set()
        sess.close()


def test_warm_cache_prewarm_and_rollback_zero_compiles():
    """Deploy-time prewarm from a bucket manifest makes session startup
    compile-free, and a hot-swap BACK to a warm-cached version (rollback)
    adopts its predictors — zero compiles, correct (old) weights."""
    from mxtpu import executor as _ex
    sj, params_a, shapes = get_fixture("mlp")
    params_b = {k: v + 0.5 for k, v in params_a.items()}
    buckets = (1, 4)
    built = prewarm(sj, params_a, shapes, buckets=buckets,
                    contexts=[mx.cpu(0)], version_tag="roll-a")
    assert built == len(buckets)
    b0 = _ex.program_build_count()
    sess = ServingSession(sj, params_a, shapes, buckets=buckets,
                          max_delay_ms=1, contexts=[mx.cpu(0)],
                          version_tag="roll-a")
    try:
        assert _ex.program_build_count() == b0, \
            "prewarmed session still compiled at startup"
        assert sess.pool.adopted
        assert sorted(sess.pool.bucket_costs()) == list(buckets)
        sess.swap_model(sj, params_b, version_tag="roll-b")  # compiles
        b1 = _ex.program_build_count()
        assert b1 > b0
        sess.swap_model(sj, params_a, version_tag="roll-a")  # rollback
        assert _ex.program_build_count() == b1, \
            "rollback to a warm version recompiled"
        assert sess.stats()["warm_cache_adoptions"] >= 2
        # and it really serves the ORIGINAL weights again
        ref = Predictor(sj, dict(params_a), input_shapes={"data": (1, 784)})
        x = _rand((1, 784), 7)
        ref.forward(data=x)
        out = sess.predict({"data": x}, timeout=30)[0]
        assert np.array_equal(out, ref.get_output(0))
    finally:
        sess.close()


def test_stale_tag_never_serves_old_weights():
    """Re-using a version tag with DIFFERENT weights must rebuild, not
    adopt: params_token mismatch evicts the stale cache entry."""
    sj, params_a, shapes = get_fixture("mlp")
    params_b = {k: v + 1.0 for k, v in params_a.items()}
    s1 = ServingSession(sj, params_a, shapes, buckets=(1,), max_delay_ms=1,
                        contexts=[mx.cpu(0)], version_tag="stale-t")
    s1.close()
    s2 = ServingSession(sj, params_b, shapes, buckets=(1,), max_delay_ms=1,
                        contexts=[mx.cpu(0)], version_tag="stale-t")
    try:
        ref = Predictor(sj, dict(params_b), input_shapes={"data": (1, 784)})
        x = _rand((1, 784), 3)
        ref.forward(data=x)
        out = s2.predict({"data": x}, timeout=30)[0]
        assert np.array_equal(out, ref.get_output(0))
    finally:
        s2.close()


def test_version_endpoint_and_debug_panels():
    """GET /v1/version reports the active version; /debug/state carries
    the admission, version and warm-cache panels mxtpu_top renders."""
    sj, params, shapes = get_fixture("mlp")
    sess = ServingSession(sj, params, shapes, buckets=(1, 4),
                          max_delay_ms=1, contexts=[mx.cpu(0)],
                          version_tag="panel-v0")
    server = ServingHTTPServer(sess, port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        base = server.endpoint
        sess.predict({"data": _rand((1, 784), 0)}, timeout=30)
        with urllib.request.urlopen(base + "/v1/version", timeout=10) as r:
            v = json.loads(r.read())
        assert v["version"] == "panel-v0" and v["generation"] == 0
        assert v["mode"] == "continuous" and len(v["symbol_hash"]) == 16
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            h = json.loads(r.read())
        assert h["mode"] == "continuous" and h["admission"] == "accepting"
        with urllib.request.urlopen(base + "/debug/state", timeout=10) as r:
            state = json.loads(r.read())
        adm = state["serving_admission"]
        assert adm["state"] == "accepting"
        assert adm["policy"] == "SignalAdmissionPolicy"
        assert "est_queue_wait_ms" in adm["signals"]
        assert state["serving_version"]["version"] == "panel-v0"
        assert any(e["version"] == "panel-v0"
                   for e in state["serving_warm_cache"])
        # in-process shed surfaces as AdmissionShed (the 429 mapping is
        # covered by the HTTP taxonomy test)
        sess._admission.queue_wait_budget_ms = -1.0
        with pytest.raises(AdmissionShed):
            sess.predict_async({"data": _rand((1, 784), 1)})
    finally:
        server.shutdown()
        server.server_close()
