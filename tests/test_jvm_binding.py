"""JVM binding (scala-package parity, VERDICT r2 #5): a JNI shim over the
C training ABI with NDArray/Module classes; a JVM client trains an MLP to
>0.9 accuracy and exercises the autograd tape. Gated on a JDK being
present (javac + jni.h), the way the R binding gates on Rscript."""
import os
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI_SO = os.path.join(REPO, "mxtpu", "native", "libmxtpu_capi.so")
JNI_SO = os.path.join(REPO, "mxtpu", "native", "libmxtpu_jni.so")


def _java_home():
    javac = shutil.which("javac")
    if javac is None:
        return None
    home = os.environ.get("JAVA_HOME")
    if home and os.path.exists(os.path.join(home, "include", "jni.h")):
        return home
    # derive from the javac path (…/bin/javac)
    cand = os.path.dirname(os.path.dirname(os.path.realpath(javac)))
    if os.path.exists(os.path.join(cand, "include", "jni.h")):
        return cand
    return None


def test_generated_jvm_op_surface_fresh(tmp_path):
    """The committed SymbolOps/NDArrayOps.java match a fresh run of the
    generator over the live registry (288-op surface, VERDICT r4 #5) —
    runs everywhere, no JDK needed."""
    import importlib.util

    gen_path = os.path.join(REPO, "scala-package", "gen_jvm_ops.py")
    spec = importlib.util.spec_from_file_location("gen_jvm_ops", gen_path)
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)
    gen.main(out_dir=str(tmp_path))

    from mxtpu.ops import registry
    core = os.path.join(REPO, "scala-package", "core", "src", "main",
                        "java", "ml", "dmlc", "mxtpu")
    for fname in ("SymbolOps.java", "NDArrayOps.java"):
        with open(os.path.join(core, fname)) as f:
            committed = f.read()
        with open(os.path.join(str(tmp_path), fname)) as f:
            fresh = f.read()
        assert committed == fresh, (
            "%s is stale — rerun scala-package/gen_jvm_ops.py" % fname)
        assert "(%d ops)" % len(registry._OPS) in committed
    # spot-check key conv-net signatures exist with declared input names
    with open(os.path.join(core, "SymbolOps.java")) as f:
        sym_src = f.read()
    for op, names in [("Convolution", '"data", "weight", "bias"'),
                      ("SoftmaxOutput", '"data", "label"'),
                      ("FullyConnected", '"data", "weight", "bias"')]:
        assert "public static Symbol %s(" % op in sym_src
        assert names in sym_src


def _compile_jvm(tmp_path, home):
    """Build the JNI shim + compile every .java; returns the classes dir."""
    r = subprocess.run(["make", "-C", os.path.join(REPO, "src"), "capi"],
                       capture_output=True, text=True)
    if not os.path.exists(CAPI_SO):
        pytest.skip("libmxtpu_capi.so did not build: %s"
                    % (r.stdout + r.stderr)[-300:])
    r = subprocess.run(
        ["gcc", "-shared", "-fPIC",
         "-I", os.path.join(home, "include"),
         "-I", os.path.join(home, "include", "linux"),
         "-I", os.path.join(REPO, "src", "capi"),
         os.path.join(REPO, "scala-package", "native", "mxtpu_jni.c"),
         "-L", os.path.dirname(CAPI_SO), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.dirname(CAPI_SO),
         "-o", JNI_SO],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    srcs = []
    for root, _, files in os.walk(os.path.join(REPO, "scala-package")):
        srcs += [os.path.join(root, f) for f in files if f.endswith(".java")]
    classes = str(tmp_path / "classes")
    r = subprocess.run(["javac", "-d", classes] + srcs,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return classes


def test_jvm_conv_train_through_generated_ops(tmp_path):
    """VERDICT r4 #5 gate: a JVM client composes a conv net natively via
    the GENERATED SymbolOps surface (no Python-built JSON), verifies the
    op census against the registry, and trains to >0.9 accuracy."""
    home = _java_home()
    if home is None:
        pytest.skip("no JDK (javac/jni.h) on this machine")
    classes = _compile_jvm(tmp_path, home)

    from mxtpu.ops import registry
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out = subprocess.run(
        ["java", "-cp", classes,
         "-Djava.library.path=" + os.path.dirname(CAPI_SO),
         "ml.dmlc.mxtpu.example.TrainConvNet", "192", "8", "4", "80"],
        capture_output=True, text=True, env=env, timeout=900)
    assert out.returncode == 0, out.stdout + out.stderr
    ops_line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("OPS ")][0]
    assert int(ops_line.split()[1]) == len(registry._OPS)
    assert "NDOPS_OK" in out.stdout, out.stdout
    acc = float([ln for ln in out.stdout.splitlines()
                 if "ACCURACY" in ln][0].split()[1])
    assert acc > 0.9, "JVM conv training reached only %.3f" % acc


def test_conv_train_flow_via_c_abi_ctypes():
    """JDK-independent proof of the TrainConvNet flow: the exact C-ABI
    call sequence the JNI maps to (atomic create -> keyed compose ->
    SimpleBind -> kvstore sgd loop), driven via ctypes, learns the same
    synthetic brightest-quadrant task."""
    import ctypes

    r = subprocess.run(["make", "-C", os.path.join(REPO, "src"), "capi"],
                       capture_output=True, text=True)
    if not os.path.exists(CAPI_SO):
        pytest.skip("libmxtpu_capi.so did not build: %s"
                    % (r.stdout + r.stderr)[-300:])
    lib = ctypes.CDLL(CAPI_SO)
    # default int restype truncates the pointer; string_at then segfaults
    lib.MXGetLastError.restype = ctypes.c_char_p

    def err():
        return lib.MXGetLastError()

    def atomic(op, attrs):
        n = len(attrs)
        keys = (ctypes.c_char_p * max(n, 1))(*[k.encode() for k in attrs])
        vals = (ctypes.c_char_p * max(n, 1))(
            *[str(v).encode() for v in attrs.values()])
        h = ctypes.c_void_p()
        assert lib.MXSymbolCreateAtomicSymbol(
            op.encode(), n, keys, vals, ctypes.byref(h)) == 0, err()
        return h

    def op_node(opname, name, attrs, argnames, inputs):
        h = atomic(opname, attrs)
        ks = (ctypes.c_char_p * len(inputs))(
            *[k.encode() for k in argnames[:len(inputs)]])
        ar = (ctypes.c_void_p * len(inputs))(*inputs)
        assert lib.MXSymbolComposeKeyed(
            h, name.encode(), len(inputs), ks, ar) == 0, err()
        return h

    data = ctypes.c_void_p()
    assert lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)) == 0
    c1 = op_node("Convolution", "conv1",
                 {"kernel": "(3,3)", "num_filter": "8", "pad": "(1,1)"},
                 ["data", "weight", "bias"], [data])
    a1 = op_node("Activation", "relu1", {"act_type": "relu"}, ["data"],
                 [c1])
    p1 = op_node("Pooling", "pool1",
                 {"kernel": "(2,2)", "stride": "(2,2)", "pool_type": "max"},
                 ["data"], [a1])
    fl = op_node("Flatten", "flatten", {}, ["data"], [p1])
    f1 = op_node("FullyConnected", "fc1", {"num_hidden": "32"},
                 ["data", "weight", "bias"], [fl])
    a2 = op_node("Activation", "relu2", {"act_type": "relu"}, ["data"],
                 [f1])
    f2 = op_node("FullyConnected", "fc2", {"num_hidden": "4"},
                 ["data", "weight", "bias"], [a2])
    net = op_node("SoftmaxOutput", "softmax", {}, ["data", "label"], [f2])

    # TrainConvNet.java's LCG data, bit-exact
    n, edge, classes, epochs = 192, 8, 4, 80
    seed, mask = 20260731, (1 << 64) - 1
    images = np.zeros(n * edge * edge, dtype=np.float32)
    labels = np.zeros(n, dtype=np.float32)
    half = edge // 2
    for i in range(n):
        seed = (seed * 6364136223846793005 + 1442695040888963407) & mask
        cls = (seed >> 33) % classes
        labels[i] = cls
        r0, c0 = (cls // 2) * half, (cls % 2) * half
        for rr in range(edge):
            for cc in range(edge):
                seed = (seed * 6364136223846793005
                        + 1442695040888963407) & mask
                noise = ((seed >> 40) & 0xff) / 512.0
                bright = r0 <= rr < r0 + half and c0 <= cc < c0 + half
                images[(i * edge + rr) * edge + cc] = (
                    (1.0 if bright else 0.0) + noise)

    names = ["data", "softmax_label"]
    indptr = (ctypes.c_uint * 3)(0, 4, 5)
    shp = (ctypes.c_uint * 5)(n, 1, edge, edge, n)
    nm = (ctypes.c_char_p * 2)(*[s.encode() for s in names])
    exe = ctypes.c_void_p()
    assert lib.MXExecutorSimpleBind(
        net, 1, 0, b"write", 2, nm, indptr, shp, ctypes.byref(exe)) == 0, \
        err()

    kv = ctypes.c_void_p()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    lib.MXKVStoreSetOptimizer.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_float, ctypes.c_float,
        ctypes.c_float, ctypes.c_float]
    assert lib.MXKVStoreSetOptimizer(kv, b"sgd", 0.3, 0.0, 0.9,
                                     1.0 / n) == 0

    nargs = ctypes.c_uint()
    argnames = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXSymbolListArguments(
        net, ctypes.byref(nargs), ctypes.byref(argnames)) == 0
    params = [argnames[i].decode() for i in range(nargs.value)
              if argnames[i].decode() not in names]

    def arg_h(name):
        h = ctypes.c_void_p()
        assert lib.MXExecutorArg(exe, name.encode(), ctypes.byref(h)) == 0
        return h

    def grad_h(name):
        h = ctypes.c_void_p()
        assert lib.MXExecutorGrad(exe, name.encode(), ctypes.byref(h)) == 0
        return h

    def copy_from(h, arr):
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        assert lib.MXNDArraySyncCopyFromCPU(
            h, arr.ctypes.data_as(ctypes.c_void_p), arr.size * 4) == 0

    def nd_size(h):
        nd = ctypes.c_uint()
        sp = ctypes.POINTER(ctypes.c_uint)()
        assert lib.MXNDArrayGetShape(
            h, ctypes.byref(nd), ctypes.byref(sp)) == 0
        out = 1
        for i in range(nd.value):
            out *= sp[i]
        return out

    seed2 = 12345  # Module.java's deterministic init
    for p in params:
        w = arg_h(p)
        total = nd_size(w)
        init = np.zeros(total, dtype=np.float32)
        for i in range(total):
            seed2 = (seed2 * 1103515245 + 12345) & mask
            init[i] = (((seed2 >> 16) & 0x7fff) / 32768.0 - 0.5) * 0.2
        copy_from(w, init)
        assert lib.MXKVStoreInit(kv, p.encode(), w) == 0

    copy_from(arg_h("data"), images)
    copy_from(arg_h("softmax_label"), labels)
    for _ in range(epochs):
        assert lib.MXExecutorForward(exe, 1) == 0
        assert lib.MXExecutorBackward(exe) == 0
        for p in params:
            assert lib.MXKVStorePush(kv, p.encode(), grad_h(p)) == 0
            assert lib.MXKVStorePull(kv, p.encode(), arg_h(p)) == 0

    assert lib.MXExecutorForward(exe, 0) == 0
    out_h = ctypes.c_void_p()
    assert lib.MXExecutorOutput(exe, 0, ctypes.byref(out_h)) == 0
    probs = np.zeros(n * classes, dtype=np.float32)
    assert lib.MXNDArraySyncCopyToCPU(
        out_h, probs.ctypes.data_as(ctypes.c_void_p), probs.size * 4) == 0
    acc = (probs.reshape(n, classes).argmax(1) == labels).mean()
    assert acc > 0.9, "C-ABI conv flow reached only %.3f" % acc


def test_jvm_client_trains_mlp(tmp_path):
    home = _java_home()
    if home is None:
        pytest.skip("no JDK (javac/jni.h) on this machine")
    r = subprocess.run(["make", "-C", os.path.join(REPO, "src"), "capi"],
                       capture_output=True, text=True)
    if not os.path.exists(CAPI_SO):
        pytest.skip("libmxtpu_capi.so did not build: %s"
                    % (r.stdout + r.stderr)[-300:])

    # 1. build the JNI shim
    r = subprocess.run(
        ["gcc", "-shared", "-fPIC",
         "-I", os.path.join(home, "include"),
         "-I", os.path.join(home, "include", "linux"),
         "-I", os.path.join(REPO, "src", "capi"),
         os.path.join(REPO, "scala-package", "native", "mxtpu_jni.c"),
         "-L", os.path.dirname(CAPI_SO), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.dirname(CAPI_SO),
         "-o", JNI_SO],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # 2. compile the Java sources
    srcs = []
    for root, _, files in os.walk(os.path.join(REPO, "scala-package")):
        srcs += [os.path.join(root, f) for f in files if f.endswith(".java")]
    classes = str(tmp_path / "classes")
    r = subprocess.run(["javac", "-d", classes] + srcs,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # 3. dataset + symbol, as the C-ABI test builds them
    import mxtpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    sym_path = str(tmp_path / "mlp.json")
    net.save(sym_path)
    rng = np.random.RandomState(0)
    n, dim, classes_n = 256, 16, 4
    centers = rng.randn(classes_n, dim) * 3
    y = rng.randint(0, classes_n, n)
    X = (centers[y] + rng.randn(n, dim)).astype("float32")
    (tmp_path / "data.bin").write_bytes(X.tobytes())
    (tmp_path / "labels.bin").write_bytes(y.astype("float32").tobytes())

    # 4. run the JVM client
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out = subprocess.run(
        ["java", "-cp", classes,
         "-Djava.library.path=" + os.path.dirname(CAPI_SO),
         "ml.dmlc.mxtpu.example.TrainMLP", sym_path,
         str(tmp_path / "data.bin"), str(tmp_path / "labels.bin"),
         str(n), str(dim), str(classes_n), "60"],
        capture_output=True, text=True, env=env, timeout=900)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "AUTOGRAD_OK" in out.stdout, out.stdout
    acc = float([ln for ln in out.stdout.splitlines()
                 if "ACCURACY" in ln][0].split()[1])
    assert acc > 0.9, "JVM training reached only %.3f" % acc
