"""JVM binding (scala-package parity, VERDICT r2 #5): a JNI shim over the
C training ABI with NDArray/Module classes; a JVM client trains an MLP to
>0.9 accuracy and exercises the autograd tape. Gated on a JDK being
present (javac + jni.h), the way the R binding gates on Rscript."""
import os
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI_SO = os.path.join(REPO, "mxtpu", "native", "libmxtpu_capi.so")
JNI_SO = os.path.join(REPO, "mxtpu", "native", "libmxtpu_jni.so")


def _java_home():
    javac = shutil.which("javac")
    if javac is None:
        return None
    home = os.environ.get("JAVA_HOME")
    if home and os.path.exists(os.path.join(home, "include", "jni.h")):
        return home
    # derive from the javac path (…/bin/javac)
    cand = os.path.dirname(os.path.dirname(os.path.realpath(javac)))
    if os.path.exists(os.path.join(cand, "include", "jni.h")):
        return cand
    return None


def test_jvm_client_trains_mlp(tmp_path):
    home = _java_home()
    if home is None:
        pytest.skip("no JDK (javac/jni.h) on this machine")
    r = subprocess.run(["make", "-C", os.path.join(REPO, "src"), "capi"],
                       capture_output=True, text=True)
    if not os.path.exists(CAPI_SO):
        pytest.skip("libmxtpu_capi.so did not build: %s"
                    % (r.stdout + r.stderr)[-300:])

    # 1. build the JNI shim
    r = subprocess.run(
        ["gcc", "-shared", "-fPIC",
         "-I", os.path.join(home, "include"),
         "-I", os.path.join(home, "include", "linux"),
         "-I", os.path.join(REPO, "src", "capi"),
         os.path.join(REPO, "scala-package", "native", "mxtpu_jni.c"),
         "-L", os.path.dirname(CAPI_SO), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.dirname(CAPI_SO),
         "-o", JNI_SO],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # 2. compile the Java sources
    srcs = []
    for root, _, files in os.walk(os.path.join(REPO, "scala-package")):
        srcs += [os.path.join(root, f) for f in files if f.endswith(".java")]
    classes = str(tmp_path / "classes")
    r = subprocess.run(["javac", "-d", classes] + srcs,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # 3. dataset + symbol, as the C-ABI test builds them
    import mxtpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    sym_path = str(tmp_path / "mlp.json")
    net.save(sym_path)
    rng = np.random.RandomState(0)
    n, dim, classes_n = 256, 16, 4
    centers = rng.randn(classes_n, dim) * 3
    y = rng.randint(0, classes_n, n)
    X = (centers[y] + rng.randn(n, dim)).astype("float32")
    (tmp_path / "data.bin").write_bytes(X.tobytes())
    (tmp_path / "labels.bin").write_bytes(y.astype("float32").tobytes())

    # 4. run the JVM client
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out = subprocess.run(
        ["java", "-cp", classes,
         "-Djava.library.path=" + os.path.dirname(CAPI_SO),
         "ml.dmlc.mxtpu.example.TrainMLP", sym_path,
         str(tmp_path / "data.bin"), str(tmp_path / "labels.bin"),
         str(n), str(dim), str(classes_n), "60"],
        capture_output=True, text=True, env=env, timeout=900)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "AUTOGRAD_OK" in out.stdout, out.stdout
    acc = float([ln for ln in out.stdout.splitlines()
                 if "ACCURACY" in ln][0].split()[1])
    assert acc > 0.9, "JVM training reached only %.3f" % acc
