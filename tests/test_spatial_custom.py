"""Tests for spatial/warping/region ops + the Custom python-op path.

Oracles: numpy recomputation (reference model: tests/python/unittest/
test_operator.py spatial-transformer / roi / correlation / custom tests).
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd


def test_bilinear_sampler_identity():
    x = np.random.randn(2, 3, 5, 7).astype("float32")
    # identity grid: x,y in [-1,1]
    ys = np.linspace(-1, 1, 5)
    xs = np.linspace(-1, 1, 7)
    gx, gy = np.meshgrid(xs, ys)
    grid = np.stack([gx, gy], axis=0)[None].repeat(2, 0).astype("float32")
    out = nd.BilinearSampler(nd.array(x), nd.array(grid)).asnumpy()
    assert np.allclose(out, x, atol=1e-5)


def test_grid_generator_affine_identity():
    # identity affine [1,0,0, 0,1,0]
    aff = np.array([[1, 0, 0, 0, 1, 0]], dtype="float32")
    g = nd.GridGenerator(nd.array(aff), transform_type="affine",
                         target_shape=(4, 6)).asnumpy()
    assert g.shape == (1, 2, 4, 6)
    assert np.allclose(g[0, 0, 0], np.linspace(-1, 1, 6), atol=1e-6)
    assert np.allclose(g[0, 1, :, 0], np.linspace(-1, 1, 4), atol=1e-6)


def test_spatial_transformer_identity():
    x = np.random.randn(2, 1, 6, 6).astype("float32")
    loc = np.tile(np.array([1, 0, 0, 0, 1, 0], dtype="float32"), (2, 1))
    out = nd.SpatialTransformer(nd.array(x), nd.array(loc),
                                target_shape=(6, 6)).asnumpy()
    assert np.allclose(out, x, atol=1e-5)


def test_roi_pooling_matches_naive():
    np.random.seed(0)
    x = np.random.randn(1, 2, 8, 8).astype("float32")
    rois = np.array([[0, 0, 0, 7, 7], [0, 2, 2, 6, 6]], dtype="float32")
    out = nd.ROIPooling(nd.array(x), nd.array(rois), pooled_size=(2, 2),
                        spatial_scale=1.0).asnumpy()
    assert out.shape == (2, 2, 2, 2)
    # whole-image ROI, 2x2 pooling = max over quadrants
    ref00 = x[0, :, 0:4, 0:4].max(axis=(1, 2))
    assert np.allclose(out[0, :, 0, 0], ref00, atol=1e-5)
    ref11 = x[0, :, 4:8, 4:8].max(axis=(1, 2))
    assert np.allclose(out[0, :, 1, 1], ref11, atol=1e-5)


def test_correlation_exact_values():
    np.random.seed(1)
    x = np.random.randn(1, 4, 6, 6).astype("float32")
    y = np.random.randn(1, 4, 6, 6).astype("float32")
    out = nd.Correlation(nd.array(x), nd.array(y), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1).asnumpy()
    assert out.shape[1] == 9
    # zero-displacement channel = per-pixel channel inner product / C
    ref0 = (x[0] * y[0]).sum(axis=0) / 4.0
    assert np.allclose(out[0, 4], ref0, atol=1e-4)
    # displacement (dy=+1, dx=0) channel index 7: x(p) . y(p + dy)
    ref_dy = np.zeros((6, 6), dtype="float32")
    ref_dy[:5] = (x[0, :, :5, :] * y[0, :, 1:, :]).sum(axis=0) / 4.0
    assert np.allclose(out[0, 7], ref_dy, atol=1e-4)


def test_deformable_conv_zero_offset_equals_conv():
    np.random.seed(2)
    x = np.random.randn(1, 2, 6, 6).astype("float32")
    w = np.random.randn(3, 2, 3, 3).astype("float32")
    off = np.zeros((1, 18, 4, 4), dtype="float32")
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        num_filter=3, no_bias=True).asnumpy()
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=3, no_bias=True).asnumpy()
    assert np.allclose(out, ref, atol=1e-4)


def test_psroi_pooling_shape():
    x = np.random.randn(1, 2 * 9, 8, 8).astype("float32")
    rois = np.array([[0, 0, 0, 7, 7]], dtype="float32")
    out = nd.contrib.PSROIPooling(nd.array(x), nd.array(rois),
                                  spatial_scale=1.0, output_dim=2,
                                  pooled_size=3, group_size=3).asnumpy()
    assert out.shape == (1, 2, 3, 3)


def test_ctc_loss_blank_last():
    np.random.seed(5)
    T, N, C = 6, 1, 5
    x = np.random.randn(T, N, C).astype("float32")
    # same label sequence expressed in both conventions must give the
    # same loss when the logits are permuted to match blank position
    lab_first = np.array([[1, 2, 0, 0]], dtype="float32")  # blank=0
    lab_last = np.array([[0, 1, -1, -1]], dtype="float32")  # blank=C-1
    x_last = np.concatenate([x[:, :, 1:], x[:, :, :1]], axis=2)
    out_first = nd.contrib.CTCLoss(nd.array(x), nd.array(lab_first)).asnumpy()
    out_last = nd.contrib.CTCLoss(nd.array(x_last), nd.array(lab_last),
                                  blank_label="last").asnumpy()
    assert abs(out_first[0] - out_last[0]) < 1e-4


def test_ctc_loss_lengths():
    np.random.seed(6)
    T, C = 8, 5
    x = np.random.randn(T, 2, C).astype("float32")
    labels = np.array([[1, 2, 3, 3], [2, 1, 0, 0]], dtype="float32")
    dl = np.array([5.0, 8.0], dtype="float32")
    ll = np.array([2.0, 2.0], dtype="float32")
    out = nd.contrib.CTCLoss(nd.array(x), nd.array(labels), nd.array(dl),
                             nd.array(ll), use_data_lengths=True,
                             use_label_lengths=True).asnumpy()
    # sample 0 truncated to 5 steps and 2 labels == plain CTC on the slice
    ref = _np_ctc_loss(x[:5, 0], [1, 2])
    assert abs(out[0] - ref) < 1e-3


def test_psroi_pooling_default_group_size():
    x = np.random.randn(1, 2 * 9, 8, 8).astype("float32")
    rois = np.array([[0, 0, 0, 7, 7]], dtype="float32")
    out = nd.contrib.PSROIPooling(nd.array(x), nd.array(rois),
                                  spatial_scale=1.0, output_dim=2,
                                  pooled_size=3).asnumpy()
    assert out.shape == (1, 2, 3, 3)


def test_deformable_psroi_no_trans():
    x = np.random.randn(1, 2 * 9, 8, 8).astype("float32")
    rois = np.array([[0, 0, 0, 7, 7]], dtype="float32")
    out = nd.contrib.DeformablePSROIPooling(
        nd.array(x), nd.array(rois), spatial_scale=1.0, output_dim=2,
        group_size=3, pooled_size=3, no_trans=True).asnumpy()
    assert out.shape == (1, 2, 3, 3)


def test_proposal_shapes_and_validity():
    np.random.seed(3)
    A = 3 * 4  # ratios x scales
    H = W = 4
    score = np.random.uniform(0, 1, (1, 2 * A, H, W)).astype("float32")
    bbox = (np.random.randn(1, 4 * A, H, W) * 0.1).astype("float32")
    im_info = np.array([[64, 64, 1.0]], dtype="float32")
    rois = nd.contrib.Proposal(nd.array(score), nd.array(bbox),
                               nd.array(im_info),
                               rpn_pre_nms_top_n=50,
                               rpn_post_nms_top_n=10,
                               feature_stride=16).asnumpy()
    assert rois.shape == (10, 5)
    assert (rois[:, 1] <= rois[:, 3] + 1e-3).all()
    assert (rois[:, 2] <= rois[:, 4] + 1e-3).all()
    assert (rois[:, 1:] >= -1e-3).all()


def _np_ctc_loss(logits, labels):
    """Plain-python CTC NLL oracle, blank=0."""
    T, C = logits.shape
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    lab = [int(l) for l in labels if l > 0]
    ext = [0]
    for l in lab:
        ext += [l, 0]
    S = len(ext)
    alpha = np.zeros((T, S))
    alpha[0, 0] = p[0, 0]
    if S > 1:
        alpha[0, 1] = p[0, ext[1]]
    for t in range(1, T):
        for s in range(S):
            a = alpha[t - 1, s]
            if s >= 1:
                a += alpha[t - 1, s - 1]
            if s >= 2 and ext[s] != 0 and ext[s] != ext[s - 2]:
                a += alpha[t - 1, s - 2]
            alpha[t, s] = a * p[t, ext[s]]
    ll = alpha[T - 1, S - 1] + (alpha[T - 1, S - 2] if S > 1 else 0)
    return -np.log(max(ll, 1e-30))


def test_ctc_loss_vs_oracle():
    np.random.seed(4)
    T, N, C = 6, 2, 5
    x = np.random.randn(T, N, C).astype("float32")
    labels = np.array([[1, 2, 0, 0], [3, 3, 1, 0]], dtype="float32")
    out = nd.contrib.CTCLoss(nd.array(x), nd.array(labels)).asnumpy()
    for i in range(N):
        ref = _np_ctc_loss(x[:, i], labels[i])
        assert abs(out[i] - ref) < 1e-3, (i, out[i], ref)


def test_khatri_rao():
    a = np.random.randn(3, 2).astype("float32")
    b = np.random.randn(3, 4).astype("float32")
    out = nd.khatri_rao(nd.array(a), nd.array(b)).asnumpy()
    ref = np.stack([np.kron(a[i], b[i]) for i in range(3)])
    assert np.allclose(out, ref, atol=1e-5)


def test_slice_assign_ops():
    x = np.zeros((4, 4), dtype="float32")
    v = np.ones((2, 2), dtype="float32")
    out = nd._slice_assign(nd.array(x), nd.array(v), begin=(1, 1),
                           end=(3, 3)).asnumpy()
    assert out[1:3, 1:3].sum() == 4 and out.sum() == 4
    out2 = nd._slice_assign_scalar(nd.array(x), begin=(0, 0), end=(2, 4),
                                   scalar=2.5).asnumpy()
    assert np.allclose(out2[:2], 2.5) and np.allclose(out2[2:], 0)


# ------------------------------------------------------------- Custom op


@mx.operator.register("sigmoid_custom")
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        class SigmoidOp(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0].asnumpy()
                self.assign(out_data[0], req[0], 1 / (1 + np.exp(-x)))

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                y = out_data[0].asnumpy()
                g = out_grad[0].asnumpy()
                self.assign(in_grad[0], req[0], g * y * (1 - y))
        return SigmoidOp()


def test_custom_op_forward():
    x = np.random.randn(3, 4).astype("float32")
    out = nd.Custom(nd.array(x), op_type="sigmoid_custom").asnumpy()
    assert np.allclose(out, 1 / (1 + np.exp(-x)), atol=1e-6)


def test_custom_op_backward_autograd():
    from mxtpu import autograd
    x = nd.array(np.random.randn(3, 4).astype("float32"))
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="sigmoid_custom")
        loss = nd.sum(y)
    loss.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), atol=1e-5)


def test_custom_op_in_symbol_executor():
    import mxtpu as mx
    data = mx.sym.Variable("data")
    y = mx.sym.Custom(data, op_type="sigmoid_custom", name="sig")
    exe = y.simple_bind(mx.cpu(), data=(2, 3))
    x = np.random.randn(2, 3).astype("float32")
    out = exe.forward(is_train=False, data=mx.nd.array(x))[0].asnumpy()
    assert np.allclose(out, 1 / (1 + np.exp(-x)), atol=1e-6)


def test_no_gradient_op():
    out = nd._NoGradient()
    assert out.asnumpy().shape == (1,)


def test_deformable_conv_groups():
    import numpy as np
    from mxtpu import nd

    rng = np.random.RandomState(0)
    N, C, H, W, F = 2, 4, 6, 6, 4
    x = rng.rand(N, C, H, W).astype('float32')
    # num_group=2: weight carries C/2 input channels per filter
    w = rng.rand(F, C // 2, 3, 3).astype('float32')
    off = np.zeros((N, 2 * 2 * 9, H, W), 'float32')  # num_deformable_group=2
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3), pad=(1, 1),
        num_filter=F, num_group=2, num_deformable_group=2)
    assert out.shape == (N, F, H, W)
    # zero offsets must equal a plain grouped convolution
    ref = np.zeros((N, F, H, W), 'float32')
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    for g in range(2):
        for f in range(2):
            fi = g * 2 + f
            for i in range(H):
                for j in range(W):
                    patch = xp[:, g * 2:(g + 1) * 2, i:i + 3, j:j + 3]
                    ref[:, fi, i, j] = (patch * w[fi]).sum(axis=(1, 2, 3))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-4)


def test_proposal_pads_with_kept_boxes():
    import numpy as np
    from mxtpu import nd

    # One dominant box; aggressive NMS keeps very few. Output must cycle
    # kept proposals, never emit suppressed ones.
    H = W = 4
    A = 1
    rng = np.random.RandomState(0)
    score = rng.rand(1, 2 * A, H, W).astype('float32')
    bbox = np.zeros((1, 4 * A, H, W), 'float32')
    im_info = np.array([[64.0, 64.0, 1.0]], 'float32')
    rois = nd.contrib.Proposal(
        nd.array(score), nd.array(bbox), nd.array(im_info),
        feature_stride=16, scales=(8,), ratios=(1.0,),
        rpn_pre_nms_top_n=16, rpn_post_nms_top_n=12, threshold=0.01,
        rpn_min_size=1).asnumpy()
    assert rois.shape == (12, 5)
    # with threshold 0.01 nearly everything overlapping is suppressed;
    # padded slots must duplicate kept boxes, so unique rows are few
    uniq = np.unique(np.round(rois, 3), axis=0)
    assert len(uniq) < 12
