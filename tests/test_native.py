"""Native runtime tests: storage pool, recordio, dependency engine,
threaded prefetch (src/core/, mirroring the reference's C++ test tier —
tests/cpp/{engine,storage} incl. the threaded_engine_test.cc random-dep
stress pattern)."""
import ctypes
import os
import random
import threading
import time

import pytest

from mxtpu import _native, engine as eng
from mxtpu.recordio import MXIndexedRecordIO, MXRecordIO

native = pytest.mark.skipif(not _native.native_available(),
                            reason="libmxtpu.so not built")


@native
def test_storage_pool_reuse():
    lib = _native.get_lib()
    p = ctypes.c_void_p()
    _native.check_call(lib.MXTPUStorageAlloc(1000, ctypes.byref(p)))
    first = p.value
    assert first % 64 == 0
    _native.check_call(lib.MXTPUStorageFree(p))
    # Same bucket (1024) must be recycled LIFO.
    _native.check_call(lib.MXTPUStorageAlloc(600, ctypes.byref(p)))
    assert p.value == first
    _native.check_call(lib.MXTPUStorageDirectFree(p))
    a, pooled = ctypes.c_uint64(), ctypes.c_uint64()
    _native.check_call(lib.MXTPUStorageStats(ctypes.byref(a),
                                             ctypes.byref(pooled)))
    _native.check_call(lib.MXTPUStorageReleaseAll())


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "a.rec")
    rec = MXRecordIO(path, "w")
    payloads = [b"hello", b"", b"x" * 1237, bytes(range(256))]
    for pl in payloads:
        rec.write(pl)
    rec.close()
    rec = MXRecordIO(path, "r")
    for pl in payloads:
        assert rec.read() == pl
    assert rec.read() is None
    rec.close()


@native
def test_recordio_native_py_interop(tmp_path):
    # Written by native, read by pure python (and vice versa).
    path = str(tmp_path / "b.rec")
    rec = MXRecordIO(path, "w")
    assert rec._nh is not None  # native path active
    rec.write(b"native-written")
    rec.close()

    os.environ["MXTPU_DISABLE_NATIVE"] = "1"
    try:
        # force a pure-python instance by monkeypatching get_lib result
        saved = _native._LIB
        _native._LIB = False
        r2 = MXRecordIO(path, "r")
        assert r2._nh is None
        assert r2.read() == b"native-written"
        r2.close()
        w2 = MXRecordIO(str(tmp_path / "c.rec"), "w")
        w2.write(b"py-written")
        w2.close()
    finally:
        _native._LIB = saved
        del os.environ["MXTPU_DISABLE_NATIVE"]
    r3 = MXRecordIO(str(tmp_path / "c.rec"), "r")
    assert r3._nh is not None
    assert r3.read() == b"py-written"
    assert r3.read() is None
    r3.close()


def test_indexed_recordio(tmp_path):
    rec = MXIndexedRecordIO(str(tmp_path / "d.idx"), str(tmp_path / "d.rec"),
                            "w")
    for i in range(20):
        rec.write_idx(i, ("rec%d" % i).encode())
    rec.close()
    rec = MXIndexedRecordIO(str(tmp_path / "d.idx"), str(tmp_path / "d.rec"),
                            "r")
    for i in [7, 0, 19, 3]:
        assert rec.read_idx(i) == ("rec%d" % i).encode()
    rec.close()


@native
def test_engine_write_serialization():
    e = eng.ThreadedEngine()
    var = e.new_variable()
    out = []
    for i in range(200):
        e.push(lambda i=i: out.append(i), mutable_vars=[var])
    e.wait_for_var(var)
    assert out == list(range(200))
    e.delete_variable(var)
    e.wait_for_all()


@native
def test_engine_reader_writer_protocol():
    e = eng.ThreadedEngine()
    var = e.new_variable()
    state = {"v": 0}
    reads = []
    lock = threading.Lock()

    def write(i):
        time.sleep(0.001)
        state["v"] = i

    def read():
        with lock:
            reads.append(state["v"])

    for i in range(1, 11):
        e.push(lambda i=i: write(i), mutable_vars=[var])
        for _ in range(3):
            e.push(read, const_vars=[var])
    e.wait_for_all()
    # every read must observe the value of the write immediately before it
    assert sorted(reads) == sorted(sum(([i] * 3 for i in range(1, 11)), []))
    for i in range(1, 11):
        assert reads[(i - 1) * 3:(i - 1) * 3 + 3] == [i, i, i]


@native
def test_engine_random_dag_stress():
    # Parity with tests/cpp/engine/threaded_engine_test.cc: random dep
    # graphs; correctness = per-var sequential consistency of counters.
    e = eng.ThreadedEngine()
    rng = random.Random(0)
    n_vars = 8
    vars_ = [e.new_variable() for _ in range(n_vars)]
    counters = [0] * n_vars
    expected = [0] * n_vars

    def bump(idxs):
        for i in idxs:
            counters[i] += 1

    for _ in range(300):
        k = rng.randint(1, 3)
        mut = rng.sample(range(n_vars), k)
        const = [i for i in rng.sample(range(n_vars), rng.randint(0, 2))
                 if i not in mut]
        for i in mut:
            expected[i] += 1
        e.push(lambda mut=mut: bump(mut),
               const_vars=[vars_[i] for i in const],
               mutable_vars=[vars_[i] for i in mut])
    e.wait_for_all()
    assert counters == expected
    for v in vars_:
        e.delete_variable(v)
    e.wait_for_all()


@native
def test_engine_priority_and_parallelism():
    e = eng.ThreadedEngine()
    assert e.num_workers >= 2
    done = threading.Event()
    e.push(done.wait)  # occupies one worker until released
    ran = threading.Event()
    e.push(ran.set, priority=10)
    assert ran.wait(timeout=5)  # independent op runs despite blocked worker
    done.set()
    e.wait_for_all()


def test_naive_engine():
    e = eng.NaiveEngine()
    var = e.new_variable()
    out = []
    e.push(lambda: out.append(1), mutable_vars=[var])
    assert out == [1]
    e.wait_for_var(var)
    e.wait_for_all()
    e.delete_variable(var)


@native
def test_threaded_iter_prefetch():
    lib = _native.get_lib()
    produced = []

    def producer(_ctx, out_item):
        i = len(produced)
        if i >= 50:
            return 1  # EOF
        produced.append(i)
        out_item[0] = i + 1  # avoid NULL handle
        return 0

    cb = _native.PRODUCE_FN(producer)
    h = ctypes.c_void_p()
    _native.check_call(lib.MXTPUThreadedIterCreate(cb, None, 4,
                                                   ctypes.byref(h)))
    got = []
    while True:
        item = ctypes.c_void_p()
        _native.check_call(lib.MXTPUThreadedIterNext(h, ctypes.byref(item)))
        if not item.value:
            break
        got.append(item.value - 1)
    assert got == list(range(50))
    _native.check_call(lib.MXTPUThreadedIterFree(h))


def test_async_checkpoint_roundtrip(tmp_path):
    """do_checkpoint-style async writes land durably and load_checkpoint
    drains in-flight writes before reading."""
    import numpy as np
    import mxtpu as mx

    prefix = str(tmp_path / "ck")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    arg = {"fc_weight": mx.nd.array(np.arange(12, dtype="float32")
                                    .reshape(4, 3)),
           "fc_bias": mx.nd.zeros((4,))}
    for epoch in range(1, 4):
        mx.model.save_checkpoint(prefix, epoch, net, arg, {},
                                 async_write=True)
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 3)
    np.testing.assert_allclose(arg2["fc_weight"].asnumpy(),
                               arg["fc_weight"].asnumpy())
    mx.nd.waitall()
    assert os.path.exists(prefix + "-0001.params")
    assert os.path.exists(prefix + "-0002.params")


def test_native_cpp_unit_tier():
    """The C++ unit binary (src/tests/native_unit_test.cc — the
    reference's tests/cpp gtest tier, SURVEY §4 row 1): engine MR/SW
    stress with order assertions, WaitForVar, pooled storage bucketing,
    recordio round-trip incl. empty records."""
    import os
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    exe = os.path.join(repo, "mxtpu", "native", "native_unit_test")
    r = subprocess.run(["make", "-C", os.path.join(repo, "src"), "test"],
                       capture_output=True, text=True)
    assert os.path.exists(exe), r.stdout + r.stderr
    out = subprocess.run([exe], capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "NATIVE_UNIT_OK" in out.stdout


def test_native_cpp_unit_tier_tsan():
    """The same native tier under ThreadSanitizer — the engine's MR/SW
    dependency protocol proven race-free by a sanitizer, not just by
    construction (beyond the reference, which has no TSAN integration).
    Skips where the toolchain lacks -fsanitize=thread."""
    import os
    import subprocess

    import pytest

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    exe = os.path.join(repo, "mxtpu", "native", "native_unit_test_tsan")
    subprocess.run(["make", "-C", os.path.join(repo, "src"), "tsan"],
                   capture_output=True, text=True)
    if not os.path.exists(exe):
        pytest.skip("toolchain lacks -fsanitize=thread")
    out = subprocess.run([exe], capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "NATIVE_UNIT_OK" in out.stdout
    assert "WARNING: ThreadSanitizer" not in out.stderr, out.stderr
