"""Observability PR (mxtpu/obs): trace timeline export, per-token
decode latency attribution, and the persistent measurement corpus.

Tier-1 (CPU, `not slow`). The PR's acceptance gates, deterministic per
the repo convention:

* **trace schema** — a fit + a streaming decode produce a Perfetto-clean
  trace.json: every span an "X" slice on a named per-thread track
  ("M" metadata), cross-thread parent links as "s"/"f" flow pairs
  joining request → batch → pool.run;
* **retire-time latency** — with an injected frozen clock,
  `decode_ttft_ms`/`decode_tbt_ms` read exactly 0.0 even when the token
  stream is drained only after the clock advances: the stamps happen at
  token RETIRE, not HTTP flush — including multi-chunk chunked-prefill
  TTFT;
* **exemplar sampling** — the seeded sampler makes which requests carry
  a structured timeline a pure function of the enqueue ordinal, so
  capture is asserted exactly, not probabilistically;
* **corpus** — N builds + M service rows round-trip to exactly N+M
  schema-valid rows, a writer killed mid-append leaves a tolerated torn
  tail, and `summarize()` reproduces the ServiceLine fit `tune.search`
  derives in-process.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxtpu as mx
import mxtpu.diagnostics as diag
from mxtpu import telemetry as tel
from mxtpu.obs import corpus as obs_corpus
from mxtpu.obs import trace as obs_trace
from mxtpu.obs import trace_export
from mxtpu.obs.sampler import TraceSampler
from mxtpu.serving import DecodeSession, ServingHTTPServer
from mxtpu.serving.decode import attn_decode_fixture, lm_decode_fixture
from mxtpu.telemetry import tracing as _tracing

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

# shared fixtures, one version tag per weight set (process warm cache:
# the suite pays each step-program compile once for this module)
_LM = {}
_ATTN = {}


def _lm(seed=0):
    if seed not in _LM:
        _LM[seed] = lm_decode_fixture(seed=seed)
    return _LM[seed]


def _attn(seed=0):
    if seed not in _ATTN:
        _ATTN[seed] = attn_decode_fixture(seed=seed)
    return _ATTN[seed]


def _session(seed=0, **kwargs):
    sym, params, shapes, state_names, _ = _lm(seed)
    kwargs.setdefault("buckets", (4,))
    kwargs.setdefault("slot_capacity", 2)
    kwargs.setdefault("version_tag", "to-v%d" % seed)
    return DecodeSession(sym, params, shapes, state_names, **kwargs)


def _kv_session(seed=0, **kwargs):
    fx = _attn(seed)
    kwargs.setdefault("buckets", (2,))
    kwargs.setdefault("slot_capacity", 2)
    kwargs.setdefault("prefill_chunk_tokens", 2)
    kwargs.setdefault("prefill_buckets", (2,))
    kwargs.setdefault("version_tag", "to-kv-v%d" % seed)
    return DecodeSession(fx["step_symbol_json"], fx["params"],
                         fx["step_example_shapes"], [], arena="paged",
                         paged=fx, **kwargs)


class FakeClock:
    """Injectable session clock (seconds)."""

    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _hist(sess, name, **labels):
    return sess.metrics.histogram(name, labels=labels or None)


# ------------------------------------------------------------ span ring
def test_span_ring_records_finished_spans():
    ring = obs_trace.install()
    assert ring is not None and obs_trace.trace_enabled()
    ring.clear()
    with _tracing.span("obs.test.outer", category="test",
                       tags={"k": 1}) as outer:
        with _tracing.span("obs.test.inner", category="test"):
            pass
    rows = [r for r in ring.snapshot()
            if r["name"].startswith("obs.test.")]
    assert [r["name"] for r in rows] == ["obs.test.inner",
                                         "obs.test.outer"]
    inner, out = rows
    assert inner["parent_id"] == out["span_id"]
    assert inner["trace_id"] == out["trace_id"] == outer.trace_id
    assert out["t1_us"] >= out["t0_us"] > 0
    assert out["tags"] == {"k": 1}
    assert inner["thread"] == threading.get_ident()


def test_span_ring_bounded():
    ring = obs_trace.SpanRing(16)
    with _tracing.span("obs.bound") as sp:
        pass
    for _ in range(100):
        ring.record(sp)
    assert len(ring) == 16
    assert ring.snapshot()[-1]["seq"] == 99
    ring.clear()
    assert len(ring) == 0


def test_diagnostics_toggle_rides_trace():
    assert obs_trace.trace_enabled()
    diag.set_enabled(False)
    try:
        assert not obs_trace.trace_enabled()
        n0 = len(obs_trace.ring())
        with _tracing.span("obs.disabled"):
            pass
        assert len(obs_trace.ring()) == n0   # sink unhooked
    finally:
        diag.set_enabled(True)
    assert obs_trace.trace_enabled()


# ------------------------------------------------------- export schema
def _assert_perfetto_clean(body):
    """The schema contract docs/observability.md declares."""
    doc = json.loads(body)
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    tids_named = set()
    for e in events:
        assert e["ph"] in ("X", "i", "M", "s", "f"), e
        if e["ph"] == "M":
            assert e["name"] in ("process_name", "thread_name")
            if e["name"] == "thread_name":
                tids_named.add(e["tid"])
            continue
        assert isinstance(e["ts"], (int, float))
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["name"] and "args" in e
            assert "span_id" in e["args"]
        if e["ph"] == "i":
            assert e["s"] == "t"
        if e["ph"] == "f":
            assert e["bp"] == "e" and "id" in e
    # every thread a slice/instant lands on has a named track
    used_tids = {e["tid"] for e in events
                 if e["ph"] in ("X", "i") and "tid" in e}
    assert used_tids <= tids_named
    return doc


def test_trace_export_cross_thread_flow_pair():
    obs_trace.install().clear()
    with _tracing.span("obs.flow.parent", category="test") as parent:
        captured = _tracing.current_span()

        def worker():
            with _tracing.span("obs.flow.child", category="test",
                               parent=captured):
                pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    doc = _assert_perfetto_clean(trace_export.dumps())
    events = doc["traceEvents"]
    child = [e for e in events
             if e["ph"] == "X" and e["name"] == "obs.flow.child"][0]
    par = [e for e in events
           if e["ph"] == "X" and e["name"] == "obs.flow.parent"][0]
    assert child["tid"] != par["tid"]
    assert child["args"]["parent_id"] == par["args"]["span_id"]
    flows = [e for e in events if e["ph"] in ("s", "f")
             and e["id"] == child["args"]["span_id"]]
    assert sorted(e["ph"] for e in flows) == ["f", "s"]
    s_ev = [e for e in flows if e["ph"] == "s"][0]
    f_ev = [e for e in flows if e["ph"] == "f"][0]
    assert s_ev["tid"] == par["tid"] and f_ev["tid"] == child["tid"]


def test_trace_export_merges_flight_instants():
    obs_trace.install().clear()
    diag.record("obstest", "ping", "detail=1")
    doc = _assert_perfetto_clean(trace_export.dumps())
    inst = [e for e in doc["traceEvents"]
            if e["ph"] == "i" and e["name"] == "obstest:ping"]
    assert inst and inst[0]["args"]["detail"] == "detail=1"


# --------------------------------------- decode attribution + timeline
def test_decode_streaming_trace_and_sampled_exemplar():
    """A streaming decode run produces (a) per-request exemplar
    timelines in causal order, (b) decode flight events, (c) a
    Perfetto-clean merged export with decode-thread tracks."""
    obs_trace.install().clear()
    with _session(trace_sample=1.0) as sess:
        res = sess.generate([3, 5], max_new_tokens=4, seed=0,
                            timeout=60)
        events = [e["event"] for e in res["trace"]]
        assert events[0] == "enqueue" and events[-1] == "retire"
        assert "admit" in events and events.count("token") == 4
        assert events.index("admit") < events.index("token")
        ts = [e["t"] for e in res["trace"]]
        assert ts == sorted(ts)
        assert sess.metrics.counter("decode_trace_sampled").value == 1
        panel = sess.debug_panel()["trace_sample"]
        assert panel["rate"] == 1.0 and panel["sampled"] == 1
        assert panel["held"] == 1
        # attribution series populated
        assert _hist(sess, "decode_ttft_ms").count == 1
        assert _hist(sess, "decode_tbt_ms").count == 3
        assert _hist(sess, "decode_phase_ms", phase="admission").count == 1
        assert _hist(sess, "decode_phase_ms", phase="step").count >= 4
        assert _hist(sess, "decode_phase_ms", phase="retire").count == 1
    flight = diag.recorder().snapshot(limit=2048)
    kinds = {(e["kind"], e["name"]) for e in flight}
    assert ("decode", "admit") in kinds
    assert ("decode", "step") in kinds
    assert ("decode", "token") in kinds
    doc = _assert_perfetto_clean(trace_export.dumps())
    xnames = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "warmup" in xnames    # the decode session's own spans landed


def test_decode_sampler_zero_rate_and_determinism():
    with _session(trace_sample=0.0) as sess:
        res = sess.generate([2], max_new_tokens=2, seed=1, timeout=60)
        assert "trace" not in res
        assert sess.metrics.counter("decode_trace_sampled").value == 0
    a = TraceSampler(rate=0.5, seed=7)
    b = TraceSampler(rate=0.5, seed=7)
    picks = [a.sampled(i) for i in range(1000)]
    assert picks == [b.sampled(i) for i in range(1000)]   # pure fn
    frac = sum(picks) / 1000.0
    assert 0.35 < frac < 0.65
    assert picks != [TraceSampler(rate=0.5, seed=8).sampled(i)
                     for i in range(1000)]                # seed matters
    assert all(TraceSampler(rate=1.0).sampled(i) for i in range(10))
    assert not any(TraceSampler(rate=0.0).sampled(i) for i in range(10))


def test_env_trace_sample_spec(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE_SAMPLE", "0.25:42")
    s = TraceSampler()
    assert s.rate == 0.25 and s.seed == 42
    monkeypatch.setenv("MXTPU_TRACE_SAMPLE", "garbage")
    s = TraceSampler()
    assert s.rate == 0.0


def test_injected_clock_ttft_tbt_stamped_at_retire():
    """The retire-time contract: with the session clock FROZEN through
    the whole decode, TTFT and every TBT read exactly 0.0 — and stay
    0.0 when the stream is drained only AFTER the clock has advanced.
    If the stamps happened at HTTP flush/stream read, the advanced
    clock would leak in."""
    clk = FakeClock(100.0)
    with _session(clock=clk, trace_sample=1.0) as sess:
        item = sess.generate_async([3, 5], max_new_tokens=4, seed=0,
                                   timeout=None, stream=True)
        res = item.wait(60)
        # tokens fully retired; NOW advance the clock, then drain
        clk.advance(50.0)
        drained = list(item.stream.events(timeout=30))
        assert any("done" in ev for ev in drained)
        assert len([ev for ev in drained if "token" in ev]) == 4
        ttft = _hist(sess, "decode_ttft_ms")
        tbt = _hist(sess, "decode_tbt_ms")
        assert ttft.count == 1 and ttft.max == 0.0
        assert tbt.count == 3 and tbt.max == 0.0
        adm = _hist(sess, "decode_phase_ms", phase="admission")
        assert adm.count == 1 and adm.max == 0.0    # same frozen clock
        # exemplar timeline carries the frozen stamp, not drain time
        assert all(e["t"] == 100.0 for e in res["trace"])


def test_injected_clock_chunked_prefill_multi_chunk_ttft():
    """kv layout: a prompt spanning >1 prefill chunk still stamps TTFT
    at the final chunk's token retire — 0.0 under a frozen clock, with
    ≥2 chunk dispatches recorded (so the multi-chunk path, not a
    single-shot prefill, produced the first token)."""
    clk = FakeClock(7.0)
    with _kv_session(clock=clk, trace_sample=1.0) as sess:
        res = sess.generate([5, 6, 7, 8], max_new_tokens=2, seed=0,
                            timeout=None)
        assert len(res["tokens"]) == 2
        assert sess.metrics.counter("decode_prefill_chunks").value >= 2
        ttft = _hist(sess, "decode_ttft_ms")
        assert ttft.count == 1 and ttft.max == 0.0
        pre = _hist(sess, "decode_phase_ms", phase="prefill")
        assert pre.count >= 2          # perf_counter-based, real time
        marks = [e["event"] for e in res["trace"]]
        assert marks.count("prefill_chunk") >= 2
        assert "block_alloc" in marks  # paged growth hit the timeline


# ------------------------------------------------------- HTTP endpoint
def test_debug_trace_endpoint_and_top_trace_out(tmp_path):
    obs_trace.install().clear()
    sess = _session(trace_sample=1.0)
    server = ServingHTTPServer(None, decode=sess, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        url = server.endpoint
        body = json.dumps({"prompt": [3, 5], "max_new_tokens": 3,
                           "seed": 1}).encode()
        req = urllib.request.Request(
            url + "/v1/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read())
        assert len(out["tokens"]) == 3
        with urllib.request.urlopen(url + "/debug/trace",
                                    timeout=30) as r:
            assert r.headers["Content-Type"] == "application/json"
            doc = _assert_perfetto_clean(r.read())
        xnames = {e["name"] for e in doc["traceEvents"]
                  if e["ph"] == "X"}
        assert "warmup" in xnames          # the session's own spans
        inames = {e["name"] for e in doc["traceEvents"]
                  if e["ph"] == "i"}
        assert "decode:step" in inames     # decode flight instants
        assert "decode:token" in inames
        # debug_state advertises the ring fill
        with urllib.request.urlopen(url + "/debug/state",
                                    timeout=30) as r:
            state = json.loads(r.read())
        assert state["trace"]["enabled"] is True
        assert state["trace"]["spans"] > 0
        # mxtpu_top --trace-out fetches the same body to a file
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        try:
            import mxtpu_top
            dest = str(tmp_path / "trace.json")
            rc = mxtpu_top.main([url, "--trace-out", dest])
            assert rc == 0
            with open(dest) as f:
                _assert_perfetto_clean(f.read())
            # the decode panel renders the new attribution lines
            metrics, state = mxtpu_top.snapshot(url)
            frame = "\n".join(mxtpu_top.render(metrics, state))
            assert "tbt" in frame and "decode phases:" in frame
            assert "sampled traces" in frame
        finally:
            sys.path.remove(os.path.join(ROOT, "tools"))
    finally:
        server.shutdown()
        sess.close()


# -------------------------------------------------------------- corpus
def _build_row(i):
    return {"id": i, "kind": "fused_step", "owner": "Module",
            "compile_ms": 12.5, "flops": 1e6 * i,
            "bytes_accessed": 2e6, "argument_bytes": 1024,
            "output_bytes": 256, "temp_bytes": 0, "n_devices": 1,
            "precision": "f32", "transforms": ["fuse_opt"]}


def test_corpus_round_trip_exact_rows(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXTPU_CORPUS_DIR", d)
    obs_corpus.reset()
    N, M = 3, 5
    for i in range(N):
        assert obs_corpus.record_build(_build_row(i))
    for j in range(M):
        assert obs_corpus.record_service(
            "serving", 10.0 + j, bucket=8 if j % 2 else 1, rows=4)
    obs_corpus.reset()
    rows = obs_corpus.load(d)
    assert len(rows) == N + M
    builds = [r for r in rows if r["row"] == "build"]
    services = [r for r in rows if r["row"] == "service"]
    assert len(builds) == N and len(services) == M
    for r in rows:
        assert r["v"] == obs_corpus.SCHEMA_VERSION and r["t"] > 0
    assert builds[0]["kind"] == "fused_step"
    assert builds[0]["knobs"]["values"]        # resolved knob vector
    assert "registry_version" in builds[0]["knobs"]
    assert isinstance(builds[0]["pipeline"], list)
    assert services[0]["source"] == "serving"
    assert services[0]["bucket"] == 1 and services[0]["rows"] == 4


def test_corpus_torn_tail_tolerated_mid_file_raises(tmp_path,
                                                    monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXTPU_CORPUS_DIR", d)
    obs_corpus.reset()
    for j in range(4):
        obs_corpus.record_service("decode_step", 1.0 + j, rows=2)
    obs_corpus.reset()
    path = obs_corpus.corpus_path(d)
    # writer killed mid-append: a torn, newline-less trailing fragment
    with open(path, "a") as f:
        f.write('{"v": 1, "row": "service", "source": "decode_st')
    rows = obs_corpus.load(d)
    assert len(rows) == 4              # every FULLY appended row survives
    # mid-file garbage is real corruption and must raise
    bad = os.path.join(d, "zz_corrupt.jsonl")
    with open(bad, "w") as f:
        f.write('{"v": 1, "row": "service", "source": "a", "ms": 1}\n')
        f.write("NOT JSON\n")
        f.write('{"v": 1, "row": "service", "source": "b", "ms": 2}\n')
    with pytest.raises(ValueError):
        obs_corpus.load(d)


def test_corpus_summarize_reproduces_service_line(tmp_path,
                                                  monkeypatch):
    from mxtpu.tune.cost import ServiceLine
    d = str(tmp_path)
    monkeypatch.setenv("MXTPU_CORPUS_DIR", d)
    obs_corpus.reset()
    measured = {1: [2.0, 2.2, 1.8], 8: [5.0, 5.4], 32: [14.0]}
    for b, costs in measured.items():
        for ms in costs:
            obs_corpus.record_service("serving", ms, bucket=b)
    obs_corpus.record_service("fit_step", 33.0, rows=64)
    obs_corpus.reset()
    out = obs_corpus.summarize(dirpath=d)
    assert out["services"] == 7 and out["builds"] == 0
    want_costs = {b: {"exec_ms": sum(c) / len(c)}
                  for b, c in measured.items()}
    assert out["bucket_costs"] == want_costs
    assert out["bucket_counts"] == {1: 3, 8: 2, 32: 1}
    assert out["source_ms_mean"]["fit_step"] == 33.0
    # offline == online: the exact fit tune.search runs in-process
    assert out["service_line"] == ServiceLine.fit(want_costs).to_dict()


def test_corpus_populated_by_decode_and_build_seams(tmp_path,
                                                    monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXTPU_CORPUS_DIR", d)
    obs_corpus.reset()
    try:
        with _session() as sess:
            sess.generate([3, 5], max_new_tokens=3, seed=0, timeout=60)
        rows = obs_corpus.load(d)
        sources = {r.get("source") for r in rows
                   if r["row"] == "service"}
        assert "decode_step" in sources
        assert "decode_request" in sources
        steps = [r for r in rows if r.get("source") == "decode_step"]
        assert all(r["ms"] > 0 and r["rows"] >= 1 for r in steps)
    finally:
        obs_corpus.reset()


def test_corpus_disabled_is_free(monkeypatch):
    monkeypatch.delenv("MXTPU_CORPUS_DIR", raising=False)
    obs_corpus.reset()
    assert not obs_corpus.enabled()
    assert obs_corpus.record_service("serving", 1.0) is False
    assert obs_corpus.record_build(_build_row(0)) is False
    assert obs_corpus.load(None) == []


# ------------------------------------------------------------ CI tools
def test_check_bench_basis_flags_missing_basis(tmp_path):
    tool = os.path.join(ROOT, "tools", "check_bench_basis.py")
    # a verdict without any basis block fails
    with open(str(tmp_path / "BENCH_bad.json"), "w") as f:
        json.dump({"speedup": 3.2, "pass": True}, f)
    proc = subprocess.run([sys.executable, tool, "--root",
                           str(tmp_path)],
                          capture_output=True, text=True)
    assert proc.returncode == 1 and "BENCH_bad.json" in proc.stdout
    # raw run logs and basis-carrying verdicts pass
    with open(str(tmp_path / "BENCH_bad.json"), "w") as f:
        json.dump({"speedup": 3.2, "pass": True,
                   "verdict_basis": "min-of-5 trials, n=4096"}, f)
    with open(str(tmp_path / "BENCH_r99.json"), "w") as f:
        json.dump({"cmd": "python x.py", "rc": 0, "tail": ""}, f)
    proc = subprocess.run([sys.executable, tool, "--root",
                           str(tmp_path)],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
