"""In-graph caffe plugin (mxtpu/caffe_bridge.py): CaffeOp/CaffeLoss
symbols run caffe-layer semantics inside the graph with gradients —
parity with the reference plugin/caffe (caffe_op.cc, caffe_loss.cc) and
its example/caffe/caffe_net.py usage."""
import numpy as np
import pytest

import mxtpu as mx


def test_caffe_op_innerproduct_matches_fullyconnected():
    """CaffeOp InnerProduct forward == native FullyConnected given the
    same weights."""
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6).astype("float32")
    w = rng.randn(5, 6).astype("float32")
    b = rng.randn(5).astype("float32")

    data = mx.sym.Variable("data")
    cop = mx.sym.CaffeOp(
        data_0=data, num_weight=2, name="ip",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: 5}}')
    exe = cop.simple_bind(ctx=mx.cpu(), data=(4, 6))
    args = dict(zip(cop.list_arguments(), exe.arg_arrays))
    args["data"][:] = mx.nd.array(x)
    args["ip_0_weight"][:] = mx.nd.array(w)
    args["ip_1_bias"][:] = mx.nd.array(b)
    out = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out, x @ w.T + b, rtol=1e-5, atol=1e-5)


def test_caffe_op_conv_pool_forward():
    """CaffeOp Convolution/Pooling agree with torch reference math
    (caffe ceil-mode pooling)."""
    import torch
    import torch.nn.functional as F

    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 7, 7).astype("float32")
    w = rng.randn(4, 3, 3, 3).astype("float32")
    b = rng.randn(4).astype("float32")

    data = mx.sym.Variable("data")
    conv = mx.sym.CaffeOp(
        data_0=data, num_weight=2, name="cv",
        prototxt='layer{type:"Convolution" convolution_param '
                 '{num_output: 4 kernel_size: 3 stride: 2 pad: 1}}')
    pool = mx.sym.CaffeOp(
        data_0=conv, name="pl",
        prototxt='layer{type:"Pooling" pooling_param '
                 '{pool: MAX kernel_size: 2 stride: 2}}')
    exe = pool.simple_bind(ctx=mx.cpu(), data=(2, 3, 7, 7))
    args = dict(zip(pool.list_arguments(), exe.arg_arrays))
    args["data"][:] = mx.nd.array(x)
    args["cv_0_weight"][:] = mx.nd.array(w)
    args["cv_1_bias"][:] = mx.nd.array(b)
    out = exe.forward(is_train=False)[0].asnumpy()

    ref = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                   torch.from_numpy(b), stride=2, padding=1)
    ref = F.max_pool2d(ref, 2, 2, 0, ceil_mode=True).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_caffe_loss_gradient():
    """CaffeLoss SoftmaxWithLoss: loss value is mean cross-entropy and
    the data gradient is grad_scale * (softmax - onehot) / n."""
    rng = np.random.RandomState(2)
    n, k = 6, 4
    logits = rng.randn(n, k).astype("float32")
    labels = rng.randint(0, k, n).astype("float32")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    loss = mx.sym.CaffeLoss(
        data, label, prototxt='layer{type:"SoftmaxWithLoss"}',
        grad_scale=2.0, name="closs")
    exe = loss.simple_bind(ctx=mx.cpu(), data=(n, k), label=(n,),
                           grad_req={"data": "write", "label": "null"})
    args = dict(zip(loss.list_arguments(), exe.arg_arrays))
    args["data"][:] = mx.nd.array(logits)
    args["label"][:] = mx.nd.array(labels)
    out = exe.forward(is_train=True)[0].asnumpy()

    e = np.exp(logits - logits.max(1, keepdims=True))
    p = e / e.sum(1, keepdims=True)
    expect_loss = -np.log(p[np.arange(n), labels.astype(int)]).mean()
    np.testing.assert_allclose(out.reshape(()), expect_loss, rtol=1e-5)

    exe.backward()
    onehot = np.zeros((n, k), dtype="float32")
    onehot[np.arange(n), labels.astype(int)] = 1.0
    expect_grad = 2.0 * (p - onehot) / n
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               expect_grad, rtol=1e-4, atol=1e-6)


def test_caffe_net_trains():
    """The reference example/caffe/caffe_net.py MLP shape — CaffeOp
    InnerProduct+TanH stack under a native SoftmaxOutput — trains to
    >0.9 on separable blobs through Module.fit, caffe blobs updated by
    the framework optimizer like any weight."""
    rng = np.random.RandomState(3)
    n, dim, k = 256, 10, 3
    centers = rng.randn(k, dim) * 3
    y = rng.randint(0, k, n)
    X = (centers[y] + rng.randn(n, dim)).astype("float32")

    data = mx.sym.Variable("data")
    fc1 = mx.sym.CaffeOp(
        data_0=data, num_weight=2, name="fc1",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: 32}}')
    act1 = mx.sym.CaffeOp(
        data_0=fc1, name="act1", prototxt='layer{type:"TanH"}')
    fc2 = mx.sym.CaffeOp(
        data_0=act1, num_weight=2, name="fc2",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: %d}}' % k)
    net = mx.sym.SoftmaxOutput(fc2, name="softmax")

    it = mx.io.NDArrayIter(X, y.astype("float32"), batch_size=64,
                           shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9})
    acc = dict(mod.score(it, mx.metric.Accuracy()))["accuracy"]
    assert acc > 0.9, "caffe-op net reached only %.3f" % acc


def test_caffe_op_symbol_serializes():
    """The prototxt rides as a symbol attr: JSON round-trip preserves an
    executable CaffeOp graph."""
    data = mx.sym.Variable("data")
    cop = mx.sym.CaffeOp(
        data_0=data, num_weight=2, name="ip",
        prototxt='layer{type:"InnerProduct" '
                 'inner_product_param{num_output: 3}}')
    back = mx.sym.load_json(cop.tojson())
    assert back.list_arguments() == cop.list_arguments()
    exe = back.simple_bind(ctx=mx.cpu(), data=(2, 5))
    out = exe.forward(is_train=False)[0]
    assert out.shape == (2, 3)


def test_caffe_op_errors():
    data = mx.sym.Variable("data")
    with pytest.raises(mx.MXNetError):
        mx.sym.CaffeOp(data_0=data, prototxt="not a layer")
    with pytest.raises(mx.MXNetError):
        mx.sym.CaffeOp(prototxt='layer{type:"TanH"}')
    sym2 = mx.sym.CaffeOp(
        data_0=data, name="bad",
        prototxt='layer{type:"NoSuchLayer"}')
    with pytest.raises(Exception):
        sym2.simple_bind(ctx=mx.cpu(), data=(2, 3))
