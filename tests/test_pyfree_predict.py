"""Python-free predict runner (VERDICT r2 #6, amalgamation parity): a
trained model exports to a frozen GraphDef and a plain C binary — linking
ONLY the TF C API, verified to pull in no libpython — reproduces the
Python forward outputs. Reference role: amalgamation/README.md's
libmxnet_predict + c_predict_api.h four-call flow."""
import json
import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tf_dir():
    try:
        import tensorflow as tf
        return os.path.dirname(tf.__file__)
    except Exception:
        return None


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_c_binary_predicts_without_python(tmp_path):
    tfdir = _tf_dir()
    if tfdir is None or not os.path.exists(
            os.path.join(tfdir, "libtensorflow_cc.so.2")):
        pytest.skip("no libtensorflow_cc available")

    import mxtpu as mx
    from mxtpu.export import export_frozen_graph

    # small trained-ish conv net (random weights suffice: the contract is
    # output EQUALITY between the Python forward and the C binary)
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    rng = np.random.RandomState(0)
    shapes, _, _ = net.infer_shape(data=(1, 1, 8, 8))
    args = {}
    for n, s in zip(net.list_arguments(), shapes):
        if n in ("data", "softmax_label"):
            continue
        args[n] = mx.nd.array(rng.randn(*s).astype("float32") * 0.3)

    pb = str(tmp_path / "model.pb")
    export_frozen_graph(net, args, {}, {"data": (1, 1, 8, 8)}, pb)
    meta = json.load(open(pb + ".json"))
    in_tensor = meta["inputs"][0]["tensor"]
    out_tensor = meta["outputs"][0]["tensor"]

    # reference outputs from the Python executor
    x = rng.rand(1, 1, 8, 8).astype("float32")
    ex = net.simple_bind(mx.cpu(), data=(1, 1, 8, 8), grad_req="null")
    for n, v in args.items():
        ex.arg_dict[n][:] = v
    ex.arg_dict["data"][:] = mx.nd.array(x)
    want = ex.forward(is_train=False)[0].asnumpy().ravel()

    (tmp_path / "input.bin").write_bytes(x.tobytes())

    exe_path = str(tmp_path / "tf_predict")
    r = subprocess.run(
        ["gcc", "-std=c99", "-I", os.path.join(tfdir, "include"),
         os.path.join(REPO, "src", "predict", "tf_predict.c"),
         os.path.join(tfdir, "libtensorflow_cc.so.2"),
         os.path.join(tfdir, "libtensorflow_framework.so.2"),
         "-Wl,-rpath," + tfdir, "-o", exe_path],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    # the binary must not link CPython — the whole point of the artifact
    ldd = subprocess.run(["ldd", exe_path], capture_output=True, text=True)
    assert "libpython" not in ldd.stdout, ldd.stdout

    out = subprocess.run(
        [exe_path, pb, in_tensor, out_tensor, str(tmp_path / "input.bin"),
         "64", "3"],
        capture_output=True, text=True, timeout=600,
        env={k: v for k, v in os.environ.items()
             if not k.startswith("PYTHON")})
    assert out.returncode == 0, out.stdout + out.stderr
    assert "PREDICT_OK" in out.stdout
    got = np.array([float(ln.split()[1]) for ln in out.stdout.splitlines()
                    if ln.startswith("OUT ")], dtype=np.float32)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
