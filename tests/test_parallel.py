"""Mesh / data-parallel / ring-attention tests on the virtual 8-device CPU
mesh (model: reference dist tests run as multi-process on one host,
SURVEY.md §4; here multi-device XLA collectives replace processes)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxtpu as mx
from mxtpu import nd, sym
from mxtpu.parallel import (DataParallelTrainer, blockwise_attention,
                            make_mesh, ring_attention)


def test_mesh_creation():
    mesh = make_mesh()
    assert len(mesh.devices.reshape(-1)) == 8
    mesh2 = make_mesh(shape=(4, 2))
    assert mesh2.axis_names == ("data", "model")


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_dp_trainer_step_and_convergence():
    mx.random.seed(1)  # deterministic init regardless of suite order
    mesh = make_mesh(shape=(8,))
    trainer = DataParallelTrainer(
        _mlp(), mesh=mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.5, "momentum": 0.9,
                          "rescale_grad": 1.0 / 64})
    trainer.init({"data": (64, 16), "softmax_label": (64,)})
    rng = np.random.RandomState(0)
    centers = rng.randn(4, 16) * 3
    cls = rng.randint(0, 4, 512)
    X = (centers[cls] + rng.randn(512, 16)).astype("float32")
    y = cls.astype("float32")
    for epoch in range(15):
        for i in range(0, 512, 64):
            trainer.step({"data": X[i:i + 64],
                          "softmax_label": y[i:i + 64]})
    outs = trainer.step({"data": X[:64], "softmax_label": y[:64]})
    acc = (np.asarray(outs[0]).argmax(axis=1) == y[:64]).mean()
    assert acc > 0.9, "dp trainer accuracy %f" % acc


def test_dp_trainer_tensor_sharding():
    """2-D mesh: data axis 4, model axis 2 with sharded params."""
    mesh = make_mesh(shape=(4, 2))
    trainer = DataParallelTrainer(
        _mlp(), mesh=mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1},
        shard_params=True)
    trainer.init({"data": (16, 16), "softmax_label": (16,)})
    X = np.random.randn(16, 16).astype("f4")
    y = np.zeros(16, dtype="f4")
    outs = trainer.step({"data": X, "softmax_label": y})
    assert np.asarray(outs[0]).shape == (16, 4)


def test_dp_matches_single_device():
    """Grad math identical to single-executor path after one step."""
    mesh = make_mesh(shape=(8,))
    net = _mlp()
    tr = DataParallelTrainer(net, mesh=mesh, optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1,
                                               "rescale_grad": 1.0 / 16})
    tr.init({"data": (16, 16), "softmax_label": (16,)})
    # copy initial params into an executor
    ex = net.simple_bind(ctx=mx.cpu(), data=(16, 16))
    for name, v in tr.params.items():
        ex.arg_dict[name]._data = jnp.asarray(np.asarray(v))
    X = np.random.RandomState(1).randn(16, 16).astype("f4")
    y = np.zeros(16, dtype="f4")
    ex.arg_dict["data"][:] = nd.array(X)
    ex.arg_dict["softmax_label"][:] = nd.array(y)
    ex.forward(is_train=True)
    ex.backward()
    tr.step({"data": X, "softmax_label": y})
    for name in ("fc1_weight", "fc2_weight"):
        manual = ex.arg_dict[name].asnumpy() - \
            0.1 * (1.0 / 16) * ex.grad_dict[name].asnumpy()
        assert np.allclose(np.asarray(tr.params[name]), manual, atol=1e-4), name


def test_blockwise_attention_matches_exact():
    B, T, H, D = 2, 64, 2, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    k = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    v = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))

    def exact(q, k, v, causal=False):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.tril(np.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    out = blockwise_attention(q, k, v, block_size=16)
    assert np.allclose(np.asarray(out), np.asarray(exact(q, k, v)), atol=1e-4)
    out_c = blockwise_attention(q, k, v, block_size=16, causal=True)
    assert np.allclose(np.asarray(out_c),
                       np.asarray(exact(q, k, v, causal=True)), atol=1e-4)


def test_ring_attention_matches_exact():
    mesh = make_mesh(shape=(1, 8), axis_names=("data", "seq"))
    B, T, H, D = 2, 64, 2, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    k = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    v = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    exact = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
    out = ring_attention(q, k, v, mesh=mesh, axis_name="seq")
    assert np.allclose(np.asarray(out), np.asarray(exact), atol=1e-4)
    # causal
    mask = np.tril(np.ones((T, T), bool))
    sc = jnp.where(mask[None, None], s, -1e30)
    exact_c = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, -1), v)
    out_c = ring_attention(q, k, v, mesh=mesh, axis_name="seq", causal=True)
    assert np.allclose(np.asarray(out_c), np.asarray(exact_c), atol=1e-4)


def test_ulysses_attention_matches_exact():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxtpu.parallel import (blockwise_attention, make_mesh,
                                ulysses_attention)

    mesh = make_mesh(shape=(4,), axis_names=("seq",))
    mkx = lambda s: jnp.asarray(
        np.random.RandomState(s).randn(2, 64, 8, 16).astype("float32") * 0.3)
    q, k, v = mkx(0), mkx(1), mkx(2)
    sh = NamedSharding(mesh, P(None, "seq", None, None))
    qd, kd, vd = (jax.device_put(x, sh) for x in (q, k, v))
    for causal in (False, True):
        out = ulysses_attention(qd, kd, vd, mesh=mesh, causal=causal)
        ref = blockwise_attention(q, k, v, block_size=32, causal=causal)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-4


def test_group2ctx_model_parallel():
    """Model parallelism across two CPU devices via AttrScope ctx_group +
    group2ctx bind (parity: tests/python/unittest/test_model_parallel.py,
    which also uses two CPU contexts)."""
    import numpy as np
    import mxtpu as mx

    with mx.AttrScope(ctx_group="dev1"):
        data = mx.sym.Variable("data")
        fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        act1 = mx.sym.Activation(fc1, act_type="relu")
    with mx.AttrScope(ctx_group="dev2"):
        fc2 = mx.sym.FullyConnected(act1, num_hidden=4, name="fc2")
        net = mx.sym.Activation(fc2, act_type="tanh")

    rng = np.random.RandomState(0)
    shapes, _, _ = net.infer_shape(data=(2, 6))
    args = {n: mx.nd.array(rng.rand(*s).astype("float32") * 0.1)
            for n, s in zip(net.list_arguments(), shapes)}
    exe = net.bind(mx.cpu(), args,
                   group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    exe.forward(is_train=False)
    split_out = exe.outputs[0].asnumpy()

    exe_single = net.bind(mx.cpu(), args)
    exe_single.forward(is_train=False)
    np.testing.assert_allclose(split_out, exe_single.outputs[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_parallel_matches_sequential():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.pipeline import pipeline_apply, stack_stage_params

    n_stages, batch, d = 4, 8, 16
    mesh = make_mesh(shape=(n_stages,), axis_names=("pipe",))
    rng = np.random.RandomState(0)
    stage_params = [{"w": jnp.asarray(rng.randn(d, d).astype("float32")
                                      * 0.3),
                     "b": jnp.asarray(rng.randn(d).astype("float32") * 0.1)}
                    for _ in range(n_stages)]

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    x = jnp.asarray(rng.randn(batch, d).astype("float32"))
    stacked = stack_stage_params(stage_params)
    out = pipeline_apply(stage_fn, stacked, x, mesh=mesh,
                         num_microbatches=4)
    ref = x
    for p in stage_params:
        ref = stage_fn(p, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_moe_expert_parallel():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.moe import moe_apply

    n_dev, n_experts, tokens, d = 4, 8, 32, 16
    mesh = make_mesh(shape=(n_dev,), axis_names=("expert",))
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(n_experts, d, d)
                               .astype("float32") * 0.3)}
    # shard leading expert axis: reshape to per-device groups
    params_sharded = {"w": params["w"].reshape(n_dev, n_experts // n_dev,
                                               d, d)}
    # shard_map expects the leading axis to be the mesh axis; flatten local
    params_in = {"w": params["w"]}

    def expert_fn(p, x):
        return jnp.tanh(x @ p["w"])

    x = jnp.asarray(rng.randn(tokens, d).astype("float32"))
    gates = jnp.asarray(rng.randn(tokens, n_experts).astype("float32"))
    out = moe_apply(expert_fn, {"w": params["w"]}, gates, x, mesh=mesh,
                    capacity_factor=8.0)  # big capacity: no overflow

    probs = np.asarray(jax.nn.softmax(gates, axis=-1))
    choice = probs.argmax(-1)
    ref = np.zeros_like(np.asarray(x))
    for t in range(tokens):
        e = int(choice[t])
        ref[t] = np.tanh(np.asarray(x)[t] @ np.asarray(params["w"][e])) \
            * probs[t, e]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_moe_topk_expert_parallel():
    """Top-2 expert-parallel MoE on the 8-device mesh: outputs must equal a
    single-device dense emulation of the same routing, and the aux loss
    matches the Switch formula."""
    import jax
    import jax.numpy as jnp

    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.moe import load_balancing_loss, moe_apply_topk

    n_dev = 4
    mesh = make_mesh(shape=(n_dev,), axis_names=("expert",),
                     devices=jax.devices()[:n_dev])
    tokens, d, n_experts, k = 16, 8, 8, 2
    rng = np.random.RandomState(0)
    W = jnp.asarray(rng.randn(n_experts, d, d).astype("float32") * 0.3)
    gate = jnp.asarray(rng.randn(tokens, n_experts).astype("float32"))
    x = jnp.asarray(rng.randn(tokens, d).astype("float32"))

    def expert_fn(w, t):  # t: (capacity, d)
        return jnp.tanh(t @ w)

    out, aux = moe_apply_topk(expert_fn, W, gate, x, k=k, mesh=mesh,
                              capacity_factor=8.0)  # ample: nothing drops

    # dense emulation (no capacity pressure): same top-k + renormalized mix
    probs = jax.nn.softmax(gate, axis=-1)
    topv, topi = jax.lax.top_k(probs, k)
    wts = topv / topv.sum(axis=-1, keepdims=True)
    want = jnp.zeros_like(x)
    for j in range(k):
        per_tok = jax.vmap(lambda e, t: jnp.tanh(t @ W[e]))(topi[:, j], x)
        want = want + wts[:, j][:, None] * per_tok
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    want_aux = load_balancing_loss(gate, jax.nn.one_hot(topi[:, 0],
                                                        n_experts))
    np.testing.assert_allclose(float(aux), float(want_aux), rtol=1e-5)


def test_moe_topk_capacity_drops():
    """With capacity 1 per expert, overflow decisions drop and fully
    dropped tokens pass through unchanged."""
    import jax
    import jax.numpy as jnp

    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.moe import moe_apply_topk

    mesh = make_mesh(shape=(2,), axis_names=("expert",),
                     devices=jax.devices()[:2])
    tokens, d, n_experts = 8, 4, 2
    # all tokens prefer expert 0, second choice expert 1
    gate = jnp.tile(jnp.asarray([[4.0, 2.0]]), (tokens, 1))
    x = jnp.asarray(np.random.RandomState(1).randn(tokens, d)
                    .astype("float32"))
    W = jnp.zeros((n_experts, d, d), jnp.float32)  # experts output tanh(0)=0

    def expert_fn(w, t):
        return t @ w  # zeros

    out, _ = moe_apply_topk(expert_fn, W, gate, x, k=2, mesh=mesh,
                            capacity_factor=1.0 / 8)  # capacity = 1
    out = np.asarray(out)
    # token 0 routed (expert0 slot0, expert1 slot0) -> combined zeros
    np.testing.assert_allclose(out[0], 0.0, atol=1e-6)
    # later tokens overflowed everywhere -> passthrough
    np.testing.assert_allclose(out[-1], np.asarray(x)[-1], rtol=1e-6)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_ring_and_ulysses_attention_gradients():
    """Backward through the sequence-parallel attentions must match the
    exact-attention gradients (training path correctness, not just fwd)."""
    import jax
    import jax.numpy as jnp

    from mxtpu.parallel import ring_attention, ulysses_attention

    mesh = make_mesh(shape=(1, 4), axis_names=("data", "seq"))
    B, T, H, D = 1, 32, 4, 8
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    k = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    v = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))
    w = jnp.asarray(rng.randn(B, T, H, D).astype("f4"))  # cotangent probe

    def exact_loss(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        mask = np.tril(np.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
        return jnp.sum(out * w)

    want = jax.grad(exact_loss, argnums=(0, 1, 2))(q, k, v)

    for fn in (ring_attention, ulysses_attention):
        def loss(q, k, v, fn=fn):
            return jnp.sum(fn(q, k, v, mesh=mesh, axis_name="seq",
                              causal=True) * w)
        got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g, wnt, nm in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(wnt), atol=2e-3,
                err_msg="%s grad wrt %s" % (fn.__name__, nm))


def test_pipeline_parallel_gradients():
    """Backward through the GPipe schedule must match serial-stage grads
    (wrt both input and the stacked stage parameters)."""
    import jax
    import jax.numpy as jnp

    from mxtpu.parallel import pipeline_apply, stack_stage_params

    n_stages, mb = 4, 2
    mesh = make_mesh(shape=(n_stages,), axis_names=("pipe",))
    rng = np.random.RandomState(5)
    Ws = [jnp.asarray(rng.randn(6, 6).astype("f4") * 0.4)
          for _ in range(n_stages)]
    stacked = stack_stage_params([{"w": w} for w in Ws])
    x = jnp.asarray(rng.randn(8, 6).astype("f4"))
    probe = jnp.asarray(rng.randn(8, 6).astype("f4"))

    def stage_fn(params, t):
        return jnp.tanh(t @ params["w"])

    def serial_loss(stacked, x):
        h = x
        for i in range(n_stages):
            h = stage_fn(jax.tree.map(lambda p: p[i], stacked), h)
        return jnp.sum(h * probe)

    def pipe_loss(stacked, x):
        out = pipeline_apply(stage_fn, stacked, x, mesh=mesh,
                             num_microbatches=8 // mb)
        return jnp.sum(out * probe)

    want_p, want_x = jax.grad(serial_loss, argnums=(0, 1))(stacked, x)
    got_p, got_x = jax.grad(pipe_loss, argnums=(0, 1))(stacked, x)
    np.testing.assert_allclose(np.asarray(got_x), np.asarray(want_x),
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(got_p["w"]),
                               np.asarray(want_p["w"]), atol=2e-4)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_moe_topk_gradients():
    """Backward through the expert-parallel exchange must match the dense
    emulation's gradients wrt inputs, gate logits, and expert weights."""
    import jax
    import jax.numpy as jnp

    from mxtpu.parallel import make_mesh, moe_apply_topk

    n_dev, tokens, d, n_experts, k = 4, 12, 6, 8, 2
    mesh = make_mesh(shape=(n_dev,), axis_names=("expert",),
                     devices=jax.devices()[:n_dev])
    rng = np.random.RandomState(7)
    W = jnp.asarray(rng.randn(n_experts, d, d).astype("f4") * 0.3)
    gate = jnp.asarray(rng.randn(tokens, n_experts).astype("f4"))
    x = jnp.asarray(rng.randn(tokens, d).astype("f4"))
    probe = jnp.asarray(rng.randn(tokens, d).astype("f4"))

    def expert_fn(w, t):
        return jnp.tanh(t @ w)

    def par_loss(W, gate, x):
        out, aux = moe_apply_topk(expert_fn, W, gate, x, k=k, mesh=mesh,
                                  capacity_factor=8.0)
        return jnp.sum(out * probe) + 0.01 * aux

    def dense_loss(W, gate, x):
        probs = jax.nn.softmax(gate, axis=-1)
        topv, topi = jax.lax.top_k(probs, k)
        wts = topv / topv.sum(axis=-1, keepdims=True)
        out = jnp.zeros_like(x)
        for j in range(k):
            per = jax.vmap(lambda e, t: jnp.tanh(t @ W[e]))(topi[:, j], x)
            out = out + wts[:, j][:, None] * per
        from mxtpu.parallel import load_balancing_loss
        aux = load_balancing_loss(gate, jax.nn.one_hot(topi[:, 0],
                                                       n_experts))
        return jnp.sum(out * probe) + 0.01 * aux

    got = jax.grad(par_loss, argnums=(0, 1, 2))(W, gate, x)
    want = jax.grad(dense_loss, argnums=(0, 1, 2))(W, gate, x)
    for g, wnt, nm in zip(got, want, ("W", "gate", "x")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wnt),
                                   rtol=2e-3, atol=2e-4,
                                   err_msg="moe grad wrt %s" % nm)


def test_dp_weight_update_sharding_matches_replicated():
    """ZeRO-style weight-update sharding (shard_update=True): optimizer
    state shards over the data axis, numbers match the replicated path."""
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(shape=(8,))
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=512, name="fc1")  # dim0 % 8 == 0
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(h, name="softmax")
    X = np.random.RandomState(2).randn(64, 16).astype("f4")
    y = np.zeros(64, dtype="f4")

    results = {}
    for flag in (False, True):
        mx.random.seed(8)
        tr = DataParallelTrainer(net, mesh=mesh, optimizer="sgd",
                                 optimizer_params={"learning_rate": 0.1,
                                                   "momentum": 0.9,
                                                   "rescale_grad": 1.0 / 64},
                                 shard_update=flag)
        tr.init({"data": (64, 16), "softmax_label": (64,)})
        for _ in range(3):
            tr.step({"data": X, "softmax_label": y})
        results[flag] = {n: np.asarray(v) for n, v in tr.params.items()}
        if flag:
            # big opt-state leaves actually sharded over 'data'
            st = tr._opt_state["fc1_weight"]
            spec = st.sharding.spec
            assert spec and spec[0] == "data", spec

    for n in results[False]:
        np.testing.assert_allclose(results[True][n], results[False][n],
                                   rtol=2e-4, atol=2e-5, err_msg=n)


def test_composed_dp_pp_matches_sequential_and_trains():
    """VERDICT r2 #10: the parallelism axes must COMPOSE. dp x pp on a
    ('data','pipe') 2-D mesh: batch shards over 'data', stage params over
    'pipe'. Checks (a) numerical equality with the sequential stage chain
    and (b) loss moves when training THROUGH the composed program."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.pipeline import pipeline_apply, stack_stage_params

    n_pipe, d = 4, 8
    mesh = make_mesh(shape=(2, n_pipe), axis_names=("data", "pipe"))
    rng = np.random.RandomState(1)
    stage_params = [{"w": jnp.asarray(rng.randn(d, d).astype("float32")
                                      * 0.4),
                     "b": jnp.zeros((d,), jnp.float32)}
                    for _ in range(n_pipe)]

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    batch = 16  # 8 per data row, 2 microbatches of 4
    x = jnp.asarray(rng.randn(batch, d).astype("float32"))
    stacked = stack_stage_params(stage_params)

    # (a) equality with the sequential chain
    out = pipeline_apply(stage_fn, stacked, x, mesh=mesh,
                         num_microbatches=2, batch_axis="data")
    ref = x
    for p in stage_params:
        ref = stage_fn(p, ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    # (b) train through the composed program: loss must fall
    y = jnp.asarray(rng.randn(batch, d).astype("float32"))

    def loss_fn(params):
        o = pipeline_apply(stage_fn, params, x, mesh=mesh,
                           num_microbatches=2, batch_axis="data")
        return jnp.mean((o - y) ** 2)

    @jax.jit
    def step(params):
        l, g = jax.value_and_grad(loss_fn)(params)
        return l, jax.tree.map(lambda p, gr: p - 0.3 * gr, params, g)

    params = stacked
    losses = []
    for _ in range(6):
        l, params = step(params)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9, losses
