"""Operator tests vs numpy oracles + finite-difference gradient checks
(model: reference tests/python/unittest/test_operator.py + test_utils.py
check_numeric_gradient/check_symbolic_forward)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, sym
from mxtpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                              check_symbolic_forward)


def test_unary_vs_numpy():
    x = np.random.uniform(0.1, 2.0, (3, 4)).astype("float32")
    cases = [("sqrt", np.sqrt), ("exp", np.exp), ("log", np.log),
             ("tanh", np.tanh), ("abs", np.abs), ("square", np.square),
             ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
             ("relu", lambda v: np.maximum(v, 0)), ("cos", np.cos)]
    for name, ref in cases:
        out = getattr(nd, name)(nd.array(x)).asnumpy()
        assert np.allclose(out, ref(x), atol=1e-5), name


def test_fully_connected_forward():
    x = np.random.randn(4, 5).astype("float32")
    w = np.random.randn(3, 5).astype("float32")
    b = np.random.randn(3).astype("float32")
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                            num_hidden=3)
    assert np.allclose(out.asnumpy(), x @ w.T + b, atol=1e-5)


def test_convolution_forward():
    # compare against explicit loop conv
    x = np.random.randn(1, 2, 5, 5).astype("float32")
    w = np.random.randn(3, 2, 3, 3).astype("float32")
    out = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=3, no_bias=True).asnumpy()
    ref = np.zeros((1, 3, 3, 3), dtype="float32")
    for f in range(3):
        for i in range(3):
            for j in range(3):
                ref[0, f, i, j] = np.sum(x[0, :, i:i + 3, j:j + 3] * w[f])
    assert np.allclose(out, ref, atol=1e-4)


def test_pooling_forward():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    mp = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                    pool_type="max").asnumpy()
    assert np.allclose(mp, [[[[5, 7], [13, 15]]]])
    ap = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                    pool_type="avg").asnumpy()
    assert np.allclose(ap, [[[[2.5, 4.5], [10.5, 12.5]]]])
    gp = nd.Pooling(nd.array(x), global_pool=True, pool_type="max").asnumpy()
    assert np.allclose(gp, [[[[15]]]])


def test_softmax_forward():
    x = np.random.randn(3, 5).astype("float32")
    out = nd.softmax(nd.array(x)).asnumpy()
    e = np.exp(x - x.max(axis=1, keepdims=True))
    assert np.allclose(out, e / e.sum(axis=1, keepdims=True), atol=1e-5)


def test_batchnorm_train_eval():
    x = np.random.randn(8, 3, 4, 4).astype("float32")
    g = np.ones(3, dtype="float32")
    b = np.zeros(3, dtype="float32")
    mm = np.zeros(3, dtype="float32")
    mv = np.ones(3, dtype="float32")
    with mx.autograd.train_mode():
        out = nd.BatchNorm(nd.array(x), nd.array(g), nd.array(b),
                           nd.array(mm), nd.array(mv), fix_gamma=False)
    o = out.asnumpy()
    # normalized per channel
    assert np.allclose(o.mean(axis=(0, 2, 3)), 0, atol=1e-4)
    assert np.allclose(o.std(axis=(0, 2, 3)), 1, atol=1e-2)


def test_gradient_fc():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=4, name="fc")
    out = sym.sum(fc)
    check_numeric_gradient(out, {"data": np.random.randn(3, 5).astype("f4")},
                           numeric_eps=1e-2, rtol=1e-2, atol=1e-2)


def test_gradient_elemwise():
    a = sym.Variable("a")
    b = sym.Variable("b")
    out = sym.sum(a * b + sym.tanh(a))
    loc = {"a": np.random.randn(3, 3).astype("f4"),
           "b": np.random.randn(3, 3).astype("f4")}
    check_numeric_gradient(out, loc, numeric_eps=1e-2, rtol=1e-2, atol=1e-2)


def test_gradient_conv_pool():
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=2, pad=(1, 1),
                          name="c")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    out = sym.sum(net)
    check_numeric_gradient(out, {"data": np.random.randn(1, 1, 4, 4)
                                 .astype("f4")},
                           numeric_eps=1e-2, rtol=5e-2, atol=5e-2)


def test_symbolic_forward_check():
    x = np.random.randn(2, 3).astype("f4")
    data = sym.Variable("x")
    out = sym.exp(data)
    check_symbolic_forward(out, {"x": x}, [np.exp(x)], rtol=1e-4, atol=1e-5)


def test_embedding_and_sequence_ops():
    w = np.random.randn(10, 4).astype("f4")
    idx = np.array([1, 3, 5], dtype="f4")
    out = nd.Embedding(nd.array(idx), nd.array(w), input_dim=10, output_dim=4)
    assert np.allclose(out.asnumpy(), w[[1, 3, 5]], atol=1e-6)
    # sequence ops on (T, N, C)
    x = np.random.randn(4, 2, 3).astype("f4")
    lens = np.array([2, 4], dtype="f4")
    masked = nd.SequenceMask(nd.array(x), nd.array(lens),
                             use_sequence_length=True, value=0.0).asnumpy()
    assert np.allclose(masked[2:, 0], 0)
    assert np.allclose(masked[:, 1], x[:, 1], atol=1e-6)
    last = nd.SequenceLast(nd.array(x), nd.array(lens),
                           use_sequence_length=True).asnumpy()
    assert np.allclose(last[0], x[1, 0], atol=1e-6)
    assert np.allclose(last[1], x[3, 1], atol=1e-6)
    rev = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True).asnumpy()
    assert np.allclose(rev[0, 0], x[1, 0], atol=1e-6)
    assert np.allclose(rev[0, 1], x[3, 1], atol=1e-6)


def test_slice_and_crop():
    x = np.arange(24, dtype="f4").reshape(2, 3, 4)
    out = nd.slice(nd.array(x), begin=(0, 1, 1), end=(2, 3, 3)).asnumpy()
    assert np.allclose(out, x[:, 1:3, 1:3])
    out2 = nd.slice_axis(nd.array(x), axis=2, begin=1, end=3).asnumpy()
    assert np.allclose(out2, x[:, :, 1:3])


def test_optimizer_ops():
    w = nd.ones((4,))
    g = nd.ones((4,)) * 2
    nd.sgd_update(w, g, lr=0.1, out=w)
    assert np.allclose(w.asnumpy(), 1 - 0.1 * 2)
    w2 = nd.ones((4,))
    mom = nd.zeros((4,))
    nd.sgd_mom_update(w2, g, mom, lr=0.1, momentum=0.9, out=[w2, mom])
    assert np.allclose(w2.asnumpy(), 0.8)
    assert np.allclose(mom.asnumpy(), -0.2)
    wa = nd.ones((4,))
    me, va = nd.zeros((4,)), nd.zeros((4,))
    nd.adam_update(wa, g, me, va, lr=0.01, out=[wa, me, va])
    assert wa.asnumpy().mean() < 1.0


def test_random_ops_seeded():
    mx.random.seed(42)
    a = nd.uniform(low=0, high=1, shape=(100,)).asnumpy()
    mx.random.seed(42)
    b = nd.uniform(low=0, high=1, shape=(100,)).asnumpy()
    assert np.allclose(a, b)
    assert 0 <= a.min() and a.max() <= 1
    n = nd.normal(loc=5, scale=0.1, shape=(2000,)).asnumpy()
    assert abs(n.mean() - 5) < 0.1


def test_where_clip_etc():
    c = nd.array(np.array([1.0, 0, 1]))
    a = nd.array(np.array([1.0, 2, 3]))
    b = nd.array(np.array([4.0, 5, 6]))
    out = nd.where(c, a, b).asnumpy()
    assert np.allclose(out, [1, 5, 3])
    assert np.allclose(nd.clip(a, a_min=1.5, a_max=2.5).asnumpy(),
                       [1.5, 2, 2.5])


def test_linalg_ops():
    a = np.random.randn(3, 3).astype("f4")
    spd = a @ a.T + 3 * np.eye(3, dtype="f4")
    l = nd.linalg_potrf(nd.array(spd)).asnumpy()
    assert np.allclose(l @ l.T, spd, atol=1e-3)
    g = nd.linalg_gemm2(nd.array(a), nd.array(a), transpose_b=True).asnumpy()
    assert np.allclose(g, a @ a.T, atol=1e-4)


def test_loss_ops_grad_semantics():
    # LinearRegressionOutput: grad = pred - label
    d = sym.Variable("d")
    l = sym.Variable("l")
    out = sym.LinearRegressionOutput(d, l, name="lro")
    pred = np.random.randn(4, 3).astype("f4")
    lab = np.random.randn(4, 3).astype("f4")
    ex = out.bind(mx.cpu(), {"d": nd.array(pred), "l": nd.array(lab)},
                  args_grad={"d": nd.zeros((4, 3))},
                  grad_req={"d": "write", "l": "null"})
    ex.forward(is_train=True)
    ex.backward()
    assert np.allclose(ex.grad_dict["d"].asnumpy(), pred - lab, atol=1e-5)


# ---------------------------------------------------------------------------
# value oracles for ops the gradient sweep skip-lists as "value-tested":
# linalg family, fft packing, count_sketch, CTC loss.

def test_linalg_value_oracles():
    rng = np.random.RandomState(0)
    A = rng.randn(4, 4).astype("float32")
    spd = A @ A.T + 4 * np.eye(4, dtype="float32")
    L = nd.linalg_potrf(nd.array(spd)).asnumpy()

    # potri: inverse of spd from its Cholesky factor
    inv = nd.linalg_potri(nd.array(L)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(spd), rtol=1e-3,
                               atol=1e-4)

    # trsm: L X = B  =>  X = L^-1 B
    B = rng.randn(4, 3).astype("float32")
    X = nd.linalg_trsm(nd.array(L), nd.array(B)).asnumpy()
    np.testing.assert_allclose(L @ X, B, rtol=1e-4, atol=1e-4)

    # sumlogdiag(L) = 0.5 * logdet(spd)
    sld = nd.linalg_sumlogdiag(nd.array(L)).asnumpy()
    np.testing.assert_allclose(sld, 0.5 * np.linalg.slogdet(spd)[1],
                               rtol=1e-4)

    # gelqf: A = L Q with Q orthonormal rows
    M = rng.randn(3, 5).astype("float32")
    Q, Lq = nd.linalg_gelqf(nd.array(M))
    Q, Lq = Q.asnumpy(), Lq.asnumpy()
    np.testing.assert_allclose(Q @ Q.T, np.eye(3), atol=1e-4)
    np.testing.assert_allclose(Lq @ Q, M, rtol=1e-3, atol=1e-4)

    # trmm: alpha * op(A) @ B (lower-triangular A)
    out = nd.linalg_trmm(nd.array(np.tril(A)), nd.array(B)).asnumpy()
    np.testing.assert_allclose(out, np.tril(A) @ B, rtol=1e-4, atol=1e-4)

    # syrk: A A^T
    out = nd.linalg_syrk(nd.array(M)).asnumpy()
    np.testing.assert_allclose(out, M @ M.T, rtol=1e-4, atol=1e-4)


def test_fft_ifft_packing_oracle():
    """contrib.fft packs complex as interleaved re/im on the last axis;
    ifft returns the unnormalized inverse (reference contrib/fft.cc)."""
    rng = np.random.RandomState(1)
    x = rng.randn(2, 8).astype("float32")
    out = nd.contrib.fft(nd.array(x)).asnumpy()
    ref = np.fft.fft(x, axis=-1)
    packed = np.stack([ref.real, ref.imag], axis=-1).reshape(2, 16)
    np.testing.assert_allclose(out, packed, rtol=1e-4, atol=1e-4)

    back = nd.contrib.ifft(nd.array(out)).asnumpy()
    np.testing.assert_allclose(back, x * 8, rtol=1e-4, atol=1e-4)


def test_count_sketch_oracle():
    rng = np.random.RandomState(2)
    n, d, k = 3, 6, 4
    x = rng.randn(n, d).astype("float32")
    h = rng.randint(0, k, d).astype("float32")
    s = rng.choice([-1.0, 1.0], d).astype("float32")
    out = nd.contrib.count_sketch(nd.array(x), nd.array(h), nd.array(s),
                                  out_dim=k).asnumpy()
    want = np.zeros((n, k), "float32")
    for i in range(d):
        want[:, int(h[i])] += s[i] * x[:, i]
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_ctc_loss_oracle():
    """CTC nll for a tiny case vs the direct path enumeration.
    T=2, C=3 (blank=0), label='1': paths for '1' are
    (1,1), (blank,1), (1,blank) -> p = p1(1)p2(1)+p1(0)p2(1)+p1(1)p2(0)."""
    logits = np.array([[[0.6, 1.2, -0.4]], [[-0.2, 0.9, 0.1]]], "float32")
    label = np.array([[1.0]], "float32")
    out = nd.contrib.CTCLoss(nd.array(logits), nd.array(label)).asnumpy()

    def softmax(v):
        e = np.exp(v - v.max())
        return e / e.sum()
    p1, p2 = softmax(logits[0, 0]), softmax(logits[1, 0])
    p = p1[1] * p2[1] + p1[0] * p2[1] + p1[1] * p2[0]
    np.testing.assert_allclose(out[0], -np.log(p), rtol=1e-4)
