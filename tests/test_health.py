"""mxtpu.obs.health + mxtpu.obs.detectors — device-resident training-
health statistics (docs/observability.md "Training health"). The
contracts:

* **detector determinism**: every detector is pure over explicit state —
  seeded synthetic stat streams assert EXACTLY which cadence fires, with
  frozen windows and no wall-clock anywhere;
* **zero added sync points** (cadence exactness): an armed fit performs
  the SAME number of ``jax.device_get`` transfers as a disarmed one —
  the stat accumulator rides the metric accum's cadence sync as a rider;
* **THE rollback gate**: an injected divergence mid-fit produces the
  divergence Finding + ``health_anomalies`` counter, fires the
  supervisor action seam, the wedged trajectory aborts BEFORE its
  snapshot, and the retry restores the last good generation — the fit
  completes with weights bit-exact against a clean run;
* **one postmortem per root cause**: a nonfinite the sanitizer already
  captured must not produce a second (health) postmortem, in either
  firing order;
* corpus ``health`` rows round-trip and keep the torn-tail tolerance;
* the Monitor adapter (default abs-mean stat) matches the legacy
  per-op path's values; a custom ``stat_func`` keeps the legacy path.
"""
import json
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import metric as M
from mxtpu import telemetry as tel
from mxtpu.analysis.findings import ERROR, WARNING
from mxtpu.models import mlp as _mlp
from mxtpu.obs import corpus as _corpus
from mxtpu.obs import detectors as D
from mxtpu.obs import health as H


def _mnist_like(n=256, seed=7):
    rng = np.random.RandomState(seed)
    return (rng.rand(n, 784).astype("float32"),
            rng.randint(0, 10, n).astype("float32"))


def _make_iter(batch_size=64, poison_batch=None):
    X, y = _mnist_like()
    if poison_batch is not None:
        X = X.copy()
        X[poison_batch * batch_size:(poison_batch + 1) * batch_size] = \
            np.inf
    return mx.io.NDArrayIter(X, y, batch_size=batch_size,
                             label_name="softmax_label")


def _fit(num_epoch=2, seed=11, module=None, it=None, **fit_kwargs):
    it = it or _make_iter()
    mod = module or mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    metric = M.create(["acc", "ce"])
    mx.random.seed(seed)
    np.random.seed(seed)
    fit_kwargs.setdefault("metric_sync", 2)
    mod.fit(it, num_epoch=num_epoch, eval_metric=metric,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            initializer=mx.initializer.Xavier(),
            **fit_kwargs)
    weights = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    return dict(metric.get_name_value()), weights, mod


def _stats(**per_class):
    """{class: stat dict} with the full stat vocabulary defaulted."""
    base = {"grad_norm": 1.0, "weight_norm": 1.0, "update_ratio": 0.01,
            "grad_max": 1.0, "nonfinite": 0}
    return {cls: dict(base, **override)
            for cls, override in per_class.items()}


# ------------------------------------------------------ detector units
def test_loss_spike_fires_only_after_full_window():
    det = D.LossSpikeDetector(window=4, spike_k=8.0)
    # window filling: nothing may fire, not even on a huge value
    assert det.observe(1.0, {}) is None
    assert det.observe(50.0, {}) is None
    assert det.observe(1.02, {}) is None
    assert det.observe(0.98, {}) is None
    # window full; next in-band value stays quiet
    assert det.observe(1.01, {}) is None


def test_loss_spike_exact_cadence_and_unpoisoned_baseline():
    det = D.LossSpikeDetector(window=4, spike_k=8.0)
    for v in (1.0, 1.02, 0.98, 1.01):
        assert det.observe(v, {}) is None
    f = det.observe(3.0, {})            # cadence 5: the spike
    assert f is not None and f.severity == WARNING
    assert f.details["kind"] == "loss_spike"
    assert f.details["threshold"] < 3.0
    # the tripping loss was NOT pushed into the window: the baseline is
    # intact, an in-band value is quiet and a repeat spike fires again
    assert det.observe(1.0, {}) is None
    assert det.observe(3.0, {}) is not None


def test_loss_spike_flat_stream_is_not_dust():
    det = D.LossSpikeDetector(window=4, spike_k=8.0)
    for _ in range(6):
        assert det.observe(1.0, {}) is None   # MAD 0, floored not zeroed


def test_divergence_nonfinite_fires_cadence_one_with_hysteresis():
    det = D.DivergenceDetector(window=4)
    f = det.observe(None, _stats(fc1_weight={"nonfinite": 3}))
    assert f is not None and f.severity == ERROR
    assert f.details["kind"] == "divergence"
    assert f.details["nonfinite"] == 3
    assert f.details["classes"] == ["fc1_weight"]
    # hysteresis: the wedged trajectory emits ONE Finding per excursion
    assert det.observe(None, _stats(fc1_weight={"nonfinite": 3})) is None
    # recovery re-arms it
    assert det.observe(1.0, _stats(fc1_weight={})) is None
    assert det.observe(None,
                       _stats(fc1_weight={"nonfinite": 1})) is not None


def test_divergence_nonfinite_loss_and_ratio_arms():
    det = D.DivergenceDetector(window=3, diverge_k=1e3)
    f = det.observe(float("nan"), _stats(fc1_weight={}))
    assert f is not None and "nonfinite" in f.message
    det = D.DivergenceDetector(window=3, diverge_k=1e3)
    for v in (1.0, 1.1, 0.9):
        assert det.observe(v, _stats(fc1_weight={})) is None
    assert det.observe(900.0, _stats(fc1_weight={})) is None  # < k*median
    f = det.observe(5000.0, _stats(fc1_weight={}))
    assert f is not None and f.details["kind"] == "divergence"


def test_dead_layer_exact_consecutive_cadence():
    det = D.DeadLayerDetector(n_cadences=3, eps=1e-12)
    dead = _stats(a={"grad_norm": 0.0}, b={"grad_norm": 1.0})
    assert det.observe(1.0, dead) is None      # run 1
    assert det.observe(1.0, dead) is None      # run 2
    f = det.observe(1.0, dead)                 # run 3: fires
    assert f is not None and f.details["class"] == "a"
    assert f.details["cadences"] == 3
    assert det.observe(1.0, dead) is None      # fired once, stays quiet
    alive = _stats(a={"grad_norm": 1.0}, b={"grad_norm": 1.0})
    assert det.observe(1.0, alive) is None     # revival re-arms
    for _ in range(2):
        assert det.observe(1.0, dead) is None
    assert det.observe(1.0, dead) is not None


def test_exploding_update_cold_start_suppression():
    det = D.ExplodingUpdateDetector(threshold=0.5, n_cadences=3)
    hot = _stats(fc1_bias={"update_ratio": 0.9})
    cool = _stats(fc1_bias={"update_ratio": 0.1})
    # a zero-init param's first cadences exceed the ratio by
    # construction; a transient excursion must never fire
    assert det.observe(1.0, hot) is None
    assert det.observe(1.0, hot) is None
    assert det.observe(1.0, cool) is None      # run reset
    assert det.observe(1.0, hot) is None
    assert det.observe(1.0, hot) is None
    f = det.observe(1.0, hot)                  # 3rd consecutive: fires
    assert f is not None and f.details["kind"] == "exploding_update"
    assert f.details["cadences"] == 3


def test_exploding_update_decaying_tail_never_fires():
    # a zero-init bias sits above threshold for many cadences while
    # ‖w‖ catches up, but the ratio decays ~1/t — that tail must not
    # fire no matter how long it lasts
    det = D.ExplodingUpdateDetector(threshold=0.5, n_cadences=3)
    r = 4.0
    for _ in range(12):
        assert det.observe(1.0, _stats(fc2_bias={"update_ratio": r})) \
            is None
        r *= 0.8                               # >2% decay per cadence
    # a genuinely growing run still fires in exactly n_cadences
    for i, rr in enumerate((0.6, 0.7, 0.9)):
        f = det.observe(1.0, _stats(fc2_bias={"update_ratio": rr}))
        assert (f is None) == (i < 2), (i, f)
    assert f.details["kind"] == "exploding_update"


def test_detector_suite_orders_error_first():
    suite = D.DetectorSuite(window=2, spike_k=4.0)
    clean = _stats(fc1_weight={})
    assert suite.observe(1.0, clean) == []
    assert suite.observe(1.0, clean) == []
    findings = suite.observe(10.0, _stats(fc1_weight={"nonfinite": 1}))
    kinds = [f.details["kind"] for f in findings]
    assert "divergence" in kinds and "loss_spike" in kinds
    assert findings[0].severity == ERROR


def test_health_policy_env_parsing(monkeypatch):
    monkeypatch.setenv("MXTPU_HEALTH_ACTION", "rollback")
    assert D.HealthPolicy.from_env().action == "rollback"
    monkeypatch.setenv("MXTPU_HEALTH_ACTION", "reformat-disk")
    assert D.HealthPolicy.from_env().action == "warn"   # unknown -> warn
    monkeypatch.delenv("MXTPU_HEALTH_ACTION")
    assert D.HealthPolicy.from_env().action == "warn"


def test_class_label_and_env_arming(monkeypatch):
    assert H.class_label(["fc1_weight"]) == "fc1_weight"
    assert H.class_label(["fc1_weight", "fc1_bias"]) == "fc1*[2]"
    monkeypatch.setenv("MXTPU_HEALTH", "1")
    assert H.armed_by_env()
    monkeypatch.setenv("MXTPU_HEALTH", "off")
    assert not H.armed_by_env()


def test_health_knobs_resolve(monkeypatch):
    from mxtpu.tune import registry as knobs
    assert knobs.resolve_int("health.cadence", floor=1) >= 1
    assert knobs.resolve_int("health.window", floor=2) >= 2
    assert float(knobs.resolve("health.spike_k")) > 0
    monkeypatch.setenv("MXTPU_HEALTH_CADENCE", "4")
    assert knobs.resolve_int("health.cadence", floor=1) == 4


def test_health_accum_fold_exact():
    import jax.numpy as jnp
    acc = H.HealthAccum(2)
    assert acc.pull() is None
    s1 = {"sums": jnp.array([[1., 2., 3., 0.], [4., 5., 6., 1.]]),
          "max": jnp.array([2., 7.])}
    s2 = {"sums": jnp.array([[10., 0., 1., 0.], [1., 1., 1., 0.]]),
          "max": jnp.array([9., 3.])}
    acc.update(s1)
    acc.update(s2)
    tree = acc.pull()
    np.testing.assert_allclose(np.asarray(tree["sums"]),
                               [[11., 2., 4., 0.], [5., 6., 7., 1.]])
    np.testing.assert_allclose(np.asarray(tree["max"]), [9., 7.])
    assert acc.finish() == 2
    assert acc.pull() is None


# ------------------------------------------------- fit-level contracts
def test_fit_health_stats_panel_and_corpus(tmp_path, monkeypatch):
    """Armed fit: finite per-class stats on every surface — gauges, the
    debug_state panel (kept after close, marked disarmed), and corpus
    health rows under the v2 schema."""
    monkeypatch.setenv("MXTPU_CORPUS_DIR", str(tmp_path))
    _corpus.reset()
    try:
        _, _, mod = _fit(health=True)
    finally:
        _corpus.reset()
    assert mod._fused is not None and mod._fused._health_classes
    panel = mx.diagnostics.debug_state().get("training_health")
    assert panel is not None and panel["armed"] is False  # fit closed
    assert panel["cadences"] > 0
    classes = {row["class"]: row for row in panel["classes"]}
    assert classes, panel
    for row in classes.values():
        for stat in H.STATS:
            assert np.isfinite(row[stat]), row
        assert row["nonfinite"] == 0
        assert row["grad_norm"] > 0 and row["weight_norm"] > 0
    # gauges landed for every (class, stat)
    health_series = [m for m in tel.registry().series()
                     if m.name == "train_health"]
    assert len(health_series) >= len(classes) * len(H.STATS)
    some = tel.registry().gauge(
        "train_health", labels={"layer_class": list(classes)[0],
                                "stat": "grad_norm"})
    assert some.value > 0
    # corpus: one health row per cadence, loadable, v2 schema
    rows = [r for r in _corpus.load(str(tmp_path))
            if r.get("row") == "health"]
    assert rows and rows[0]["v"] == _corpus.SCHEMA_VERSION == 2
    assert set(rows[0]["stats"]) == set(classes)
    for s in rows[0]["stats"].values():
        assert set(s) == set(H.STATS)


def test_corpus_health_row_roundtrip_and_torn_tail(tmp_path,
                                                   monkeypatch):
    monkeypatch.setenv("MXTPU_CORPUS_DIR", str(tmp_path))
    _corpus.reset()
    try:
        stats = {"fc1*[2]": {"grad_norm": 0.5, "weight_norm": 2.0,
                             "update_ratio": 0.01, "grad_max": 1.5,
                             "nonfinite": 0}}
        assert _corpus.record_health(3, stats, loss=1.25,
                                     anomalies=["divergence"])
        path = _corpus.corpus_path()
        rows = _corpus.load(str(tmp_path))
        assert len(rows) == 1
        row = rows[0]
        assert row["row"] == "health" and row["cadence"] == 3
        assert row["loss"] == 1.25
        assert row["anomalies"] == ["divergence"]
        assert row["stats"] == stats
        # writer killed mid-append: a torn FINAL line is tolerated and
        # every fully-appended row still loads
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"v":2,"row":"hea')
        assert _corpus.load(str(tmp_path)) == rows
    finally:
        _corpus.reset()


def test_fit_health_adds_zero_sync_points():
    """Cadence exactness: the armed fit's jax.device_get call count
    equals the disarmed fit's — the stat window rides the metric
    accum's one cadence transfer (the BENCH_health.json proof, as a
    regression gate)."""
    import jax
    it = _make_iter()
    mod_off = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    mod_on = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    _fit(num_epoch=1, module=mod_off, it=it, health=False)  # warm
    _fit(num_epoch=1, module=mod_on, it=it, health=True)
    real_get, counts = jax.device_get, {"n": 0}

    def counting(*a, **kw):
        counts["n"] += 1
        return real_get(*a, **kw)

    def counted(mod, health):
        counts["n"] = 0
        jax.device_get = counting
        try:
            _fit(num_epoch=1, module=mod, it=it, health=health,
                 force_init=False)
        finally:
            jax.device_get = real_get
        return counts["n"]

    gets_off = counted(mod_off, False)
    gets_on = counted(mod_on, True)
    assert gets_off > 0
    assert gets_on - gets_off == 0, (gets_off, gets_on)


@pytest.mark.slow
def test_fit_health_bf16_parity():
    """bf16 mixed-precision fit: the stats observe the f32 masters —
    finite everywhere, zero nonfinite elements, and the panel stats are
    close to the plain-f32 fit's (same data, same seed)."""
    from mxtpu.compile import pipeline as P
    _, _, _ = _fit(health=True)
    f32_panel = mx.diagnostics.debug_state()["training_health"]
    os.environ["MXTPU_PIPELINE"] = "bf16"
    P.configure(None)
    try:
        _, _, mod = _fit(health=True)
        rep = mod._fused.pipeline_report
        assert rep is not None and "bf16" in rep.applied
    finally:
        os.environ.pop("MXTPU_PIPELINE", None)
        P.configure(None)
    panel = mx.diagnostics.debug_state()["training_health"]
    f32 = {r["class"]: r for r in f32_panel["classes"]}
    b16 = {r["class"]: r for r in panel["classes"]}
    assert set(f32) == set(b16)
    for cls, row in b16.items():
        assert row["nonfinite"] == 0
        for stat in H.STATS:
            assert np.isfinite(row[stat]), (cls, row)
        # masters are f32: the stat magnitudes track the f32 fit's
        assert row["grad_norm"] == pytest.approx(
            f32[cls]["grad_norm"], rel=0.25, abs=1e-4), cls


# ------------------------------------------------- THE rollback gate
def test_health_divergence_rollback_gate(tmp_path, monkeypatch):
    """Injected divergence (an inf batch mid-epoch) -> divergence
    Finding + health_anomalies counter -> the armed rollback policy
    fires the supervisor seam -> the wedged trajectory aborts BEFORE
    its snapshot -> the retry restores the last good generation and
    the fit completes with weights bit-exact against a clean run."""
    monkeypatch.setenv("MXTPU_HEALTH_ACTION", "rollback")
    prefix = str(tmp_path / "ck")
    m_full, w_full, _ = _fit(health=True, metric_sync=1)

    sup = mx.elastic.Supervisor(retries=2, backoff_s=0.0)
    cfg = mx.elastic.ElasticConfig(prefix, every_n_steps=1, sync=True,
                                   supervisor=sup)
    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    metric = M.create(["acc", "ce"])
    attempts = []
    div0 = tel.registry().counter("health_anomalies",
                                  labels={"kind": "divergence"}).value

    def fit_fn(resume):
        attempts.append(resume)
        # attempt 1 feeds an all-inf batch 2; the retry's data is clean
        it = _make_iter(poison_batch=2 if len(attempts) == 1 else None)
        mx.random.seed(11)
        np.random.seed(11)
        mod.fit(it, num_epoch=2, eval_metric=metric, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05,
                                  "momentum": 0.9},
                initializer=mx.initializer.Xavier(), metric_sync=1,
                health=True, elastic=cfg, resume=resume)

    sup.run(fit_fn)

    assert attempts == [False, True]
    assert sup.retries_done == 1
    # the detector fired exactly once (hysteresis) and was surfaced
    div = tel.registry().counter("health_anomalies",
                                 labels={"kind": "divergence"}).value
    assert div == div0 + 1
    pm = mx.diagnostics.last_postmortem()
    assert pm is not None and pm["source"] == "health"
    assert "divergence" in pm["reason"]
    # the wedged step was never snapshotted: the retry replayed the
    # poisoned batch with clean data and the result is bit-exact
    w_sup = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in w_full:
        np.testing.assert_array_equal(w_full[k], w_sup[k], err_msg=k)
    assert m_full["accuracy"] == dict(metric.get_name_value())["accuracy"]


# ------------------------------------------- sanitizer interplay
def test_sanitizer_first_skips_health_postmortem():
    """Order A: the sanitizer already captured this window's nonfinite —
    the health action must NOT emit a duplicate postmortem for the same
    wreckage (and still fires the policy seam)."""
    import jax.numpy as jnp
    from mxtpu.analysis import sanitizer as san
    from mxtpu.analysis.findings import Finding
    from mxtpu.base import NumericsError
    _, _, mod = _fit(num_epoch=1)
    sess = H.HealthSession(mod._fused, detect=True)
    try:
        reg = tel.registry()
        h0 = reg.counter("diag_postmortems",
                         labels={"source": "health"}).value
        # a REAL sanitizer trip between the session's baseline and _act
        san.enable("all")
        try:
            with pytest.raises(NumericsError):
                san.sanitize_tree("fwd_eval",
                                  [jnp.array([float("nan")])])
        finally:
            san.disable()
        f = Finding("health", ERROR, "divergence: test",
                    details={"kind": "divergence"})
        sess._act(f)
        assert reg.counter("diag_postmortems",
                           labels={"source": "health"}).value == h0
        # Order B: baseline refreshed, no new trip -> health owns it
        sess._san_trips = san.trip_count()
        sess._act(f)
        assert reg.counter("diag_postmortems",
                           labels={"source": "health"}).value == h0 + 1
        assert mx.diagnostics.last_postmortem()["source"] == "health"
    finally:
        sess.close()


def test_sanitizer_armed_fit_one_postmortem_per_root_cause():
    """Order A end-to-end: with the sanitizer armed the poisoned step
    trips IN the step (NumericsError), and health — armed in the same
    fit — adds no second postmortem for the same nonfinite."""
    from mxtpu.analysis import sanitizer as san
    from mxtpu.base import NumericsError
    reg = tel.registry()
    s0 = reg.counter("diag_postmortems",
                     labels={"source": "sanitizer"}).value
    h0 = reg.counter("diag_postmortems",
                     labels={"source": "health"}).value
    san.enable("all")
    try:
        with pytest.raises(NumericsError):
            _fit(health=True, it=_make_iter(poison_batch=1),
                 num_epoch=1)
    finally:
        san.disable()
    assert reg.counter("diag_postmortems",
                       labels={"source": "sanitizer"}).value == s0 + 1
    assert reg.counter("diag_postmortems",
                       labels={"source": "health"}).value == h0


# --------------------------------------------- Monitor adapter parity
def _small_module():
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))])
    mx.random.seed(3)
    np.random.seed(3)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.0})
    return mod


def _monitor_values(mod, mon):
    mod.install_monitor(mon)
    rng = np.random.RandomState(0)
    db = mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(16, 8).astype("float32"))],
        label=[mx.nd.array(rng.randint(0, 3, (16,)).astype("float32"))])
    mon.tic()
    mod.forward_backward(db)
    mod.update()
    return {name: float(stat.split()[0])
            for _, name, stat in mon.toc()}


def test_monitor_adapter_matches_legacy_values(monkeypatch):
    """Satellite: the default-stat Monitor rides the device tap kernels
    and reports the same abs-mean per tensor the legacy per-op path
    computes (lr=0 so both runs see identical weights)."""
    mod_leg = _small_module()
    monkeypatch.setenv("MXTPU_MONITOR_ADAPTER", "0")
    mon_leg = mx.monitor.Monitor(interval=1, pattern=".*")
    legacy = _monitor_values(mod_leg, mon_leg)
    assert mon_leg._adapter is None
    monkeypatch.delenv("MXTPU_MONITOR_ADAPTER")

    mod_ad = _small_module()
    mod_ad.set_params(*mod_leg.get_params())
    mon_ad = mx.monitor.Monitor(interval=1, pattern=".*")
    adapter = _monitor_values(mod_ad, mon_ad)
    assert mon_ad._adapter is mod_ad    # really the device-tap path
    shared = set(legacy) & set(adapter)
    assert any("fc1" in n for n in shared), (legacy, adapter)
    assert any("softmax" in n for n in shared)
    for name in shared:
        assert adapter[name] == pytest.approx(legacy[name], rel=1e-4), \
            name


def test_monitor_custom_stat_func_keeps_legacy_path():
    mod = _small_module()
    mon = mx.monitor.Monitor(interval=1, pattern=".*",
                             stat_func=lambda x: float(
                                 np.max(np.abs(x.asnumpy()))))
    vals = _monitor_values(mod, mon)
    assert mon._adapter is None and not mon._default_stat
    assert vals and all(np.isfinite(v) for v in vals.values())


def test_monitor_adapter_through_fit_collects_taps():
    """fit(monitor=) with an adapter-eligible monitor: sampled batches
    force a cadence so taps land before toc_print, and device metrics
    stay enabled (the legacy path had to disable them)."""
    mon = mx.monitor.Monitor(interval=2, pattern=".*fc.*")
    delivered = []
    orig = mon._deliver_taps

    def spy(host):
        delivered.append(dict(host))
        orig(host)

    mon._deliver_taps = spy
    _fit(num_epoch=1, monitor=mon)
    assert delivered, "no device taps were delivered through the fit"
    names = set().union(*delivered)
    assert any("fc1" in n for n in names), names
    for host in delivered:
        for v in host.values():
            assert np.isfinite(float(v))
