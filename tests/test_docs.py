"""Documentation rot guard: every dotted mx.* API name mentioned in the
tutorials must resolve on the live package (the reference's docs are
generated from the registry, which gives the same guarantee)."""
import os
import re

import pytest

import mxtpu as mx

DOCS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "docs", "tutorials")

# names like mx.nd.save / mx.gluon.loss.SoftmaxCrossEntropyLoss; stop at '('
_PAT = re.compile(r"\bmx\.((?:[A-Za-z_][\w]*\.)*[A-Za-z_][\w]*)")

# doc-prose tokens that are not attribute paths
_SKIP = {"X", "sym.X"}


@pytest.mark.parametrize("fname", sorted(os.listdir(DOCS)))
def test_tutorial_names_resolve(fname):
    text = open(os.path.join(DOCS, fname)).read()
    missing = []
    for m in _PAT.finditer(text):
        path = m.group(1)
        if path in _SKIP:
            continue
        obj = mx
        for part in path.split("."):
            obj = getattr(obj, part, None)
            if obj is None:
                missing.append(path)
                break
    assert not missing, "%s references unknown APIs: %s" % (
        fname, sorted(set(missing)))


def test_notebooks_execute():
    """Notebook tutorials (examples/notebooks, parity example/notebooks
    + MXNetTutorialTemplate.ipynb): every code cell executes in order
    and the notebooks' embedded assertions hold."""
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    nbs = [
        os.path.join(root, "examples", "notebooks",
                     "basics_ndarray_symbol.ipynb"),
        os.path.join(root, "examples", "notebooks",
                     "module_training.ipynb"),
    ]
    sentinels = {"basics_ndarray_symbol.ipynb": "BASICS_OK",
                 "module_training.ipynb": "MODULE_OK"}
    for path in nbs:
        with open(path) as f:
            nb = json.load(f)
        ns = {}
        for cell in nb["cells"]:
            if cell["cell_type"] != "code":
                continue
            exec(compile("".join(cell["source"]), path, "exec"), ns)
        assert ns.get(sentinels[os.path.basename(path)]) is True
    # the template is structure, not runnable code: just validate JSON +
    # that its code cells compile
    tpl = os.path.join(root, "examples", "MXTPUTutorialTemplate.ipynb")
    with open(tpl) as f:
        nb = json.load(f)
    assert any(c["cell_type"] == "markdown" for c in nb["cells"])
    for cell in nb["cells"]:
        if cell["cell_type"] == "code":
            compile("".join(cell["source"]), tpl, "exec")
