"""Documentation rot guard: every dotted mx.* API name mentioned in the
tutorials must resolve on the live package (the reference's docs are
generated from the registry, which gives the same guarantee)."""
import os
import re

import pytest

import mxtpu as mx

DOCS = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "docs", "tutorials")

# names like mx.nd.save / mx.gluon.loss.SoftmaxCrossEntropyLoss; stop at '('
_PAT = re.compile(r"\bmx\.((?:[A-Za-z_][\w]*\.)*[A-Za-z_][\w]*)")

# doc-prose tokens that are not attribute paths
_SKIP = {"X", "sym.X"}


@pytest.mark.parametrize("fname", sorted(os.listdir(DOCS)))
def test_tutorial_names_resolve(fname):
    text = open(os.path.join(DOCS, fname)).read()
    missing = []
    for m in _PAT.finditer(text):
        path = m.group(1)
        if path in _SKIP:
            continue
        obj = mx
        for part in path.split("."):
            obj = getattr(obj, part, None)
            if obj is None:
                missing.append(path)
                break
    assert not missing, "%s references unknown APIs: %s" % (
        fname, sorted(set(missing)))
