"""mx.registry / mx.log / mx.libinfo / mx.name small-parity modules
(reference: python/mxnet/{registry,log,libinfo,name}.py)."""
import logging

import pytest

import mxtpu as mx
from mxtpu.base import MXNetError


def test_registry_register_alias_create():
    class Animal:
        def __init__(self, sound="?"):
            self.sound = sound

    register = mx.registry.get_register_func(Animal, "animal")
    alias = mx.registry.get_alias_func(Animal, "animal")
    create = mx.registry.get_create_func(Animal, "animal")

    @alias("doggo", "pup")
    class Dog(Animal):
        pass

    register(Dog)
    assert isinstance(create("dog"), Dog)
    assert isinstance(create("PUP"), Dog)
    inst = Dog()
    assert create(inst) is inst
    a = create('["doggo", {"sound": "woof"}]')
    assert isinstance(a, Dog) and a.sound == "woof"
    b = create('{"animal": "dog", "sound": "arf"}')
    assert b.sound == "arf"
    with pytest.raises(MXNetError):
        create("cat")
    with pytest.raises(MXNetError):
        register(int)


def test_registry_override_warns():
    class Base:
        pass

    register = mx.registry.get_register_func(Base, "base")

    class A(Base):
        pass

    register(A, "thing")

    class B(Base):
        pass

    with pytest.warns(UserWarning):
        register(B, "thing")


def test_log_get_logger(tmp_path, capsys):
    log_file = str(tmp_path / "x.log")
    lg = mx.log.get_logger("mxtpu_test_file", filename=log_file,
                           level=mx.log.INFO)
    lg.info("hello %d", 7)
    lg2 = mx.log.get_logger("mxtpu_test_file")  # idempotent
    assert lg2 is lg and len(lg.handlers) == 1
    for h in lg.handlers:
        h.flush()
    text = open(log_file).read()
    assert "hello 7" in text and text.startswith("I ")


def test_libinfo():
    paths = mx.libinfo.find_lib_path()
    assert any(p.endswith("libmxtpu.so") for p in paths)
    assert mx.libinfo.__version__ == mx.__version__


def test_contrib_namespace_modules():
    """mx.contrib.ndarray / mx.contrib.symbol re-export the registry
    contrib namespaces (reference python/mxnet/contrib/{ndarray,symbol})."""
    import numpy as np
    x = mx.nd.array(np.ones((2, 4), "f"))
    out = mx.contrib.ndarray.fft(x)
    assert out.shape == (2, 8)
    s = mx.contrib.symbol.fft(mx.sym.Variable("d"))
    assert s.list_outputs()[0].endswith("_output")
    with pytest.raises(AttributeError):
        mx.contrib.ndarray.not_a_real_op


def _tools_path():
    import os
    import sys
    p = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    if p not in sys.path:
        sys.path.insert(0, p)


def test_parse_log_tool(tmp_path):
    _tools_path()
    import parse_log
    lf = tmp_path / "t.log"
    lf.write_text(
        "INFO:root:Epoch[0] Train-accuracy=0.5\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.4\n"
        "INFO:root:Epoch[0] Time cost=2.5\n"
        "INFO:root:Epoch[1] Train-accuracy=0.8\n"
        "INFO:root:Epoch[1] Validation-accuracy=0.7\n"
        "INFO:root:Epoch[1] Time cost=2.2\n")
    table = parse_log.parse(lf.read_text().splitlines())
    assert table[1]["train"] == 0.8 and table[0]["time"] == 2.5
    md = parse_log.render(table, "markdown")
    assert md.splitlines()[2].startswith("| 0 |")


def test_measure_bandwidth_tool():
    _tools_path()
    import measure_bandwidth
    res = measure_bandwidth.run([0.5], iters=2)
    names = {r["collective"] for r in res}
    assert names == {"psum", "reduce_scatter", "all_gather"}
    assert all(r["algo_gbps"] > 0 for r in res)


def test_gluon_utils_sha1_and_download(tmp_path):
    """check_sha1 + download local-file semantics (gluon/utils.py parity;
    the network path is exercised against a file:// URL so the gate runs
    offline)."""
    import hashlib

    from mxtpu.gluon import utils as gutils

    src = tmp_path / "blob.bin"
    src.write_bytes(b"mxtpu" * 100)
    digest = hashlib.sha1(src.read_bytes()).hexdigest()
    assert gutils.check_sha1(str(src), digest)
    assert not gutils.check_sha1(str(src), "0" * 40)
    url = "file://" + str(src)
    out = gutils.download(url, path=str(tmp_path / "copy.bin"),
                          sha1_hash=digest)
    assert open(out, "rb").read() == src.read_bytes()
    # cached: second call with matching hash does not re-fetch
    before = (tmp_path / "copy.bin").stat().st_mtime_ns
    gutils.download(url, path=str(tmp_path / "copy.bin"), sha1_hash=digest)
    assert (tmp_path / "copy.bin").stat().st_mtime_ns == before
    with pytest.raises(OSError):
        gutils.download(url, path=str(tmp_path / "bad.bin"),
                        sha1_hash="0" * 40)


def test_test_utils_sparse_helpers():
    """np_reduce / rand_sparse_ndarray / create_sparse_array parity
    helpers (reference test_utils.py:244-420)."""
    import numpy as np

    from mxtpu import test_utils as tu

    r = tu.np_reduce(np.arange(24).reshape(2, 3, 4).astype("f"), (0, 2),
                     True, np.sum)
    assert r.shape == (1, 3, 1)
    np.testing.assert_allclose(
        r, np.arange(24).reshape(2, 3, 4).sum((0, 2), keepdims=True))
    sp, dense = tu.rand_sparse_ndarray((6, 5), "csr", density=0.4)
    assert sp.stype == "csr"
    np.testing.assert_allclose(sp.asnumpy(), dense)
    rs = tu.create_sparse_array((4, 4), "row_sparse", data_init=2.0)
    assert rs.stype == "row_sparse"
    np.testing.assert_allclose(rs.asnumpy(), np.full((4, 4), 2.0))
    with pytest.raises(ValueError):
        tu.create_sparse_array((4, 4), "nonsense")


def test_feedforward_predict_row_order():
    """FeedForward legacy API end to end: fit on blobs, predict keeps the
    caller's ROW ORDER (the training iterator shuffles, predict must not
    — reference model.py _init_iter is_train split)."""
    import numpy as np

    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=3, name="fc"), name="softmax")
    rng = np.random.RandomState(0)
    centers = rng.randn(3, 6) * 3
    y = rng.randint(0, 3, 90)
    X = (centers[y] + rng.randn(90, 6)).astype("float32")
    mx.random.seed(4)
    ff = mx.model.FeedForward(symbol=net, num_epoch=8, learning_rate=0.3,
                              numpy_batch_size=30)
    ff.fit(X=X, y=y.astype("float32"))
    acc = (ff.predict(X).argmax(1) == y).mean()
    assert acc > 0.9, acc
    it = mx.io.NDArrayIter(X, y.astype("float32"), batch_size=30)
    sc = ff.score(it)  # score rides the unshuffled path; iter carries labels
    val = sc if np.isscalar(sc) else dict(sc).get("accuracy")
    assert val > 0.9, sc


def test_random_module_samplers():
    """mx.random.uniform/normal/poisson/... module samplers (parity
    python/mxnet/random.py re-exports), seeded-reproducible."""
    import numpy as np

    mx.random.seed(3)
    u = mx.random.uniform(2, 5, shape=(1000,)).asnumpy()
    assert u.min() > 2 and u.max() < 5
    n = mx.random.normal(10, 0.5, shape=(2000,)).asnumpy()
    assert abs(n.mean() - 10) < 0.1
    p = mx.random.poisson(4.0, shape=(2000,)).asnumpy()
    assert abs(p.mean() - 4) < 0.3
    g = mx.random.gamma(2.0, 3.0, shape=(3000,)).asnumpy()
    assert abs(g.mean() - 6.0) < 0.5  # E[gamma(a, b)] = a*b
    m = mx.random.multinomial(
        mx.nd.array(np.array([0.0, 1.0, 0.0], "float32")), shape=(5,))
    assert (m.asnumpy() == 1).all()
    mx.random.seed(3)
    u2 = mx.random.uniform(2, 5, shape=(1000,)).asnumpy()
    np.testing.assert_allclose(u, u2)
