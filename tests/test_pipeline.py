"""Asynchronous training pipeline (docs/training_pipeline.md): device-resident
metric accumulation, bounded in-flight stepping, device-side input prefetch,
and the PrefetchingIter lifecycle contract.

Numerics model: the pipelined fit runs the SAME fused step program in the
same order as the synchronous path — weights must match bit-for-bit, and
integer-summed metrics (accuracy) must match exactly; float partial sums
(cross-entropy) accumulate on device in f32 instead of host f64, and the
elementwise math runs in XLA instead of numpy, so loss parity is asserted
to float32 tolerance.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import metric as M
from mxtpu import telemetry as tel
from mxtpu.models import mlp as _mlp


def _mnist_like(n=256, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 784).astype("float32")
    y = rng.randint(0, 10, n).astype("float32")
    return X, y


def _fit_mlp(pipelined, num_epoch=2, seed=11, **fit_kwargs):
    X, y = _mnist_like()
    it = mx.io.NDArrayIter(X, y, batch_size=64, label_name="softmax_label")
    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    metric = M.create(["acc", "ce"])
    mx.random.seed(seed)
    if pipelined:
        kwargs = dict(device_metrics=True, max_in_flight=3,
                      device_prefetch=True, metric_sync=2)
    else:
        kwargs = dict(device_metrics=False, max_in_flight=1,
                      device_prefetch=False)
    kwargs.update(fit_kwargs)
    mod.fit(it, num_epoch=num_epoch, eval_metric=metric, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), **kwargs)
    weights = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    return dict(metric.get_name_value()), weights, mod


def test_pipelined_fit_matches_sync_path():
    """K-in-flight + device metrics + device prefetch must reproduce the
    synchronous path: identical weights (same program, same order) and
    identical end-of-epoch metric values."""
    m_sync, w_sync, _ = _fit_mlp(pipelined=False)
    m_pipe, w_pipe, mod = _fit_mlp(pipelined=True)
    assert mod._fused is not None, "fused step was not armed"
    for k in w_sync:
        np.testing.assert_array_equal(
            w_sync[k], w_pipe[k],
            err_msg="weights diverged at %s: the pipeline changed the "
                    "training math" % k)
    # accuracy sums are integers: exact
    assert m_sync["accuracy"] == m_pipe["accuracy"], (m_sync, m_pipe)
    # cross-entropy partial sums accumulate in f32 on device
    np.testing.assert_allclose(m_sync["cross-entropy"],
                               m_pipe["cross-entropy"], rtol=1e-5)


def test_device_metric_accum_matches_host_metrics():
    rng = np.random.RandomState(0)
    pred = rng.rand(32, 10).astype("f4")
    pred /= pred.sum(1, keepdims=True)
    lab = rng.randint(0, 10, 32).astype("f4")
    for spec in ("acc", "ce", "mse", "mae", "rmse",
                 ["acc", "ce"]):
        host, dev = M.create(spec), M.create(spec)
        accum = M.DeviceMetricAccum.wrap(dev)
        assert accum is not None, spec
        for _ in range(3):
            host.update([mx.nd.array(lab)], [mx.nd.array(pred)])
            accum.update([mx.nd.array(lab)], [mx.nd.array(pred)])
        accum.sync()
        for (hn, hv), (dn, dv) in zip(host.get_name_value(),
                                      dev.get_name_value()):
            assert hn == dn
            np.testing.assert_allclose(hv, dv, rtol=1e-5, err_msg=str(spec))
    topk_h, topk_d = M.TopKAccuracy(top_k=3), M.TopKAccuracy(top_k=3)
    accum = M.DeviceMetricAccum.wrap(topk_d)
    topk_h.update([mx.nd.array(lab)], [mx.nd.array(pred)])
    accum.update([mx.nd.array(lab)], [mx.nd.array(pred)])
    accum.sync()
    assert topk_h.get()[1] == topk_d.get()[1]
    # instance counts stay exact host ints
    assert topk_d.num_inst == 32
    # metrics without kernels refuse the wrap (numpy fallback stays)
    assert M.DeviceMetricAccum.wrap(M.F1()) is None
    assert M.DeviceMetricAccum.wrap(M.create(["acc", M.F1()])) is None


def test_device_prefetch_hides_slow_producer():
    """A producer slower than free but faster than the step must be fully
    hidden. Deterministic stall accounting: instead of sleeping wall-clock
    and asserting an elapsed-time percentile (which fails under host
    contention — the old flake), the consumer WAITS on the producer's
    ``data_ready`` event before each ``next()``, making 'the step outlasts
    the fetch' a scheduling invariant. Every arrival must then find its
    batch already staged: ``io_prefetch_ready{state=hit}`` counts all
    n+1 arrivals (+1: the end-of-data probe) and ``state=wait`` none."""
    reg = tel.registry()
    hit0 = reg.counter("io_prefetch_ready", labels={"state": "hit"}).value
    wait0 = reg.counter("io_prefetch_ready", labels={"state": "wait"}).value
    X = np.random.RandomState(0).rand(96, 8).astype("f4")
    base = mx.io.NDArrayIter(X, np.zeros(96, "f4"), batch_size=4)
    it = mx.io.DevicePrefetchIter(
        mx.test_utils.FixedLatencyIter(base, 0.002))
    n = 0
    while True:
        # the "training step": by construction it ends only after the
        # producer staged the next batch — no timing assumption at all
        for e in it.data_ready:
            e.wait()
        try:
            it.next()
        except StopIteration:
            break
        n += 1
    it.close()
    assert n == 24
    hits = reg.counter("io_prefetch_ready",
                       labels={"state": "hit"}).value - hit0
    waits = reg.counter("io_prefetch_ready",
                        labels={"state": "wait"}).value - wait0
    assert hits + waits == n + 1  # +1: the end-of-data probe
    assert waits == 0, \
        "%d consumer arrivals blocked on the producer: prefetch failed " \
        "to hide the fetch latency" % waits


def test_prefetching_iter_lifecycle():
    """close() joins the producer threads; an exhausted iterator resets and
    iterates again; a closed iterator raises instead of hanging."""
    X = np.random.randn(16, 3).astype("f4")
    base = mx.io.NDArrayIter(X, np.zeros(16, "f4"), batch_size=4)
    it = mx.io.PrefetchingIter(base)
    assert len(list(it)) == 4
    it.reset()                   # regression: reset after exhaustion
    assert len(list(it)) == 4
    it.close()
    it.close()                   # idempotent
    assert not any(t.is_alive() for t in it.prefetch_threads)
    with pytest.raises(mx.base.MXNetError):
        it.reset()
    with pytest.raises(mx.base.MXNetError):
        it.next()
    # context-manager form
    with mx.io.PrefetchingIter(
            mx.io.NDArrayIter(X, np.zeros(16, "f4"), batch_size=4)) as it2:
        assert len(list(it2)) == 4
    assert not any(t.is_alive() for t in it2.prefetch_threads)


def test_ndarrayiter_multiworker_assembly_parity():
    X = np.random.RandomState(3).randn(17, 5).astype("f4")
    y = np.arange(17).astype("f4")
    multi = mx.io.NDArrayIter(X, y, batch_size=5, num_workers=2)
    single = mx.io.NDArrayIter(X, y, batch_size=5)
    for _ in range(2):           # epoch 1 + reset + epoch 2
        for bm, bs in zip(multi, single):
            np.testing.assert_array_equal(bm.data[0].asnumpy(),
                                          bs.data[0].asnumpy())
            np.testing.assert_array_equal(bm.label[0].asnumpy(),
                                          bs.label[0].asnumpy())
            assert bm.pad == bs.pad
        multi.reset()
        single.reset()
    multi.close()


def test_fit_emits_dispatch_and_pacing_series():
    reg = tel.registry()
    d0 = reg.histogram("fit_dispatch_ms").count
    s0 = reg.histogram("fit_step_ms").count
    w0 = reg.histogram("fit_sync_wait_ms").count
    m0 = reg.histogram("fit_metric_sync_ms").count
    _, _, mod = _fit_mlp(pipelined=True, num_epoch=1)
    assert mod._fused is not None
    batches = 256 // 64
    assert reg.histogram("fit_dispatch_ms").count == d0 + batches
    assert reg.histogram("fit_step_ms").count == s0 + batches
    # K=3 over 4 batches: window fills once -> at least one pacing wait
    assert reg.histogram("fit_sync_wait_ms").count > w0
    # cadence 2 over 4 batches + epoch end
    assert reg.histogram("fit_metric_sync_ms").count >= m0 + 2


def test_speedometer_consumes_cadence_snapshot():
    """With a device accumulator attached, Speedometer must read the
    cadence-synced snapshot, not force its own host sync."""
    from mxtpu.model import BatchEndParam
    m = M.create("acc")
    accum = M.DeviceMetricAccum.wrap(m)
    lab = np.array([0, 1, 1, 0], "f4")
    pred = np.eye(2, dtype="f4")[[0, 1, 0, 0]]
    accum.update([mx.nd.array(lab)], [mx.nd.array(pred)])
    accum.sync()
    m._device_accum = accum

    def _boom():
        raise AssertionError("Speedometer forced a host metric sync")
    m.get_name_value = _boom

    spd = mx.callback.Speedometer(batch_size=4, frequent=1,
                                  auto_reset=False, log=False)
    spd(BatchEndParam(epoch=0, nbatch=0, eval_metric=m, locals=None))
    spd(BatchEndParam(epoch=0, nbatch=1, eval_metric=m, locals=None))
    got = tel.registry().gauge("train_metric",
                               labels={"metric": "accuracy"}).value
    assert got == 0.75, got


def test_every_batch_sync_covers_first_batch():
    """Under the metric_sync=1 fallback (foreign batch callback), even the
    nbatch=0 callback must see synced values — never a nan metric."""
    X, y = _mnist_like(n=128)
    it = mx.io.NDArrayIter(X, y, batch_size=64, label_name="softmax_label")
    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    seen = []

    def spy(param):  # non-Speedometer: forces per-batch sync
        seen.append(dict(param.eval_metric.get_name_value()))

    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            batch_end_callback=spy, device_metrics=True)
    assert seen and not np.isnan(seen[0]["accuracy"]), seen[0]


def test_multi_context_module_keeps_numpy_metric_path():
    """Per-update-mean metrics (MSE/RMSE) are NOT merged-batch-equivalent
    across executor slices — the classic multi-exec path must decline the
    device view and keep the sliced numpy numerics."""
    import os
    X, y = _mnist_like(n=128)
    os.environ["MXTPU_FUSED_MODULE"] = "0"
    try:
        it = mx.io.NDArrayIter(X, y, batch_size=64,
                               label_name="softmax_label")
        mod = mx.mod.Module(_mlp.get_symbol(10),
                            context=[mx.cpu(0), mx.cpu(1)])
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd")
        batch = next(iter(it))
        mod.forward_backward(batch)
        mod.update()
        assert mod._device_step_view(batch) is None
    finally:
        os.environ.pop("MXTPU_FUSED_MODULE", None)


def test_fit_skips_epoch_param_roundtrip_when_device_resident():
    """With the fused step armed and no epoch_end_callback, fit must not
    round-trip parameters through get_params/set_params each epoch; with a
    callback, the params still flow to it."""
    X, y = _mnist_like(n=128)
    it = mx.io.NDArrayIter(X, y, batch_size=64, label_name="softmax_label")
    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    calls = []
    orig = mod.get_params
    mod.get_params = lambda: (calls.append(1), orig())[1]
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})
    assert mod._fused is not None
    assert not calls, "fit still round-trips params with device-resident " \
        "weights (%d get_params calls)" % len(calls)

    seen = []
    mod2 = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    mod2.fit(it, num_epoch=1, optimizer="sgd",
             optimizer_params={"learning_rate": 0.05},
             epoch_end_callback=lambda e, s, a, x: seen.append(set(a)))
    assert seen and "fc1_weight" in seen[0]
