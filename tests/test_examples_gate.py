"""Convergence gates driven through the EXAMPLE ENTRY POINTS themselves
(VERDICT r1 weak #7): the baseline configs must train, not just their
re-implementations in test files.

Model: reference tests/python/train/test_mlp.py:82 (accuracy >0.95 gate),
example/rnn/lstm_bucketing.py (perplexity falls), example/ssd/evaluate.py
(mAP improves with training).
"""
import os
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _example(*parts):
    path = os.path.join(_ROOT, "examples", *parts)
    sys.path.insert(0, os.path.dirname(path))
    return path


@pytest.mark.parametrize("network,epochs", [
    ("mlp", 12),
    pytest.param("lenet", 5, marks=pytest.mark.slow),  # tier-1 time
    # budget: the conv path is covered by the quicker gates; the full
    # 5-epoch lenet convergence gate runs in the slow tier
])
def test_train_mnist_gate(tmp_path, network, epochs):
    """LeNet/MLP on deterministic idx-format glyph MNIST through
    examples/image_classification/train_mnist.py must clear 0.95
    validation accuracy (the reference's MNIST gate)."""
    _example("image_classification", "train_mnist.py")
    import train_mnist
    acc = train_mnist.main([
        "--data-dir", str(tmp_path / "mnist"),
        "--network", network, "--num-epochs", str(epochs),
        "--lr", "0.05", "--batch-size", "64"])
    assert acc > 0.95, "%s reached only %.3f" % (network, acc)


def test_lstm_bucketing_gate():
    """BucketingModule LSTM LM through examples/rnn/lstm_bucketing.py:
    validation perplexity must fall clearly below its starting point
    (synthetic next-token corpus; random baseline ppl ~58).

    Gate re-derived 2026-08-04 (un-quarantining the PR-2 red): under
    jax 0.4.37 this config's loss plateaus for ~6 epochs before the
    phase transition — the old 6-epoch budget measured the plateau, not
    convergence (ratio stalled at 0.85-0.88). At 10 epochs the seeded
    trajectory breaks through decisively (ratios vs epoch-1:
    [1.0, .99, 1.01, 1.04, .99, .99, .72, .67, .62, .65]), so the 0.8
    bar is kept AS-IS and only the training budget moved to where
    current-jax convergence actually happens. Divergence still fails
    this gate: lr sweeps at 0.05/0.1 blow up past ratio 1.3."""
    _example("rnn", "lstm_bucketing.py")
    import mxtpu as mx
    import lstm_bucketing
    mx.random.seed(7)  # deterministic init regardless of suite order
    np.random.seed(7)  # NDArrayIter shuffle draws from numpy's global RNG
    ppl = lstm_bucketing.main([
        "--num-epochs", "10", "--num-hidden", "64", "--num-embed", "32"])
    assert len(ppl) == 10
    assert min(ppl[2:]) < ppl[0] * 0.8, \
        "perplexity did not fall: %s" % (ppl,)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_transformer_lm_gate():
    """Transformer LM through examples/transformer_lm/train_lm.py:
    perplexity falls AND the trained-weights seq-parallel ring-attention
    check agrees with single-device flash attention."""
    _example("transformer_lm", "train_lm.py")
    import mxtpu as mx
    import train_lm
    mx.random.seed(7)  # deterministic init regardless of suite order
    np.random.seed(7)  # NDArrayIter shuffle draws from numpy's global RNG
    ppl = train_lm.main(["--epochs", "2", "--seq-len", "32",
                         "--d-model", "64", "--num-heads", "4",
                         "--seq-parallel"])
    assert len(ppl) == 2
    assert ppl[1] < ppl[0] * 0.8, "perplexity did not fall: %s" % (ppl,)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_ssd_gate(tmp_path):
    """SSD through examples/ssd/train.py + evaluate.py: mAP on painted
    synthetic boxes must improve materially over the untrained net."""
    _example("ssd", "train.py")
    import mxtpu as mx
    import train as ssd_train
    import evaluate as ssd_eval
    prefix = str(tmp_path / "ssd")
    common = ["--data-shape", "64", "--num-classes", "3",
              "--num-scales", "3", "--batch-size", "8",
              "--network", "tiny"]
    map_untrained = ssd_eval.main(common + ["--num-batches", "2"])
    # seed immediately before training so the init draw is deterministic
    # regardless of suite order or the eval above
    mx.random.seed(2)
    np.random.seed(2)  # NDArrayIter shuffle draws from numpy's global RNG
    _mod, metrics = ssd_train.main(common + [
        "--num-batches", "8", "--num-epochs", "12", "--lr", "0.05",
        "--prefix", prefix])
    assert dict(metrics)["CrossEntropy"] < 1.2, metrics
    map_trained = ssd_eval.main(common + [
        "--num-batches", "2", "--prefix", prefix, "--epoch", "12"])
    assert map_trained > max(map_untrained, 0.05), \
        "mAP did not improve: %.4f -> %.4f" % (map_untrained, map_trained)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_train_imagenet_on_packed_rec(tmp_path):
    """config-2 flow end to end on real (synthetic-JPEG) recordio data:
    pack a .rec, run examples/image_classification/train_imagenet.py on a
    tiny resnet, get a steady-state throughput measurement (VERDICT r1
    weak #5: steady-state step time with real data)."""
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    _example("image_classification", "train_imagenet.py")
    import bench_input
    import train_imagenet

    rec = bench_input.make_rec(str(tmp_path / "synth.rec"), 96, edge=40)
    speed = train_imagenet.main([
        "--data-train", rec, "--num-layers", "18",
        "--image-shape", "3,32,32", "--num-classes", "10",
        "--batch-size", "16", "--num-epochs", "2", "--kv-store", "local",
        "--speedometer-period", "2"])
    assert speed > 0, "no steady-state throughput measured"


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_gluon_word_lm_gate():
    """Imperative Gluon LSTM LM through examples/gluon/word_language_model
    (parity: the reference's example/gluon/word_language_model): validation
    perplexity must fall on the synthetic Markov corpus."""
    _example("gluon", "word_language_model.py")
    import mxtpu as mx
    import word_language_model
    mx.random.seed(11)
    np.random.seed(11)  # NDArrayIter shuffle draws from numpy's global RNG
    ppl = word_language_model.main(["--epochs", "4", "--n-tokens", "8000",
                                    "--num-hidden", "48", "--lr", "2"])
    assert len(ppl) == 4
    assert ppl[-1] < ppl[0] * 0.5, "val ppl did not fall: %s" % (ppl,)


def test_gluon_super_resolution_gate():
    """ESPCN-style super resolution through examples/gluon/
    super_resolution.py (parity: the reference's gluon example): val PSNR
    must rise clearly above the untrained net's."""
    _example("gluon", "super_resolution.py")
    import mxtpu as mx
    import super_resolution
    mx.random.seed(3)
    np.random.seed(3)  # NDArrayIter shuffle draws from numpy's global RNG
    psnrs = super_resolution.main(["--epochs", "2"])
    assert psnrs[-1] > psnrs[0] + 3.0, \
        "PSNR did not improve enough: %s" % (psnrs,)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_gluon_dcgan_gate():
    """DCGAN through examples/gluon/dcgan.py (parity: the reference's
    example/gluon/dcgan.py): the Conv2DTranspose generator must at some
    point genuinely fool the discriminator (min fake-detection < 0.9,
    vs ~1.0 against an untrained generator)."""
    _example("gluon", "dcgan.py")
    import mxtpu as mx
    import dcgan
    mx.random.seed(5)
    np.random.seed(5)  # NDArrayIter shuffle draws from numpy's global RNG
    acc0, min_acc = dcgan.main(["--epochs", "4"])
    assert min_acc < 0.9, \
        "generator never fooled the discriminator: first=%s min=%s" \
        % (acc0, min_acc)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_train_imagenet_network_flag_variants(tmp_path):
    """The --network dispatch covers the full symbols/ family: run one
    tiny epoch with resnext (grouped conv) and mobilenet (depthwise) on
    packed recordio data — the config-2 flow exercised for the round-3
    factories."""
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    _example("image_classification", "train_imagenet.py")
    import bench_input
    import train_imagenet

    rec = bench_input.make_rec(str(tmp_path / "synth.rec"), 32, edge=40)
    for network in ("resnext", "resnet-v1"):
        speed = train_imagenet.main([
            "--data-train", rec, "--network", network, "--num-layers", "26"
            if network == "resnext" else "18",
            "--image-shape", "3,32,32", "--num-classes", "10",
            "--batch-size", "16", "--num-epochs", "1", "--kv-store",
            "local", "--speedometer-period", "1"])
        assert speed > 0, network


def test_model_parallel_lstm_gate():
    """group2ctx model parallelism end to end (parity:
    example/model-parallel-lstm/lstm.py): a 2-layer LSTM LM with layer
    groups placed on two devices trains and perplexity falls."""
    _example("rnn", "model_parallel_lstm.py")
    import model_parallel_lstm
    ppl = model_parallel_lstm.main(["--epochs", "3", "--n-tokens", "3000"])
    assert len(ppl) == 3
    assert ppl[-1] < ppl[0] * 0.97, "perplexity did not fall: %s" % (ppl,)


def test_sparse_linear_classification_gate():
    """Sparse pipeline end to end (parity: example/sparse/
    linear_classification.py): LibSVM csr batches + row_sparse weight via
    kvstore row_sparse_pull + server-side SGD; accuracy must climb well
    above chance."""
    _example("sparse", "linear_classification.py")
    import linear_classification
    accs = linear_classification.main(["--epochs", "5",
                                       "--num-examples", "512"])
    assert accs[-1] > 0.8, "sparse training reached only %s" % (accs,)


def test_adversary_fgsm_gate():
    """FGSM adversarial examples (parity: example/adversary): input-space
    gradients through the imperative tape — clean accuracy high, one
    signed-gradient step collapses it."""
    _example("adversary", "fgsm_mnist.py")
    import fgsm_mnist
    clean, adv = fgsm_mnist.main(["--epochs", "3", "--epsilon", "0.3",
                                  "--num-examples", "768"])
    assert clean > 0.95, clean
    assert adv < clean - 0.2, (clean, adv)


def test_text_cnn_gate():
    """Kim-CNN sentence classification (parity:
    example/cnn_text_classification): embedding + parallel conv widths +
    max-over-time through Module.fit; val accuracy > 0.9."""
    _example("cnn_text_classification", "text_cnn.py")
    import text_cnn
    acc = text_cnn.main(["--epochs", "4"])
    assert acc > 0.9, acc


def test_bi_lstm_sort_gate():
    """BidirectionalCell end to end (parity: example/bi-lstm-sort): a
    BiLSTM learns to emit the sorted input sequence — each position
    depends on the WHOLE sequence, so the backward direction must work;
    held-out token accuracy > 0.85."""
    _example("bi-lstm-sort", "sort_io.py")
    import sort_io
    acc = sort_io.main(["--epochs", "5", "--num-examples", "1536"])
    assert acc > 0.85, acc


def test_multitask_gate():
    """Two loss heads on one trunk via sym.Group (parity:
    example/multi-task): both tasks learn jointly."""
    _example("multi-task", "multitask_mnist.py")
    import multitask_mnist
    d, p = multitask_mnist.main(["--epochs", "4"])
    assert d > 0.95 and p > 0.95, (d, p)


def test_svm_output_gate():
    """SVMOutput hinge-loss head end to end (parity: example/svm_mnist):
    both the linear-hinge and squared-hinge variants train."""
    _example("svm_mnist", "svm_mnist.py")
    import svm_mnist
    assert svm_mnist.main(["--epochs", "4"]) > 0.95
    assert svm_mnist.main(["--epochs", "4", "--squared"]) > 0.95


def test_autoencoder_gate():
    """AE reconstruction through LinearRegressionOutput (parity:
    example/autoencoder): bottleneck reconstruction captures most of the
    low-rank data's power."""
    _example("autoencoder", "autoencoder.py")
    import autoencoder
    mse, var = autoencoder.main(["--epochs", "5"])
    assert mse < 0.35 * var, (mse, var)


def test_lstm_bucketing_fused_gate():
    """The fused variant (cudnn_lstm_bucketing.py parity: one multi-layer
    RNN op lowered to an XLA while loop) trains under BucketingModule."""
    _example("rnn", "lstm_bucketing.py")
    import mxtpu as mx
    import lstm_bucketing
    mx.random.seed(7)
    np.random.seed(7)  # NDArrayIter shuffle rides the global numpy RNG
    ppl = lstm_bucketing.main([
        "--fused", "--num-epochs", "8", "--num-hidden", "64",
        "--num-embed", "32"])
    assert min(ppl[2:]) < ppl[0] * 0.85, \
        "fused perplexity did not fall: %s" % (ppl,)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_nce_loss_gate():
    """NCE training (parity: example/nce-loss): binary noise-contrastive
    objective with unigram negatives; the NCE-trained embeddings beat the
    unigram baseline by a wide margin under FULL-softmax evaluation."""
    _example("nce-loss", "nce_lm.py")
    import nce_lm
    acc, base = nce_lm.main(["--epochs", "6", "--lr", "1.0"])
    assert acc > 3 * base, (acc, base)


def test_numpy_ops_custom_softmax_gate():
    """Custom-op softmax head (examples/numpy_ops/custom_softmax.py,
    parity example/numpy-ops/custom_softmax.py): the numpy CustomOp loss
    trains an MLP to >0.9 val accuracy through the host-callback path."""
    _example("numpy_ops", "custom_softmax.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import custom_softmax
    acc = custom_softmax.main(["--epochs", "6"])
    assert acc > 0.9, "custom-softmax MLP reached only %.3f" % acc


def test_recommenders_matrix_fact_gate():
    """Matrix factorization (examples/recommenders/matrix_fact.py, parity
    example/recommenders/matrix_fact.py): embeddings + inner product +
    LinearRegressionOutput recover low-rank ratings to RMSE < 0.35
    (ground-truth noise is 0.1; untrained is ~1.0)."""
    _example("recommenders", "matrix_fact.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import matrix_fact
    score = matrix_fact.main(["--epochs", "8"])
    assert score < 0.35, "MF val RMSE stuck at %.3f" % score


def test_gan_symbolic_gate():
    """Symbolic DCGAN (examples/gan/dcgan_sym.py, parity
    example/gan/dcgan.py): the Module-level GAN loop — inputs_need_grad,
    fake/real grad accumulation, G updated through D.get_input_grads() —
    must let the generator genuinely fool the discriminator at some
    point (min fake-detect accuracy < 0.9)."""
    _example("gan", "dcgan_sym.py")
    import mxtpu as mx
    import dcgan_sym
    mx.random.seed(7)
    np.random.seed(7)  # NDArrayIter shuffle draws from numpy's global RNG
    first_acc, min_acc = dcgan_sym.main(["--epochs", "3"])
    assert min_acc < 0.9, \
        "generator never fooled D: first=%s min=%s" % (first_acc, min_acc)


def test_fcn_xs_gate():
    """FCN segmentation (examples/fcn-xs/fcn_xs.py, parity
    example/fcn-xs/symbol_fcnxs.py): conv trunk + 1x1 score +
    Deconvolution upsample + Crop + multi_output SoftmaxOutput reaches
    >0.9 per-pixel accuracy on separable rectangles."""
    _example("fcn-xs", "fcn_xs.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import fcn_xs
    acc = fcn_xs.main(["--epochs", "12"])
    assert acc > 0.9, "fcn-xs pixel accuracy stuck at %.3f" % acc


def test_neural_style_gate():
    """Neural style (examples/neural-style/nstyle.py, parity
    example/neural-style/nstyle.py): input-space optimization against
    Gram/content targets — the weighted loss must fall by >60%."""
    _example("neural-style", "nstyle.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import nstyle
    first, last = nstyle.main(["--iters", "40"])
    assert last < first * 0.4, \
        "style loss barely moved: %.5f -> %.5f" % (first, last)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_dqn_gate():
    """DQN on the deterministic grid world (examples/reinforcement-learning/
    dqn.py, parity example/reinforcement-learning/dqn): replay + target net
    + TD regression must produce a greedy policy that reaches the goal —
    mean return over fixed starts > 0.5 (random policy is ~ -0.3)."""
    _example("reinforcement-learning", "dqn.py")
    import mxtpu as mx
    mx.random.seed(42)
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import dqn
    ret = dqn.main(["--updates", "400"])
    assert ret > 0.5, "greedy return stuck at %.3f" % ret


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_parallel_actor_critic_gate():
    """Parallel A2C on vectorized CartPole (examples/reinforcement-learning/
    parallel_actor_critic.py, parity example/reinforcement-learning/
    parallel_actor_critic): mean episode length over the last completed
    episodes must clear 50 (untrained policy balances ~10-25 steps)."""
    _example("reinforcement-learning", "parallel_actor_critic.py")
    import mxtpu as mx
    mx.random.seed(42)
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import parallel_actor_critic
    steps = parallel_actor_critic.main(["--iters", "250"])
    assert steps > 50, "episode length stuck at %.1f" % steps


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_stochastic_depth_gate():
    """Stochastic-depth residual net (examples/stochastic-depth/
    sd_cifar10.py, parity example/stochastic-depth): whole-branch Bernoulli
    gates via in-graph Dropout-on-ones train to >0.85 val accuracy, and the
    gates are identity at inference (deterministic eval)."""
    _example("stochastic-depth", "sd_cifar10.py")
    import mxtpu as mx
    mx.random.seed(42)
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import sd_cifar10
    acc = sd_cifar10.main(["--epochs", "8"])
    assert acc > 0.85, "stochastic-depth net reached only %.3f" % acc


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_dec_gate():
    """Deep Embedded Clustering (examples/dec/dec.py, parity
    example/dec/dec.py): AE pretrain + Student-t KL refinement with
    trainable centroids must reach >0.9 clustering accuracy on 4 blobs
    through a 2-D bottleneck."""
    _example("dec", "dec.py")
    import mxtpu as mx
    mx.random.seed(42)
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import dec
    acc = dec.main([])
    assert acc > 0.9, "DEC cluster accuracy stuck at %.3f" % acc


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_vae_gate():
    """Variational autoencoder (examples/vae/vae.py, parity example/vae):
    reparameterized ELBO training must cut the validation negative ELBO to
    under half its untrained value."""
    _example("vae", "vae.py")
    import mxtpu as mx
    mx.random.seed(42)
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import vae
    start, end = vae.main(["--epochs", "30"])
    assert end < 0.5 * start, "-ELBO %.2f -> %.2f (no real improvement)" \
        % (start, end)


def test_dsd_gate():
    """Dense-Sparse-Dense retraining (examples/dsd/dsd.py, parity
    example/dsd): magnitude pruning to 60% sparsity must actually zero the
    weights mid-phase, and the final re-densified model must hold the dense
    baseline's accuracy (within 2 points) or beat it."""
    _example("dsd", "dsd.py")
    import mxtpu as mx
    mx.random.seed(42)
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import dsd
    dense, sparse, final, frac_zero = dsd.main([])
    assert frac_zero > 0.55, "mask not applied: zero frac %.2f" % frac_zero
    assert final > 0.8, "DSD model never learned: final %.3f" % final
    assert final >= dense - 0.02, \
        "DSD lost accuracy: dense %.3f -> final %.3f" % (dense, final)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_speech_acoustic_gate():
    """Frame-level acoustic model (examples/speech-demo/speech_acoustic.py,
    parity example/speech-demo): BiLSTM over synthetic filterbank frames
    with per-frame cross-entropy must clear 0.9 frame accuracy (chance is
    ~0.17 over 6 phoneme classes)."""
    _example("speech-demo", "speech_acoustic.py")
    import mxtpu as mx
    mx.random.seed(42)
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import speech_acoustic
    acc = speech_acoustic.main(["--epochs", "8"])
    assert acc > 0.9, "frame accuracy stuck at %.3f" % acc


def test_sgld_bnn_gate():
    """SGLD Bayesian net (examples/bayesian-methods/sgld_bnn.py, parity
    example/bayesian-methods): posterior-ensemble prediction must classify
    two-moons >0.9 and be more uncertain off-distribution than on it."""
    _example("bayesian-methods", "sgld_bnn.py")
    import mxtpu as mx
    mx.random.seed(42)
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import sgld_bnn
    acc_single, acc_ens, h_mean, h_ens, spread = sgld_bnn.main(
        ["--epochs", "30", "--burn-in", "15", "--lr", "0.0003"])
    assert acc_ens > 0.9, "ensemble accuracy %.3f" % acc_ens
    assert spread > 1e-4, "posterior collapsed: weight spread %.5f" % spread
    # Jensen: mixture entropy dominates the mean per-sample entropy
    assert h_ens >= h_mean - 1e-6, \
        "mixture entropy %.3f below mean single %.3f" % (h_ens, h_mean)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_lstm_ocr_ctc_gate():
    """LSTM+CTC OCR (examples/ctc/lstm_ocr.py, parity example/ctc/
    lstm_ocr.py + example/captcha): an unrolled two-layer LSTM over image
    columns with the `_contrib_CTCLoss` head must read >0.8 of held-out
    variable-length digit strips exactly (greedy CTC decode)."""
    _example("ctc", "lstm_ocr.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import lstm_ocr
    acc = lstm_ocr.main(["--epochs", "25", "--lr", "0.01"])
    assert acc > 0.8, "OCR sequence accuracy stuck at %.3f" % acc


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_rcnn_gate():
    """Faster R-CNN (examples/rcnn/train_end2end.py, parity example/rcnn):
    RPN anchor losses + `_contrib_Proposal` + CustomOp proposal-target
    sampling + ROIPooling heads trained jointly must localize+classify
    >0.8 of synthetic single-object scenes (IoU>0.5, right class)."""
    _example("rcnn", "train_end2end.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import train_end2end
    acc = train_end2end.main(["--epochs", "6"])
    assert acc > 0.8, "rcnn detection accuracy stuck at %.3f" % acc


def test_python_loss_module_gate():
    """SequentialModule + PythonLossModule (examples/module/python_loss.py,
    parity example/module/python_loss.py): a numpy multiclass-hinge
    gradient injected behind a symbolic trunk trains to >0.9."""
    _example("module", "python_loss.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import python_loss
    acc = python_loss.main(["--epochs", "8"])
    assert acc > 0.9, "hinge-loss MLP stuck at %.3f" % acc


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_time_major_rnn_gate():
    """Time-major unroll (examples/rnn-time-major/rnn_cell_demo.py, parity
    example/rnn-time-major): LSTM LM over (T, N) batches converges toward
    the corpus noise floor."""
    _example("rnn-time-major", "rnn_cell_demo.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import rnn_cell_demo
    hist = rnn_cell_demo.main(["--epochs", "6"])
    assert hist[-1] < hist[0] * 0.6, "perplexity did not fall: %s" % hist
    assert hist[-1] < 2.2, "final perplexity %.2f above noise floor" % hist[-1]


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_profiler_matmul_example():
    """Profiler demo (examples/profiler/profiler_matmul.py, parity
    example/profiler): every dot in the chain gets a chrome-trace span."""
    import os
    import tempfile
    _example("profiler", "profiler_matmul.py")
    import profiler_matmul
    with tempfile.TemporaryDirectory() as d:
        spans, dots = profiler_matmul.main(
            ["--chain", "4", "--file", os.path.join(d, "t.json")])
    assert dots == 4, "expected 4 dot spans, saw %d (total %d)" % (dots, spans)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_memcost_example():
    """Residual-memory plans (examples/memcost/inception_memcost.py,
    parity example/memcost): block remat must cut the saved-activation
    bytes by >2x vs keep-all, and whole-forward mirror below block."""
    _example("memcost", "inception_memcost.py")
    import inception_memcost
    res = inception_memcost.main(["--batch-size", "4", "--image-size", "96"])
    keep = res["keep_all"]["act_mb"]
    block = res["block"]["act_mb"]
    mirror = res["mirror"]["act_mb"]
    assert block < keep / 2, "block remat saved nothing: %s" % (res,)
    assert mirror <= block, "mirror above block: %s" % (res,)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_torch_module_example_gate():
    """Torch-in-graph (examples/torch/torch_module.py, parity
    example/torch): a torch.nn block inside the Symbol trains to >0.9."""
    _example("torch", "torch_module.py")
    import mxtpu as mx
    mx.random.seed(42)  # deterministic init regardless of suite order
    np.random.seed(42)  # NDArrayIter shuffle draws from numpy's global RNG
    import torch_module
    acc = torch_module.main(["--epochs", "6"])
    assert acc > 0.9, "torch-in-graph accuracy stuck at %.3f" % acc


def test_python_howto_examples():
    """API how-tos (examples/python-howto/howtos.py, parity
    example/python-howto): monitor stats, multi-output Group, conv
    debugging, manual DataIter driving — all four mechanisms work."""
    _example("python-howto", "howtos.py")
    import howtos
    assert howtos.main() is True


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_adversarial_vae_gate():
    """VAE/GAN hybrid (examples/mxnet_adversarial_vae/vaegan.py, parity
    example/mxnet_adversarial_vae): three-way E/G/D training must drive
    reconstruction well below the data power while the discriminator
    falls from certainty toward equilibrium."""
    _example("mxnet_adversarial_vae", "vaegan.py")
    import vaegan
    # determinism comes from vaegan.main's own --seed (it reseeds both
    # RNGs first thing)
    d_accs, recs, mse, power = vaegan.main(["--epochs", "8"])
    assert mse < power / 4, "reconstruction never learned: %.3f vs %.3f" \
        % (mse, power)
    assert recs[-1] < recs[0] * 0.8, "recon loss did not fall: %s" % recs
    assert d_accs[-1] < 0.98, "D stayed certain: %s" % d_accs


@pytest.mark.parametrize("network,epochs,floor", [("mlp", 10, 0.9),
                                                  ("lenet", 8, 0.85)])
def test_caffe_net_gate(network, epochs, floor):
    """In-graph caffe layers (examples/caffe/caffe_net.py, parity
    example/caffe/caffe_net.py): MLP and LeNet composed from
    mx.sym.CaffeOp inline-prototxt layers must learn their synthetic
    tasks through Module.fit."""
    _example("caffe", "caffe_net.py")
    import caffe_net
    acc = caffe_net.main(["--network", network, "--epochs", str(epochs)])
    assert acc > floor, "caffe %s reached only %.3f" % (network, acc)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note): the
# heaviest convergence gates run in the slow tier (-m slow) so the
# 870s window covers the whole suite instead of truncating mid-file
def test_kaggle_ndsb1_gate(tmp_path):
    """Full NDSB-1 recipe (examples/kaggle-ndsb1, parity
    example/kaggle-ndsb1): class-folder tree -> gen_img_list
    (stratified) -> im2rec pack -> ImageRecordIter train w/ checkpoint
    -> checkpoint predict -> kaggle submission csv."""
    import csv
    import subprocess

    import cv2

    _example("kaggle-ndsb1", "gen_img_list.py")
    import gen_img_list
    import predict_dsb
    import submission_dsb
    import train_dsb

    # synthetic "plankton": class = dominant color channel pattern
    rng = np.random.RandomState(5)
    classes = ["copepod", "diatom", "protist", "shrimp"]
    train_dir = tmp_path / "data" / "train"
    test_dir = tmp_path / "data" / "test"
    test_dir.mkdir(parents=True)
    for li, cls in enumerate(classes):
        sub = train_dir / cls
        sub.mkdir(parents=True)
        for i in range(24):
            img = (rng.rand(32, 32, 3) * 60).astype(int)
            img[..., li % 3] += 150
            if li == 3:  # 4th class: bright everywhere
                img += 120
            img = np.clip(img, 0, 255).astype("uint8")
            cv2.imwrite(str(sub / ("%s_%d.jpg" % (cls, i))), img)
    for i in range(12):
        li = i % 4
        img = (rng.rand(32, 32, 3) * 60).astype(int)
        img[..., li % 3] += 150
        if li == 3:
            img += 120
        img = np.clip(img, 0, 255).astype("uint8")
        cv2.imwrite(str(test_dir / ("t%03d.jpg" % i)), img)

    data = str(tmp_path / "data")
    gen_img_list.main(["--image-folder", str(train_dir),
                       "--out-folder", data, "--train", "--stratified"])
    gen_img_list.main(["--image-folder", str(test_dir),
                       "--out-folder", data, "--out-file", "test.lst"])
    # stratified split: every class in both lists
    for lst in ("tr.lst", "va.lst"):
        labels = {ln.split("\t")[1] for ln in open(os.path.join(data, lst))}
        assert len(labels) == 4, (lst, labels)

    im2rec = os.path.join(_ROOT, "tools", "im2rec.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT)
    for name, root in [("tr", str(train_dir)), ("va", str(train_dir)),
                       ("test", str(test_dir))]:
        r = subprocess.run(
            [sys.executable, im2rec, os.path.join(data, name), root,
             "--resize", "24"], capture_output=True, text=True, env=env)
        assert r.returncode == 0, r.stdout + r.stderr

    prefix = str(tmp_path / "models" / "dsb")
    os.makedirs(os.path.dirname(prefix))
    acc = train_dsb.main(["--data-dir", data, "--num-classes", "4",
                          "--edge", "24", "--batch-size", "24",
                          "--num-epochs", "25", "--width", "0.5",
                          "--optimizer", "adam", "--lr", "0.002",
                          "--model-prefix", prefix])
    assert acc > 0.8, "ndsb1 val accuracy only %.3f" % acc

    probs = predict_dsb.main(["--model-prefix", prefix, "--epoch", "25",
                              "--test-rec", os.path.join(data, "test.rec"),
                              "--num-classes", "4", "--edge", "24",
                              "--batch-size", "6",
                              "--out", str(tmp_path / "probs.npy")])
    assert probs.shape == (12, 4)

    out_csv = str(tmp_path / "submission.csv")
    submission_dsb.main(["--probs", str(tmp_path / "probs.npy"),
                         "--test-lst", os.path.join(data, "test.lst"),
                         "--classes", os.path.join(data, "classes.txt"),
                         "--out", out_csv])
    with open(out_csv) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["image"] + classes
    assert len(rows) == 13
    body = np.array([[float(x) for x in r[1:]] for r in rows[1:]])
    np.testing.assert_allclose(body.sum(axis=1), 1.0, atol=1e-4)


def test_kaggle_ndsb2_gate(tmp_path):
    """NDSB-2 recipe (examples/kaggle-ndsb2, parity
    example/kaggle-ndsb2): synthetic beating-heart studies ->
    Preprocessing (CSV tensors + CDF label encode) -> frame-diff LeNet
    through CSVIter/FeedForward/LogisticRegressionOutput with the CRPS
    metric; training must beat the predict-the-prior CRPS baseline."""
    import csv as _csv

    import cv2

    _example("kaggle-ndsb2", "Preprocessing.py")
    import Preprocessing
    import Train

    rng = np.random.RandomState(9)
    frames, edge, cdf = 8, 24, 40
    root = tmp_path / "train"
    root.mkdir()
    labels = []
    for s in range(24):
        sid = "s%03d" % s
        (root / sid).mkdir()
        base_r = rng.uniform(4, 9)       # diastole radius
        amp = rng.uniform(0.3, 0.6)      # contraction amount
        for t in range(frames):
            phase = np.cos(2 * np.pi * t / frames) * 0.5 + 0.5
            r = base_r * (1 - amp * phase)
            img = np.zeros((edge, edge), np.uint8)
            cv2.circle(img, (edge // 2, edge // 2), int(round(r)), 200,
                       -1)
            cv2.imwrite(str(root / sid / ("frame_%02d.png" % t)), img)
        area = np.pi * base_r ** 2
        labels.append((sid, area * (1 - amp) ** 2 / 20, area / 20))
    with open(root / "labels.csv", "w", newline="") as f:
        w = _csv.writer(f)
        w.writerow(["Id", "Systole", "Diastole"])
        for row in labels:
            w.writerow([row[0], "%.2f" % row[1], "%.2f" % row[2]])

    prefix = str(tmp_path / "train")
    cwd = os.getcwd()
    Preprocessing.main(["--root", str(root), "--out-prefix", prefix,
                        "--frames", str(frames), "--edge", str(edge),
                        "--cdf-dim", str(cdf)])
    assert os.path.exists("%s-%dx%d-data.csv" % (prefix, edge, edge))

    sys_score, dia_score = Train.main(
        ["--data-prefix", prefix, "--frames", str(frames),
         "--edge", str(edge), "--cdf-dim", str(cdf),
         "--num-filter", "12", "--batch-size", "12",
         "--num-epochs", "12", "--lr", "0.01"])

    # baseline: predicting the mean encoded target everywhere
    enc = np.loadtxt(prefix + "-systole.csv", delimiter=",")
    base = Train.CRPS(enc, np.tile(enc.mean(0), (enc.shape[0], 1)))
    assert sys_score < base * 0.6, (sys_score, base)
    assert dia_score < base * 0.8, (dia_score, base)
    assert os.getcwd() == cwd
