"""Test env: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's trick of testing multi-device paths with multiple CPU
contexts (SURVEY.md §4, tests/python/unittest/test_model_parallel.py).

TPU tier: ``MXTPU_TEST_TPU=1 pytest -m tpu`` keeps the accelerator backend
available (CPU stays reachable via jax.devices('cpu')) and runs the
cross-device consistency tests — the analogue of the reference's GPU tier
(tests/python/gpu/test_operator_gpu.py check_consistency).
"""
import os

import pytest

_TPU_TIER = os.environ.get("MXTPU_TEST_TPU") == "1"

if not _TPU_TIER:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

# Some environments eagerly register an accelerator PJRT plugin at
# interpreter startup (sitecustomize), which overrides JAX_PLATFORMS set
# here. jax.config.update still wins as long as no backend has been
# initialized yet, so force it explicitly too.
import jax  # noqa: E402

if not _TPU_TIER:
    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: cross-device consistency tests that need a real "
        "accelerator (run with MXTPU_TEST_TPU=1 pytest -m tpu)")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` gate "
        "(long convergence runs and known-flaky-threshold gates)")


def pytest_collection_modifyitems(config, items):
    for item in items:
        if "tpu" in item.keywords and not _TPU_TIER:
            item.add_marker(pytest.mark.skip(
                reason="TPU tier disabled (set MXTPU_TEST_TPU=1)"))
        elif "tpu" not in item.keywords and _TPU_TIER and \
                config.getoption("-m") == "tpu":
            pass  # -m tpu already deselects these
