"""Test env: force an 8-device virtual CPU mesh BEFORE jax initializes.

Mirrors the reference's trick of testing multi-device paths with multiple CPU
contexts (SURVEY.md §4, tests/python/unittest/test_model_parallel.py)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Some environments eagerly register an accelerator PJRT plugin at
# interpreter startup (sitecustomize), which overrides JAX_PLATFORMS set
# here. jax.config.update still wins as long as no backend has been
# initialized yet, so force it explicitly too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
