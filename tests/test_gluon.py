"""Gluon tests (model: reference tests/python/unittest/test_gluon.py,
test_nn.py convergence tests)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, gluon, nd
from mxtpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(4, 3))
    p.initialize(init=mx.initializer.One(), ctx=mx.cpu())
    assert np.allclose(p.data().asnumpy(), 1)
    assert p.list_ctx() == [mx.cpu()]
    p.zero_grad()
    assert np.allclose(p.grad().asnumpy(), 0)


def test_parameter_dict():
    params = gluon.ParameterDict("net_")
    w = params.get("w", shape=(2, 2))
    assert w.name == "net_w"
    assert params.get("w") is w
    params.initialize(ctx=mx.cpu())


def test_dense_forward():
    layer = nn.Dense(8, in_units=4)
    layer.initialize(ctx=mx.cpu())
    x = nd.ones((2, 4))
    out = layer(x)
    assert out.shape == (2, 8)


def test_dense_deferred_init():
    layer = nn.Dense(8)
    layer.initialize(ctx=mx.cpu())
    out = layer(nd.ones((2, 5)))
    assert out.shape == (2, 8)
    assert layer.weight.shape == (8, 5)


def test_sequential_and_training():
    mx.random.seed(5)  # deterministic init regardless of suite order
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize(init=mx.initializer.Xavier(), ctx=mx.cpu())

    rng = np.random.RandomState(0)
    centers = rng.randn(4, 16) * 3
    y = rng.randint(0, 4, 512)
    X = (centers[y] + rng.randn(512, 16)).astype("float32")

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(10):
        for i in range(0, 512, 64):
            data = nd.array(X[i:i + 64])
            label = nd.array(y[i:i + 64].astype("float32"))
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(64)
    preds = net(nd.array(X)).asnumpy().argmax(axis=1)
    acc = (preds == y).mean()
    assert acc > 0.9, "gluon training accuracy %f" % acc


def test_hybridize_consistency():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"))
    net.add(nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    x = nd.array(np.random.randn(3, 8).astype("f4"))
    out_imperative = net(x).asnumpy()
    net.hybridize()
    out_hybrid = net(x).asnumpy()
    assert np.allclose(out_imperative, out_hybrid, atol=1e-5)


def test_hybridize_training():
    """Gradients must flow through the cached (fused) op."""
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"))
    net.add(nn.Dense(2))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    x = nd.array(np.random.randn(4, 6).astype("f4"))
    with autograd.record():
        out = net(x)
        loss = (out * out).sum()
    loss.backward()
    w = net[0].weight
    assert float(np.abs(w.grad().asnumpy()).sum()) > 0


def test_conv_block():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3, padding=1, activation="relu"))
    net.add(nn.MaxPool2D(2, 2))
    net.add(nn.BatchNorm())
    net.add(nn.Flatten())
    net.add(nn.Dense(3))
    net.initialize(ctx=mx.cpu())
    out = net(nd.ones((2, 3, 8, 8)))
    assert out.shape == (2, 3)


def test_batchnorm_running_stats():
    layer = nn.BatchNorm(in_channels=3)
    layer.initialize(ctx=mx.cpu())
    x = nd.array(np.random.randn(8, 3, 4, 4).astype("f4") * 3 + 1)
    rm0 = layer.running_mean.data().asnumpy().copy()
    with autograd.record():
        layer(x)
    rm1 = layer.running_mean.data().asnumpy()
    assert not np.allclose(rm0, rm1)
    # eval mode: no update
    layer(x)
    rm2 = layer.running_mean.data().asnumpy()
    assert np.allclose(rm1, rm2)


def test_embedding_block():
    emb = nn.Embedding(10, 4)
    emb.initialize(ctx=mx.cpu())
    out = emb(nd.array(np.array([1, 2], dtype="f4")))
    assert out.shape == (2, 4)


def test_losses():
    pred = nd.array(np.random.randn(4, 5).astype("f4"))
    label = nd.array(np.array([0, 1, 2, 3], dtype="f4"))
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    assert l.shape == (4,)
    l2 = gluon.loss.L2Loss()(pred, nd.zeros((4, 5)))
    assert np.allclose(l2.asnumpy(),
                       (pred.asnumpy() ** 2).mean(axis=1) / 2, atol=1e-5)
    l1 = gluon.loss.L1Loss()(pred, nd.zeros((4, 5)))
    assert np.allclose(l1.asnumpy(), np.abs(pred.asnumpy()).mean(axis=1),
                       atol=1e-6)
    hu = gluon.loss.HuberLoss()(pred, nd.zeros((4, 5)))
    assert hu.shape == (4,)


def test_save_load_params(tmp_path):
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
    net.initialize(ctx=mx.cpu())
    fname = str(tmp_path / "net.params")
    net.save_params(fname)
    net2 = nn.HybridSequential(prefix="model_")
    with net2.name_scope():
        net2.add(nn.Dense(4, in_units=3))
    net2.load_params(fname, ctx=mx.cpu())
    x = nd.ones((1, 3))
    assert np.allclose(net(x).asnumpy(), net2(x).asnumpy())


def test_split_and_load():
    data = nd.array(np.arange(16).reshape(8, 2).astype("f4"))
    parts = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(1)])
    assert len(parts) == 2
    assert parts[0].shape == (4, 2)


def test_model_zoo_resnet_tiny():
    net = gluon.model_zoo.vision.resnet18_v1(classes=10)
    net.initialize(init=mx.initializer.Xavier(), ctx=mx.cpu())
    out = net(nd.ones((1, 3, 32, 32)))
    assert out.shape == (1, 10)


def test_dataset_dataloader():
    X = np.random.randn(32, 3).astype("f4")
    y = np.arange(32).astype("f4")
    ds = gluon.data.ArrayDataset(X, y)
    assert len(ds) == 32
    loader = gluon.data.DataLoader(ds, batch_size=8, shuffle=True)
    seen = 0
    for data, label in loader:
        assert data.shape == (8, 3)
        seen += data.shape[0]
    assert seen == 32


def test_trainer_fused_sweep_matches_classic(tmp_path):
    """Trainer.step's one-program update sweep must match the per-param
    updater path, and .states files must interoperate."""
    import os

    def run(fused, states_out=None, states_in=None):
        mx.random.seed(9)
        os.environ["MXTPU_FUSED_TRAINER"] = "1" if fused else "0"
        try:
            net = gluon.nn.Sequential()
            with net.name_scope():
                net.add(gluon.nn.Dense(16, activation="relu"))
                net.add(gluon.nn.Dense(4))
            net.initialize(mx.initializer.Xavier())
            trainer = gluon.Trainer(net.collect_params(), "sgd",
                                    {"learning_rate": 0.1,
                                     "momentum": 0.9})
            rng = np.random.RandomState(0)
            X = mx.nd.array(rng.randn(32, 8).astype("float32"))
            y = mx.nd.array(rng.randint(0, 4, 32).astype("float32"))
            loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
            for _ in range(5):
                with autograd.record():
                    loss = loss_fn(net(X), y)
                loss.backward()
                trainer.step(32)
            if states_in:
                trainer.load_states(states_in)
            if states_out:
                trainer.save_states(states_out)
            # strip the run-dependent sequentialN_ prefix for comparison
            return {k.split("_", 1)[1]: v.data().asnumpy()
                    for k, v in net.collect_params().items()}
        finally:
            os.environ.pop("MXTPU_FUSED_TRAINER", None)

    sf = str(tmp_path / "fused.states")
    w_fused = run(True, states_out=sf)
    w_plain = run(False, states_in=sf)  # classic path loads fused states
    for k in w_plain:
        np.testing.assert_allclose(w_fused[k], w_plain[k], rtol=2e-3,
                                   atol=2e-4, err_msg=k)


def test_layernorm_block():
    """nn.LayerNorm: deferred in_channels init, hybridized numerics vs
    numpy, gradients flow to gamma/beta."""
    from mxtpu import autograd
    from mxtpu.gluon import nn

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.LayerNorm())
    net.initialize()
    net.hybridize()
    rng = np.random.RandomState(0)
    x = mx.nd.array((rng.randn(2, 5, 8) * 10 + 100).astype("float32"))
    with autograd.record():
        y = net(x)
        loss = (y * y).mean()
    loss.backward()
    xn = x.asnumpy()
    ref = (xn - xn.mean(-1, keepdims=True)) / \
        np.sqrt(xn.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(y.asnumpy(), ref, rtol=1e-4, atol=1e-4)
    params = net.collect_params()
    gkey = [k for k in params.keys() if k.endswith("gamma")][0]
    assert params[gkey].shape == (8,)  # deferred init resolved
    assert float(np.abs(params[gkey].grad().asnumpy()).sum()) > 0


def test_round3_loss_family_numeric():
    """The 13 round-3 losses (parity loss.py:390-861) match their numpy
    formulas and work under hybridize."""
    import numpy as np
    from mxtpu import gluon, nd
    L = gluon.loss

    rng = np.random.RandomState(3)
    p = rng.randn(8, 1).astype("float32")
    y = rng.choice([-1.0, 1.0], (8, 1)).astype("float32")
    r = rng.randn(8, 1).astype("float32")

    def run(loss, lab):
        return loss(nd.array(p), nd.array(lab)).asnumpy()

    m = p * y
    np.testing.assert_allclose(
        run(L.SoftMargin(), y), np.maximum(0, 1 - m).mean(1), rtol=1e-5)
    np.testing.assert_allclose(
        run(L.SquaredSoftMargin(), y),
        (np.maximum(0, 1 - m) ** 2).mean(1), rtol=1e-5)
    np.testing.assert_allclose(
        run(L.Exponential(), y), np.exp(-m).mean(1), rtol=1e-5)
    np.testing.assert_allclose(
        run(L.Logistic(), y), np.log1p(np.exp(-m)).mean(1), rtol=1e-5)
    err = np.abs(p - r)
    rho = 1.0
    np.testing.assert_allclose(
        run(L.Huber(rho), r),
        np.where(err < rho, 0.5 / rho * err ** 2,
                 err - 0.5 * rho).mean(1), rtol=1e-5)
    np.testing.assert_allclose(
        run(L.Quantile(0.3), r),
        np.maximum(0.3 * (p - r), -0.7 * (p - r)).mean(1), rtol=1e-5)
    np.testing.assert_allclose(
        run(L.LogCosh(), r),
        (err + np.log(0.5 + 0.5 * np.exp(-2 * err))).mean(1),
        rtol=1e-4, atol=1e-6)
    lam = np.abs(rng.randn(8, 1)).astype("float32")
    np.testing.assert_allclose(
        run(L.Poisson(), lam), (np.exp(p) - p * lam).mean(1), rtol=1e-5)

    # hybridized path agrees for a parameter-free loss
    hl = L.Huber(0.7)
    hl.hybridize()
    got = hl(nd.array(p), nd.array(r)).asnumpy()
    e = np.abs(p - r)
    want = np.where(e < 0.7, 0.5 / 0.7 * e ** 2, e - 0.35).mean(1)
    np.testing.assert_allclose(got, want, rtol=1e-5)

    # MaxMargin: correct class with big margin -> zero loss
    logits = np.full((2, 4), -5.0, "float32")
    logits[0, 1] = 5.0
    logits[1, 2] = 5.0
    lbl = np.array([1.0, 2.0], "float32")
    out = L.MaxMargin()(nd.array(logits), nd.array(lbl)).asnumpy()
    np.testing.assert_allclose(out, np.zeros(2), atol=1e-5)
