"""Autograd tests (model: reference tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, nd


def test_simple_grad():
    x = nd.array(np.array([[1.0, 2], [3, 4]]))
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_grad():
    x = nd.array(np.array([1.0, 2, 3]))
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x)
        z = (y * y).sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * np.exp(2 * x.asnumpy()), rtol=1e-4)


def test_head_grads():
    x = nd.array(np.array([1.0, 2, 3]))
    x.attach_grad()
    with autograd.record():
        y = x * 2
    y.backward(nd.array(np.array([1.0, 10, 100])))
    assert np.allclose(x.grad.asnumpy(), [2, 20, 200])


def test_training_modes():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
    with autograd.record(train_mode=False):
        assert autograd.is_recording()
        assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()


def test_pause():
    x = nd.ones((2,))
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = y * 3  # not recorded
        w = (y * y).sum()
    w.backward()
    assert np.allclose(x.grad.asnumpy(), 8)


def test_grad_add_req():
    x = nd.array(np.array([1.0, 2]))
    grad = nd.zeros((2,))
    autograd.mark_variables([x], [grad], "add")
    with autograd.record():
        y = (x * x).sum()
    y.backward(retain_graph=False)
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(grad.asnumpy(), 2 * 2 * x.asnumpy())


def test_detach():
    x = nd.array(np.array([2.0]))
    x.attach_grad()
    with autograd.record():
        y = x * 3
        z = y.detach() * x
    z.backward()
    # z = const(6) * x -> dz/dx = 6
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_multi_variable():
    a = nd.array(np.array([1.0, 2]))
    b = nd.array(np.array([3.0, 4]))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    assert np.allclose(a.grad.asnumpy(), b.asnumpy())
    assert np.allclose(b.grad.asnumpy(), a.asnumpy())


def test_dropout_modes():
    x = nd.ones((100, 100))
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    frac_zero = (y.asnumpy() == 0).mean()
    assert 0.3 < frac_zero < 0.7
    with autograd.record(train_mode=False):
        y2 = nd.Dropout(x, p=0.5)
    assert np.allclose(y2.asnumpy(), 1)


def test_function_custom_grad():
    class Double(autograd.Function):
        def forward(self, x):
            return x * 2

        def backward(self, dy):
            return dy * 7  # deliberately wrong constant to verify custom path

    x = nd.array(np.array([1.0, 2]))
    x.attach_grad()
    f = Double()
    with autograd.record():
        y = f(x)
        z = y.sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [7, 7])


def test_getitem_records_on_tape():
    """Slicing under autograd.record must flow gradients (previously the
    view bypassed the tape and backward returned silent zeros): basic
    slices, integer rows, NDArray-index take, and a loud error for
    non-recordable fancy keys."""
    x = nd.array(np.ones((4,), "float32"))
    x.attach_grad()
    with autograd.record():
        y = (x[1:3] * 3.0).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [0, 3, 3, 0])

    w = nd.array(np.arange(12).reshape(3, 4).astype("float32"))
    w.attach_grad()
    with autograd.record():
        z = (w[1] * 2.0).sum()
    z.backward()
    np.testing.assert_allclose(w.grad.asnumpy()[1], np.full(4, 2.0))
    np.testing.assert_allclose(w.grad.asnumpy()[0], np.zeros(4))

    t = nd.array(np.arange(6).reshape(3, 2).astype("float32"))
    t.attach_grad()
    with autograd.record():
        u = t[nd.array(np.array([0.0, 2.0], "float32"))].sum()
    u.backward()
    np.testing.assert_allclose(t.grad.asnumpy(),
                               [[1, 1], [0, 0], [1, 1]])

    # strided/reversed slices ride the tape via slice + take
    s = nd.array(np.arange(5).astype("float32"))
    s.attach_grad()
    with autograd.record():
        v = (s[::2] * 2.0).sum() + (s[::-1] * 3.0).sum()
    v.backward()
    np.testing.assert_allclose(s.grad.asnumpy(), [5, 3, 5, 3, 5])

    with pytest.raises(mx.base.MXNetError):
        with autograd.record():
            x[np.array([True, False, True, False])]  # masking: not recordable


def test_view_methods_record_on_tape():
    """T / flatten / broadcast_to / expand_dims must ride the tape like
    reshape does — each previously built a raw view whose gradient was a
    silent zero."""
    x = nd.array(np.ones((2, 2), "float32"))
    x.attach_grad()
    with autograd.record():
        y = x.reshape((4,)).sum() + x.flatten().sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((2, 2), 2.0))
    with autograd.record():
        z = (x.T * 3).sum() + x.broadcast_to((2, 2)).sum() \
            + x.expand_dims(0).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((2, 2), 5.0))
    # a REAL broadcast: the vjp must sum the cotangent back over the
    # broadcast dim; astype/copy must also stay on the tape
    b = nd.array(np.ones((1, 2), "float32"))
    b.attach_grad()
    with autograd.record():
        w = b.broadcast_to((3, 2)).sum() + b.astype("float32").sum() \
            + b.copy().sum()
    w.backward()
    np.testing.assert_allclose(b.grad.asnumpy(), np.full((1, 2), 5.0))
