"""C-ABI semantics regressions (advisor round-4 findings): aux-blob copy
direction in MXNDArraySyncCopyFromNDArray, stable MXNDArrayGetData host
pins, MXFuncInvokeEx attribute forwarding, found/not-found semantics of
MXSymbolGetName/GetAttr, and the R adapter's >64-param spill path.

Reference contracts: src/c_api/c_api.cc:258-264 (SyncCopyFromNDArray dst
blob indicator), include/mxnet/c_api.h:392 (GetData), :1830 (FuncInvokeEx).
"""
import ctypes
import os
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI_SO = os.path.join(REPO, "mxtpu", "native", "libmxtpu_capi.so")
R_SO = os.path.join(REPO, "mxtpu", "native", "libmxtpu_r.so")


def _build(target="capi"):
    subprocess.run(["make", "-C", os.path.join(REPO, "src"), target],
                   capture_output=True, text=True)


# ------------------------------------------------------- bridge level

def test_sync_copy_from_ndarray_dst_aux_blob():
    """loc>=0 writes src into DST's loc-th aux blob (csr: indptr/indices;
    row_sparse: indices) — not a slice of src into the whole dst."""
    from mxtpu import capi_bridge as cb
    from mxtpu.ndarray import array, sparse

    csr = sparse.csr_matrix(
        (np.array([1.0, 2.0]), np.array([0, 2]), np.array([0, 1, 2])),
        shape=(2, 4))
    dst_h = cb._register(csr)
    # new indptr [0,0,2]: both nnz move to row 1
    src_h = cb._register(array(np.array([0, 0, 2], dtype=np.int64)))
    assert cb.ndarray_sync_copy_from_ndarray(dst_h, src_h, 0) == 0
    np.testing.assert_array_equal(np.asarray(csr._sp_indptr), [0, 0, 2])
    # new indices [1,3]
    src2_h = cb._register(array(np.array([1, 3], dtype=np.int64)))
    assert cb.ndarray_sync_copy_from_ndarray(dst_h, src2_h, 1) == 0
    dense = csr.asnumpy()
    expect = np.zeros((2, 4), dtype=np.float32)
    expect[1, 1], expect[1, 3] = 1.0, 2.0
    np.testing.assert_allclose(dense, expect)
    with pytest.raises(ValueError):
        cb.ndarray_sync_copy_from_ndarray(dst_h, src_h, 2)

    rs = sparse.row_sparse_array(
        (np.ones((1, 3), dtype=np.float32), np.array([0])), shape=(4, 3))
    rs_h = cb._register(rs)
    idx_h = cb._register(array(np.array([2], dtype=np.int64)))
    assert cb.ndarray_sync_copy_from_ndarray(rs_h, idx_h, 0) == 0
    assert rs.asnumpy()[2].sum() == 3.0 and rs.asnumpy()[0].sum() == 0.0


def test_sync_copy_from_ndarray_sparse_data_blob():
    """loc<0 with a sparse dst targets the nnz data BLOB (the first call
    of the reference's sparse-assembly sequence), not a dense broadcast."""
    from mxtpu import capi_bridge as cb
    from mxtpu.ndarray import array, sparse

    csr = sparse.csr_matrix(
        (np.array([1.0, 2.0]), np.array([0, 2]), np.array([0, 1, 2])),
        shape=(2, 4))
    h = cb._register(csr)
    vals_h = cb._register(array(np.array([5.0, 9.0], dtype=np.float32)))
    assert cb.ndarray_sync_copy_from_ndarray(h, vals_h, -1) == 0
    dense = csr.asnumpy()
    assert dense[0, 0] == 5.0 and dense[1, 2] == 9.0


def test_sync_copy_from_ndarray_dense_full_copy():
    from mxtpu import capi_bridge as cb
    from mxtpu.ndarray import array, zeros

    dst = zeros((2, 3))
    src = array(np.arange(6, dtype=np.float32).reshape(2, 3))
    dh, sh = cb._register(dst), cb._register(src)
    assert cb.ndarray_sync_copy_from_ndarray(dh, sh, -1) == 0
    np.testing.assert_allclose(dst.asnumpy(), src.asnumpy())
    with pytest.raises(ValueError):  # aux copy into dense is an error
        cb.ndarray_sync_copy_from_ndarray(dh, sh, 0)


def test_data_ptr_stable_per_handle():
    """Repeat MXNDArrayGetData calls return the SAME pinned buffer
    (earlier pointers never dangle) with refreshed contents."""
    from mxtpu import capi_bridge as cb
    from mxtpu.ndarray import array

    arr = array(np.arange(4, dtype=np.float32))
    h = cb._register(arr)
    p1 = cb.ndarray_data_ptr(h)
    arr[:] = array(np.full((4,), 7.0, dtype=np.float32))
    p2 = cb.ndarray_data_ptr(h)
    assert p1 == p2
    host = np.ctypeslib.as_array(
        ctypes.cast(p1, ctypes.POINTER(ctypes.c_float)), shape=(4,))
    np.testing.assert_allclose(host, 7.0)


def test_func_invoke_forwards_attrs_and_rejects_scalars():
    from mxtpu import capi_bridge as cb
    from mxtpu.ndarray import array, zeros

    src = array(np.array([-2.0, 0.5, 2.0], dtype=np.float32))
    out = zeros((3,))
    sh, oh = cb._register(src), cb._register(out)
    cb.func_invoke("clip", [sh], [], [oh], ["a_min", "a_max"], ["0", "1"])
    np.testing.assert_allclose(out.asnumpy(), [0.0, 0.5, 1.0])
    with pytest.raises(RuntimeError):
        cb.func_invoke("clip", [sh], [0.0], [oh], ["a_min", "a_max"],
                       ["0", "1"])


def test_symbol_attr_found_semantics_bridge():
    from mxtpu import capi_bridge as cb
    import mxtpu as mx

    v = mx.sym.Variable("data")
    v._set_attr(empty="")
    h = cb._register(v)
    assert cb.symbol_get_attr(h, "empty") == (True, "")
    assert cb.symbol_get_attr(h, "absent") == (False, "")
    assert cb.symbol_get_name(h) == (True, "data")


# ------------------------------------------------------------ C level

def _capi():
    _build("capi")
    if not os.path.exists(CAPI_SO):
        pytest.skip("libmxtpu_capi.so did not build")
    lib = ctypes.CDLL(CAPI_SO)
    # default int restype truncates the 64-bit pointer; string_at on the
    # truncated value segfaults the moment an assert message evaluates
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def test_c_symbol_get_attr_empty_string_found():
    lib = _capi()
    import mxtpu as mx

    sym_json = mx.sym.Variable("x").tojson().encode()
    h = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromJSON(sym_json, ctypes.byref(h)) == 0
    assert lib.MXSymbolSetAttr(h, b"marker", b"") == 0
    out = ctypes.c_char_p()
    success = ctypes.c_int(-1)
    assert lib.MXSymbolGetAttr(h, b"marker", ctypes.byref(out),
                               ctypes.byref(success)) == 0
    assert success.value == 1 and out.value == b""
    assert lib.MXSymbolGetAttr(h, b"absent", ctypes.byref(out),
                               ctypes.byref(success)) == 0
    assert success.value == 0
    assert lib.MXSymbolGetName(h, ctypes.byref(out),
                               ctypes.byref(success)) == 0
    assert success.value == 1 and out.value == b"x"
    lib.MXSymbolFree(h)


def test_c_func_invoke_ex_forwards_params():
    lib = _capi()
    from mxtpu import capi_bridge as cb
    from mxtpu.ndarray import array, zeros

    fn = ctypes.c_void_p()
    assert lib.MXGetFunction(b"clip", ctypes.byref(fn)) == 0

    src = array(np.array([-2.0, 0.5, 2.0], dtype=np.float32))
    out = zeros((3,))
    sh, oh = cb._register(src), cb._register(out)
    use = (ctypes.c_void_p * 1)(ctypes.c_void_p(sh))
    mut = (ctypes.c_void_p * 1)(ctypes.c_void_p(oh))
    keys = (ctypes.c_char_p * 2)(b"a_min", b"a_max")
    vals = (ctypes.c_char_p * 2)(b"0", b"1")
    rc = lib.MXFuncInvokeEx(fn, use, None, mut, 2, keys, vals)
    assert rc == 0, lib.MXGetLastError()
    np.testing.assert_allclose(out.asnumpy(), [0.0, 0.5, 1.0])
    # params required but not supplied: loud failure, not silent defaults
    assert lib.MXFuncInvoke(fn, use, None, mut) == -1


# ------------------------------------------------------------ R level

def test_r_symbol_atomic_past_64_params():
    """n>64 spills to the heap and reaches the C API (previously rc=-1
    with a stale MXGetLastError message)."""
    _build("r")
    if not os.path.exists(R_SO):
        pytest.skip("libmxtpu_r.so did not build")
    lib = ctypes.CDLL(R_SO)

    def _atomic(op, keys, vals):
        n = len(keys)
        rc = ctypes.c_int(0)
        out_id = ctypes.c_int(0)
        ks = (ctypes.c_char_p * max(n, 1))(*[k.encode() for k in keys])
        vs = (ctypes.c_char_p * max(n, 1))(*[v.encode() for v in vals])
        lib.mx_r_symbol_atomic(
            ctypes.byref(ctypes.c_char_p(op.encode())),
            ctypes.byref(ctypes.c_int(n)), ks, vs,
            ctypes.byref(out_id), ctypes.byref(rc))
        return rc.value

    def _last_error():
        buf = ctypes.create_string_buffer(512)
        pbuf = ctypes.c_char_p(ctypes.addressof(buf))
        lib.mx_r_last_error(ctypes.byref(pbuf))
        return buf.value

    # seed the last-error slot with a distinctive failure
    assert _atomic("definitely_no_such_op", [], []) == -1
    assert b"definitely_no_such_op" in _last_error()

    # 70 params: the call must REACH the C API (pre-fix this returned -1
    # before calling anything, leaving the stale message above in place)
    keys = ["a_min", "a_max"] + ["bogus%d" % i for i in range(68)]
    vals = ["0", "1"] + ["x"] * 68
    rc = _atomic("clip", keys, vals)
    assert rc == 0 or b"clip" in _last_error()


def test_c_rtc_string_source_kernel():
    """MXRtcCreate/Push through the C ABI with a string kernel (the
    reference's NVRTC role; here the TPU kernel language is jax Python
    — see src/capi/c_api_full.cc MXRtcCreate): compile once, push on
    NDArray handles, outputs land in the caller's arrays."""
    lib = _capi()
    from mxtpu import capi_bridge as cb
    from mxtpu.ndarray import array, zeros

    x = array(np.array([1.0, -2.0, 3.0], dtype=np.float32))
    out = zeros((3,))
    xh, oh = cb._register(x), cb._register(out)

    names = (ctypes.c_char_p * 1)(b"x")
    onames = (ctypes.c_char_p * 1)(b"y")
    kernel = b"y = jnp.tanh(x) * 2.0"
    h = ctypes.c_void_p()
    rc = lib.MXRtcCreate(b"tanh2", 1, 1, names, onames, None, None,
                         kernel, ctypes.byref(h))
    assert rc == 0, lib.MXGetLastError()
    ins = (ctypes.c_void_p * 1)(ctypes.c_void_p(xh))
    outs = (ctypes.c_void_p * 1)(ctypes.c_void_p(oh))
    assert lib.MXRtcPush(h, 1, 1, ins, outs, 1, 1, 1, 1, 1, 1) == 0, \
        lib.MXGetLastError()
    np.testing.assert_allclose(out.asnumpy(),
                               2 * np.tanh([1.0, -2.0, 3.0]), rtol=1e-5)
    assert lib.MXRtcFree(h) == 0

    # a kernel that never assigns its output fails loudly at Push
    h2 = ctypes.c_void_p()
    assert lib.MXRtcCreate(b"bad", 1, 1, names, onames, None, None,
                           b"z = x + 1", ctypes.byref(h2)) == 0
    assert lib.MXRtcPush(h2, 1, 1, ins, outs, 1, 1, 1, 1, 1, 1) == -1
    assert b"did not assign" in lib.MXGetLastError()
