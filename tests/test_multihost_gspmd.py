"""Multi-host GSPMD training: two jax.distributed processes, each with 4
virtual CPU devices, form ONE global 8-device mesh and run the fused
data-parallel train step over it — the actual multi-host pod path (ICI
within a host, DCN across hosts), the role the reference's NCCL/MPI +
ps-lite stack plays at pod scale (SURVEY §2.4).

Invariants: the step executes, gradients all-reduce across processes
(replicated params remain bit-identical on every process), and training
moves the loss."""
import os
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    sys.path.insert(0, %r)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=4")
    import jax
    jax.distributed.initialize(coordinator_address="localhost:%%d",
                               num_processes=2,
                               process_id=int(sys.argv[1]))
    import jax.numpy as jnp
    import mxtpu as mx
    from mxtpu.parallel import make_mesh
    from mxtpu.parallel.dp import DataParallelTrainer

    assert jax.device_count() == 8 and jax.local_device_count() == 4
    mesh = make_mesh(shape=(8,), devices=jax.devices())

    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(
        mx.sym.Variable("data"), num_hidden=4, name="fc"), name="softmax")
    batch = 16
    tr = DataParallelTrainer(
        net, mesh=mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.5, "momentum": 0.9,
                          "rescale_grad": 1.0 / batch})
    tr.init({"data": (batch, 6), "softmax_label": (batch,)})

    rng = np.random.RandomState(0)  # same global batch on both processes
    centers = rng.randn(4, 6) * 3
    y = rng.randint(0, 4, batch)
    X = (centers[y] + rng.randn(batch, 6)).astype("float32")

    from jax.experimental import multihost_utils
    losses = []
    for step in range(8):
        outs = tr.step({"data": X, "softmax_label": y.astype("float32")})
        # outputs are batch-sharded across processes: gather the tiles
        probs = np.asarray(multihost_utils.process_allgather(outs[0],
                                                             tiled=True))
        losses.append(-np.log(probs[np.arange(batch), y] + 1e-9).mean())
    assert losses[-1] < losses[0] * 0.7, losses

    # replicated params must be bit-identical across processes: compare a
    # hash via the collective mean (equal iff mean == local value)
    w = np.asarray(jax.device_get(tr._params["fc_weight"]))
    w_mean = multihost_utils.process_allgather(w).mean(axis=0)
    assert np.array_equal(w, w_mean), "params diverged across processes"
    print("MULTIHOST_OK", jax.process_index(), round(float(losses[-1]), 4))
""")


def test_two_process_global_mesh_training():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    src = (WORKER % REPO) % port
    env = dict(os.environ, PYTHONPATH=REPO)
    for v in ("MXTPU_ROOT_URI", "MXTPU_ROOT_PORT", "MXTPU_NUM_WORKERS",
              "MXTPU_ROLE", "MXTPU_WORKER_ID", "DMLC_PS_ROOT_URI",
              "DMLC_ROLE", "XLA_FLAGS", "JAX_PLATFORMS"):
        env.pop(v, None)
    procs = [subprocess.Popen([sys.executable, "-c", src, str(r)], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for r in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out.decode())
        assert p.returncode == 0, out.decode()
    assert all("MULTIHOST_OK" in o for o in outs), outs
