"""mxtpu.elastic — async checkpointing, exact fit-resume, preemption
recovery (docs/elastic.md). The contracts:

* **kill-at-step-N resume parity** (THE gate): a fit killed at step N
  and resumed from its elastic snapshot matches an uninterrupted fit
  BIT-EXACT on weights and exactly on integer-summed metrics — on the
  plain fused path, under ``MXTPU_PIPELINE=bf16`` (f32 masters), and on
  the forced 8-device CPU mesh (weight-update sharding preserved);
* **crash-window atomicity**: a generation is durable only after its
  pointer flip; a writer killed mid-serialize (or a torn data file)
  leaves the previous generation loadable;
* **supervision**: a watchdog wedge detection triggers
  checkpoint-restore-retry through :class:`Supervisor.run` without
  human intervention, and SIGTERM flushes a final snapshot before
  :class:`Preempted` propagates;
* epoch checkpoint callbacks ride the async snapshot writer and keep
  the fused params device-resident through a checkpointing fit.
"""
import json
import os
import signal
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import metric as M
from mxtpu.elastic import snapshot as esnap
from mxtpu.models import mlp as _mlp


class Kill(Exception):
    """Simulated hard death of the training process."""


def _mnist_like(n=256, seed=7):
    rng = np.random.RandomState(seed)
    return (rng.rand(n, 784).astype("float32"),
            rng.randint(0, 10, n).astype("float32"))


def _make_iter(batch_size=64, shuffle=False):
    X, y = _mnist_like()
    return mx.io.NDArrayIter(X, y, batch_size=batch_size, shuffle=shuffle,
                             label_name="softmax_label")


def _fit(num_epoch=2, seed=11, kill_at_step=None, module=None,
         optimizer="sgd", opt_params=None, **fit_kwargs):
    """One mlp fit; ``kill_at_step`` raises Kill after that many batch
    callbacks (1-based), simulating the process dying mid-epoch."""
    it = _make_iter()
    mod = module or mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    metric = M.create(["acc", "ce"])
    mx.random.seed(seed)
    np.random.seed(seed)
    steps = [0]
    cb = None
    if kill_at_step is not None:
        def cb(param):
            steps[0] += 1
            if steps[0] >= kill_at_step:
                raise Kill()
    try:
        mod.fit(it, num_epoch=num_epoch, eval_metric=metric,
                optimizer=optimizer,
                optimizer_params=opt_params or {"learning_rate": 0.05,
                                                "momentum": 0.9},
                initializer=mx.initializer.Xavier(),
                batch_end_callback=cb, metric_sync=2, **fit_kwargs)
    except Kill:
        pass
    weights = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    return dict(metric.get_name_value()), weights, mod


def _assert_resume_parity(tmp_path, kill_at_step=3, **fit_kwargs):
    """Uninterrupted vs killed-at-step-N + resumed: weights bit-exact,
    integer-summed metrics exact."""
    prefix = str(tmp_path / "ck")
    m_full, w_full, _ = _fit(**fit_kwargs)
    cfg = mx.elastic.ElasticConfig(prefix, every_n_steps=1, sync=True)
    _fit(kill_at_step=kill_at_step, elastic=cfg, **fit_kwargs)
    man = esnap.latest_manifest(prefix)
    assert man is not None and man["cursor"]["global_step"] == kill_at_step
    m_res, w_res, mod = _fit(resume=prefix, elastic=False, **fit_kwargs)
    for k in w_full:
        np.testing.assert_array_equal(
            w_full[k], w_res[k],
            err_msg="weights diverged at %s: resume is not exact" % k)
    assert m_full["accuracy"] == m_res["accuracy"], (m_full, m_res)
    # float sums may differ in summation order only
    np.testing.assert_allclose(m_full["cross-entropy"],
                               m_res["cross-entropy"], rtol=1e-5)
    return mod


# --------------------------------------------------------- THE parity gate
def test_kill_at_step_resume_parity(tmp_path):
    mod = _assert_resume_parity(tmp_path)
    assert mod._fused is not None


def test_kill_at_step_resume_parity_bf16(tmp_path):
    """Same gate under the bf16 mixed-precision rewrite: the snapshot
    carries the f32 masters (the fused state's params ARE the masters)
    and resume is still bit-exact."""
    from mxtpu.compile import pipeline as P
    os.environ["MXTPU_PIPELINE"] = "bf16"
    P.configure(None)
    try:
        mod = _assert_resume_parity(tmp_path)
        rep = mod._fused.pipeline_report
        assert rep is not None and "bf16" in rep.applied, \
            "bf16 rewrite was not applied — gate would not cover masters"
        for v in mod.get_params()[0].values():
            assert v.dtype == np.float32  # masters, not bf16
    finally:
        os.environ.pop("MXTPU_PIPELINE", None)
        # re-READ (env now unset -> empty) rather than pin an explicit
        # (): an explicit configure marks the pipeline operator-pinned,
        # which would block later TunedConfig artifacts (mxtpu.tune)
        # from refreshing it for the rest of the process
        P.configure(None)


def test_kill_at_step_resume_parity_mesh(tmp_path):
    """Same gate on the forced 8-device CPU mesh: the snapshot writes
    the optimizer state per-shard with specs in the manifest, and the
    restored state keeps the PR-6 weight-update sharding split."""
    import jax
    from jax.sharding import PartitionSpec as P
    mod = _assert_resume_parity(tmp_path, mesh=8)
    fused = mod._fused
    assert fused is not None and fused._plan is not None
    leaf = jax.tree.leaves(fused.opt_state["fc1_weight"])[0]
    assert leaf.sharding.spec == P("data"), leaf.sharding.spec
    assert len(leaf.sharding.device_set) == 8
    # the manifest really recorded per-shard pieces, not a global dump
    man = esnap.latest_manifest(str(tmp_path / "ck"))
    entry = man["opt_entries"]["fc1_weight"]
    assert entry["spec"] == ["data"]
    assert len(entry["shards"]["0"]["pieces"]) == 8


def test_resume_from_epoch_boundary_snapshot(tmp_path):
    """With epoch-cadence snapshots only, a mid-epoch kill resumes from
    the epoch boundary and replays the epoch — still bit-exact (RNG
    streams restored to the boundary state)."""
    prefix = str(tmp_path / "ck")
    m_full, w_full, _ = _fit()
    cfg = mx.elastic.ElasticConfig(prefix, every_n_steps=0, sync=True)
    _fit(kill_at_step=6, elastic=cfg)          # dies inside epoch 1
    man = esnap.latest_manifest(prefix)
    assert man["cursor"]["epoch_boundary"] is True
    assert man["cursor"]["epoch"] == 0
    m_res, w_res, _ = _fit(resume=prefix, elastic=False)
    for k in w_full:
        np.testing.assert_array_equal(w_full[k], w_res[k], err_msg=k)
    assert m_full["accuracy"] == m_res["accuracy"]


def test_epoch_boundary_snapshot_carries_post_reset_iterator(tmp_path):
    """An epoch-boundary generation must record the POST-reset iterator
    state: a reshuffling iterator (BucketSentenceIter) has already drawn
    the next epoch's schedule when the snapshot is taken, and a boundary
    resume must replay that schedule — not the fresh iterator's
    construction-time shuffle."""
    prefix = str(tmp_path / "ck")
    cfg = mx.elastic.ElasticConfig(prefix, every_n_steps=0, sync=True)
    _fit(num_epoch=1, elastic=cfg)
    man = esnap.latest_manifest(prefix)
    assert man["cursor"]["epoch_boundary"] is True
    assert man["iterator"]["supported"] is True
    state = mx.elastic.ResumeState(man, esnap.load_arrays(man))
    it_state = state.iterator_state()
    # post-reset NDArrayIter cursor: one batch BEFORE the first
    assert it_state["cursor"] == -64


def test_resume_adam_counters(tmp_path):
    """Adam's bias correction reads the per-index update counts — a
    resume that lost them would silently rescale lr. Exactness of the
    resumed weights proves the counters round-tripped."""
    _assert_resume_parity(tmp_path, optimizer="adam",
                          opt_params={"learning_rate": 0.003})


# ------------------------------------------------------- atomicity / files
def test_generation_pointer_and_prune(tmp_path):
    prefix = str(tmp_path / "run")
    w = esnap.writer()
    for g in (1, 2, 3, 4):
        w.submit(esnap.SnapshotJob(
            "generation", {"arg:w": np.full(4, g, "f4")}, prefix=prefix,
            generation=g, keep=2,
            manifest={"format": esnap.FORMAT,
                      "cursor": {"epoch": 0, "nbatch": g,
                                 "global_step": g}}))
    w.flush()
    man = esnap.latest_manifest(prefix)
    assert man["_generation"] == 4
    assert esnap.load_arrays(man)["arg:w"][0] == 4.0
    assert esnap.list_generations(prefix) == [3, 4]  # keep=2 pruned 1, 2


def test_torn_generation_falls_back(tmp_path):
    """Crash-window contract: a generation whose data file is torn (or
    missing) must not load — the previous generation does."""
    prefix = str(tmp_path / "run")
    w = esnap.writer()
    w.submit(esnap.SnapshotJob(
        "generation", {"arg:w": np.arange(4, dtype="f4")}, prefix=prefix,
        generation=1,
        manifest={"format": esnap.FORMAT,
                  "cursor": {"epoch": 0, "nbatch": 0, "global_step": 1}}))
    w.flush()
    # a torn gen 2: manifest + pointer landed, data file truncated
    # (the reverse order of the writer's protocol — simulates the worst
    # case of a crash + a buggy writer; load must still not trust it)
    base = esnap.data_basename(prefix, 2)
    data_path = str(tmp_path / base)
    with open(data_path, "wb") as f:
        f.write(b"MXTPU001\x00")  # truncated mid-header
    man2 = {"format": esnap.FORMAT,
            "cursor": {"epoch": 0, "nbatch": 1, "global_step": 2},
            "data_files": {base: {"bytes": 9999}}}
    with open(esnap.manifest_path(prefix, 2), "w") as f:
        json.dump(man2, f)
    with open(esnap.pointer_path(prefix), "w") as f:
        json.dump({"format": esnap.FORMAT, "generation": 2,
                   "manifest": os.path.basename(
                       esnap.manifest_path(prefix, 2))}, f)
    man = esnap.latest_manifest(prefix)
    assert man is not None and man["_generation"] == 1
    np.testing.assert_array_equal(esnap.load_arrays(man)["arg:w"],
                                  np.arange(4, dtype="f4"))


def test_writer_killed_mid_serialize_keeps_previous(tmp_path,
                                                    monkeypatch):
    """Kill the writer inside the data serialize: the tmp file may be
    torn but no manifest/pointer flips — the previous generation loads
    and the error is counted, not raised into training."""
    from mxtpu import telemetry as tel
    prefix = str(tmp_path / "run")
    w = esnap.writer()
    w.submit(esnap.SnapshotJob(
        "generation", {"arg:w": np.ones(4, "f4")}, prefix=prefix,
        generation=1,
        manifest={"format": esnap.FORMAT,
                  "cursor": {"epoch": 0, "nbatch": 0, "global_step": 1}}))
    w.flush()

    def _die(path, arrays):
        with open(path, "wb") as f:
            f.write(b"MXTPU0")      # partial magic, then "power loss"
        raise OSError("simulated writer death mid-serialize")

    monkeypatch.setattr(esnap, "_write_ndsave_atomic", _die)
    errs0 = tel.registry().counter("elastic_snapshot_errors").value
    w.submit(esnap.SnapshotJob(
        "generation", {"arg:w": np.full(4, 2.0, "f4")}, prefix=prefix,
        generation=2,
        manifest={"format": esnap.FORMAT,
                  "cursor": {"epoch": 0, "nbatch": 1, "global_step": 2}}))
    w.flush()
    monkeypatch.undo()
    assert tel.registry().counter("elastic_snapshot_errors").value == \
        errs0 + 1
    man = esnap.latest_manifest(prefix)
    assert man["_generation"] == 1
    assert esnap.load_arrays(man)["arg:w"][0] == 1.0


# ----------------------------------------------------------- supervision
def test_watchdog_action_hook_fires_after_postmortem():
    from mxtpu.diagnostics import Watchdog, add_action, remove_action
    seen = []
    add_action(seen.append)
    try:
        wd = Watchdog(interval=0.01, engine_stall_s=0.02, wait_stall_s=99,
                      engine_probe=lambda: (3, 7))
        t0 = time.monotonic()
        while not seen and time.monotonic() - t0 < 3.0:
            time.sleep(0.03)
            wd.check()
    finally:
        remove_action(seen.append)
    assert seen and "engine stalled" in seen[0]
    pm = mx.diagnostics.last_postmortem()
    assert pm is not None and pm["source"] == "watchdog"


def test_watchdog_restore_retry_end_to_end(tmp_path):
    """The acceptance gate's recovery half: a fit wedged mid-flight (the
    wedged-fake-engine fixture) is detected by the watchdog, aborted at
    the next step boundary, restored from the last durable generation,
    retried, and completes — no human in the loop, and the final numbers
    match an uninterrupted fit."""
    from mxtpu.diagnostics import Watchdog
    prefix = str(tmp_path / "ck")
    m_full, w_full, _ = _fit()

    wedge = {"on": False}
    wd = Watchdog(interval=0.01, engine_stall_s=0.03, wait_stall_s=99,
                  engine_probe=lambda: (3, 7) if wedge["on"] else (0, 0)
                  ).start()
    sup = mx.elastic.Supervisor(retries=2, backoff_s=0.05)
    cfg = mx.elastic.ElasticConfig(prefix, every_n_steps=1, sync=True,
                                   supervisor=sup)
    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    metric = M.create(["acc", "ce"])
    attempts = []

    def fit_fn(resume):
        attempts.append(resume)
        if len(attempts) == 1:
            def cb(param):
                if param.nbatch == 2:     # wedge mid-epoch, attempt 1
                    wedge["on"] = True
                    time.sleep(0.2)       # let the watchdog sample it
        else:
            wedge["on"] = False
            cb = None
        mx.random.seed(11)
        np.random.seed(11)
        mod.fit(_make_iter(), num_epoch=2, eval_metric=metric,
                optimizer="sgd",
                optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
                initializer=mx.initializer.Xavier(),
                batch_end_callback=cb, metric_sync=2,
                elastic=cfg, resume=resume)

    try:
        sup.run(fit_fn)
    finally:
        wd.stop()
    assert attempts == [False, True]
    assert sup.retries_done == 1
    assert m_full["accuracy"] == dict(metric.get_name_value())["accuracy"]
    w_sup = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in w_full:
        np.testing.assert_array_equal(w_full[k], w_sup[k], err_msg=k)


def test_supervisor_gives_up_after_bounded_retries():
    sup = mx.elastic.Supervisor(retries=2, backoff_s=0.0)
    calls = []

    def always_wedged(resume):
        calls.append(resume)
        raise mx.elastic.WedgeAbort("synthetic wedge")

    with pytest.raises(mx.elastic.WedgeAbort):
        sup.run(always_wedged)
    assert calls == [False, True, True]     # 1 try + 2 bounded retries


def test_sigterm_flushes_final_snapshot_then_resume(tmp_path):
    """SIGTERM-as-preemption-warning: the handler flags, the fit flushes
    a FINAL durable snapshot at the next step boundary and raises
    Preempted; a later fit(resume=) continues from it."""
    prefix = str(tmp_path / "ck")
    sup = mx.elastic.Supervisor()
    assert sup.install_sigterm()
    cfg = mx.elastic.ElasticConfig(prefix, supervisor=sup)  # no cadence

    def cb(param):
        if param.nbatch == 1:
            os.kill(os.getpid(), signal.SIGTERM)

    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    mx.random.seed(11)
    np.random.seed(11)
    try:
        with pytest.raises(mx.elastic.Preempted):
            mod.fit(_make_iter(), num_epoch=2, optimizer="sgd",
                    optimizer_params={"learning_rate": 0.05},
                    initializer=mx.initializer.Xavier(),
                    batch_end_callback=cb, elastic=cfg)
    finally:
        sup.uninstall_sigterm()
    man = esnap.latest_manifest(prefix)
    assert man is not None and man["cursor"]["global_step"] == 3
    # the next incarnation resumes and completes
    metric = M.create(["acc", "ce"])
    mod2 = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    mx.random.seed(11)
    np.random.seed(11)
    mod2.fit(_make_iter(), num_epoch=2, eval_metric=metric,
             optimizer="sgd", optimizer_params={"learning_rate": 0.05},
             initializer=mx.initializer.Xavier(), resume=prefix)
    assert metric.get_name_value()


# ------------------------------------------------- epoch checkpoints / io
def test_epoch_checkpoint_callbacks_ride_async_writer(tmp_path):
    """module_checkpoint/do_checkpoint go through the snapshot writer:
    the fused step stays armed with device-resident params, fit never
    round-trips params for the elastic-aware callback (set_params spy),
    and the files load back equal to the live weights."""
    prefix_m = str(tmp_path / "modck")
    prefix_d = str(tmp_path / "dock")
    mod = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    set_calls = []
    orig = mod.set_params
    mod.set_params = lambda *a, **k: (set_calls.append(1),
                                      orig(*a, **k))[1]
    mx.random.seed(11)
    np.random.seed(11)
    mod.fit(_make_iter(), num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            initializer=mx.initializer.Xavier(),
            epoch_end_callback=mx.callback.module_checkpoint(
                mod, prefix_m, save_optimizer_states=True))
    assert mod._fused is not None and mod._params_device_resident()
    assert not set_calls, \
        "fit round-tripped params for an elastic-aware checkpoint callback"
    mx.model.wait_checkpoints()
    sym, args, auxs = mx.model.load_checkpoint(prefix_m, 2)
    live = mod.get_params()[0]
    for k, v in args.items():
        np.testing.assert_array_equal(v.asnumpy(), live[k].asnumpy(),
                                      err_msg=k)
    # versioned manifest landed beside the legacy file
    man = json.load(open(prefix_m + "-0002.params.manifest.json"))
    assert man["format"] == "mxtpu-checkpoint-1"
    assert sorted(args) == man["params"]
    # optimizer states file round-trips through the writer too
    mod.load_optimizer_states(prefix_m + "-0002.states")

    # do_checkpoint still receives (device-backed) params and writes
    mod2 = mx.mod.Module(_mlp.get_symbol(10), context=mx.cpu())
    mx.random.seed(11)
    np.random.seed(11)
    mod2.fit(_make_iter(), num_epoch=1, optimizer="sgd",
             optimizer_params={"learning_rate": 0.05},
             initializer=mx.initializer.Xavier(),
             epoch_end_callback=mx.callback.do_checkpoint(prefix_d))
    assert mod2._fused is not None
    mx.model.wait_checkpoints()
    _, args2, _ = mx.model.load_checkpoint(prefix_d, 1)
    live2 = mod2.get_params()[0]
    for k, v in args2.items():
        np.testing.assert_array_equal(v.asnumpy(), live2[k].asnumpy(),
                                      err_msg=k)


def test_ndarrayiter_cursor_roundtrip():
    """The shuffle permutation travels with the cursor: a freshly
    constructed (differently shuffled) iterator restored from the state
    yields the exact continuation of the original stream."""
    X, y = _mnist_like(n=96)
    np.random.seed(3)
    it1 = mx.io.NDArrayIter(X, y, batch_size=16, shuffle=True)
    batches = []
    for i, b in enumerate(it1):
        if i == 2:
            state = it1.checkpoint_state()
        batches.append(b.data[0].asnumpy())
    np.random.seed(99)  # a resumed process draws a different shuffle
    it2 = mx.io.NDArrayIter(X, y, batch_size=16, shuffle=True)
    assert it2.restore_state(state)
    for want in batches[3:]:
        got = next(it2).data[0].asnumpy()
        np.testing.assert_array_equal(want, got)
    # shape mismatch declines (resume then replays instead)
    it3 = mx.io.NDArrayIter(X[:32], y[:32], batch_size=16, shuffle=True)
    assert not it3.restore_state(state)


def test_bucket_sentence_iter_cursor_roundtrip():
    import random as pyrandom
    sent = [[i % 17 + 1] * (3 + i % 5) for i in range(60)]
    pyrandom.seed(5)
    np.random.seed(5)
    it1 = mx.rnn.BucketSentenceIter(sent, batch_size=4, buckets=[4, 8])
    firsts = []
    for i, b in enumerate(it1):
        if i == 1:
            state = it1.checkpoint_state()
        firsts.append((b.bucket_key, b.data[0].asnumpy()))
    pyrandom.seed(77)
    np.random.seed(77)
    it2 = mx.rnn.BucketSentenceIter(sent, batch_size=4, buckets=[4, 8])
    assert it2.restore_state(state)
    for want_key, want in firsts[2:]:
        got = next(it2)
        assert got.bucket_key == want_key
        np.testing.assert_array_equal(want, got.data[0].asnumpy())


def test_snapshot_series_emitted(tmp_path):
    from mxtpu import telemetry as tel
    reg = tel.registry()
    prefix = str(tmp_path / "ck")
    cfg = mx.elastic.ElasticConfig(prefix, every_n_steps=2, sync=True)
    b0 = reg.counter("elastic_snapshot_bytes").value
    s0 = reg.histogram("elastic_snapshot_stall_ms").count
    _fit(num_epoch=1, elastic=cfg)
    assert reg.counter("elastic_snapshot_bytes").value > b0
    assert reg.histogram("elastic_snapshot_stall_ms").count > s0
    assert reg.gauge("elastic_snapshot_age_s").value >= 0.0
