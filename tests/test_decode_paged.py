"""Paged KV-cache decode serving (PR-16: PagedArena, attention decode,
chunked prefill, token streaming).

Tier-1 (CPU, `not slow`) unless marked. The PR's acceptance gates, all
on exact counters / byte comparisons per the PR-2 deterministic
convention:

* **paged gather math** — the `rows` layout (recurrent state as
  one-token rows in a `PagedArena`) emits byte-identical tokens to the
  PR-15 contiguous arena on the same fixture and arrival schedule;
* **attention decode** — kv-layout tokens are byte-identical joined vs
  alone (bf16 pipeline and mid-run hot-swap in the slow tier), and
  NaN-poisoned unwritten cache blocks leave every token unchanged
  (select-not-multiply inertness, proven end-to-end);
* **never-stall** — a long prompt chunked at
  `decode.prefill_chunk_tokens` causes ZERO oversized prefill
  dispatches while a generating sequence waits
  (`decode_prefill_stalls == 0`, exact counter); the unchunked
  baseline on the same schedule shows >= 1;
* **ledger exactness** — the `decode_kv` origin's live bytes equal
  `blocks_live x block_bytes` at every transition, and injected
  prefill / block-alloc / evict failures never leak a KV block (free
  list exact, ledger back to baseline);
* **streaming** — `?stream=1` delivers every token then a terminal
  event; a mid-stream deadline terminates the chunked response
  cleanly, and pre-commit errors keep the JSON status taxonomy.
"""
import http.client
import json
import threading
import time

import jax.numpy as jnp
import pytest

import mxtpu.diagnostics as diag
from mxtpu import faults
from mxtpu.analysis import concurrency as conc
from mxtpu.base import MXNetError
from mxtpu.serving import DecodeSession, ServingHTTPServer
from mxtpu.serving.decode import (PagedArena, TokenStream,
                                  attn_decode_fixture, lm_decode_fixture)

# shared fixtures + one version tag per weight set: sessions adopt the
# process warm cache, so the suite pays each program compile once
_LM = {}
_ATTN = {}


def _lm(seed=0):
    if seed not in _LM:
        _LM[seed] = lm_decode_fixture(seed=seed)
    return _LM[seed]


def _attn(seed=0):
    if seed not in _ATTN:
        _ATTN[seed] = attn_decode_fixture(seed=seed)
    return _ATTN[seed]


def _rows_or_slots_session(arena, seed=0, **kwargs):
    sym, params, shapes, state_names, _ = _lm(seed)
    kwargs.setdefault("buckets", (4,))
    kwargs.setdefault("slot_capacity", 2)
    kwargs.setdefault("version_tag", "tp-v%d" % seed)
    return DecodeSession(sym, params, shapes, state_names, arena=arena,
                         **kwargs)


def _kv_session(seed=0, **kwargs):
    fx = _attn(seed)
    kwargs.setdefault("buckets", (2,))
    kwargs.setdefault("slot_capacity", 2)
    kwargs.setdefault("prefill_chunk_tokens", 2)
    kwargs.setdefault("prefill_buckets", (2,))
    kwargs.setdefault("version_tag", "tkv-v%d" % seed)
    return DecodeSession(fx["step_symbol_json"], fx["params"],
                         fx["step_example_shapes"], [], arena="paged",
                         paged=fx, **kwargs)


REQS = [([3, 5], 5, 0, 0.0), ([2], 6, 1, 0.5), ([7, 8, 9], 4, 2, 0.5),
        ([4], 5, 3, 0.0), ([6, 2], 3, 4, 0.9)]


def _run_joined(sess, reqs):
    """Seeded concurrent arrival schedule: join/leave churn between
    steps (capacity < request count forces queue + slot reuse)."""
    res = [None] * len(reqs)

    def run(i):
        prompt, max_new, rseed, temp = reqs[i]
        res[i] = sess.generate(prompt, max_new_tokens=max_new,
                               seed=rseed, temperature=temp, timeout=60)

    ts = [threading.Thread(target=run, args=(i,))
          for i in range(len(reqs))]
    for j, t in enumerate(ts):
        t.start()
        if j % 2:
            time.sleep(0.003)
    for t in ts:
        t.join(timeout=120)
    assert all(r is not None for r in res), "hung generate waiter"
    return [r["tokens"] for r in res]


# --------------------------------------------------- paged rows layout
def test_paged_rows_byte_identity_with_contiguous_slots():
    """The paged gather/scatter math proven on the PR-15 fixture before
    any attention enters: same requests, same churny schedule, tokens
    byte-identical between the contiguous and rows layouts."""
    with _rows_or_slots_session("slots") as sess:
        baseline = _run_joined(sess, REQS)
        assert sess.metrics.counter(
            "decode_steps_with_admittable_waiting").value == 0
    with _rows_or_slots_session("paged") as sess:
        assert sess.arena.__class__ is PagedArena
        paged = _run_joined(sess, REQS)
        assert sess.metrics.counter(
            "decode_steps_with_admittable_waiting").value == 0
    assert paged == baseline


# ------------------------------------------------------ arena geometry
def test_paged_arena_ledger_exact_and_free_list():
    """decode_kv live bytes == blocks_live x block_bytes at EVERY
    transition; release returns the exact block set; over-budget and
    dry-pool growth raise without losing blocks."""
    base = diag.ledger().live_bytes(origin="decode_kv")
    specs = [{"name": "k", "shape": (2, 4), "dtype": "float32"},
             {"name": "v", "shape": (2, 4), "dtype": "float32"}]
    with PagedArena(2, 4, 5, 3, specs) as a:
        # 2 leaves x block_size 4 x (2x4) f32 elements = 256 B/block
        assert a.block_bytes == 256
        s0 = a.allocate()
        s1 = a.allocate()
        assert a.allocate() is None
        a.ensure_tokens(s0, 5)          # 2 blocks
        a.ensure_tokens(s1, 4)          # 1 block
        assert a.blocks_live == 3
        assert diag.ledger().live_bytes(origin="decode_kv") \
            == base + 3 * a.block_bytes
        with pytest.raises(MXNetError):
            a.ensure_tokens(s1, 13)     # > max_blocks_per_seq (3)
        a.ensure_tokens(s1, 12)         # to the cap is fine
        assert a.blocks_live == 5
        with pytest.raises(MXNetError):
            a.ensure_tokens(s0, 9)      # pool dry (5 total, 5 live)
        a.release(s1)
        assert a.blocks_live == 2 and a.blocks_free == 3
        assert diag.ledger().live_bytes(origin="decode_kv") \
            == base + 2 * a.block_bytes
        a.release(s0)
        assert a.blocks_free == a.blocks_total
        assert diag.ledger().live_bytes(origin="decode_kv") == base
    assert diag.ledger().live_bytes(origin="decode_kv") == base


# --------------------------------------------------- attention decode
def test_attn_joined_vs_alone_byte_identity():
    """kv layout: the same requests decoded under churn and alone emit
    byte-identical tokens (chunked prefill + paged attention included),
    and no KV block survives the last retirement."""
    reqs = [([1, 2, 3, 4, 5], 5, 0, 0.0), ([3, 1], 5, 1, 0.5),
            ([2, 2, 2, 2, 2, 2, 2], 4, 2, 0.5), ([4], 6, 3, 0.9)]
    with _kv_session() as sess:
        joined = _run_joined(sess, reqs)
        alone = [sess.generate(p, max_new_tokens=m, seed=s,
                               temperature=t, timeout=60)["tokens"]
                 for p, m, s, t in reqs]
        assert sess.arena.blocks_free == sess.arena.blocks_total
        assert sess.metrics.counter("decode_prefill_stalls").value == 0
    assert joined == alone


def test_attn_padded_blocks_provably_inert():
    """NaN-poison the ENTIRE kv pool right after construction: every
    row a valid lane can see is scattered before it is read, and pad
    lanes are select-not-multiply masked — so tokens are byte-identical
    to the clean run even with NaN garbage underneath."""
    reqs = [([1, 2, 3, 4, 5], 4, 0, 0.0), ([3, 1], 4, 1, 0.5)]
    with _kv_session() as sess:
        clean = [sess.generate(p, max_new_tokens=m, seed=s,
                               temperature=t, timeout=60)["tokens"]
                 for p, m, s, t in reqs]
    with _kv_session() as sess:
        sess.arena._arrays = [jnp.full_like(x, jnp.nan)
                              for x in sess.arena._arrays]
        poisoned = [sess.generate(p, max_new_tokens=m, seed=s,
                                  temperature=t, timeout=60)["tokens"]
                    for p, m, s, t in reqs]
    assert poisoned == clean


@pytest.mark.slow
def test_attn_byte_identity_under_bf16_pipeline(monkeypatch):
    """The kv step/prefill programs ride the active compile pipeline:
    under MXTPU_PIPELINE=bf16 decode still emits the same tokens joined
    vs alone (bf16 vs f32 tokens MAY differ; determinism must not)."""
    monkeypatch.setenv("MXTPU_PIPELINE", "bf16")
    reqs = [([1, 2, 3, 4, 5], 5, 0, 0.0), ([3, 1], 5, 1, 0.5),
            ([2, 2, 2, 2, 2], 4, 2, 0.5)]
    with _kv_session(version_tag="tkv-bf16") as sess:
        joined = _run_joined(sess, reqs)
        alone = [sess.generate(p, max_new_tokens=m, seed=s,
                               temperature=t, timeout=60)["tokens"]
                 for p, m, s, t in reqs]
    assert joined == alone


@pytest.mark.slow
def test_attn_swap_model_mid_run_byte_identity():
    """A mid-run hot-swap rebuilds the (step, prefill) pool PAIR in
    lockstep: sequences admitted before the flip finish on the old
    weights byte-identically; post-flip sequences run the new ones."""
    fx = _attn(0)
    fx2 = _attn(1)
    with _kv_session() as sess:
        before = sess.generate([1, 2, 3, 4, 5], max_new_tokens=4,
                               timeout=60)["tokens"]
        item = sess.generate_async([1, 2, 3, 4, 5], max_new_tokens=4,
                                   timeout=60, stream=True)
        # the first streamed token proves the sequence was ADMITTED
        # (old pool pinned) before the flip below
        first = item.stream.get(60)
        assert "token" in first
        sess.swap_model(fx2["step_symbol_json"], fx2["params"],
                        version_tag="tkv-v1-swap",
                        prefill_symbol_json=fx2["prefill_symbol_json"])
        inflight = item.wait(60)
        after = sess.generate([1, 2, 3, 4, 5], max_new_tokens=4,
                              timeout=60)
        # in-flight rode its admission-time version...
        assert inflight["tokens"] == before
        # ...and post-swap traffic really changed weights
        assert after["version"] == "tkv-v1-swap"
        with _kv_session(seed=1, version_tag="tkv-v1-swap-ref") as ref:
            assert after["tokens"] == ref.generate(
                [1, 2, 3, 4, 5], max_new_tokens=4, timeout=60)["tokens"]


# ------------------------------------------------- chunked prefill
def test_long_prompt_never_stalls_decode_chunked_vs_baseline():
    """THE TTFT/stall acceptance gate, on exact counters: with chunked
    prefill a long prompt produces ZERO oversized prefill dispatches
    while a generating sequence waits; the unchunked baseline on the
    same schedule produces >= 1. Liveness tripwire stays 0 in both."""
    def run(chunked, tag):
        kwargs = dict(prefill_chunk_tokens=2, version_tag=tag)
        if chunked:
            kwargs["prefill_buckets"] = (2,)
        else:
            kwargs.update(prefill_chunked=False, prefill_buckets=(8,))
        with _kv_session(**kwargs) as sess:
            short = sess.generate_async([1], max_new_tokens=15,
                                        timeout=60)
            # the short request must be GENERATING when the long prompt
            # arrives — wait for its first emitted token
            deadline = time.monotonic() + 30
            while sess.metrics.counter("decode_tokens_total").value < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.002)
            long = sess.generate_async([1, 2, 3, 4, 5, 6, 7, 8],
                                       max_new_tokens=4, timeout=60)
            a, b = short.wait(60), long.wait(60)
            assert len(a["tokens"]) == 15 and len(b["tokens"]) == 4
            assert sess.metrics.counter(
                "decode_steps_with_admittable_waiting").value == 0
            stalls = sess.metrics.counter("decode_prefill_stalls").value
            chunks = sess.metrics.counter("decode_prefill_chunks").value
            ttft_n = sess.stats()["decode_ttft_ms"]["count"]
        return stalls, chunks, ttft_n

    stalls_c, chunks_c, ttft_c = run(True, "tkv-chunked")
    stalls_u, chunks_u, _ = run(False, "tkv-unchunked")
    assert stalls_c == 0              # the never-stall contract
    assert stalls_u >= 1              # the indicted baseline
    assert chunks_c > chunks_u        # same prompt, bounded quanta
    assert ttft_c >= 2                # every request observed TTFT


def test_prefill_chunk_pricing_math():
    """Admission prices the remaining prompt at one step per CHUNK; the
    final chunk's step double-counts with the first generated token."""
    from mxtpu.serving.decode.session import _Sequence
    s = _Sequence(list(range(10)), 6, None, 0, 0.0, None)
    assert s.remaining_tokens() == 16             # per-token (rows/slots)
    assert s.remaining_tokens(4) == 3 + 6 - 1     # ceil(10/4) chunks
    s.pos = 8
    assert s.remaining_tokens(4) == 1 + 6 - 1
    s.pos = 10
    s.out_tokens = [1]
    assert s.remaining_tokens(4) == 5             # prompt done: rem_new


def test_paged_knob_resolution_precedence(monkeypatch):
    """decode.block_size / max_blocks_per_seq / prefill_chunk_tokens:
    bundle beats artifact/default, explicit beats bundle, env reaches
    sessions that get neither (hand-picked defaults preserved)."""
    from mxtpu.tune import registry as treg
    assert treg.get_knob("decode.block_size").default == 16
    assert treg.get_knob("decode.max_blocks_per_seq").default == 16
    assert treg.get_knob("decode.prefill_chunk_tokens").default == 32
    # kv session: the fixture bundle's geometry (4, 4) wins over the
    # knob defaults; explicit argument wins over the bundle
    with _kv_session(warmup=False) as sess:
        assert sess.block_size == 4
        assert sess.max_blocks_per_seq == 4
        assert sess.prefill_chunk_tokens == 2    # explicit in _kv_session
    with _kv_session(warmup=False, kv_blocks=6, block_size=4,
                     max_blocks_per_seq=3) as sess:
        assert sess.max_blocks_per_seq == 3      # explicit beats bundle
        assert sess.arena.blocks_total == 6      # explicit pool size
    monkeypatch.setenv("MXTPU_DECODE_BLOCK_SIZE", "64")
    with _rows_or_slots_session("slots", warmup=False) as sess:
        assert sess.block_size == 64             # env beats default


def test_kv_budget_refused_at_submit():
    with _kv_session(warmup=False) as sess:
        budget = sess.block_size * sess.max_blocks_per_seq
        with pytest.raises(MXNetError):
            sess.generate_async([1] * budget, max_new_tokens=1)


# ------------------------------------------------------------- chaos
def test_chaos_prefill_and_block_alloc_leak_nothing():
    """Injected prefill failures and block-alloc failures (each
    indistinguishable from a dry pool) fail individual requests but
    leak nothing: free list exact, decode_kv ledger back to baseline,
    the session keeps serving."""
    base = diag.ledger().live_bytes(origin="decode_kv")
    with _kv_session() as sess:
        with faults.scope("serving.decode.prefill:p=1.0,seed=2,times=3"):
            for i in range(3):
                with pytest.raises(Exception):
                    sess.generate([1, 2, 3, 4], max_new_tokens=2,
                                  timeout=30)
        with faults.scope(
                "serving.decode.block_alloc:p=1.0,seed=3,times=2"):
            for i in range(2):
                with pytest.raises(Exception):
                    sess.generate([1, 2], max_new_tokens=2, timeout=30)
        assert sess.arena.blocks_free == sess.arena.blocks_total
        assert sess.arena.free_slots == sess.arena.capacity
        assert diag.ledger().live_bytes(origin="decode_kv") == base
        # post-chaos the same session still serves
        r = sess.generate([1, 2, 3], max_new_tokens=2, timeout=30)
        assert r["finish_reason"] == "length"
        assert sess.arena.blocks_free == sess.arena.blocks_total
    assert diag.ledger().live_bytes(origin="decode_kv") == base


def test_evict_injection_never_leaks_blocks():
    """The _evict finally contract extended to the paged arena: an
    injected eviction failure may fail the request, but every block in
    the table comes back."""
    with _kv_session() as sess:
        with faults.scope("serving.decode.evict:p=1.0,seed=1,times=3"):
            for i in range(3):
                try:
                    sess.generate([1, 2, 3], max_new_tokens=2,
                                  timeout=30)
                except Exception:
                    pass
        assert sess.arena.blocks_free == sess.arena.blocks_total
        assert sess.arena.free_slots == sess.arena.capacity


# -------------------------------------------------------- concurrency
def test_armed_witness_kv_gate():
    """Concurrent kv decode (arena + stream locks live) under the armed
    lock-order witness: zero violations, acyclic observed graph."""
    with conc.scope() as w:
        with _kv_session() as sess:
            stream = sess.generate_stream([1, 2, 3], max_new_tokens=3,
                                          timeout=60)
            toks = [e for e in stream.events(timeout=60)]
            assert any("done" in e for e in toks)
            _run_joined(sess, [([2, 3], 3, 0, 0.0), ([1], 3, 1, 0.5),
                               ([4, 5, 6], 3, 2, 0.0)])
    rep = w.report()
    assert w.violations == 0, rep.render()
    assert w.state()["acyclic"], w.state()["cycles"]


# ---------------------------------------------------------- streaming
def test_token_stream_unit():
    s = TokenStream()
    s.put({"token": 1, "index": 0})
    s.put({"done": {}})
    s.close()
    s.put({"token": 9, "index": 9})       # dropped after close
    assert s.get(1) == {"token": 1, "index": 0}
    assert s.get(1) == {"done": {}}
    assert s.get(1) is None and s.closed
    empty = TokenStream()
    with pytest.raises(TimeoutError):
        empty.get(0.01)


def test_generate_stream_events_match_result():
    with _kv_session() as sess:
        item = sess.generate_async([1, 2, 3, 4, 5], max_new_tokens=4,
                                   stream=True, timeout=60)
        events = list(item.stream.events(timeout=60))
        tokens = [e["token"] for e in events if "token" in e]
        done = [e for e in events if "done" in e]
        assert done and done[0]["done"]["tokens"] == tokens
        assert [e["index"] for e in events if "token" in e] \
            == list(range(len(tokens)))
        assert item.wait(1)["tokens"] == tokens


def test_stream_closed_on_every_failure_path():
    """A failing request's stream terminates with the error event —
    never a hung consumer (here: injected prefill failure)."""
    with _kv_session() as sess:
        with faults.scope("serving.decode.prefill:p=1.0,seed=5,times=1"):
            stream = sess.generate_stream([1, 2, 3, 4], max_new_tokens=2,
                                          timeout=30)
            events = list(stream.events(timeout=30))
        assert events and "error" in events[-1]


# --------------------------------------------------------------- HTTP
def _http_sess():
    sess = _kv_session()
    server = ServingHTTPServer(None, decode=sess, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return sess, server


def test_http_stream_tokens_and_terminal_event():
    sess, server = _http_sess()
    try:
        host, port = server.server_address[:2]
        c = http.client.HTTPConnection(host, port, timeout=60)
        c.request("POST", "/v1/generate?stream=1",
                  json.dumps({"prompt": [1, 2, 3, 4, 5],
                              "max_new_tokens": 4, "seed": 1,
                              "temperature": 0.5}),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        assert r.status == 200
        assert r.getheader("Transfer-Encoding") == "chunked"
        assert r.getheader("Content-Type") == "application/x-ndjson"
        lines = [json.loads(l) for l in r if l.strip()]
        c.close()
        tokens = [e["token"] for e in lines if "token" in e]
        done = [e for e in lines if "done" in e]
        assert done and done[0]["done"]["tokens"] == tokens
        assert len(tokens) == 4
        # plain (non-stream) POST still returns one JSON body
        c = http.client.HTTPConnection(host, port, timeout=60)
        c.request("POST", "/v1/generate",
                  json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 2}),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        assert r.status == 200
        assert len(json.loads(r.read())["tokens"]) == 2
        c.close()
    finally:
        server.shutdown()


def test_http_stream_error_taxonomy():
    """Pre-commit errors keep the JSON status taxonomy even with
    ?stream=1; a mid-stream deadline arrives as a clean terminal error
    chunk on the already-committed 200."""
    sess, server = _http_sess()
    try:
        host, port = server.server_address[:2]
        # bad request BEFORE the stream commits -> plain 400 JSON
        c = http.client.HTTPConnection(host, port, timeout=60)
        c.request("POST", "/v1/generate?stream=1",
                  json.dumps({"prompt": []}),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        assert r.status == 400 and "error" in json.loads(r.read())
        c.close()
        # over-budget prompt -> 400 too (kv budget check at submit)
        c = http.client.HTTPConnection(host, port, timeout=60)
        c.request("POST", "/v1/generate?stream=1",
                  json.dumps({"prompt": [1] * 20, "max_new_tokens": 4}),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        assert r.status == 400
        r.read()
        c.close()
        # mid-stream deadline: 200 committed, terminal error event, the
        # chunked body terminates cleanly (readlines() returns)
        c = http.client.HTTPConnection(host, port, timeout=60)
        c.request("POST", "/v1/generate?stream=1",
                  json.dumps({"prompt": [1] * 8, "max_new_tokens": 8,
                              "timeout_sec": 0.0005}),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        assert r.status == 200
        lines = [json.loads(l) for l in r if l.strip()]
        c.close()
        assert lines and "error" in lines[-1]
        assert lines[-1].get("type") in ("TimeoutError",)
    finally:
        server.shutdown()


def test_debug_panel_kv_block():
    with _kv_session() as sess:
        sess.generate([1, 2, 3], max_new_tokens=2, timeout=30)
        panel = sess.debug_panel()
        assert panel["arena"] == "kv"
        assert panel["kv"]["blocks_total"] == sess.arena.blocks_total
        assert panel["kv"]["live_kv_bytes"] == 0
        assert panel["prefill"]["chunk_tokens"] == 2
        assert panel["prefill"]["chunks"] >= 1
        assert panel["prefill"]["stalls"] == 0
