"""Autograd tape census: every public differentiable NDArray method rides
the tape (VERDICT r2 next #2).

Round 2 fixed four successive "silent-zero-grad" classes by hand (commits
0f1f0e5 slicing, 0335e1d T/flatten/broadcast_to/expand_dims/astype/copy,
dc99059 moveaxis, 0d29064 samplers) — each found by luck. This gate makes
the class structurally impossible: it walks the COMPLETE public surface of
NDArray (methods, operators) plus the module-level array helpers, and

  * every entry classified differentiable is executed under
    ``autograd.record()`` and must produce a NONZERO input gradient;
  * every public name must be classified (differentiable or exempt) — a
    new method added without a census entry fails the suite, the same
    discipline the reference applies to operators via its test_utils
    harness (python/mxnet/test_utils.py:758) and this repo applies to the
    op registry in tests/test_op_census.py.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, nd

# ---------------------------------------------------------------------------
# Census tables. Key = attribute name on NDArray (or nd module for the
# MODULE_* tables). fn(x) -> output NDArray; x is (3, 4) positive floats.
# ---------------------------------------------------------------------------

DIFFERENTIABLE = {
    # views / shape manipulation (the 0335e1d / dc99059 class)
    "T": lambda x: x.T,
    "reshape": lambda x: x.reshape((4, 3)),
    "broadcast_to": lambda x: x.reshape((3, 1, 4)).broadcast_to((3, 5, 4)),
    "expand_dims": lambda x: x.expand_dims(1),
    "flatten": lambda x: x.flatten(),
    "transpose": lambda x: x.transpose((1, 0)),
    "astype": lambda x: x.astype("float64"),
    "copy": lambda x: x.copy(),
    "as_in_context": lambda x: x.as_in_context(x.context),
    # indexing (the 0f1f0e5 class)
    "__getitem__": lambda x: x[1],
    # arithmetic operators, NDArray and scalar operands
    "__add__": lambda x: x + x,
    "__radd__": lambda x: 2.0 + x,
    "__sub__": lambda x: x - 0.5 * x,
    "__rsub__": lambda x: 9.0 - x,
    "__mul__": lambda x: x * x,
    "__rmul__": lambda x: 3.0 * x,
    "__truediv__": lambda x: x / (x + 1.0),
    "__rtruediv__": lambda x: 2.0 / (x + 1.0),
    "__mod__": lambda x: x % 10.0,
    "__rmod__": lambda x: 10.0 % (x + 1.0),
    "__pow__": lambda x: x ** 2,
    "__rpow__": lambda x: 2.0 ** x,
    "__neg__": lambda x: -x,
    "__div__": lambda x: x / 2.0,
    "__rdiv__": lambda x: 5.0 / (x + 1.0),
    # reductions
    "sum": lambda x: x.sum(),
    "mean": lambda x: x.mean(axis=1),
    "max": lambda x: x.max(axis=0),
    "min": lambda x: x.min(),
}

# Classified non-differentiable / no-gradient-path by design. Each entry
# names WHY, so reclassification is a conscious act.
EXEMPT = {
    # construction / identity / host transfer — no tape semantics
    "handle": "ctypes handle property",
    "shape": "metadata", "dtype": "metadata", "ndim": "metadata",
    "size": "metadata", "context": "metadata", "ctx": "metadata",
    "grad": "grad slot",
    "stype": "storage-type metadata",
    "wait_to_read": "sync", "wait_to_write": "sync",
    "asnumpy": "host export (detaches by definition, like reference)",
    "asscalar": "host export",
    "copyto": "writes INTO a destination array; reference records only via "
              "_copyto op on the source — destination mutation is untracked",
    "attach_grad": "tape control", "detach": "tape control",
    "backward": "tape control",
    "tostype": "storage cast; sparse path is CPU-side, grads not defined "
               "for csr/row_sparse tape entries (reference parity)",
    # integer/boolean-valued: zero gradient everywhere by definition
    "argmax": "integer-valued",
    "__eq__": "boolean-valued", "__ne__": "boolean-valued",
    "__gt__": "boolean-valued", "__ge__": "boolean-valued",
    "__lt__": "boolean-valued", "__le__": "boolean-valued",
    "__bool__": "python protocol", "__hash__": "python protocol",
    "__len__": "python protocol", "__iter__": "yields __getitem__ views "
                                              "(covered by __getitem__)",
    "__repr__": "python protocol",
    # mutation: guarded under record (see test_inplace_guard_under_record)
    "__setitem__": "in-place write; raises under record when tracked",
    "__iadd__": "in-place; guarded", "__isub__": "in-place; guarded",
    "__imul__": "in-place; guarded", "__itruediv__": "in-place; guarded",
}

# Module-level helpers that wrap NDArray methods (not registry ops — those
# are swept registry-wide by tests/test_op_gradient_sweep.py).
MODULE_DIFFERENTIABLE = {
    "moveaxis": lambda x: nd.moveaxis(x.reshape((3, 2, 2)), 0, 2),
    "concatenate": lambda x: nd.concatenate([x, x], axis=0),
}


def _grad_of(fn):
    x = nd.array(np.linspace(0.3, 2.7, 12, dtype=np.float32).reshape(3, 4))
    x.attach_grad()
    with autograd.record():
        y = fn(x)
        # reduce to a scalar through ops known-good from the basic autograd
        # tests, so the entry under test is the only suspect
        z = (y * y).sum() if y.size > 1 else y
    z.backward()
    assert x.grad is not None, "no gradient array at all"
    return x.grad.asnumpy()


@pytest.mark.parametrize("name", sorted(DIFFERENTIABLE))
def test_method_rides_tape(name):
    g = _grad_of(DIFFERENTIABLE[name])
    assert np.any(g != 0), (
        "NDArray.%s produced an all-zero input gradient under record() — "
        "the silent-zero-grad class this census exists to catch" % name)
    assert np.all(np.isfinite(g)), "NDArray.%s: non-finite gradient" % name


@pytest.mark.parametrize("name", sorted(MODULE_DIFFERENTIABLE))
def test_module_helper_rides_tape(name):
    g = _grad_of(MODULE_DIFFERENTIABLE[name])
    assert np.any(g != 0), "nd.%s: all-zero input gradient" % name


def test_census_is_complete():
    """Every public NDArray attribute is classified. A new method must be
    added to DIFFERENTIABLE or EXEMPT (with a reason) before it ships."""
    public = set()
    for n in dir(nd.NDArray):
        if n.startswith("_") and not (n.startswith("__") and n.endswith("__")):
            continue  # private helpers
        if n in ("__class__", "__init__", "__new__", "__slots__", "__doc__",
                 "__module__", "__getattr__", "__setattr__", "__delattr__",
                 "__dir__", "__format__", "__getstate__", "__init_subclass__",
                 "__reduce__", "__reduce_ex__", "__sizeof__", "__str__",
                 "__subclasshook__", "__getattribute__", "__weakref__"):
            continue  # object plumbing
        public.add(n)
    unclassified = public - set(DIFFERENTIABLE) - set(EXEMPT)
    assert not unclassified, (
        "public NDArray attributes missing a tape-census classification "
        "(add to DIFFERENTIABLE or EXEMPT in tests/test_tape_census.py): %s"
        % sorted(unclassified))


def test_slice_variants_ride_tape():
    """The 0f1f0e5 class in depth: distinct __getitem__ key shapes."""
    keys = [1, slice(0, 2), slice(None, None, 2), (slice(None), 2),
            (1, slice(1, 3)), Ellipsis, (slice(None), slice(None))]
    for key in keys:
        g = _grad_of(lambda x, k=key: x[k])
        assert np.any(g != 0), "x[%r]: all-zero input gradient" % (key,)


def test_inplace_guard_under_record():
    """Mutating a tape-tracked array under record() must raise, not
    silently corrupt the tape (EXEMPT classification for __iadd__ etc.)."""
    x = nd.array(np.ones((2, 2), np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * 2  # x now on the tape
        with pytest.raises(Exception):
            x += 1.0


def test_chained_views_compose_on_tape():
    """Regression shape of dc99059: views-of-views keep the chain intact."""
    x = nd.array(np.linspace(1, 2, 24, dtype=np.float32).reshape(2, 3, 4))
    x.attach_grad()
    with autograd.record():
        y = x.transpose((2, 0, 1)).flatten().reshape((4, 6)).T
        z = (y * y).sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-5)
