"""Image pipeline tests (parity tier: tests/python/unittest/test_image.py +
test_io.py ImageRecordIter coverage in the reference)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import image as img
from mxtpu import recordio


def _make_rec(tmp_path, n=12, size=40, label_width=1, det=False):
    """Write a small .rec/.idx of random JPEGs; returns (rec, idx) paths."""
    import cv2

    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        arr = (rng.rand(size, size, 3) * 255).astype("uint8")
        ok, buf = cv2.imencode(".jpg", arr)
        assert ok
        if det:
            # [header_width=2, object_width=5, id,xmin,ymin,xmax,ymax]
            label = [2, 5, float(i % 3), 0.1, 0.2, 0.6, 0.7]
            header = recordio.IRHeader(0, label, i, 0)
        elif label_width > 1:
            header = recordio.IRHeader(0, [float(i), float(i + 1)], i, 0)
        else:
            header = recordio.IRHeader(0, float(i % 3), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.tobytes()))
    rec.close()
    return rec_path, idx_path


def test_imdecode_imresize(tmp_path):
    import cv2

    rng = np.random.RandomState(0)
    arr = (rng.rand(30, 20, 3) * 255).astype("uint8")
    ok, buf = cv2.imencode(".png", arr)
    out = img.imdecode(buf.tobytes())
    assert out.shape == (30, 20, 3)
    # png is lossless; BGR->RGB flip must match
    np.testing.assert_array_equal(out.asnumpy(), arr[:, :, ::-1])
    small = img.imresize(out, 10, 15)
    assert small.shape == (15, 10, 3)
    padded = img.copyMakeBorder(out, 1, 2, 3, 4)
    assert padded.shape == (33, 27, 3)


def test_resize_short_and_crops():
    rng = np.random.RandomState(0)
    arr = (rng.rand(48, 64, 3) * 255).astype("uint8")
    r = img.resize_short(arr, 32)
    assert min(r.shape[:2]) == 32 and r.shape[0] == 32
    c, rect = img.center_crop(arr, (32, 32))
    assert c.shape == (32, 32, 3) and rect[2:] == (32, 32)
    rc, _ = img.random_crop(arr, (20, 24))
    assert rc.shape == (24, 20, 3)


def test_augmenter_chain():
    augs = img.CreateAugmenter((3, 24, 24), resize=28, rand_crop=True,
                               rand_mirror=True, mean=True, std=True,
                               brightness=0.1, contrast=0.1, saturation=0.1,
                               pca_noise=0.05)
    rng = np.random.RandomState(0)
    arr = (rng.rand(40, 36, 3) * 255).astype("uint8")
    out = arr
    for a in augs:
        out = a(out)[0].asnumpy()
    assert out.shape == (24, 24, 3)
    assert out.dtype == np.float32


def test_image_iter_rec(tmp_path):
    rec_path, idx_path = _make_rec(tmp_path)
    it = img.ImageIter(batch_size=4, data_shape=(3, 32, 32),
                       path_imgrec=rec_path, path_imgidx=idx_path,
                       shuffle=True)
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 3, 32, 32)
    assert batches[0].label[0].shape == (4,)
    it.reset()
    assert len(list(it)) == 3


def test_image_record_iter(tmp_path):
    rec_path, idx_path = _make_rec(tmp_path, n=10)
    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path, data_shape=(3, 32, 32),
        batch_size=4, shuffle=True, rand_mirror=True,
        mean_r=123.0, mean_g=117.0, mean_b=104.0, preprocess_threads=2)
    epoch = list(it)
    assert len(epoch) == 3  # 10 -> 3 batches with wrap-pad
    assert epoch[-1].pad == 2
    assert epoch[0].data[0].shape == (4, 3, 32, 32)
    it.reset()
    assert len(list(it)) == 3


def test_image_record_iter_parts(tmp_path):
    rec_path, idx_path = _make_rec(tmp_path, n=12)
    a = mx.io.ImageRecordIter(path_imgrec=rec_path, path_imgidx=idx_path,
                              data_shape=(3, 32, 32), batch_size=3,
                              num_parts=2, part_index=0)
    b = mx.io.ImageRecordIter(path_imgrec=rec_path, path_imgidx=idx_path,
                              data_shape=(3, 32, 32), batch_size=3,
                              num_parts=2, part_index=1)
    la = np.concatenate([x.label[0].asnumpy() for x in a])
    lb = np.concatenate([x.label[0].asnumpy() for x in b])
    assert len(la) == len(lb) == 6
    # disjoint shards covering the dataset
    ka = set(zip(la.tolist(), range(0)))  # labels repeat; compare counts
    assert len(la) + len(lb) == 12


def test_image_det_record_iter(tmp_path):
    rec_path, idx_path = _make_rec(tmp_path, n=8, det=True)
    it = mx.io.ImageDetRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path, data_shape=(3, 32, 32),
        batch_size=4, rand_mirror_prob=0.5, rand_crop_prob=0.0)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 32, 32)
    lab = batch.label[0].asnumpy()
    assert lab.ndim == 3 and lab.shape[2] == 5
    # each image has exactly one valid object row
    valid = (lab[:, :, 0] >= 0).sum(axis=1)
    np.testing.assert_array_equal(valid, np.ones(4))
    # box coords stay normalized
    rows = lab[lab[:, :, 0] >= 0]
    assert (rows[:, 1:] >= 0).all() and (rows[:, 1:] <= 1).all()


def test_det_flip_updates_boxes():
    rng = np.random.RandomState(0)
    arr = (rng.rand(20, 20, 3) * 255).astype("uint8")
    label = np.full((4, 5), -1.0, np.float32)
    label[0] = [1, 0.1, 0.2, 0.4, 0.6]
    aug = img.detection.DetHorizontalFlipAug(1.0)
    out, new_label = aug(arr, label)
    np.testing.assert_allclose(new_label[0],
                               [1, 0.6, 0.2, 0.9, 0.6], rtol=1e-6)
    np.testing.assert_array_equal(out, arr[:, ::-1])


def test_im2rec_tool(tmp_path):
    import cv2

    root = tmp_path / "imgs" / "cat"
    root.mkdir(parents=True)
    rng = np.random.RandomState(0)
    for i in range(4):
        cv2.imwrite(str(root / ("%d.jpg" % i)),
                    (rng.rand(16, 16, 3) * 255).astype("uint8"))
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "im2rec.py")
    prefix = str(tmp_path / "out")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, tool, prefix, str(tmp_path / "imgs"),
                    "--list", "--recursive"], check=True, env=env)
    subprocess.run([sys.executable, tool, prefix, str(tmp_path / "imgs")],
                   check=True, env=env)
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               path_imgidx=prefix + ".idx",
                               data_shape=(3, 16, 16), batch_size=2)
    assert len(list(it)) == 2


def test_nd_cv_ops(tmp_path):
    import cv2

    rng = np.random.RandomState(0)
    arr = (rng.rand(8, 8, 3) * 255).astype("uint8")
    path = str(tmp_path / "x.png")
    cv2.imwrite(path, arr)
    out = mx.nd.imread(path)
    assert out.shape == (8, 8, 3)
    small = mx.nd.imresize(out, 4, 4)
    assert small.shape == (4, 4, 3)


def test_image_record_uint8_iter(tmp_path):
    """ImageRecordUInt8Iter (parity iter_image_recordio_2.cc:602): raw
    uint8 batches, byte-identical to the float iterator's pixels, half
    the bytes; mean/std/scale rejected; _v1 aliases resolve; all four
    names creatable through the registry (the C-ABI name path)."""
    import mxtpu as mx

    rec_path, idx_path = _make_rec(tmp_path, n=8)
    kw = dict(path_imgrec=rec_path, path_imgidx=idx_path,
              data_shape=(3, 32, 32), batch_size=4)
    it8 = mx.io.ImageRecordUInt8Iter(**kw)
    b8 = next(iter(it8))
    assert b8.data[0].dtype == np.uint8
    assert it8.provide_data[0].dtype == np.uint8

    itf = mx.io.ImageRecordIter(**kw)
    bf = next(iter(itf))
    np.testing.assert_array_equal(b8.data[0].asnumpy(),
                                  bf.data[0].asnumpy().astype(np.uint8))

    with pytest.raises(mx.MXNetError):
        mx.io.ImageRecordUInt8Iter(scale=1.0 / 255, **kw)

    # _v1 aliases + registry (by-name creation, the MXDataIterCreateIter
    # seam)
    from mxtpu.io import create_iterator
    for name in ("ImageRecordIter", "ImageRecordUInt8Iter",
                 "ImageRecordIter_v1", "ImageRecordUInt8Iter_v1"):
        it = create_iterator(name, **kw)
        assert next(iter(it)).data[0].shape == (4, 3, 32, 32)
