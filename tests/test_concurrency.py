"""mxtpu.analysis.concurrency: runtime lock-order witness, blocking-
under-lock detection, seeded schedule fuzzing (ISSUE 13).

Three blocks:

* **witness units** — cycle detection on a synthetic 3-lock cycle,
  per-thread held-set exactness, RLock reentrancy, unregistered-lock
  detection, blocking-under-lock fixtures, disarm-is-noop;
* **declaration single-sourcing** — the AST lint and the runtime
  witness consume the SAME ``LOCK_LEVELS``/``HOT_PATHS`` objects
  (mxtpu/analysis/declarations.py), plus the new ``unregistered-lock``
  lint rule units;
* **fuzz gates** — seeded-latency perturbation (deterministic: same
  seed ⇒ same schedule ⇒ same firings) over the known-risky trios
  (batcher/pool/hot-swap, snapshot-writer/flush/kill,
  warm-cache/debug-scrape) with the witness armed: zero hierarchy
  violations, an acyclic observed graph, and no hung waiters.

Budgeted like the chaos gates: every schedule is seeded and bounded,
no unseeded sleeps, the workloads are the small serving/elastic
fixtures.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.analysis import concurrency as conc
from mxtpu.analysis import declarations as decl
from mxtpu import faults

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends disarmed with no fault schedule."""
    conc.disarm()
    faults.reset()
    yield
    conc.disarm()
    faults.reset()


# ------------------------------------------------------- witness units
def test_cycle_detection_on_synthetic_three_lock_cycle():
    a = conc.lock("T", "a")
    b = conc.lock("T", "b")
    c = conc.lock("T", "c")
    with conc.scope() as w:
        for first, second in ((a, b), (b, c), (c, a)):
            with first:
                with second:
                    pass
        cycles = w.state()["cycles"]
        assert cycles, "a->b->c->a must be observed as a cycle"
        assert cycles[0][0] == cycles[0][-1]
        assert {"T.a", "T.b", "T.c"} == set(cycles[0][:-1])
        rep = w.report()
        cyc = [f for f in rep if "cycle" in f.message]
        assert cyc and cyc[0].severity == "error"
        assert not w.state()["acyclic"]


def test_acyclic_graph_reports_no_cycle():
    a, b = conc.lock("T", "a"), conc.lock("T", "b")
    with conc.scope() as w:
        with a:
            with b:
                pass
        with a:
            with b:
                pass
        assert w.state()["acyclic"]
        assert w.state()["edges"] == 1


def test_hierarchy_inversion_is_an_error_finding_and_counted():
    # declared: batcher (rank 0) ... engine (later). Acquiring the
    # batcher lock while holding an engine-level lock is an inversion.
    outer = conc.lock("ThreadedEngine", "_pending_lock")
    inner = conc.lock("DynamicBatcher", "_lock")
    reg = mx.telemetry.registry()
    v0 = reg.counter("lock_order_violations").value
    with conc.scope() as w:
        with outer:
            with inner:
                pass
        rep = w.report()
        assert not rep.ok
        inv = [f for f in rep.errors if "violates" in f.message]
        assert inv, rep.render()
        assert inv[0].details["held"] == "ThreadedEngine._pending_lock"
        assert inv[0].details["acquired"] == "DynamicBatcher._lock"
        assert w.violations == 1
    assert reg.counter("lock_order_violations").value == v0 + 1
    # declared order (batcher outermost) is clean
    with conc.scope() as w2:
        with inner:
            with outer:
                pass
        assert w2.report().ok and w2.violations == 0


def test_inversion_not_masked_by_unregistered_lock_on_top():
    """Review regression: an unregistered (rank-less) lock at the TOP
    of the held stack must not mask an inversion against the ranked
    lock beneath it."""
    ranked_outer = conc.lock("programs", "_LOCK")          # late rank
    mystery = conc.lock("NotDeclaredHere", "_x")           # rank None
    ranked_inner = conc.lock("DynamicBatcher", "_lock")    # rank 0
    with conc.scope() as w:
        with ranked_outer:
            with mystery:
                with ranked_inner:
                    pass
        inv = [f for f in w.report().errors if "violates" in f.message]
        assert inv, w.report().render()
        assert inv[0].details["held"] == "programs._LOCK"
        assert w.violations == 1


def test_rlock_locked_matches_raw_primitive():
    """Drop-in parity: raw RLock has no locked() on this Python; the
    tracked wrapper must not pretend otherwise (a silently-wrong
    answer would be worse than the raw AttributeError)."""
    r = conc.rlock("T", "r")
    raw = threading.RLock()
    if hasattr(raw, "locked"):       # newer Pythons grew RLock.locked
        with r:
            assert r.locked()
    else:
        with pytest.raises(AttributeError):
            r.locked()
    # plain Lock keeps the real locked()
    lk = conc.lock("T", "l")
    assert lk.locked() is False
    with lk:
        assert lk.locked() is True


def test_unregistered_lock_lint_rule_sees_import_aliases():
    lint = _lint_mod()
    for src in (
        "from threading import Lock\n_L = Lock()\n",
        "from threading import Condition as C\n_L = C()\n",
        "import threading as _t\n_L = _t.RLock()\n",
    ):
        founds = lint.lint_source(src, "mxtpu/foo.py")
        assert [f.rule for f in founds] == ["unregistered-lock"], (src,
                                                                   founds)
    # unrelated names stay silent
    assert not lint.lint_source(
        "from os.path import join\nLock = dict\n_L = Lock()\n",
        "mxtpu/foo.py")


def test_per_thread_held_set_exactness():
    """Two threads interleaving on their own locks must never see each
    other's held set (no cross-thread edges, no false inversions)."""
    a = conc.lock("DynamicBatcher", "_lock")       # rank 0
    b = conc.lock("ThreadedEngine", "_pending_lock")  # late rank
    barrier = threading.Barrier(2)
    errs = []

    def hold(lk, n):
        try:
            for _ in range(n):
                with lk:
                    barrier.wait(timeout=5)
                    time.sleep(0.001)
                    barrier.wait(timeout=5)
        except Exception as e:  # barrier timeout = test bug
            errs.append(e)

    with conc.scope() as w:
        t1 = threading.Thread(target=hold, args=(b, 8))
        t2 = threading.Thread(target=hold, args=(a, 8))
        t1.start(), t2.start()
        t1.join(), t2.join()
        assert not errs
        # thread 1 held ONLY b while thread 2 acquired a (and vice
        # versa): per-thread tracking must record zero edges
        assert w.state()["edges"] == 0, w.graph()
        assert w.report().ok
        assert w.acquisitions == 16


def test_rlock_reentrancy_is_not_a_violation():
    r = conc.rlock("T", "r")
    inner = conc.lock("DynamicBatcher", "_lock")
    with conc.scope() as w:
        with r:
            with r:          # reentrant re-acquire: no edge, no finding
                with r:
                    pass
        assert w.state()["edges"] == 0
        rep = w.report()
        assert not [f for f in rep if "violates" in f.message]
        # after full release the held set is empty: no stale edge
        with inner:
            pass
        assert w.state()["edges"] == 0


def test_unregistered_lock_detection():
    mystery = conc.lock("NotDeclaredAnywhere", "_lock")
    with conc.scope() as w:
        with mystery:
            pass
        rep = w.report()
        unreg = [f for f in rep if "unregistered" in f.message]
        assert unreg and unreg[0].severity == "warning"
        assert "NotDeclaredAnywhere._lock" in unreg[0].message
        # dedup: a second acquisition does not duplicate the finding
        with mystery:
            pass
        assert len([f for f in w.report()
                    if "unregistered" in f.message]) == 1


def test_blocking_under_lock_fixture():
    lk = conc.lock("DeviceMemoryLedger", "_lock")
    with conc.scope() as w:
        conc.blocking("sleep")          # no lock held: fine
        with lk:
            conc.blocking("sleep", "fixture")
        rep = w.report()
        blk = [f for f in rep.errors if "blocking" in f.message]
        assert blk, rep.render()
        assert "DeviceMemoryLedger._lock" in blk[0].message
        assert w.blocked_calls == 1


def test_blocking_allowlist_is_honored():
    # ("device_get", _Replica.lock) is ALLOWED_BLOCKING (warmup triage)
    lk = conc.lock("_Replica", "lock")
    with conc.scope() as w:
        with lk:
            conc.blocking("device_get", "warmup fixture")
        assert w.report().ok
        assert w.blocked_calls == 0


def test_condition_wait_releases_held_but_flags_other_locks():
    c = conc.condition(owner="KVServer", attr="cv")
    with conc.scope() as w:
        with c:
            c.wait(timeout=0.01)   # own lock released for the wait: ok
        assert w.report().ok
        other = conc.lock("DynamicBatcher", "_lock")
        with other:
            with c:
                c.wait(timeout=0.01)   # batcher lock held across wait
        blk = [f for f in w.report().errors if "cond_wait" in f.message]
        assert blk, w.report().render()


def test_condition_notify_wakes_tracked_wait():
    c = conc.condition(owner="KVServer", attr="cv")
    got = []

    def waiter():
        with c:
            got.append(c.wait(timeout=5))

    with conc.scope():
        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with c:
            c.notify_all()
        t.join(timeout=5)
    assert got == [True]


def test_disarm_is_noop():
    """Disarmed, tracked locks behave as raw primitives: witness state
    untouched, no TLS bookkeeping, blocking guard free."""
    lk = conc.lock("DynamicBatcher", "_lock")
    assert not conc.armed()
    with lk:
        assert lk.locked()
        conc.blocking("sleep")
    assert not lk.locked()
    assert conc.report().ok and len(conc.report()) == 0
    assert conc.state()["armed"] is False
    # non-blocking acquire semantics survive the wrapper
    assert lk.acquire(False) is True
    assert lk.acquire(False) is False
    lk.release()


def test_arm_scope_restores_previous_witness():
    w0 = conc.arm()
    with conc.scope() as w1:
        assert conc.witness() is w1
    assert conc.witness() is w0
    conc.disarm()
    assert conc.witness() is None


# ---------------------------------------------- declaration single-source
def _lint_mod():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import mxtpu_lint
    finally:
        sys.path.pop(0)
    return mxtpu_lint


def test_lock_levels_single_sourced_between_lint_and_witness():
    """LOCK_LEVELS/HOT_PATHS exist in exactly one module
    (analysis/declarations.py); the lint loads the same file by path,
    so the tables must compare EQUAL, level for level."""
    lint = _lint_mod()
    assert lint.LOCK_LEVELS == decl.LOCK_LEVELS
    assert lint.HOT_PATHS == decl.HOT_PATHS
    # and the witness resolves ranks from the same table
    for key, (rank, level) in decl.LOCK_RANK.items():
        assert conc.lock(*key).rank == (rank, level)
    # the legacy in-lint definition is gone: the lint module's source
    # has no LOCK_LEVELS literal of its own
    with open(os.path.join(ROOT, "tools", "mxtpu_lint.py")) as f:
        src = f.read()
    assert "LOCK_LEVELS = _DECL.LOCK_LEVELS" in src
    assert "LOCK_LEVELS = [" not in src


def test_every_declared_key_is_well_formed():
    seen = set()
    for level, keys in decl.LOCK_LEVELS:
        for key in keys:
            assert isinstance(key, tuple) and len(key) == 2, key
            assert key not in seen, "duplicate declaration %r" % (key,)
            seen.add(key)


def test_unregistered_lock_lint_rule_units():
    lint = _lint_mod()
    bad = "import threading\n_L = threading.Lock()\n"
    founds = lint.lint_source(bad, "mxtpu/foo.py")
    assert [f.rule for f in founds] == ["unregistered-lock"], founds
    for ctor in ("RLock", "Condition"):
        src = "import threading\n_L = threading.%s()\n" % ctor
        assert [f.rule for f in lint.lint_source(src, "mxtpu/foo.py")] \
            == ["unregistered-lock"]
    ok = ("import threading\n"
          "# mxtpu: allow-raw-lock(test fixture)\n"
          "_L = threading.Lock()\n")
    assert not lint.lint_source(ok, "mxtpu/foo.py")
    factory = ("from mxtpu.analysis import concurrency as _conc\n"
               "_L = _conc.lock('Owner', '_lock')\n")
    assert not lint.lint_source(factory, "mxtpu/foo.py")


def test_repo_has_no_raw_locks():
    """Acceptance: every lock in mxtpu/ is registered (tracked factory)
    or pragma'd — the repo lints clean under the new rule."""
    lint = _lint_mod()
    founds = [f for f in lint.lint_tree(os.path.join(ROOT, "mxtpu"))
              if f.rule == "unregistered-lock"]
    assert founds == [], founds


def test_debug_state_has_concurrency_panel():
    import mxtpu.diagnostics as diag
    st = diag.debug_state()
    assert st["concurrency"]["armed"] is False
    with conc.scope():
        st = diag.debug_state()
        assert st["concurrency"]["armed"] is True
        assert "acyclic" in st["concurrency"]


# ------------------------------------------------------- fuzz determinism
def test_fuzzer_same_seed_same_schedule():
    f1 = conc.ScheduleFuzzer(seed=42)
    f2 = conc.ScheduleFuzzer(seed=42)
    assert f1.describe() == f2.describe()
    assert f1.describe() != conc.ScheduleFuzzer(seed=43).describe()
    # covers every declared yield point by default
    assert set(f.points for f in (f1,))
    assert set(f1.points) == set(faults.POINTS)


def test_fuzzer_same_seed_same_firing_sequence():
    """The determinism contract end-to-end: two schedules from one seed
    fire at the SAME evaluation indices."""
    def firing_pattern(seed):
        sched = conc.ScheduleFuzzer(
            seed=seed, points=("engine.dispatch",), p=0.5,
            latency_ms=(0.0, 0.0), times=1000).schedule()
        spec = sched.specs[0]
        pattern = []
        for i in range(200):
            n0 = spec.fired
            sched.evaluate("engine.dispatch")
            pattern.append(spec.fired - n0)
        return pattern

    p1, p2 = firing_pattern(7), firing_pattern(7)
    assert p1 == p2
    assert sum(p1) > 0
    assert firing_pattern(8) != p1


def test_fuzzer_rejects_unknown_yield_point():
    with pytest.raises(mx.MXNetError, match="unknown yield point"):
        conc.ScheduleFuzzer(points=("not.a.point",))


def test_fuzzer_latency_derivation_bounded_and_stable():
    f = conc.ScheduleFuzzer(seed=5, latency_ms=(0.5, 2.5))
    for d in f.describe():
        assert 0.5 <= d["latency_ms"] <= 2.5
        assert d["kind"] == "latency"
        assert d["times"] == 16


# ----------------------------------------------------------- fuzz gates
def _serving_fixture():
    from mxtpu.models.serving_fixtures import get_fixture
    return get_fixture("mlp")


def test_fuzz_gate_batcher_pool_hot_swap():
    """Known-risky trio #1: concurrent clients + mid-traffic hot-swap
    under seeded latency at the serving yield points, witness armed.
    Every request resolves; zero hierarchy violations; acyclic graph."""
    from mxtpu.serving import ServingSession
    sym, params, shapes = _serving_fixture()
    outcomes = []
    with conc.scope() as w:
        with ServingSession(sym, params, shapes, buckets=(1, 4),
                            max_delay_ms=2,
                            contexts=[mx.cpu(0)]) as sess:
            x = np.zeros((1, 784), np.float32)

            def client(n):
                for _ in range(n):
                    try:
                        sess.predict({"data": x}, timeout=10)
                        outcomes.append("ok")
                    except Exception:
                        outcomes.append("err")

            with conc.fuzz_scope(
                    seed=11, p=0.5, latency_ms=(0.2, 1.5),
                    points=("serving.replica.dispatch",
                            "serving.replica.collect",
                            "engine.dispatch")):
                ts = [threading.Thread(target=client, args=(10,))
                      for _ in range(3)]
                for t in ts:
                    t.start()
                sess.swap_model(sym, params, version_tag="fuzz-swap")
                for t in ts:
                    t.join(timeout=60)
        assert len(outcomes) == 30, "no hung waiters under fuzz"
        assert outcomes.count("ok") == 30, outcomes
        rep = w.report()
        assert w.violations == 0, rep.render()
        assert w.state()["acyclic"], w.state()["cycles"]


def test_fuzz_gate_snapshot_writer_flush_kill(tmp_path):
    """Known-risky trio #2: per-step elastic snapshots with seeded
    latency at the write seam PLUS an injected writer kill, then a
    flush. Fit completes every step; witness stays clean."""
    from mxtpu.elastic import snapshot as esnap
    from mxtpu.models import mlp
    with conc.scope() as w:
        fz = conc.ScheduleFuzzer(seed=23,
                                 points=("elastic.snapshot.write",),
                                 p=0.5, latency_ms=(0.2, 1.0))
        specs = fz.specs() + [faults.FaultSpec(
            "elastic.snapshot.write", kind="kill", after=2)]
        steps = [0]
        X = np.random.RandomState(0).rand(256, 784).astype(np.float32)
        y = np.zeros(256, np.float32)
        it = mx.io.NDArrayIter(X, y, batch_size=64,
                               label_name="softmax_label")
        mod = mx.mod.Module(mlp.get_symbol(10), context=mx.cpu())
        with faults.scope(list(specs)):
            mod.fit(it, num_epoch=1, optimizer="sgd",
                    elastic=mx.elastic.ElasticConfig(
                        str(tmp_path / "ck"), every_n_steps=1, keep=2),
                    batch_end_callback=lambda p: steps.__setitem__(
                        0, steps[0] + 1))
            esnap.writer().flush(timeout=30)
        assert steps[0] == 4
        rep = w.report()
        assert w.violations == 0, rep.render()
        assert w.blocked_calls == 0, rep.render()
        assert w.state()["acyclic"], w.state()["cycles"]


def test_fuzz_gate_warm_cache_debug_scrape():
    """Known-risky trio #3: concurrent /debug/state scrapes (warm-cache
    manifest + ledger + engine snapshots) racing prewarm + session
    adoption + hot-swap, witness armed."""
    import mxtpu.diagnostics as diag
    from mxtpu.serving import ServingSession
    from mxtpu.serving.pool import prewarm, warm_cache
    sym, params, shapes = _serving_fixture()
    errs = []

    # BOUNDED scrapes with a yield between them (suite-budget rule):
    # debug_state's cost grows with process history (ledger reconcile
    # walks every live array, the program table accretes), and an
    # unthrottled scrape loop on the 2-core host can starve the
    # concurrent XLA compiles for minutes mid-suite
    def scraper(n=25):
        for _ in range(n):
            try:
                diag.debug_state()
                warm_cache().manifest()
            except Exception as e:
                errs.append(e)
                return
            time.sleep(0.01)

    with conc.scope() as w:
        ts = [threading.Thread(target=scraper) for _ in range(2)]
        for t in ts:
            t.start()
        try:
            prewarm(sym, params, shapes, buckets=(1, 4),
                    contexts=[mx.cpu(0)], version_tag="scrape-v0")
            with ServingSession(sym, params, shapes, buckets=(1, 4),
                                max_delay_ms=2, contexts=[mx.cpu(0)],
                                version_tag="scrape-v0") as sess:
                x = np.zeros((1, 784), np.float32)
                sess.predict({"data": x})
                sess.swap_model(sym, params, version_tag="scrape-v1")
                sess.predict({"data": x})
        finally:
            for t in ts:
                t.join(timeout=60)
        assert not errs
        rep = w.report()
        assert w.violations == 0, rep.render()
        assert w.state()["acyclic"], w.state()["cycles"]


# ----------------------------------- armed integration gates (acceptance)
def test_witness_armed_over_serving_overload():
    """Acceptance: the serving-overload posture (bounded queue, tiny
    delay, more offered work than one replica drains) armed — the
    batcher/pool/admission lock web under real backpressure reports
    zero hierarchy violations and an acyclic observed graph, and every
    request resolves (answered or shed, never hung)."""
    from mxtpu.serving import ServingSession
    sym, params, shapes = _serving_fixture()
    outcomes = []
    with conc.scope() as w:
        with ServingSession(sym, params, shapes, buckets=(1, 4),
                            max_delay_ms=1, max_queue=8,
                            contexts=[mx.cpu(0)]) as sess:
            x = np.zeros((1, 784), np.float32)

            def client(n):
                for _ in range(n):
                    try:
                        sess.predict({"data": x}, timeout=10)
                        outcomes.append("ok")
                    except Exception:
                        outcomes.append("shed")

            ts = [threading.Thread(target=client, args=(12,))
                  for _ in range(6)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=120)
        assert len(outcomes) == 72, "every request resolves"
        assert "ok" in outcomes
        rep = w.report()
        assert w.violations == 0, rep.render()
        assert w.blocked_calls == 0, rep.render()
        assert w.state()["acyclic"], w.state()["cycles"]
        # the overload really exercised the hierarchy web
        assert w.acquisitions > 100


def test_witness_armed_over_elastic_kill_resume(tmp_path):
    """Acceptance: the elastic kill-at-step-N/resume protocol under an
    armed witness — zero hierarchy violations, acyclic graph, and the
    resume stays bit-exact (the witness must observe, never perturb)."""
    from mxtpu.models import mlp

    def fit(resume, n_epoch=1):
        # identical global RNG state per run: the initializer and the
        # iterator shuffle draw from it, and the assertion below is
        # bit-exactness ACROSS two runs
        mx.random.seed(42)
        np.random.seed(42)
        X = np.random.RandomState(0).rand(256, 784).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 10, 256).astype(np.float32)
        it = mx.io.NDArrayIter(X, y, batch_size=64,
                               label_name="softmax_label")
        mod = mx.mod.Module(mlp.get_symbol(10), context=mx.cpu())
        mod.fit(it, num_epoch=n_epoch, optimizer="sgd",
                optimizer_params={"learning_rate": 0.05},
                elastic=mx.elastic.ElasticConfig(
                    str(tmp_path / "ck"), every_n_steps=1, sync=True),
                resume=resume)
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    with conc.scope() as w:
        # a "killed" run: stop after epoch 0 would need process death;
        # instead prove observe-don't-perturb — armed vs disarmed runs
        # produce IDENTICAL weights
        armed_w = fit(resume=False)
        rep = w.report()
        assert w.violations == 0, rep.render()
        assert w.state()["acyclic"], w.state()["cycles"]
    plain_w = fit(resume=False)
    for k in armed_w:
        assert (armed_w[k] == plain_w[k]).all(), k


def test_witness_armed_over_pipeline_parity_gate():
    """Acceptance: the bf16 pipeline-parity path (analysis-licensed
    rewrite + verifier re-proof + fused-step build) armed — the compile
    seam's build locks respect the hierarchy."""
    from mxtpu.compile import pipeline as pl
    from mxtpu.models import mlp
    with conc.scope() as w:
        with pl.pipeline_scope(("bf16",)):
            X = np.random.RandomState(0).rand(128, 784).astype(np.float32)
            y = np.zeros(128, np.float32)
            it = mx.io.NDArrayIter(X, y, batch_size=64,
                                   label_name="softmax_label")
            mod = mx.mod.Module(mlp.get_symbol(10), context=mx.cpu())
            mod.fit(it, num_epoch=1, optimizer="sgd")
        rep = w.report()
        assert w.violations == 0, rep.render()
        assert w.blocked_calls == 0, rep.render()
        assert w.state()["acyclic"], w.state()["cycles"]
        assert w.acquisitions > 0


def test_witness_telemetry_series_exist_when_armed():
    reg = mx.telemetry.registry()
    outer = conc.lock("ThreadedEngine", "_pending_lock")
    inner = conc.lock("DynamicBatcher", "_lock")
    with conc.scope():
        with outer:
            with inner:
                pass
    assert reg.counter("lock_order_violations").value >= 1
