"""Gluon RNN tests (mirrors tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, gluon


def _init(block):
    block.collect_params().initialize(mx.init.Xavier(), ctx=mx.cpu())
    return block


def test_lstm_layer_shapes():
    lstm = _init(gluon.rnn.LSTM(10, num_layers=2, bidirectional=True))
    x = mx.nd.array(np.random.randn(7, 4, 5).astype("float32"))
    out = lstm(x)
    assert out.shape == (7, 4, 20)
    h0 = mx.nd.zeros((4, 4, 10))
    c0 = mx.nd.zeros((4, 4, 10))
    out, states = lstm(x, [h0, c0])
    assert out.shape == (7, 4, 20)
    assert [s.shape for s in states] == [(4, 4, 10), (4, 4, 10)]


def test_gru_rnn_layer_ntc():
    gru = _init(gluon.rnn.GRU(6, num_layers=1, layout="NTC"))
    x = mx.nd.array(np.random.randn(3, 5, 4).astype("float32"))
    out = gru(x)
    assert out.shape == (3, 5, 6)
    rnn = _init(gluon.rnn.RNN(6, activation="tanh", layout="NTC"))
    assert rnn(x).shape == (3, 5, 6)


def test_rnn_layer_backward():
    lstm = _init(gluon.rnn.LSTM(8))
    x = mx.nd.array(np.random.randn(5, 2, 3).astype("float32"))
    with autograd.record():
        out = lstm(x)
        loss = mx.nd.sum(out * out)
    loss.backward()
    g = lstm.collect_params()["%sl0_i2h_weight" % lstm.prefix].grad()
    assert g.shape == (32, 3)
    assert float(mx.nd.sum(mx.nd.abs(g)).asnumpy()) > 0


def test_layer_matches_cell_unroll():
    """Fused gluon LSTM layer == LSTMCell.unroll with shared packed weights."""
    T, N, I, H = 4, 2, 3, 5
    layer = _init(gluon.rnn.LSTM(H, input_size=I))
    x = np.random.randn(T, N, I).astype("float32")
    out_layer = layer(mx.nd.array(x)).asnumpy()

    cell = gluon.rnn.LSTMCell(H, input_size=I)
    cell.collect_params().initialize(ctx=mx.cpu())
    p = layer.collect_params()
    cp = cell.collect_params()
    cp["%si2h_weight" % cell.prefix].set_data(
        p["%sl0_i2h_weight" % layer.prefix].data())
    cp["%sh2h_weight" % cell.prefix].set_data(
        p["%sl0_h2h_weight" % layer.prefix].data())
    cp["%si2h_bias" % cell.prefix].set_data(
        p["%sl0_i2h_bias" % layer.prefix].data())
    cp["%sh2h_bias" % cell.prefix].set_data(
        p["%sl0_h2h_bias" % layer.prefix].data())
    out_cell, _ = cell.unroll(T, mx.nd.array(x), layout="TNC",
                              merge_outputs=True)
    assert np.allclose(out_layer, out_cell.asnumpy(), atol=1e-5)


def test_gluon_cell_stack_and_modifiers():
    cell = gluon.rnn.SequentialRNNCell()
    cell.add(gluon.rnn.LSTMCell(8))
    cell.add(gluon.rnn.ResidualCell(gluon.rnn.GRUCell(8)))
    cell.add(gluon.rnn.DropoutCell(0.2))
    _init(cell)
    x = mx.nd.array(np.random.randn(4, 3, 6).astype("float32"))
    outs, states = cell.unroll(3, x, merge_outputs=True)
    assert outs.shape == (4, 3, 8)
    assert len(states) == 3


def test_gluon_bidirectional_cell():
    cell = gluon.rnn.BidirectionalCell(gluon.rnn.LSTMCell(4, prefix="l_"),
                                       gluon.rnn.LSTMCell(4, prefix="r_"))
    _init(cell)
    x = mx.nd.array(np.random.randn(2, 3, 5).astype("float32"))
    outs, states = cell.unroll(3, x, merge_outputs=True)
    assert outs.shape == (2, 3, 8)
    assert len(states) == 4


def test_gluon_zoneout_cell():
    cell = gluon.rnn.ZoneoutCell(gluon.rnn.RNNCell(6), 0.3, 0.2)
    _init(cell)
    x = mx.nd.array(np.random.randn(2, 4, 3).astype("float32"))
    outs, _ = cell.unroll(4, x, merge_outputs=True)
    assert outs.shape == (2, 4, 6)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_rnn_cell_trains_in_net():
    """Tiny seq classifier with a gluon LSTM trains under Trainer."""
    rng = np.random.RandomState(0)
    net = gluon.nn.HybridSequential()
    lstm = gluon.rnn.LSTM(16, layout="NTC")
    dense = gluon.nn.Dense(2)
    # sequence: class = whether the mean of features is positive
    X = rng.randn(64, 6, 4).astype("float32")
    Y = (X.mean(axis=(1, 2)) > 0).astype("float32")
    params = gluon.ParameterDict()
    lstm.collect_params().initialize(mx.init.Xavier(), ctx=mx.cpu())
    dense.collect_params().initialize(mx.init.Xavier(), ctx=mx.cpu())
    allp = gluon.ParameterDict()
    allp.update(lstm.collect_params())
    allp.update(dense.collect_params())
    trainer = gluon.Trainer(allp, "adam", {"learning_rate": 0.02})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(30):
        with autograd.record():
            h = lstm(mx.nd.array(X))
            out = dense(h[:, -1, :])
            loss = loss_fn(out, mx.nd.array(Y))
        loss.backward()
        trainer.step(64)
        losses.append(float(mx.nd.mean(loss).asnumpy()))
    assert losses[-1] < losses[0] * 0.85, losses[::10]
