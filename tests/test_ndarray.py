"""NDArray imperative tests (model: reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd


def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), 0)
    b = nd.ones((2, 2), dtype="int32")
    assert b.dtype == np.int32
    c = nd.full((2, 3), 7.5)
    assert np.allclose(c.asnumpy(), 7.5)
    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)


def test_elementwise():
    a = nd.array(np.array([[1.0, 2], [3, 4]]))
    b = nd.array(np.array([[4.0, 3], [2, 1]]))
    assert np.allclose((a + b).asnumpy(), 5)
    assert np.allclose((a * b).asnumpy(), [[4, 6], [6, 4]])
    assert np.allclose((a - 1).asnumpy(), [[0, 1], [2, 3]])
    assert np.allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    assert np.allclose((1 / b).asnumpy(), [[0.25, 1 / 3.0], [0.5, 1]])
    assert np.allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    assert np.allclose((-a).asnumpy(), [[-1, -2], [-3, -4]])


def test_comparisons():
    a = nd.array(np.array([1.0, 2, 3]))
    b = nd.array(np.array([3.0, 2, 1]))
    assert np.allclose((a == b).asnumpy(), [0, 1, 0])
    assert np.allclose((a > b).asnumpy(), [0, 0, 1])
    assert np.allclose((a <= b).asnumpy(), [1, 1, 0])


def test_inplace_ops():
    a = nd.ones((2, 2))
    a += 1
    assert np.allclose(a.asnumpy(), 2)
    a *= 3
    assert np.allclose(a.asnumpy(), 6)


def test_indexing():
    a = nd.array(np.arange(12).reshape(3, 4).astype("float32"))
    assert np.allclose(a[1].asnumpy(), [4, 5, 6, 7])
    assert np.allclose(a[1:3].asnumpy(), np.arange(4, 12).reshape(2, 4))
    a[0] = 0
    assert np.allclose(a.asnumpy()[0], 0)
    a[:] = 1
    assert np.allclose(a.asnumpy(), 1)


def test_reshape_transpose():
    a = nd.array(np.arange(6).astype("float32"))
    b = a.reshape((2, 3))
    assert b.shape == (2, 3)
    assert b.T.shape == (3, 2)
    c = nd.transpose(b)
    assert c.shape == (3, 2)
    d = nd.Reshape(b, shape=(3, 2))
    assert d.shape == (3, 2)
    e = nd.Reshape(b, shape=(0, -1))
    assert e.shape == (2, 3)


def test_reduce():
    a = nd.array(np.arange(12).reshape(3, 4).astype("float32"))
    assert np.isclose(a.sum().asscalar(), 66)
    assert np.allclose(nd.sum(a, axis=0).asnumpy(), [12, 15, 18, 21])
    assert np.allclose(nd.max(a, axis=1).asnumpy(), [3, 7, 11])
    assert np.allclose(nd.mean(a, axis=1, keepdims=True).asnumpy().shape,
                       (3, 1))
    assert np.allclose(nd.sum(a, axis=1, exclude=True).asnumpy(), [12, 15, 18, 21])


def test_dot():
    a = np.random.randn(3, 4).astype("float32")
    b = np.random.randn(4, 5).astype("float32")
    c = nd.dot(nd.array(a), nd.array(b))
    assert np.allclose(c.asnumpy(), a @ b, atol=1e-5)
    c2 = nd.dot(nd.array(a.T), nd.array(b), transpose_a=True)
    assert np.allclose(c2.asnumpy(), a @ b, atol=1e-5)
    bd = nd.batch_dot(nd.array(np.random.randn(2, 3, 4).astype("f4")),
                      nd.array(np.random.randn(2, 4, 5).astype("f4")))
    assert bd.shape == (2, 3, 5)


def test_broadcast():
    a = nd.array(np.ones((3, 1)).astype("float32"))
    b = nd.array(np.ones((1, 4)).astype("float32"))
    c = nd.broadcast_add(a, b)
    assert c.shape == (3, 4)
    assert np.allclose(c.asnumpy(), 2)
    d = nd.broadcast_to(a, shape=(3, 5))
    assert d.shape == (3, 5)


def test_concat_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concatenate([a, b], axis=0)
    assert c.shape == (4, 3)
    c2 = nd.Concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    parts = nd.SliceChannel(c2, num_outputs=2, axis=1)
    assert parts[0].shape == (2, 3)


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs.bin")
    a = nd.array(np.random.randn(3, 4).astype("float32"))
    b = nd.array(np.arange(5).astype("int32"))
    nd.save(fname, {"a": a, "b": b})
    loaded = nd.load(fname)
    assert np.allclose(loaded["a"].asnumpy(), a.asnumpy())
    assert np.array_equal(loaded["b"].asnumpy(), b.asnumpy())
    nd.save(fname, [a, b])
    llist = nd.load(fname)
    assert np.allclose(llist[0].asnumpy(), a.asnumpy())


def test_wait_and_context():
    a = nd.ones((4,), ctx=mx.cpu())
    a.wait_to_read()
    nd.waitall()
    assert a.context.device_type == "cpu"
    b = a.as_in_context(mx.cpu(0))
    assert b.shape == (4,)


def test_astype_copy():
    a = nd.ones((2, 2))
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.copy()
    c[:] = 5
    assert np.allclose(a.asnumpy(), 1)


def test_take_onehot():
    w = nd.array(np.arange(12).reshape(4, 3).astype("float32"))
    idx = nd.array(np.array([0, 2], dtype="float32"))
    t = nd.take(w, idx)
    assert np.allclose(t.asnumpy(), [[0, 1, 2], [6, 7, 8]])
    oh = nd.one_hot(idx, depth=4)
    assert np.allclose(oh.asnumpy(), [[1, 0, 0, 0], [0, 0, 1, 0]])


def test_arange_ordering():
    a = nd.arange(0, 10, 2)
    assert np.allclose(a.asnumpy(), [0, 2, 4, 6, 8])
    x = nd.array(np.array([3.0, 1, 2]))
    assert np.allclose(nd.sort(x).asnumpy(), [1, 2, 3])
    assert np.allclose(nd.argsort(x).asnumpy(), [1, 2, 0])
    assert np.allclose(nd.topk(x, k=2, ret_typ="value").asnumpy(), [3, 2])
    assert np.allclose(nd.argmax(x, axis=0).asnumpy(), 0)


def test_module_level_arithmetic():
    """mx.nd.add/subtract/multiply/divide/power/maximum/minimum accept
    array-or-scalar on either side (parity ndarray.py:1748-2610)."""
    a = nd.array(np.full((2, 3), 6.0, "float32"))
    b = nd.array(np.full((2, 3), 4.0, "float32"))
    assert float(nd.add(a, b).asnumpy()[0, 0]) == 10
    assert float(nd.subtract(a, 1).asnumpy()[0, 0]) == 5
    assert float(nd.multiply(2, a).asnumpy()[0, 0]) == 12
    assert float(nd.divide(a, b).asnumpy()[0, 0]) == 1.5
    assert float(nd.true_divide(a, 3).asnumpy()[0, 0]) == 2
    assert float(nd.modulo(a, b).asnumpy()[0, 0]) == 2
    assert float(nd.power(a, 2).asnumpy()[0, 0]) == 36
    assert float(nd.maximum(a, 7).asnumpy()[0, 0]) == 7
    assert float(nd.minimum(7, a).asnumpy()[0, 0]) == 6
    assert nd.add(2, 3) == 5 and nd.maximum(2, 3) == 3
    # scalar-LHS for the non-commutative ops (reflected dunders)
    assert float(nd.power(2, nd.array(np.full((2,), 3.0, "f")))
                 .asnumpy()[0]) == 8
    assert float(nd.modulo(7, b).asnumpy()[0, 0]) == 3
    assert float(nd.subtract(10, a).asnumpy()[0, 0]) == 4
    assert float(nd.divide(12, b).asnumpy()[0, 0]) == 3
