"""Operator-census parity test: every op name in SURVEY.md Appendix A
(the reference's registered-operator census) must resolve — either in the
op registry or as an nd-namespace function (host ops like the cv codecs).
"""
import mxtpu  # noqa: F401
import mxtpu.ndarray as nd
from mxtpu.ops import registry

LEGACY = """Activation BatchNorm BatchNorm_v1 BilinearSampler Concat
Convolution Convolution_v1 Correlation Crop Deconvolution Dropout
FullyConnected GridGenerator IdentityAttachKLSparseReg InstanceNorm
L2Normalization LRN LeakyReLU LinearRegressionOutput
LogisticRegressionOutput MAERegressionOutput MakeLoss Pad Pooling
Pooling_v1 RNN ROIPooling SVMOutput SequenceLast SequenceMask
SequenceReverse SliceChannel Softmax SoftmaxActivation SoftmaxOutput
SpatialTransformer SwapAxis UpSampling _contrib_CTCLoss
_contrib_DeformableConvolution _contrib_DeformablePSROIPooling
_contrib_MultiBoxDetection _contrib_MultiBoxPrior _contrib_MultiBoxTarget
_contrib_MultiProposal _contrib_PSROIPooling _contrib_Proposal
_contrib_count_sketch _contrib_fft _contrib_ifft""".split()

FAMILIES = """relu sigmoid _copy BlockGrad make_loss
_identity_with_attr_like_rhs Cast negative reciprocal abs sign round rint
ceil floor trunc fix square sqrt rsqrt cbrt rcbrt exp log log10 log2
log1p expm1 sin cos tan arcsin arccos arctan degrees radians sinh cosh
tanh arcsinh arccosh arctanh gamma gammaln
elemwise_add _grad_add elemwise_sub elemwise_mul elemwise_div _mod _hypot
_maximum _minimum _power _equal _not_equal _greater _greater_equal
_lesser _lesser_equal add_n
_plus_scalar _minus_scalar _rminus_scalar _mul_scalar _div_scalar
_rdiv_scalar _mod_scalar _rmod_scalar _maximum_scalar _minimum_scalar
_power_scalar _rpower_scalar _hypot_scalar smooth_l1
broadcast_add broadcast_sub broadcast_mul broadcast_div broadcast_mod
broadcast_power broadcast_maximum broadcast_minimum broadcast_hypot
broadcast_equal broadcast_not_equal broadcast_greater
broadcast_greater_equal broadcast_lesser broadcast_lesser_equal
sum mean prod nansum nanprod max min norm argmax argmin argmax_channel
pick broadcast_axis broadcast_to
softmax log_softmax softmax_cross_entropy
Reshape Flatten transpose expand_dims slice slice_axis _slice_assign
_crop_assign_scalar clip repeat tile reverse stack
Embedding take batch_take one_hot gather_nd scatter_nd
dot batch_dot topk sort argsort _zeros _ones _arange zeros_like
ones_like where
_linalg_gemm _linalg_gemm2 _linalg_potrf _linalg_potri _linalg_trmm
_linalg_trsm _linalg_sumlogdiag _linalg_syrk _linalg_gelqf
cast_storage _sparse_retain _square_sum
_random_uniform _random_normal _random_gamma _random_exponential
_random_poisson _random_negative_binomial
_random_generalized_negative_binomial
sample_uniform sample_normal sample_gamma sample_exponential
sample_poisson sample_negative_binomial
sample_generalized_negative_binomial sample_multinomial
sgd_update sgd_mom_update mp_sgd_update mp_sgd_mom_update adam_update
rmsprop_update rmspropalex_update ftrl_update
_cvimread _cvimdecode _cvimresize _cvcopyMakeBorder
Custom _NoGradient _contrib_quantize _contrib_dequantize""".split()


def test_op_census_complete():
    have = set(registry.list_ops())
    missing = [name for name in LEGACY + FAMILIES
               if name not in have and not hasattr(nd, name)]
    assert not missing, "census ops missing: %s" % missing
