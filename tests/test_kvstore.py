"""KVStore exact-value invariants (model: reference
tests/python/unittest/test_kvstore.py + tests/nightly/dist_sync_kvstore.py
:28-60 — after push from n sources, pulled value equals n * expected)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import kv, nd

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def test_single_kv_pair():
    store = kv.create("local")
    store.init(3, nd.ones(SHAPE))
    out = nd.zeros(SHAPE)
    store.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 1)
    store.push(3, nd.ones(SHAPE) * 4)
    store.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 4)


def test_aggregation():
    """Push a list (one per 'device') -> values are summed."""
    store = kv.create("local")
    store.init(3, nd.ones(SHAPE))
    num_devs = 4
    devs = [mx.cpu(i % 2) for i in range(num_devs)]
    vals = [nd.ones(SHAPE, ctx=d) for d in devs]
    store.push(3, vals)
    out = nd.zeros(SHAPE)
    store.pull(3, out=out)
    assert np.allclose(out.asnumpy(), num_devs)


def test_list_kv_pairs():
    store = kv.create("local")
    store.init(KEYS, [nd.ones(SHAPE)] * len(KEYS))
    store.push(KEYS, [nd.ones(SHAPE) * 2] * len(KEYS))
    outs = [nd.zeros(SHAPE) for _ in KEYS]
    store.pull(KEYS, out=outs)
    for o in outs:
        assert np.allclose(o.asnumpy(), 2)


def test_updater():
    store = kv.create("local")
    store.init(3, nd.ones(SHAPE))

    def updater(key, recv, stored):
        stored += recv * 2

    store.set_updater(updater)
    store.push(3, nd.ones(SHAPE))
    out = nd.zeros(SHAPE)
    store.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 3)  # 1 + 2*1
    # aggregated push then updater
    store.push(3, [nd.ones(SHAPE)] * 4)
    store.pull(3, out=out)
    assert np.allclose(out.asnumpy(), 11)  # 3 + 2*4


def test_optimizer_on_kvstore():
    """update_on_kvstore semantics: push grad, pull updated weight."""
    store = kv.create("local")
    opt = mx.optimizer.SGD(learning_rate=0.1, rescale_grad=1.0)
    store.set_optimizer(opt)
    w = nd.ones(SHAPE)
    store.init(0, w)
    g = nd.ones(SHAPE)
    store.push(0, g)
    out = nd.zeros(SHAPE)
    store.pull(0, out=out)
    assert np.allclose(out.asnumpy(), 1 - 0.1)


def test_kvstore_types_and_rank():
    for name in ("local", "device", "dist_sync", "dist_async"):
        store = kv.create(name)
        assert store.type == name
    store = kv.create("local")
    assert store.rank == 0
    assert store.num_workers == 1
    with pytest.raises(mx.MXNetError):
        kv.create("unknown_type")


def test_row_sparse_pull():
    store = kv.create("local")
    store.init("emb", nd.array(np.arange(12).reshape(4, 3).astype("f4")))
    out = nd.zeros((4, 3))
    store.row_sparse_pull("emb", out=out,
                          row_ids=nd.array(np.array([0., 2.])))
    assert out.shape == (4, 3)
