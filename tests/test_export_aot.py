"""AOT export: a trained model serializes to a self-contained StableHLO
artifact that a PYTHON-FREE-of-mxtpu process (bare jax) runs bit-for-bit.
Role parity: amalgamation's standalone libmxnet_predict
(amalgamation/README.md) — deployment without the framework."""
import os
import subprocess
import sys
import textwrap

import numpy as np

import mxtpu as mx
from mxtpu import export as mxa

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_small():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    y = (X[:, 0] > 0).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer_params={"learning_rate": 0.3})
    args, aux = mod.get_params()
    return net, args, aux, X


def test_export_roundtrip_in_process(tmp_path):
    net, args, aux, X = _train_small()
    path = str(tmp_path / "model.mxa")
    mxa.export_serving(net, args, aux, {"data": (4, 8)}, path)

    # reference output through the framework
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))], for_training=False)
    mod.set_params(args, aux, allow_missing=True)
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(X[:4])], label=None),
                is_train=False)
    want = mod.get_outputs()[0].asnumpy()

    fn, meta = mxa.load_serving(path)
    got = np.asarray(fn(X[:4])[0])
    assert meta["inputs"][0]["name"] == "data"
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_export_runs_without_mxtpu(tmp_path):
    """The artifact must execute in a subprocess that never imports mxtpu
    (bare jax), proving framework-free deployment."""
    net, args, aux, X = _train_small()
    path = str(tmp_path / "model.mxa")
    mxa.export_serving(net, args, aux, {"data": (4, 8)}, path)
    np.save(str(tmp_path / "x.npy"), X[:4])

    script = textwrap.dedent("""
        import json, struct, sys
        import numpy as np
        sys.modules['mxtpu'] = None  # poison: importing mxtpu must fail
        import jax
        import jax.export  # explicit: plain `import jax` skips it on <0.5
        path, xpath = sys.argv[1], sys.argv[2]
        with open(path, 'rb') as f:
            assert f.read(8) == b'MXTPUAOT'
            _, hlen = struct.unpack('<II', f.read(8))
            meta = json.loads(f.read(hlen).decode())
            payload = f.read()
        exported = jax.export.deserialize(payload)
        x = np.load(xpath)
        out = exported.call(jax.numpy.asarray(x))
        probs = np.asarray(out[0])
        assert probs.shape == (4, 2), probs.shape
        assert np.allclose(probs.sum(axis=1), 1.0, atol=1e-4)
        print('BARE_JAX_OK', float(probs[0, 0]))
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTHONPATH", None)  # no repo on path: mxtpu unavailable
    r = subprocess.run([sys.executable, "-c", script, path,
                        str(tmp_path / "x.npy")],
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "BARE_JAX_OK" in r.stdout
