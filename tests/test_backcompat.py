"""Serialization back-compat gate: committed v1 golden artifacts must keep
loading bit-for-bit (model: the reference's versioned fixtures —
tests/python/unittest/legacy_ndarray.v0, save_000800.json loaded in
test_module.py). Any format change must remain able to READ these."""
import os

import numpy as np

import mxtpu as mx

HERE = os.path.dirname(os.path.abspath(__file__))
FIX = os.path.join(HERE, "fixtures")


def test_ndarray_v1_fixture_loads():
    loaded = mx.nd.load(os.path.join(FIX, "ndarray_v1.params"))
    want = np.load(os.path.join(FIX, "ndarray_v1_expected.npz"))
    assert set(loaded) == set(want.files)
    for k in want.files:
        got = loaded[k].asnumpy()
        np.testing.assert_array_equal(got, want[k])
        assert str(loaded[k].dtype) == str(want[k].dtype)


def test_module_v1_checkpoint_loads_and_predicts():
    prefix = os.path.join(FIX, "module_v1")
    sym, args, aux = mx.model.load_checkpoint(prefix, 1)
    assert "fc_weight" in args
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 3))], for_training=False)
    mod.set_params(args, aux, allow_missing=True)
    x = np.load(os.path.join(FIX, "module_v1_input.npy"))
    want = np.load(os.path.join(FIX, "module_v1_expected.npy"))
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x)], label=None),
                is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(), want,
                               rtol=1e-6)


def test_module_load_api_on_v1_checkpoint():
    mod = mx.mod.Module.load(os.path.join(FIX, "module_v1"), 1)
    mod.bind(data_shapes=[("data", (2, 3))], for_training=False)
    x = np.load(os.path.join(FIX, "module_v1_input.npy"))
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x)], label=None),
                is_train=False)
    want = np.load(os.path.join(FIX, "module_v1_expected.npy"))
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(), want,
                               rtol=1e-6)
