"""Sparse NDArray tests (model: reference tests/python/unittest/
test_sparse_ndarray.py + test_sparse_operator.py)."""
import os
import tempfile

import numpy as np

import mxtpu as mx
from mxtpu import nd


def _rand_csr(shape, density=0.3):
    dense = (np.random.uniform(0, 1, shape) < density) * \
        np.random.randn(*shape)
    return dense.astype("float32")


def test_csr_roundtrip():
    dense = _rand_csr((5, 8))
    csr = nd.sparse.csr_matrix(dense)
    assert csr.stype == "csr"
    assert csr.shape == (5, 8)
    assert np.allclose(csr.asnumpy(), dense)
    back = csr.tostype("default")
    assert back.stype == "default"
    assert np.allclose(back.asnumpy(), dense)


def test_csr_components():
    data = np.array([1, 2, 3], dtype="float32")
    indices = np.array([0, 2, 1])
    indptr = np.array([0, 2, 2, 3])
    csr = nd.sparse.csr_matrix((data, indices, indptr), shape=(3, 3))
    ref = np.array([[1, 0, 2], [0, 0, 0], [0, 3, 0]], dtype="float32")
    assert np.allclose(csr.asnumpy(), ref)
    assert np.allclose(csr.data.asnumpy(), data)
    assert np.allclose(csr.indices.asnumpy(), indices)
    assert np.allclose(csr.indptr.asnumpy(), indptr)
    assert csr.nnz == 3


def test_row_sparse_roundtrip():
    dense = np.zeros((6, 3), dtype="float32")
    dense[1] = 1.0
    dense[4] = 2.0
    rsp = nd.sparse.row_sparse_array(dense)
    assert rsp.stype == "row_sparse"
    assert np.allclose(rsp.indices.asnumpy(), [1, 4])
    assert np.allclose(rsp.asnumpy(), dense)


def test_cast_storage():
    dense = _rand_csr((4, 5))
    x = nd.array(dense)
    csr = nd.cast_storage(x, "csr")
    assert csr.stype == "csr"
    rsp = nd.cast_storage(x, "row_sparse")
    assert rsp.stype == "row_sparse"
    assert np.allclose(csr.asnumpy(), dense)
    assert np.allclose(rsp.asnumpy(), dense)
    d2 = nd.cast_storage(csr, "default")
    assert np.allclose(d2.asnumpy(), dense)


def test_sparse_dot():
    np.random.seed(0)
    a = _rand_csr((4, 6))
    b = np.random.randn(6, 3).astype("float32")
    csr = nd.sparse.csr_matrix(a)
    out = nd.dot(csr, nd.array(b))
    assert out.stype == "default"
    assert np.allclose(out.asnumpy(), a @ b, atol=1e-5)
    # transpose_a -> row_sparse output (embedding-grad path)
    outT = nd.dot(csr, nd.array(np.random.randn(4, 3).astype("float32")),
                  transpose_a=True)
    assert outT.stype == "row_sparse"


def test_sparse_retain():
    dense = np.zeros((6, 2), dtype="float32")
    dense[1] = 1
    dense[3] = 3
    dense[5] = 5
    rsp = nd.sparse.row_sparse_array(dense)
    kept = nd.sparse_retain(rsp, nd.array(np.array([1, 5])))
    ref = dense.copy()
    ref[3] = 0
    assert np.allclose(kept.asnumpy(), ref)


def test_sparse_add():
    d1 = np.zeros((5, 2), dtype="float32")
    d1[0] = 1
    d2 = np.zeros((5, 2), dtype="float32")
    d2[0] = 2
    d2[3] = 3
    r = nd.elemwise_add(nd.sparse.row_sparse_array(d1),
                        nd.sparse.row_sparse_array(d2))
    assert r.stype == "row_sparse"
    assert np.allclose(r.asnumpy(), d1 + d2)


def test_sparse_zeros():
    z = nd.sparse.zeros("csr", (3, 4))
    assert z.stype == "csr" and z.asnumpy().sum() == 0
    z2 = nd.sparse.zeros("row_sparse", (3, 4))
    assert z2.stype == "row_sparse" and z2.asnumpy().sum() == 0


def test_storage_fallback_dense_op():
    # any dense op on sparse input densifies transparently (reference
    # executor storage fallback)
    dense = _rand_csr((3, 4))
    csr = nd.sparse.csr_matrix(dense)
    out = nd.relu(csr)
    assert np.allclose(out.asnumpy(), np.maximum(dense, 0), atol=1e-6)


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    w = np.random.randn(8, 4).astype("float32")
    kv.init("emb", nd.array(w))
    out = nd.sparse.zeros("row_sparse", (8, 4))
    kv.row_sparse_pull("emb", out=out, row_ids=nd.array(np.array([2, 5])))
    assert sorted(out.indices.asnumpy().tolist()) == [2, 5]
    assert np.allclose(out.asnumpy()[2], w[2], atol=1e-6)
    assert np.allclose(out.asnumpy()[0], 0)


def test_libsvm_iter_csr():
    content = "1 0:1.5 3:2.0\n0 1:1.0\n1 2:0.5 3:1.0\n"
    with tempfile.NamedTemporaryFile("w", suffix=".libsvm",
                                     delete=False) as f:
        f.write(content)
        path = f.name
    try:
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                              batch_size=2)
        batches = list(it)
        assert len(batches) == 2
        b0 = batches[0]
        assert b0.data[0].stype == "csr"
        ref0 = np.array([[1.5, 0, 0, 2.0], [0, 1.0, 0, 0]], dtype="float32")
        assert np.allclose(b0.data[0].asnumpy(), ref0)
        assert batches[1].pad == 1
    finally:
        os.unlink(path)


def test_sparse_dot_transpose_b():
    np.random.seed(1)
    a = _rand_csr((4, 6))
    b = np.random.randn(3, 6).astype("float32")
    out = nd.dot(nd.sparse.csr_matrix(a), nd.array(b), transpose_b=True)
    assert np.allclose(out.asnumpy(), a @ b.T, atol=1e-5)


def test_sparse_add_csr_keeps_csr():
    a = _rand_csr((4, 5))
    b = _rand_csr((4, 5))
    out = nd.elemwise_add(nd.sparse.csr_matrix(a), nd.sparse.csr_matrix(b))
    assert out.stype == "csr"
    assert np.allclose(out.asnumpy(), a + b, atol=1e-6)


def test_libsvm_iter_tiny_dataset_pad():
    import tempfile
    content = "1 0:1.0\n0 1:2.0\n1 2:3.0\n"
    with tempfile.NamedTemporaryFile("w", suffix=".libsvm",
                                     delete=False) as f:
        f.write(content)
        path = f.name
    try:
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                              batch_size=8)
        batches = list(it)
        assert len(batches) == 1
        assert batches[0].pad == 5
        assert batches[0].data[0].shape == (8, 4)
    finally:
        os.unlink(path)


def test_sparse_save_load_dense_interop():
    # sparse arrays serialize through their dense view for checkpoint parity
    dense = _rand_csr((3, 3))
    csr = nd.sparse.csr_matrix(dense)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "x.params")
        nd.save(path, {"w": csr.todense()})
        back = nd.load(path)["w"]
        assert np.allclose(back.asnumpy(), dense)


def test_csr_negative_and_step_slices():
    import numpy as np
    import pytest
    from mxtpu.ndarray import sparse as sp
    from mxtpu.base import MXNetError

    dense = np.zeros((6, 4), np.float32)
    dense[0, 1] = 1; dense[2, 3] = 2; dense[5, 0] = 3
    csr = sp.csr_matrix(dense)
    np.testing.assert_allclose(csr[:-1].asnumpy(), dense[:-1])
    np.testing.assert_allclose(csr[-3:].asnumpy(), dense[-3:])
    np.testing.assert_allclose(csr[2:2].asnumpy(), dense[2:2])
    with pytest.raises(MXNetError):
        csr[0:6:2]


def test_sparse_dense_write_resyncs_components():
    import numpy as np
    import jax.numpy as jnp
    from mxtpu.ndarray import sparse as sp

    dense = np.zeros((4, 3), np.float32)
    dense[1, 2] = 5.0
    csr = sp.csr_matrix(dense)
    new = np.zeros((4, 3), np.float32)
    new[0, 0] = 7.0
    csr._data = jnp.asarray(new)  # dense write (kvstore pull path)
    assert csr.nnz == 1
    np.testing.assert_allclose(np.asarray(csr.data.asnumpy()), [7.0])
    np.testing.assert_allclose(csr.asnumpy(), new)

    rsp = sp.row_sparse_array(dense)
    rsp._data = jnp.asarray(new)
    np.testing.assert_allclose(np.asarray(rsp.indices.asnumpy()), [0])
    np.testing.assert_allclose(rsp.asnumpy(), new)
