"""Caffe converter (tools/caffe_converter.py): prototxt parsing, wire-format
weight extraction, symbol building, and a numeric end-to-end check against
a hand-computed conv+fc forward. Role parity: the reference's
tools/caffe_converter test_converter.py flow, offline."""
import os
import struct
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import caffe_converter as cc  # noqa: E402

PROTOTXT = """
name: "TinyNet"
input: "data"
input_dim: 1
input_dim: 2
input_dim: 6
input_dim: 6
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 3 kernel_size: 3 stride: 1 pad: 1 }
}
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer {
  name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "fc1" type: "InnerProduct" bottom: "pool1" top: "fc1"
  inner_product_param { num_output: 4 }
}
layer { name: "prob" type: "Softmax" bottom: "fc1" top: "prob" }
"""


# -- minimal protobuf wire ENCODER (test-side) ------------------------------
def _varint(v):
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _field(num, wire, payload):
    return _varint(num << 3 | wire) + payload


def _ld(num, payload):
    return _field(num, 2, _varint(len(payload)) + payload)


def _blob(arr):
    arr = np.asarray(arr, "<f4")
    shape = b"".join(_varint(int(d)) for d in arr.shape)
    return _ld(7, _ld(1, shape)) + _ld(5, arr.tobytes())


def _layer(name, blobs):
    body = _ld(1, name.encode())
    for b in blobs:
        body += _ld(7, _blob(b))
    return _ld(100, body)


def test_prototxt_parser():
    net = cc.parse_prototxt(PROTOTXT)
    assert net["name"] == "TinyNet"
    assert net["input_dim"] == [1, 2, 6, 6]
    layers = net["layer"]
    assert [l["type"] for l in layers] == [
        "Convolution", "ReLU", "Pooling", "InnerProduct", "Softmax"]
    assert layers[0]["convolution_param"]["num_output"] == 3


def test_convert_and_run(tmp_path):
    import mxtpu as mx

    rng = np.random.RandomState(0)
    w_conv = rng.randn(3, 2, 3, 3).astype("float32") * 0.3
    b_conv = rng.randn(3).astype("float32") * 0.1
    w_fc = rng.randn(4, 3 * 3 * 3).astype("float32") * 0.2
    b_fc = rng.randn(4).astype("float32") * 0.1

    model = (_layer("conv1", [w_conv, b_conv]) +
             _layer("fc1", [w_fc, b_fc]))
    mpath = str(tmp_path / "net.caffemodel")
    open(mpath, "wb").write(model)

    sym, args, aux = cc.convert_model(PROTOTXT, mpath)
    assert set(args) == {"conv1_weight", "conv1_bias", "fc1_weight",
                        "fc1_bias"}
    np.testing.assert_array_equal(args["conv1_weight"].asnumpy(), w_conv)

    x = rng.randn(1, 2, 6, 6).astype("float32")
    mod = mx.mod.Module(sym, context=mx.cpu(),
                        label_names=[n for n in sym.list_arguments()
                                     if n.endswith("label")] or None)
    mod.bind(data_shapes=[("data", (1, 2, 6, 6))], for_training=False)
    mod.set_params(args, aux, allow_missing=True)
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x)], label=None),
                is_train=False)
    got = mod.get_outputs()[0].asnumpy()

    # numpy oracle: conv(pad1) -> relu -> maxpool2 -> fc -> softmax
    from numpy.lib.stride_tricks import sliding_window_view
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    win = sliding_window_view(xp[0], (3, 3), axis=(1, 2))  # (2, 6, 6, 3, 3)
    conv = np.einsum("chwij,ocij->ohw", win, w_conv) + b_conv[:, None, None]
    relu = np.maximum(conv, 0)
    pool = relu.reshape(3, 3, 2, 3, 2).max(axis=(2, 4))
    fc = w_fc @ pool.reshape(-1) + b_fc
    e = np.exp(fc - fc.max())
    want = (e / e.sum())[None]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_batchnorm_scale_folding(tmp_path):
    proto = """
    input: "data"
    input_dim: 1
    input_dim: 2
    input_dim: 4
    input_dim: 4
    layer { name: "bn" type: "BatchNorm" bottom: "data" top: "bn" }
    layer { name: "sc" type: "Scale" bottom: "bn" top: "bn"
            scale_param { bias_term: true } }
    layer { name: "relu" type: "ReLU" bottom: "bn" top: "out" }
    """
    mean = np.array([0.5, -0.5], "float32")
    var = np.array([4.0, 1.0], "float32")
    factor = np.array([2.0], "float32")  # caffe stores scaled stats
    gamma = np.array([1.5, 0.5], "float32")
    beta = np.array([0.1, -0.1], "float32")
    model = (_layer("bn", [mean * 2, var * 2, factor]) +
             _layer("sc", [gamma, beta]))
    mpath = str(tmp_path / "bn.caffemodel")
    open(mpath, "wb").write(model)

    sym, args, aux = cc.convert_model(proto, mpath)
    np.testing.assert_allclose(aux["bn_moving_mean"].asnumpy(), mean)
    np.testing.assert_allclose(aux["bn_moving_var"].asnumpy(), var)
    np.testing.assert_allclose(args["bn_gamma"].asnumpy(), gamma)
    np.testing.assert_allclose(args["bn_beta"].asnumpy(), beta)
