"""R-package: stock-R (`dyn.load` + `.C`) binding over the C training ABI
trains an MLP from pure R — the reference's R-package tier
(R-package/R/ over include/mxnet/c_api.h) on this runtime.

The adapter (R-package/src/mxtpu_r.c) compiles with plain gcc, so the
build is exercised even without R; the R-driven training gate runs only
where Rscript exists."""
import os
import shutil
import subprocess

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R_SO = os.path.join(REPO, "mxtpu", "native", "libmxtpu_r.so")


def test_r_adapter_builds():
    r = subprocess.run(["make", "-C", os.path.join(REPO, "src"), "r"],
                       capture_output=True, text=True)
    assert os.path.exists(R_SO), r.stdout + r.stderr


def test_r_adapter_entry_points(tmp_path):
    """Drive the .C-shaped shims exactly as R's .C would (all-pointer
    args, integer handle ids) — validates the adapter without an R
    installation."""
    import ctypes

    subprocess.run(["make", "-C", os.path.join(REPO, "src"), "r"],
                   capture_output=True, text=True)
    if not os.path.exists(R_SO):
        pytest.skip("libmxtpu_r.so did not build")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PYTHONPATH"] = REPO
    lib = ctypes.CDLL(R_SO)
    i32 = ctypes.c_int

    def ip(v):
        return ctypes.byref(i32(v))

    # ndarray create -> set -> get roundtrip
    out_id, rc = i32(0), i32(-1)
    shape = (i32 * 2)(2, 3)
    lib.mx_r_ndarray_create(shape, ip(2), ip(0), ip(1), ip(0),
                            ctypes.byref(out_id), ctypes.byref(rc))
    assert rc.value == 0
    vals = (ctypes.c_double * 6)(*range(6))
    lib.mx_r_ndarray_set(ctypes.byref(out_id), vals, ip(6), ctypes.byref(rc))
    assert rc.value == 0
    got = (ctypes.c_double * 6)()
    lib.mx_r_ndarray_get(ctypes.byref(out_id), got, ip(6), ctypes.byref(rc))
    assert rc.value == 0 and list(got) == [0, 1, 2, 3, 4, 5]
    ndim, shp = i32(0), (i32 * 32)()
    lib.mx_r_ndarray_shape(ctypes.byref(out_id), ctypes.byref(ndim), shp,
                           ctypes.byref(rc))
    assert rc.value == 0 and list(shp[:ndim.value]) == [2, 3]

    # symbol json -> list arguments (the '\n'-joined contract R parses)
    import mxtpu as mx
    s = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fc")
    sym_id = i32(0)
    js = ctypes.c_char_p(s.tojson().encode())
    lib.mx_r_symbol_from_json(ctypes.byref(js), ctypes.byref(sym_id),
                              ctypes.byref(rc))
    assert rc.value == 0
    buf = ctypes.create_string_buffer(8192)
    pbuf = ctypes.c_char_p(ctypes.addressof(buf))
    lib.mx_r_symbol_list(ctypes.byref(sym_id), ip(0), ctypes.byref(pbuf),
                         ctypes.byref(rc))
    assert rc.value == 0
    names = buf.value.decode().split("\n")
    assert names == ["data", "fc_weight", "fc_bias"], names


@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="Rscript unavailable")
def test_r_binding_trains_mlp(tmp_path):
    subprocess.run(["make", "-C", os.path.join(REPO, "src"), "r"],
                   capture_output=True, text=True)
    if not os.path.exists(R_SO):
        pytest.skip("libmxtpu_r.so did not build")

    import mxtpu as mx

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    net.save(str(tmp_path / "mlp.json"))
    rng = np.random.RandomState(0)
    n, dim, classes = 256, 16, 4
    centers = rng.randn(classes, dim) * 3
    y = rng.randint(0, classes, n)
    X = (centers[y] + rng.randn(n, dim)).astype("float32")
    (tmp_path / "data.bin").write_bytes(X.tobytes())
    (tmp_path / "labels.bin").write_bytes(y.astype("float32").tobytes())

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    out = subprocess.run(
        ["Rscript", os.path.join(REPO, "R-package", "tests", "train_mlp.R"),
         os.path.dirname(R_SO), str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=600)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "R BINDING OK" in out.stdout, out.stdout + out.stderr


def test_r_compose_entry_points():
    """The atomic/compose adapter entries the generated R op wrappers
    (R-package/R/ops.R) sit on: build an MLP symbol exactly as
    mx.symbol.create does from R (.C all-pointer shapes), then bind and
    step it."""
    import ctypes

    subprocess.run(["make", "-C", os.path.join(REPO, "src"), "r"],
                   capture_output=True, text=True)
    if not os.path.exists(R_SO):
        pytest.skip("libmxtpu_r.so did not build")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PYTHONPATH"] = REPO
    lib = ctypes.CDLL(R_SO)
    i32 = ctypes.c_int

    def ip(v):
        return ctypes.byref(i32(v))

    def strv(*ss):
        arr = (ctypes.c_char_p * max(len(ss), 1))()
        for i, s in enumerate(ss):
            arr[i] = s.encode()
        return arr

    def intv(*vs):
        arr = (i32 * max(len(vs), 1))()
        for i, v in enumerate(vs):
            arr[i] = v
        return arr

    out_id, rc = i32(0), i32(0)
    lib.mx_r_symbol_variable(strv("data"), ctypes.byref(out_id),
                             ctypes.byref(rc))
    assert rc.value == 0
    data_id = out_id.value

    # FullyConnected(data, num_hidden=8) -> SoftmaxOutput
    lib.mx_r_symbol_atomic(strv("FullyConnected"), ip(1),
                           strv("num_hidden"), strv("8"),
                           ctypes.byref(out_id), ctypes.byref(rc))
    assert rc.value == 0, "atomic FC failed"
    fc_id = out_id.value
    lib.mx_r_symbol_compose(ip(fc_id), strv("fc1"), ip(1), strv("data"),
                            intv(data_id), ctypes.byref(rc))
    assert rc.value == 0, "compose FC failed"

    lib.mx_r_symbol_atomic(strv("SoftmaxOutput"), ip(0), strv(), strv(),
                           ctypes.byref(out_id), ctypes.byref(rc))
    assert rc.value == 0
    sm_id = out_id.value
    lib.mx_r_symbol_compose(ip(sm_id), strv("softmax"), ip(1),
                            strv("data"), intv(fc_id), ctypes.byref(rc))
    assert rc.value == 0

    # arguments of the composed graph come back in order
    buf = ctypes.create_string_buffer(8192)
    pbuf = (ctypes.c_char_p * 1)(ctypes.cast(buf, ctypes.c_char_p))
    lib.mx_r_symbol_list(ip(sm_id), ip(0), pbuf, ctypes.byref(rc))
    assert rc.value == 0
    args = buf.value.decode().split("\n")
    assert args == ["data", "fc1_weight", "fc1_bias", "softmax_label"], args

    # bind + one forward step through the same executor shims R uses
    names = strv("data", "softmax_label")
    indptr = intv(0, 2, 3)
    dims = intv(4, 16, 4)
    lib.mx_r_executor_bind(ip(sm_id), ip(1), ip(0), strv("write"),
                           names, ip(2), indptr, dims,
                           ctypes.byref(out_id), ctypes.byref(rc))
    assert rc.value == 0, "bind failed"
    exec_id = out_id.value
    lib.mx_r_executor_forward(ip(exec_id), ip(1), ctypes.byref(rc))
    assert rc.value == 0
    lib.mx_r_executor_backward(ip(exec_id), ctypes.byref(rc))
    assert rc.value == 0


def test_r_op_surface_is_current():
    """Regenerating ops.R reproduces the committed file (restored
    afterwards so a stale surface keeps failing instead of self-healing
    on the second run)."""
    ops_r = os.path.join(REPO, "R-package", "R", "ops.R")
    before = open(ops_r).read()
    try:
        r = subprocess.run(
            ["python", os.path.join(REPO, "R-package", "gen_r_ops.py")],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        assert r.returncode == 0, r.stdout + r.stderr
        assert before == open(ops_r).read(), \
            "committed R op surface is stale — rerun R-package/gen_r_ops.py"
    finally:
        open(ops_r, "w").write(before)
