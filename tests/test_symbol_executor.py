"""Symbol composition / inference / executor tests (model: reference
tests/python/unittest/{test_symbol.py,test_executor.py,test_infer_shape.py})."""
import json

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, sym


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_compose_and_listing():
    net = _mlp()
    assert net.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(8, 10))
    shapes = dict(zip(net.list_arguments(), arg_shapes))
    assert shapes["fc1_weight"] == (16, 10)
    assert shapes["fc1_bias"] == (16,)
    assert shapes["fc2_weight"] == (4, 16)
    assert out_shapes == [(8, 4)]


def test_infer_shape_partial():
    data = sym.Variable("data")
    out = sym.FullyConnected(data, num_hidden=4, name="fc")
    arg_shapes, out_shapes, _ = out.infer_shape_partial()
    assert out_shapes is not None or arg_shapes is not None


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    parsed = json.loads(js)
    assert "nodes" in parsed and "heads" in parsed
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    # still executable after roundtrip
    ex = net2.simple_bind(ctx=mx.cpu(), data=(2, 5))
    ex.forward()


def test_get_internals():
    net = _mlp()
    internals = net.get_internals()
    outs = internals.list_outputs()
    assert "fc1_output" in outs
    fc1 = internals["fc1_output"]
    assert fc1.list_arguments() == ["data", "fc1_weight", "fc1_bias"]


def test_symbol_arithmetic():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b) * 2 - a / b
    ex = c.bind(mx.cpu(), {"a": nd.array(np.array([2.0, 4])),
                           "b": nd.array(np.array([1.0, 2]))})
    out = ex.forward()[0].asnumpy()
    assert np.allclose(out, [(2 + 1) * 2 - 2, (4 + 2) * 2 - 2])


def test_bind_forward_backward():
    net = _mlp()
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 6))
    for name in ("fc1_weight", "fc2_weight"):
        ex.arg_dict[name][:] = nd.array(
            np.random.randn(*ex.arg_dict[name].shape).astype("f4") * 0.1)
    ex.arg_dict["data"][:] = nd.array(np.random.randn(4, 6).astype("f4"))
    ex.arg_dict["softmax_label"][:] = nd.array(np.array([0., 1, 2, 3]))
    out = ex.forward(is_train=True)[0]
    assert out.shape == (4, 4)
    assert np.allclose(out.asnumpy().sum(axis=1), 1, atol=1e-5)
    ex.backward()
    assert float(np.abs(ex.grad_dict["fc1_weight"].asnumpy()).sum()) > 0


def test_grad_req_add_and_null():
    a = sym.Variable("a")
    out = sym.sum(a * a)
    ga = nd.zeros((3,))
    ex = out.bind(mx.cpu(), {"a": nd.array(np.array([1.0, 2, 3]))},
                  args_grad={"a": ga}, grad_req="add")
    ex.forward(is_train=True)
    ex.backward()
    ex.forward(is_train=True)
    ex.backward()
    assert np.allclose(ga.asnumpy(), 2 * np.array([1.0, 2, 3]) * 2)


def test_executor_reshape():
    net = _mlp()
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 6))
    ex2 = ex.reshape(data=(8, 6), softmax_label=(8,))
    ex2.forward()
    assert ex2.outputs[0].shape == (8, 4)
    # params shared
    assert ex2.arg_dict["fc1_weight"] is ex.arg_dict["fc1_weight"]


def test_shared_exec_memory():
    net = _mlp()
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 6))
    ex2 = net.simple_bind(ctx=mx.cpu(), data=(2, 6), shared_exec=ex)
    assert ex2.arg_dict["fc1_weight"] is ex.arg_dict["fc1_weight"]
    assert ex2.arg_dict["data"] is not ex.arg_dict["data"]


def test_multi_output_symbol():
    a = sym.Variable("a")
    s = sym.SliceChannel(a, num_outputs=3, axis=1, name="sc")
    assert len(s.list_outputs()) == 3
    ex = s.bind(mx.cpu(), {"a": nd.array(np.arange(6).reshape(2, 3)
                                         .astype("f4"))})
    outs = ex.forward()
    assert len(outs) == 3
    assert outs[0].shape == (2, 1)
    g = sym.Group([s[0], s[2]])
    assert len(g.list_outputs()) == 2


def test_eval_api():
    a = sym.Variable("a")
    out = (a * 2).eval(ctx=mx.cpu(), a=nd.ones((2, 2)))
    assert np.allclose(out[0].asnumpy(), 2)


def test_save_load_file(tmp_path):
    net = _mlp()
    path = str(tmp_path / "net.json")
    net.save(path)
    net2 = sym.load(path)
    assert net2.list_arguments() == net.list_arguments()


def test_attr_and_name():
    a = sym.Variable("a", lr_mult=2.0)
    assert a.attr("__lr_mult__") == "2.0"
    fc = sym.FullyConnected(a, num_hidden=3, name="myfc")
    assert fc.name == "myfc"


def test_attr_scope():
    import mxtpu as mx

    with mx.AttrScope(ctx_group="dev1", lr_mult="0.1"):
        a = mx.sym.Variable("a")
        fc = mx.sym.FullyConnected(a, num_hidden=4, name="fc_scoped")
    plain = mx.sym.FullyConnected(mx.sym.Variable("b"), num_hidden=4,
                                  name="fc_plain")
    assert fc.attr("__ctx_group__") == "dev1"
    assert fc.attr("__lr_mult__") == "0.1"
    assert plain.attr("__ctx_group__") is None
    # nesting: inner scope overrides, exits cleanly
    with mx.AttrScope(ctx_group="g0"):
        with mx.AttrScope(ctx_group="g1"):
            inner = mx.sym.FullyConnected(mx.sym.Variable("c"),
                                          num_hidden=2, name="fc_inner")
        outer = mx.sym.FullyConnected(mx.sym.Variable("d"),
                                      num_hidden=2, name="fc_outer")
    assert inner.attr("__ctx_group__") == "g1"
    assert outer.attr("__ctx_group__") == "g0"


def test_visualization_print_summary(capsys):
    """mx.viz.print_summary renders the layer table (parity test_viz.py)."""
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="c1")
    net = mx.sym.Activation(net, act_type="relu", name="r1")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mx.visualization.print_summary(net, shape={"data": (1, 3, 8, 8)})
    out = capsys.readouterr().out
    assert "c1" in out and "fc" in out
    assert "Total params" in out


def test_visualization_plot_network_graph():
    """plot_network emits a graphviz dot source naming every layer."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc_viz")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    dot = mx.visualization.plot_network(net, shape={"data": (1, 8)})
    src = getattr(dot, "source", None) or str(dot)
    assert "fc_viz" in src


def test_name_manager_prefix_scope():
    """mx.name.Prefix / NameManager context scoping (parity
    python/mxnet/name.py): auto-names inside the scope get the prefix and
    a fresh counter; the outer counter resumes after exit."""
    import mxtpu as mx
    a = mx.sym.FullyConnected(mx.sym.Variable("x"), num_hidden=4)
    with mx.name.Prefix("net_"):
        b = mx.sym.FullyConnected(mx.sym.Variable("x"), num_hidden=4)
        c = mx.sym.Activation(b, act_type="relu")
    d = mx.sym.FullyConnected(mx.sym.Variable("x"), num_hidden=4)
    na, nb, nc, ndm = (s.list_outputs()[0] for s in (a, b, c, d))
    assert nb.startswith("net_fullyconnected0")
    assert nc.startswith("net_activation0")
    assert not ndm.startswith("net_")
    # the outer manager's counter advanced past 'a', unaffected by scope
    assert ndm.split("_output")[0] != na.split("_output")[0]


def test_symbol_module_math():
    """sym.pow/maximum/minimum/hypot with symbol-or-scalar operands, and
    the reflected %/** dunders (parity symbol.py:2267-2446)."""
    import numpy as np
    x = mx.sym.Variable("x")

    def run(sym_out, xv):
        exe = sym_out.simple_bind(ctx=mx.cpu(), x=(len(xv),),
                                  grad_req="null")
        exe.arg_dict["x"][:] = mx.nd.array(np.asarray(xv, "float32"))
        return exe.forward()[0].asnumpy()

    np.testing.assert_allclose(run(mx.sym.pow(3, x), [2, 3]), [9, 27])
    np.testing.assert_allclose(run(mx.sym.maximum(x, 2.5), [2, 3]),
                               [2.5, 3])
    np.testing.assert_allclose(run(mx.sym.minimum(2.5, x), [2, 3]),
                               [2, 2.5])
    np.testing.assert_allclose(run(mx.sym.hypot(x, 4.0), [3, 0]), [5, 4])
    np.testing.assert_allclose(run(2 % x, [3, 5]), [2, 2])
    np.testing.assert_allclose(run(2 ** x, [2, 3]), [4, 8])
    assert mx.sym.pow(2, 3) == 8 and mx.sym.maximum(2, 5) == 5
    y = mx.sym.Variable("y")
    assert "hypot" in mx.sym.hypot(x, y).list_outputs()[0]


def test_list_attr():
    """Symbol.list_attr returns this node's attrs (parity list_attr);
    recursive=True is the reference's deprecated path and raises."""
    f = mx.sym.FullyConnected(mx.sym.Variable("x"), num_hidden=4, name="fc")
    assert f.list_attr()["num_hidden"] == "4"
    v = mx.sym.Variable("w", lr_mult=2.0)
    assert v.list_attr()["__lr_mult__"] == "2.0"
    with pytest.raises(mx.base.MXNetError):
        f.list_attr(recursive=True)


def test_backward_out_grads_cached_vjp():
    """backward(out_grads) flips the executor into heads-mode: the first
    call replays forward+backward (no residuals were saved), every later
    forward runs the fwd_vjp program and backward applies the cached vjp
    closure without recomputing the forward (VERDICT r3 weak #6)."""
    a = sym.Variable("a")
    w = sym.Variable("w")
    out = sym.FullyConnected(a, weight=w, no_bias=True,
                             num_hidden=3, name="fc")
    aval = np.random.randn(2, 5).astype("f4")
    wval = np.random.randn(3, 5).astype("f4")
    ga, gw = nd.zeros((2, 5)), nd.zeros((3, 5))
    ex = out.bind(mx.cpu(), {"a": nd.array(aval), "w": nd.array(wval)},
                  args_grad={"a": ga, "w": gw})
    heads = nd.array(np.random.randn(2, 3).astype("f4"))

    ex.forward(is_train=True)
    ex.backward(out_grads=heads)            # recompute path, flips mode
    assert ex._heads_mode
    g1a, g1w = ga.asnumpy().copy(), gw.asnumpy().copy()

    ex.forward(is_train=True)
    assert ex._cached_vjp is not None       # vjp saved by the forward
    ex.backward(out_grads=heads)            # cached path, no fwd replay
    assert "fwd_vjp" in ex._fns and "vjp_apply" in ex._fns
    assert np.allclose(ga.asnumpy(), g1a, atol=1e-5)
    assert np.allclose(gw.asnumpy(), g1w, atol=1e-5)
    # analytic check: d(a@w.T)/da = heads @ w, d/dw = heads.T @ a
    assert np.allclose(ga.asnumpy(), heads.asnumpy() @ wval, atol=1e-4)
    assert np.allclose(gw.asnumpy(), heads.asnumpy().T @ aval, atol=1e-4)

    # heads-mode forward still supports implicit backward (ones cotangent)
    ex.forward(is_train=True)
    ex.backward()
    ones = np.ones((2, 3), "f4")
    assert np.allclose(ga.asnumpy(), ones @ wval, atol=1e-4)
