"""Compiler round 2: the transform CATALOG through the gated pipeline
seam — optimizer-update fusion (``fuse_opt``), conv layout selection
(``layout``), and liveness-driven remat/buffer-reuse (``remat_reuse``),
composed with the PR-7 ``bf16`` pass.

Acceptance gates (ISSUE 14):
* each transform shows a per-model win on its deterministic basis
  (fuse_opt: fewer update chains / bit-exact parity; layout: modeled
  byte-movement cut after boundary-conversion cost; remat_reuse:
  residual-peak-bytes cut from the liveness walk);
* the composed bf16+fuse_opt+layout+remat_reuse pipeline passes the
  PR-7 parity-gate convention on the mlp/lenet fixtures;
* composition order is canonical regardless of operator spelling, and
  every pass is individually rejectable-with-fallback — the remaining
  passes still apply and training completes.
"""
import logging

import numpy as np
import pytest

import mxtpu as mx
import mxtpu.symbol as S
from mxtpu import analysis
from mxtpu import diagnostics as diag
from mxtpu import telemetry as tel
from mxtpu.analysis import dataflow, rewrite
from mxtpu.compile import pipeline
from mxtpu.models import lenet, mlp


def _fit(symbol, names, n=256, batch=64, epochs=2, image=False, seed=7):
    rng = np.random.RandomState(0)
    X = rng.rand(n, 1, 28, 28).astype(np.float32) if image \
        else rng.rand(n, 784).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, n).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch,
                           label_name="softmax_label")
    mod = mx.mod.Module(symbol, context=mx.cpu(),
                        logger=logging.getLogger("quiet"))
    mod.logger.setLevel(logging.ERROR)
    metric = mx.metric.create(["acc", "ce"])
    with pipeline.pipeline_scope(names):
        mx.random.seed(seed)
        np.random.seed(seed)
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                eval_metric=metric)
    args, _ = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in args.items()}, \
        dict(zip(*metric.get()))


def _deep_mlp(classes=10, width=128, depth=4):
    """Equal-width FC stack: the fixture whose weights/biases form real
    dtype/shape classes for the update-fusion pass (mlp/lenet have none
    — every layer is a different shape)."""
    x = S.Variable("data")
    for i in range(depth):
        x = S.FullyConnected(x, num_hidden=width, name="dfc%d" % i)
        x = S.Activation(x, act_type="relu", name="drelu%d" % i)
    x = S.FullyConnected(x, num_hidden=classes, name="dout")
    return S.SoftmaxOutput(x, name="softmax")


def _lenet_hints(batch=64):
    sym = lenet.get_symbol(10)
    arg_shapes, _, _ = sym.infer_shape(data=(batch, 1, 28, 28),
                                       softmax_label=(batch,))
    return sym, dict(zip(sym.list_arguments(), arg_shapes))


# ------------------------------------------------------------ the catalog
def test_catalog_registers_all_passes():
    names = [n for n, _ in rewrite.list_transforms()]
    for want in ("bf16", "layout", "fuse_opt", "remat_reuse"):
        assert want in names, names


def test_canonical_order_normalizes_operator_spelling():
    # the ISSUE's spelling — and any other — sequences canonically
    assert pipeline.canonical_order(
        ["bf16", "fuse_opt", "layout", "remat_reuse"]) == \
        ("layout", "bf16", "fuse_opt", "remat_reuse")
    assert pipeline.canonical_order(
        ["remat_reuse", "layout"]) == ("layout", "remat_reuse")
    # non-catalog names keep their exact slots (test/experimental passes)
    assert pipeline.canonical_order(
        ["_probe", "remat_reuse", "bf16"]) == \
        ("_probe", "bf16", "remat_reuse")


def test_transform_graph_reports_canonical_passes():
    sym, hints = _lenet_hints()
    _sym2, rep = pipeline.transform_graph(
        sym, kind="test", shapes=hints,
        passes=["remat_reuse", "bf16", "layout"])
    assert rep.passes == ("layout", "bf16", "remat_reuse")


# ------------------------------------------------------------ conv layout
def test_conv_layout_analysis_finds_lenet_run():
    sym, hints = _lenet_hints()
    plan = dataflow.conv_layout(sym, shapes=hints)
    assert len(plan.runs) == 1
    run = plan.runs[0]
    assert run["applied"], plan.summary()
    # two convs + two poolings (pooling auto-names carry a global
    # counter, so match by prefix rather than exact index)
    assert {n for n in run["core"] if not n.startswith("pooling")} == \
        {"conv1", "conv2"}
    assert sum(n.startswith("pooling") for n in run["core"]) == 2
    # the deterministic decision basis: interior wrap savings beat the
    # boundary converts (the ISSUE's "net byte-movement cut")
    assert run["benefit_bytes"] > run["boundary_bytes"] > 0


def test_conv_layout_rejects_when_boundary_dominates():
    """A lone conv saves nothing: entry+exit converts equal the modeled
    wrap the backend would pay — the cost model must keep NCHW."""
    data = S.Variable("data")
    conv = S.Convolution(data, kernel=(3, 3), num_filter=8, name="c")
    plan = dataflow.conv_layout(S.Group([conv]),
                                shapes={"data": (4, 3, 16, 16)})
    assert len(plan.runs) == 1
    assert not plan.runs[0]["applied"]
    sym2, rep = pipeline.transform_graph(
        conv, kind="test", shapes={"data": (4, 3, 16, 16)},
        passes=["layout"])
    assert sym2 is conv and rep.applied == []


def test_layout_rewrite_structure_and_forward_parity():
    sym, hints = _lenet_hints(batch=8)
    sym2, rep = pipeline.transform_graph(sym, kind="test", shapes=hints,
                                         passes=["layout"])
    assert rep.applied == ["layout"] and rep.symbol_changed
    # arguments/aux unchanged — weights keep OIHW storage, bind dicts
    # and checkpoints still fit
    assert sym2.list_arguments() == sym.list_arguments()
    assert sym2.list_outputs() == sym.list_outputs()
    dbg = sym2.debug_str()
    assert "data_nhwc" in dbg          # run-entry convert
    assert "_nchw" in dbg              # run-exit convert
    # interior edges carry NO converts: exactly one each way
    assert dbg.count("_nhwc(") == 1 and dbg.count("_nchw(") == 1
    # conv/pool retargeted, and the transformed graph re-proves
    attrs = sym2.attr_dict()
    assert attrs["conv1"]["layout"] == "NHWC"
    pools = [k for k in attrs if k.startswith("pooling")
             and not k.endswith(("_nhwc", "_nchw"))]
    assert pools and all(attrs[p]["layout"] == "NHWC" for p in pools)
    assert not sym2.lint(shapes=hints).errors
    # forward parity: same params through both graphs, same outputs
    ex = sym.simple_bind(ctx=mx.cpu(), data=(8, 1, 28, 28),
                         grad_req="null")
    for name, arr in ex.arg_dict.items():
        if name != "data":
            arr[:] = np.random.RandomState(hash(name) % 997).rand(
                *arr.shape).astype(np.float32) * 0.1
    ex2 = sym2.bind(mx.cpu(), dict(ex.arg_dict), grad_req="null")
    x = np.random.RandomState(3).rand(8, 1, 28, 28).astype(np.float32)
    o1 = ex.forward(is_train=False, data=mx.nd.array(x))[0].asnumpy()
    o2 = ex2.forward(is_train=False, data=mx.nd.array(x))[0].asnumpy()
    np.testing.assert_allclose(o1, o2, atol=1e-5)


def test_layout_parity_gate_fit():
    """Training parity through the NHWC rewrite alone: same data/seed,
    f32 arithmetic both sides — metrics match to float tolerance and
    weights stay within the reduction-order envelope."""
    _, w0, v0 = _fit(lenet.get_symbol(10), [], epochs=1, image=True)
    mod, w1, v1 = _fit(lenet.get_symbol(10), ["layout"], epochs=1,
                       image=True)
    assert mod._fused.pipeline_report.applied == ["layout"]
    assert v0["accuracy"] == v1["accuracy"]
    assert abs(v0["cross-entropy"] - v1["cross-entropy"]) < 1e-3
    for k in w0:
        assert np.max(np.abs(w0[k] - w1[k])) < 1e-3, k


# ------------------------------------------------------ update fusion
def test_update_fusion_plan_groups_by_class():
    sym = _deep_mlp()
    shapes, _, _ = sym.infer_shape(data=(64, 784), softmax_label=(64,))
    hints = dict(zip(sym.list_arguments(), shapes))
    trainable = [n for n in sym.list_arguments()
                 if n not in ("data", "softmax_label")]
    # default bound (compile.fuse_opt_max_kb=32): only the SMALL
    # launch-bound class batches — the 64 KB weight matrices stay on
    # their bandwidth-bound per-parameter chains
    plan = dataflow.update_fusion_plan(sym, shapes=hints,
                                       trainable=trainable)
    assert set(plan.classes) == {"float32:128"}
    # raising the bound admits the weight-matrix class too
    plan = dataflow.update_fusion_plan(sym, shapes=hints,
                                       trainable=trainable,
                                       max_member_bytes=None)
    assert set(plan.classes) == {"float32:128", "float32:128x128"}
    assert plan.classes["float32:128x128"] == \
        ["dfc1_weight", "dfc2_weight", "dfc3_weight"]
    # mlp has no two same-shape trainables: the pass must skip, not force
    msym = mlp.get_symbol(10)
    ms, _, _ = msym.infer_shape(data=(64, 784), softmax_label=(64,))
    mplan = dataflow.update_fusion_plan(
        msym, shapes=dict(zip(msym.list_arguments(), ms)),
        trainable=[n for n in msym.list_arguments()
                   if n not in ("data", "softmax_label")])
    assert mplan.classes == {}


def test_fuse_opt_parity_is_bit_exact(monkeypatch):
    """THE fuse_opt gate: the batched update region computes the same
    elementwise arithmetic as the per-parameter chains — weights after
    a fit are IDENTICAL, while the step really batched both classes
    (the knob raised so the weight-matrix class batches too and the
    stacked arithmetic is covered for matrices, not just vectors)."""
    monkeypatch.setenv("MXTPU_FUSE_OPT_MAX_KB", "1024")
    _, w0, v0 = _fit(_deep_mlp(), [], epochs=1)
    mod, w1, v1 = _fit(_deep_mlp(), ["fuse_opt"], epochs=1)
    rep = mod._fused.pipeline_report
    assert rep.applied == ["fuse_opt"]
    assert [k for k, _ in mod._fused._update_groups] == \
        ["float32:128", "float32:128x128"]
    assert len(mod._fused._validated_update_groups()) == 2
    for k in w0:
        assert np.array_equal(w0[k], w1[k]), k
    assert v0 == v1


def test_fuse_opt_momentum_and_adam_parity():
    """The batched region must hold for stateful rules too (momentum
    buffers / Adam moments stack along the class axis)."""
    for opt, params in (("sgd", {"learning_rate": 0.05,
                                 "momentum": 0.9}),
                        ("adam", {"learning_rate": 0.01})):
        results = []
        for names in ([], ["fuse_opt"]):
            rng = np.random.RandomState(0)
            X = rng.rand(128, 784).astype(np.float32)
            y = np.random.RandomState(1).randint(0, 10, 128).astype(
                np.float32)
            it = mx.io.NDArrayIter(X, y, batch_size=64,
                                   label_name="softmax_label")
            mod = mx.mod.Module(_deep_mlp(), context=mx.cpu(),
                                logger=logging.getLogger("quiet"))
            mod.logger.setLevel(logging.ERROR)
            with pipeline.pipeline_scope(names):
                mx.random.seed(7)
                np.random.seed(7)
                mod.fit(it, num_epoch=1, optimizer=opt,
                        optimizer_params=params)
            args, _ = mod.get_params()
            results.append({k: v.asnumpy() for k, v in args.items()})
        for k in results[0]:
            assert np.array_equal(results[0][k], results[1][k]), \
                (opt, k)


def test_fuse_opt_invalid_group_falls_back_per_parameter():
    """An unsound annotation (two different-shape parameters claiming
    one class) must be re-proven away at build time: the step logs,
    keeps the per-parameter chains, and training completes."""
    sym = mlp.get_symbol(10)
    var_extra = {}
    for node in sym._topo():
        if node.is_variable and node.name in ("fc1_weight", "fc2_weight"):
            var_extra[id(node)] = {"__update_class__": "bogus:class"}
    bad = rewrite._annotate_clone(sym, var_extra=var_extra)
    mod, w, vals = _fit(bad, [], epochs=1)
    assert mod._fused._update_groups == \
        [("bogus:class", ["fc1_weight", "fc2_weight"])]
    assert mod._fused._validated_update_groups() == []
    assert np.isfinite(vals["cross-entropy"])


# ------------------------------------------------------ remat + reuse
def test_remat_plan_threshold_and_peak_cut():
    sym, hints = _lenet_hints()
    plan = dataflow.remat_reuse_plan(sym, shapes=hints, threshold=4.0)
    # cheap elementwise/pool residuals annotated, conv/FC kept
    # (activation/pooling auto-names carry a global counter)
    assert any(n.startswith("activation") for n in plan.remat_names)
    assert any(n.startswith("pooling") for n in plan.remat_names)
    assert "conv1" not in plan.remat_names
    assert "fc1" not in plan.remat_names
    # the deterministic basis: residual-peak bytes fall
    assert plan.residual_peak_after < plan.residual_peak_before
    assert plan.peak_cut_pct > 10.0
    # threshold 0 annotates nothing
    empty = dataflow.remat_reuse_plan(sym, shapes=hints, threshold=0.0)
    assert empty.remat == set()


def test_remat_reuse_pairs_on_repeated_blocks():
    """Same-shape activations of consecutive blocks: block N's entry
    dies before block N+2's is born — the plan must pair them."""
    sym = _deep_mlp(depth=6)
    shapes, _, _ = sym.infer_shape(data=(64, 784), softmax_label=(64,))
    hints = dict(zip(sym.list_arguments(), shapes))
    plan = dataflow.remat_reuse_plan(sym, shapes=hints, threshold=4.0)
    assert plan.reuse_pairs, plan.summary()
    assert plan.reuse_bytes > 0
    dead, new, nbytes = plan.reuse_pairs[0]
    assert dead != new and nbytes == 64 * 128 * 4


def test_remat_reuse_fit_applies_annotations_and_parity():
    _, w0, v0 = _fit(lenet.get_symbol(10), [], epochs=1, image=True)
    mod, w1, v1 = _fit(lenet.get_symbol(10), ["remat_reuse"], epochs=1,
                       image=True)
    rep = mod._fused.pipeline_report
    assert rep.applied == ["remat_reuse"]
    # the step really runs the drop-these-names checkpoint policy
    assert mod._fused._remat == "annotated"
    tagged = [n.name for n in mod._fused._graph_symbol._topo()
              if not n.is_variable and n._extra_attrs.get("__remat__")]
    assert tagged, "no __remat__ annotations on the step graph"
    # recompute is arithmetic-identical: metrics and weights match
    assert v0["accuracy"] == v1["accuracy"]
    assert abs(v0["cross-entropy"] - v1["cross-entropy"]) < 1e-5
    for k in w0:
        assert np.max(np.abs(w0[k] - w1[k])) < 1e-5, k
    # telemetry gauges carry the modeled bytes
    assert tel.registry().gauge("transform_remat_bytes").value > 0


def test_explicit_remat_mode_wins_over_annotations(monkeypatch):
    """An operator-pinned fit.remat=block must override the pass's
    annotations (explicit beats derived, like every knob)."""
    monkeypatch.setenv("MXTPU_REMAT", "block")
    mod, _, vals = _fit(lenet.get_symbol(10), ["remat_reuse"], epochs=1,
                        image=True)
    assert mod._fused._remat == "block"
    assert np.isfinite(vals["cross-entropy"])


def test_env_set_none_suppresses_annotations(monkeypatch):
    """MXTPU_REMAT=none (explicitly SET) pins no-remat: the pass's
    annotations stay on the graph but the step must NOT build the
    checkpoint policy — the operator's escape hatch from an
    annotation-driven slowdown without editing the pipeline list."""
    monkeypatch.setenv("MXTPU_REMAT", "none")
    mod, _, vals = _fit(lenet.get_symbol(10), ["remat_reuse"], epochs=1,
                        image=True)
    assert mod._fused._remat == "none"   # not "annotated"
    tagged = [n.name for n in mod._fused._graph_symbol._topo()
              if not n.is_variable and n._extra_attrs.get("__remat__")]
    assert tagged, "pass should still annotate; only the step ignores it"
    assert np.isfinite(vals["cross-entropy"])


# --------------------------------------------------- composed pipeline
@pytest.mark.parametrize("model,kw", [
    ("mlp", {}),
    ("lenet", {"image": True}),
])
def test_full_catalog_parity_gate(model, kw):
    """THE composed acceptance gate (PR-7 convention): the full
    bf16+fuse_opt+layout+remat_reuse pipeline vs the plain f32 fit on
    the same data/seed — integer metrics exact-or-gated at 2/256, ce
    within 1e-2, weights within the bf16 quantization envelope."""
    get = mlp.get_symbol if model == "mlp" else lenet.get_symbol
    _, w0, v0 = _fit(get(10), [], **kw)
    mod, w1, v1 = _fit(get(10),
                       ["bf16", "fuse_opt", "layout", "remat_reuse"],
                       **kw)
    rep = mod._fused.pipeline_report
    assert rep.passes == ("layout", "bf16", "fuse_opt", "remat_reuse")
    assert rep.rejected == []
    assert "bf16" in rep.applied and "remat_reuse" in rep.applied
    if model == "lenet":
        assert "layout" in rep.applied   # mlp has no conv run
    assert abs(v0["accuracy"] - v1["accuracy"]) <= 2 / 256.0, (v0, v1)
    assert abs(v0["cross-entropy"] - v1["cross-entropy"]) < 1e-2, \
        (v0, v1)
    for k in w0:
        assert np.max(np.abs(w0[k] - w1[k])) < 5e-3, k
    # per-transform ProgramRecord tags on the AOT row
    recs = diag.programs("fused_step")
    assert recs and recs[-1]["precision"] == "mixed_bf16"
    assert "remat_reuse" in recs[-1]["transforms"]
    # every transformed build ships with its equivalence certificate
    assert recs[-1]["cert"] == "ok"
    table = diag.program_table("fused_step")
    assert "xforms" in table.splitlines()[0]
    assert "cert" in table.splitlines()[0]
    # ... and the report certifies each applied pass individually
    for e in rep.entries:
        if e["applied"]:
            assert e["cert"] is not None and e["cert"].ok, e["name"]


def test_transform_counters_emitted():
    before_a = tel.registry().counter("transform_applied",
                                      labels={"pass": "bf16"}).value
    sym, hints = _lenet_hints()
    pipeline.transform_graph(sym, kind="test", shapes=hints,
                             passes=["bf16"])
    after_a = tel.registry().counter("transform_applied",
                                     labels={"pass": "bf16"}).value
    assert after_a == before_a + 1


# ------------------------------------------------------ rejection chain
class _BreakingPass(rewrite.TransformPass):
    """Unsound transform: duplicates the head under a colliding name —
    the name_collision verifier must reject it."""

    name = "_test_breaker"

    def run(self, tctx):
        from mxtpu.symbol.symbol import Symbol, _Node
        head, idx = tctx.symbol._outputs[0]
        clash = next(n for n in tctx.symbol._topo()
                     if not n.is_variable and n is not head)
        dup = _Node(head.op, clash.name, dict(head.attrs),
                    list(head.inputs))
        self.action(tctx, "duplicated head under colliding name")
        return Symbol([(dup, idx)])


def test_per_pass_rejection_rest_of_catalog_still_applies():
    """One rejected pass must not poison the composition: the passes
    around it still apply, and the fused fit trains to completion on
    the partially transformed graph."""
    rewrite._TRANSFORMS.setdefault("_test_breaker", _BreakingPass())
    try:
        before_r = tel.registry().counter(
            "transform_rejected", labels={"pass": "_test_breaker"}).value
        mod, w, vals = _fit(
            lenet.get_symbol(10),
            ["layout", "_test_breaker", "bf16", "remat_reuse"],
            epochs=1, image=True)
        rep = mod._fused.pipeline_report
        assert rep.rejected == ["_test_breaker"]
        assert rep.applied == ["layout", "bf16", "remat_reuse"]
        off = [e for e in rep.entries
               if e["name"] == "_test_breaker"][0]["offending"]
        assert off and off[0].pass_name == "name_collision"
        assert off[0].severity == analysis.ERROR
        assert np.isfinite(vals["cross-entropy"])
        after_r = tel.registry().counter(
            "transform_rejected", labels={"pass": "_test_breaker"}).value
        assert after_r == before_r + 1
    finally:
        rewrite._TRANSFORMS.pop("_test_breaker", None)


@pytest.mark.parametrize("broken", ["layout", "fuse_opt", "remat_reuse"])
def test_each_new_pass_individually_rejectable(broken, monkeypatch):
    """Force each catalog pass to emit an unsound graph and prove the
    gate rejects exactly it, falls back, and the rest still apply."""
    orig = rewrite._TRANSFORMS[broken]

    def bad_run(tctx, _orig=orig):
        out = type(orig).run(_orig, tctx)
        if out is None:
            # make the pass "apply" unsoundly even where it would skip
            out = tctx.symbol
        from mxtpu.symbol.symbol import Symbol, _Node
        head, idx = out._outputs[0]
        clash = next(n for n in out._topo()
                     if not n.is_variable and n is not head)
        dup = _Node(head.op, clash.name, dict(head.attrs),
                    list(head.inputs))
        return Symbol([(dup, idx)])

    monkeypatch.setattr(orig, "run", bad_run)
    sym, hints = _lenet_hints()
    sym2, rep = pipeline.transform_graph(
        sym, kind="test", shapes=hints,
        passes=["layout", "bf16", "fuse_opt", "remat_reuse"])
    assert rep.rejected == [broken]
    assert broken not in rep.applied
    assert "bf16" in rep.applied
    assert rep.symbol_changed     # the rest of the catalog still landed
