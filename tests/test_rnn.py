"""RNN stack tests: fused RNN op vs unfused cells, cells API, bucketing
training (mirrors tests/python/unittest/test_rnn.py + test_bucketing.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.ops.rnn import (rnn_pack_weights, rnn_param_size,
                           rnn_unpack_weights)


def test_rnn_param_size():
    # lstm: G=4; layer0: 4*H*(I+H) + 8H; layer1 input = H
    assert rnn_param_size(1, 10, 6, "lstm") == 4 * 6 * (10 + 6) + 8 * 6
    s1 = rnn_param_size(2, 10, 6, "gru", bidirectional=True)
    # layer0: 2 dirs * (3*6*(10+6) + 36); layer1 input = 12
    assert s1 == 2 * (3 * 6 * 16 + 36) + 2 * (3 * 6 * (12 + 6) + 36)


def test_rnn_pack_unpack_roundtrip():
    rng = np.random.RandomState(3)
    for mode in ("rnn_tanh", "lstm", "gru"):
        for bi in (False, True):
            n = rnn_param_size(2, 5, 4, mode, bi)
            flat = rng.randn(n).astype("float32")
            w = rnn_unpack_weights(flat, 2, 5, 4, mode, bi)
            flat2 = rnn_pack_weights(w, 2, 5, 4, mode, bi)
            assert np.allclose(flat, flat2)


def _np_lstm_ref(x, w, h0, c0, H):
    """Single-layer unidirectional LSTM in numpy (finite oracle)."""
    T, N, _ = x.shape
    wx, wh = w["l0_stack_wx"], w["l0_stack_wh"]
    bx, bh = w["l0_stack_bx"], w["l0_stack_bh"]
    h, c = h0.copy(), c0.copy()
    outs = []

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    for t in range(T):
        g = x[t] @ wx.T + bx + h @ wh.T + bh
        i, f, gg, o = np.split(g, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(gg)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    return np.stack(outs), h, c


def test_fused_lstm_matches_numpy():
    rng = np.random.RandomState(0)
    T, N, I, H = 4, 3, 5, 6
    ps = rnn_param_size(1, I, H, "lstm")
    flat = (rng.randn(ps) * 0.2).astype("float32")
    x = rng.randn(T, N, I).astype("float32")
    h0 = rng.randn(1, N, H).astype("float32")
    c0 = rng.randn(1, N, H).astype("float32")

    out = mx.nd.RNN(mx.nd.array(x), mx.nd.array(flat), mx.nd.array(h0),
                    mx.nd.array(c0), state_size=H, num_layers=1, mode="lstm",
                    state_outputs=True)
    # rebuild stacked weights from the unpacked per-gate dict
    w = rnn_unpack_weights(flat, 1, I, H, "lstm")
    wx = np.concatenate([w["l0_i2h_%s_weight" % g] for g in "ifco"])
    wh = np.concatenate([w["l0_h2h_%s_weight" % g] for g in "ifco"])
    bx = np.concatenate([w["l0_i2h_%s_bias" % g] for g in "ifco"])
    bh = np.concatenate([w["l0_h2h_%s_bias" % g] for g in "ifco"])
    ref_out, ref_h, ref_c = _np_lstm_ref(
        x, {"l0_stack_wx": wx, "l0_stack_wh": wh, "l0_stack_bx": bx,
            "l0_stack_bh": bh}, h0[0], c0[0], H)
    assert np.allclose(out[0].asnumpy(), ref_out, atol=1e-5)
    assert np.allclose(out[1].asnumpy()[0], ref_h, atol=1e-5)
    assert np.allclose(out[2].asnumpy()[0], ref_c, atol=1e-5)


@pytest.mark.parametrize("mode", ["rnn_relu", "rnn_tanh", "lstm", "gru"])
def test_fused_matches_unfused(mode):
    """FusedRNNCell.unroll == its unfuse()d SequentialRNNCell unroll."""
    rng = np.random.RandomState(1)
    T, N, I, H, L = 3, 2, 4, 5, 2
    fused = mx.rnn.FusedRNNCell(H, num_layers=L, mode=mode, prefix="f_")
    fo, _ = fused.unroll(T, mx.sym.Variable("data"), layout="NTC",
                         merge_outputs=True)
    stack = fused.unfuse()
    uo, _ = stack.unroll(T, mx.sym.Variable("data"), layout="NTC",
                         merge_outputs=True)

    ps = rnn_param_size(L, I, H, mode)
    flat = (rng.randn(ps) * 0.3).astype("float32")
    data = rng.randn(N, T, I).astype("float32")

    fex = fo.simple_bind(mx.cpu(), data=(N, T, I))
    fex.copy_params_from({"f_parameters": mx.nd.array(flat),
                          "data": mx.nd.array(data)})
    fex.forward(data=mx.nd.array(data))
    fused_out = fex.outputs[0].asnumpy()

    args = {"f_" + k: mx.nd.array(v) for k, v in rnn_unpack_weights(
        flat, L, I, H, mode).items()}
    args = stack.pack_weights(args)  # per-gate -> gate-stacked cell params
    uex = uo.simple_bind(mx.cpu(), data=(N, T, I))
    uex.copy_params_from(args, allow_extra_params=True)
    uex.forward(data=mx.nd.array(data))
    unfused_out = uex.outputs[0].asnumpy()
    assert np.allclose(fused_out, unfused_out, atol=1e-4), \
        "%s mismatch %g" % (mode, np.abs(fused_out - unfused_out).max())


def test_bidirectional_cell():
    T, N, I, H = 3, 2, 4, 5
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(H, prefix="l_"), mx.rnn.LSTMCell(H, prefix="r_"))
    outputs, states = cell.unroll(T, mx.sym.Variable("data"),
                                  merge_outputs=True)
    ex = outputs.simple_bind(mx.cpu(), data=(N, T, I))
    ex.forward(is_train=False)
    assert ex.outputs[0].shape == (N, T, 2 * H)
    assert len(states) == 4


def test_residual_zoneout_dropout_cells():
    T, N, I, H = 3, 2, 5, 5
    cell = mx.rnn.SequentialRNNCell()
    cell.add(mx.rnn.ResidualCell(mx.rnn.GRUCell(H, prefix="g0_")))
    cell.add(mx.rnn.DropoutCell(0.3))
    cell.add(mx.rnn.ZoneoutCell(mx.rnn.RNNCell(H, prefix="r0_"), 0.2, 0.1))
    outputs, _ = cell.unroll(T, mx.sym.Variable("data"), merge_outputs=True)
    ex = outputs.simple_bind(mx.cpu(), data=(N, T, I))
    ex.forward(is_train=True)
    assert ex.outputs[0].shape == (N, T, H)


def test_rnn_grad_flows():
    T, N, I, H = 4, 2, 3, 5
    sym = mx.sym.RNN(mx.sym.Variable("data"), mx.sym.Variable("p"),
                     mx.sym.Variable("s"), state_size=H, num_layers=1,
                     mode="gru")
    ex = sym.simple_bind(mx.cpu(), data=(T, N, I))
    rng = np.random.RandomState(0)
    for k, v in ex.arg_dict.items():
        v[:] = mx.nd.array(rng.randn(*v.shape).astype("float32") * 0.2)
    ex.forward(is_train=True)
    ex.backward(out_grads=mx.nd.ones(ex.outputs[0].shape))
    for name in ("data", "p", "s"):
        g = ex.grad_dict[name].asnumpy()
        assert np.isfinite(g).all()
        assert np.abs(g).sum() > 0, "no grad flow to %s" % name


def _make_lm_iter(batch_size=16):
    # learnable structure: ascending token runs (next = prev + 1 mod vocab),
    # so a trained LM beats the uniform-perplexity floor decisively
    rng = np.random.RandomState(0)
    sentences = []
    for _ in range(300):
        start = rng.randint(1, 40)
        ln = rng.randint(3, 15)
        sentences.append([(start + i - 1) % 39 + 1 for i in range(ln)])
    return mx.rnn.BucketSentenceIter(sentences, batch_size,
                                     buckets=[8, 16], invalid_label=0)


def _lm_sym_gen(vocab=40, E=16, H=24):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=E,
                                 name="embed")
        stack = mx.rnn.SequentialRNNCell()
        stack.add(mx.rnn.LSTMCell(num_hidden=H, prefix="lstm_l0_"))
        outputs, _ = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, H))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label, name="softmax")
        return pred, ("data",), ("softmax_label",)
    return sym_gen


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_bucketing_lm_trains():
    """Tiny LSTM LM perplexity drops under training (test_bucketing.py)."""
    mx.random.seed(6)  # deterministic init regardless of suite order
    train = _make_lm_iter()
    mod = mx.mod.BucketingModule(_lm_sym_gen(),
                                 default_bucket_key=train.default_bucket_key)
    mod.fit(train, num_epoch=5, eval_metric=mx.metric.Perplexity(0),
            optimizer="sgd", optimizer_params={"learning_rate": 0.2},
            initializer=mx.init.Xavier())
    train.reset()
    score = mod.score(train, mx.metric.Perplexity(0))
    ppl = dict(score)["perplexity"]
    assert np.isfinite(ppl)
    assert ppl < 15, "perplexity should beat uniform(~39): %g" % ppl


def test_bucket_sentence_iter_shapes():
    it = _make_lm_iter(batch_size=8)
    seen = set()
    for batch in it:
        assert batch.data[0].shape[0] == 8
        seen.add(batch.bucket_key)
        assert batch.data[0].shape[1] == batch.bucket_key
    assert seen == {8, 16}
    # labels are next-token shifted
    it.reset()
    b = next(it)
    d = b.data[0].asnumpy()
    lbl = b.label[0].asnumpy()
    assert np.allclose(d[:, 1:], lbl[:, :-1])


def test_conv_rnn_cells_unroll_and_train():
    """ConvRNN/ConvLSTM/ConvGRU cells (parity rnn_cell.py:1094-1380):
    NCHW feature-map states, conv gates; unroll binds, forward is finite,
    and gradients reach the conv weights."""
    import numpy as np
    import mxtpu as mx

    B, T, C, H, W, NH = 2, 3, 4, 8, 8, 6
    rng = np.random.RandomState(0)
    for cls, n_states in ((mx.rnn.ConvRNNCell, 1),
                          (mx.rnn.ConvLSTMCell, 2),
                          (mx.rnn.ConvGRUCell, 1)):
        cell = cls(input_shape=(C, H, W), num_hidden=NH)
        data = mx.sym.Variable("data")
        steps = [mx.sym.Reshape(mx.sym.slice_axis(
            data, axis=1, begin=t, end=t + 1), shape=(-1, C, H, W))
            for t in range(T)]
        outs, states = cell.unroll(T, inputs=steps)
        assert len(states) == n_states
        net = mx.sym.sum(outs[-1])
        shapes, _, _ = net.infer_shape(data=(B, T, C, H, W))
        args = {n: mx.nd.array(rng.randn(*s).astype("float32") * 0.2)
                for n, s in zip(net.list_arguments(), shapes)}
        grads = {n: mx.nd.zeros(v.shape) for n, v in args.items()
                 if n != "data"}
        ex = net.bind(mx.cpu(), args, args_grad=grads)
        out = ex.forward(is_train=True)[0].asnumpy()
        assert np.isfinite(out).all()
        ex.backward()
        g = grads[cell._iW.list_arguments()[0]].asnumpy()
        assert np.abs(g).max() > 0, cls.__name__
