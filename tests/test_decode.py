"""Stateful autoregressive decode serving (mxtpu/serving/decode).

Tier-1 (CPU, `not slow`). The PR's acceptance gates, all on exact
counters / byte comparisons per the PR-2 deterministic convention:

* **correctness** — with requests joining and leaving the batch between
  steps under a seeded arrival schedule, every request's token sequence
  is byte-identical to the same request decoded alone — including with
  the bf16 compile pipeline active, and across a mid-run ``swap_model``
  (in-flight sequences finish on their admission-time version);
* **liveness** — zero decode steps run with admittable requests left
  outside a free slot (asserted from the tripwire counter, not
  timing), and a completed sequence's slot is reusable by the very
  next step;
* **admission** — length-aware est-completion pricing sheds (429) when
  the arena is full behind LONG sequences, while a short-remaining mix
  at the same queue state still admits;
* **chaos** — injected step errors + a worker kill mid-decode resolve
  every in-flight request (completion or clean failure, zero hung
  waiters) and the arena leaks nothing (ledger ``decode_state`` back
  to baseline);
* **concurrency** — the armed witness reports zero hierarchy
  violations and an acyclic observed graph under concurrent decode.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxtpu as mx
import mxtpu.diagnostics as diag
from mxtpu import faults
from mxtpu.analysis import concurrency as conc
from mxtpu.base import MXNetError
from mxtpu.compile import pipeline
from mxtpu.serving import (AdmissionShed, AdmissionSignals,
                           DecodeAdmissionPolicy, DecodeSession,
                           SequenceSlotArena, ServingHTTPServer)
from mxtpu.serving.decode import lm_decode_fixture


# one fixture per module: every session built from THE SAME weight
# arrays under one version tag adopts the process warm cache — the
# suite pays the step-program compile once, not per test
_FIXTURE = {}


def _fixture(seed=0):
    if seed not in _FIXTURE:
        _FIXTURE[seed] = lm_decode_fixture(seed=seed)
    return _FIXTURE[seed]


def _session(seed=0, **kwargs):
    sym, params, shapes, state_names, _ = _fixture(seed)
    kwargs.setdefault("buckets", (4,))
    kwargs.setdefault("slot_capacity", 2)
    kwargs.setdefault("version_tag", "t-v%d" % seed)
    return DecodeSession(sym, params, shapes, state_names, **kwargs)


REQS = [([3, 5], 5, 0, 0.0), ([2], 6, 1, 0.5), ([7, 8, 9], 4, 2, 0.5),
        ([4], 5, 3, 0.0), ([6, 2], 3, 4, 0.9)]


def _decode_alone(seed=0, reqs=REQS):
    """Each request decoded as the ONLY sequence in flight."""
    out = []
    with _session(seed=seed, slot_capacity=1) as sess:
        for prompt, max_new, rseed, temp in reqs:
            out.append(sess.generate(prompt, max_new_tokens=max_new,
                                     seed=rseed, temperature=temp,
                                     timeout=60)["tokens"])
    return out


def _decode_joined(seed=0, reqs=REQS, capacity=2):
    """The same requests under a seeded concurrent arrival schedule:
    they join/leave the in-flight batch between steps (capacity <
    request count forces queue + slot-reuse churn)."""
    res = [None] * len(reqs)
    with _session(seed=seed, slot_capacity=capacity) as sess:

        def run(i):
            prompt, max_new, rseed, temp = reqs[i]
            res[i] = sess.generate(prompt, max_new_tokens=max_new,
                                   seed=rseed, temperature=temp,
                                   timeout=60)

        ts = [threading.Thread(target=run, args=(i,))
              for i in range(len(reqs))]
        for j, t in enumerate(ts):
            t.start()
            if j % 2:           # seeded stagger: joins land mid-decode
                time.sleep(0.003)
        for t in ts:
            t.join(timeout=120)
        tripped = sess.metrics.counter(
            "decode_steps_with_admittable_waiting").value
    assert all(r is not None for r in res), "hung generate waiter"
    return [r["tokens"] for r in res], res, tripped


# ------------------------------------------------------------ satellites
def test_state_spec_lstm_gru_stacked():
    """rnn_cell satellite: concrete zero-state shapes without a warmup
    batch, for single cells, stacks, and the fused cell."""
    lstm = mx.rnn.LSTMCell(8, prefix="l_")
    specs = lstm.state_spec(3)
    assert [tuple(s["shape"]) for s in specs] == [(3, 8), (3, 8)]
    arrs = lstm.begin_state_arrays(3)
    assert all(a.shape == (3, 8) and a.dtype == np.float32
               and not a.any() for a in arrs)

    gru = mx.rnn.GRUCell(5, prefix="g_")
    assert [tuple(s["shape"]) for s in gru.state_spec(2)] == [(2, 5)]

    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(4, prefix="s0_"))
    stack.add(mx.rnn.GRUCell(6, prefix="s1_"))
    specs = stack.state_spec(7)
    assert [tuple(s["shape"]) for s in specs] == [(7, 4), (7, 4), (7, 6)]
    names = [s["name"] for s in specs]
    assert len(set(names)) == 3  # unique state names across the stack

    fused = mx.rnn.FusedRNNCell(8, num_layers=2, mode="lstm")
    specs = fused.state_spec(3)
    assert [tuple(s["shape"]) for s in specs] == [(2, 3, 8), (2, 3, 8)]
    assert fused.begin_state_arrays(3, dtype="bfloat16")[0].dtype \
        == np.dtype("bfloat16")


def test_state_spec_matches_step_program_states():
    """The fixture's example state shapes ARE the cell stack's
    state_spec at batch 1 — the arena can size itself blind."""
    sym, params, shapes, state_names, meta = _fixture()
    stack = mx.rnn.SequentialRNNCell()
    for i in range(meta["num_layers"]):
        stack.add(mx.rnn.LSTMCell(meta["num_hidden"],
                                  prefix="lstm_l%d_" % i))
    specs = stack.state_spec(1)
    assert len(specs) == len(state_names)
    for name, spec in zip(state_names, specs):
        assert tuple(shapes[name]) == tuple(spec["shape"])


# ----------------------------------------------------------------- arena
def _tiny_specs():
    return [{"name": "h", "shape": (1, 3), "dtype": "float32"},
            {"name": "c", "shape": (1, 3), "dtype": "float32"}]


def test_arena_alloc_release_and_ledger():
    base = diag.ledger().live_bytes(origin="decode_state")
    arena = SequenceSlotArena(3, _tiny_specs())
    assert diag.ledger().live_bytes(origin="decode_state") \
        == base + 2 * 3 * 3 * 4
    slots = [arena.allocate() for _ in range(3)]
    assert sorted(slots) == [0, 1, 2]
    assert arena.allocate() is None          # full
    assert arena.free_slots == 0 and arena.occupancy == 1.0
    arena.release(slots[1])
    assert arena.allocate() == slots[1]      # reusable immediately
    with pytest.raises(MXNetError):
        arena.release(99)
    arena.release(slots[0])
    with pytest.raises(MXNetError):
        arena.release(slots[0])              # double free is loud
    arena.close()
    assert diag.ledger().live_bytes(origin="decode_state") == base


def test_arena_gather_scatter_exact():
    """Round-trip exactness: scatter writes land at their slots, fresh
    rows gather as zeros, pad rows (idx == capacity) are dropped."""
    arena = SequenceSlotArena(4, _tiny_specs())
    rows = np.arange(8, dtype=np.float32).reshape(4, 2)[:, :1] \
        * np.ones((4, 3), np.float32)
    new = [rows + 10, rows + 20]
    arena.scatter(np.array([0, 1, 2, 3]), new)
    got = arena.gather(np.array([2, 0, 4], np.int32),
                       np.array([0.0, 0.0, 1.0], np.float32))
    import jax
    h, c = jax.device_get(got)
    np.testing.assert_array_equal(h[0], new[0][2])
    np.testing.assert_array_equal(h[1], new[0][0])
    assert not h[2].any()                    # pad row zeroed
    np.testing.assert_array_equal(c[0], new[1][2])
    # scatter with a pad index must not corrupt live slots
    arena.scatter(np.array([1, 4], np.int32),
                  [np.full((2, 3), -1, np.float32)] * 2)
    h2 = jax.device_get(arena.gather(np.array([1, 0], np.int32),
                                     np.zeros(2, np.float32)))[0]
    np.testing.assert_array_equal(h2[0], np.full(3, -1, np.float32))
    np.testing.assert_array_equal(h2[1], new[0][0])  # slot 0 untouched
    # fresh mask zeroes IN the gather, not in the arena
    g = jax.device_get(arena.gather(np.array([0], np.int32),
                                    np.ones(1, np.float32)))[0]
    assert not g.any()
    arena.close()


def test_arena_fresh_mask_clears_nan_from_previous_occupant():
    """Slot reuse after a diverged sequence: a slot whose previous
    occupant scattered NaN/Inf state must gather as EXACT zeros for a
    fresh sequence (select, not multiply — 0*NaN is NaN)."""
    import jax
    arena = SequenceSlotArena(2, _tiny_specs())
    poison = [np.full((2, 3), np.nan, np.float32),
              np.full((2, 3), np.inf, np.float32)]
    arena.scatter(np.array([0, 1], np.int32), poison)
    got = jax.device_get(arena.gather(np.array([0, 1], np.int32),
                                      np.ones(2, np.float32)))
    for leaf in got:
        assert np.isfinite(leaf).all() and not leaf.any()
    arena.close()


def test_state_dtype_bf16_halves_arena_bytes_and_decodes():
    """DecodeSession(state_dtype="bfloat16"): the arena keeps sequence
    state in the narrow dtype (half the device bytes of f32) and decode
    still runs deterministically within the session."""
    with _session(slot_capacity=2) as f32:
        f32_bytes = f32.arena.state_bytes()
        with _session(slot_capacity=2, state_dtype="bfloat16",
                      version_tag="t-bf16") as bf:
            assert bf.arena.state_bytes() * 2 == f32_bytes
            assert all(s["dtype"] == "bfloat16" for s in bf.arena.specs)
            a = bf.generate([3, 5], max_new_tokens=4, timeout=60)
            b = bf.generate([3, 5], max_new_tokens=4, timeout=60)
            assert a["tokens"] == b["tokens"]  # state round-trip is
            # deterministic even through the narrow dtype


def test_arena_programs_have_cost_rows():
    """Gather/scatter ride the compile seam: `decode_state` programs
    appear in the diagnostics table with captured cost rows."""
    arena = SequenceSlotArena(2, _tiny_specs())
    arena.gather(np.array([0], np.int32), np.ones(1, np.float32))
    rec = diag.latest_record("decode_state")
    assert rec is not None and rec.kind == "decode_state"
    arena.close()


# ------------------------------------------------- THE correctness gate
def test_correctness_gate_joined_equals_alone():
    alone = _decode_alone()
    joined, results, tripped = _decode_joined()
    assert joined == alone, (joined, alone)
    assert tripped == 0
    # the schedule really did interleave: some sequence joined after
    # step 0 (otherwise this tested nothing)
    assert max(r["join_step"] for r in results) > 0


def test_correctness_gate_bf16_pipeline():
    """Same gate with the bf16 rewrite active: the step program is a
    first-class pipeline citizen and identity still holds bit-for-bit."""
    with pipeline.pipeline_scope(["bf16"]):
        alone = _decode_alone()
        joined, _, tripped = _decode_joined()
    assert joined == alone
    assert tripped == 0


def test_correctness_gate_mid_run_swap():
    """swap_model mid-decode: in-flight sequences finish on their
    admission-time version byte-for-byte; post-swap admissions run the
    new weights byte-for-byte."""
    alone_v1 = _decode_alone(seed=0, reqs=[([3], 24, 0, 0.0),
                                           ([5], 24, 0, 0.0)])
    alone_v2 = _decode_alone(seed=9, reqs=[([4], 6, 0, 0.0)])
    sym2, params2, _, _, _ = _fixture(9)
    res = [None] * 3
    with _session(seed=0, slot_capacity=2) as sess:

        def run(i, prompt, n):
            res[i] = sess.generate(prompt, max_new_tokens=n, timeout=120)

        ts = [threading.Thread(target=run, args=(0, [3], 24)),
              threading.Thread(target=run, args=(1, [5], 24))]
        for t in ts:
            t.start()
        # both sequences must be IN FLIGHT before the flip, so the gate
        # really tests admission-time pinning (not just ordering)
        deadline = time.monotonic() + 10
        while len(sess._active) < 2 and time.monotonic() < deadline:
            time.sleep(0.002)
        info = sess.swap_model(sym2, params2, version_tag="t-v9")
        assert info["generation"] == 1
        run(2, [4], 6)
        for t in ts:
            t.join(timeout=120)
    assert [res[0]["version"], res[1]["version"]] == ["t-v0", "t-v0"]
    assert res[2]["version"] == "t-v9"
    assert [res[0]["tokens"], res[1]["tokens"]] == alone_v1
    assert [res[2]["tokens"]] == alone_v2


# --------------------------------------------------- THE liveness gate
def test_liveness_gate_zero_idle_steps_and_slot_reuse():
    """Under queue-non-empty load: the tripwire counter proves no step
    dispatched with an admittable request outside a free slot, and a
    retired sequence's slot is taken by the next sequence at the SAME
    step count (reusable by the next step)."""
    reqs = [([2], 6, 0, 0.0)] * 4
    tokens, results, tripped = _decode_joined(reqs=reqs, capacity=2)
    assert tripped == 0
    finishes = sorted(r["finish_step"] for r in results)
    late_joins = sorted(r["join_step"] for r in results)[2:]
    # the two queued requests joined at EXACTLY the step counts where
    # the first two finished — the freed slot is in the very next
    # dispatched step, not one later (exact counters, no timing)
    assert late_joins == finishes[:2], (late_joins, finishes)


def test_join_latency_and_series():
    with _session(slot_capacity=2) as sess:
        sess.generate([2], max_new_tokens=2, timeout=60)
        stats = sess.stats()
        # 1-token prompt + 2 generated = exactly 2 steps (the last
        # prompt token's logits emit the first generated token)
        assert stats["decode_steps_total"] == 2
        assert stats["decode_tokens_total"] == 2
        assert stats["decode_join_latency_ms"]["count"] == 1
        assert stats["decode_evictions{reason=length}"] == 1
        assert stats["decode_active_sequences"] == 0
        assert "decode_slot_occupancy" in stats
        assert "decode_tokens_per_sec" in stats
        panel = sess.debug_panel()
        assert panel["slot_capacity"] == 2
        assert panel["admission"]["step_cost_basis"] in (
            "cost-rows", "live-steps")
        assert panel["state_bytes"] > 0


# -------------------------------------------------- THE admission gate
def test_admission_gate_length_aware_pricing():
    """Arena full + queue at the watermark: LONG remaining sequences
    price the join wait over budget (429); a SHORT-remaining mix at the
    same queue state admits (the PR-11 mix-aware pattern)."""
    def load(max_new):
        sess = _session(slot_capacity=2, join_watermark=1,
                        join_wait_budget_ms=60.0)
        holders = [threading.Thread(
            target=lambda: _swallow(sess.generate, [2],
                                    max_new_tokens=max_new, timeout=120))
            for _ in range(2)]
        for t in holders:
            t.start()
        # wait until both holders occupy their slots
        deadline = time.monotonic() + 10
        while sess.arena.free_slots and time.monotonic() < deadline:
            time.sleep(0.002)
        # one queued request reaches the watermark
        queued = threading.Thread(
            target=lambda: _swallow(sess.generate, [3],
                                    max_new_tokens=max_new, timeout=120))
        queued.start()
        deadline = time.monotonic() + 10
        while not sess._queue and sess.arena.free_slots == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.002)
        return sess, holders + [queued]

    # (helper closes over nothing mutable — each mix builds fresh)

    # LONG mix: thousands of remaining tokens ahead -> shed
    sess, threads = load(max_new=4000)
    if sess.arena.free_slots == 0:        # still loaded, as scheduled
        with pytest.raises(AdmissionShed) as exc:
            sess.generate_async([5], max_new_tokens=4000)
        assert "slots" in str(exc.value)
        assert sess._sheds_by_reason.get("slots") == 1
    sess.close(drain=False)
    for t in threads:
        t.join(timeout=30)

    # SHORT mix at the same queue shape: est join wait is a few steps
    # -> admits (whether or not the holders already finished)
    sess, threads = load(max_new=2)
    item = sess.generate_async([5], max_new_tokens=2)
    assert item.wait(60)["finish_reason"] == "length"
    sess.close()
    for t in threads:
        t.join(timeout=30)


def _swallow(fn, *a, **kw):
    try:
        fn(*a, **kw)
    except Exception:
        pass


def test_decode_admission_policy_units():
    """Pure-function decisions over synthetic signals."""
    pol = DecodeAdmissionPolicy(join_wait_budget_ms=100.0,
                                join_watermark=2)
    base = dict(slot_capacity=4, slots_free=0, queue_depth=2,
                queue_limit=256)
    long = AdmissionSignals(est_join_wait_ms=500.0,
                            est_tokens_ahead=250, **base)
    d = pol.decide(long)
    assert not d.admit and d.reason.startswith("slots")
    short = AdmissionSignals(est_join_wait_ms=12.0, est_tokens_ahead=6,
                             **base)
    assert pol.decide(short).admit
    # below the watermark the queue absorbs long waits without a shed
    trickle = AdmissionSignals(est_join_wait_ms=500.0,
                               est_tokens_ahead=250,
                               slot_capacity=4, slots_free=0,
                               queue_depth=1, queue_limit=256)
    assert pol.decide(trickle).admit
    # free slots always admit
    free = AdmissionSignals(est_join_wait_ms=0.0, slot_capacity=4,
                            slots_free=2, queue_depth=0, queue_limit=256)
    assert pol.decide(free).admit
    wedged = AdmissionSignals(watchdog_age_s=99.0, slot_capacity=4,
                              slots_free=2)
    assert not pol.decide(wedged).admit


def test_est_join_wait_uses_exact_remaining_tokens():
    """The signal math: with the arena full, est_tokens_ahead is the
    exact sorted-remaining count for the arrival's queue position."""
    with _session(slot_capacity=2) as sess:
        holders = [threading.Thread(
            target=lambda: _swallow(sess.generate, [2],
                                    max_new_tokens=100, timeout=60))
            for _ in range(2)]
        for t in holders:
            t.start()
        deadline = time.monotonic() + 10
        while sess.arena.free_slots and time.monotonic() < deadline:
            time.sleep(0.002)
        s = sess._signals()
        if s.slots_free == 0:
            assert 0 < s.est_tokens_ahead <= 101
            assert s.est_join_wait_ms == pytest.approx(
                s.est_batch_ms * s.est_tokens_ahead)
        sess.close(drain=False)
        for t in holders:
            t.join(timeout=30)


# ------------------------------------------------------ THE chaos gate
def test_chaos_gate_step_errors_and_kill():
    """Injected step errors + a worker kill mid-decode: every in-flight
    request resolves (tokens or a clean error, zero hung waiters), the
    worker respawns, the arena leaks nothing and the ledger's
    decode_state origin returns to baseline."""
    base = diag.ledger().live_bytes(origin="decode_state")
    sess = _session(slot_capacity=2)
    outcomes = []

    def run(i):
        try:
            sess.generate([2 + i % 8], max_new_tokens=6, timeout=30)
            outcomes.append("ok")
        except Exception as exc:
            outcomes.append(type(exc).__name__)

    # the kill spec is FIRST for its point: specs fire in declaration
    # order, so the crossing that arms it really dies (a raise-spec
    # firing the same crossing would otherwise preempt it)
    with faults.scope("serving.decode.step:kind=kill,after=4;"
                      "serving.decode.step:p=0.4,seed=7;"
                      "serving.decode.evict:p=0.3,seed=3"):
        ts = [threading.Thread(target=run, args=(i,)) for i in range(10)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
    assert len(outcomes) == 10, "hung waiters under chaos"
    assert "ok" not in outcomes or True  # any mix is legal; none hang
    # the schedule really fired, including the kill -> respawn: waiters
    # are answered BEFORE the death path increments the counter, so
    # poll it rather than race the handler's tail
    deadline = time.monotonic() + 10
    while sess.metrics.counter("decode_worker_respawns").value < 1 \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sess.metrics.counter("decode_worker_respawns").value >= 1
    # zero slot leaks: everything resolved, so the arena is empty again
    deadline = time.monotonic() + 10
    while sess.arena.free_slots < sess.arena.capacity \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sess.arena.free_slots == sess.arena.capacity
    # the respawned worker serves post-chaos traffic
    r = sess.generate([3], max_new_tokens=2, timeout=30)
    assert r["finish_reason"] == "length"
    sess.close()
    assert diag.ledger().live_bytes(origin="decode_state") == base


def test_max_new_tokens_cap_protects_the_data_plane():
    """An unauthenticated request cannot pin a slot for an unbounded
    number of steps: max_new_tokens over the server cap is refused
    (MXNetError in-process, 400 over HTTP)."""
    from mxtpu.serving.decode.session import (MAX_NEW_TOKENS_CAP,
                                              MAX_REQUEST_TOKENS_CAP)
    with _session(slot_capacity=1) as sess:
        with pytest.raises(MXNetError):
            sess.generate_async([2], max_new_tokens=MAX_NEW_TOKENS_CAP + 1)
        # a giant PROMPT pins a slot one prefill step per token — the
        # total-step cap refuses it even with a tiny generation budget
        with pytest.raises(MXNetError):
            sess.generate_async([2] * MAX_REQUEST_TOKENS_CAP,
                                max_new_tokens=1)
        # at the cap itself the request is admitted
        item = sess.generate_async([2], max_new_tokens=MAX_NEW_TOKENS_CAP,
                                   timeout=60)
        sess.close(drain=False)
        _swallow(item.wait, 5)


def test_fail_chunk_preserves_already_finished_results():
    """A chunk member that finished cleanly before a later member's
    eviction raised keeps its delivered result — fail() must never
    overwrite a completed generation (and it isn't double-counted)."""
    from mxtpu.serving.decode.session import _Sequence
    with _session(slot_capacity=2) as sess:
        done = _Sequence([2], 1, None, 0, 0.0, None)
        done.item.finish({"tokens": [7], "finish_reason": "length"})
        pending = _Sequence([3], 1, None, 0, 0.0, None)
        failed_before = sess.metrics.counter("requests_failed").value
        sess._fail_chunk([done, pending], RuntimeError("step died"))
        assert done.item.wait(1)["tokens"] == [7]      # result intact
        with pytest.raises(RuntimeError):
            pending.item.wait(1)
        assert sess.metrics.counter("requests_failed").value \
            == failed_before + 1


def test_evict_injection_never_leaks_slots():
    """An eviction fault alone: requests may fail but every slot comes
    back (the _evict finally contract)."""
    with _session(slot_capacity=2) as sess:
        with faults.scope("serving.decode.evict:p=1.0,seed=1,times=4"):
            for i in range(4):
                _swallow(sess.generate, [2], max_new_tokens=1,
                         timeout=30)
        assert sess.arena.free_slots == sess.arena.capacity
        evs = [v for k, v in sess.stats().items()
               if str(k).startswith("decode_evictions")]
        assert sum(evs) >= 4


# --------------------------------------------------- concurrency gate
def test_armed_witness_decode_gate():
    """Concurrent decode under the armed lock-order witness: zero
    hierarchy violations, zero blocking-under-lock, acyclic graph."""
    with conc.scope() as w:
        joined, _, tripped = _decode_joined(
            reqs=[([2], 4, 0, 0.0)] * 6, capacity=2)
        assert len(joined) == 6 and tripped == 0
    rep = w.report()
    assert w.violations == 0, rep.render()
    assert w.blocked_calls == 0, rep.render()
    assert w.state()["acyclic"], w.state()["cycles"]


# ------------------------------------------------------------- tuning
def test_decode_knobs_resolve_through_tune():
    """DecodeSession(tuned=) wiring: artifact beats default, env beats
    artifact, explicit beats both (warmup=False keeps this compile-free)."""
    cfg = mx.tune.TunedConfig(values={"decode.slot_capacity": 3,
                                      "decode.max_new_tokens_default": 7,
                                      "decode.join_watermark": 2})
    s = _session(tuned=cfg, slot_capacity=None, warmup=False)
    try:
        assert s.slot_capacity == 3
        assert s.max_new_tokens_default == 7
        assert s.join_watermark == 2
    finally:
        s.close()
    import os
    os.environ["MXTPU_DECODE_SLOTS"] = "5"
    try:
        s = _session(tuned=cfg, slot_capacity=None, warmup=False)
        try:
            assert s.slot_capacity == 5       # env beats artifact
        finally:
            s.close()
    finally:
        del os.environ["MXTPU_DECODE_SLOTS"]
    s = _session(tuned=cfg, slot_capacity=4, warmup=False)
    try:
        assert s.slot_capacity == 4           # explicit beats both
    finally:
        s.close()


# ---------------------------------------------------------------- HTTP
def test_http_generate_roundtrip_and_debug_panel():
    sess = _session(slot_capacity=2, id2word={i: "w%d" % i
                                              for i in range(16)})
    server = ServingHTTPServer(None, decode=sess, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        url = server.endpoint
        body = json.dumps({"prompt": [3, 5], "max_new_tokens": 3,
                           "seed": 1}).encode()
        req = urllib.request.Request(url + "/v1/generate", data=body,
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            out = json.loads(resp.read())
        assert len(out["tokens"]) == 3
        assert out["finish_reason"] == "length"
        assert out["text"].startswith("w")
        with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert health["mode"] == "decode" and health["status"] == "ok"
        with urllib.request.urlopen(url + "/debug/state",
                                    timeout=30) as r:
            state = json.loads(r.read())
        assert state["decode"]["slot_capacity"] == 2
        assert state["decode"]["tokens_out"] >= 3
        assert "admission" in state["decode"]
        with urllib.request.urlopen(url + "/v1/metrics", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["decode_steps_total"] >= 1
        # bad request taxonomy
        req = urllib.request.Request(url + "/v1/generate",
                                     data=b'{"prompt": []}')
        try:
            urllib.request.urlopen(req, timeout=30)
            assert False, "empty prompt must 400"
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
    finally:
        server.shutdown()


def test_http_admin_swap_targets_decode(tmp_path):
    """On a combined server the swap payload's ``target`` routes the
    rollout: ``"decode"`` rolls the decode pool (predict untouched), a
    bogus target is 400 — a decode checkpoint can never land on the
    predict pool by routing accident."""
    from mxtpu.models.serving_fixtures import get_fixture
    from mxtpu.serving import ServingSession
    sym9, params9, _, _, _ = _fixture(9)
    symf = tmp_path / "step.json"
    symf.write_text(sym9)
    pf = str(tmp_path / "step.params")
    mx.nd.save(pf, params9)
    psym, pparams, pshapes = get_fixture("mlp")
    psess = ServingSession(psym, pparams, pshapes, buckets=(1,),
                           version_tag="p-v0")
    dsess = _session(seed=0, slot_capacity=2)
    server = ServingHTTPServer(psess, decode=dsess, port=0,
                               admin_token="hunter2")
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        url = server.endpoint

        def swap(body):
            req = urllib.request.Request(
                url + "/v1/admin/swap", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json",
                         "X-Admin-Token": "hunter2"})
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        info = swap({"symbol_file": str(symf), "params_file": pf,
                     "version_tag": "h-v9", "target": "decode"})
        assert info["version"] == "h-v9" and info["mode"] == "decode"
        assert dsess.version_tag == "h-v9"
        assert psess.version_tag == "p-v0"          # predict untouched
        try:
            swap({"symbol_file": str(symf), "params_file": pf,
                  "target": "bogus"})
            assert False, "bogus target must 400"
        except urllib.error.HTTPError as exc:
            assert exc.code == 400
    finally:
        server.shutdown()


def test_http_combined_server_exposes_both_sessions():
    """Predict + decode on one port: distinct metric namespaces in one
    scrape (no duplicate Prometheus series, no clobbered JSON keys),
    decode visible in /healthz and /v1/version|metrics, and a closed
    decode session drains the WHOLE server."""
    from mxtpu.models.serving_fixtures import get_fixture
    from mxtpu.serving import ServingSession
    psym, pparams, pshapes = get_fixture("mlp")
    psess = ServingSession(psym, pparams, pshapes, buckets=(1,))
    dsess = _session(slot_capacity=2)
    server = ServingHTTPServer(psess, decode=dsess, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        url = server.endpoint
        dsess.generate([2], max_new_tokens=2, timeout=60)
        with urllib.request.urlopen(url + "/metrics?format=json",
                                    timeout=30) as r:
            snap = json.loads(r.read())
        # distinct namespaces: decode steps under mxtpu_decode, the
        # predict session's series untouched under mxtpu_serving
        assert snap["mxtpu_decode"]["decode_steps_total"] >= 1
        assert "decode_steps_total" not in snap["mxtpu_serving"]
        assert "queue_depth" in snap["mxtpu_serving"]
        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            prom = r.read().decode()
        assert "mxtpu_decode_decode_steps_total" in prom
        # exactly one sample per shared-name series per namespace
        assert prom.count("\nmxtpu_serving_queue_depth ") == 1
        assert prom.count("\nmxtpu_decode_queue_depth ") == 1
        with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert health["decode"]["version"] == dsess.version_tag
        with urllib.request.urlopen(url + "/v1/version",
                                    timeout=30) as r:
            ver = json.loads(r.read())
        assert ver["decode"]["mode"] == "decode"
        with urllib.request.urlopen(url + "/v1/metrics",
                                    timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["decode"]["decode_steps_total"] >= 1
        # EITHER session draining drains the server
        dsess.close()
        try:
            urllib.request.urlopen(url + "/healthz", timeout=30)
            assert False, "closed decode session must 503"
        except urllib.error.HTTPError as exc:
            assert exc.code == 503
    finally:
        server.shutdown()
