"""io / metric / optimizer / initializer / recordio unit tests (model:
reference tests/python/unittest/{test_io.py,test_metric.py,test_optimizer.py,
test_init.py,test_recordio.py})."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd


def test_ndarray_iter():
    X = np.arange(40).reshape(10, 4).astype("f4")
    y = np.arange(10).astype("f4")
    it = mx.io.NDArrayIter(X, y, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    assert batches[2].pad == 2
    it.reset()
    assert len(list(it)) == 3
    it2 = mx.io.NDArrayIter(X, y, batch_size=4, last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_ndarray_iter_provide():
    X = np.zeros((8, 2, 3), dtype="f4")
    it = mx.io.NDArrayIter(X, batch_size=4)
    desc = it.provide_data[0]
    assert desc.name == "data"
    assert desc.shape == (4, 2, 3)


def test_resize_iter():
    X = np.zeros((8, 2), dtype="f4")
    it = mx.io.ResizeIter(mx.io.NDArrayIter(X, batch_size=4), size=5)
    assert len(list(it)) == 5


def test_prefetching_iter():
    X = np.random.randn(16, 3).astype("f4")
    y = np.zeros(16, dtype="f4")
    base = mx.io.NDArrayIter(X, y, batch_size=4)
    it = mx.io.PrefetchingIter(base)
    count = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3)
        count += 1
    assert count == 4


def test_csv_iter(tmp_path):
    data_path = str(tmp_path / "data.csv")
    np.savetxt(data_path, np.arange(20).reshape(5, 4), delimiter=",")
    it = mx.io.CSVIter(data_csv=data_path, data_shape=(4,), batch_size=2)
    b = next(iter(it))
    assert b.data[0].shape == (2, 4)


def test_metrics():
    acc = mx.metric.create("acc")
    pred = nd.array(np.array([[0.9, 0.1], [0.2, 0.8]]))
    label = nd.array(np.array([0., 1]))
    acc.update([label], [pred])
    assert acc.get()[1] == 1.0
    mse = mx.metric.create("mse")
    mse.update([nd.zeros((2, 1))], [nd.ones((2, 1))])
    assert np.isclose(mse.get()[1], 1.0)
    top2 = mx.metric.create("top_k_accuracy", top_k=2)
    top2.update([label], [pred])
    assert top2.get()[1] == 1.0
    comp = mx.metric.create(["acc", "mse"])
    assert isinstance(comp, mx.metric.CompositeEvalMetric)
    ppl = mx.metric.Perplexity(ignore_label=None)
    ppl.update([label], [pred])
    assert ppl.get()[1] > 1.0


def test_custom_metric():
    def my_mse(label, pred):
        return float(((label.reshape(-1, 1) - pred) ** 2).mean())
    m = mx.metric.np(my_mse)
    m.update([nd.zeros((2,))], [nd.ones((2, 1))])
    assert np.isclose(m.get()[1], 1.0)


def test_optimizers_step():
    for name in ("sgd", "adam", "rmsprop", "adagrad", "adadelta", "ftrl",
                 "adamax", "nadam", "nag", "sgld"):
        opt = mx.optimizer.create(name, learning_rate=0.01, wd=0.0)
        w = nd.ones((4,))
        g = nd.ones((4,)) * 0.5
        state = opt.create_state(0, w)
        w_before = w.asnumpy().copy()
        opt.update(0, w, g, state)
        assert not np.allclose(w.asnumpy(), w_before), name


def test_lr_scheduler():
    opt = mx.optimizer.SGD(learning_rate=1.0,
                           lr_scheduler=mx.lr_scheduler.FactorScheduler(
                               step=2, factor=0.5))
    w = nd.ones((2,))
    g = nd.ones((2,))
    s = opt.create_state(0, w)
    lrs = []
    for _ in range(6):
        opt.update(0, w, g, s)
        lrs.append(opt._get_lr(0))
    assert lrs[-1] < lrs[0]
    multi = mx.lr_scheduler.MultiFactorScheduler(step=[2, 4], factor=0.1)
    multi.base_lr = 1.0
    assert np.isclose(multi(5), 0.01)


def test_updater_serialization():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    upd = mx.optimizer.get_updater(opt)
    w, g = nd.ones((2,)), nd.ones((2,))
    upd(0, g, w)
    states = upd.get_states()
    assert isinstance(states, bytes)


def test_initializers():
    for init, check in [
            (mx.initializer.Zero(), lambda a: np.allclose(a, 0)),
            (mx.initializer.One(), lambda a: np.allclose(a, 1)),
            (mx.initializer.Constant(2.5), lambda a: np.allclose(a, 2.5)),
            (mx.initializer.Uniform(0.1), lambda a: np.abs(a).max() <= 0.1),
            (mx.initializer.Xavier(), lambda a: a.std() > 0),
            (mx.initializer.Normal(0.01), lambda a: a.std() < 0.1),
            (mx.initializer.Orthogonal(), lambda a: a.std() > 0)]:
        arr = nd.zeros((8, 8))
        init("test_weight", arr)
        assert check(arr.asnumpy()), type(init).__name__
    # suffix dispatch
    arr = nd.zeros((4,))
    mx.initializer.Uniform()("fc1_bias", arr)
    assert np.allclose(arr.asnumpy(), 0)
    arr2 = nd.zeros((4,))
    mx.initializer.Uniform()("bn_gamma", arr2)
    assert np.allclose(arr2.asnumpy(), 1)


def test_recordio(tmp_path):
    from mxtpu import recordio
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(5):
        writer.write(b"record%d" % i)
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert reader.read() == b"record%d" % i
    assert reader.read() is None


def test_indexed_recordio(tmp_path):
    from mxtpu import recordio
    path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(5):
        writer.write_idx(i, b"record%d" % i)
    writer.close()
    reader = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert reader.read_idx(3) == b"record3"
    assert reader.keys == list(range(5))


def test_recordio_pack_unpack():
    from mxtpu import recordio
    header = recordio.IRHeader(0, 3.0, 7, 0)
    packed = recordio.pack(header, b"payload")
    h2, content = recordio.unpack(packed)
    assert h2.label == 3.0
    assert h2.id == 7
    assert content == b"payload"
    # vector label
    header = recordio.IRHeader(0, np.array([1.0, 2, 3], dtype="f4"), 1, 0)
    packed = recordio.pack(header, b"x")
    h3, content = recordio.unpack(packed)
    assert np.allclose(h3.label, [1, 2, 3])


def test_kvstore_save_load_optimizer_states(tmp_path):
    store = mx.kv.create("local")
    store.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    store.init(0, nd.ones((2,)))
    store.push(0, nd.ones((2,)))
    fname = str(tmp_path / "states.bin")
    store.save_optimizer_states(fname)
    store.load_optimizer_states(fname)


def test_map_metric():
    import importlib.util
    import os
    import numpy as np
    import mxtpu as mx

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "ssd", "evaluate.py")
    spec = importlib.util.spec_from_file_location("ssd_evaluate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    m = mod.MApMetric(ovp_thresh=0.5)
    # one image, one gt box of class 0; detections: one perfect hit and
    # one false positive of class 1
    label = np.full((1, 4, 5), -1.0, "float32")
    label[0, 0] = [0, 0.1, 0.1, 0.5, 0.5]
    det = np.full((1, 4, 6), -1.0, "float32")
    det[0, 0] = [0, 0.9, 0.1, 0.1, 0.5, 0.5]   # matches gt -> tp
    det[0, 1] = [1, 0.8, 0.6, 0.6, 0.9, 0.9]   # class with no gt
    m.update([mx.nd.array(label)], [mx.nd.array(det)])
    name, val = m.get()
    assert name == "mAP"
    assert abs(val - 1.0) < 1e-6  # class 0 AP=1; class 1 has no gt -> skip

    # a missed gt halves recall
    m2 = mod.MApMetric()
    label2 = np.full((1, 4, 5), -1.0, "float32")
    label2[0, 0] = [0, 0.1, 0.1, 0.5, 0.5]
    label2[0, 1] = [0, 0.6, 0.6, 0.9, 0.9]
    m2.update([mx.nd.array(label2)], [mx.nd.array(det)])
    _, val2 = m2.get()
    assert abs(val2 - 0.5) < 1e-6


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_benchmark_score_smoke():
    """tools/benchmark_score.py (parity example/image-classification/
    benchmark_score.py): the zoo inference sweep runs and reports img/s."""
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "benchmark_score.py"),
         "--networks", "resnet-18", "--batch-sizes", "2",
         "--num-batches", "2", "--cpu"],
        capture_output=True, text=True, timeout=600,
        # PYTHONPATH=repo deliberately REPLACES the baked axon sitecustomize
        # path: with the device relay wedged, that sitecustomize hangs any
        # fresh interpreter at import (see .claude/skills/verify gotchas)
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo))
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["images_per_sec"] > 0
