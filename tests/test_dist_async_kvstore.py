"""dist_async ON the jax.distributed path (VERDICT r3 #8): two
jax.distributed processes create KVStore('dist_async') with no launcher
env; rank 0 hosts the async parameter server in-process, every rank
connects over the coordinator's host, and the reference's async staleness
semantics hold (kvstore_dist_server.h:164-300):

  * NO cross-worker barrier in push/pull — rank 0 never pushes, yet its
    pulls observe rank 1's updates (a synchronous psum mapping would
    deadlock or never show them);
  * every push applies immediately — three pushes from one worker move
    the weight three optimizer steps, no quorum wait.
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys, time
    import numpy as np
    sys.path.insert(0, %(repo)r)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.distributed.initialize(coordinator_address="localhost:%(port)d",
                               num_processes=2,
                               process_id=int(sys.argv[1]))
    import mxtpu as mx

    rank = jax.process_index()
    kv = mx.kv.create("dist_async")
    assert kv.type == "dist_async"
    assert kv.num_workers == 2 and kv.rank == rank

    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.init("w", mx.nd.zeros((4,)))
    out = mx.nd.zeros((4,))

    if rank == 1:
        # three immediate-apply updates; no other worker participates
        g = mx.nd.array(np.ones(4, "float32"))
        for _ in range(3):
            kv.push("w", g)
        kv.pull("w", out=out)
        print("RANK1", out.asnumpy().tolist(), flush=True)
    else:
        # rank 0 NEVER pushes: under async semantics its pulls still see
        # rank 1's three steps (w = -0.3) within the wait window
        deadline = time.time() + 60
        seen = None
        while time.time() < deadline:
            kv.pull("w", out=out)
            seen = out.asnumpy()
            if abs(seen[0] + 0.3) < 1e-5:
                break
            time.sleep(0.2)
        print("RANK0", seen.tolist(), flush=True)
        assert abs(seen[0] + 0.3) < 1e-5, seen

    kv.barrier()
    kv.close()
    print("DONE", rank, flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_dist_async(tmp_path):
    port = _free_port()
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(WORKER % {"repo": REPO, "port": port})
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    # the async PS binds coordinator_port+1000 by default; pick our own
    # free port to avoid collisions with parallel test runs
    env["MXTPU_ASYNC_PS_PORT"] = str(_free_port())
    procs = [subprocess.Popen([sys.executable, script, str(i)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, "rank %d failed:\n%s" % (i, out)
        assert "DONE %d" % i in out, out
    # rank 0 observed rank 1's three async steps without pushing
    r0 = [l for l in outs[0].splitlines() if l.startswith("RANK0")]
    assert r0 and "-0.3" in r0[0], outs[0]


def test_transport_bandwidth_at_gradient_sizes():
    """Binary out-of-band framing (docs/dist_async_transport.md): a
    64 MB tensor round-trips correctly and the loopback rate clears a
    conservative floor — an accidental extra copy in the framing would
    halve it and fail here."""
    import time

    import numpy as np

    from mxtpu.kvstore_server import KVClient, KVServer

    server = KVServer(0, num_workers=1)
    server.run_in_thread()
    client = KVClient("127.0.0.1", server.port)
    arr = np.random.RandomState(0).rand(8 << 20)  # 64 MB float64
    client.init("g", arr, rank=0)
    client.push("g", arr)  # no updater: merged value is assigned
    np.testing.assert_array_equal(client.pull("g"), arr)
    reps = 4
    t0 = time.perf_counter()
    for _ in range(reps):
        client.push("g", arr)
    push_rate = arr.nbytes * reps / (time.perf_counter() - t0) / 1e6
    t0 = time.perf_counter()
    for _ in range(reps):
        out = client.pull("g")
    pull_rate = arr.nbytes * reps / (time.perf_counter() - t0) / 1e6
    client.stop()
    assert out.nbytes == arr.nbytes
    # measured ~440/~1000 MB/s on the build machine; floor leaves 4x
    # headroom for slow CI hosts
    assert push_rate > 100, "push transport regressed: %.0f MB/s" % push_rate
    assert pull_rate > 150, "pull transport regressed: %.0f MB/s" % pull_rate
