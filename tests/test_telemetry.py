"""mxtpu.telemetry: registry exactness under concurrency, fixed-bucket
percentiles, Prometheus/JSON exposition, correlated tracing across the
engine's thread hop, and the built-in fit/kvstore instrumentation."""
import json
import re
import threading

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import telemetry as tel
from mxtpu.telemetry.metrics import Histogram, MetricsRegistry


# ------------------------------------------------------------- concurrency
def test_concurrent_writers_exact_totals():
    """N threads hammering shared counters and histograms: totals must be
    EXACT — a lost increment means a lock is missing on the hot path."""
    reg = MetricsRegistry(namespace="t")
    n_threads, n_iter = 8, 2000
    ctr = reg.counter("stress_total")
    hist = reg.histogram("stress_ms")
    lctr = [reg.counter("stress_labeled", labels={"worker": str(i)})
            for i in range(n_threads)]
    barrier = threading.Barrier(n_threads)

    def worker(i):
        barrier.wait()
        for k in range(n_iter):
            ctr.inc()
            lctr[i].inc(2)
            hist.observe(float(k % 50))
            # dynamic lookup path must be exact too (registry lock)
            reg.counter("stress_dynamic").inc()

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * n_iter
    assert ctr.value == total
    assert reg.counter("stress_dynamic").value == total
    assert hist.count == total
    assert sum(hist.bucket_counts) == total
    assert hist.sum == pytest.approx(n_threads * sum(k % 50
                                                     for k in range(n_iter)))
    for i in range(n_threads):
        assert lctr[i].value == 2 * n_iter


# ------------------------------------------------------------- histograms
def test_histogram_fixed_bucket_percentiles():
    h = Histogram("lat", bounds=(1, 2, 4, 8, 16, 32, float("inf")))
    for v in range(1, 101):  # uniform 1..100
        h.observe(float(v))
    assert h.count == 100 and h.min == 1.0 and h.max == 100.0
    # values past the last finite bound resolve to the observed max
    assert h.percentile(99) == 100.0
    # interior percentiles are bucket-accurate: p25 of uniform(1,100) = 25
    # lands in the (16, 32] bucket
    assert 16 <= h.percentile(25) <= 32
    assert h.mean == pytest.approx(50.5)
    # empty histogram is quiet
    assert Histogram("e").percentile(50) == 0.0


def test_histogram_appends_inf_bound():
    h = Histogram("x", bounds=(1, 2))
    h.observe(99.0)
    assert h.bounds[-1] == float("inf")
    assert h.count == 1 and sum(h.bucket_counts) == 1


# ------------------------------------------------------------- exposition
def test_prometheus_text_exposition_parses():
    reg = MetricsRegistry(namespace="tp")
    reg.counter("reqs", help='total "requests"').inc(5)
    reg.gauge("depth").set(3)
    h = reg.histogram("lat_ms", labels={"route": "predict"},
                      bounds=(1, 10, float("inf")))
    for v in (0.5, 5, 50):
        h.observe(v)
    text = tel.prometheus_text(reg)
    lines = [l for l in text.splitlines() if l]
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$')
    for line in lines:
        assert line.startswith("#") or sample_re.match(line), line
    assert "# TYPE tp_reqs counter" in text
    assert "tp_reqs 5" in text
    assert "# TYPE tp_lat_ms histogram" in text
    # cumulative buckets: le=1 -> 1, le=10 -> 2, le=+Inf -> 3 == _count
    assert 'tp_lat_ms_bucket{le="1",route="predict"} 1' in text
    assert 'tp_lat_ms_bucket{le="10",route="predict"} 2' in text
    assert 'tp_lat_ms_bucket{le="+Inf",route="predict"} 3' in text
    assert 'tp_lat_ms_count{route="predict"} 3' in text


def test_json_snapshot_and_dump(tmp_path):
    reg = MetricsRegistry(namespace="tj")
    reg.counter("a").inc(7)
    reg.histogram("h").observe(4.0)
    snap = tel.json_snapshot(reg)
    assert snap["tj"]["a"] == 7
    assert snap["tj"]["h"]["count"] == 1
    pj = tel.dump(str(tmp_path / "m.json"), reg, fmt="json")
    assert json.load(open(pj))["tj"]["a"] == 7
    pp = tel.dump(str(tmp_path / "m.prom"), reg, fmt="prometheus")
    assert "tj_a 7" in open(pp).read()
    with pytest.raises(ValueError):
        tel.dump(str(tmp_path / "m.x"), reg, fmt="xml")


# ------------------------------------------------------------- tracing
def test_span_nesting_and_ids():
    with tel.span("outer") as outer:
        assert tel.current_span() is outer
        assert outer.parent_id == 0 and outer.trace_id == outer.span_id
        with tel.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.trace_id == outer.trace_id
    assert tel.current_span() is None
    assert tel.trace_id() == 0
    assert inner.duration_ms >= 0.0


def test_span_cross_thread_parenting():
    """The serving/engine pattern: capture the submitting span, restore it
    as parent on the worker thread -> one trace id."""
    seen = {}

    def worker(parent):
        with tel.span("work", parent=parent) as s:
            seen["trace"] = s.trace_id
            seen["parent"] = s.parent_id

    with tel.span("request") as req:
        t = threading.Thread(target=worker, args=(tel.current_span(),))
        t.start()
        t.join()
    assert seen["trace"] == req.trace_id
    assert seen["parent"] == req.span_id


def test_engine_push_flows_span_ids():
    """engine push -> (native worker) dispatch carries the pushing span."""
    eng = mx.engine.get()
    seen = {}
    with tel.span("step") as root:
        eng.push(lambda: seen.setdefault("trace", tel.trace_id()))
        eng.wait_for_all()
    assert seen["trace"] == root.trace_id
    reg = tel.registry()
    assert reg.counter("engine_ops_completed").value >= 1
    assert reg.histogram("engine_queue_wait_ms").count >= 1


def test_spans_feed_registry_histogram():
    before = tel.registry().histogram(
        tel.SPAN_HISTOGRAM, labels={"span": "probe_span"}).count
    with tel.span("probe_span"):
        pass
    after = tel.registry().histogram(
        tel.SPAN_HISTOGRAM, labels={"span": "probe_span"}).count
    assert after == before + 1


def test_span_timebase_matches_profiler():
    """Telemetry spans and profiler.scope spans share one wall-clock
    timebase in the chrome://tracing dump (a perf_counter/time.time mix
    would scatter one trace across decades)."""
    from mxtpu import profiler
    profiler.clear()
    profiler.set_config(mode="symbolic", filename="/tmp/unused_tb.json")
    profiler.set_state("run")
    try:
        with tel.span("tb_tel"):
            pass
        with profiler.scope("tb_prof"):
            pass
    finally:
        profiler.set_state("stop")
    with profiler._lock:
        ts = {e["name"]: e["ts"] for e in profiler._events
              if e["ph"] == "B"}
    assert abs(ts["tb_tel"] - ts["tb_prof"]) < 60e6, ts  # same minute
    profiler.clear()


def test_engine_gauges_track_singleton():
    """Throwaway engine constructions (tests build their own instances)
    must not rebind the process gauges away from the live singleton."""
    eng = mx.engine.get()
    g = tel.registry().gauge("engine_workers")
    expected = eng.num_workers
    mx.engine.NaiveEngine()  # must not shadow the singleton's gauges
    if type(eng).__name__ == "ThreadedEngine":
        mx.engine.ThreadedEngine()
    assert g.value == expected


# ------------------------------------------------------------- disable
def test_profiler_keeps_spans_when_telemetry_disabled():
    """MXTPU_TELEMETRY=0 silences metrics, not an explicitly running
    profiler session: trace spans keep landing in the dump."""
    from mxtpu import profiler
    profiler.clear()
    profiler.set_config(mode="symbolic", filename="/tmp/unused_td.json")
    profiler.set_state("run")
    tel.set_enabled(False)
    try:
        with tel.span("disabled_but_profiled") as s:
            pass
        assert s.span_id != 0  # real span, not the null stand-in
    finally:
        tel.set_enabled(True)
        profiler.set_state("stop")
    with profiler._lock:
        names = {e["name"] for e in profiler._events}
    assert "disabled_but_profiled" in names
    profiler.clear()


def test_set_enabled_false_is_noop():
    tel.set_enabled(False)
    try:
        assert not tel.enabled()
        c = tel.counter("disabled_probe")
        c.inc(100)
        assert c.value == 0
        with tel.span("disabled_span") as s:
            assert s.span_id == 0
    finally:
        tel.set_enabled(True)
    # the real series was never created
    assert all(m.name != "disabled_probe" for m in tel.registry().series())


# ------------------------------------------------- built-in instrumentation
def _fit_once(batch_end_callback=None, epochs=1):
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    y = rng.randint(0, 4, 64).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                              name="fct"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=epochs, batch_end_callback=batch_end_callback,
            optimizer_params={"learning_rate": 0.1})
    return mod


def test_fit_emits_into_registry():
    reg = tel.registry()
    steps0 = reg.histogram("fit_step_ms").count
    samples0 = reg.counter("fit_samples").value
    io0 = reg.counter("io_batches", labels={"iter": "NDArrayIter"}).value
    _fit_once(batch_end_callback=mx.callback.Speedometer(16, frequent=2,
                                                         auto_reset=False))
    assert reg.histogram("fit_step_ms").count >= steps0 + 4
    assert reg.counter("fit_samples").value == samples0 + 64
    assert reg.gauge("fit_samples_per_sec").value > 0
    assert reg.counter("io_batches",
                       labels={"iter": "NDArrayIter"}).value > io0
    # the Speedometer rewrite emits structured series, not just log lines
    assert reg.gauge("train_samples_per_sec").value > 0
    assert reg.gauge("train_metric", labels={"metric": "accuracy"}
                     ).value >= 0.0
    # executor compile telemetry saw the program build
    assert reg.counter("executor_program_builds_total").value >= 1


def test_kvstore_push_pull_metrics():
    reg = tel.registry()
    pb0 = reg.counter("kvstore_push_bytes").value
    lb0 = reg.counter("kvstore_pull_bytes").value
    kv = mx.kv.create("local")
    a = mx.nd.ones((4, 8))
    kv.init("w", a)
    kv.push("w", mx.nd.ones((4, 8)))
    out = mx.nd.zeros((4, 8))
    kv.pull("w", out=out)
    assert reg.counter("kvstore_push_bytes").value == pb0 + 4 * 8 * 4
    assert reg.counter("kvstore_pull_bytes").value == lb0 + 4 * 8 * 4
    assert reg.histogram("kvstore_push_ms").count >= 1
    assert reg.histogram("kvstore_pull_ms").count >= 1


def test_prefetching_iter_stall_metric():
    reg = tel.registry()
    s0 = reg.histogram("io_prefetch_stall_ms").count
    X = np.arange(32, dtype="float32").reshape(8, 4)
    y = np.zeros(8, "float32")
    base = mx.io.NDArrayIter(X, y, batch_size=4)
    pf = mx.io.PrefetchingIter(base)
    batches = list(pf)
    assert len(batches) == 2
    assert reg.histogram("io_prefetch_stall_ms").count > s0
