"""Module API tests incl. tiny-model convergence (model: reference
tests/python/unittest/test_module.py + tests/python/train/test_mlp.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, sym


def _toy_data(n=512, dim=16, classes=4, seed=0):
    """Separable Gaussian blobs (converges fast -> tests optimization, not
    task difficulty)."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, dim) * 3
    y = rng.randint(0, classes, n)
    X = (centers[y] + rng.randn(n, dim)).astype("float32")
    return X, y.astype("float32")


def _mlp(classes=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_module_bind_forward():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    batch = mx.io.DataBatch(data=[nd.ones((8, 16))],
                            label=[nd.zeros((8,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (8, 4)


def test_module_fit_convergence():
    """MLP on separable data must reach >0.9 accuracy (parity
    tests/python/train/test_mlp.py threshold idea)."""
    mx.random.seed(7)
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=12,
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.initializer.Xavier())
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")
    assert score[0][1] > 0.9, "accuracy %f too low" % score[0][1]


def test_module_predict_and_params():
    X, y = _toy_data(n=64)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    preds = mod.predict(it)
    assert preds.shape == (64, 4)
    arg_params, aux_params = mod.get_params()
    assert "fc1_weight" in arg_params


def test_module_checkpoint(tmp_path):
    X, y = _toy_data(n=64)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer()
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    mod2 = mx.mod.Module.load(prefix, 1)
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        assert np.allclose(a1[k].asnumpy(), a2[k].asnumpy()), k


def test_module_multi_device():
    """Multi-'device' DP on CPU contexts (the reference's own trick:
    test_multi_device_exec.py uses cpu(0), cpu(1))."""
    mx.random.seed(7)
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(train, num_epoch=10, kvstore="local",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9},
            initializer=mx.initializer.Xavier())
    score = mod.score(mx.io.NDArrayIter(X, y, batch_size=32), "acc")
    assert score[0][1] > 0.85, "multi-device accuracy %f" % score[0][1]


def test_module_input_grads():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 16))],
             label_shapes=[("softmax_label", (4,))],
             inputs_need_grad=True)
    mod.init_params()
    batch = mx.io.DataBatch(data=[nd.ones((4, 16))], label=[nd.zeros((4,))])
    mod.forward(batch, is_train=True)
    mod.backward()
    ig = mod.get_input_grads()[0]
    assert ig.shape == (4, 16)


def test_bucketing_module():
    """Variable-length buckets share parameters (parity
    tests/python/train/test_bucketing.py shape)."""
    buckets = [4, 8]

    def sym_gen(seq_len):
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=8, name="fc_shared")
        net = sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer()
    for key, feat in [(8, 8), (4, 4)]:
        batch = mx.io.DataBatch(
            data=[nd.ones((4, feat))], label=[nd.zeros((4,))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", (4, feat))],
            provide_label=[mx.io.DataDesc("softmax_label", (4,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    # same parameter object across buckets
    m4 = mod._buckets[4]._exec_group.execs[0].arg_dict["fc_shared_weight"]
    m8 = mod._buckets[8]._exec_group.execs[0].arg_dict["fc_shared_weight"]
    assert m4 is not None and m8 is not None


def test_sequential_module():
    net1 = sym.FullyConnected(sym.Variable("data"), num_hidden=8, name="fc1")
    net2 = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("fc1_output"), num_hidden=4, name="fc2"), name="softmax")
    mod = mx.mod.SequentialModule()
    mod.add(mx.mod.Module(net1, label_names=None, context=mx.cpu()))
    mod.add(mx.mod.Module(net2, data_names=("fc1_output",),
                          context=mx.cpu()),
            take_labels=True, auto_wiring=True)
    mod.bind(data_shapes=[("data", (4, 16))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer()
    batch = mx.io.DataBatch(data=[nd.ones((4, 16))], label=[nd.zeros((4,))])
    mod.forward(batch, is_train=True)
    out = mod.get_outputs()[0]
    assert out.shape == (4, 4)
