"""cpp-package generated op surface (VERDICT r3 #6): op.h is generated
from the live registry (cpp-package/OpWrapperGenerator.py — the
reference's cpp-package/OpWrapperGenerator.py flow), and a C++ client
trains a conv net through the generated wrappers (reference
cpp-package/example training pattern)."""
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPI_SO = os.path.join(REPO, "mxtpu", "native", "libmxtpu_capi.so")
OP_H = os.path.join(REPO, "cpp-package", "include", "mxtpu-cpp", "op.h")


def _build():
    r = subprocess.run(["make", "-C", os.path.join(REPO, "src"), "capi"],
                       capture_output=True, text=True)
    return os.path.exists(CAPI_SO), r.stdout + r.stderr


def test_generator_is_current(tmp_path):
    """Regenerating op.h produces the committed file (all 288 ops)."""
    import shutil
    saved = OP_H + ".orig"
    shutil.copy(OP_H, saved)
    try:
        r = subprocess.run(
            ["python", os.path.join(REPO, "cpp-package",
                                    "OpWrapperGenerator.py")],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        assert r.returncode == 0, r.stdout + r.stderr
        assert "emitted" in r.stdout
        n = int(r.stdout.split("emitted")[1].split()[0])
        assert n >= 288, "op surface shrank: %d" % n
        with open(OP_H) as f_new, open(saved) as f_old:
            assert f_new.read() == f_old.read(), \
                "committed op.h is stale — rerun OpWrapperGenerator.py"
    finally:
        shutil.copy(saved, OP_H)
        os.remove(saved)


def test_cpp_conv_train(tmp_path):
    """C++ conv net via generated wrappers reaches >0.9 train accuracy."""
    ok, log = _build()
    if not ok:
        pytest.skip("libmxtpu_capi.so did not build: %s" % log[-400:])
    exe = str(tmp_path / "conv_train")
    src = os.path.join(REPO, "cpp-package", "example", "conv_train.cpp")
    r = subprocess.run(
        ["g++", "-std=c++17",
         "-I", os.path.join(REPO, "cpp-package", "include"),
         "-I", os.path.join(REPO, "src", "capi"), src, "-o", exe,
         "-L", os.path.dirname(CAPI_SO), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.dirname(CAPI_SO)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    out = subprocess.run(
        [exe, "12"], capture_output=True, text=True, timeout=600,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "IMPERATIVE OK" in out.stdout, out.stdout
    acc = float([l for l in out.stdout.splitlines()
                 if "ACCURACY" in l][0].split()[1])
    assert acc > 0.9, "C++ conv training reached only %.3f" % acc


def _cc_example(tmp_path, name):
    exe = str(tmp_path / name)
    src = os.path.join(REPO, "cpp-package", "example", name + ".cpp")
    r = subprocess.run(
        ["g++", "-std=c++17",
         "-I", os.path.join(REPO, "cpp-package", "include"),
         "-I", os.path.join(REPO, "cpp-package", "example"),
         "-I", os.path.join(REPO, "src", "capi"), src, "-o", exe,
         "-L", os.path.dirname(CAPI_SO), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.dirname(CAPI_SO)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return exe


def _run_example(exe, args=()):
    out = subprocess.run(
        [exe] + list(args), capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 PYTHONPATH=REPO), timeout=900)
    assert out.returncode == 0, out.stdout + out.stderr
    return out.stdout


def _accuracy_of(stdout):
    line = [ln for ln in stdout.splitlines() if "ACCURACY" in ln][0]
    return float(line.split()[1])


@pytest.mark.parametrize("name,floor", [("alexnet", 0.9),
                                        ("googlenet", 0.9)])
@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_cpp_example_convnets(tmp_path, name, floor):
    """Reference cpp-package conv examples (alexnet.cpp, googlenet.cpp):
    the full topologies composed through the generated op surface train
    on the quadrant task."""
    ok, log = _build()
    if not ok:
        pytest.skip("libmxtpu_capi.so did not build: %s" % log[-400:])
    acc = _accuracy_of(_run_example(_cc_example(tmp_path, name)))
    assert acc > floor, "%s reached only %.3f" % (name, acc)


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_cpp_example_char_rnn(tmp_path):
    """Reference charRNN.cpp: primitive-op LSTM LM unrolled with shared
    weights learns next-char prediction and greedy-samples text."""
    ok, log = _build()
    if not ok:
        pytest.skip("libmxtpu_capi.so did not build: %s" % log[-400:])
    out = _run_example(_cc_example(tmp_path, "char_rnn"))
    assert _accuracy_of(out) > 0.8, out
    sample = [ln for ln in out.splitlines() if ln.startswith("SAMPLE ")][0]
    assert len(sample.split(" ", 1)[1]) >= 20, out


@pytest.mark.slow  # tier-1 time budget (ROADMAP ops note, PR 7):
# heaviest non-gate tests run in the slow tier (-m slow) so the
# 870s dots-in-window metric keeps measuring the whole fast tier
def test_cpp_example_feature_extract(tmp_path):
    """Reference feature_extract flow: internal layer bound via
    GetInternals, weights transferred by name, features discriminative."""
    ok, log = _build()
    if not ok:
        pytest.skip("libmxtpu_capi.so did not build: %s" % log[-400:])
    out = _run_example(_cc_example(tmp_path, "feature_extract"))
    assert "FEATURES OK" in out, out
