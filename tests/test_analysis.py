"""mxtpu.analysis: graph-verifier pass suite (golden findings on crafted
negative fixtures + clean healthy fixtures), the sharpened infer_shape
errors, the donation-safety audit on a live module, the runtime numerics
sanitizer through Module.fit and a serving request (postmortem with
source=sanitizer), and the CI codebase lint (tools/mxtpu_lint.py —
negative rule fixtures + the repo-lints-clean tier-1 gate)."""
import json
import logging
import os
import subprocess
import sys

import numpy as np
import pytest

import mxtpu as mx
import mxtpu.symbol as S
from mxtpu import analysis
from mxtpu import diagnostics as diag
from mxtpu import telemetry as tel
from mxtpu.analysis import NumericsError
from mxtpu.models import lenet, mlp

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _fit_mlp(nan_at=None, epochs=1, n=256, batch=64):
    X = np.random.RandomState(0).rand(n, 784).astype(np.float32)
    if nan_at is not None:
        X[nan_at] = np.nan
    y = np.zeros(n, np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch,
                           label_name="softmax_label")
    mod = mx.mod.Module(mlp.get_symbol(10), context=mx.cpu(),
                        logger=logging.getLogger("quiet"))
    mod.logger.setLevel(logging.ERROR)
    mod.fit(it, num_epoch=epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05})
    return mod, it


# ----------------------------------------------------------------- framework
def test_cli_reports_registered_passes():
    """Acceptance: `python -m mxtpu.analysis` reports >=5 registered
    passes (the pass catalog)."""
    proc = subprocess.run([sys.executable, "-m", "mxtpu.analysis"],
                          capture_output=True, text=True,
                          env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    first = proc.stdout.splitlines()[0]
    n = int(first.split(":")[1].split()[0])
    assert n >= 5, proc.stdout


def test_cli_analyzes_json_graph(tmp_path):
    sym = mlp.get_symbol(10)
    g = json.loads(sym.tojson())
    g["nodes"].append({"op": "relu", "name": "orphan_relu",
                       "inputs": [[0, 0, 0]]})
    path = tmp_path / "model.json"
    path.write_text(json.dumps(g))
    proc = subprocess.run(
        [sys.executable, "-m", "mxtpu.analysis", str(path),
         "--shape", "data=64,784", "--json"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["counts"]["error"] == 0
    assert any(f["pass"] == "dead_code" and f.get("node") == "orphan_relu"
               for f in report["findings"]), report


def test_list_passes_has_expected_suite():
    names = [n for n, _ in analysis.list_passes()]
    for want in ("shape_infer", "dead_code", "name_collision", "ctx_groups",
                 "donation", "numerics"):
        assert want in names
    assert len(names) >= 5


# --------------------------------------------------------------- shape_infer
def test_shape_pass_missing_input_provenance():
    r = mlp.get_symbol(10).lint()
    errs = [f for f in r.by_pass("shape_infer")
            if f.severity == analysis.ERROR]
    assert errs, r.render()
    first = errs[0]
    assert "data" in first.provenance
    assert "data" in (first.fix_hint or "")
    assert "partial_shapes" in first.details


def test_shape_pass_clean_on_healthy_fixtures():
    assert mlp.get_symbol(10).lint(data=(64, 784)).ok
    assert lenet.get_symbol(10).lint(data=(8, 1, 28, 28)).ok


def test_shape_pass_reports_op_failure():
    a = S.Variable("a", shape=(2, 3))
    b = S.Variable("b", shape=(4, 5))
    bad = S.broadcast_add(a, b)
    r = bad.lint()
    errs = r.by_pass("shape_infer")
    assert errs and errs[0].severity == analysis.ERROR
    assert "inference failed" in errs[0].message


def test_sharpened_infer_shape_insufficient_error():
    """Satellite: symbol.py:520's bare 'insufficient information' now
    reports the arg->node provenance path and the partial shape dict."""
    sym = mlp.get_symbol(10)
    with pytest.raises(mx.MXNetError) as ei:
        sym.infer_shape(fc3_bias=(10,))
    msg = str(ei.value)
    assert "insufficient information" in msg
    assert "provenance" in msg and "data" in msg
    assert "inferred so far" in msg
    assert "fc3_bias=(10,)" in msg  # the partially-inferred dict


def test_sharpened_unresolved_argument_error():
    """Satellite: symbol.py:346's 'cannot determine shape' names the
    consumers (or the unused-input case) and gives a hint."""
    with pytest.raises(mx.MXNetError) as ei:
        S.Variable("x").infer_shape()
    msg = str(ei.value)
    assert "cannot determine shape of argument 'x'" in msg
    assert "hint" in msg


# ----------------------------------------------------------------- dead code
def test_dead_node_detection_in_json():
    sym = mlp.get_symbol(10)
    g = json.loads(sym.tojson())
    clean = analysis.analyze_json(json.dumps(g), shapes={"data": (4, 784)})
    assert not clean.by_pass("dead_code"), clean.render()
    g["nodes"].append({"op": "relu", "name": "dead1",
                       "inputs": [[0, 0, 0]]})
    g["nodes"].append({"op": "null", "name": "dead_var", "inputs": []})
    r = analysis.analyze_json(json.dumps(g), shapes={"data": (4, 784)})
    found = {f.node: f.severity for f in r.by_pass("dead_code")}
    assert found.get("dead1") == analysis.WARNING
    assert found.get("dead_var") == analysis.INFO


def test_binding_arg_mismatch():
    sym = mlp.get_symbol(10)
    r = analysis.analyze(
        sym, shapes={"data": (4, 784)},
        args={"data", "softmax_label", "fc1_weight", "fc1_bias",
              "fc2_weight", "fc2_bias", "fc3_weight", "fc3_bias",
              "stale_extra_weight"})
    msgs = [f.message for f in r.by_pass("dead_code")]
    assert any("stale_extra_weight" in m and "no such" in m for m in msgs)
    assert not any("fc1_weight" in m for m in msgs)


def test_unconsumed_multi_output_head():
    data = S.Variable("data", shape=(4, 8))
    split = S.SliceChannel(data, num_outputs=2, name="split")
    r = split[0].lint(data=(4, 8))
    infos = r.by_pass("dead_code")
    assert infos and "output 1" in infos[0].message


# ------------------------------------------------------------ name collision
def test_name_collision_fires_and_healthy_clean():
    a = S.Variable("w")
    b = S.Variable("w")
    r = (a + b).lint(w=(2, 2))
    errs = r.by_pass("name_collision")
    assert errs and errs[0].severity == analysis.ERROR
    assert not mlp.get_symbol(10).lint(data=(4, 784)).by_pass(
        "name_collision")


# ---------------------------------------------------------------- ctx groups
def test_ctx_group_mismatch():
    with mx.AttrScope(ctx_group="stage1"):
        x = S.FullyConnected(S.Variable("data"), num_hidden=4, name="fca")
    r = x.lint(data=(2, 8), group2ctx={"stage2": mx.cpu(0)})
    by_sev = {f.severity for f in r.by_pass("ctx_groups")}
    assert analysis.WARNING in by_sev  # stage1 unmapped
    assert analysis.INFO in by_sev     # stage2 unused
    ok = x.lint(data=(2, 8), group2ctx={"stage1": mx.cpu(0)})
    assert not ok.by_pass("ctx_groups"), ok.render()


# ------------------------------------------------------------------ numerics
def test_numerics_unclamped_exp_and_softmax():
    x = S.Variable("x")
    e = S.exp(x)
    soft = e / S.sum(e)
    r = soft.lint(x=(4, 8))
    msgs = [f.message for f in r.by_pass("numerics")]
    assert any("unclamped exp" in m for m in msgs)
    assert any("hand-rolled softmax" in m for m in msgs)


def test_numerics_eps_free_division_and_guard():
    x = S.Variable("x")
    r = (x / S.sum(x)).lint(x=(4,))
    assert any("eps-free division" in f.message
               for f in r.by_pass("numerics"))
    guarded = (x / (S.sum(x) + 1e-6)).lint(x=(4,))
    assert not guarded.by_pass("numerics"), guarded.render()


def test_numerics_log_guard():
    x = S.Variable("x")
    r = S.log(x).lint(x=(4,))
    assert any("unguarded log" in f.message for f in r.by_pass("numerics"))
    ok = S.log(x + 1e-6).lint(x=(4,))
    assert not ok.by_pass("numerics"), ok.render()
    clamped = S.exp(S.clip(x, -10, 10)).lint(x=(4,))
    assert not clamped.by_pass("numerics"), clamped.render()


# ------------------------------------------------------------------ donation
def test_module_check_clean_after_fit():
    mod, _ = _fit_mlp()
    r = mod.check()
    assert not r.errors and not r.warnings, r.render()
    assert "donation" in r.passes_run


def test_donation_audit_flags_host_alias():
    mod, _ = _fit_mlp()
    mod._arg_params["fc1_weight"]._data = mod._fused.params["fc1_weight"]
    r = mod.check()
    errs = r.by_pass("donation")
    assert errs, r.render()
    assert any("aliases a buffer in the fused step's donation list"
               in f.message and f.node == "fc1_weight" for f in errs)


def test_donation_audit_flags_deleted_buffer():
    mod, it = _fit_mlp()
    stale = mod._fused.params["fc1_weight"]
    it.reset()
    batch = next(iter(it))
    mod.forward_backward(batch)  # donates `stale`
    mod._arg_params["fc1_weight"]._data = stale
    r = mod.check()
    assert any("already-deleted" in f.message
               for f in r.by_pass("donation")), r.render()


def test_fused_load_does_not_alias_host_params():
    """Regression for the hazard the audit found: device_put of an
    already-committed array returns the SAME buffer, so the fused step's
    donation used to delete the module's host _arg_params. load() now
    snapshots; the host params stay readable after a donated step."""
    mod, _ = _fit_mlp()
    for name, v in mod._arg_params.items():
        arr = np.asarray(v._data)  # raises on a deleted buffer
        assert np.isfinite(arr).all() or True  # readable is the contract


# ----------------------------------------------------------------- sanitizer
def test_sanitizer_fit_nan_postmortem():
    """Acceptance: MXTPU_SANITIZE set => an injected NaN in a fit step
    produces a structured postmortem with source=sanitizer."""
    trips0 = tel.registry().counter("sanitizer_trips",
                                    labels={"kind": "fused_step"}).value
    fit_pm0 = tel.registry().counter("diag_postmortems",
                                     labels={"source": "fit"}).value
    analysis.sanitizer_enable("nan")
    try:
        with pytest.raises(NumericsError) as ei:
            _fit_mlp(nan_at=(7, 3))
        assert "fused_step" in str(ei.value)
    finally:
        analysis.sanitizer_disable()
    pm = diag.last_postmortem()
    assert pm is not None and pm["source"] == "sanitizer"
    assert "flight" in pm and "ledger" in pm  # routed through debug_state
    assert tel.registry().counter(
        "sanitizer_trips", labels={"kind": "fused_step"}).value > trips0
    # NumericsError is an MXNetError: fit must NOT double-dump
    assert tel.registry().counter(
        "diag_postmortems", labels={"source": "fit"}).value == fit_pm0


def test_sanitizer_trip_does_not_orphan_fused_state():
    """The fused step DONATES its old state; a sanitizer trip raised
    before the step's unpack used to leave FusedState pointing at
    deleted buffers. step() must adopt the returned (NaN'd but
    readable) state from the exception — a caller that catches and
    checkpoints must not hit 'Array has been deleted'."""
    import jax
    mod, _ = _fit_mlp()            # healthy fit builds mod._fused
    fused = mod._fused
    assert fused is not None
    bad = [mx.nd.array(np.full((64, 784), np.nan, np.float32))]
    lbl = [mx.nd.array(np.zeros(64, np.float32))]
    analysis.sanitizer_enable("nan")
    try:
        with pytest.raises(NumericsError):
            fused.step(bad, lbl)
    finally:
        analysis.sanitizer_disable()
    # every state buffer must be LIVE (adopted from the exception)
    for group in (fused.state.params, fused.state.aux,
                  fused.state.opt_state):
        for leaf in jax.tree.leaves(group or {}):
            assert not leaf.is_deleted()
    # a subsequent step still dispatches (state usable, not orphaned)
    fused.step([mx.nd.array(np.random.rand(64, 784).astype(np.float32))],
               lbl)
    # and the donation audit agrees nothing is orphaned
    rep = mod.check()
    assert not [f for f in rep.by_pass("donation")
                if f.severity == analysis.ERROR], rep.render()


def test_sanitizer_env_coercion():
    """MXTPU_SANITIZE=1 (the 0/1 convention of the sibling MXTPU_* vars)
    must arm 'all', and an unrecognized value must not break import."""
    for val, expect in (("1", "all"), ("true", "all"), ("nan", "nan"),
                        ("bogus", "all"), ("0", "None"), ("off", "None")):
        proc = subprocess.run(
            [sys.executable, "-c",
             "import mxtpu.analysis as a; print(a.sanitizer_mode())"],
            capture_output=True, text=True,
            env={**os.environ, "MXTPU_SANITIZE": val,
                 "JAX_PLATFORMS": "cpu"}, cwd=ROOT)
        assert proc.returncode == 0, (val, proc.stderr)
        assert proc.stdout.strip() == expect, (val, proc.stdout)


def test_sanitizer_serving_request():
    """A NaN produced while serving fails THAT request with
    NumericsError, fires a source=sanitizer postmortem, and leaves the
    worker alive for the next (healthy) request."""
    analysis.sanitizer_enable("nan")
    sess = mx.serving.ServingSession(
        S.log(S.Variable("data")).tojson(), {}, {"data": (1, 4)},
        buckets=(1,), warmup=False)
    try:
        with pytest.raises(NumericsError):
            sess.predict({"data": -np.ones((1, 4), np.float32)}, timeout=30)
        pm = diag.last_postmortem()
        assert pm["source"] == "sanitizer"
        out = sess.predict({"data": np.ones((1, 4), np.float32)},
                           timeout=30)
        assert np.allclose(out[0], 0.0)
    finally:
        sess.close()
        analysis.sanitizer_disable()


def test_sanitizer_modes_nan_vs_inf():
    sym = S.exp(S.Variable("data"))
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array([[1000.0]])})
    analysis.sanitizer_enable("nan")
    try:
        ex.forward()  # inf, not nan: mode 'nan' must stay silent
        analysis.sanitizer_enable("inf")
        ex2 = sym.bind(mx.cpu(), {"data": mx.nd.array([[2000.0]])})
        with pytest.raises(NumericsError) as ei:
            ex2.forward()
        assert "Inf" in str(ei.value)
    finally:
        analysis.sanitizer_disable()


def test_sanitizer_disabled_is_unhooked():
    # the hook seam lives in the compile pipeline since PR 7 (the
    # executor re-exports set_output_sanitizer for compatibility)
    from mxtpu.compile import pipeline as pipe_mod
    analysis.sanitizer_enable("all")
    assert pipe_mod._OUTPUT_SANITIZER is not None
    analysis.sanitizer_disable()
    assert pipe_mod._OUTPUT_SANITIZER is None
    sym = S.log(S.Variable("data"))
    ex = sym.bind(mx.cpu(), {"data": mx.nd.array([[-1.0]])})
    out = ex.forward()  # nan flows through unchecked — no raise
    assert np.isnan(out[0].asnumpy()).all()


# ------------------------------------------------------------- codebase lint
def _lint_mod():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import mxtpu_lint
    finally:
        sys.path.pop(0)
    return mxtpu_lint


def test_codebase_lint():
    """Tier-1 CI gate: tools/mxtpu_lint.py exits 0 on the repo (hot-path
    sync pragmas present, lock hierarchy respected, threads managed)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "mxtpu_lint.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lint_host_sync_rule_and_pragma():
    lint = _lint_mod()
    src = "def f(x):\n    return x.asnumpy()\n"
    assert [f.rule for f in lint.lint_source(src, "mxtpu/engine.py")] \
        == ["host-sync"]
    # same code outside a declared hot path: silent
    assert not lint.lint_source(src, "mxtpu/visualization.py")
    ok = "def f(x):\n    # mxtpu: allow-sync(test)\n    return x.asnumpy()\n"
    assert not lint.lint_source(ok, "mxtpu/engine.py")
    scalar = "def f(x):\n    return float(x.sum())\n"
    assert [f.rule for f in lint.lint_source(scalar, "mxtpu/executor.py")] \
        == ["host-sync"]


def test_lint_metric_scope_restriction():
    lint = _lint_mod()
    hot = ("class DeviceMetricAccum:\n"
           "    def f(self, x):\n        return x.asnumpy()\n")
    cold = ("class Accuracy:\n"
            "    def f(self, x):\n        return x.asnumpy()\n")
    assert lint.lint_source(hot, "mxtpu/metric.py")
    assert not lint.lint_source(cold, "mxtpu/metric.py")


def test_lint_lock_order_rule():
    lint = _lint_mod()
    bad = ("class DeviceMemoryLedger:\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            with _PM_LOCK:\n                pass\n")
    founds = lint.lint_source(bad, "mxtpu/diagnostics/ledger.py")
    assert [f.rule for f in founds] == ["lock-order"], founds
    ok = ("class DeviceMemoryLedger:\n"
          "    def f(self):\n"
          "        with _PM_LOCK:\n"
          "            with self._lock:\n                pass\n")
    assert not lint.lint_source(ok, "mxtpu/diagnostics/ledger.py")


def test_lint_thread_lifecycle_rule():
    lint = _lint_mod()
    bad = ("import threading\n"
           "def f():\n    threading.Thread(target=f).start()\n")
    assert [f.rule for f in lint.lint_source(bad, "mxtpu/foo.py")] \
        == ["thread-lifecycle"]
    daemon = ("import threading\n"
              "def f():\n"
              "    threading.Thread(target=f, daemon=True).start()\n")
    assert not lint.lint_source(daemon, "mxtpu/foo.py")
    joined = ("import threading\n"
              "class W:\n"
              "    def start(self):\n"
              "        self.t = threading.Thread(target=self.run)\n"
              "    def close(self):\n        self.t.join()\n")
    assert not lint.lint_source(joined, "mxtpu/foo.py")
    # regression: os.path.join / ", ".join are NOT thread joins — they
    # used to suppress the rule for nearly every module in the repo
    path_join = ("import os, threading\n"
                 "P = os.path.join('a', 'b')\n"
                 "S = ', '.join(['x'])\n"
                 "def f():\n    threading.Thread(target=f).start()\n")
    assert [f.rule for f in lint.lint_source(path_join, "mxtpu/foo.py")] \
        == ["thread-lifecycle"]
    # a join that appears BEFORE the ctor in the file still counts
    join_first = ("import threading\n"
                  "class W:\n"
                  "    def close(self):\n        self.t.join()\n"
                  "    def start(self):\n"
                  "        self.t = threading.Thread(target=self.run)\n")
    assert not lint.lint_source(join_first, "mxtpu/foo.py")


def test_lint_swallowed_exception_rule():
    lint = _lint_mod()
    # except: pass and except Exception: pass on a hot path are findings
    bare = "def f():\n    try:\n        g()\n    except:\n        pass\n"
    assert [f.rule for f in lint.lint_source(bare, "mxtpu/engine.py")] \
        == ["swallowed-exception"]
    broad = ("def f():\n    try:\n        g()\n"
             "    except Exception:\n        pass\n")
    assert [f.rule for f in lint.lint_source(broad, "mxtpu/engine.py")] \
        == ["swallowed-exception"]
    # log-and-continue without counter/re-raise is still a swallow
    logcont = ("def f():\n    for i in x:\n        try:\n            g()\n"
               "        except Exception:\n"
               "            log.warning('oops')\n            continue\n")
    assert [f.rule for f in lint.lint_source(logcont, "mxtpu/engine.py")] \
        == ["swallowed-exception"]
    # NOT findings: narrow catch, re-raise, counter, real fallback work
    narrow = ("def f():\n    try:\n        g()\n"
              "    except OSError:\n        pass\n")
    assert not lint.lint_source(narrow, "mxtpu/engine.py")
    reraise = ("def f():\n    try:\n        g()\n"
               "    except Exception:\n        log.error('x')\n"
               "        raise\n")
    assert not lint.lint_source(reraise, "mxtpu/engine.py")
    counted = ("def f():\n    try:\n        g()\n"
               "    except Exception:\n"
               "        _tel.counter('errs').inc()\n")
    assert not lint.lint_source(counted, "mxtpu/engine.py")
    fallback = ("def f():\n    try:\n        return g()\n"
                "    except Exception:\n        return None\n")
    assert not lint.lint_source(fallback, "mxtpu/engine.py")
    # pragma'd (on the body line) and cold-path code are silent
    pragma = ("def f():\n    try:\n        g()\n"
              "    except Exception:\n"
              "        pass  # mxtpu: allow-swallow(test)\n")
    assert not lint.lint_source(pragma, "mxtpu/engine.py")
    assert not lint.lint_source(bare, "mxtpu/visualization.py")


def test_lint_transform_algebra_rule():
    """Registry completeness (ISSUE 20): a registered TransformPass
    without a declared rewrite algebra — or a catalog pass missing from
    CANONICAL_ORDER — is a lint error."""
    lint = _lint_mod()
    bare = ("@register_transform\n"
            "class MyPass(TransformPass):\n"
            "    name = \"my_pass\"\n")
    assert [f.rule for f in lint.lint_source(
        bare, "mxtpu/analysis/rewrite.py")] == ["transform-algebra"]
    declared = bare + "    algebra = \"annotation_only\"\n"
    assert not lint.lint_source(declared, "mxtpu/analysis/rewrite.py")
    # the pragma escape (a deliberate certify-refused experiment)
    pragma = ("@register_transform\n"
              "class MyPass(TransformPass):  "
              "# mxtpu: allow-algebra(experiment)\n"
              "    name = \"my_pass\"\n")
    assert not lint.lint_source(pragma, "mxtpu/analysis/rewrite.py")
    # decorator spellings all count as registration
    spelled = ("@rewrite.register_transform\n"
               "class P(TransformPass):\n    name = \"p\"\n")
    assert [f.rule for f in lint.lint_source(
        spelled, "mxtpu/analysis/rewrite.py")] == ["transform-algebra"]
    # a declared catalog pass absent from CANONICAL_ORDER is an error...
    drifted = ("CANONICAL_ORDER = (\"other\",)\n"
               "@register_transform\n"
               "class P(TransformPass):\n"
               "    name = \"p\"\n"
               "    algebra = \"annotation_only\"\n")
    founds = lint.lint_source(drifted, "mxtpu/analysis/rewrite.py")
    assert [f.rule for f in founds] == ["transform-algebra",
                                       "transform-algebra"], founds
    assert any("CANONICAL_ORDER" in f.message for f in founds)
    # ... and so is a CANONICAL_ORDER name with no registered class
    assert any("names 'other'" in f.message for f in founds)
    synced = ("CANONICAL_ORDER = (\"p\",)\n"
              "@register_transform\n"
              "class P(TransformPass):\n"
              "    name = \"p\"\n"
              "    algebra = \"annotation_only\"\n")
    assert not lint.lint_source(synced, "mxtpu/analysis/rewrite.py")
    # the live catalog file must lint clean (registry complete)
    path = os.path.join(ROOT, "mxtpu", "analysis", "rewrite.py")
    with open(path) as fh:
        src = fh.read()
    assert not [f for f in lint.lint_source(src,
                                            "mxtpu/analysis/rewrite.py")
                if f.rule == "transform-algebra"]
