"""mxtpu.compile + mxtpu.analysis v2: the dataflow-analysis engine
(precision-flow, liveness), the transform-pass pipeline seam carved out
of executor.py, and the bf16 mixed-precision rewrite behind it.

Acceptance gates:
* parity — a bf16-rewritten mlp/lenet fit matches the f32 fit (integer
  metrics exact-or-gated, ce within documented tolerance, master
  weights stay f32);
* safety — every transformed graph re-passes the full verifier suite
  before compile, and a transform that violates a verifier pass is
  REJECTED with the offending Finding and the build falls back to the
  unrewritten graph;
* seam — with the pipeline empty the executor build path is
  byte-identical in behavior (existing dispatch/AOT/demotion tests in
  test_diagnostics.py keep covering the instrumentation that moved).
"""
import logging
import os
import sys

import numpy as np
import pytest

import mxtpu as mx
import mxtpu.symbol as S
from mxtpu import analysis
from mxtpu import diagnostics as diag
from mxtpu.analysis import dataflow, rewrite
from mxtpu.compile import pipeline
from mxtpu.models import lenet, mlp

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _fit(symbol, names, n=256, dim=784, classes=10, batch=64, epochs=2,
         seed=7, image=False):
    rng = np.random.RandomState(0)
    if image:
        X = rng.rand(n, 1, 28, 28).astype(np.float32)
    else:
        X = rng.rand(n, dim).astype(np.float32)
    y = np.random.RandomState(1).randint(0, classes, n).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch,
                           label_name="softmax_label")
    mod = mx.mod.Module(symbol, context=mx.cpu(),
                        logger=logging.getLogger("quiet"))
    mod.logger.setLevel(logging.ERROR)
    metric = mx.metric.create(["acc", "ce"])
    with pipeline.pipeline_scope(names):
        mx.random.seed(seed)
        np.random.seed(seed)
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1},
                eval_metric=metric)
    args, _ = mod.get_params()
    vals = dict(zip(*metric.get()))
    return mod, {k: v.asnumpy() for k, v in args.items()}, vals


# ------------------------------------------------------------ dataflow engine
def test_precision_flow_classifies_mlp():
    sym = mlp.get_symbol(10)
    plan = dataflow.precision_flow(sym, shapes={"data": (64, 784)})
    by_name = {n.name: plan.classes[id(n)] for n in sym._topo()
               if not n.is_variable}
    # matmul compute and its elementwise followers are bf16-safe
    for node in ("fc1", "relu1", "fc2", "relu2", "fc3"):
        assert by_name[node] == dataflow.BF16_SAFE, (node, by_name)
    # the loss head is an f32 island
    assert by_name["softmax"] == dataflow.F32_ISLAND
    # every FC weight/bias demands a master copy
    for p in ("fc1_weight", "fc1_bias", "fc2_weight", "fc3_weight"):
        assert plan.var_class[p] == dataflow.MASTER_WEIGHT
    # data feeds a bf16 node too (cast at use), label does not
    assert plan.var_class["softmax_label"] == dataflow.F32_ISLAND


def test_precision_flow_islands_norm_and_explog():
    data = S.Variable("data")
    conv = S.Convolution(data, kernel=(3, 3), num_filter=8, name="conv")
    bn = S.BatchNorm(conv, name="bn")
    act = S.Activation(bn, act_type="relu", name="act")
    e = S.exp(act, name="e")
    plan = dataflow.precision_flow(
        S.Group([e]), shapes={"data": (2, 3, 8, 8)})
    by_name = {n.name: plan.classes[id(n)] for n in e._topo()
               if not n.is_variable}
    assert by_name["conv"] == dataflow.BF16_SAFE
    assert by_name["bn"] == dataflow.F32_ISLAND   # normalization stats
    assert by_name["e"] == dataflow.F32_ISLAND    # exp overflows in bf16
    # the relu between two islands follows its f32 producer
    assert by_name["act"] == dataflow.F32_ISLAND


def test_precision_flow_reasons_and_findings():
    sym = mlp.get_symbol(10)
    plan = dataflow.precision_flow(sym, shapes={"data": (64, 784)})
    findings = plan.to_findings()
    assert all(f.severity == analysis.INFO for f in findings)
    fc1 = [f for f in findings if f.node == "fc1"]
    assert fc1 and "bf16-safe" in fc1[0].message
    assert "matmul" in fc1[0].message


def test_liveness_last_use_and_peak():
    sym = mlp.get_symbol(10)
    info = dataflow.liveness(sym, shapes={"data": (64, 784)})
    assert info.complete
    # the head stays live to the end; its bytes are known exactly
    assert info.head_bytes == 64 * 10 * 4
    assert info.peak_live_bytes > 0
    # fc1's activation (64x128 f32) must die before the walk ends:
    # its last use is relu1, not the head
    topo = sym._topo()
    idx = {n.name: i for i, n in enumerate(topo)}
    fc1 = [n for n in topo if n.name == "fc1"][0]
    assert info.last_use[(id(fc1), 0)] == idx["relu1"]
    assert info.last_use[(id(fc1), 0)] < len(topo)


def test_liveness_cross_checks_executor_ledger():
    """The live-set at the end of the walk is exactly the graph outputs,
    and the ledger's executor_outputs slot accounts those same buffers —
    the dataflow estimate and the runtime slot model must agree."""
    sym = mlp.get_symbol(10)
    ex = sym.simple_bind(ctx=mx.cpu(), data=(8, 784))
    ex.forward(is_train=False,
               data=mx.nd.array(np.zeros((8, 784), np.float32)))
    findings = dataflow.liveness_ledger_check(ex)
    assert findings == [], [f.render() for f in findings]


# ------------------------------------------------------- pipeline seam/config
def test_pipeline_empty_is_default_and_identity():
    assert pipeline.configured() == ()
    sym = mlp.get_symbol(10)
    sym2, rep = pipeline.transform_graph(sym, kind="test")
    assert sym2 is sym
    assert not rep.symbol_changed and rep.entries == []


def test_pipeline_scope_and_env_reset():
    with pipeline.pipeline_scope(["bf16"]):
        assert pipeline.configured() == ("bf16",)
        with pipeline.pipeline_scope([]):
            assert pipeline.configured() == ()
    assert pipeline.configured() == ()


def test_executor_program_builds_unchanged_with_empty_pipeline():
    """Seam acceptance: the executor's build path routed through
    mxtpu/compile/pipeline.py must not change observable build behavior
    when the pipeline is empty."""
    sym = mlp.get_symbol(10)
    ex = sym.simple_bind(ctx=mx.cpu(), data=(8, 784))
    before = mx.executor.program_build_count()
    ex.forward(is_train=False,
               data=mx.nd.array(np.zeros((8, 784), np.float32)))
    assert mx.executor.program_build_count() == before + 1
    ex.forward(is_train=False,
               data=mx.nd.array(np.ones((8, 784), np.float32)))
    assert mx.executor.program_build_count() == before + 1  # cache hit


def test_transform_registry_lists_bf16():
    names = [n for n, _ in rewrite.list_transforms()]
    assert "bf16" in names
    with pytest.raises(mx.MXNetError):
        rewrite.get_transform("no_such_transform")


# ------------------------------------------------------------- bf16 rewrite
def test_bf16_rewrite_graph_structure():
    sym = mlp.get_symbol(10)
    sym2, rep = pipeline.transform_graph(
        sym, kind="test", shapes={"data": (64, 784)}, passes=["bf16"])
    assert rep.symbol_changed and rep.applied == ["bf16"]
    # arguments/aux unchanged: checkpoints and bind dicts still fit
    assert sym2.list_arguments() == sym.list_arguments()
    assert sym2.list_outputs() == sym.list_outputs()
    # output dtype contract preserved (head cast back to f32)
    _, out_types, _ = sym2.infer_type(data="float32")
    assert out_types == [np.dtype("float32")]
    # weights are cast at use: a Cast node feeds each FullyConnected
    dbg = sym2.debug_str()
    assert "fc1_weight_bf16_amp" in dbg and "fc3_f32_amp" in dbg
    # the transformed graph re-passes the verifier suite under the same
    # (enriched) hints every bound consumer has: a Cast between weight
    # and FC blocks the top-down infer_args backfill, so the pipeline
    # pins variables to what the unrewritten graph proved about them
    arg_shapes, _, _ = sym.infer_shape(data=(64, 784))
    hints = dict(zip(sym.list_arguments(), arg_shapes))
    assert not sym2.lint(shapes=hints).errors


def test_bf16_rewrite_reports_per_node_provenance():
    sym = mlp.get_symbol(10)
    report = sym.lint(data=(64, 784), pipeline="bf16")
    msgs = [f for f in report if f.pass_name == "bf16"]
    assert msgs, report.render()
    fc1 = [f for f in msgs if f.node == "fc1"]
    assert fc1 and "computes in bf16" in fc1[0].message
    assert "fc1_weight" in fc1[0].provenance
    applied = [f for f in report if f.pass_name == "pipeline"]
    assert applied and "applied" in applied[0].message


def test_bf16_skips_graph_with_no_compute():
    sym = S.exp(S.Variable("data"), name="e")
    sym2, rep = pipeline.transform_graph(
        sym, kind="test", shapes={"data": (4, 4)}, passes=["bf16"])
    assert sym2 is sym and rep.applied == []
    acts = rep.entries[0]["actions"]
    assert any("rewrite skipped" in f.message for f in acts)


# --------------------------------------------------------- rejection path
class _BreakingPass(rewrite.TransformPass):
    """Deliberately unsound transform: duplicates the head node under a
    name that collides with an existing node — the name_collision
    verifier must reject it."""

    name = "_test_breaker"

    def run(self, tctx):
        from mxtpu.symbol.symbol import Symbol, _Node
        head, idx = tctx.symbol._outputs[0]
        clash = None
        for n in tctx.symbol._topo():
            if not n.is_variable and n is not head:
                clash = n
                break
        dup = _Node(head.op, clash.name, dict(head.attrs),
                    list(head.inputs))
        self.action(tctx, "duplicated head under colliding name '%s'"
                    % clash.name)
        return Symbol([(dup, idx)])


def test_rejected_rewrite_surfaces_finding_and_falls_back():
    rewrite._TRANSFORMS.setdefault("_test_breaker", _BreakingPass())
    try:
        sym = mlp.get_symbol(10)
        sym2, rep = pipeline.transform_graph(
            sym, kind="test", shapes={"data": (64, 784)},
            passes=["_test_breaker"])
        # fallback: the unrewritten graph is returned
        assert sym2 is sym
        assert rep.rejected == ["_test_breaker"] and rep.applied == []
        offending = rep.entries[0]["offending"]
        assert offending, rep.render()
        assert offending[0].pass_name == "name_collision"
        assert offending[0].severity == analysis.ERROR
        # the report surface shows the rejection with the Finding
        fs = rep.findings()
        assert any("REJECTED" in f.message and "name_collision"
                   in f.message for f in fs)
    finally:
        rewrite._TRANSFORMS.pop("_test_breaker", None)


def test_rejected_rewrite_fit_still_trains():
    """End to end: a rejected transform must not break training — the
    fused step silently builds from the unrewritten graph."""
    rewrite._TRANSFORMS.setdefault("_test_breaker", _BreakingPass())
    try:
        mod, w, vals = _fit(mlp.get_symbol(10), ["_test_breaker"],
                            epochs=1)
        assert mod._fused is not None
        assert mod._fused.pipeline_report.rejected == ["_test_breaker"]
        assert mod._fused._graph_symbol is mod._fused.symbol
        assert np.isfinite(vals["cross-entropy"])
    finally:
        rewrite._TRANSFORMS.pop("_test_breaker", None)


def test_crashing_transform_is_skipped_not_fatal():
    class _Crasher(rewrite.TransformPass):
        name = "_test_crasher"

        def run(self, tctx):
            raise RuntimeError("boom")

    rewrite._TRANSFORMS.setdefault("_test_crasher", _Crasher())
    try:
        sym = mlp.get_symbol(10)
        sym2, rep = pipeline.transform_graph(
            sym, kind="test", shapes={"data": (64, 784)},
            passes=["_test_crasher", "bf16"])
        assert rep.entries[0]["error"] is not None
        assert rep.applied == ["bf16"] and rep.symbol_changed
        assert sym2 is not sym
    finally:
        rewrite._TRANSFORMS.pop("_test_crasher", None)


# ------------------------------------------------------------- parity gates
@pytest.mark.parametrize("model,kw", [
    ("mlp", {}),
    ("lenet", {"image": True}),
])
def test_bf16_parity_gate(model, kw):
    """THE acceptance gate: bf16-rewritten fit vs f32 fit on the same
    data/seed. Integer-summed metrics (accuracy counts) exact or within
    the documented gate; ce within tolerance; master weights f32 and
    within the quantization-drift envelope."""
    get = mlp.get_symbol if model == "mlp" else lenet.get_symbol
    _, w32, v32 = _fit(get(10), [], **kw)
    mod, wbf, vbf = _fit(get(10), ["bf16"], **kw)
    # the fused step really built from the rewritten graph
    assert mod._fused is not None
    assert mod._fused.pipeline_report.applied == ["bf16"]
    assert mod._fused._graph_symbol is not mod._fused.symbol
    # master weights stay f32 on device
    for name, leaf in mod._fused.params.items():
        assert str(leaf.dtype) == "float32", (name, leaf.dtype)
    for name, st in mod._fused.opt_state.items():
        import jax
        for leaf in jax.tree.leaves(st):
            assert str(leaf.dtype) == "float32", (name, leaf.dtype)
    # integer metric: accuracy over 256 samples — exact-or-gated at
    # one reclassified sample per 128 (bf16 forward can flip an argmax
    # that sits on a decision boundary)
    assert abs(v32["accuracy"] - vbf["accuracy"]) <= 2 / 256.0, \
        (v32, vbf)
    # ce within documented tolerance (docs/compile.md): bf16 activations
    # carry ~3 decimal digits; after softmax the loss agrees to ~1e-2
    assert abs(v32["cross-entropy"] - vbf["cross-entropy"]) < 1e-2, \
        (v32, vbf)
    # weights drift only by accumulated quantized-gradient deltas
    for k in w32:
        assert np.max(np.abs(w32[k] - wbf[k])) < 5e-3, k


def test_bf16_program_record_tagged():
    diag.programs  # module import sanity
    _fit(mlp.get_symbol(10), ["bf16"], epochs=1)
    recs = diag.programs("fused_step")
    assert recs, "fused_step program not captured"
    assert recs[-1]["precision"] == "mixed_bf16"
    table = diag.program_table("fused_step")
    assert "prec" in table.splitlines()[0]
    assert "mixed_bf16" in table


def test_module_check_reports_pipeline():
    X = np.zeros((64, 784), np.float32)
    y = np.zeros(64, np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=64,
                           label_name="softmax_label")
    mod = mx.mod.Module(mlp.get_symbol(10), context=mx.cpu(),
                        logger=logging.getLogger("quiet"))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    report = mod.check(pipeline="bf16")
    assert any(f.pass_name == "bf16" for f in report)
    assert any(f.pass_name == "pipeline" and "applied" in f.message
               for f in report)


# ------------------------------------------------------ sanitizer interplay
def test_sanitizer_bf16_fused_step_trips_and_adopts_state():
    """Satellite gate: a bf16-rewritten fused step under MXTPU_SANITIZE
    still trips on injected NaN, the postmortem names the precision
    mode, and the module's state holds readable (non-donated) buffers
    afterwards."""
    X = np.random.RandomState(0).rand(128, 784).astype(np.float32)
    X[70] = np.nan
    y = np.zeros(128, np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=64,
                           label_name="softmax_label")
    mod = mx.mod.Module(mlp.get_symbol(10), context=mx.cpu(),
                        logger=logging.getLogger("quiet"))
    mod.logger.setLevel(logging.CRITICAL)
    analysis.sanitizer_enable("nan")
    try:
        with pipeline.pipeline_scope(["bf16"]):
            with pytest.raises(analysis.NumericsError) as ei:
                mod.fit(it, num_epoch=1, optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1})
    finally:
        analysis.sanitizer_disable()
    assert "precision=" in str(ei.value)
    assert "bf16" in str(ei.value)  # pipeline mode reported
    pm = diag.last_postmortem()
    assert pm is not None and pm["source"] == "sanitizer"
    # donation recovery: the fused state was adopted from the failed
    # step — every leaf is readable, none deleted
    import jax
    for leaf in jax.tree.leaves((mod._fused.params, mod._fused.aux,
                                 mod._fused.opt_state)):
        assert not leaf.is_deleted()


def test_sanitizer_flag_reduce_upcasts_bf16():
    """The flag-reduce must classify bf16 values correctly (upcast to
    f32 before isnan/isinf) — a bf16 NaN trips, a large-but-finite bf16
    value does not."""
    import jax.numpy as jnp
    analysis.sanitizer_enable("all")
    try:
        ok = jnp.asarray([3e38], jnp.bfloat16)  # finite in bf16
        analysis.sanitize_tree("probe", [ok])   # must not raise
        bad = jnp.asarray([np.nan], jnp.bfloat16)
        with pytest.raises(analysis.NumericsError) as ei:
            analysis.sanitize_tree("probe", [bad])
        assert "precision=bf16" in str(ei.value)
    finally:
        analysis.sanitizer_disable()


# ------------------------------------------------------------- codebase lint
def test_f64_lint_rule_units():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from mxtpu_lint import lint_source
    finally:
        sys.path.pop(0)
    src = (
        "import numpy as np\n"
        "class Hot:\n"
        "    def f(self):\n"
        "        a = np.zeros(5)\n"                       # flagged
        "        b = np.array([0.5])\n"                   # flagged (+sync)
        "        c = np.float64(3)\n"                     # flagged
        "        d = np.zeros(5, np.float32)\n"           # ok: positional
        "        # mxtpu: allow-f64(test fixture)\n"
        "        e = np.ones(9)\n"                        # pragma'd
        "        f = np.asarray([1, 2])\n"                # ok: int literals
        "        g = np.empty(3, dtype=np.float32)\n"     # ok: dtype kw
    )
    found = [f for f in lint_source(src, "mxtpu/executor.py")
             if f.rule == "f64-promotion"]
    assert [f.line for f in found] == [4, 5, 6], found
    # not-hot modules are exempt
    assert [f for f in lint_source(src, "mxtpu/unlisted.py")
            if f.rule == "f64-promotion"] == []


def test_moved_build_lock_still_in_declared_hierarchy():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        from mxtpu_lint import _LOCK_RANK, HOT_PATHS
    finally:
        sys.path.pop(0)
    assert ("pipeline", "_BUILD_LOCK") in _LOCK_RANK
    assert "mxtpu/compile/pipeline.py" in HOT_PATHS
