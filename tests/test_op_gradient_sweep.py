"""Registry-wide gradient trust chain: every registered op is either
finite-difference gradient-checked here, or explicitly skipped with a
reason (non-differentiable output, random, exact-value-tested elsewhere).

Model: the reference's per-op finite-difference oracles
(python/mxnet/test_utils.py:758 check_numeric_gradient, used throughout
tests/python/unittest/test_operator.py). The census test at the bottom
enforces that newly registered ops cannot dodge classification.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, sym
from mxtpu.ops.registry import _OPS
from mxtpu.test_utils import (check_numeric_gradient,
                              check_symbolic_backward)

_RNG = np.random.RandomState(42)


def _rand(shape, lo=-1.0, hi=1.0, away_zero=0.0):
    x = _RNG.uniform(lo, hi, size=shape).astype("float32")
    if away_zero:
        x = np.where(np.abs(x) < away_zero,
                     np.sign(x + 1e-12) * away_zero, x)
    return x


S = (3, 4)          # default small dense shape (12 elements -> fast FD)
S4 = (1, 2, 4, 4)   # default NCHW shape

# ---------------------------------------------------------------------------
# unary ops checkable as-is; value = input domain (lo, hi, away_zero)
UNARY = {
    "abs": (-1, 1, 0.1), "arccos": (-0.9, 0.9, 0), "arccosh": (1.2, 3, 0),
    "arcsin": (-0.9, 0.9, 0), "arcsinh": (-2, 2, 0), "arctan": (-2, 2, 0),
    "arctanh": (-0.9, 0.9, 0), "cbrt": (0.2, 2, 0), "cos": (-2, 2, 0),
    "cosh": (-2, 2, 0), "degrees": (-2, 2, 0), "erf": (-2, 2, 0),
    "exp": (-1, 1, 0), "expm1": (-1, 1, 0), "gamma": (1.2, 3, 0),
    "gammaln": (1.2, 3, 0), "identity": (-1, 1, 0), "_copy": (-1, 1, 0),
    "log": (0.2, 3, 0), "log10": (0.2, 3, 0), "log1p": (-0.4, 2, 0),
    "log2": (0.2, 3, 0), "log_softmax": (-2, 2, 0), "negative": (-1, 1, 0),
    "radians": (-2, 2, 0), "rcbrt": (0.3, 2, 0), "reciprocal": (0.3, 2, 0),
    "relu": (-1, 1, 0.05), "rsqrt": (0.3, 2, 0), "sigmoid": (-2, 2, 0),
    "sin": (-2, 2, 0), "sinh": (-2, 2, 0), "smooth_l1": (-2, 2, 0.1),
    "softmax": (-2, 2, 0), "softsign": (-2, 2, 0.05), "sqrt": (0.2, 2, 0),
    "square": (-2, 2, 0), "tan": (-1, 1, 0.05), "tanh": (-2, 2, 0),
    "Flatten": (-1, 1, 0), "BlockGrad": (-1, 1, 0),  # zero-grad special-cased
    "SoftmaxActivation": (-2, 2, 0), "make_loss": (-1, 1, 0),
}

# binary lhs/rhs elemwise & broadcast ops; value = (lhs domain, rhs domain)
POS = (0.3, 2, 0)
ANY = (-1, 1, 0.2)
BINARY = {
    "elemwise_add": (ANY, ANY), "elemwise_sub": (ANY, ANY),
    "elemwise_mul": (ANY, ANY), "elemwise_div": (ANY, POS),
    "_grad_add": (ANY, ANY), "_hypot": (ANY, ANY), "_power": (POS, ANY),
    "_maximum": (ANY, ANY), "_minimum": (ANY, ANY),
    "broadcast_add": (ANY, ANY), "broadcast_plus": (ANY, ANY),
    "broadcast_sub": (ANY, ANY), "broadcast_minus": (ANY, ANY),
    "broadcast_mul": (ANY, ANY), "broadcast_div": (ANY, POS),
    "broadcast_power": (POS, ANY), "broadcast_hypot": (ANY, ANY),
    "broadcast_maximum": (ANY, ANY), "broadcast_minimum": (ANY, ANY),
    "dot": (ANY, ANY), "batch_dot": (ANY, ANY),
}

# scalar-attr unary arithmetic; value = (domain, attrs)
SCALAR = {
    "_plus_scalar": (ANY, {"scalar": 0.7}),
    "_minus_scalar": (ANY, {"scalar": 0.7}),
    "_rminus_scalar": (ANY, {"scalar": 0.7}),
    "_mul_scalar": (ANY, {"scalar": 0.7}),
    "_div_scalar": (ANY, {"scalar": 0.7}),
    "_rdiv_scalar": (POS, {"scalar": 0.7}),
    "_power_scalar": (POS, {"scalar": 1.7}),
    "_rpower_scalar": (ANY, {"scalar": 1.7}),
    "_hypot_scalar": (ANY, {"scalar": 0.7}),
    "_maximum_scalar": ((-1, 1, 0.1), {"scalar": 0.0}),
    "_minimum_scalar": ((-1, 1, 0.1), {"scalar": 0.0}),
    "clip": ((-2, 2, 0.15), {"a_min": -1.0, "a_max": 1.0}),
}

# structured ops: name -> dict(build=..., location=..., grad_nodes=...,
# attrs passed to the sym composer; primary shapes drive infer_shape)
SPECS = {
    "FullyConnected": dict(primary={"data": S}, attrs={"num_hidden": 5}),
    "Convolution": dict(primary={"data": (1, 2, 5, 5)},
                        attrs={"kernel": (3, 3), "num_filter": 2}),
    "Deconvolution": dict(primary={"data": (1, 2, 4, 4)},
                          attrs={"kernel": (2, 2), "num_filter": 2}),
    "Pooling": dict(primary={"data": S4},
                    attrs={"kernel": (2, 2), "stride": (2, 2),
                           "pool_type": "max"}),
    "Pooling_avg": dict(op="Pooling", primary={"data": S4},
                        attrs={"kernel": (2, 2), "stride": (2, 2),
                               "pool_type": "avg"}),
    "BatchNorm": dict(primary={"data": S4},
                      attrs={"fix_gamma": False, "use_global_stats": True},
                      aux="bn"),  # filled by suffix in the driver
    "InstanceNorm": dict(primary={"data": S4}),
    "LayerNorm": dict(primary={"data": S}),
    "L2Normalization": dict(primary={"data": S}),
    "LRN": dict(primary={"data": S4}, attrs={"nsize": 3}),
    "Activation": dict(primary={"data": S}, attrs={"act_type": "tanh"}),
    "LeakyReLU": dict(primary={"data": S},
                      attrs={"act_type": "leaky", "slope": 0.3},
                      domain=(-1, 1, 0.1)),
    "Embedding": dict(primary={"data": (2, 3)},
                      attrs={"input_dim": 6, "output_dim": 4},
                      int_inputs={"data": (0, 6)}, grad_nodes=["weight"]),
    "Concat": dict(op="Concat", nvar=2, primary={"arg0": S, "arg1": S},
                   attrs={"dim": 1}),
    "add_n": dict(op="add_n", nvar=2, primary={"arg0": S, "arg1": S}),
    "stack": dict(op="stack", nvar=2, primary={"arg0": S, "arg1": S}),
    "khatri_rao": dict(op="khatri_rao", nvar=2,  # row-wise: shared dim0
                       primary={"arg0": (3, 2), "arg1": (3, 4)}),
    "scatter_nd": dict(primary={"data": (4,), "indices": (1, 4)},
                       attrs={"shape": (6,)}, grad_nodes=["data"],
                       int_inputs={"indices": (0, 6)}),
    "SliceChannel": dict(primary={"data": (2, 4)},
                         attrs={"num_outputs": 2, "axis": 1}),
    "Reshape": dict(primary={"data": S}, attrs={"shape": (4, 3)}),
    "reshape_like": dict(primary={"lhs": S, "rhs": (4, 3)},
                         grad_nodes=["lhs"]),
    "expand_dims": dict(primary={"data": S}, attrs={"axis": 1}),
    "transpose": dict(primary={"data": S}),
    "SwapAxis": dict(primary={"data": S}, attrs={"dim1": 0, "dim2": 1}),
    "slice": dict(primary={"data": S}, attrs={"begin": (0, 1), "end": (2, 3)}),
    "slice_axis": dict(primary={"data": S},
                       attrs={"axis": 1, "begin": 1, "end": 3}),
    "reverse": dict(primary={"data": S}, attrs={"axis": 1}),
    "tile": dict(primary={"data": S}, attrs={"reps": (2, 1)}),
    "repeat": dict(primary={"data": S}, attrs={"repeats": 2}),
    "broadcast_to": dict(primary={"data": (1, 4)}, attrs={"shape": (3, 4)}),
    "broadcast_axis": dict(primary={"data": (1, 4)},
                           attrs={"axis": 0, "size": 3}),
    "Pad": dict(primary={"data": S4},
                attrs={"mode": "constant",
                       "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
    "space_to_depth": dict(primary={"data": (1, 1, 4, 4)},
                           attrs={"block_size": 2}),
    "UpSampling": dict(primary={"data": (1, 2, 3, 3)},
                       attrs={"scale": 2, "sample_type": "nearest"}),
    "Crop": dict(primary={"data": (1, 2, 5, 5)},
                 attrs={"h_w": (3, 3), "num_args": 1}),
    "sum": dict(primary={"data": S}),
    "mean": dict(primary={"data": S}),
    "nansum": dict(primary={"data": S}),
    "nanprod": dict(primary={"data": S}, domain=(0.3, 1.5, 0)),
    "prod": dict(primary={"data": S}, domain=(0.3, 1.5, 0)),
    "max": dict(primary={"data": S}),
    "min": dict(primary={"data": S}),
    "norm": dict(primary={"data": S}, domain=(0.3, 1, 0)),
    "sum_axis": dict(primary={"data": S}, attrs={"axis": 1}),
    "_square_sum": dict(primary={"data": S}, attrs={"axis": 1}),
    "sort": dict(primary={"data": S}, attrs={"axis": 1}),
    "where": dict(primary={"condition": S, "x": S, "y": S},
                  grad_nodes=["x", "y"],
                  int_inputs={"condition": (0, 2)}),
    "take": dict(primary={"a": (5, 3), "indices": (4,)},
                 grad_nodes=["a"], int_inputs={"indices": (0, 5)}),
    "batch_take": dict(primary={"a": (3, 4), "indices": (3,)},
                       grad_nodes=["a"], int_inputs={"indices": (0, 4)}),
    "gather_nd": dict(primary={"data": (4, 3), "indices": (1, 2)},
                      grad_nodes=["data"], int_inputs={"indices": (0, 3)}),
    "pick": dict(primary={"data": (3, 4), "index": (3,)},
                 grad_nodes=["data"], int_inputs={"index": (0, 4)}),
    "SequenceLast": dict(primary={"data": (4, 2, 3)}),
    "SequenceMask": dict(primary={"data": (4, 2, 3)}),
    "SequenceReverse": dict(primary={"data": (4, 2, 3)}),
    "softmax_cross_entropy": dict(primary={"data": (3, 5), "label": (3,)},
                                  grad_nodes=["data"],
                                  int_inputs={"label": (0, 5)}),
    "IdentityAttachKLSparseReg": dict(primary={"data": S},
                                      domain=(0.1, 0.9, 0)),
    "GridGenerator": dict(primary={"data": (2, 6)},
                          attrs={"transform_type": "affine",
                                 "target_shape": (4, 4)}),
    "BilinearSampler": dict(primary={"data": (1, 2, 4, 4),
                                     "grid": (1, 2, 3, 3)},
                            domain=(-0.7, 0.7, 0)),
    "SpatialTransformer": dict(
        primary={"data": (1, 1, 4, 4), "loc": (1, 6)},
        attrs={"target_shape": (3, 3), "transform_type": "affine",
               "sampler_type": "bilinear"}, domain=(-0.3, 0.3, 0)),
    "ROIPooling": dict(
        primary={"data": (1, 1, 6, 6), "rois": (1, 5)},
        attrs={"pooled_size": (2, 2), "spatial_scale": 1.0},
        grad_nodes=["data"],
        fixed={"rois": np.array([[0, 0, 0, 4, 4]], "float32")}),
    "Correlation": dict(primary={"data1": (1, 1, 4, 4),
                                 "data2": (1, 1, 4, 4)},
                        attrs={"kernel_size": 1, "max_displacement": 1,
                               "stride1": 1, "stride2": 1}),
    "_linalg_gemm": dict(primary={"A": (2, 3), "B": (3, 2), "C": (2, 2)}),
    "_linalg_gemm2": dict(primary={"A": (2, 3), "B": (3, 2)}),
    "_linalg_syrk": dict(primary={"A": (2, 3)}),
    "_linalg_trmm": dict(primary={"A": (3, 3), "B": (3, 3)},
                         fixed={"A": np.tril(_rand((3, 3), 0.5, 1.5))
                                .astype("float32")},
                         grad_nodes=["B"]),
    "_contrib_FlashAttention": dict(
        primary={"query": (1, 4, 2, 4), "key": (1, 4, 2, 4),
                 "value": (1, 4, 2, 4)}, tol=dict(rtol=3e-2, atol=3e-3)),
    "_slice_assign": dict(primary={"lhs": S, "rhs": (2, 2)},
                          attrs={"begin": (0, 0), "end": (2, 2)}),
    "_slice_assign_scalar": dict(primary={"data": S},
                                 attrs={"begin": (0, 0), "end": (2, 2),
                                        "scalar": 0.5}),
    "_identity_with_attr_like_rhs": dict(primary={"lhs": S, "rhs": S},
                                         grad_nodes=["lhs"]),
}

# ops whose gradient is NOT finite-difference checked, with the reason.
SKIP = {
    # integer / boolean / index outputs (no gradient by definition)
    "argmax": "integer output", "argmin": "integer output",
    "argmax_channel": "integer output", "argsort": "integer output",
    "topk": "index output (default ret_typ)", "one_hot": "integer input",
    "sign": "derivative zero a.e.; kink at 0", "round": "step function",
    "rint": "step function", "fix": "step function",
    "floor": "step function", "ceil": "step function",
    "trunc": "step function",
    "_equal": "boolean output", "_not_equal": "boolean output",
    "_greater": "boolean output", "_greater_equal": "boolean output",
    "_lesser": "boolean output", "_lesser_equal": "boolean output",
    "_equal_scalar": "boolean output", "_not_equal_scalar": "boolean output",
    "_greater_scalar": "boolean output",
    "_greater_equal_scalar": "boolean output",
    "_lesser_scalar": "boolean output", "_lesser_equal_scalar":
        "boolean output",
    "broadcast_equal": "boolean output", "broadcast_not_equal":
        "boolean output",
    "broadcast_greater": "boolean output", "broadcast_greater_equal":
        "boolean output",
    "broadcast_lesser": "boolean output", "broadcast_lesser_equal":
        "boolean output",
    # modulo family: fwd tested in test_operator; grad undefined at wraps
    "_mod": "mod derivative undefined at wrap points",
    "_mod_scalar": "mod derivative undefined at wrap points",
    "_rmod_scalar": "mod derivative undefined at wrap points",
    "broadcast_mod": "mod derivative undefined at wrap points",
    # initializers / constants (no differentiable inputs)
    "_zeros": "no inputs", "_ones": "no inputs", "_full": "no inputs",
    "_arange": "no inputs", "_NoGradient": "explicitly gradient-free",
    "zeros_like": "constant output", "ones_like": "constant output",
    # dtype/storage plumbing
    "Cast": "dtype plumbing; identity derivative",
    "cast_storage": "storage plumbing; identity derivative",
    "_contrib_quantize": "int8 output",
    "_contrib_dequantize": "int8 input",
    "quantize_int8": "int8 output; inference-only (quant rewrite)",
    "dequantize_int8": "int8 input; inference-only (quant rewrite), "
                       "exact-value tested in tests/test_quant.py",
    # random samplers (stochastic output; distribution tests elsewhere)
    "_random_exponential": "stochastic", "_random_gamma": "stochastic",
    "_random_generalized_negative_binomial": "stochastic",
    "_random_negative_binomial": "stochastic",
    "_random_normal": "stochastic", "_random_poisson": "stochastic",
    "_random_uniform": "stochastic",
    "sample_exponential": "stochastic", "sample_gamma": "stochastic",
    "sample_generalized_negative_binomial": "stochastic",
    "sample_multinomial": "stochastic",
    "sample_negative_binomial": "stochastic",
    "sample_normal": "stochastic", "sample_poisson": "stochastic",
    "sample_uniform": "stochastic", "Dropout": "stochastic mask",
    # fused optimizer update kernels: exact-value tested in
    # tests/test_io_metric_optim.py against the Python optimizers
    "sgd_update": "exact-value tested", "sgd_mom_update":
        "exact-value tested",
    "mp_sgd_update": "exact-value tested", "mp_sgd_mom_update":
        "exact-value tested",
    "adam_update": "exact-value tested", "rmsprop_update":
        "exact-value tested",
    "rmspropalex_update": "exact-value tested", "ftrl_update":
        "exact-value tested",
    # loss heads with semantic (non-derivative) backward: verified by
    # closed-form check_symbolic_backward below
    "SoftmaxOutput": "semantic backward; closed-form checked below",
    "LinearRegressionOutput": "semantic backward; closed-form checked below",
    "LogisticRegressionOutput":
        "semantic backward; closed-form checked below",
    "MAERegressionOutput": "semantic backward; closed-form checked below",
    "SVMOutput": "semantic backward; closed-form checked below",
    "MakeLoss": "semantic backward; closed-form checked below",
    "_contrib_CTCLoss": "loss head; value-tested in test_operator",
    # detection / region ops: piecewise-constant index outputs
    "_contrib_MultiBoxPrior": "constant anchor generator",
    "_contrib_MultiBoxDetection": "nms index output",
    "_contrib_MultiBoxTarget": "matching index output",
    "_contrib_Proposal": "nms index output",
    "_contrib_PSROIPooling": "value-tested in test_spatial_custom",
    "_contrib_DeformablePSROIPooling":
        "value-tested in test_spatial_custom",
    "_contrib_DeformableConvolution":
        "value-tested in test_spatial_custom",
    # misc
    "Custom": "needs user-registered op; tested in test_spatial_custom",
    "RNN": "stateful rng op; vs-numpy tested in test_rnn",
    "_contrib_fft": "complex re-packing; value-tested in test_operator",
    "_contrib_ifft": "complex re-packing; value-tested in test_operator",
    "_contrib_count_sketch": "hash-indexed; value-tested in test_operator",
    "_linalg_gelqf": "decomposition grad not defined by the reference",
    "_linalg_potrf": "SPD-manifold grad; value-tested in test_operator",
    "_linalg_potri": "SPD-manifold grad; value-tested in test_operator",
    "_linalg_trsm": "triangular-solve grad; value-tested in test_operator",
    "_linalg_sumlogdiag": "value-tested in test_operator",
    "Embedding_data": "integer input",  # placeholder, not an op
}
SKIP.pop("Embedding_data")


def _canonical_ops():
    seen = {}
    for name, op in sorted(_OPS.items()):
        if op.name not in seen:
            seen[op.name] = op
    return seen


# snapshot at import (collection) time: ops user tests register later via
# mx.operator.register (e.g. test_spatial_custom's sigmoid_custom) are not
# part of the framework census
_CENSUS_AT_IMPORT = frozenset(_canonical_ops())


def _primary_symbol(opname, spec):
    op = _OPS[opname]
    nvar = spec.get("nvar")
    attrs = dict(spec.get("attrs", {}))
    fn = getattr(sym, opname)
    if nvar:
        vs = [sym.Variable("arg%d" % i) for i in range(nvar)]
        return fn(vs, **attrs)
    arg_names = op.arg_names
    if callable(arg_names):
        parsed = op.parse_attrs(attrs)
        arg_names = arg_names(parsed)
    pv = {n: sym.Variable(n) for n in spec["primary"] if n in arg_names}
    pos = [pv[n] for n in arg_names if n in pv]
    return fn(*pos, **attrs)


def _location_for(s, spec):
    """Fill every argument of symbol s with data of the right domain."""
    shapes = {k: v for k, v in spec["primary"].items()}
    arg_shapes, _, aux_shapes = s.infer_shape(**shapes)
    lo, hi, away = spec.get("domain", (-1.0, 1.0, 0.0))
    ints = spec.get("int_inputs", {})
    fixed = spec.get("fixed", {})
    loc = {}
    for n, shp in zip(s.list_arguments(), arg_shapes):
        if n in fixed:
            loc[n] = fixed[n]
        elif n in ints:
            lo_i, hi_i = ints[n]
            loc[n] = _RNG.randint(lo_i, hi_i, size=shp).astype("float32")
        else:
            loc[n] = _rand(shp, lo, hi, away)
    return loc


_ALL_CHECKS = []
for _n in UNARY:
    _ALL_CHECKS.append((_n, "unary"))
for _n in BINARY:
    _ALL_CHECKS.append((_n, "binary"))
for _n in SCALAR:
    _ALL_CHECKS.append((_n, "scalar"))
for _n in SPECS:
    _ALL_CHECKS.append((_n, "spec"))


@pytest.mark.parametrize("name,kind", _ALL_CHECKS)
def test_op_gradient(name, kind):
    if kind == "unary":
        lo, hi, away = UNARY[name]
        s = getattr(sym, name)(sym.Variable("data"))
        loc = {"data": _rand(S, lo, hi, away)}
        if name == "BlockGrad":
            # gradient must be exactly zero
            x = nd.array(loc["data"])
            g = nd.zeros(S)
            exe = s.bind(mx.cpu(), {"data": x}, args_grad={"data": g})
            exe.forward(is_train=True)
            exe.backward()
            assert np.abs(g.asnumpy()).max() == 0.0
            return
        check_numeric_gradient(s, loc, numeric_eps=1e-3, rtol=2e-2,
                               atol=2e-3)
    elif kind == "binary":
        dl, dr = BINARY[name]
        shapes = {"dot": ((2, 3), (3, 2)), "batch_dot": ((2, 2, 3), (2, 3, 2)),
                  }.get(name, (S, S))
        s = getattr(sym, name)(sym.Variable("lhs"), sym.Variable("rhs"))
        loc = {"lhs": _rand(shapes[0], *dl), "rhs": _rand(shapes[1], *dr)}
        check_numeric_gradient(s, loc, numeric_eps=1e-3, rtol=2e-2,
                               atol=2e-3)
    elif kind == "scalar":
        dom, attrs = SCALAR[name]
        s = getattr(sym, name)(sym.Variable("data"), **attrs)
        loc = {"data": _rand(S, *dom)}
        check_numeric_gradient(s, loc, numeric_eps=1e-3, rtol=2e-2,
                               atol=2e-3)
    else:
        spec = SPECS[name]
        opname = spec.get("op", name)
        s = _primary_symbol(opname, spec)
        loc = _location_for(s, spec)
        grad_nodes = spec.get("grad_nodes")
        if grad_nodes:
            # auto-created parameter args carry the op-instance prefix
            # (e.g. 'embedding0_weight'); resolve by exact name or suffix
            args = s.list_arguments()
            grad_nodes = [next(a for a in args
                               if a == g or a.endswith("_" + g) or
                               a.endswith(g))
                          for g in grad_nodes]
        aux = spec.get("aux")
        if aux == "bn":  # moving stats by prefixed name: mean=0, var=1
            _, _, aux_shapes = s.infer_shape(**spec["primary"])
            aux = {n: (np.ones(shp, "float32") if n.endswith("var")
                       else np.zeros(shp, "float32"))
                   for n, shp in zip(s.list_auxiliary_states(), aux_shapes)}
        tol = spec.get("tol", {})
        check_numeric_gradient(
            s, loc, aux_states=aux, grad_nodes=grad_nodes,
            numeric_eps=tol.get("eps", 1e-3), rtol=tol.get("rtol", 2e-2),
            atol=tol.get("atol", 2e-3))


# ---------------------------------------------------------------------------
# loss heads: the backward is a semantic rule, not d(forward); verify the
# closed form the reference defines (src/operator/softmax_output-inl.h etc.)

def test_softmax_output_backward_closed_form():
    x = _rand((4, 5))
    lbl = _RNG.randint(0, 5, 4).astype("float32")
    s = sym.SoftmaxOutput(sym.Variable("data"), sym.Variable("label"),
                          grad_scale=1.0)
    p = np.exp(x - x.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    onehot = np.eye(5, dtype="float32")[lbl.astype(int)]
    check_symbolic_backward(
        s, {"data": x, "label": lbl}, [np.ones((4, 5), "float32")],
        {"data": (p - onehot).astype("float32")}, rtol=1e-4, atol=1e-5)


def test_regression_outputs_backward_closed_form():
    x = _rand((4, 3))
    lbl = _rand((4, 3))
    cases = {
        "LinearRegressionOutput": x - lbl,
        "LogisticRegressionOutput": 1 / (1 + np.exp(-x)) - lbl,
        "MAERegressionOutput": np.sign(x - lbl),
    }
    for opname, expect in cases.items():
        s = getattr(sym, opname)(sym.Variable("data"), sym.Variable("label"))
        check_symbolic_backward(
            s, {"data": x, "label": lbl}, [np.ones((4, 3), "float32")],
            {"data": expect.astype("float32")}, rtol=1e-4, atol=1e-5)


def test_make_loss_backward_closed_form():
    x = _rand((4, 3))
    s = sym.MakeLoss(sym.Variable("data"), grad_scale=2.0)
    check_symbolic_backward(
        s, {"data": x}, [np.ones((4, 3), "float32")],
        {"data": np.full((4, 3), 2.0, "float32")}, rtol=1e-5, atol=1e-6)


def test_svm_output_backward_closed_form():
    x = _rand((4, 3))
    lbl = _RNG.randint(0, 3, 4).astype("float32")
    onehot = np.eye(3, dtype="float32")[lbl.astype(int)]
    sgn = 1 - 2 * onehot
    dist = sgn * x + 1.0
    expect = 2 * np.maximum(dist, 0) * sgn  # squared hinge (use_linear=False)
    s = sym.SVMOutput(sym.Variable("data"), sym.Variable("label"),
                      margin=1.0, regularization_coefficient=1.0)
    check_symbolic_backward(
        s, {"data": x, "label": lbl}, [np.ones((4, 3), "float32")],
        {"data": expect.astype("float32")}, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# census: every canonical op is classified exactly once

def test_every_op_classified():
    ops = _CENSUS_AT_IMPORT
    checked = set(UNARY) | set(BINARY) | set(SCALAR) | \
        {SPECS[k].get("op", k) for k in SPECS}
    classified = checked | set(SKIP)
    missing = sorted(set(ops) - classified)
    assert not missing, (
        "ops neither gradient-checked nor skip-listed (add them to the "
        "sweep or to SKIP with a reason): %s" % missing)
    phantom = sorted((checked & set(SKIP)))
    assert not phantom, "ops both checked and skipped: %s" % phantom
    # at least the VERDICT's bar: >200 canonical ops classified, and the
    # checked set is the growing majority
    assert len(checked - {"Pooling_avg"}) >= 120, len(checked)
