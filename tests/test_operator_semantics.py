"""Semantic value oracles for the trickier operators — the parts of the
reference's test_operator.py (tests/python/unittest/test_operator.py)
beyond elementwise/np-trivial ops: indexing/gather families, ordering,
padding, shape manipulators, grouped/dilated convolution, pooling
conventions, and sampling ops. Every test compares against an
independent numpy computation."""
import numpy as np

import mxtpu as mx
from mxtpu import nd


def _rand(shape, seed=0, lo=-2.0, hi=2.0):
    rng = np.random.RandomState(seed)
    return rng.uniform(lo, hi, size=shape).astype("float32")


def test_take_axis0_oracle():
    w = _rand((5, 3))
    idx = np.array([0, 4, 2, 2], "float32")
    out = nd.take(nd.array(w), nd.array(idx)).asnumpy()
    np.testing.assert_allclose(out, w[idx.astype(int)])


def test_gather_nd_oracle():
    x = _rand((3, 4, 5))
    # indices (M, N): M leading dims indexed, trailing dims kept
    ind = np.array([[0, 2, 1], [3, 0, 2]], "float32")  # (2, N=3)
    out = nd.gather_nd(nd.array(x), nd.array(ind)).asnumpy()
    ref = x[ind[0].astype(int), ind[1].astype(int)]
    np.testing.assert_allclose(out, ref)


def test_one_hot_on_off_values():
    out = nd.one_hot(nd.array([1.0, 0.0, 3.0]), depth=4, on_value=7.0,
                     off_value=-1.0).asnumpy()
    ref = np.full((3, 4), -1.0, "float32")
    for i, j in enumerate([1, 0, 3]):
        ref[i, j] = 7.0
    np.testing.assert_allclose(out, ref)


def test_topk_value_and_indices():
    x = _rand((3, 8), seed=3)
    idx = nd.topk(nd.array(x), k=3, axis=-1).asnumpy()  # ret_typ=indices
    vals = nd.topk(nd.array(x), k=3, axis=-1, ret_typ="value").asnumpy()
    ref_idx = np.argsort(-x, axis=-1)[:, :3]
    np.testing.assert_allclose(idx, ref_idx.astype("float32"))
    np.testing.assert_allclose(vals, -np.sort(-x, axis=-1)[:, :3], rtol=1e-6)


def test_sort_argsort_descending():
    x = _rand((4, 6), seed=5)
    np.testing.assert_allclose(
        nd.sort(nd.array(x), axis=-1, is_ascend=False).asnumpy(),
        -np.sort(-x, axis=-1), rtol=1e-6)
    np.testing.assert_allclose(
        nd.argsort(nd.array(x), axis=-1).asnumpy(),
        np.argsort(x, axis=-1).astype("float32"))


def test_pad_constant_and_edge():
    x = _rand((1, 2, 3, 4), seed=7)
    pw = (0, 0, 0, 0, 1, 2, 2, 1)
    out_c = nd.Pad(nd.array(x), mode="constant", pad_width=pw,
                   constant_value=3.5).asnumpy()
    ref_c = np.pad(x, ((0, 0), (0, 0), (1, 2), (2, 1)), mode="constant",
                   constant_values=3.5)
    np.testing.assert_allclose(out_c, ref_c)
    out_e = nd.Pad(nd.array(x), mode="edge", pad_width=pw).asnumpy()
    ref_e = np.pad(x, ((0, 0), (0, 0), (1, 2), (2, 1)), mode="edge")
    np.testing.assert_allclose(out_e, ref_e)


def test_tile_repeat_flip_swapaxis():
    x = _rand((2, 3, 4), seed=9)
    np.testing.assert_allclose(nd.tile(nd.array(x), reps=(2, 1, 3)).asnumpy(),
                               np.tile(x, (2, 1, 3)))
    np.testing.assert_allclose(
        nd.repeat(nd.array(x), repeats=3, axis=1).asnumpy(),
        np.repeat(x, 3, axis=1))
    np.testing.assert_allclose(nd.flip(nd.array(x), axis=2).asnumpy(),
                               x[:, :, ::-1])
    np.testing.assert_allclose(
        nd.SwapAxis(nd.array(x), dim1=0, dim2=2).asnumpy(),
        np.swapaxes(x, 0, 2))


def test_broadcast_axis_oracle():
    x = _rand((1, 3, 1), seed=11)
    out = nd.broadcast_axis(nd.array(x), axis=(0, 2), size=(4, 2)).asnumpy()
    np.testing.assert_allclose(out, np.broadcast_to(x, (4, 3, 2)))


def test_batch_dot_transpose_flags():
    a = _rand((2, 3, 4), seed=13)
    b = _rand((2, 5, 4), seed=14)
    out = nd.batch_dot(nd.array(a), nd.array(b), transpose_b=True).asnumpy()
    ref = np.einsum("bik,bjk->bij", a, b)
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    out2 = nd.batch_dot(nd.array(a.transpose(0, 2, 1)), nd.array(b),
                        transpose_a=True, transpose_b=True).asnumpy()
    np.testing.assert_allclose(out2, ref, rtol=1e-5)


def test_grouped_convolution_oracle():
    """num_group=C_in == depthwise: each output channel sees one input
    channel (reference conv with num_group, src/operator/convolution)."""
    c, h = 4, 6
    x = _rand((2, c, h, h), seed=17)
    w = _rand((c, 1, 3, 3), seed=18)
    out = nd.Convolution(nd.array(x), weight=nd.array(w), no_bias=True,
                         kernel=(3, 3), num_filter=c, num_group=c).asnumpy()
    # per-channel correlate oracle
    ref = np.zeros((2, c, h - 2, h - 2), "float32")
    for n in range(2):
        for ch in range(c):
            for i in range(h - 2):
                for j in range(h - 2):
                    ref[n, ch, i, j] = (x[n, ch, i:i + 3, j:j + 3]
                                        * w[ch, 0]).sum()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_dilated_convolution_oracle():
    x = _rand((1, 1, 7, 7), seed=19)
    w = _rand((1, 1, 3, 3), seed=20)
    out = nd.Convolution(nd.array(x), weight=nd.array(w), no_bias=True,
                         kernel=(3, 3), num_filter=1,
                         dilate=(2, 2)).asnumpy()
    ref = np.zeros((1, 1, 3, 3), "float32")
    for i in range(3):
        for j in range(3):
            patch = x[0, 0, i:i + 5:2, j:j + 5:2]
            ref[0, 0, i, j] = (patch * w[0, 0]).sum()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_pooling_conventions():
    """'valid' floors the output size, 'full' ceils (pooling-inl.h
    pooling_convention); avg pooling divides by the window size."""
    x = _rand((1, 1, 5, 5), seed=21)
    val = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="max").asnumpy()
    assert val.shape == (1, 1, 2, 2)
    full = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                      pool_type="max",
                      pooling_convention="full").asnumpy()
    assert full.shape == (1, 1, 3, 3)
    np.testing.assert_allclose(full[0, 0, 2, 2], x[0, 0, 4, 4])
    g = nd.Pooling(nd.array(x), kernel=(2, 2), pool_type="avg",
                   global_pool=True).asnumpy()
    np.testing.assert_allclose(g.reshape(()), x.mean(), rtol=1e-6)


def test_upsampling_nearest_oracle():
    x = _rand((1, 2, 3, 3), seed=23)
    out = nd.UpSampling(nd.array(x), scale=2,
                        sample_type="nearest").asnumpy()
    ref = x.repeat(2, axis=2).repeat(2, axis=3)
    np.testing.assert_allclose(out, ref)


def test_sequence_ops_oracle():
    """SequenceMask/SequenceLast/SequenceReverse with per-batch lengths
    (sequence_mask.cc et al: axis 0 is time)."""
    T, B, D = 4, 3, 2
    x = _rand((T, B, D), seed=25)
    lens = np.array([2, 4, 1], "float32")
    m = nd.SequenceMask(nd.array(x), nd.array(lens),
                        use_sequence_length=True, value=-9.0).asnumpy()
    ref = x.copy()
    for b, l in enumerate(lens.astype(int)):
        ref[l:, b, :] = -9.0
    np.testing.assert_allclose(m, ref)
    last = nd.SequenceLast(nd.array(x), nd.array(lens),
                           use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(
        last, np.stack([x[int(l) - 1, b] for b, l in enumerate(lens)]))
    rev = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True).asnumpy()
    for b, l in enumerate(lens.astype(int)):
        np.testing.assert_allclose(rev[:l, b], x[:l, b][::-1])
        np.testing.assert_allclose(rev[l:, b], x[l:, b])


def test_slice_axis_oracle():
    x = _rand((4, 6), seed=27)
    out = nd.slice_axis(nd.array(x), axis=1, begin=1, end=5).asnumpy()
    np.testing.assert_allclose(out, x[:, 1:5])
    neg = nd.slice_axis(nd.array(x), axis=0, begin=-2, end=None).asnumpy()
    np.testing.assert_allclose(neg, x[-2:])


def test_grid_generator_bilinear_sampler_identity():
    """An affine identity grid sampled bilinearly reproduces the input
    (spatial transformer pair, grid_generator.cc + bilinear_sampler.cc)."""
    x = _rand((1, 1, 5, 5), seed=29)
    theta = nd.array(np.array([[1, 0, 0, 0, 1, 0]], "float32"))
    grid = nd.GridGenerator(theta, transform_type="affine",
                            target_shape=(5, 5))
    out = nd.BilinearSampler(nd.array(x), grid).asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-5, atol=1e-5)


def test_reduction_axis_keepdims_matrix():
    x = _rand((2, 3, 4), seed=31)
    for op, ref in [("sum", np.sum), ("max", np.max), ("min", np.min),
                    ("prod", np.prod), ("mean", np.mean)]:
        out = getattr(nd, op)(nd.array(x), axis=(0, 2),
                              keepdims=True).asnumpy()
        np.testing.assert_allclose(out, ref(x, axis=(0, 2), keepdims=True),
                                   rtol=1e-5)
    # negative axis
    np.testing.assert_allclose(nd.sum(nd.array(x), axis=-1).asnumpy(),
                               x.sum(-1), rtol=1e-5)


def test_deconvolution_torch_oracle():
    """Deconvolution matches torch.conv_transpose2d element-for-element
    across channels/stride/pad/output_padding/groups (the reference's
    (C_in, C_out/g, kH, kW) weight convention, deconvolution-inl.h).
    Guards the transposed-channel bug that C_in == C_out shapes hide."""
    torch = __import__("torch")
    F = torch.nn.functional
    rng = np.random.RandomState(0)
    cases = [(3, 5, 4, 2, 1, 0, 1, 1), (16, 8, 4, 1, 0, 0, 1, 1),
             (4, 4, 3, 1, 1, 0, 1, 1), (2, 3, 4, 2, 1, 1, 1, 1),
             (4, 6, 3, 2, 1, 0, 2, 1), (3, 4, 3, 2, 1, 0, 1, 2),
             (4, 6, 3, 1, 2, 0, 2, 2)]
    for ci, co, k, s, p, a, g, d in cases:
        x = rng.randn(2, ci, 5, 5).astype("float32")
        w = rng.randn(ci, co // g, k, k).astype("float32")
        ref = F.conv_transpose2d(torch.from_numpy(x), torch.from_numpy(w),
                                 stride=s, padding=p, output_padding=a,
                                 groups=g, dilation=d).numpy()
        out = nd.Deconvolution(nd.array(x), weight=nd.array(w),
                               kernel=(k, k), num_filter=co, stride=(s, s),
                               pad=(p, p), adj=(a, a), dilate=(d, d),
                               num_group=g).asnumpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4,
                                   err_msg=str((ci, co, k, s, p, a, g, d)))
    # target_shape overrides adj (deconvolution-inl.h target_shape)
    x = rng.randn(1, 2, 4, 4).astype("float32")
    w = rng.randn(2, 3, 4, 4).astype("float32")
    out = nd.Deconvolution(nd.array(x), weight=nd.array(w), kernel=(4, 4),
                           num_filter=3, stride=(2, 2), pad=(1, 1),
                           target_shape=(9, 9)).asnumpy()
    assert out.shape == (1, 3, 9, 9)
    ref = F.conv_transpose2d(torch.from_numpy(x), torch.from_numpy(w),
                             stride=2, padding=1, output_padding=1).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
