"""Monitor: per-batch tensor statistics through Module training (parity:
python/mxnet/monitor.py + its use in BaseModule.fit(monitor=) — the
reference installs an output callback on every executor and prints a stat
per tensor per monitored batch)."""
import numpy as np
import pytest

import mxtpu as mx


def _mlp_module(batch=16, dim=8, classes=3):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (batch, dim))],
             label_shapes=[("softmax_label", (batch,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    return mod


def _batch(batch=16, dim=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return mx.io.DataBatch(
        data=[mx.nd.array(rng.randn(batch, dim).astype("float32"))],
        label=[mx.nd.array(rng.randint(0, classes, (batch,))
                           .astype("float32"))])


def test_monitor_collects_stats_during_training():
    mod = _mlp_module()
    mon = mx.monitor.Monitor(interval=1, pattern=".*")
    mod.install_monitor(mon)
    db = _batch()
    mon.tic()
    mod.forward_backward(db)
    mod.update()
    res = mon.toc()
    assert res, "monitor captured nothing"
    names = {k for _, k, _ in res}
    # per-op outputs from the executors must appear, not just final outputs
    assert any("fc1" in n for n in names), names
    assert any("softmax" in n for n in names), names
    # stat strings parse back to finite floats
    for _, _, s in res:
        for tok in s.split():
            assert np.isfinite(float(tok))


def test_monitor_interval_and_pattern():
    mod = _mlp_module()
    mon = mx.monitor.Monitor(interval=2, pattern=".*fc2.*")
    mod.install_monitor(mon)
    db = _batch()
    seen = []
    for i in range(4):
        mon.tic()
        mod.forward_backward(db)
        mod.update()
        seen.append(mon.toc())
    # interval=2: batches 0 and 2 activate, 1 and 3 do not
    assert seen[0] and seen[2]
    assert not seen[1] and not seen[3]
    for res in (seen[0], seen[2]):
        for _, name, _ in res:
            assert "fc2" in name, name


def test_monitor_toc_sort_and_clean_deactivation():
    """toc(sort=True) returns entries ordered by tensor name; toc always
    leaves the monitor deactivated with an empty queue — including when
    nothing matched, and when stat_func raises mid-collection."""
    mod = _mlp_module()
    mon = mx.monitor.Monitor(interval=1, pattern=".*", sort=True)
    mod.install_monitor(mon)
    db = _batch()
    mon.tic()
    mod.forward_backward(db)
    mod.update()
    res = mon.toc()
    names = [k for _, k, _ in res]
    assert names == sorted(names), names
    assert not mon.activated and mon.queue == []

    # nothing matched: toc still deactivates and returns []
    empty = mx.monitor.Monitor(interval=1, pattern="no_such_tensor",
                               sort=True)
    empty.tic()
    assert empty.activated
    assert empty.toc() == []
    assert not empty.activated and empty.queue == []

    # a throwing stat_func must not wedge the monitor in activated state
    # (pre-fix, toc left activated=True and the stale queue behind, so
    # every later batch kept paying the per-op execution path)
    def boom(arr):
        raise RuntimeError("bad stat")

    class FakeExe:
        output_names = ["some_output"]
        outputs = [object()]

    angry = mx.monitor.Monitor(interval=1, pattern=".*", stat_func=boom,
                               sort=True)
    angry.exes.append(FakeExe())
    angry.tic()
    assert angry.activated
    with pytest.raises(RuntimeError):
        angry.toc()
    assert not angry.activated and angry.queue == []
    # and the next cycle works normally again
    angry.stat_func = lambda x: 1.0
    angry.tic()
    assert angry.toc()


def test_monitor_through_fit_loop():
    """fit(monitor=) wires tic/toc_print around every batch (parity
    base_module.py fit's monitor plumbing)."""
    mod = _mlp_module()
    mon = mx.monitor.Monitor(interval=1, pattern=".*softmax.*")
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype("float32")
    y = rng.randint(0, 3, 64).astype("float32")
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod.fit(it, num_epoch=1, monitor=mon,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            force_init=False)
    # the monitor survived a full epoch and kept collecting
    assert mon.step >= 4
