"""mxtpu.serving: dynamic batcher, executor pool, session, HTTP layer.

Tier-1 (CPU, `not slow`). The byte-identity contract under test: a request
served through the batching pipeline returns EXACTLY the rows a direct
Predictor.forward produces at the same bucket shape — padding rows and row
position must not perturb real rows (verified bitwise)."""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.models.serving_fixtures import get_fixture
from mxtpu.predict import Predictor
from mxtpu.serving import (DynamicBatcher, ExecutorPool, MetricsRegistry,
                           QueueFull, ServingHTTPServer, ServingSession,
                           pad_rows, pick_bucket)


def _rand(shape, seed):
    return np.random.RandomState(seed).rand(*shape).astype(np.float32)


# ------------------------------------------------------------------ batcher
def test_pick_bucket_and_padding():
    assert pick_bucket(1, (1, 8, 32)) == 1
    assert pick_bucket(2, (1, 8, 32)) == 8
    assert pick_bucket(8, (1, 8, 32)) == 8
    assert pick_bucket(9, (1, 8, 32)) == 32
    x = _rand((3, 4), 0)
    p = pad_rows(x, 8)
    assert p.shape == (8, 4)
    assert np.array_equal(p[:3], x)
    assert not p[3:].any()
    assert pad_rows(x, 3) is x  # no-op copy-free path


def test_batcher_deadline_flush():
    """A lone request must not wait for a full bucket: the max-latency
    deadline releases a padded partial batch."""
    b = DynamicBatcher(["data"], buckets=(4, 8), max_delay_ms=30)
    t0 = time.time()
    item = b.submit({"data": _rand((1, 4), 0)})
    batch = b.next_batch(timeout=5)
    waited = time.time() - t0
    assert batch is not None and batch.n_valid == 1
    assert batch.bucket == 4  # smallest bucket covering one example
    assert batch.inputs["data"].shape == (4, 4)
    assert waited >= 0.025  # held back ~the deadline before flushing
    batch.finish([np.zeros((4, 2), np.float32)])
    assert item.wait(1)[0].shape == (1, 2)


def test_batcher_full_bucket_flushes_immediately():
    b = DynamicBatcher(["data"], buckets=(1, 4), max_delay_ms=10_000)
    for i in range(4):
        b.submit({"data": _rand((1, 3), i)})
    t0 = time.time()
    batch = b.next_batch(timeout=5)
    assert batch is not None and batch.bucket == 4 and batch.n_valid == 4
    assert time.time() - t0 < 5  # did NOT wait the 10s deadline
    # rows keep submission order
    for i, it in enumerate(batch.items):
        assert np.array_equal(batch.inputs["data"][i], it.inputs["data"][0])


def test_batcher_backpressure_queue_full():
    b = DynamicBatcher(["data"], buckets=(1,), max_delay_ms=5, max_queue=2)
    b.submit({"data": _rand((1, 2), 0)})
    b.submit({"data": _rand((1, 2), 1)})
    with pytest.raises(QueueFull):
        b.submit({"data": _rand((1, 2), 2)})


def test_batcher_timeout_reaps_queued_requests():
    b = DynamicBatcher(["data"], buckets=(8,), max_delay_ms=50)
    item = b.submit({"data": _rand((1, 2), 0)}, timeout=0.01)
    time.sleep(0.03)
    assert b.next_batch(timeout=0.2) is None  # reaped, nothing to serve
    with pytest.raises(TimeoutError):
        item.wait(0.1)


def test_batcher_close_drains_tail():
    b = DynamicBatcher(["data"], buckets=(4,), max_delay_ms=10_000)
    b.submit({"data": _rand((1, 2), 0)})
    b.close()
    batch = b.next_batch(timeout=1)
    assert batch is not None and batch.n_valid == 1  # tail flushed on close
    assert b.next_batch(timeout=1) is None  # then drain-complete


# ------------------------------------------------------------------ pool
def test_pool_cache_reuses_executables():
    sj, params, shapes = get_fixture("mlp")
    metrics = MetricsRegistry()
    pool = ExecutorPool(sj, params, shapes,
                        contexts=[mx.cpu(0)], cache_size=2, metrics=metrics)
    pool.warmup((1, 8))
    misses0 = metrics.counter("executor_cache_misses").value
    x = _rand((8, 784), 0)
    pool.run({"data": x})
    pool.run({"data": x})
    assert metrics.counter("executor_cache_misses").value == misses0
    assert metrics.counter("executor_cache_hits").value >= 2
    # a third shape overflows cache_size=2 and evicts
    pool.run({"data": _rand((4, 784), 1)})
    assert metrics.counter("executor_cache_evictions").value >= 1


def test_predictor_forward_batch_buckets():
    sj, params, shapes = get_fixture("mlp")
    pred = Predictor(sj, dict(params), input_shapes={"data": (1, 784)},
                     bucket_sizes=(1, 8))
    ref = Predictor(sj, dict(params), input_shapes={"data": (8, 784)})
    x = _rand((3, 784), 0)
    out = pred.forward_batch({"data": x})[0]
    assert out.shape == (3, 10)
    ref.forward(data=pad_rows(x, 8))
    assert np.array_equal(out, ref.get_output(0)[:3])  # byte-identical
    assert isinstance(pred.symbol_hash, str) and len(pred.symbol_hash) == 16


# ------------------------------------------------------------------ session
def test_concurrent_clients_byte_identical():
    """32 concurrent clients through the batching pipeline: every response
    must be byte-identical to a direct Predictor.forward at one of the
    bucket shapes (row position and padding provably inert)."""
    sj, params, shapes = get_fixture("lenet")
    buckets = (1, 8, 32)
    # direct references: request in row 0 of each bucket-sized batch
    refs = {}
    for b in buckets:
        refs[b] = Predictor(sj, dict(params),
                            input_shapes={"data": (b, 1, 28, 28)})

    def direct(x, b):
        refs[b].forward(data=pad_rows(x, b))
        return refs[b].get_output(0)[:1]

    with ServingSession(sj, params, shapes, buckets=buckets,
                        max_delay_ms=5, contexts=[mx.cpu(0)]) as sess:
        results, errors = {}, []
        lock = threading.Lock()

        def client(i):
            x = _rand((1, 1, 28, 28), i)
            try:
                out = sess.predict({"data": x}, timeout=60)[0]
                with lock:
                    results[i] = (x, out)
            except Exception as exc:
                errors.append((i, exc))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(32)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert len(results) == 32
        for i, (x, out) in results.items():
            assert any(np.array_equal(out, direct(x, b)) for b in buckets), \
                "client %d response not byte-identical to any bucket" % i
        stats = sess.stats()
        assert stats["requests_completed"] == 32
        assert stats["batches_formed"] <= 32
        assert 0 < stats["batch_fill_ratio"] <= 1.0
        assert stats["request_latency_ms"]["count"] == 32


def test_session_backpressure_and_timeout():
    # burst mode: the test stalls the dispatcher by patching pool.run
    # (the burst hot path); admission off so the bounded queue itself
    # provides the backpressure under test
    sj, params, shapes = get_fixture("mlp")
    sess = ServingSession(sj, params, shapes, buckets=(1, 4),
                          max_delay_ms=1, max_queue=3, warmup=True,
                          contexts=[mx.cpu(0)], mode="burst")
    try:
        # swamp the queue while holding the dispatcher out of the picture:
        # submit directly into the bounded batcher queue
        blocker = threading.Event()
        orig_run = sess.pool.run

        def slow_run(*a, **kw):
            blocker.wait(5)
            return orig_run(*a, **kw)

        sess.pool.run = slow_run
        # first request occupies the (single) dispatcher inside slow_run...
        stuck = sess.predict_async({"data": _rand((1, 784), 50)})
        deadline = time.time() + 5
        while sess.batcher.depth > 0 and time.time() < deadline:
            time.sleep(0.005)
        # ...then the bounded queue fills behind it
        items = [sess.predict_async({"data": _rand((1, 784), i)})
                 for i in range(2)]
        # a queued request with a tiny deadline times out rather than hang
        with pytest.raises(TimeoutError):
            sess.predict({"data": _rand((1, 784), 99)}, timeout=0.05)
        # the expired item still occupies its slot until reaped: full now
        with pytest.raises(QueueFull):
            sess.predict_async({"data": _rand((1, 784), 3)})
        blocker.set()
    finally:
        sess.close()
    # graceful drain: queued (non-expired) work was answered on close
    assert all(it.event.is_set() for it in items), \
        "drain did not answer queued requests"


def test_misshapen_request_rejected_at_submit():
    """A request whose trailing dims don't match the model must be
    rejected at the door (never batched — it would poison a whole
    concatenate) and must not kill the dispatcher."""
    sj, params, shapes = get_fixture("mlp")
    with ServingSession(sj, params, shapes, buckets=(1, 4),
                        max_delay_ms=2, contexts=[mx.cpu(0)]) as sess:
        import mxtpu as _mx
        with pytest.raises(_mx.MXNetError):
            sess.predict({"data": _rand((1, 5), 0)})  # wrong feature dim
        with pytest.raises(_mx.MXNetError):
            sess.predict({"data": np.float32(3.0)})  # 0-d input
        # the service is still alive and serving
        out = sess.predict({"data": _rand((1, 784), 1)}, timeout=30)
        assert out[0].shape == (1, 10)


def test_session_close_rejects_new_work():
    sj, params, shapes = get_fixture("mlp")
    sess = ServingSession(sj, params, shapes, buckets=(1,), warmup=False,
                          contexts=[mx.cpu(0)])
    sess.close()
    from mxtpu.serving import BatcherClosed
    with pytest.raises(BatcherClosed):
        sess.predict({"data": _rand((1, 784), 0)})


# ------------------------------------------------------------------ HTTP
def test_http_endpoint_roundtrip():
    sj, params, shapes = get_fixture("mlp")
    sess = ServingSession(sj, params, shapes, buckets=(1, 4),
                          max_delay_ms=2, contexts=[mx.cpu(0)])
    server = ServingHTTPServer(sess, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        base = server.endpoint
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok" and health["buckets"] == [1, 4]
        x = _rand((1, 784), 0)
        req = urllib.request.Request(
            base + "/v1/predict",
            data=json.dumps({"inputs": {"data": x.tolist()}}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = np.asarray(json.loads(r.read())["outputs"][0],
                             dtype=np.float32)
        direct = Predictor(sj, dict(params), input_shapes={"data": (1, 784)})
        direct.forward(data=x)
        assert np.allclose(out, direct.get_output(0), atol=1e-6)
        with urllib.request.urlopen(base + "/v1/metrics", timeout=10) as r:
            stats = json.loads(r.read())
        assert stats["requests_completed"] >= 1
        # malformed body -> 400, unknown path -> 404
        bad = urllib.request.Request(base + "/v1/predict", data=b"notjson")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        server.shutdown()
        server.server_close()


# ------------------------------------------------------------- observability
def test_metrics_endpoint_prometheus_text():
    """GET /metrics returns parseable Prometheus text exposition carrying
    engine, executor-cache and serving series (qps + latency p99 among
    them), and ?format=json returns the same data as JSON."""
    import re
    sj, params, shapes = get_fixture("mlp")
    sess = ServingSession(sj, params, shapes, buckets=(1, 4),
                          max_delay_ms=2, contexts=[mx.cpu(0)])
    server = ServingHTTPServer(sess, port=0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        base = server.endpoint
        for i in range(3):
            sess.predict({"data": _rand((1, 784), i)}, timeout=30)
        req = urllib.request.urlopen(base + "/metrics", timeout=10)
        assert req.headers["Content-Type"].startswith("text/plain")
        text = req.read().decode()
        # every non-comment line matches the Prometheus sample grammar
        sample_re = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [^ ]+$')
        assert text.endswith("\n")
        for line in text.splitlines():
            if line:
                assert line.startswith("#") or sample_re.match(line), line
        # process-wide series: engine + executor-cache
        assert "# TYPE mxtpu_engine_ops_dispatched counter" in text
        assert "mxtpu_engine_queue_depth" in text
        assert "mxtpu_executor_program_builds_total" in text
        # serving series, including the derived operator numbers
        assert "# TYPE mxtpu_serving_requests_completed counter" in text
        assert "# TYPE mxtpu_serving_qps gauge" in text
        assert "mxtpu_serving_request_latency_ms_p99" in text
        assert "mxtpu_serving_request_latency_ms_bucket" in text
        assert "mxtpu_serving_executor_cache_hits" in text
        # histogram buckets are cumulative and end at +Inf == _count
        lat = [l for l in text.splitlines()
               if l.startswith("mxtpu_serving_request_latency_ms_bucket")]
        counts = [int(l.rsplit(" ", 1)[1]) for l in lat]
        assert counts == sorted(counts) and counts[-1] >= 3
        count_line = next(l for l in text.splitlines() if
                          l.startswith("mxtpu_serving_request_latency_ms_count"))
        assert int(count_line.rsplit(" ", 1)[1]) == counts[-1]

        # same data as JSON
        with urllib.request.urlopen(base + "/metrics?format=json",
                                    timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["mxtpu_serving"]["requests_completed"] >= 3
        assert "qps" in snap["mxtpu_serving"]
        assert "engine_ops_dispatched" in snap["mxtpu"]
    finally:
        server.shutdown()
        server.server_close()


def test_request_trace_spans_correlated():
    """One request's trace id flows submit -> batch -> pool.dispatch:
    with the profiler running, the serving.request B event and the
    batch/dispatch events share a trace_id in their args."""
    from mxtpu import profiler
    sj, params, shapes = get_fixture("mlp")
    with ServingSession(sj, params, shapes, buckets=(1,),
                        max_delay_ms=1, contexts=[mx.cpu(0)]) as sess:
        profiler.clear()
        profiler.set_config(mode="symbolic", filename="/tmp/unused_srv.json")
        profiler.set_state("run")
        try:
            sess.predict({"data": _rand((1, 784), 0)}, timeout=30)
        finally:
            profiler.set_state("stop")
        with profiler._lock:
            events = [e for e in profiler._events if e.get("args")]
        by_name = {}
        for e in events:
            by_name.setdefault(e["name"].split("[")[0], e["args"])
        assert "serving.request" in by_name, sorted(by_name)
        root = by_name["serving.request"]["trace_id"]
        assert by_name["batch"]["trace_id"] == root
        # continuous mode dispatches async (pool.dispatch); burst mode
        # runs sync (pool.run) — the queue-hop correlation contract is
        # the same either way
        dispatch = by_name.get("pool.dispatch") or by_name.get("pool.run")
        assert dispatch is not None, sorted(by_name)
        assert dispatch["trace_id"] == root
        profiler.clear()


def test_warmup_precompiles_no_builds_under_traffic():
    """After warmup, serving traffic at warmed buckets must not construct
    new executor programs (the executor.py cache-hook seam)."""
    from mxtpu import executor as _ex
    sj, params, shapes = get_fixture("mlp")
    sess = ServingSession(sj, params, shapes, buckets=(1, 4),
                          max_delay_ms=1, contexts=[mx.cpu(0)])
    try:
        sess.predict({"data": _rand((1, 784), 0)}, timeout=30)
        before = _ex.program_build_count()
        for i in range(6):
            sess.predict({"data": _rand((1, 784), i)}, timeout=30)
        assert _ex.program_build_count() == before
        stats = sess.stats()
        assert stats["executor_cache_hit_rate"] > 0
        # the session's own build listener saw the warmup compiles...
        assert stats["program_builds"] >= len(sess.buckets)
        # ...and no further builds during the warmed-bucket traffic above
        assert stats["program_builds"] == sess.stats()["program_builds"]
    finally:
        sess.close()
