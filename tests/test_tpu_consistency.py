"""Cross-device consistency on the real accelerator: the same symbol run
on CPU and on the TPU must agree on outputs AND gradients within a
dtype-appropriate tolerance ladder.

Model: the reference's second trust tier — tests/python/gpu/
test_operator_gpu.py check_consistency, which runs every op on cpu+gpu
contexts and compares. Run with:

    MXTPU_TEST_TPU=1 python -m pytest tests/ -m tpu -q
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import sym
from mxtpu.test_utils import check_consistency

pytestmark = pytest.mark.tpu


def _require_accel():
    import jax
    try:
        dev = jax.devices()[0]
    except Exception as e:  # backend init failed
        pytest.skip("no accelerator backend: %s" % e)
    if dev.platform == "cpu":
        pytest.skip("default backend is CPU; no accelerator present")
    return mx.tpu()


def _ctx_list(accel, **shapes):
    return [dict(ctx=mx.cpu(), **shapes), dict(ctx=accel, **shapes)]


def test_dense_mlp_consistency():
    accel = _require_accel()
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=8, name="fc2")
    # 'highest' on TPU is 3-pass bf16, not bit-exact f32: ~1e-4 relative
    # residual through two matmul layers + tanh backward
    check_consistency(net, _ctx_list(accel, data=(4, 10)),
                      rtol=5e-3, atol=1e-3)


def test_conv_bn_relu_consistency():
    accel = _require_accel()
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                          name="conv")
    net = sym.BatchNorm(net, fix_gamma=False, name="bn")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    check_consistency(net, _ctx_list(accel, data=(2, 3, 8, 8)),
                      rtol=2e-3, atol=2e-3)


def test_softmax_head_consistency():
    accel = _require_accel()
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=10, name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")
    check_consistency(net, _ctx_list(accel, data=(4, 6),
                                     softmax_label=(4,)),
                      rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("opname", [
    "exp", "log", "sqrt", "rsqrt", "sigmoid", "tanh", "erf", "relu",
    "square", "abs", "cbrt", "log1p", "expm1", "sin", "cos",
])
def test_unary_consistency(opname):
    accel = _require_accel()
    data = sym.Variable("data")
    # positive-domain inputs keep log/sqrt/rsqrt well-defined on both
    net = getattr(sym, opname)(sym._plus_scalar(sym.square(data),
                                                scalar=0.5))
    # TPU transcendental approximations (tanh/erf) carry ~4e-4 relative
    # error vs the CPU libm reference
    check_consistency(net, _ctx_list(accel, data=(3, 5)),
                      rtol=2e-3, atol=5e-4)


@pytest.mark.parametrize("opname", [
    "broadcast_add", "broadcast_mul", "broadcast_maximum", "dot",
    "batch_dot",
])
def test_binary_consistency(opname):
    accel = _require_accel()
    shapes = {"dot": ((4, 5), (5, 3)), "batch_dot": ((2, 3, 4), (2, 4, 3))
              }.get(opname, ((4, 5), (4, 5)))
    net = getattr(sym, opname)(sym.Variable("lhs"), sym.Variable("rhs"))
    check_consistency(net, _ctx_list(accel, lhs=shapes[0], rhs=shapes[1]),
                      rtol=1e-3, atol=1e-4)


def test_reduction_consistency():
    accel = _require_accel()
    data = sym.Variable("data")
    net = sym.Group([sym.sum(data), sym.max(data), sym.mean(data),
                     sym.norm(data)])
    check_consistency(net, _ctx_list(accel, data=(6, 7)),
                      rtol=1e-3, atol=1e-4)


def test_resnet_block_forward_consistency():
    """One bottleneck block fwd+bwd, the bench model's building block."""
    accel = _require_accel()
    data = sym.Variable("data")
    b = sym.Convolution(data, kernel=(1, 1), num_filter=8, no_bias=True,
                        name="c1")
    b = sym.BatchNorm(b, fix_gamma=False, name="b1")
    b = sym.Activation(b, act_type="relu")
    b = sym.Convolution(b, kernel=(3, 3), pad=(1, 1), num_filter=8,
                        no_bias=True, name="c2")
    b = sym.BatchNorm(b, fix_gamma=False, name="b2")
    net = sym.Activation(sym.elemwise_add(
        sym.Convolution(data, kernel=(1, 1), num_filter=8, no_bias=True,
                        name="sc"), b), act_type="relu")
    check_consistency(net, _ctx_list(accel, data=(2, 4, 8, 8)),
                      rtol=2e-3, atol=2e-3)


def test_grouped_and_depthwise_conv_consistency():
    """Grouped (resnext cardinality) and depthwise (mobilenet) convs:
    feature_group_count lowering must agree between CPU and the chip."""
    accel = _require_accel()
    data = sym.Variable("data")
    g = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=8,
                        num_group=4, no_bias=True, name="grouped")
    check_consistency(g, _ctx_list(accel, data=(2, 8, 6, 6)),
                      rtol=2e-3, atol=2e-3)
    dw = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=8,
                         num_group=8, no_bias=True, name="depthwise")
    check_consistency(dw, _ctx_list(accel, data=(2, 8, 6, 6)),
                      rtol=2e-3, atol=2e-3)


def _run_on_chip_subprocess(code, ok_token):
    """Run pallas-kernel code against the real chip in a watchdogged
    subprocess: a wedged device relay hangs the first jax call forever,
    and that must SKIP the tier, not hang it. PYTHONPATH PREPENDS the
    repo (replacing it would drop the axon plugin path, turning the
    wedged-tunnel hang into a bogus unknown-backend failure)."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    try:
        r = subprocess.run([sys.executable, "-u", "-c", code], env=env,
                           capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        pytest.skip("device relay hung during Mosaic compile/run "
                    "(wedged tunnel)")
    if "NO_ACCELERATOR" in r.stdout:
        pytest.skip("subprocess saw no accelerator backend")
    assert r.returncode == 0, r.stdout + r.stderr
    assert ok_token in r.stdout


def test_pallas_flash_kernel_on_chip():
    """The compiled (non-interpret) Pallas flash kernel must match the
    reference attention math on the real chip — values and gradients.
    CPU runs exercise the same kernel only in interpret mode, so this is
    the one test that validates the Mosaic-lowered kernel itself."""
    # NO parent-process jax call here: against a wedged relay the first
    # jax call hangs forever, and this test's contract is to skip, not
    # hang — so the accelerator probe lives inside the subprocess too.
    code = r"""
import sys
import numpy as np, jax, jax.numpy as jnp
if jax.default_backend() == "cpu":
    print("NO_ACCELERATOR")
    sys.exit(0)
from mxtpu.ops import attention as att
rng = np.random.RandomState(0)
B, H, T, D = 2, 4, 384, 64  # off-block-multiple T exercises the tail
q = jnp.asarray(rng.randn(B, H, T, D).astype("float32") * 0.5)
k = jnp.asarray(rng.randn(B, H, T, D).astype("float32") * 0.5)
v = jnp.asarray(rng.randn(B, H, T, D).astype("float32"))

def ref(q, k, v):
    s = jnp.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(D)
    mask = np.tril(np.ones((T, T), bool))
    s = jnp.where(mask, s, -1e30)
    return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(s, axis=-1), v)

with jax.default_matmul_precision("highest"):
    out = att.flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=128)
    expect = ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=2e-3, atol=2e-3)
    g = jax.grad(lambda a, b, c: att.flash_attention(
        a, b, c, causal=True).sum())(q, k, v)
    g_ref = jax.grad(lambda a, b, c: ref(a, b, c).sum())(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=5e-3, atol=5e-3)
print("PALLAS_ON_CHIP_OK")
"""
    _run_on_chip_subprocess(code, "PALLAS_ON_CHIP_OK")


def test_pallas_epilogue_kernel_on_chip():
    """The Mosaic-compiled BN-apply+ReLU+add epilogue (ops/epilogue.py)
    must match the XLA formulation on the real chip — CPU only exercises
    interpret mode. Subprocess-watchdogged like the flash-kernel check."""
    code = r"""
import sys
import numpy as np, jax, jax.numpy as jnp
if jax.default_backend() == "cpu":
    print("NO_ACCELERATOR")
    sys.exit(0)
from mxtpu.ops.epilogue import (bn_apply_relu_add,
                                bn_apply_relu_add_reference, fold_bn)
rng = np.random.RandomState(4)
m, c = 4096, 256
x = jnp.asarray(rng.randn(m, c), jnp.bfloat16)
r = jnp.asarray(rng.randn(m, c), jnp.bfloat16)
scale, shift = fold_bn(jnp.asarray(rng.rand(c) + 0.5, jnp.float32),
                       jnp.asarray(rng.randn(c), jnp.float32),
                       jnp.asarray(rng.randn(c), jnp.float32),
                       jnp.asarray(rng.rand(c) + 0.1, jnp.float32))
got = np.asarray(bn_apply_relu_add(x, scale, shift, r)).astype("f4")
want = np.asarray(bn_apply_relu_add_reference(x, scale, shift, r)
                  ).astype("f4")
np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
print("EPILOGUE_ON_CHIP_OK")
"""
    _run_on_chip_subprocess(code, "EPILOGUE_ON_CHIP_OK")
